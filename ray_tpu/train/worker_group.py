"""Worker group: one actor per training worker.

Capability parity with the reference's WorkerGroup (reference:
python/ray/train/v2/_internal/execution/worker_group/worker_group.py:113 —
actors placed via placement group, train_fn runs on a thread inside each
actor (thread_runner.py), poll_status :609 aggregates worker states).

Recovery additions: ``poll_status`` distinguishes DEAD workers (actor
process gone — ActorDiedError on the poll) from application errors, per
rank, so the controller can attribute a failure to a worker/slice and pick
a restart tier; groups can be built from ``recycled`` pre-warmed spare
actors (hot-spare promotion: the fork+import seconds are already paid) via
``TrainWorker.reconfigure``.
"""

from __future__ import annotations

import threading
import traceback
from dataclasses import dataclass, field
from typing import Any, Callable

import ray_tpu
from ray_tpu.devtools.annotations import guarded_by
from ray_tpu.core.exceptions import GetTimeoutError
from ray_tpu.train.session import TrainContext, drain_reports, set_context


@guarded_by("_res_lock", "_result", "_error")
class TrainWorker:
    """Actor hosting one training worker; the user's train_fn runs on a
    dedicated thread so poll() stays responsive (max_concurrency=4)."""

    def __init__(self, rank: int, world_size: int, experiment: str,
                 storage_path: str | None, env: dict[str, str] | None = None):
        import os

        for k, v in (env or {}).items():
            os.environ[k] = v
        self.ctx = TrainContext(
            world_rank=rank, world_size=world_size, experiment_name=experiment,
            storage_path=storage_path, local_rank=0,
        )
        self._thread: threading.Thread | None = None
        self._status = "IDLE"  # IDLE | RUNNING | FINISHED | ERRORED
        # Result/context handoff train-fn thread -> actor-call thread
        # (rtlint R1): poll() must never see a half-published result.
        self._res_lock = threading.Lock()
        self._result: Any = None
        self._error: str | None = None

    def reconfigure(self, rank: int, world_size: int, experiment: str,
                    storage_path: str | None) -> bool:
        """Re-rank a pre-warmed spare (or a finished worker) into a new
        group: fresh context, clean status. The process — with its imported
        framework and warmed jax backend — is the asset being recycled."""
        if self._status == "RUNNING":
            raise RuntimeError("cannot reconfigure a running worker")
        old_writer = getattr(self.ctx, "_replica_writer", None)
        if old_writer is not None:
            try:
                old_writer.close()  # don't strand a push thread per restart
            except Exception:
                pass
        with self._res_lock:
            self.ctx = TrainContext(
                world_rank=rank, world_size=world_size,
                experiment_name=experiment,
                storage_path=storage_path, local_rank=0,
            )
            self._thread = None
            self._status = "IDLE"
            self._result = None
            self._error = None
        return True

    def setup_env(self, coordinator_addr: str | None, restart_count: int,
                  latest_checkpoint: str | None, num_slices: int = 1,
                  replica: dict | None = None):
        self.ctx.coordinator_addr = coordinator_addr
        self.ctx.restart_count = restart_count
        self.ctx.latest_checkpoint = latest_checkpoint
        self.ctx.num_slices = max(1, int(num_slices))
        self.ctx.replica = dict(replica) if replica else None
        return True

    def set_dataset_shards(self, shards: dict) -> bool:
        self.ctx.dataset_shards = dict(shards)
        return True

    def run(self, train_fn: Callable, config: dict | None) -> bool:
        if self._status == "RUNNING":
            raise RuntimeError("worker already running")
        self._status = "RUNNING"

        def main():
            import inspect

            set_context(self.ctx)
            try:
                if len(inspect.signature(train_fn).parameters) >= 1:
                    result = train_fn(config if config is not None else {})
                else:
                    result = train_fn()
                with self._res_lock:
                    self._result = result
                    self._status = "FINISHED"
            except BaseException:  # noqa: BLE001
                with self._res_lock:
                    self._error = traceback.format_exc()
                    self._status = "ERRORED"
            finally:
                set_context(None)

        self._thread = threading.Thread(target=main, daemon=True,
                                        name=f"train-fn-{self.ctx.world_rank}")
        self._thread.start()
        return True

    def poll(self) -> dict:
        return {
            "rank": self.ctx.world_rank,
            "status": self._status,
            "reports": drain_reports(self.ctx),
            "error": self._error,
        }

    def get_result(self):
        return self._result

    def ping(self) -> str:
        return "pong"

    def exec_fn(self, fn, *args, **kwargs):
        """Run an arbitrary function in this worker (backend setup hooks)."""
        return fn(*args, **kwargs)


@dataclass
class WorkerStatus:
    finished: bool = False
    errors: dict[int, str] = field(default_factory=dict)
    # rank -> death reason: the actor itself is gone (process killed, node
    # lost), as opposed to an error the train_fn raised and reported.
    dead: dict[int, str] = field(default_factory=dict)
    reports: list[dict] = field(default_factory=list)


def _actor_options(scaling) -> dict[str, Any]:
    res = scaling.worker_resources()
    opts: dict[str, Any] = {"max_concurrency": 4}
    opts["num_cpus"] = res.get("CPU", 0)
    opts["num_tpus"] = res.get("TPU", 0)
    extra = {k: v for k, v in res.items() if k not in ("CPU", "TPU")}
    if extra:
        opts["resources"] = extra
    return opts


def create_spare(scaling, experiment: str, storage_path: str | None,
                 env: dict[str, str] | None = None):
    """A hot-spare TrainWorker actor outside any group (rank -1): its
    process boots (framework + jax import — the seconds that dominate a
    cold restart) while training runs, and a later group recycles it via
    reconfigure()."""
    WorkerActor = ray_tpu.remote(TrainWorker)
    return WorkerActor.options(**_actor_options(scaling)).remote(
        -1, 0, experiment, storage_path, env)


class WorkerGroup:
    def __init__(self, scaling, experiment: str, storage_path: str | None,
                 env: dict[str, str] | None = None,
                 num_workers: int | None = None,
                 recycled: list | None = None):
        self.scaling = scaling
        n = num_workers if num_workers is not None else scaling.num_workers
        self.num_workers = n
        opts = _actor_options(scaling)
        WorkerActor = ray_tpu.remote(TrainWorker)
        spares = list(recycled or [])
        self.recycled_count = 0
        self.workers = []
        for rank in range(n):
            handle = None
            while spares and handle is None:
                cand = spares.pop(0)
                try:
                    ray_tpu.get([cand.reconfigure.remote(
                        rank, n, experiment, storage_path)], timeout=30)
                    handle = cand
                    self.recycled_count += 1
                except Exception:  # noqa: BLE001 - spare died while idle
                    try:
                        ray_tpu.kill(cand)
                    except Exception:
                        pass
            if handle is None:
                handle = WorkerActor.options(**opts).remote(
                    rank, n, experiment, storage_path, env)
            self.workers.append(handle)

    def setup(self, coordinator_addr: str | None, restart_count: int,
              latest_checkpoint: str | None, num_slices: int = 1,
              replica: dict | None = None):
        ray_tpu.get([
            w.setup_env.remote(coordinator_addr, restart_count,
                               latest_checkpoint, num_slices, replica)
            for w in self.workers
        ], timeout=120)

    def assign_dataset_shards(self, per_rank: list[dict]) -> None:
        """per_rank[i] = {name: DataIterator} for worker rank i."""
        ray_tpu.get([w.set_dataset_shards.remote(per_rank[i])
                     for i, w in enumerate(self.workers)], timeout=120)

    def run(self, train_fn: Callable, config: dict | None):
        ray_tpu.get([w.run.remote(train_fn, config) for w in self.workers],
                    timeout=120)

    def poll_status(self, timeout: float = 30.0) -> WorkerStatus:
        status = WorkerStatus()
        refs = [w.poll.remote() for w in self.workers]
        polls: list[dict | None] = []
        for rank, ref in enumerate(refs):
            try:
                polls.append(ray_tpu.get([ref], timeout=timeout)[0])
            except GetTimeoutError:
                raise  # poll stall is the caller's timeout, not a death
            except Exception as e:  # noqa: BLE001 - ActorDied/connection
                status.dead[rank] = f"{type(e).__name__}: {e}"
                polls.append(None)
        states = [p["status"] for p in polls if p is not None]
        for p in polls:
            if p is None:
                continue
            status.reports.extend(
                {**r, "rank": p["rank"]} for r in p["reports"])
            if p["error"]:
                status.errors[p["rank"]] = p["error"]
        status.finished = (not status.dead
                           and all(s == "FINISHED" for s in states))
        return status

    def results(self) -> list:
        return ray_tpu.get([w.get_result.remote() for w in self.workers],
                           timeout=120)

    def shutdown(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass


class SparePool:
    """Controller-owned reserve of pre-warmed TrainWorker actors. fill()
    creates them without blocking (actor creation is async; each spare's
    process boots in the background and we fire a ping to force the spawn);
    take() hands alive spares to the next WorkerGroup, which promotes them
    via reconfigure()."""

    def __init__(self, scaling, experiment: str, storage_path: str | None,
                 size: int, env: dict[str, str] | None = None,
                 warmup: Callable | None = None):
        self.scaling = scaling
        self.experiment = experiment
        self.storage_path = storage_path
        self.size = max(0, int(size))
        self.env = env
        self.warmup = warmup
        self._spares: list = []

    def fill(self) -> None:
        while len(self._spares) < self.size:
            h = create_spare(self.scaling, self.experiment,
                             self.storage_path, self.env)
            if self.warmup is not None:
                # Run the user's warmup (imports, mesh, compile) in the
                # spare NOW, in the background — promotion later finds the
                # process hot. Result/errors discarded: a broken warmup
                # degrades promotion back to first-step cost, not failure.
                h.exec_fn.remote(self.warmup)
            else:
                h.ping.remote()  # force the process spawn; result discarded
            self._spares.append(h)

    def take(self, k: int) -> list:
        out, self._spares = self._spares[:k], self._spares[k:]
        return out

    def available(self) -> int:
        return len(self._spares)

    def shutdown(self) -> None:
        for h in self._spares:
            try:
                ray_tpu.kill(h)
            except Exception:  # noqa: BLE001
                pass
        self._spares.clear()
