"""Serve inference fast path: KV-block-aware prefix routing
(serve/prefix.py + the router/controller/replica publication loop) and
the router hot path. Router-level tests run without a cluster, like
test_serve_resilience.TestRouterChurn; end-to-end drills carry the
``serveload`` marker. The zero-copy P/D KV hand-off round-trips live in
tests/test_pd_kv_handoff.py."""

import threading
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.serve.config import ReplicaInfo
from ray_tpu.serve.prefix import (
    block_hashes,
    match_len,
    text_block_hashes,
    union_hashes,
)
from ray_tpu.serve.router import Router


def _replicas(n, cap=4, draining=(), prefix=None, block=8):
    """prefix: {index: token-id sequence} — published as chain hashes."""
    out = []
    for i in range(n):
        blocks = None
        if prefix and i in prefix:
            blocks = union_hashes([prefix[i]], block)
        out.append(ReplicaInfo(
            replica_id=f"r{i}", deployment_name="d", actor_name=f"a{i}",
            max_ongoing_requests=cap, draining=(i in draining),
            prefix_blocks=blocks, prefix_block=block if blocks else 0))
    return out


# ------------------------------------------------------------- hash units
class TestPrefixHashes:
    def test_chained_blocks_identify_whole_prefix(self):
        a = list(range(100))
        b = list(range(100))
        b[50] = 999  # diverges inside block 6 (block=8: tokens 48..55)
        ha, hb = block_hashes(a, 8), block_hashes(b, 8)
        assert len(ha) == 100 // 8
        assert ha[:6] == hb[:6]
        # chaining: every hash AFTER the divergence differs too
        assert all(x != y for x, y in zip(ha[6:], hb[6:]))

    def test_partial_tail_block_not_hashed(self):
        assert len(block_hashes(list(range(17)), 8)) == 2
        assert block_hashes([1, 2, 3], 8) == ()
        assert block_hashes([], 8) == ()
        assert block_hashes([1, 2], 0) == ()

    def test_match_len_stops_at_first_miss(self):
        h = block_hashes(list(range(64)), 8)
        held = set(h[:5])
        assert match_len(h, held) == 5
        held.add(h[7])  # a gap: chained publication can't produce this
        assert match_len(h, held) == 5

    def test_text_domain_stable(self):
        h1 = text_block_hashes("sys-prompt " * 50, 64)
        h2 = text_block_hashes("sys-prompt " * 50 + "tail", 64)
        assert h1 and h1 == h2[:len(h1)]

    def test_stable_across_input_container(self):
        ids = tuple(range(32))
        assert block_hashes(ids, 8) == block_hashes(list(ids), 8) == \
            block_hashes(np.asarray(ids), 8)


# --------------------------------------------------------- router scoring
class TestPrefixRouting:
    def test_longest_match_wins(self):
        shared = list(range(64))
        reps = _replicas(3, prefix={0: shared[:16], 1: shared[:48]})
        router = Router("d", lambda: reps)
        router.notify_replicas_changed(reps)
        req = block_hashes(shared, 8)
        for _ in range(50):
            got = router._choose_locked(reps, prefix_hashes=req)
            assert got is not None and got.replica_id == "r1"

    def test_tie_break_equal_match_goes_least_loaded(self):
        shared = list(range(32))
        reps = _replicas(3, cap=100, prefix={0: shared, 2: shared})
        router = Router("d", lambda: reps)
        router.notify_replicas_changed(reps)
        with router._lock:
            router._inflight["r0"] = 2
            router._inflight["r2"] = 0
        req = block_hashes(shared, 8)
        for _ in range(50):
            got = router._choose_locked(reps, prefix_hashes=req)
            assert got is not None and got.replica_id == "r2"

    def test_balance_delta_overrides_locality(self):
        shared = list(range(32))
        reps = _replicas(2, cap=100, prefix={0: shared})
        router = Router("d", lambda: reps)
        router.notify_replicas_changed(reps)
        with router._lock:
            # matched replica is far above the least-loaded sibling
            router._inflight["r0"] = router.HINT_BALANCE_DELTA + 3
            router._inflight["r1"] = 0
        got = router._choose_locked(reps,
                                    prefix_hashes=block_hashes(shared, 8))
        assert got is not None and got.replica_id == "r1"

    def test_no_match_falls_back_to_pow2(self):
        reps = _replicas(3, prefix={0: list(range(32))})
        router = Router("d", lambda: reps)
        router.notify_replicas_changed(reps)
        req = block_hashes(list(range(1000, 1064)), 8)
        seen = {router._choose_locked(reps, prefix_hashes=req).replica_id
                for _ in range(100)}
        assert len(seen) > 1  # not pinned anywhere

    def test_never_prefix_routes_to_draining_replica(self):
        """Satellite regression guard (extends the PR-8 draining pin): the
        replica with the BEST prefix match is draining — it must get no
        traffic, via hint, prefix, or pow-2."""
        shared = list(range(64))
        reps = _replicas(3, draining={1},
                         prefix={1: shared, 0: shared[:8]})
        router = Router("d", lambda: reps)
        router.notify_replicas_changed(reps)
        req = block_hashes(shared, 8)
        for _ in range(100):
            got = router._choose_locked(reps, route_hint="h",
                                        prefix_hashes=req)
            assert got is not None and got.replica_id != "r1"
        # and the drain also evicted it from the prefix map itself
        assert "r1" not in router._prefix_map

    def test_prefix_map_drops_dead_replicas_on_snapshot(self):
        shared = list(range(32))
        reps = _replicas(3, prefix={0: shared, 1: shared})
        router = Router("d", lambda: reps)
        router.notify_replicas_changed(reps)
        assert set(router._prefix_map) == {"r0", "r1"}
        # r0 dies: the next snapshot no longer lists it
        survivors = [r for r in reps if r.replica_id != "r0"]
        router.notify_replicas_changed(survivors)
        assert set(router._prefix_map) == {"r1"}
        got = router._choose_locked(survivors,
                                    prefix_hashes=block_hashes(shared, 8))
        assert got is not None and got.replica_id == "r1"

    def test_prefix_map_ttl_ages_out_stale_entries(self):
        shared = list(range(32))
        reps = _replicas(2, prefix={0: shared})
        router = Router("d", lambda: reps)
        router.notify_replicas_changed(reps)
        router._prefix_ttl = 0.05
        time.sleep(0.08)  # no snapshot refresh within the TTL
        req = block_hashes(shared, 8)
        seen = {router._choose_locked(reps, prefix_hashes=req).replica_id
                for _ in range(100)}
        assert len(seen) > 1  # aged out: degraded to pow-2, not pinned

    def test_long_poll_liveness_refreshes_ttl(self):
        """The controller republishes only on CHANGE: a healthy
        deployment with a stable warm cache sends no snapshots, so each
        completed long-poll round touches the map — the TTL must expire
        only when polling stops (wedged controller), never steady state."""
        shared = list(range(32))
        reps = _replicas(2, prefix={0: shared})
        router = Router("d", lambda: reps)
        router.notify_replicas_changed(reps)
        router._prefix_ttl = 0.05
        req = block_hashes(shared, 8)
        for _ in range(4):  # total sleep well past the TTL
            time.sleep(0.03)
            router.touch_prefix_map()  # = one completed listen round
        got = router._choose_locked(reps, prefix_hashes=req)
        assert got is not None and got.replica_id == "r0"  # still pinned

    def test_breaker_open_match_falls_through(self):
        from ray_tpu.serve.resilience import CircuitBreakerConfig

        shared = list(range(32))
        reps = _replicas(2, prefix={0: shared})
        router = Router("d", lambda: reps)
        router.notify_replicas_changed(reps)
        router.breaker.config = CircuitBreakerConfig(
            failure_threshold=1, open_s=60.0)
        router.breaker.record_failure("r0")
        got = router._choose_locked(reps,
                                    prefix_hashes=block_hashes(shared, 8))
        assert got is not None and got.replica_id == "r1"


# ------------------------------------------------- engine hash publication
def test_engine_publishes_cached_prefix_hashes():
    from ray_tpu.llm import LLMConfig, LLMEngine, SamplingParams

    cfg = LLMConfig(model="tiny", max_num_seqs=2, max_seq_len=96,
                    prefix_block_tokens=8)
    eng = LLMEngine(cfg)
    try:
        prompt = list(range(1, 34))  # 33 tokens -> 4 full blocks of 8
        eng.generate(prompt, SamplingParams(max_tokens=2, temperature=0.0),
                     timeout=120)
        held = set(eng.prefix_block_hashes())
        want = block_hashes(prompt, 8)
        assert want and set(want) <= held
        # request-side hashes of a shared-prefix prompt match fully
        req = block_hashes(prompt + [200, 201, 202], 8)
        assert match_len(req, held) == len(want)
        # an unrelated prompt matches nothing
        assert match_len(block_hashes(list(range(500, 533)), 8), held) == 0
    finally:
        eng.shutdown()


# --------------------------------------------------------- e2e publication
@pytest.fixture
def serve_rt():
    try:
        ray_tpu.shutdown()
        ray_tpu.init()
    except Exception as e:  # noqa: BLE001 - environment without runtime
        pytest.skip(f"serve runtime unavailable: {e}")
    yield
    serve.shutdown()
    ray_tpu.shutdown()


@pytest.mark.serveload
def test_controller_publishes_prefix_blocks_and_router_scores(
        serve_rt, tmp_path):
    """End to end: a deployment whose callable publishes
    router_prefix_blocks reaches the router's prefix map through the
    controller poll + long-poll snapshot, and matching requests land on
    the publishing replica."""
    marker = list(range(100, 132))
    hashes = list(block_hashes(marker, 8))

    @serve.deployment(num_replicas=2, max_ongoing_requests=8,
                      health_check_period_s=0.2)
    class Cachey:
        def __init__(self, claim_dir):
            # exactly ONE replica claims (and publishes) the prefix —
            # replica instances can't share class state, so claim through
            # the filesystem like the PR-8 hedge drill.
            import os

            try:
                os.mkdir(os.path.join(claim_dir, "prefix-claimed"))
                self.claimed = True
            except FileExistsError:
                self.claimed = False

        def router_prefix_blocks(self):
            return {"blocks": hashes, "block": 8} if self.claimed else \
                {"blocks": [], "block": 8}

        def __call__(self, x):
            return self.claimed

    handle = serve.run(Cachey.bind(str(tmp_path)), route_prefix=None)
    router = handle._ensure_router()
    # generous: controller poll (0.5 s cadence) + long-poll fan-out must
    # land under full-suite load on the 1-core box
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if any(held for held, _ in router._prefix_map.values()):
            break
        time.sleep(0.05)
    assert any(held for held, _ in router._prefix_map.values()), \
        "prefix publication never reached the router"
    # requests whose hashes extend the published prefix pin to the
    # claiming replica (12/12). The reaper releases in-flight counts
    # asynchronously — drain between sequential requests so stale counts
    # can't trip the HINT_BALANCE_DELTA diversion (by-design balancing,
    # but a flake in a determinism assertion).
    def drained():
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            with router._lock:
                if not any(router._inflight.values()):
                    return
            time.sleep(0.005)

    req_hashes = tuple(block_hashes(marker + [7, 8, 9], 8))
    got = []
    for _ in range(12):
        drained()
        got.append(handle.options(prefix_hashes=req_hashes).remote("x")
                   .result(timeout=30))
    assert all(got), f"prefix-matched requests scattered: {got}"
    # ...while unmatched requests still spread over both replicas
    spread = {handle.remote("x").result(timeout=30) for _ in range(30)}
    assert spread == {True, False}


@pytest.mark.serveload
def test_router_throughput_smoke(serve_rt):
    """Load-factor-scaled router hot-path floor: closed-loop unary
    assignments through the full handle → router → replica → reaper path
    must clear a floor that a per-request-thread router could not.
    The full bench (devbench/router_bench.py) gates 10k+/s on an idle
    box; this smoke uses a conservative floor so suite load can't flake
    it."""
    from _test_util import load_factor

    @serve.deployment(num_replicas=2, max_ongoing_requests=64,
                      max_queued_requests=-1)
    class Echo:
        def __call__(self, x):
            return x

    handle = serve.run(Echo.bind(), route_prefix=None)
    router = handle._ensure_router()
    # warmup (compile/jit-free path, but primes caches + reaper)
    for i in range(50):
        handle.remote(i).result(timeout=30)

    stop = time.monotonic() + 1.5
    counts = [0] * 4

    def client(k):
        while time.monotonic() < stop:
            ref, rid = router.assign_request("__call__", (k,), {},
                                             timeout=10.0)
            ray_tpu.get(ref, timeout=10)
            counts[k] += 1

    threads = [threading.Thread(target=client, args=(k,)) for k in range(4)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    took = time.monotonic() - t0
    rps = sum(counts) / took
    floor = 1500.0 / load_factor()
    assert rps >= floor, \
        f"router hot path {rps:.0f} req/s under the {floor:.0f} floor"
