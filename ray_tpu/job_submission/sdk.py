"""Job submission SDK: HTTP client for the dashboard's job REST surface.

Capability parity with the reference's JobSubmissionClient (reference:
python/ray/dashboard/modules/job/sdk.py:36 JobSubmissionClient —
submit_job/get_job_status/get_job_logs/stop_job/delete_job/list_jobs over the
dashboard REST API).
"""

from __future__ import annotations

import json
import time
import urllib.parse
import urllib.request


class JobSubmissionClient:
    def __init__(self, address: str):
        """``address`` is the dashboard HTTP address, e.g. ``http://host:port``."""
        self._base = address.rstrip("/")
        if not self._base.startswith("http"):
            self._base = f"http://{self._base}"

    def _get(self, path: str) -> dict | list:
        with urllib.request.urlopen(f"{self._base}{path}", timeout=30) as r:
            return json.loads(r.read())

    def _post(self, path: str, payload: dict) -> dict:
        req = urllib.request.Request(
            f"{self._base}{path}", data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=30) as r:
            return json.loads(r.read())

    def submit_job(self, *, entrypoint: str, submission_id: str | None = None,
                   runtime_env: dict | None = None,
                   metadata: dict | None = None) -> str:
        payload = {"entrypoint": entrypoint}
        if submission_id:
            payload["submission_id"] = submission_id
        if runtime_env:
            payload["runtime_env"] = runtime_env
        if metadata:
            payload["metadata"] = metadata
        return self._post("/api/jobs/submit", payload)["submission_id"]

    def get_job_info(self, submission_id: str) -> dict:
        sid = urllib.parse.quote(submission_id, safe="")
        return self._get(f"/api/jobs/status?submission_id={sid}")

    def get_job_status(self, submission_id: str) -> str:
        return self.get_job_info(submission_id)["status"]

    def get_job_logs(self, submission_id: str) -> str:
        sid = urllib.parse.quote(submission_id, safe="")
        return self._get(f"/api/jobs/logs?submission_id={sid}")["logs"]

    def list_jobs(self) -> list[dict]:
        return self._get("/api/jobs/list")

    def stop_job(self, submission_id: str) -> bool:
        return self._post("/api/jobs/stop",
                          {"submission_id": submission_id})["stopped"]

    def delete_job(self, submission_id: str) -> bool:
        return self._post("/api/jobs/delete",
                          {"submission_id": submission_id})["deleted"]

    def wait_until_status(self, submission_id: str, statuses,
                          timeout: float = 60.0) -> str:
        deadline = time.monotonic() + timeout
        status = None
        while time.monotonic() < deadline:
            status = self.get_job_status(submission_id)
            if status in statuses:
                return status
            time.sleep(0.25)
        raise TimeoutError(
            f"job {submission_id} not in {statuses} within {timeout}s "
            f"(last: {status})")
