/* ray_tpu dashboard SPA — hand-written, no build toolchain.
 *
 * Capability parity with the reference's React client
 * (python/ray/dashboard/client/): live cluster state over the same JSON
 * endpoints this server already exposes — nodes / actors / tasks /
 * placement groups / jobs tables with auto-refresh, a per-node log viewer,
 * and overview stat tiles with sparklines fed from polled state history.
 */
"use strict";

const POLL_MS = 2500;
const HISTORY = 60; // sparkline points kept per metric (~2.5 min)

// ---------------------------------------------------------------- utilities

async function getJSON(path) {
  const r = await fetch(path);
  if (!r.ok) throw new Error(`${path}: HTTP ${r.status}`);
  return r.json();
}

function el(tag, attrs = {}, ...children) {
  const node = document.createElement(tag);
  for (const [k, v] of Object.entries(attrs)) {
    if (k === "class") node.className = v;
    else if (k.startsWith("on")) node.addEventListener(k.slice(2), v);
    else node.setAttribute(k, v);
  }
  for (const c of children) {
    node.append(c instanceof Node ? c : document.createTextNode(String(c)));
  }
  return node;
}

function shortId(v) {
  return typeof v === "string" && v.length > 14 ? v.slice(0, 12) + "…" : v;
}

const STATE_CLASS = {
  ALIVE: "good", RUNNING: "good", FINISHED: "good", SUCCEEDED: "good",
  CREATED: "neutral", PENDING: "warning", PENDING_CREATION: "warning",
  QUEUED: "warning", RESTARTING: "serious", RECONSTRUCTING: "serious",
  STOPPED: "neutral", DEAD: "critical", FAILED: "critical",
  REMOVED: "neutral",
};

function badge(state) {
  const cls = STATE_CLASS[state] || "neutral";
  return el("span", { class: `badge ${cls}` }, state ?? "—");
}

// ------------------------------------------------------------- sparklines

const tip = el("div", { id: "viz-tip" });
document.body.append(tip);

function sparkline(points, { width = 200, height = 36, label = "" } = {}) {
  const svg = document.createElementNS("http://www.w3.org/2000/svg", "svg");
  svg.setAttribute("viewBox", `0 0 ${width} ${height}`);
  svg.setAttribute("preserveAspectRatio", "none");
  if (points.length < 2) return svg;
  const max = Math.max(...points, 1e-9);
  const min = Math.min(...points, 0);
  const span = max - min || 1;
  const xs = points.map((_, i) => (i / (points.length - 1)) * width);
  const ys = points.map(p => height - 3 - ((p - min) / span) * (height - 6));
  const line = xs.map((x, i) => `${i ? "L" : "M"}${x.toFixed(1)},${ys[i].toFixed(1)}`).join("");
  const fill = `${line}L${width},${height}L0,${height}Z`;
  const mk = (d, cls) => {
    const p = document.createElementNS("http://www.w3.org/2000/svg", "path");
    p.setAttribute("d", d);
    p.setAttribute("class", cls);
    return p;
  };
  svg.append(mk(fill, "spark-fill"), mk(line, "spark-line"));
  // hover layer: nearest-point crosshair tooltip
  const dot = document.createElementNS("http://www.w3.org/2000/svg", "circle");
  dot.setAttribute("r", "3");
  dot.setAttribute("class", "spark-dot");
  dot.style.display = "none";
  svg.append(dot);
  svg.addEventListener("mousemove", ev => {
    const rect = svg.getBoundingClientRect();
    const fx = ((ev.clientX - rect.left) / rect.width) * width;
    let best = 0;
    for (let i = 1; i < xs.length; i++) {
      if (Math.abs(xs[i] - fx) < Math.abs(xs[best] - fx)) best = i;
    }
    dot.style.display = "";
    dot.setAttribute("cx", xs[best]);
    dot.setAttribute("cy", ys[best]);
    const ago = ((points.length - 1 - best) * POLL_MS) / 1000;
    tip.style.display = "block";
    tip.style.left = `${ev.clientX + 12}px`;
    tip.style.top = `${ev.clientY + 12}px`;
    tip.textContent = `${label}: ${points[best]} (${ago.toFixed(0)}s ago)`;
  });
  svg.addEventListener("mouseleave", () => {
    dot.style.display = "none";
    tip.style.display = "none";
  });
  return svg;
}

// --------------------------------------------------------------- overview

const history = new Map(); // metric name -> number[]

function record(name, value) {
  if (!Number.isFinite(value)) return;
  const arr = history.get(name) || [];
  arr.push(value);
  while (arr.length > HISTORY) arr.shift();
  history.set(name, arr);
}

function tile(label, value, sparkKey) {
  const t = el("div", { class: "tile" },
    el("div", { class: "label" }, label),
    el("div", { class: "value" }, value));
  const pts = history.get(sparkKey) || [];
  t.append(sparkline(pts, { label }));
  return t;
}

// Internal scheduling markers, not schedulable resources: PG-derived keys
// and accelerator head/host markers must not render as utilization bars.
function isMarkerResource(key) {
  return /(^node:)|(^bundle_)|(_pg_)|(-head$)/.test(key);
}

async function renderOverview(view, key) {
  const [status, summary, nodes, actors] = await Promise.all([
    getJSON("/api/cluster_status"), getJSON("/api/task_summary"),
    getJSON("/api/nodes"), getJSON("/api/actors"),
  ]);
  if (view.dataset.tab !== key) return; // stale render: tab changed
  const total = status.cluster_resources || {};
  const avail = status.available_resources || {};
  // summary shape: {task_name: {STATE: count, ...}, ...}
  const byState = {};
  for (const states of Object.values(summary || {})) {
    for (const [s, n] of Object.entries(states)) {
      byState[s] = (byState[s] || 0) + n;
    }
  }
  const running = byState.RUNNING || 0;
  const finished = byState.FINISHED || 0;
  const failed = byState.FAILED || 0;
  const aliveNodes = nodes.filter(n => n.alive).length;
  const aliveActors = actors.filter(a => a.state === "ALIVE").length;
  const cpuUsed = (total.CPU || 0) - (avail.CPU || 0);

  record("running", running);
  record("finished", finished);
  record("cpu_used", cpuUsed);
  record("actors", aliveActors);

  view.replaceChildren(
    el("h2", {}, "Cluster"),
    el("div", { class: "tiles" },
      tile("Tasks running", running, "running"),
      tile("Tasks finished", finished, "finished"),
      tile("CPUs in use", cpuUsed, "cpu_used"),
      tile("Live actors", aliveActors, "actors")),
    el("h2", {}, "Resources"),
    el("div", {},
      ...Object.keys(total).filter(k => !isMarkerResource(k)).sort().map(k => {
        const used = (total[k] || 0) - (avail[k] || 0);
        const pct = total[k] ? (used / total[k]) * 100 : 0;
        return el("div", { class: "resbar" },
          el("span", { class: "name" }, k),
          el("span", { class: "track" },
            el("span", { class: "used", style: `width:${pct.toFixed(1)}%` })),
          el("span", { class: "nums" },
            `${used.toFixed(1)} / ${(total[k] || 0).toFixed(1)}`));
      })),
    el("h2", {}, "Health"),
    el("div", {},
      el("span", {}, `${aliveNodes}/${nodes.length} nodes alive · `),
      el("span", {}, `${failed} failed tasks `),
      failed ? badge("FAILED") : badge("ALIVE")));
}

// ----------------------------------------------------------------- tables

function table(rows, columns, filterText) {
  const needle = (filterText || "").toLowerCase();
  const filtered = needle
    ? rows.filter(r => JSON.stringify(r).toLowerCase().includes(needle))
    : rows;
  const thead = el("tr", {}, ...columns.map(c => el("th", {}, c.title)));
  const body = filtered.map(r =>
    el("tr", {}, ...columns.map(c => {
      const v = c.get(r);
      return el("td", { class: c.mono ? "mono" : "" },
        v instanceof Node ? v : (v ?? "—"));
    })));
  return el("table", {}, thead, ...body);
}

const ROW_CAP = 500; // rows per table; server-side limited AND DOM-capped

function tableTab(endpoint, columns) {
  const sep = endpoint.includes("?") ? "&" : "?";
  const url = `${endpoint}${sep}limit=${ROW_CAP}`;
  let filter = "";
  return async (view, key) => {
    const rows = (await getJSON(url)).slice(0, ROW_CAP);
    if (view.dataset.tab !== key) return; // stale render: tab changed
    // Refresh in place: replacing the <input> mid-keystroke would steal
    // focus/caret every poll, so reuse it and swap only the table.
    let input = view.querySelector("input[type=text]");
    if (!input) {
      input = el("input", {
        type: "text", placeholder: "filter…", value: filter,
      });
      view.replaceChildren(
        el("div", { class: "toolbar" }, input,
          el("span", { class: "muted" })),
        table([], columns, ""));
    }
    const redraw = rs => {
      const old = view.querySelector("table");
      if (old) old.replaceWith(table(rs, columns, filter));
    };
    input.oninput = ev => {
      filter = ev.target.value;
      redraw(rows);
    };
    view.querySelector(".muted").textContent =
      rows.length >= ROW_CAP ? `first ${ROW_CAP} rows` : `${rows.length} rows`;
    redraw(rows);
  };
}

const TABS = {
  overview: { title: "Overview", render: renderOverview },
  nodes: {
    title: "Nodes",
    render: tableTab("/api/nodes", [
      { title: "Node", get: r => shortId(r.node_id), mono: true },
      { title: "State", get: r => badge(r.alive ? "ALIVE" : "DEAD") },
      { title: "Address", get: r => Array.isArray(r.addr)
          ? r.addr.join(":") : r.addr, mono: true },
      { title: "CPU", get: r => r.resources && r.resources.CPU },
      { title: "TPU", get: r => r.resources && (r.resources.TPU ?? "—") },
      { title: "Labels", get: r => JSON.stringify(r.labels || {}), mono: true },
    ]),
  },
  actors: {
    title: "Actors",
    render: tableTab("/api/actors", [
      { title: "Actor", get: r => shortId(r.actor_id), mono: true },
      { title: "Name", get: r => r.name },
      { title: "Namespace", get: r => r.namespace },
      { title: "State", get: r => badge(r.state) },
      { title: "Node", get: r => shortId(r.node_id), mono: true },
      { title: "Restarts", get: r => r.restarts },
      { title: "Death reason", get: r => r.death_reason },
    ]),
  },
  tasks: {
    title: "Tasks",
    render: tableTab("/api/tasks", [
      { title: "Task", get: r => shortId(r.task_id), mono: true },
      { title: "Name", get: r => r.name },
      { title: "State", get: r => badge(r.state) },
      { title: "Worker", get: r => shortId(r.worker_id), mono: true },
      { title: "Duration", get: r => (r.start_ts && r.end_ts)
          ? `${(r.end_ts - r.start_ts).toFixed(3)}s` : "—" },
    ]),
  },
  pgs: {
    title: "Placement Groups",
    render: tableTab("/api/placement_groups", [
      { title: "Group", get: r => shortId(r.placement_group_id), mono: true },
      { title: "Name", get: r => r.name },
      { title: "State", get: r => badge(r.state) },
      { title: "Strategy", get: r => r.strategy },
      { title: "Bundles", get: r => r.bundles != null
          ? JSON.stringify(r.bundles) : "—", mono: true },
    ]),
  },
  jobs: {
    title: "Jobs",
    render: async (view, key) => {
      let rows = [];
      try {
        rows = await getJSON("/api/jobs/list");
      } catch {
        if (view.dataset.tab !== key) return;
        view.replaceChildren(
          el("p", { class: "muted" },
            "Job manager not running in this session."));
        return;
      }
      if (view.dataset.tab !== key) return; // stale render: tab changed
      view.replaceChildren(table(rows, [
        { title: "Job", get: r => r.submission_id || r.job_id, mono: true },
        { title: "Status", get: r => badge(r.status) },
        { title: "Entrypoint", get: r => r.entrypoint, mono: true },
        { title: "Message", get: r => r.message },
      ]));
    },
  },
  logs: {
    title: "Logs",
    render: async (view, key) => {
      const nodes = await getJSON("/api/nodes");
      if (view.dataset.tab !== key) return; // stale render: tab changed
      const sel = el("select", {},
        ...nodes.map(n => el("option", { value: n.node_id },
          `${shortId(n.node_id)} (${n.alive ? "ALIVE" : "DEAD"})`)));
      const list = el("div", { class: "loglist" });
      const pre = el("pre", { class: "logview" }, "select a file…");
      async function loadList() {
        const files = await getJSON(
          `/api/logs?node_id=${encodeURIComponent(sel.value)}`);
        list.replaceChildren(...files.map(f =>
          el("a", {
            href: "#logs", onclick: async ev => {
              ev.preventDefault();
              const r = await fetch(
                `/api/logs/get?node_id=${encodeURIComponent(sel.value)}` +
                `&filename=${encodeURIComponent(f.filename || f)}`);
              pre.textContent = await r.text();
            },
          }, f.filename || f)));
      }
      sel.addEventListener("change", loadList);
      view.replaceChildren(
        el("div", { class: "toolbar" }, "Node: ", sel),
        list, pre);
      if (nodes.length) await loadList();
    },
    manual: true, // no auto-refresh: would clobber an open log view
  },
};

// ------------------------------------------------------------------ shell

const initialHash = location.hash.replace("#", "");
let active = TABS[initialHash] ? initialHash : "overview";
let timer = null;
let inFlightTab = null;

function nav() {
  const tabs = document.getElementById("tabs");
  // href navigation fires hashchange, which drives switchTab — no onclick
  // (a second handler would double-fetch every endpoint per click).
  tabs.replaceChildren(...Object.entries(TABS).map(([key, t]) =>
    el("a", {
      href: `#${key}`, class: key === active ? "active" : "",
    }, t.title)));
}

async function refresh() {
  // Single-flight PER TAB: a slow poll must not stack on itself, but a
  // tab switch may start rendering immediately (the stale-render guards
  // make the superseded render a no-op).
  const tab = active;
  if (inFlightTab === tab) return;
  inFlightTab = tab;
  const view = document.getElementById("view");
  const conn = document.getElementById("conn");
  if (!view.dataset.tab) view.dataset.tab = tab;
  try {
    await TABS[tab].render(view, tab);
    conn.classList.remove("down");
    conn.title = "connected";
  } catch (e) {
    conn.classList.add("down");
    conn.title = `disconnected: ${e}`;
  } finally {
    if (inFlightTab === tab) inFlightTab = null;
  }
}

function schedule() {
  if (timer) clearInterval(timer);
  timer = setInterval(() => {
    if (document.getElementById("auto").checked && !TABS[active].manual) {
      refresh();
    }
  }, POLL_MS);
}

function switchTab(key) {
  active = key;
  const view = document.getElementById("view");
  if (view.dataset.tab !== key) {
    view.dataset.tab = key;
    view.replaceChildren(); // don't let tab A's widgets leak into tab B
  }
  nav();
  refresh();
  schedule();
}

window.addEventListener("hashchange", () => {
  const key = location.hash.replace("#", "");
  if (TABS[key]) switchTab(key);
});

nav();
refresh();
schedule();
