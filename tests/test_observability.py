"""Metrics / tracing / task events / state API / dashboard.

Mirrors the reference's observability test surface (reference:
python/ray/tests/test_metrics_agent.py, test_state_api.py, tracing tests):
everything runs against the in-process runtime.
"""

import json
import urllib.error
import urllib.request

import pytest

from ray_tpu.core import events
from ray_tpu.util import metrics, tracing


@pytest.fixture(autouse=True)
def _clean_buffers():
    events.global_event_buffer().clear()
    tracing.clear()
    tracing.disable_tracing()
    yield
    tracing.disable_tracing()


class TestMetrics:
    def test_counter_gauge(self):
        c = metrics.Counter("test_requests_total", "reqs", tag_keys=("route",))
        c.inc(tags={"route": "/a"})
        c.inc(2, tags={"route": "/a"})
        c.inc(tags={"route": "/b"})
        g = metrics.Gauge("test_queue_depth", "depth")
        g.set(7)
        text = metrics.registry().export_prometheus()
        assert 'test_requests_total{route="/a"} 3.0' in text
        assert 'test_requests_total{route="/b"} 1.0' in text
        assert "test_queue_depth 7.0" in text
        assert "# TYPE test_requests_total counter" in text

    def test_histogram_buckets(self):
        h = metrics.Histogram("test_latency_s", "lat", boundaries=[0.1, 1.0])
        for v in (0.05, 0.5, 5.0):
            h.observe(v)
        text = metrics.registry().export_prometheus()
        assert 'test_latency_s_bucket{le="0.1"} 1' in text
        assert 'test_latency_s_bucket{le="1.0"} 2' in text
        assert 'test_latency_s_bucket{le="+Inf"} 3' in text
        assert "test_latency_s_count 3" in text

    def test_counter_rejects_negative_and_unknown_tags(self):
        c = metrics.Counter("test_neg", "", tag_keys=("a",))
        with pytest.raises(ValueError):
            c.inc(-1)
        with pytest.raises(ValueError):
            c.inc(tags={"bogus": "x"})

    def test_le_canonical_float_format(self):
        """Integer boundaries must render like their float equivalents
        (le="5.0", not le="5") so scrapers see one canonical format."""
        h = metrics.Histogram("test_int_bounds", "", boundaries=[1, 5])
        h.observe(0.5)
        h.observe(3)
        text = metrics.registry().export_prometheus()
        assert 'test_int_bounds_bucket{le="1.0"} 1' in text
        assert 'test_int_bounds_bucket{le="5.0"} 2' in text
        assert 'le="1"' not in text and 'le="5"' not in text

    def test_label_escaping_shared_helper(self):
        c = metrics.Counter("test_escape", "", tag_keys=("path",))
        c.inc(tags={"path": 'a"b\\c\nd'})
        text = metrics.registry().export_prometheus()
        assert 'path="a\\"b\\\\c\\nd"' in text

    def test_export_prometheus_concurrent_writers(self):
        """N writer threads inc/observe while the main thread exports: no
        exceptions, and the final export carries every increment."""
        import threading

        c = metrics.Counter("test_conc_total", "", tag_keys=("t",))
        h = metrics.Histogram("test_conc_lat", "", boundaries=[0.5, 1.0])
        n_threads, n_iters = 8, 300
        start = threading.Barrier(n_threads + 1)
        errors: list = []

        def writer(idx: int):
            try:
                start.wait(timeout=10)
                for _ in range(n_iters):
                    c.inc(tags={"t": str(idx)})
                    h.observe(0.25)
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=writer, args=(i,))
                   for i in range(n_threads)]
        for t in threads:
            t.start()
        start.wait(timeout=10)
        exports = []
        while any(t.is_alive() for t in threads):
            exports.append(metrics.registry().export_prometheus())
        for t in threads:
            t.join(timeout=10)
        assert not errors
        final = metrics.registry().export_prometheus()
        for i in range(n_threads):
            assert f'test_conc_total{{t="{i}"}} {float(n_iters)}' in final
        assert f"test_conc_lat_count {n_threads * n_iters}" in final
        assert exports  # exporting concurrently never raised

    def test_snapshot_merge_and_federated_export(self):
        """Round-trip: registry -> snapshot -> (merge) -> federated text
        with node_id labels on every series."""
        c = metrics.Counter("test_fed_total", "reqs", tag_keys=("route",))
        c.inc(2, tags={"route": "/x"})
        g = metrics.Gauge("test_fed_depth", "")
        g.set(3)
        h = metrics.Histogram("test_fed_lat", "", boundaries=[1.0])
        h.observe(0.5)
        snap_a = metrics.registry().snapshot()
        c.inc(3, tags={"route": "/x"})  # node B reports a later state
        snap_b = metrics.registry().snapshot()
        # Two processes on one node merge: counters sum, gauges last-write.
        merged = metrics.merge_snapshots([snap_a, snap_b])
        entry = next(e for e in merged["metrics"]
                     if e["name"] == "test_fed_total")
        assert dict((tuple(k), v) for k, v in entry["points"])[("/x",)] == 7.0
        text = metrics.export_prometheus_federated(
            {"nodeA": snap_a, "nodeB": snap_b})
        assert 'test_fed_total{route="/x",node_id="nodeA"} 2.0' in text
        assert 'test_fed_total{route="/x",node_id="nodeB"} 5.0' in text
        assert 'test_fed_depth{node_id="nodeA"} 3.0' in text
        assert 'test_fed_lat_bucket{node_id="nodeA",le="1.0"} 1' in text
        # HELP/TYPE once per metric name, not once per node
        assert text.count("# TYPE test_fed_total counter") == 1

    def test_dropped_events_counter_exported(self):
        buf = events.TaskEventBuffer(max_events=2)
        for i in range(5):
            buf.record(f"t{i}", "noisy", "SUBMITTED")
        assert buf.dropped == 3
        text = metrics.registry().export_prometheus()
        assert "task_events_dropped_total" in text
        value = next(
            float(line.rsplit(" ", 1)[1]) for line in text.splitlines()
            if line.startswith("task_events_dropped_total "))
        assert value >= 3


class TestTaskEventsAndTimeline:
    def test_events_recorded(self, rt_start):
        rt = rt_start

        @rt.remote
        def f():
            return 1

        assert rt.get(f.remote()) == 1
        states = {e.state for e in events.global_event_buffer().events()}
        assert {"SUBMITTED", "RUNNING", "FINISHED"} <= states

    def test_failed_task_event(self, rt_start):
        rt = rt_start

        @rt.remote(max_retries=0)
        def boom():
            raise ValueError("x")

        with pytest.raises(Exception):
            rt.get(boom.remote())
        states = [e.state for e in events.global_event_buffer().events()]
        assert "FAILED" in states

    def test_timeline_chrome_trace(self, rt_start, tmp_path):
        rt = rt_start

        @rt.remote
        def g():
            return 2

        rt.get([g.remote() for _ in range(3)])
        trace = rt.timeline()
        assert len(trace) >= 3
        assert all(ev["ph"] == "X" and ev["dur"] >= 0 for ev in trace)
        path = rt.timeline(str(tmp_path / "trace.json"))
        with open(path) as f:
            assert json.load(f)


class TestTracing:
    def test_span_propagation_into_task(self, rt_start):
        rt = rt_start
        tracing.enable_tracing()

        @rt.remote
        def traced():
            return 42

        with tracing.span("driver-op") as root:
            ref = traced.remote()
            assert rt.get(ref) == 42
        spans = tracing.spans()
        names = [s.name for s in spans]
        assert "driver-op" in names
        assert "traced" in names
        worker_span = next(s for s in spans if s.name == "traced")
        assert worker_span.trace_id == root.trace_id
        assert worker_span.parent_id == root.span_id

    def test_disabled_is_noop(self, rt_start):
        rt = rt_start

        @rt.remote
        def f():
            return 1

        rt.get(f.remote())
        assert tracing.spans() == []

    def test_span_error_status(self):
        tracing.enable_tracing()
        with pytest.raises(RuntimeError):
            with tracing.span("bad"):
                raise RuntimeError("no")
        s = tracing.spans()[-1]
        assert s.status.startswith("ERROR")
        assert s.attributes["exception.type"] == "RuntimeError"
        assert s.attributes["exception.message"] == "no"

    def test_span_context_restored_in_pool_threads(self):
        """A span opened on an executor pool thread must not leak its ids
        into the next task that reuses the same thread."""
        from concurrent.futures import ThreadPoolExecutor

        tracing.enable_tracing()
        pool = ThreadPoolExecutor(max_workers=1)

        def traced_work():
            with tracing.span("pooled-op"):
                pass
            return tracing.current_context()

        def probe():
            return tracing.current_context()

        assert pool.submit(traced_work).result() is None
        # Same thread, next task: no inherited context.
        assert pool.submit(probe).result() is None
        pool.shutdown()

    def test_flush_new_keeps_local_spans(self):
        tracing.enable_tracing()
        with tracing.span("a"):
            pass
        with tracing.span("b"):
            pass
        batch, cursor = tracing.flush_new(0)
        assert [s["name"] for s in batch] == ["a", "b"]
        assert len(tracing.spans()) == 2  # flush is a copy, not a drain
        batch2, cursor2 = tracing.flush_new(cursor)
        assert batch2 == [] and cursor2 == cursor
        with tracing.span("c"):
            pass
        batch3, _ = tracing.flush_new(cursor)
        assert [s["name"] for s in batch3] == ["c"]


class TestStateApi:
    def test_list_entities(self, rt_start):
        rt = rt_start
        from ray_tpu.util import state

        @rt.remote
        class A:
            def ping(self):
                return "pong"

        a = A.remote()
        assert rt.get(a.ping.remote()) == "pong"
        nodes = state.list_nodes()
        assert len(nodes) == 1 and nodes[0]["alive"]
        actors = state.list_actors()
        assert len(actors) == 1 and actors[0]["state"] == "ALIVE"
        tasks = state.list_tasks(filters=[("state", "=", "FINISHED")])
        assert any(t["name"] == "ping" for t in tasks)
        summary = state.summarize_tasks()
        assert summary["ping"]["FINISHED"] == 1
        objs = state.list_objects()
        assert objs[0]["num_objects"] >= 0

    def test_filters(self, rt_start):
        rt = rt_start

        @rt.remote
        def ok():
            return 1

        rt.get(ok.remote())
        from ray_tpu.util import state

        assert state.list_tasks(filters=[("state", "=", "NOPE")]) == []
        with pytest.raises(ValueError):
            state.list_tasks(filters=[("state", ">", "x")])


class TestClusterEvents:
    def test_worker_events_reach_driver(self, wait_for):
        """Worker-side RUNNING/FINISHED events flush to the head and appear in
        the driver's list_tasks and timeline (reference: TaskEventBuffer →
        GcsTaskManager → state API)."""
        import ray_tpu
        from ray_tpu.util import state

        ray_tpu.shutdown()
        ray_tpu.init(address="local-cluster", num_cpus=2)
        try:
            @ray_tpu.remote
            def traced_task():
                return 7

            assert ray_tpu.get(traced_task.remote()) == 7

            def finished():
                rows = state.list_tasks(filters=[("name", "=", "traced_task")])
                return rows and rows[0]["state"] == "FINISHED"

            wait_for(finished, timeout=15, desc="worker events at the head")
            trace = ray_tpu.timeline()
            assert any(ev["name"] == "traced_task" for ev in trace)
        finally:
            ray_tpu.shutdown()


class TestDashboard:
    def test_http_endpoints(self, rt_start):
        rt = rt_start
        from ray_tpu.dashboard.http_server import DashboardServer

        @rt.remote
        def h():
            return 1

        rt.get(h.remote())
        srv = DashboardServer()
        host, port = srv.start()
        try:
            def get(path):
                with urllib.request.urlopen(f"http://{host}:{port}{path}", timeout=5) as r:
                    body = r.read()
                    return r.headers.get_content_type(), body

            ctype, body = get("/api/version")
            assert ctype == "application/json"
            assert json.loads(body)["version"]
            _, body = get("/api/nodes")
            assert json.loads(body)[0]["alive"]
            _, body = get("/api/tasks")
            assert any(t["name"] == "h" for t in json.loads(body))
            _, body = get("/api/cluster_status")
            assert "cluster_resources" in json.loads(body)
            ctype, body = get("/metrics")
            assert ctype == "text/plain"
            _, body = get("/api/timeline")
            assert isinstance(json.loads(body), list)
            # web UI at the root: an SPA shell that loads the app module
            ctype, body = get("/")
            assert ctype == "text/html"
            page = body.decode()
            assert "/app.js" in page and "</html>" in page
            ctype, body = get("/app.js")
            assert ctype == "text/javascript"
            app = body.decode()
            # the client drives the same JSON API surface
            for ep in ("/api/cluster_status", "/api/nodes", "/api/actors",
                       "/api/tasks", "/api/placement_groups",
                       "/api/jobs/list", "/api/logs"):
                assert ep in app, ep
            ctype, _ = get("/app.css")
            assert ctype == "text/css"
            # per-node log endpoints exist (cluster mode returns data; the
            # in-process runtime yields an empty listing)
            _, body = get("/api/logs")
            assert json.loads(body) == []
        finally:
            srv.stop()

    def test_unknown_route_404(self, rt_start):
        from ray_tpu.dashboard.http_server import DashboardServer

        srv = DashboardServer()
        host, port = srv.start()
        try:
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(f"http://{host}:{port}/nope", timeout=5)
        finally:
            srv.stop()


def test_otlp_export_shape(rt_start):
    from ray_tpu.util import tracing

    tracing.clear()
    tracing.enable_tracing()
    try:
        with tracing.span("outer", kind="client"):
            with tracing.span("inner"):
                pass
        otlp = tracing.export_otlp()
        spans = otlp["resourceSpans"][0]["scopeSpans"][0]["spans"]
        names = {s["name"] for s in spans}
        assert {"outer", "inner"} <= names
        inner = next(s for s in spans if s["name"] == "inner")
        outer = next(s for s in spans if s["name"] == "outer")
        assert inner["parentSpanId"] == outer["spanId"]
        assert inner["traceId"] == outer["traceId"]
        assert int(inner["endTimeUnixNano"]) >= int(inner["startTimeUnixNano"])
    finally:
        tracing.disable_tracing()


def test_cross_process_trace_propagation(rt_start):
    """A traced submission's context rides the TaskSpec into the executor
    (reference: _DictPropagator through task metadata)."""
    import ray_tpu
    from ray_tpu import remote
    from ray_tpu.util import tracing

    tracing.clear()
    tracing.enable_tracing()
    try:
        @remote
        def traced():
            return 1

        with tracing.span("driver", kind="client"):
            ref = traced.remote()
        assert ray_tpu.get(ref, timeout=30) == 1
        by_name = {s.name: s for s in tracing.spans()}
        assert "driver" in by_name and "traced" in by_name
        assert by_name["traced"].trace_id == by_name["driver"].trace_id
    finally:
        tracing.disable_tracing()


def test_cli_status_and_list(rt_start, capsys):
    from ray_tpu.scripts.cli import main

    assert main(["status"]) == 0
    out = capsys.readouterr().out
    assert "Cluster resources" in out and "CPU" in out
    assert main(["list", "nodes", "--json"]) == 0
    import json as _json

    rows = _json.loads(capsys.readouterr().out)
    assert isinstance(rows, list)


def test_cli_timeline(rt_start, tmp_path, capsys):
    import ray_tpu
    from ray_tpu import remote
    from ray_tpu.scripts.cli import main

    @remote
    def work():
        return 1

    ray_tpu.get([work.remote() for _ in range(3)])
    out = str(tmp_path / "tl.json")
    assert main(["timeline", "--out", out]) == 0
    import json as _json

    doc = _json.load(open(out))
    # Chrome-trace object format: task slices + span rows under traceEvents.
    assert isinstance(doc, dict) and doc["traceEvents"]
    names = {ev.get("name") for ev in doc["traceEvents"]}
    assert "work" in names


def test_usage_recording(rt_start, tmp_path, monkeypatch):
    from ray_tpu import usage

    usage.record_library_usage("train")
    usage.record_library_usage("train")  # dedup
    assert "library:train" in usage.recorded_features()
    monkeypatch.setenv("RTPU_USAGE_STATS_ENABLED", "0")
    usage.record_library_usage("secret")
    assert "library:secret" not in usage.recorded_features()


class TestFlightRecorder:
    def test_failing_task_dumps_bundle(self, rt_start, tmp_path, wait_for,
                                       monkeypatch):
        """A terminally failing task produces a debug bundle with the task's
        events, the client + worker spans, and a metrics snapshot —
        retrievable via ray_tpu.util.state (reference capability: a
        post-mortem slice of GcsTaskManager + the metrics agent)."""
        import os

        from ray_tpu.core import flight_recorder
        from ray_tpu.utils.config import get_config

        monkeypatch.setattr(get_config(), "temp_dir", str(tmp_path))
        rt = rt_start
        tracing.enable_tracing()
        gate = str(tmp_path / "gate")

        @rt.remote(max_retries=0)
        def kaboom(gate_path):
            import os as _os
            import time as _time

            deadline = _time.monotonic() + 5
            while not _os.path.exists(gate_path) and \
                    _time.monotonic() < deadline:
                _time.sleep(0.005)
            raise ValueError("flight-test")

        with tracing.span("driver-submit", kind="client"):
            ref = kaboom.remote(gate)
        # Open the gate only once the client span is closed, so the bundle
        # dumped at failure time deterministically contains it.
        with open(gate, "w") as f:
            f.write("go")
        with pytest.raises(Exception):
            rt.get(ref)

        def bundle():
            for rec in reversed(flight_recorder.list_records()):
                b = flight_recorder.get_record(rec["name"])
                if b["kind"] == "task_failure" and any(
                        e["state"] == "FAILED" and e["name"] == "kaboom"
                        for e in b["events"]):
                    return b
            return None

        b = wait_for(bundle, timeout=10, desc="task_failure flight record")
        assert "flight-test" in b["reason"]
        span_names = {s["name"] for s in b["spans"]}
        assert "driver-submit" in span_names  # client side
        assert "kaboom" in span_names  # worker side
        worker_span = next(s for s in b["spans"] if s["name"] == "kaboom")
        client_span = next(s for s in b["spans"]
                           if s["name"] == "driver-submit")
        assert worker_span["trace_id"] == client_span["trace_id"]
        assert b["metrics"]["metrics"]  # snapshot captured
        assert os.path.dirname(bundle_path := flight_recorder.list_records()
                               [-1]["path"]) == flight_recorder.records_dir()
        assert os.path.exists(bundle_path)
        # state API surface
        from ray_tpu.util.state import get_flight_record, list_flight_records

        rows = list_flight_records(kind="task_failure")
        assert rows
        assert get_flight_record(rows[-1]["name"])["kind"] == "task_failure"

    def test_bundle_pruning(self, tmp_path, monkeypatch):
        from ray_tpu.core import flight_recorder
        from ray_tpu.utils.config import get_config

        monkeypatch.setattr(get_config(), "temp_dir", str(tmp_path))
        monkeypatch.setattr(get_config(), "flight_recorder_max_bundles", 3)
        monkeypatch.setattr(flight_recorder, "MIN_INTERVAL_S", 0.0)
        for i in range(6):
            assert flight_recorder.record("task_failure", reason=f"r{i}")
        rows = flight_recorder.list_records()
        assert len(rows) == 3
        assert flight_recorder.get_record(rows[-1]["name"])["reason"] == "r5"

    def test_disabled(self, tmp_path, monkeypatch):
        from ray_tpu.core import flight_recorder
        from ray_tpu.utils.config import get_config

        monkeypatch.setattr(get_config(), "temp_dir", str(tmp_path))
        monkeypatch.setattr(get_config(), "flight_recorder_enabled", False)
        assert flight_recorder.record("task_failure") is None
        assert flight_recorder.list_records() == []


class TestHotPathMetrics:
    def test_train_report_gauges(self):
        from ray_tpu.train import session

        # Distinctive rank: other suites' Trainer runs report under ranks
        # 0..n in this same process-wide registry.
        ctx = session.TrainContext(world_rank=77)
        session.set_context(ctx)
        try:
            session.report({"loss": 1.0, "tokens": 512})
            session.report({"loss": 0.9, "tokens": 512,
                            "flops": 1e9, "peak_flops": 1e12})
        finally:
            session.set_context(None)
        text = metrics.registry().export_prometheus()
        assert 'train_step_time_s{rank="77"}' in text
        assert 'train_tokens_per_s{rank="77"}' in text
        assert 'train_mfu{rank="77"}' in text
        assert 'train_reports_total{rank="77"} 2.0' in text

    def test_serve_replica_ttft_tpot(self):
        from ray_tpu.serve.replica import ServeReplica
        from ray_tpu.utils import serialization

        def double(x):
            return x * 2

        rep = ServeReplica("obsdep", "r1", serialization.serialize(double),
                           serialization.serialize(((), {})))
        assert rep.handle_request("__call__", (21,), {}) == 42
        text = metrics.registry().export_prometheus()
        assert 'serve_ttft_s_count{deployment="obsdep"} 1' in text
        assert 'serve_request_latency_s_count{deployment="obsdep"} 1' in text
        assert 'serve_replica_requests_total{deployment="obsdep",' \
               'replica="r1"} 1.0' in text

        def gen(n):
            for i in range(n):
                yield i

        rep2 = ServeReplica("obsgen", "r2", serialization.serialize(gen),
                            serialization.serialize(((), {})))
        chunks = list(rep2.handle_request_streaming("__call__", (3,), {}))
        assert chunks[0] == {"streaming": True} and chunks[1:] == [0, 1, 2]
        text = metrics.registry().export_prometheus()
        assert 'serve_ttft_s_count{deployment="obsgen"} 1' in text
        assert 'serve_tpot_s_count{deployment="obsgen"} 2' in text

    def test_collective_op_metrics(self, cpu_mesh_devices):
        import numpy as np

        try:
            import ray_tpu.collective as col
        except ImportError as e:  # pre-existing env gap (jax.shard_map)
            pytest.skip(f"collective backend unimportable here: {e}")

        col.init_collective_group(backend="xla", group_name="obs_coll",
                                  devices=cpu_mesh_devices, world_size=8)
        try:
            out = np.asarray(col.allreduce(np.ones(8, np.float32),
                                           group_name="obs_coll"))
            np.testing.assert_allclose(out, 8 * np.ones(8))
        finally:
            col.destroy_collective_group("obs_coll")
        text = metrics.registry().export_prometheus()
        assert 'collective_op_latency_s_count{op="allreduce",' \
               'group="obs_coll"} 1' in text
        assert 'collective_op_bytes_count{op="allreduce",' \
               'group="obs_coll"} 1' in text


class TestFederatedTelemetry:
    def test_two_node_metrics_at_head(self, wait_for):
        """Acceptance path: a 2-node cluster whose workers populate train +
        serve metrics; the head's telemetry table and the dashboard's
        /metrics show series from BOTH nodes under distinct node_id labels."""
        import urllib.request as _rq

        import ray_tpu
        from ray_tpu.cluster_utils import Cluster
        from ray_tpu.core.worker import global_worker
        from ray_tpu.utils.ids import JobID

        c = Cluster()
        c.add_node(num_cpus=1, node_id="obsnodea")
        c.add_node(num_cpus=1, node_id="obsnodeb")
        rt = c.connect()
        old = (global_worker.runtime, global_worker.worker_id,
               global_worker.node_id, global_worker.mode,
               global_worker.job_id)
        global_worker.runtime = rt
        global_worker.worker_id = rt.worker_id
        global_worker.node_id = rt.node_id
        global_worker.job_id = JobID.from_random()
        global_worker.mode = "cluster"
        try:
            @ray_tpu.remote(num_cpus=1)
            class Reporter:
                def bump(self):
                    from ray_tpu.serve.replica import ServeReplica
                    from ray_tpu.train import session
                    from ray_tpu.utils import serialization as ser

                    ctx = session.TrainContext(world_rank=0)
                    session.set_context(ctx)
                    session.report({"tokens": 128})
                    session.report({"tokens": 128})
                    session.set_context(None)
                    rep = ServeReplica(
                        "fed", "r0", ser.serialize(lambda x: x),
                        ser.serialize(((), {})))
                    rep.handle_request("__call__", (1,), {})
                    return True

            # One 1-CPU actor per 1-CPU node: placement must spread them.
            a, b = Reporter.remote(), Reporter.remote()
            assert ray_tpu.get([a.bump.remote(), b.bump.remote()],
                               timeout=120) == [True, True]

            def both_nodes():
                # Only WORKER-process sources count: this pytest process
                # (driver + in-process daemons, source "<node>:<ourpid>")
                # reports a registry other tests already filled with train
                # series, which must not satisfy the wait before both
                # Reporter workers actually flushed.
                import os as _os

                me = f":{_os.getpid()}"
                nodes = set()
                for src, row in rt.get_telemetry().get(
                        "sources", {}).items():
                    if src.endswith(me):
                        continue
                    for entry in (row.get("snapshot") or {}).get(
                            "metrics", []):
                        if entry["name"] == "train_step_time_s" and \
                                entry.get("points"):
                            nodes.add(row["node_id"])
                return nodes if len(nodes) >= 2 else None

            nodes = wait_for(both_nodes, timeout=30,
                             desc="train metrics from both nodes")
            assert nodes == {"obsnodea", "obsnodeb"}

            from ray_tpu.dashboard.http_server import DashboardServer

            srv = DashboardServer()
            host, port = srv.start()
            try:
                with _rq.urlopen(f"http://{host}:{port}/metrics",
                                 timeout=10) as r:
                    text = r.read().decode()
            finally:
                srv.stop()
            for nid in ("obsnodea", "obsnodeb"):
                assert f'train_step_time_s{{rank="0",node_id="{nid}"}}' \
                    in text, text[:2000]
                assert f'train_tokens_per_s{{rank="0",node_id="{nid}"}}' \
                    in text
            assert 'serve_ttft_s_bucket{deployment="fed"' in text
            assert 'serve_ttft_s_count{deployment="fed"' in text
        finally:
            rt.shutdown()
            c.shutdown()
            (global_worker.runtime, global_worker.worker_id,
             global_worker.node_id, global_worker.mode,
             global_worker.job_id) = old


class TestLogs:
    def test_list_and_tail_worker_logs(self, wait_for):
        """Per-node worker log listing + tail through the daemons
        (reference: `ray logs` via the dashboard agent)."""
        from ray_tpu.cluster_utils import Cluster
        from ray_tpu.core.remote_function import remote
        from ray_tpu.core.worker import global_worker
        from ray_tpu.util.state.api import get_log, list_logs
        from ray_tpu.utils.ids import JobID

        import ray_tpu

        c = Cluster()
        c.add_node(num_cpus=2)
        rt = c.connect()
        old = (global_worker.runtime, global_worker.worker_id,
               global_worker.node_id, global_worker.mode,
               global_worker.job_id)
        global_worker.runtime = rt
        global_worker.worker_id = rt.worker_id
        global_worker.node_id = rt.node_id
        global_worker.job_id = JobID.from_random()
        global_worker.mode = "cluster"
        try:
            @remote
            def noisy():
                print("log-marker-xyzzy")
                return 1

            assert ray_tpu.get(noisy.remote(), timeout=60) == 1

            def marker_logged():
                logs = list_logs()
                if not logs:
                    return None
                assert all("filename" in l and "node_id" in l for l in logs)
                if any("log-marker-xyzzy" in get_log(l["filename"],
                                                     l["node_id"])
                       for l in logs):
                    return logs
                return None

            logs = wait_for(marker_logged, timeout=10,
                            desc="worker print in a log file")
            with pytest.raises(FileNotFoundError):
                get_log("../etc/passwd", logs[0]["node_id"])
        finally:
            rt.shutdown()
            c.shutdown()
            (global_worker.runtime, global_worker.worker_id,
             global_worker.node_id, global_worker.mode,
             global_worker.job_id) = old
