"""In-process multi-node test cluster.

Capability parity with the reference's workhorse test fixture (reference:
python/ray/cluster_utils.py:135 ``class Cluster``, add_node :202 — N raylets
+ 1 GCS as local processes with fake resource specs, no device checks): here
the head and node daemons run on this process's io loop (cheap on a 1-core
box) while workers are real subprocesses, so scheduling/spillback/failure
paths cross true process boundaries.
"""

from __future__ import annotations

import uuid

from ray_tpu.core.cluster.client import start_head, start_node
from ray_tpu.core.cluster.node_daemon import NodeDaemon
from ray_tpu.core.cluster.protocol import EventLoopThread
from ray_tpu.core.cluster.runtime import ClusterRuntime


class Cluster:
    def __init__(self, persist_path: str | None = None):
        self._io = EventLoopThread.get()
        self._persist_path = persist_path
        self.head = start_head(persist_path=persist_path)
        self.nodes: list[NodeDaemon] = []

    def restart_head(self) -> None:
        """Chaos: kill the control plane and bring it back on the SAME
        address — daemons/drivers reconnect, state reloads from the
        persistence snapshot (reference: GCS restart backed by Redis,
        redis_store_client.cc + HandleNotifyGCSRestart)."""
        host, port = self.head.rpc.host, self.head.rpc.port
        self._io.run(self.head.stop())
        self.head = start_head(host=host, port=port,
                               persist_path=self._persist_path)

    def crash_head(self) -> None:
        """Chaos: hard-kill the control plane — NO final snapshot flush
        (kill -9 semantics) — and bring it back on the same address. State
        must come back from the per-mutation WAL (reference: GCS persists
        each mutation to Redis, so a crash between snapshots loses
        nothing)."""
        host, port = self.head.rpc.host, self.head.rpc.port
        head = self.head

        async def hard_stop():
            if head._health_task:
                head._health_task.cancel()
            if head._persist_task:
                head._persist_task.cancel()
            # Default group commit coalesces per event-loop tick, and this
            # coroutine is scheduled BEHIND any pending flush callback — so
            # every ACKed mutation's record is already at the OS. (With
            # wal_group_commit_ms > 0 a kill may drop the window's tail;
            # that is the documented trade.)
            head._wal_f = None
            await head.rpc.stop()

        self._io.run(hard_stop())
        self.head = start_head(host=host, port=port,
                               persist_path=self._persist_path)

    @property
    def address(self) -> str:
        return f"{self.head.rpc.host}:{self.head.rpc.port}"

    def add_node(self, num_cpus: float = 1, resources: dict | None = None,
                 labels: dict | None = None, node_id: str | None = None) -> NodeDaemon:
        totals = {"CPU": float(num_cpus)}
        totals.update(resources or {})
        daemon = start_node(self.head.rpc.host, self.head.rpc.port, totals,
                            labels, node_id or uuid.uuid4().hex)
        self.nodes.append(daemon)
        return daemon

    def remove_node(self, daemon: NodeDaemon, graceful: bool = True):
        """Kill a node (chaos testing — reference: RayletKiller
        test_utils.py:1365)."""
        self._io.run(daemon.stop())
        if daemon in self.nodes:
            self.nodes.remove(daemon)

    def kill_workers(self, node: NodeDaemon | None = None) -> int:
        """Chaos: SIGKILL every worker process on a node (reference:
        WorkerKillerActor, test_utils.py:1279). Returns the kill count —
        objects held only by those workers become reconstruction fodder."""
        import signal

        targets = [node] if node else list(self.nodes)
        n = 0
        for d in targets:
            for w in list(d.workers.values()) + list(d._unregistered):
                if w.proc is not None and w.proc.poll() is None:
                    try:
                        w.proc.send_signal(signal.SIGKILL)
                        n += 1
                    except OSError:
                        pass
        return n

    def connect(self, node: NodeDaemon | None = None) -> ClusterRuntime:
        target = node or (self.nodes[0] if self.nodes else None)
        rt = ClusterRuntime(
            self.head.rpc.host, self.head.rpc.port,
            node_daemon_addr=(target.rpc.host, target.rpc.port) if target else None,
            shm_name=target.shm_name if target else None,
        )
        return rt

    def shutdown(self):
        for d in list(self.nodes):
            try:
                self._io.run(d.stop())
            except Exception:
                pass
        self.nodes.clear()
        try:
            self._io.run(self.head.stop())
        except Exception:
            pass
