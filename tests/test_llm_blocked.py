"""Block-pooled KV cache mode (reference capability: vLLM PagedAttention,
llm/_internal/serve/engines/vllm/vllm_models.py:148 — re-designed
TPU-first: static-shape block pool + int32 tables + gather reads, no
device page tables).

Covers: exact-greedy parity with the dense layout, the
2×-slots-at-equal-HBM memory claim, preemption on pool exhaustion with
correct resume-by-recompute, and prefix adoption through block copies.
"""

import numpy as np
import pytest

from ray_tpu.llm import LLMConfig, SamplingParams
from ray_tpu.llm.engine import LLMEngine


def _gen(engine, prompts, max_tokens=12):
    sp = SamplingParams(temperature=0.0, max_tokens=max_tokens)
    reqs = [engine.submit(p, sp) for p in prompts]
    outs = []
    for r in reqs:
        assert r.done.wait(120), "generation timed out"
        assert r.error is None, r.error
        outs.append(list(r.out_tokens))
    return outs


@pytest.fixture(scope="module")
def dense_engine():
    eng = LLMEngine(LLMConfig(model="tiny", max_num_seqs=4, max_seq_len=128))
    yield eng
    eng.shutdown()


def test_blocked_matches_dense_greedy(dense_engine):
    prompts = ["hello block world", "a different prompt!", "third one",
               "and a somewhat longer fourth prompt to chunk"]
    want = _gen(dense_engine, prompts)
    eng = LLMEngine(LLMConfig(model="tiny", max_num_seqs=4, max_seq_len=128,
                              kv_block_size=16,
                              kv_num_blocks=4 * 128 // 16))
    try:
        got = _gen(eng, prompts)
    finally:
        eng.shutdown()
    assert got == want


def test_blocked_half_memory_double_slots(dense_engine):
    """The auto-sized pool holds max_slots×max_seq/2 tokens: HBM equal to
    a dense cache of HALF the slots — i.e. 2× slots at equal HBM — and
    still serves a full house of typical-length requests."""
    slots = 8
    eng = LLMEngine(LLMConfig(model="tiny", max_num_seqs=slots,
                              max_seq_len=128, kv_block_size=16))
    try:
        dense_bytes_half_slots = (
            dense_engine.cache["k"].nbytes + dense_engine.cache["v"].nbytes)
        blocked_bytes = eng.cache["k"].nbytes + eng.cache["v"].nbytes
        # dense_engine has 4 slots at the same max_seq; blocked has 8.
        assert blocked_bytes == dense_bytes_half_slots
        outs = _gen(eng, [f"prompt number {i}" for i in range(slots)],
                    max_tokens=10)
        assert all(len(o) == 10 for o in outs)
        assert eng.preemptions == 0
    finally:
        eng.shutdown()


def test_pool_exhaustion_preempts_and_resumes_exactly():
    """A pool too small for all concurrent requests preempts the newest
    (recompute-style); every request still completes and greedy output is
    IDENTICAL to an uncontended run."""
    prompts = ["first request prompt", "second request here",
               "third request text"]
    big = LLMEngine(LLMConfig(model="tiny", max_num_seqs=3, max_seq_len=128,
                              kv_block_size=16, kv_num_blocks=24))
    try:
        want = _gen(big, prompts, max_tokens=16)
    finally:
        big.shutdown()

    # 7 blocks of 16 = 112 tokens total; three ~20-token prompts growing
    # by 16 generated tokens each cannot all fit at once.
    eng = LLMEngine(LLMConfig(model="tiny", max_num_seqs=3, max_seq_len=128,
                              kv_block_size=16, kv_num_blocks=7))
    try:
        got = _gen(eng, prompts, max_tokens=16)
        assert eng.preemptions > 0, "pool pressure never triggered"
        # Preemption evicts the NEWEST request; older requests' outputs are
        # untouched and must match exactly. The preempted request resumes
        # by re-prefilling prompt+generated — its continuation is correct
        # but not bitwise-stable (prefill vs incremental-decode bf16
        # rounding can flip near-tied argmaxes on this random tiny model;
        # vLLM's recompute preemption has the same property), so assert
        # strong agreement rather than equality.
        assert got[0] == want[0] and got[1] == want[1]
        agree = sum(a == b for a, b in zip(got[2], want[2]))
        assert len(got[2]) == 16 and agree >= 12, (agree, got[2], want[2])
    finally:
        eng.shutdown()


def test_pool_too_small_for_single_prompt_fails_cleanly():
    eng = LLMEngine(LLMConfig(model="tiny", max_num_seqs=2, max_seq_len=128,
                              kv_block_size=16, kv_num_blocks=2))
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=4)
        req = eng.submit("a prompt that is longer than two blocks of kv",
                         sp)
        assert req.done.wait(60)
        assert req.error and "pool exhausted" in req.error
    finally:
        eng.shutdown()


def test_blocked_prefix_adoption():
    shared = "You are a careful assistant. Answer briefly and stay calm. "
    eng = LLMEngine(LLMConfig(model="tiny", max_num_seqs=4, max_seq_len=256,
                              kv_block_size=16))
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=8)
        r1 = eng.submit(shared + "Q1?", sp)
        assert r1.done.wait(120) and r1.error is None
        # Keep r1's slot live as a donor? r1 finished — blocked mode frees
        # blocks at finish, so adoption needs a LIVE donor: hold one open.
        long_req = eng.submit(shared + "Hold this slot open please",
                              SamplingParams(temperature=0.0,
                                             max_tokens=48))
        import time

        deadline = time.monotonic() + 60
        while time.monotonic() < deadline and not eng._prefix_live:
            time.sleep(0.02)
        assert eng._prefix_live, "donor never finished prefill"
        before = eng.prefix_hits
        r2 = eng.submit(shared + "Q2?", sp)
        assert r2.done.wait(120) and r2.error is None
        assert eng.prefix_hits > before, "no block-prefix adoption"
        assert long_req.done.wait(120)
    finally:
        eng.shutdown()


def test_blocked_rejects_pd_and_spec():
    eng = LLMEngine(LLMConfig(model="tiny", max_num_seqs=2, max_seq_len=128,
                              kv_block_size=16))
    try:
        with pytest.raises(ValueError, match="dense"):
            eng.prefill_only("prompt")
        with pytest.raises(ValueError, match="dense"):
            eng.submit_prefilled({})
    finally:
        eng.shutdown()
    with pytest.raises(ValueError, match="dense KV layout"):
        LLMEngine(LLMConfig(model="tiny", max_num_seqs=2, max_seq_len=128,
                            kv_block_size=16, speculative_model="tiny"))
