"""Datasources: each produces a list of ReadTasks (reference capability:
python/ray/data/datasource/ + read_api.py:934 read_parquet).

A ReadTask is a zero-arg callable returning one Block; the executor runs them
as remote tasks so reads parallelize and blocks land in the object store.
"""

from __future__ import annotations

import glob as _glob
import os
from dataclasses import dataclass, field
from typing import Any, Callable

import numpy as np

from ray_tpu.data.block import (
    Block,
    block_from_arrow,
    block_from_numpy,
    block_from_pandas,
    block_from_rows,
)


@dataclass
class ReadTask:
    fn: Callable[[], Block]
    # best-effort metadata for planning; -1 means unknown
    num_rows: int = -1
    metadata: dict = field(default_factory=dict)

    def __call__(self) -> Block:
        return self.fn()


class Datasource:
    def get_read_tasks(self, parallelism: int) -> list[ReadTask]:
        raise NotImplementedError

    def name(self) -> str:
        return type(self).__name__


class RangeDatasource(Datasource):
    def __init__(self, n: int, column: str = "id"):
        self._n = n
        self._col = column

    def get_read_tasks(self, parallelism: int) -> list[ReadTask]:
        parallelism = max(1, min(parallelism, self._n or 1))
        chunk = self._n // parallelism
        rem = self._n % parallelism
        tasks, start = [], 0
        for i in range(parallelism):
            size = chunk + (1 if i < rem else 0)
            lo, hi = start, start + size
            start = hi
            col = self._col

            def fn(lo=lo, hi=hi, col=col) -> Block:
                return {col: np.arange(lo, hi, dtype=np.int64)}

            tasks.append(ReadTask(fn, num_rows=size))
        return [t for t in tasks if t.num_rows > 0] or [
            ReadTask(lambda col=self._col: {col: np.arange(0, dtype=np.int64)},
                     num_rows=0)
        ]


class ItemsDatasource(Datasource):
    def __init__(self, items: list):
        self._items = list(items)

    def get_read_tasks(self, parallelism: int) -> list[ReadTask]:
        n = len(self._items)
        parallelism = max(1, min(parallelism, n or 1))
        chunk = n // parallelism
        rem = n % parallelism
        tasks, start = [], 0
        for i in range(parallelism):
            size = chunk + (1 if i < rem else 0)
            part = self._items[start:start + size]
            start += size
            if not part and n > 0:
                continue

            def fn(part=part) -> Block:
                rows = [r if isinstance(r, dict) else {"item": r} for r in part]
                return block_from_rows(rows)

            tasks.append(ReadTask(fn, num_rows=size))
        return tasks or [ReadTask(lambda: {}, num_rows=0)]


def _expand_paths(paths, suffixes: tuple[str, ...]) -> list[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            for suf in suffixes:
                out.extend(sorted(_glob.glob(os.path.join(p, f"*{suf}"))))
        elif any(c in p for c in "*?["):
            out.extend(sorted(_glob.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no files matched {paths!r}")
    return out


class FileDatasource(Datasource):
    suffixes: tuple[str, ...] = ()

    def __init__(self, paths, **read_kwargs):
        self._paths = _expand_paths(paths, self.suffixes)
        self._kwargs = read_kwargs

    def read_file(self, path: str) -> Block:
        raise NotImplementedError

    def get_read_tasks(self, parallelism: int) -> list[ReadTask]:
        tasks = []
        for path in self._paths:
            def fn(path=path):
                return self.read_file(path)

            tasks.append(ReadTask(fn, metadata={"path": path}))
        return tasks


class ParquetDatasource(FileDatasource):
    suffixes = (".parquet",)

    def read_file(self, path: str) -> Block:
        pq = _import_pq()

        return block_from_arrow(pq.read_table(path, **self._kwargs))


class CSVDatasource(FileDatasource):
    suffixes = (".csv",)

    def read_file(self, path: str) -> Block:
        pd = _import_pd()

        return block_from_pandas(pd.read_csv(path, **self._kwargs))


class JSONDatasource(FileDatasource):
    suffixes = (".json", ".jsonl")

    def read_file(self, path: str) -> Block:
        import json

        rows = []
        with open(path) as f:
            text = f.read().strip()
        if text.startswith("["):
            rows = json.loads(text)
        else:
            for line in text.splitlines():
                line = line.strip()
                if line:
                    rows.append(json.loads(line))
        return block_from_rows(rows)


class NumpyDatasource(FileDatasource):
    suffixes = (".npy",)

    def read_file(self, path: str) -> Block:
        return block_from_numpy(np.load(path, allow_pickle=False))


class BinaryDatasource(FileDatasource):
    """Whole-file bytes, one row per file (images etc.)."""

    suffixes = ()

    def read_file(self, path: str) -> Block:
        with open(path, "rb") as f:
            data = f.read()
        col = np.empty(1, dtype=object)
        col[0] = data
        pcol = np.empty(1, dtype=object)
        pcol[0] = path
        return {"bytes": col, "path": pcol}


class ImageDatasource(FileDatasource):
    """Decoded images, one row per file (reference capability:
    python/ray/data/datasource/image_datasource.py — decode via PIL into an
    ``image`` ndarray column plus the source ``path``).

    ``size=(h, w)`` resizes at read time (rows then stack into one dense
    [N, h, w, C] batch per block — the shape a trainer wants); without it,
    variable-shape arrays ride an object column. ``mode`` converts color
    space (default RGB).
    """

    suffixes = (".png", ".jpg", ".jpeg", ".bmp", ".gif", ".webp")

    def __init__(self, paths, size: tuple[int, int] | None = None,
                 mode: str = "RGB"):
        super().__init__(paths)
        self._size = size
        self._mode = mode

    def read_file(self, path: str) -> Block:
        Image = _import_pil()

        with Image.open(path) as im:
            if self._mode:
                im = im.convert(self._mode)
            if self._size is not None:
                h, w = self._size
                im = im.resize((w, h))  # PIL takes (width, height)
            arr = np.asarray(im)
        if self._size is not None:
            img_col = arr[None]  # dense [1, h, w, C]
        else:
            img_col = np.empty(1, dtype=object)
            img_col[0] = arr
        pcol = np.empty(1, dtype=object)
        pcol[0] = path
        return {"image": img_col, "path": pcol}


class TFRecordDatasource(FileDatasource):
    """tf.train.Example records decoded into columns (reference:
    datasource/tfrecords_datasource.py) — no tensorflow dependency, the
    framing + proto wire format are parsed directly (data/tfrecord.py).
    ``raw=True`` skips Example parsing and yields one ``data`` bytes
    column (arbitrary payloads, e.g. serialized tensors)."""

    suffixes = (".tfrecord", ".tfrecords")

    def __init__(self, paths, raw: bool = False,
                 validate_data_crc: bool = False):
        super().__init__(paths)
        self._raw = raw
        self._validate = validate_data_crc

    def read_file(self, path: str) -> Block:
        from ray_tpu.data.tfrecord import (
            example_rows_to_block,
            parse_example,
            read_records,
        )

        records = list(read_records(path,
                                    validate_data_crc=self._validate))
        if self._raw:
            col = np.empty(len(records), object)
            for i, r in enumerate(records):
                col[i] = r
            return {"data": col}
        return example_rows_to_block([parse_example(r) for r in records])


class SQLDatasource(Datasource):
    """Rows from a DB-API 2.0 database (reference capability:
    python/ray/data/read_api.py read_sql — sql + zero-arg connection
    factory). Works with any DB-API driver; sqlite3 (stdlib) in tests.

    Unsharded, the query runs as ONE read task. With ``shard_column`` (a
    NUMERIC column) + ``num_shards``, the table is range-partitioned by
    bound predicates computed from MIN/MAX so shards read in parallel —
    the same strategy as the reference's sharded read_sql. Bounds are
    inlined as numeric literals (driver paramstyles differ; numbers are
    portable), and rows with a NULL shard key ride the first shard so
    sharding never silently drops rows.
    """

    def __init__(self, sql: str, connection_factory: Callable[[], Any],
                 shard_column: str | None = None, num_shards: int = 1):
        self._sql = sql
        self._factory = connection_factory
        self._shard_column = shard_column
        self._num_shards = max(1, num_shards)

    @staticmethod
    def _fetch(factory, sql, params=()) -> Block:
        conn = factory()
        try:
            cur = conn.cursor()
            cur.execute(sql, params)
            cols = [d[0] for d in cur.description]
            rows = cur.fetchall()
        finally:
            conn.close()
        return block_from_rows([dict(zip(cols, r)) for r in rows])

    def get_read_tasks(self, parallelism: int) -> list[ReadTask]:
        factory, sql = self._factory, self._sql
        if self._shard_column is None or self._num_shards == 1:
            return [ReadTask(lambda: self._fetch(factory, sql))]
        col = self._shard_column
        conn = factory()
        try:
            cur = conn.cursor()
            cur.execute(f"SELECT MIN({col}), MAX({col}) "  # noqa: S608
                        f"FROM ({sql}) __rtpu_bounds")
            lo, hi = cur.fetchone()
        finally:
            conn.close()
        if lo is None:  # empty result set (or all-NULL shard column)
            return [ReadTask(lambda: self._fetch(factory, sql))]
        if not isinstance(lo, (int, float)) or isinstance(lo, bool):
            raise ValueError(
                f"shard_column {col!r} must be numeric for range "
                f"sharding (got {type(lo).__name__}); omit shard_column "
                f"to read unsharded")
        tasks = []
        int_bounds = isinstance(lo, int) and isinstance(hi, int)

        def bound(i: int):
            # Integer columns get EXACT integer bounds — float math loses
            # precision above 2**53 (ns-epoch timestamps, snowflake ids)
            # and a rounded-up lower bound silently excludes the MIN rows
            # from every shard.
            if int_bounds:
                return lo + (hi - lo) * i // self._num_shards
            return lo + (hi - lo) / self._num_shards * i

        for i in range(self._num_shards):
            a = bound(i)
            b = hi if i == self._num_shards - 1 else bound(i + 1)
            # last shard closes the interval so MAX rows aren't dropped
            op = "<=" if i == self._num_shards - 1 else "<"
            pred = f"({col} >= {a!r} AND {col} {op} {b!r})"
            if i == 0:  # NULL keys satisfy no range predicate
                pred = f"({pred} OR {col} IS NULL)"
            shard_sql = (f"SELECT * FROM ({sql}) __rtpu_shard "  # noqa: S608
                         f"WHERE {pred}")
            tasks.append(ReadTask(
                lambda s=shard_sql: self._fetch(factory, s)))
        return tasks


class WebDatasetDatasource(FileDatasource):
    """WebDataset-style tar shards (reference capability:
    python/ray/data/read_api.py read_webdataset): each shard is a .tar whose
    members group into samples by key = basename up to the first dot; the
    remaining extension names the column. One read task per shard — the
    natural parallel unit.

    Decoding: .json → parsed object, .txt/.cls → str (cls additionally int
    when it parses), image extensions → decoded ndarray when PIL is
    available (else raw bytes), everything else → bytes. Columns are named
    by the FULL extension ("seg.png"), decode dispatches on the last
    segment ("png") — standard WebDataset member naming.
    """

    suffixes = (".tar",)
    _IMG_EXT = ("png", "jpg", "jpeg", "bmp", "gif", "webp")

    def __init__(self, paths, decode_images: bool = True):
        super().__init__(paths)
        self._decode_images = decode_images

    def _decode(self, ext: str, data: bytes):
        import io
        import json

        ext = ext.rsplit(".", 1)[-1]  # "seg.png" decodes as "png"
        if ext == "json":
            return json.loads(data)
        if ext in ("txt", "text"):
            return data.decode()
        if ext == "cls":
            text = data.decode().strip()
            try:
                return int(text)
            except ValueError:
                return text
        if ext in self._IMG_EXT and self._decode_images:
            try:
                Image = _import_pil()
                with Image.open(io.BytesIO(data)) as im:
                    return np.asarray(im.convert("RGB"))
            except ImportError:
                return data
        return data

    def read_file(self, path: str) -> Block:
        import tarfile

        samples: dict[str, dict] = {}
        order: list[str] = []
        with tarfile.open(path) as tf:
            for member in tf:
                if not member.isfile():
                    continue
                # WebDataset convention: the sample key is the member PATH
                # up to the first dot of the basename — basename-only keys
                # would merge train/0001.jpg and val/0001.jpg into one
                # sample (silent loss on per-class-directory shards).
                dirpart, base = os.path.split(member.name)
                if "." in base:
                    stem, ext = base.split(".", 1)
                else:
                    stem, ext = base, "bin"
                key = f"{dirpart}/{stem}" if dirpart else stem
                data = tf.extractfile(member).read()
                if key not in samples:
                    samples[key] = {"__key__": key}
                    order.append(key)
                samples[key][ext.lower()] = self._decode(ext.lower(), data)
        return block_from_rows([samples[k] for k in order])


class MongoDatasource(Datasource):
    """Documents from a MongoDB collection (reference capability:
    python/ray/data/read_api.py read_mongo — uri/database/collection +
    optional aggregation pipeline). ``client_factory`` is a zero-arg
    callable returning a pymongo-shaped client (injectable: tests and
    driverless environments use a fake; omitted, pymongo is imported and
    connected to ``uri``).

    Sharding: ``num_shards`` partitions the collection by _id ranges
    whose boundaries are the documents at even rank offsets (sorted by
    _id, one count + N skip probes) so shards read in parallel;
    combining ``pipeline`` with ``num_shards > 1`` raises (a pipeline can
    reorder/reshape documents, making _id ranges meaningless). The
    reference delegates range splitting to the mongo cluster
    (splitVector); _id-range partitioning is the driver-portable
    equivalent at this scale."""

    def __init__(self, uri: str, database: str, collection: str,
                 pipeline: list | None = None,
                 client_factory: Callable[[], Any] | None = None,
                 num_shards: int = 1):
        self._uri = uri
        self._db = database
        self._coll = collection
        self._pipeline = list(pipeline or [])
        self._factory = client_factory
        self._num_shards = max(1, num_shards)

    def _client(self):
        if self._factory is not None:
            return self._factory()
        try:
            import pymongo  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "read_mongo needs pymongo (not in this image) or an "
                "injectable client_factory") from e
        return pymongo.MongoClient(self._uri)

    def _fetch(self, extra_stages: list | None = None) -> Block:
        client = self._client()
        try:
            coll = client[self._db][self._coll]
            rows = [dict(d) for d in coll.aggregate(
                list(self._pipeline) + list(extra_stages or []))]
        finally:
            close = getattr(client, "close", None)
            if close:
                close()
        return block_from_rows(rows)

    def get_read_tasks(self, parallelism: int) -> list[ReadTask]:
        if self._num_shards == 1:
            return [ReadTask(lambda: self._fetch())]
        if self._pipeline:
            # skip/limit windows over pipeline OUTPUT are only correct
            # under a total order, and pipelines can project _id away or
            # emit ties ($unwind) that MongoDB's unstable sort splits
            # differently per shard — silent row loss/duplication. The
            # reference likewise shards the raw collection (splitVector),
            # not pipeline output.
            raise ValueError(
                "read_mongo: num_shards > 1 cannot be combined with a "
                "pipeline (no total order over pipeline output to "
                "partition on); shard the raw collection and apply the "
                "pipeline per shard upstream, or use num_shards=1")
        client = self._client()
        try:
            coll = client[self._db][self._coll]
            total = coll.count_documents({})
            per = max(1, (total + self._num_shards - 1) // self._num_shards)
            # _id range partition (every document has a unique, indexed
            # _id): boundary docs at the shard edges make closed/open
            # [lo, hi) predicates that are deterministic under concurrent
            # writes — unlike skip/limit windows.
            bounds = []
            for i in range(1, self._num_shards):
                edge = list(coll.aggregate([
                    {"$sort": {"_id": 1}}, {"$skip": i * per},
                    {"$limit": 1}, {"$project": {"_id": 1}}]))
                bounds.append(edge[0]["_id"] if edge else None)
        finally:
            close = getattr(client, "close", None)
            if close:
                close()
        tasks = []
        prev = None
        for hi in bounds + [None]:
            match: dict = {}
            if prev is not None:
                match["$gte"] = prev
            if hi is not None:
                match["$lt"] = hi
            stage = [{"$match": {"_id": match}}] if match else []
            tasks.append(ReadTask(lambda st=stage: self._fetch(st)))
            prev = hi
            if hi is None:
                # No boundary doc at this edge (total < num_shards or the
                # collection shrank): this task already took [prev, ∞) —
                # further shards would re-read the whole collection.
                break
        return tasks


class BigQueryDatasource(Datasource):
    """Rows from a BigQuery table via Storage-API-shaped read streams
    (reference capability: python/ray/data/read_api.py read_bigquery).
    ``client_factory`` returns an object with ``create_read_session(table,
    max_streams) -> [stream_id, ...]`` and ``read_rows(stream_id) ->
    iterable[dict]`` — the google-cloud-bigquery-storage surface reduced
    to its data motion; tests inject a fake, real use wraps the Google
    client. One read task per stream (the Storage API's parallel unit)."""

    def __init__(self, table: str, client_factory: Callable[[], Any],
                 max_streams: int = 8):
        self._table = table
        self._factory = client_factory
        self._max_streams = max(1, max_streams)

    def get_read_tasks(self, parallelism: int) -> list[ReadTask]:
        client = self._factory()
        try:
            streams = list(client.create_read_session(self._table,
                                                      self._max_streams))
        finally:
            close = getattr(client, "close", None)
            if close:
                close()

        def read_stream(stream_id):
            c = self._factory()
            try:
                return block_from_rows([dict(r) for r in
                                        c.read_rows(stream_id)])
            finally:
                close = getattr(c, "close", None)
                if close:
                    close()

        return [ReadTask(lambda s=s: read_stream(s),
                         metadata={"stream": s}) for s in streams]


class DeltaLakeDatasource(Datasource):
    """A Delta Lake table from its transaction log (reference capability:
    ray.data.read_delta / delta-rs integration — here implemented directly:
    replay ``_delta_log/*.json`` add/remove actions to the live file set,
    then read each data file with the parquet reader, injecting the file's
    ``partitionValues`` as literal columns the way partitioned parquet
    lakes expect). One read task per live data file."""

    def __init__(self, table_path: str):
        self._root = table_path

    def _live_files(self) -> list[tuple[str, dict]]:
        import json as _json

        log_dir = os.path.join(self._root, "_delta_log")
        live: dict[str, dict] = {}
        ckpt_version = -1
        # Checkpointed tables vacuum old JSON commits: seed the file set
        # from the parquet checkpoint named by _last_checkpoint, then
        # replay only the JSON commits AFTER it.
        last_ck = os.path.join(log_dir, "_last_checkpoint")
        if os.path.exists(last_ck):
            with open(last_ck) as f:
                ckpt_version = int(_json.load(f)["version"])
            parts = sorted(_glob.glob(os.path.join(
                log_dir, f"{ckpt_version:020d}.checkpoint*.parquet")))
            if not parts:
                raise FileNotFoundError(
                    f"_last_checkpoint names version {ckpt_version} but no "
                    f"matching *.checkpoint*.parquet exists in {log_dir!r}")
            pq = _import_pq()
            for part in parts:
                tbl = pq.read_table(part)
                for row in tbl.to_pylist():
                    a = row.get("add")
                    if a and a.get("path"):
                        live[a["path"]] = a.get("partitionValues") or {}
                    r = row.get("remove")
                    if r and r.get("path"):
                        live.pop(r["path"], None)

        logs = sorted(_glob.glob(os.path.join(log_dir, "*.json")))
        if not logs and ckpt_version < 0:
            raise FileNotFoundError(
                f"no _delta_log under {self._root!r} — not a Delta table")
        for log in logs:  # commits replay in version order
            version = int(os.path.splitext(os.path.basename(log))[0])
            if version <= ckpt_version:
                continue  # already folded into the checkpoint
            with open(log) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    action = _json.loads(line)
                    if "add" in action:
                        a = action["add"]
                        live[a["path"]] = a.get("partitionValues", {}) or {}
                    elif "remove" in action:
                        live.pop(action["remove"]["path"], None)
        return [(os.path.join(self._root, p), pv)
                for p, pv in sorted(live.items())]

    def get_read_tasks(self, parallelism: int) -> list[ReadTask]:
        tasks = []
        for path, part_values in self._live_files():
            def fn(path=path, pv=part_values):
                from ray_tpu.data.block import _to_column

                pq = _import_pq()
                block = block_from_arrow(pq.read_table(path))
                n = len(next(iter(block.values()))) if block else 0
                for col, val in pv.items():
                    block[col] = _to_column([val] * n)
                return block

            tasks.append(ReadTask(fn, metadata={"path": path}))
        return tasks


# ---------------------------------------------------------------------------
# write tasks


import threading as _threading

# Concurrent *first* imports of pyarrow/pandas C-extension submodules from
# parallel task threads segfault CPython's import machinery — take one lock
# around the lazy import, then use the cached module freely from any thread.
_IMPORT_LOCK = _threading.Lock()


def _import_pq():
    with _IMPORT_LOCK:
        import pyarrow.parquet as pq

        return pq


def _import_pd():
    with _IMPORT_LOCK:
        import pandas as pd

        return pd


def _import_pil():
    with _IMPORT_LOCK:
        from PIL import Image

        return Image


def write_block_parquet(block: Block, path: str, index: int) -> str:
    pq = _import_pq()

    from ray_tpu.data.block import BlockAccessor

    out = os.path.join(path, f"part-{index:05d}.parquet")
    pq.write_table(BlockAccessor(block).to_arrow(), out)
    return out


def write_block_csv(block: Block, path: str, index: int) -> str:
    from ray_tpu.data.block import BlockAccessor

    out = os.path.join(path, f"part-{index:05d}.csv")
    BlockAccessor(block).to_pandas().to_csv(out, index=False)
    return out


def write_block_json(block: Block, path: str, index: int) -> str:
    import json

    from ray_tpu.data.block import BlockAccessor

    out = os.path.join(path, f"part-{index:05d}.jsonl")
    with open(out, "w") as f:
        for row in BlockAccessor(block).iter_rows():
            f.write(json.dumps(row, default=_json_default) + "\n")
    return out


def write_block_sql(block: Block, sql: str, connection_factory) -> int:
    """executemany one block's rows through a fresh DB-API connection.
    Values are converted to Python scalars (drivers reject numpy types)."""
    from ray_tpu.data.block import BlockAccessor

    rows = []
    for row in BlockAccessor(block).iter_rows():
        rows.append(tuple(v.item() if isinstance(v, np.generic) else v
                          for v in row.values()))
    if not rows:
        return 0
    conn = connection_factory()
    try:
        conn.cursor().executemany(sql, rows)
        conn.commit()
    finally:
        conn.close()
    return len(rows)


def _json_default(v: Any):
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, np.ndarray):
        return v.tolist()
    raise TypeError(f"not JSON serializable: {type(v)}")
