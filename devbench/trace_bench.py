"""Request-tracing cost + tail-capture bench → PERF_TRACE.json.

Three claims, each measured on the in-process runtime:

- **overhead** — closed-loop handle round trips (``handle.remote() →
  result()``) through one shared router in three arms: tracing compiled
  off (``disable_tracing``: every helper is a no-op and the hot paths
  keep their nullcontext fast path), tracing on at the default head
  sampling rate (Config.trace_sample_rate), and tracing on sampling
  everything. The gated arms serve a representative handler (~1ms of
  calibrated CPU work — the denominator a production request actually
  has; a no-op echo handler measures the router, not the tracing tax a
  user pays) and are compared on CPU-per-request, the stable metric on
  a saturated shared box. The no-op echo is still measured and reported
  as the absolute fixed cost per request in µs — the worst-case
  microbench number. Gates: the default-sampling arm within 10% of the
  off arm (CPU-per-request, representative handler); the echo off arm
  within noise of the PERF_ROUTER e2e baseline (the added code compiled
  off must cost ≈ nothing). The pure routing-decision loop is also
  measured for a direct PERF_ROUTER decide comparison — tracing never
  touches it.
- **tail capture** — head sampling set to 0 (pure tail sampling), the
  deployment's latency window primed with fast traffic, then
  chaos-delayed stragglers injected: every straggler's trace must be
  retroactively kept (promoted from the tail ring) — 100% capture.
- **waterfall** — one slow request traced across the three planes that
  serve it (caller handle/router, replica pool thread, LLM engine
  scheduler loop — separate processes in cluster mode, separate
  execution contexts here; the context rides metadata either way),
  reconstructed through the same assembly the ``ray_tpu trace`` CLI
  uses: the TTFT phase breakdown (queue → prefill → decode) under the
  request root, rendered and embedded in the report.

Run: python devbench/trace_bench.py [--quick]   → PERF_TRACE.json
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
sys.path.insert(0, os.path.join(_REPO, "tests"))

from _test_util import load_factor as _load_factor  # noqa: E402 - one
# load-factor policy for every timing gate in the repo

NUM_REPLICAS = 4
STRAGGLERS = 5
WORK_TARGET_S = 0.001  # representative handler: ~1ms of real CPU work


def _spin(iters: int) -> float:
    x = 1.0001
    for _ in range(iters):
        x = x * 1.0000001 + 1e-9
    return x


def _calibrate_work(target_s: float = WORK_TARGET_S) -> int:
    """Iterations of _spin that burn ~target_s of CPU on this box."""
    iters = 4000
    while True:
        t0 = time.process_time()
        _spin(iters)
        dt = time.process_time() - t0
        if dt >= target_s * 0.5 or iters >= 512_000:
            return max(1000, int(iters * target_s / max(dt, 1e-9)))
        iters *= 2


def _deploy(sample_rate, name="TraceBenchEcho", sleep_key=None,
            work_iters=0):
    from ray_tpu import serve

    @serve.deployment(name=name, num_replicas=NUM_REPLICAS,
                      max_ongoing_requests=1_000_000,
                      max_queued_requests=-1,
                      trace_sample_rate=sample_rate)
    class Echo:
        def __call__(self, x):
            if work_iters:
                _spin(work_iters)
            if sleep_key is not None and isinstance(x, str) \
                    and x.startswith(sleep_key):
                time.sleep(0.25)  # chaos-delayed straggler
            return x

    # One app per deployment: redeploying an app name tears down the
    # deployments the previous call created, and the overhead arms must
    # coexist in one runtime.
    return serve.run(Echo.bind(), name=f"trace-bench-{name}",
                     route_prefix=None)


def _measure_e2e(handle, clients: int, seconds: float) -> tuple:
    """Closed-loop drive → (wall rps, CPU µs per request).

    process_time() counts every thread — caller, router, replica pool —
    so CPU-per-request is the full-path cost and does not swing with
    scheduler luck the way wall-clock rps does on a saturated box.
    """
    stop = time.monotonic() + seconds
    counts = [0] * clients

    def client(k):
        while time.monotonic() < stop:
            handle.remote(k).result(timeout=30)
            counts[k] += 1

    threads = [threading.Thread(target=client, args=(k,))
               for k in range(clients)]
    c0 = time.process_time()
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - t0
    cpu = time.process_time() - c0
    n = sum(counts)
    return (n / wall if wall else 0.0,
            (cpu / n) * 1e6 if n else 0.0)


def _measure_decide(router, reps, seconds: float) -> float:
    t0 = time.perf_counter()
    n = 0
    deadline = t0 + seconds
    while time.perf_counter() < deadline:
        for _ in range(100):
            with router._lock:
                chosen = router._choose_locked(reps)
                rid = chosen.replica_id
                router._inflight[rid] = router._inflight.get(rid, 0) + 1
            router._release(rid)
        n += 100
    return n / (time.perf_counter() - t0)


def _interleave(handles, arms, slices, slice_dur) -> dict:
    """Many short rotated slices, median per arm: box load drifts over
    the run and GC/flush bursts land at random, so one long round per
    arm measures whichever arm drew the quiet slot. Rotating the order
    every slice gives each arm every position, gc.collect() before a
    slice stops one arm paying the previous arm's allocation debt, and
    the median shrugs off the spiky slices."""
    import gc

    from ray_tpu.util import tracing

    samples: dict[str, list[tuple]] = {a: [] for a, _, _ in arms}
    for r in range(slices):
        rotated = arms[r % len(arms):] + arms[:r % len(arms)]
        for arm, _, enabled in rotated:
            (tracing.enable_tracing if enabled
             else tracing.disable_tracing)()
            gc.collect()
            samples[arm].append(_measure_e2e(handles[arm], 4, slice_dur))
            tracing.clear()  # bound buffers between slices
    out = {}
    for arm, _, _ in arms:
        rps = sorted(v[0] for v in samples[arm])
        cpu = sorted(v[1] for v in samples[arm])
        out[arm] = {"e2e_rps": round(rps[len(rps) // 2], 1),
                    "cpu_us_per_req": round(cpu[len(cpu) // 2], 1)}
    return out


def _overhead_arms(dur: float, rounds: int = 3) -> dict:
    """One warmed runtime, the three arms interleaved round-robin: on a
    small shared box, two separately-built runtimes differ by more than
    the tracing overhead being measured — only an interleaved comparison
    isolates the tracing cost."""
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.util import tracing

    arms = (("off", None, False),
            ("default", None, True),   # Config.trace_sample_rate
            ("full", 1.0, True))
    echo_arms = (("off", None, False), ("default", None, True))
    out: dict = {}
    ray_tpu.shutdown()
    ray_tpu.init()
    try:
        tracing.clear()
        work_iters = _calibrate_work()
        handles, echo_handles = {}, {}
        for arm, sample_rate, _ in arms:
            tracing.disable_tracing()
            handles[arm] = _deploy(sample_rate,
                                   name=f"TraceBenchWork_{arm}",
                                   work_iters=work_iters)
            for i in range(30):  # prime caches, reaper, replica pools
                handles[arm].remote(i).result(timeout=30)
        for arm, sample_rate, _ in echo_arms:
            tracing.disable_tracing()
            echo_handles[arm] = _deploy(sample_rate,
                                        name=f"TraceBenchEcho_{arm}")
            for i in range(100):
                echo_handles[arm].remote(i).result(timeout=30)
        slices = rounds * 4
        slice_dur = dur * rounds / slices
        arms_out = _interleave(handles, arms, slices, slice_dur)
        out.update(arms_out)
        # Echo microbench: a no-op handler isolates the absolute fixed
        # tracing cost per request — reported in µs, not gated as a
        # percentage (the denominator is synthetic).
        echo_out = _interleave(echo_handles, echo_arms, slices, slice_dur)
        out["echo_fixed_cost"] = {
            "off_e2e_rps": echo_out["off"]["e2e_rps"],
            "off_cpu_us_per_req": echo_out["off"]["cpu_us_per_req"],
            "default_cpu_us_per_req":
                echo_out["default"]["cpu_us_per_req"],
            "tracing_cost_us_per_req": round(
                echo_out["default"]["cpu_us_per_req"]
                - echo_out["off"]["cpu_us_per_req"], 1),
        }
        out["work_iters"] = work_iters
        tracing.disable_tracing()
        router = echo_handles["off"]._ensure_router()
        out["decide_rps"] = round(
            _measure_decide(router, router._get_replicas(), dur), 1)
        serve.shutdown()
    finally:
        try:
            serve.shutdown()
        except Exception:  # noqa: BLE001
            pass
        tracing.disable_tracing()
        tracing.clear()
        ray_tpu.shutdown()
    return out


def _tail_capture(dur_prime: int) -> dict:
    """Pure tail sampling + injected stragglers: 100% of the delayed
    requests must be retroactively kept."""
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.util import tracing

    ray_tpu.shutdown()
    ray_tpu.init()
    try:
        tracing.clear()
        tracing.enable_tracing()
        handle = _deploy(0.0, name="TraceBenchTail", sleep_key="slow")
        # Prime the deployment's rolling-p99 latency window past its
        # min-sample floor with fast traffic.
        for i in range(dur_prime):
            handle.remote(i).result(timeout=30)
        straggler_tids = []
        for i in range(STRAGGLERS):
            resp = handle.remote(f"slow{i}")
            straggler_tids.append(resp._span.trace_id)
            resp.result(timeout=30)
        kept = {s.trace_id for s in tracing.spans()}
        captured = sum(1 for t in straggler_tids if t in kept)
        keep_reasons = sorted({
            ev.get("reason") for s in tracing.spans()
            if s.trace_id in straggler_tids
            for ev in s.events if ev.get("name") == "tail_keep"})
        serve.shutdown()
        return {"stragglers": STRAGGLERS, "captured": captured,
                "capture_rate": captured / STRAGGLERS,
                "keep_reasons": keep_reasons,
                "tail_stats": tracing.tail_stats()}
    finally:
        try:
            serve.shutdown()
        except Exception:  # noqa: BLE001
            pass
        tracing.disable_tracing()
        tracing.clear()
        ray_tpu.shutdown()


def _waterfall() -> dict:
    """One slow traced request across the serve planes, its LLM TTFT
    phase breakdown stamped by the engine scheduler loop, reconstructed
    the way ``ray_tpu trace <id>`` does it."""
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.llm import LLMConfig, LLMEngine, SamplingParams
    from ray_tpu.util import tracing

    ray_tpu.shutdown()
    ray_tpu.init()
    try:
        tracing.clear()
        tracing.enable_tracing()

        @serve.deployment(name="TraceBenchLLM", trace_sample_rate=1.0)
        class Gen:
            def __init__(self):
                self.eng = LLMEngine(LLMConfig(model="tiny",
                                               max_num_seqs=2,
                                               max_seq_len=64))

            def __call__(self, prompt):
                time.sleep(0.05)  # the "slow request" under diagnosis
                out = self.eng.generate(list(range(8)),
                                        SamplingParams(max_tokens=4))
                return len(out.token_ids)

        handle = serve.run(Gen.bind(), name="trace-wf", route_prefix=None)
        resp = handle.remote("hello")
        tid = resp._span.trace_id
        ntok = resp.result(timeout=60)
        assert ntok == 4, ntok
        spans = sorted((s for s in tracing.spans() if s.trace_id == tid),
                       key=lambda s: s.start_ts)
        names = [s.name for s in spans]
        t0 = min(s.start_ts for s in spans)
        lines = [f"{s.name:<28} {(s.start_ts - t0) * 1e3:8.1f}ms "
                 f"+{max(0.0, s.end_ts - s.start_ts) * 1e3:.1f}ms"
                 for s in spans]
        serve.shutdown()
        phases = {"root": any(n.startswith("serve.request.") for n in names),
                  "router_attempt": any(n.startswith("serve.attempt.")
                                        for n in names),
                  "replica": any("handle_request" in n for n in names),
                  "engine_queue": "engine.queue" in names,
                  "engine_prefill": "engine.prefill" in names,
                  "engine_decode": "engine.decode" in names}
        return {"trace_id": tid, "num_spans": len(spans),
                "phases": phases,
                "reconstructed": all(phases.values()),
                "waterfall": lines}
    finally:
        try:
            serve.shutdown()
        except Exception:  # noqa: BLE001
            pass
        tracing.disable_tracing()
        tracing.clear()
        ray_tpu.shutdown()


def run_bench(quick: bool = False, out_path: str | None = None) -> dict:
    dur = 1.0 if quick else 3.0
    arms = _overhead_arms(dur)
    tail = _tail_capture(dur_prime=80 if quick else 150)
    wf = _waterfall()

    lf = _load_factor()
    off, dflt, full = (arms[a]["cpu_us_per_req"]
                       for a in ("off", "default", "full"))
    # Noise floors widen with the box's load factor, like every timing
    # gate in this repo; the 10% overhead budget itself does not.
    # Overhead = extra CPU per request on the representative handler.
    overhead_default = (dflt - off) / off if off else 0.0
    overhead_full = (full - off) / off if off else 0.0
    echo = arms.get("echo_fixed_cost", {})

    baseline = {}
    base_path = os.path.join(_REPO, "PERF_ROUTER.json")
    if os.path.exists(base_path):
        try:
            with open(base_path) as f:
                base = json.load(f)
            rates = (base.get("quick_refresh") or base).get("rates", {})
            baseline = {"e2e_rps": rates.get("e2e_rps"),
                        "decide_rps": rates.get("decide_rps")}
        except Exception:  # noqa: BLE001
            pass

    def _within_noise(ours, theirs):
        if not theirs:
            return None  # no baseline on disk: nothing to compare
        # Half the baseline, load-factor-relaxed: catches a hot path
        # pricing itself out, tolerates different-day box noise.
        return ours >= theirs / (2.0 * lf)

    report = {
        "bench": "request_tracing",
        "quick": quick,
        "config": {"num_replicas": NUM_REPLICAS, "duration_s": dur,
                   "e2e_clients": 4, "stragglers": STRAGGLERS},
        "arms": arms,
        "overhead": {
            "default_sampling_pct": round(100 * overhead_default, 2),
            "full_sampling_pct": round(100 * overhead_full, 2),
            "echo_fixed_cost_us_per_req":
                echo.get("tracing_cost_us_per_req"),
        },
        "tail_capture": tail,
        "waterfall": wf,
        "baseline_perf_router": baseline,
        "acceptance": {
            "default_sampling_within_10pct": overhead_default <= 0.10,
            "off_arm_within_noise_of_perf_router":
                _within_noise(echo.get("off_e2e_rps", 0.0),
                              baseline.get("e2e_rps")),
            "decide_within_noise_of_perf_router":
                _within_noise(arms.get("decide_rps", 0.0),
                              baseline.get("decide_rps")),
            "tail_capture_100pct": tail["capture_rate"] == 1.0,
            "ttft_waterfall_reconstructed": wf["reconstructed"],
            "load_factor": round(lf, 2),
        },
        "provenance": {
            "date": time.strftime("%Y-%m-%d %H:%M:%S"),
            "cpus": os.cpu_count(),
            "loadavg": list(os.getloadavg()),
            "box_note": (
                "in-process runtime on a small CPU box. Overhead arms = "
                "closed-loop handle.remote().result() through one shared "
                "router, 4 clients, 4 replicas each doing ~1ms of "
                "calibrated CPU work (the denominator a production "
                "request actually has), compared on CPU-per-request "
                "(process_time over all threads / requests — stable on "
                "a saturated box where wall-clock rps swings with "
                "scheduler luck). The off arm has tracing disabled (the "
                "compiled-off fast path); default samples at "
                "Config.trace_sample_rate with the tail ring live; full "
                "records every request. echo_fixed_cost isolates the "
                "absolute per-request tracing cost in µs against a no-op "
                "handler — a worst-case microbench, reported, not gated "
                "as a percentage. Tail capture: head sampling 0, "
                "rolling-p99 window primed with fast traffic, then 0.25s "
                "chaos-delayed stragglers — every one must be "
                "retroactively kept. Waterfall: serve handle → router → "
                "replica → tiny LLM engine, TTFT phases stamped by the "
                "engine scheduler thread onto the request trace."),
        },
    }
    out_path = out_path or os.path.join(_REPO, "PERF_TRACE.json")
    doc = report
    if quick and os.path.exists(out_path):
        # Namespaced quick refresh: never overwrite full-run provenance.
        try:
            with open(out_path) as f:
                existing = json.load(f)
            if not existing.get("quick"):
                existing["quick_refresh"] = report
                doc = existing
        except Exception:  # noqa: BLE001
            pass
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=2)
    return report


if __name__ == "__main__":
    rep = run_bench(quick="--quick" in sys.argv[1:])
    print(json.dumps(rep, indent=2))
