"""`@remote` functions.

Capability parity with the reference's RemoteFunction (reference:
python/ray/remote_function.py:41, `._remote` :314 → core_worker.submit_task
:487): decorating a function yields a handle whose ``.remote(...)`` submits a
task and returns ObjectRef(s); ``.options(...)`` overrides resources,
num_returns, retries, scheduling strategy per call site.
"""

from __future__ import annotations

import functools
from typing import Any

from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.task_spec import SchedulingStrategy, TaskSpec
from ray_tpu.core.worker import global_worker
from ray_tpu.util import tracing
from ray_tpu.utils import serialization
from ray_tpu.utils.ids import TaskID


_DEFAULT_TASK_OPTIONS = dict(
    num_cpus=1,
    num_tpus=0,
    resources=None,
    num_returns=1,
    max_retries=3,
    retry_exceptions=False,
    scheduling_strategy=None,
    runtime_env=None,
    name=None,
)


def _build_resources(opts: dict[str, Any]) -> dict[str, float]:
    res: dict[str, float] = {}
    if opts.get("num_cpus"):
        res["CPU"] = float(opts["num_cpus"])
    if opts.get("num_tpus"):
        res["TPU"] = float(opts["num_tpus"])
    for k, v in (opts.get("resources") or {}).items():
        res[k] = float(v)
    return res


def _prepare_runtime_env(runtime, env: dict | None) -> dict | None:
    """Validate the env and replace local working_dir/py_modules paths with
    packaged kv:// URIs (reference: runtime envs are packaged at submission,
    python/ray/_private/runtime_env/packaging.py)."""
    if not env:
        return env
    from ray_tpu.runtime_env.packaging import upload_runtime_env
    from ray_tpu.runtime_env.runtime_env import RuntimeEnv

    validated = RuntimeEnv.from_dict(env).to_dict()
    return upload_runtime_env(runtime, validated)


def resolve_strategy(resources: dict[str, float], strategy):
    """Normalize the user-facing scheduling strategy: placement-group
    strategies rewrite demands onto the bundle's derived resources."""
    if strategy is None:
        return resources, SchedulingStrategy()
    if isinstance(strategy, str):
        if strategy not in ("DEFAULT", "SPREAD"):
            raise ValueError(
                f"unknown scheduling strategy {strategy!r} "
                "(expected 'DEFAULT', 'SPREAD', or a strategy object)")
        return resources, SchedulingStrategy(kind=strategy)
    if isinstance(strategy, SchedulingStrategy):
        return resources, strategy
    # PlacementGroupSchedulingStrategy (duck-typed to avoid import cycle)
    if hasattr(strategy, "placement_group"):
        from ray_tpu.util.placement_group import rewrite_resources_for_pg

        return (rewrite_resources_for_pg(resources, strategy),
                strategy.to_scheduling_strategy())
    if hasattr(strategy, "to_scheduling_strategy"):
        return resources, strategy.to_scheduling_strategy()
    raise TypeError(f"unsupported scheduling strategy {strategy!r}")


def extract_arg_refs(args: tuple, kwargs: dict) -> list[ObjectRef]:
    refs = [a for a in args if isinstance(a, ObjectRef)]
    refs += [v for v in kwargs.values() if isinstance(v, ObjectRef)]
    refs += serialization.find_nested_refs(
        [a for a in args if not isinstance(a, ObjectRef)]
        + [v for v in kwargs.values() if not isinstance(v, ObjectRef)]
    )
    return refs


class RemoteFunction:
    def __init__(self, fn, options: dict[str, Any]):
        self._fn = fn
        self._options = {**_DEFAULT_TASK_OPTIONS, **options}
        self._fn_blob: bytes | None = None
        self._fn_id: str | None = None  # content address of _fn_blob
        functools.update_wrapper(self, fn)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function {self._fn.__name__!r} cannot be called directly; "
            f"use {self._fn.__name__}.remote(...)"
        )

    def options(self, **overrides) -> "RemoteFunction":
        # Share the serialized definition AND its registry id: an options()
        # copy that only changes resources must not re-pickle or re-export
        # the identical fn_blob (same content hash → same registry entry).
        new = RemoteFunction(self._fn, {**self._options, **overrides})
        new._fn_blob = self._fn_blob
        new._fn_id = self._fn_id
        return new

    def _definition(self) -> tuple[bytes, str]:
        """(fn_blob, fn_id), serialized and hashed once per handle chain."""
        if self._fn_blob is None:
            self._fn_blob = serialization.dumps_function(self._fn)
        if self._fn_id is None:
            from ray_tpu.core.fn_registry import fn_id

            self._fn_id = fn_id(self._fn_blob)
        return self._fn_blob, self._fn_id

    def remote(self, *args, **kwargs):
        worker = global_worker
        worker.check_connected()
        fn_blob, fn_id = self._definition()
        # Registry fast path: runtimes exposing export_function receive the
        # definition once (idempotent, cached per runtime) and the spec
        # carries only the content id; runtimes without a registry embed
        # the blob as before.
        export = getattr(worker.runtime, "export_function", None)
        if export is not None:
            export(fn_id, fn_blob)
            fn_blob = b""
        else:
            fn_id = ""
        opts = self._options
        args_blob, arg_refs = serialization.serialize_args((args, kwargs))
        resources, strategy = resolve_strategy(
            _build_resources(opts), opts["scheduling_strategy"])
        runtime_env = _prepare_runtime_env(worker.runtime, opts["runtime_env"])
        spec = TaskSpec(
            task_id=TaskID.of(worker.job_id),
            job_id=worker.job_id,
            fn_blob=fn_blob,
            fn_id=fn_id,
            args_blob=args_blob,
            arg_ref_ids=[r.id for r in arg_refs],
            arg_owner_ids=[r.owner_id for r in arg_refs],
            num_returns=opts["num_returns"],
            resources=resources,
            max_retries=opts["max_retries"],
            retry_exceptions=bool(opts["retry_exceptions"]),
            scheduling_strategy=strategy,
            runtime_env=runtime_env,
            name=opts["name"] or self._fn.__name__,
            owner_id=worker.worker_id,
            trace_ctx=tracing.inject(),
        )
        refs = worker.runtime.submit_task(spec)
        if opts["num_returns"] == "streaming":
            from ray_tpu.core.object_ref import ObjectRefGenerator

            return ObjectRefGenerator(spec.task_id, worker.worker_id,
                                      end_ref=refs[0])
        if opts["num_returns"] == 1:
            return refs[0]
        return refs


def remote(*args, **kwargs):
    """`@remote` / `@remote(num_cpus=2, ...)` for functions and classes."""
    from ray_tpu.core.actor import ActorClass

    def decorate(target, options):
        if isinstance(target, type):
            return ActorClass(target, options)
        return RemoteFunction(target, options)

    if len(args) == 1 and callable(args[0]) and not kwargs:
        return decorate(args[0], {})
    if args:
        raise TypeError("remote() takes keyword options only, e.g. @remote(num_cpus=2)")

    def wrapper(target):
        return decorate(target, kwargs)

    return wrapper
