"""R1 fixture: @guarded_by declaration violated.

The annotation is the precise half of the race checker: once an attr is
DECLARED guarded, any mutation outside the declared lock is flagged with
no sharedness inference needed."""

import threading

from ray_tpu.devtools.annotations import guarded_by


@guarded_by("_lock", "_table")
class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._table = {}

    def put_locked(self, k, v):
        with self._lock:
            self._table[k] = v  # OK: declared lock held

    def put_racy(self, k, v):
        self._table[k] = v  # BUG: guarded attr mutated without _lock

    @guarded_by("_lock")
    def _evict_locked(self, k):
        self._table.pop(k, None)  # OK: caller holds _lock by contract
