"""Trial state tracked by the controller.

Reference shape: python/ray/tune/experiment/trial.py Trial (status FSM
PENDING/RUNNING/PAUSED/TERMINATED/ERROR, config, last_result).
"""

from __future__ import annotations

import uuid
from typing import Any


class Trial:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    PAUSED = "PAUSED"
    TERMINATED = "TERMINATED"
    ERROR = "ERROR"

    def __init__(self, config: dict, experiment_name: str = "exp",
                 trial_id: str | None = None):
        self.trial_id = trial_id or uuid.uuid4().hex[:8]
        self.config = config
        self.experiment_name = experiment_name
        self.status = Trial.PENDING
        self.last_result: dict = {}
        self.results: list[dict] = []
        self.error: str | None = None
        self.actor = None  # ActorHandle once launched
        self.pending_step = None  # outstanding ObjectRef
        self.checkpoint: Any = None
        self.pbt_request: dict | None = None
        self.restarts = 0

    def metric_history(self, metric: str) -> list:
        return [r[metric] for r in self.results if metric in r]

    def __repr__(self) -> str:
        return f"Trial({self.trial_id}, {self.status})"
