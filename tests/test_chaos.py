"""Chaos layer + recovery tiers: injected kill/delay/drop fire and are
correctly scoped (worker vs slice vs daemon vs RPC), and the train
controller picks the right restart tier under real process kills —
replica restore while replicas survive, checkpoint fallback when the
buddy store is lost with the slice. (Reference shapes: the reference's
chaos utilities — RayletKiller / WorkerKillerActor — plus
python/ray/train/v2 failure_handling tests.)"""

import json
import os
import threading
import time

import pytest

import ray_tpu
from ray_tpu.chaos import injector

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _chaos_reset():
    injector.reset_for_tests()
    yield
    os.environ.pop("RTPU_CHAOS", None)
    injector.reset_for_tests()


# --------------------------------------------------------------- injector
def test_rule_matching_scoping_and_budget():
    injector.install([
        {"point": "train.step", "action": "kill", "match": {"rank": 1},
         "at_step": 3, "count": 1, "mode": "raise"},
        {"point": "rpc.server", "action": "delay",
         "match": {"method": "get_object.*"}, "delay_s": 0.2, "count": -1},
        {"point": "daemon.tick", "action": "kill",
         "match": {"node": "^abc"}, "count": 1},
    ], replace=True)
    # wrong rank / wrong step never fire
    assert injector.decide("train.step", rank=0, step=3) is None
    assert injector.decide("train.step", rank=1, step=2) is None
    # right rank+step fires once, then the count budget is spent
    assert injector.decide("train.step", rank=1, step=3) is not None
    assert injector.decide("train.step", rank=1, step=3) is None
    # regex scoping for rpc methods / node ids
    assert injector.rpc_server_action("ping") is None
    act = injector.rpc_server_action("get_object_chunk")
    assert act == ("delay", 0.2)
    assert injector.decide("daemon.tick", node="zzz") is None
    assert injector.decide("daemon.tick", node="abcdef") is not None
    # firing log records what fired where
    pts = [f["point"] for f in injector.fired()]
    assert pts == ["train.step", "rpc.server", "daemon.tick"]


def test_rule_arming_probability_and_kill_modes(tmp_path):
    injector.install([
        {"point": "train.step", "action": "kill", "after_s": 3600.0},
        {"point": "train.step", "action": "kill", "match": {"rank": 5},
         "prob": 0.0},
    ], replace=True)
    # not armed yet / probability 0: nothing fires
    assert injector.decide("train.step", rank=5, step=0) is None
    injector.install([
        {"point": "train.step", "action": "kill", "mode": "raise",
         "match": {"rank": 2}, "mark": str(tmp_path / "marks")},
    ], replace=True)
    with pytest.raises(BaseException, match="injected kill"):
        injector.maybe_kill("train.step", rank=2, step=0)
    marks = os.listdir(tmp_path / "marks")
    assert len(marks) == 1
    mark = json.load(open(tmp_path / "marks" / marks[0]))
    assert mark["attrs"]["rank"] == 2 and mark["ts"] <= time.time()


def test_head_outage_rule_points():
    """The head-outage drill points (PR: head fault tolerance): head.tick
    kill consumes its budget like daemon.tick; partition rules carry a
    direction and match by node regex without logging per-frame."""
    injector.install([
        {"point": "head.tick", "action": "kill", "count": 1},
        {"point": "partition", "action": "drop",
         "match": {"node": "^node-b"}, "direction": "from_head"},
    ], replace=True)
    rule = injector.decide("head.tick")
    assert rule is not None and rule.action == "kill"
    assert injector.decide("head.tick") is None  # budget spent
    assert injector.partition_action("node-b7", "from_head") == \
        ("drop", 0.0)
    assert injector.partition_action("node-b7", "to_head") is None
    assert injector.partition_action("node-a1", "from_head") is None
    # many frames, ONE firing-log entry (a severed heartbeat stream must
    # not flood the log)
    for _ in range(10):
        injector.partition_action("node-b7", "from_head")
    assert len(injector.fired("partition")) == 1
    # rule serialization round-trips the direction
    d = rule.to_dict()
    assert "direction" in d
    assert injector.ChaosRule.from_dict(
        {"point": "partition", "direction": "to_head"}).direction == \
        "to_head"


def test_env_schedule_and_unknown_keys():
    with pytest.raises(ValueError, match="unknown chaos rule keys"):
        injector.ChaosRule.from_dict({"point": "train.step", "bogus": 1})
    with pytest.raises(ValueError, match="unknown chaos point"):
        injector.ChaosRule.from_dict({"point": "nope"})
    with pytest.raises(ValueError, match="direction"):
        injector.ChaosRule.from_dict({"point": "partition",
                                      "direction": "up"})
    os.environ["RTPU_CHAOS"] = json.dumps(
        [{"point": "train.step", "action": "kill", "match": {"rank": 7}}])
    injector.reset_for_tests()
    assert injector.decide("train.step", rank=7, step=0) is not None
    # clear() disarms even though the env var is still set
    injector.clear()
    assert injector.decide("train.step", rank=7, step=0) is None


# ------------------------------------------------------------- rpc probes
def test_rpc_delay_and_drop_fire_on_dispatch():
    from ray_tpu.core.cluster.protocol import (
        EventLoopThread,
        RpcClient,
        RpcServer,
    )

    io = EventLoopThread.get()
    server = RpcServer("127.0.0.1", 0)

    async def echo(conn, value=0):
        return {"value": value}

    server.register("echo", echo)
    host, port = io.run(server.start())
    cli = RpcClient(host, port)
    try:
        t0 = time.monotonic()
        assert cli.call("echo", value=1)["value"] == 1
        base = time.monotonic() - t0
        injector.install([
            {"point": "rpc.server", "action": "delay",
             "match": {"method": "^echo$"}, "delay_s": 0.4, "count": 1},
            {"point": "rpc.server", "action": "drop",
             "match": {"method": "^echo$"}, "count": 1, "after_s": 0.0},
        ], replace=True)
        t0 = time.monotonic()
        assert cli.call("echo", value=2)["value"] == 2
        assert time.monotonic() - t0 >= 0.35, "delay rule did not fire"
        # drop: the request vanishes; the caller times out
        with pytest.raises(Exception):
            cli.call("echo", value=3, timeout=0.7)
        # both budgets spent: traffic is healthy again, ~base latency
        t0 = time.monotonic()
        assert cli.call("echo", value=4)["value"] == 4
        assert time.monotonic() - t0 < 0.3 + base
    finally:
        io.run(server.stop())


# ------------------------------------------------------- cluster fixtures
@pytest.fixture
def chaos_cluster(tmp_path):
    """Factory for a real multi-process cluster (subprocess workers —
    os._exit kills must take down a process, not the test). Call
    ``start(rules)`` to install a chaos schedule in the env BEFORE any
    worker forks, then build the cluster. Skips where the cluster fixture
    can't come up (no fork/subprocess support)."""
    from ray_tpu.core.worker import global_worker
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.utils import config as config_mod
    from ray_tpu.utils.ids import JobID

    state = {}

    def start(rules=None, prestart=4):
        if rules is not None:
            os.environ["RTPU_CHAOS"] = json.dumps(rules)
        os.environ["RTPU_HEALTH_CHECK_PERIOD_S"] = "0.5"
        config_mod.set_config(config_mod.Config.load())
        ray_tpu.shutdown()
        try:
            cluster = Cluster()
            cluster.add_node(num_cpus=8)
            rt = cluster.connect()
        except Exception as e:  # noqa: BLE001 - no subprocess support
            pytest.skip(f"cluster fixture unavailable: {e}")
        state["cluster"], state["rt"] = cluster, rt
        state["old"] = (global_worker.runtime, global_worker.worker_id,
                        global_worker.node_id, global_worker.mode,
                        global_worker.job_id)
        global_worker.runtime = rt
        global_worker.worker_id = rt.worker_id
        global_worker.node_id = rt.node_id
        global_worker.job_id = JobID.from_random()
        global_worker.mode = "cluster"
        if prestart:
            try:
                rt._daemon.call("prestart_workers", n=prestart, timeout=10)
            except Exception:
                pass
        return cluster, rt

    yield start
    if "rt" in state:
        try:
            state["rt"].shutdown()
            state["cluster"].shutdown()
        except Exception:
            pass
        (global_worker.runtime, global_worker.worker_id,
         global_worker.node_id, global_worker.mode,
         global_worker.job_id) = state["old"]
    os.environ.pop("RTPU_HEALTH_CHECK_PERIOD_S", None)
    config_mod.set_config(config_mod.Config.load())


def _make_recovery_train_fn():
    """Closure factory: a nested function cloudpickles by value, so worker
    subprocesses don't need the test module importable."""

    def train_fn(config):
        import json
        import os
        import time

        import numpy as np

        from ray_tpu.train import get_context, replicate, report

        ctx = get_context()
        rank = ctx.get_world_rank()
        start, w, source = 0, np.zeros(2, np.float32), "fresh"
        rs = ctx.get_replica_state()
        if rs is not None:
            start, w, source = rs.step + 1, rs.state["w"], "replica"
        elif ctx.get_checkpoint():
            start = int(np.load(os.path.join(ctx.get_checkpoint(),
                                             "step.npy"))) + 1
            w = np.load(os.path.join(ctx.get_checkpoint(), "w.npy"))
            source = "checkpoint"
        for step in range(start, config["steps"]):
            w = w + 1.0
            replicate({"w": w, "step": step}, step)
            ck = None
            # Sparse backstop checkpoints (every 4th step), the production
            # cadence the recovery bench uses: replicate every step,
            # checkpoint every minutes. Checkpointing EVERY step made the
            # replica-tier drill a race — the dying worker's final push
            # (killed inside the same step's report) had to beat os._exit
            # to keep replica coverage >= the checkpoint step, so the test
            # flaked under load.
            if rank == 0 and step % 4 == 0:
                d = os.path.join(ctx.storage_path,
                                 f"ck_{step}_{ctx.restart_count}")
                os.makedirs(d, exist_ok=True)
                np.save(os.path.join(d, "step.npy"), np.array(step))
                np.save(os.path.join(d, "w.npy"), w)
                with open(os.path.join(d, "rtpu_meta.json"), "w") as f:
                    json.dump({"step": step, "time": time.time()}, f)
                ck = d
            report({"step": step, "rank": rank, "restart": ctx.restart_count,
                    "source": source, "ts": time.time()}, checkpoint=ck)
            time.sleep(0.25)
        return float(w.sum())

    return train_fn


def _run_controller(tmp_path, *, world, num_slices=1, hot_spares=0,
                    replicate_every=1, steps=6, max_failures=2, name="chaos"):
    from ray_tpu.train import (
        CheckpointConfig,
        FailureConfig,
        RunConfig,
        ScalingConfig,
    )
    from ray_tpu.train.backend import JaxBackendConfig
    from ray_tpu.train.controller import TrainController

    ctl = TrainController(
        _make_recovery_train_fn(), {"steps": steps},
        ScalingConfig(num_workers=world, hot_spares=hot_spares),
        RunConfig(name=name, storage_path=str(tmp_path),
                  failure_config=FailureConfig(max_failures=max_failures),
                  checkpoint_config=CheckpointConfig(
                      replicate_every=replicate_every)),
        JaxBackendConfig(num_slices=num_slices),
    )
    return ctl, ctl.run()


# ------------------------------------------------------- recovery drills
def test_kill_worker_mid_step_replica_tier(chaos_cluster, tmp_path):
    """Chaos kills one worker process mid-step; surviving replicas + a hot
    spare give a replica-tier fast restart that resumes past the kill
    step instead of replaying from scratch."""
    marks = str(tmp_path / "marks")
    chaos_cluster(rules=[
        {"point": "train.step", "action": "kill",
         "match": {"rank": 1, "restart": 0}, "at_step": 2, "mark": marks}])
    ctl, result = _run_controller(tmp_path, world=2, hot_spares=1,
                                  name="chaos-worker")
    assert result.ok, result.error
    assert len(result.restarts) == 1
    decision = result.restarts[0]
    assert decision["tier"] == "replica"
    assert decision["trigger"] == "worker_dead"
    assert decision["dead_ranks"] == [1]
    assert decision["restore_step"] >= 1
    # the injection actually fired inside the worker process
    assert len(os.listdir(marks)) == 1
    # restarted ranks resumed from replicas (no restart-1 step below the
    # restore point, and the resume source says replica)
    resumed = [m for m in result.metrics_history if m["restart"] == 1]
    assert resumed and all(m["source"] == "replica" for m in resumed)
    assert min(m["step"] for m in resumed) == decision["restore_step"] + 1
    # detection rode the fast path, not the 15 s reap cadence
    inject = json.load(open(os.path.join(marks, os.listdir(marks)[0])))
    assert decision["detected_ts"] - inject["ts"] < 5.0


def test_kill_slice_with_buddy_store_checkpoint_fallback(chaos_cluster,
                                                         tmp_path):
    """Chaos kills a whole slice mid-step AND the test kills the store
    holding that slice's replicas (the buddy-slice-also-lost case): the
    controller must fall back to the checkpoint tier and still finish."""
    from ray_tpu.train.replica import store_name

    marks = str(tmp_path / "marks")
    chaos_cluster(rules=[
        {"point": "train.step", "action": "kill",
         "match": {"slice": 1, "restart": 0}, "at_step": 2, "count": 2,
         "mark": marks}])

    def kill_buddy_store():
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if os.path.isdir(marks) and os.listdir(marks):
                break
            time.sleep(0.05)
        # slice 1 pushes to store (1+1) % 2 = 0: kill it so the dead
        # ranks' shards are unrecoverable
        try:
            ray_tpu.kill(ray_tpu.get_actor(store_name("chaos-slice", 0)))
        except Exception:
            pass

    killer = threading.Thread(target=kill_buddy_store)
    killer.start()
    ctl, result = _run_controller(tmp_path, world=4, num_slices=2,
                                  steps=5, name="chaos-slice")
    killer.join()
    assert result.ok, result.error
    decision = result.restarts[0]
    assert decision["tier"] == "checkpoint"
    assert decision["trigger"] == "worker_dead"
    assert set(decision["dead_ranks"]) == {2, 3}  # the whole slice, scoped
    # both slice workers' kills fired
    assert len(os.listdir(marks)) == 2
    # the restart resumed from the checkpoint, not from scratch
    resumed = [m for m in result.metrics_history if m["restart"] == 1]
    assert resumed and all(m["source"] == "checkpoint" for m in resumed)
    assert min(m["step"] for m in resumed) >= 1


def test_kill_daemon_scoped(chaos_cluster):
    """daemon.tick kill takes down exactly the matched node: the head
    declares it dead on the disconnect fast path while the other node
    stays alive."""
    cluster, rt = chaos_cluster(prestart=0)
    doomed = cluster.add_node(num_cpus=1, node_id="doomedchaosnode")
    from ray_tpu.util.state import inject_chaos, list_nodes

    # wait for the node to register
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        if any(n["node_id"] == "doomedchaosnode" and n["alive"]
               for n in list_nodes()):
            break
        time.sleep(0.1)
    inject_chaos([{"point": "daemon.tick", "action": "kill",
                   "match": {"node": "^doomedchaos"}}])
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        rows = {n["node_id"]: n["alive"] for n in list_nodes()}
        if rows.get("doomedchaosnode") is False:
            break
        time.sleep(0.2)
    rows = {n["node_id"]: n["alive"] for n in list_nodes()}
    assert rows.get("doomedchaosnode") is False, rows
    # the OTHER node (the fixture's) is untouched
    assert sum(1 for alive in rows.values() if alive) >= 1
    if doomed in cluster.nodes:
        cluster.nodes.remove(doomed)
