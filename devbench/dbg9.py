import jax, jax.numpy as jnp, numpy as np
from jax import lax
NEG_INF = -1e30
rng = np.random.default_rng(0)
B,H,S,D,KB = 2,4,2048,64,512

def blockwise(q, k, v, cast_qk_f32=False, cast_p=True, m0=NEG_INF):
    b, h, sq, d = q.shape
    skv = k.shape[2]
    nblocks = skv // KB
    scale = 1.0 / np.sqrt(d)
    kb = k.reshape(b, h, nblocks, KB, d).transpose(2, 0, 1, 3, 4)
    vb = v.reshape(b, h, nblocks, KB, d).transpose(2, 0, 1, 3, 4)
    def step(carry, inputs):
        o, m, l = carry
        kblk, vblk = inputs
        if cast_qk_f32:
            s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32), kblk.astype(jnp.float32)) * scale
        else:
            s = jnp.einsum("bhqd,bhkd->bhqk", q, kblk).astype(jnp.float32) * scale
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + p.sum(axis=-1)
        pv = p.astype(vblk.dtype) if cast_p else p
        o_new = o * alpha[..., None] + jnp.einsum("bhqk,bhkd->bhqd", pv, vblk.astype(pv.dtype)).astype(jnp.float32)
        return (o_new, m_new, l_new), None
    o0 = jnp.zeros((b, h, sq, d), jnp.float32)
    mm0 = jnp.full((b, h, sq), m0, jnp.float32)
    l0 = jnp.zeros((b, h, sq), jnp.float32)
    (o, m, l), _ = lax.scan(step, (o0, mm0, l0), (kb, vb))
    l = jnp.maximum(l, 1e-30)
    return (o / l[..., None]).astype(q.dtype)

q = jnp.asarray(rng.standard_normal((B,H,S,D)), jnp.bfloat16)
k = jnp.asarray(rng.standard_normal((B,H,S,D)), jnp.bfloat16)
v = jnp.asarray(rng.standard_normal((B,H,S,D)), jnp.bfloat16)
def chk(name, **kw):
    f = lambda q,k,v: blockwise(q,k,v,**kw).astype(jnp.float32).sum()
    _, g = jax.jit(jax.value_and_grad(f, argnums=(0,1,2)))(q,k,v)
    nan = [bool(jnp.isnan(x.astype(jnp.float32)).any()) for x in g]
    print(name, kw, "nan:", nan, flush=True)
chk("base")
chk("qk_f32", cast_qk_f32=True)
chk("p_f32", cast_p=False)
chk("m0_-30", m0=-30.0)
chk("m0_-3e4", m0=-3e4)
