"""Worker group: one actor per training worker.

Capability parity with the reference's WorkerGroup (reference:
python/ray/train/v2/_internal/execution/worker_group/worker_group.py:113 —
actors placed via placement group, train_fn runs on a thread inside each
actor (thread_runner.py), poll_status :609 aggregates worker states).
"""

from __future__ import annotations

import threading
import traceback
from dataclasses import dataclass, field
from typing import Any, Callable

import ray_tpu
from ray_tpu.train.session import TrainContext, drain_reports, set_context


class TrainWorker:
    """Actor hosting one training worker; the user's train_fn runs on a
    dedicated thread so poll() stays responsive (max_concurrency=4)."""

    def __init__(self, rank: int, world_size: int, experiment: str,
                 storage_path: str | None, env: dict[str, str] | None = None):
        import os

        for k, v in (env or {}).items():
            os.environ[k] = v
        self.ctx = TrainContext(
            world_rank=rank, world_size=world_size, experiment_name=experiment,
            storage_path=storage_path, local_rank=0,
        )
        self._thread: threading.Thread | None = None
        self._status = "IDLE"  # IDLE | RUNNING | FINISHED | ERRORED
        self._result: Any = None
        self._error: str | None = None

    def setup_env(self, coordinator_addr: str | None, restart_count: int,
                  latest_checkpoint: str | None, num_slices: int = 1):
        self.ctx.coordinator_addr = coordinator_addr
        self.ctx.restart_count = restart_count
        self.ctx.latest_checkpoint = latest_checkpoint
        self.ctx.num_slices = max(1, int(num_slices))
        return True

    def set_dataset_shards(self, shards: dict) -> bool:
        self.ctx.dataset_shards = dict(shards)
        return True

    def run(self, train_fn: Callable, config: dict | None) -> bool:
        if self._status == "RUNNING":
            raise RuntimeError("worker already running")
        self._status = "RUNNING"

        def main():
            import inspect

            set_context(self.ctx)
            try:
                if len(inspect.signature(train_fn).parameters) >= 1:
                    self._result = train_fn(config if config is not None else {})
                else:
                    self._result = train_fn()
                self._status = "FINISHED"
            except BaseException:  # noqa: BLE001
                self._error = traceback.format_exc()
                self._status = "ERRORED"
            finally:
                set_context(None)

        self._thread = threading.Thread(target=main, daemon=True,
                                        name=f"train-fn-{self.ctx.world_rank}")
        self._thread.start()
        return True

    def poll(self) -> dict:
        return {
            "rank": self.ctx.world_rank,
            "status": self._status,
            "reports": drain_reports(self.ctx),
            "error": self._error,
        }

    def get_result(self):
        return self._result

    def ping(self) -> str:
        return "pong"

    def exec_fn(self, fn, *args, **kwargs):
        """Run an arbitrary function in this worker (backend setup hooks)."""
        return fn(*args, **kwargs)


@dataclass
class WorkerStatus:
    finished: bool = False
    errors: dict[int, str] = field(default_factory=dict)
    reports: list[dict] = field(default_factory=list)


class WorkerGroup:
    def __init__(self, scaling, experiment: str, storage_path: str | None,
                 env: dict[str, str] | None = None,
                 num_workers: int | None = None):
        self.scaling = scaling
        n = num_workers if num_workers is not None else scaling.num_workers
        self.num_workers = n
        res = scaling.worker_resources()
        WorkerActor = ray_tpu.remote(TrainWorker)
        opts: dict[str, Any] = {"max_concurrency": 4}
        opts["num_cpus"] = res.get("CPU", 0)
        opts["num_tpus"] = res.get("TPU", 0)
        extra = {k: v for k, v in res.items() if k not in ("CPU", "TPU")}
        if extra:
            opts["resources"] = extra
        self.workers = [
            WorkerActor.options(**opts).remote(
                rank, n, experiment, storage_path, env)
            for rank in range(n)
        ]

    def setup(self, coordinator_addr: str | None, restart_count: int,
              latest_checkpoint: str | None, num_slices: int = 1):
        ray_tpu.get([
            w.setup_env.remote(coordinator_addr, restart_count,
                               latest_checkpoint, num_slices)
            for w in self.workers
        ], timeout=120)

    def assign_dataset_shards(self, per_rank: list[dict]) -> None:
        """per_rank[i] = {name: DataIterator} for worker rank i."""
        ray_tpu.get([w.set_dataset_shards.remote(per_rank[i])
                     for i, w in enumerate(self.workers)], timeout=120)

    def run(self, train_fn: Callable, config: dict | None):
        ray_tpu.get([w.run.remote(train_fn, config) for w in self.workers],
                    timeout=120)

    def poll_status(self, timeout: float = 30.0) -> WorkerStatus:
        status = WorkerStatus()
        polls = ray_tpu.get([w.poll.remote() for w in self.workers],
                            timeout=timeout)
        states = [p["status"] for p in polls]
        for p in polls:
            status.reports.extend(
                {**r, "rank": p["rank"]} for r in p["reports"])
            if p["error"]:
                status.errors[p["rank"]] = p["error"]
        status.finished = all(s == "FINISHED" for s in states)
        return status

    def results(self) -> list:
        return ray_tpu.get([w.get_result.remote() for w in self.workers],
                           timeout=120)

    def shutdown(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
