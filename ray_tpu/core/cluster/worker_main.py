"""Worker process entry point: executes pushed tasks and hosts actors.

Capability parity with the reference's worker side (reference:
src/ray/core_worker/core_worker.cc HandlePushTask :3335 → TaskReceiver →
ordered/concurrent execution queues; python worker loop in
python/ray/_private/worker.py main_loop): the worker registers with its node
daemon, then serves ``push_task`` (stateless tasks) and
``init_actor``/``push_actor_task`` (actor hosting) over RPC. Task code runs
with this process's ClusterRuntime as the global runtime, so nested
``ray_tpu.get``/``.remote`` calls work from inside tasks.
"""

from __future__ import annotations

import asyncio
import inspect
import os
import queue
import threading
from collections import deque
from typing import Any

import cloudpickle

from ray_tpu.core.cluster.protocol import EventLoopThread, pack_reply
from ray_tpu.core.cluster.runtime import ClusterRuntime
from ray_tpu.core.exceptions import (
    ActorDiedError,
    OutOfMemoryError,
    TaskCancelledError,
    TaskError,
)
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.task_spec import ActorCreationSpec, TaskSpec
from ray_tpu.utils import serialization
from ray_tpu.utils.config import get_config


def _run_batch_contained(specs, run_one) -> list:
    """Run ``run_one(spec)`` for each spec in order, containing stale
    cancel_task async-interrupts that land BETWEEN tasks (see
    _SerialExecutor._run, which swallows exactly this case). An escape
    would fail the whole batch and get a healthy worker marked dead by
    the submitter."""
    replies: list = []
    while len(replies) < len(specs):
        try:
            while len(replies) < len(specs):
                replies.append(run_one(specs[len(replies)]))
        except TaskCancelledError:
            continue  # late interrupt for an already-finished task
    return replies


class _SerialExecutor:
    """One-task-at-a-time executor whose worker thread survives async-raised
    interrupts. cancel_task delivers TaskCancelledError via
    PyThreadState_SetAsyncExc; if the target task finishes before delivery,
    the exception lands between tasks — a ThreadPoolExecutor thread would die
    (and max_workers=1 never replaces it, wedging the worker), this loop
    swallows it and keeps serving. Interface subset of concurrent.futures
    used by loop.run_in_executor: submit() -> Future."""

    def __init__(self):
        import concurrent.futures
        import queue as _q

        self._futures = concurrent.futures
        self._q: "_q.Queue" = _q.Queue()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="task-exec")
        self._thread.start()

    def submit(self, fn, *args):
        fut = self._futures.Future()
        self._q.put((fut, fn, args))
        return fut

    def shutdown(self, wait=True):  # noqa: ARG002 - interface compat
        self._q.put(None)

    def _run(self):
        while True:
            try:
                item = self._q.get()
                if item is None:
                    return
                fut, fn, args = item
                if not fut.set_running_or_notify_cancel():
                    continue
                try:
                    fut.set_result(fn(*args))
                except BaseException as e:  # noqa: BLE001
                    fut.set_exception(e)
            except TaskCancelledError:
                continue  # late async interrupt landed between tasks


class WorkerProcess:
    def __init__(self):
        head = os.environ["RTPU_HEAD"].split(":")
        daemon = os.environ["RTPU_NODE_DAEMON"].split(":")
        self.runtime = ClusterRuntime(
            head[0], int(head[1]),
            node_daemon_addr=(daemon[0], int(daemon[1])),
            is_worker=True,
        )
        # Bind the process-global worker so user code sees the cluster runtime.
        from ray_tpu.core.worker import global_worker
        from ray_tpu.utils.ids import JobID

        global_worker.runtime = self.runtime
        global_worker.worker_id = self.runtime.worker_id
        global_worker.node_id = self.runtime.node_id
        global_worker.job_id = JobID.from_random()
        global_worker.mode = "cluster"

        self._io = EventLoopThread.get()
        srv = self.runtime.server
        srv.register("push_task", self._push_task)
        srv.register("init_actor", self._init_actor)
        # Fast-path frames, dispatched INLINE in the read loop (no task
        # spawn, no reply future): the execution thread deserializes the
        # spec, runs it, packs the reply itself, and posts the pre-packed
        # bytes back with one loop wake (reference: the direct-call path in
        # core_worker.cc answers PushTask from the executing thread).
        # push_actor_task (streaming) MUST ride the same inline dispatch:
        # mixing an inline route with a task-spawned one would let later
        # calls reach the mailbox before an earlier streaming call.
        srv.register_raw("push_task_batch", self._push_task_batch_raw)
        srv.register_raw("push_actor_task", self._push_actor_call_raw)
        srv.register_raw("push_actor_calls", self._push_actor_calls_raw)
        srv.register("cancel_task", self._cancel_task)
        srv.register("exit_worker", self._exit_worker)
        # On-demand profiling plane (head -> node_daemon -> here): captures
        # run on an executor thread so they sample task/actor execution
        # instead of blocking behind it. (dump_stack / memory_snapshot
        # one-shots are registered by ClusterRuntime for every process.)
        srv.register("profile", self._profile)
        # Cancellation state: ids cancelled before start, and the thread
        # currently executing each task (for async interrupt).
        self._cancelled_tasks: set[str] = set()
        self._running_tasks: dict[str, int] = {}  # task_id hex -> thread ident
        # Deserialized-function cache keyed by the exact code blob — repeat
        # submissions of the same @remote function skip the unpickle
        # (reference: function_manager.py caches imported remote functions).
        # Only specs from registry-less submitters (client-mode proxies)
        # still embed blobs; registry specs use _registry_cache below.
        self._fn_cache: dict[bytes, Any] = {}
        # Registry-fetched definitions, LRU-bounded by serialized size
        # (reference: FunctionManager fetch-and-cache from the GCS table).
        from ray_tpu.core.fn_registry import FnCache

        self._registry_cache = FnCache(get_config().fn_cache_max_bytes)
        self._task_executor = _SerialExecutor()
        # Cross-thread reply buffer: execution threads enqueue pre-packed
        # reply frames the moment each call finishes (nothing is ever held
        # across a later execution), and ONE loop wake drains everything
        # enqueued since the last drain — the same coalescing the submit
        # buffer uses on the driver side. Under load one self-pipe write
        # covers a burst of replies; when idle, the wake is immediate.
        self._reply_buf: deque = deque()
        self._reply_wake = False
        self._reply_lock = threading.Lock()
        self._actor_instance: Any = None
        self._actor_id_hex: str | None = None
        self._actor_mailbox: "queue.Queue" = queue.Queue()
        self._actor_loop: asyncio.AbstractEventLoop | None = None
        self._actor_pool = None
        self._exit_event = threading.Event()

        self.node_id_hex = os.environ.get("RTPU_NODE_ID", "")
        self.runtime._daemon.call(
            "register_worker_proc",
            worker_id=self.runtime.worker_id.hex(),
            host=self.runtime.addr[0], port=self.runtime.addr[1],
            pid=os.getpid(),
            # Containerized workers see a different pid than the daemon's
            # Popen (the runner's); the fork nonce is the reliable join key.
            nonce=os.environ.get("RTPU_WORKER_NONCE", ""),
        )
        # Task events, spans, and metric snapshots all reach the head via
        # the runtime's telemetry flusher (ClusterRuntime._telemetry_flusher
        # — reference: TaskEventBuffer flushing into GcsTaskManager plus the
        # metrics agent push); workers need no extra thread here.

    # ------------------------------------------------------------------ tasks
    async def _push_task(self, conn, spec_blob: bytes):
        spec: TaskSpec = serialization.loads_spec(spec_blob)
        loop = asyncio.get_running_loop()
        emit = self._stream_emitter(conn, loop, spec) \
            if spec.num_returns == "streaming" else None
        # Serial execution: one normal task at a time per leased worker
        # (reference semantics — a worker runs one task; pipelined pushes
        # queue here, matching lease-based resource accounting).
        return await loop.run_in_executor(self._task_executor,
                                          self._execute_task, spec, emit)

    def _push_task_batch_raw(self, conn, msg: dict):
        """Batched push, raw-dispatched: N specs in one frame, executed in
        order, N results in one reply. Spec deserialization AND reply
        packing happen on the execution thread; the io loop's only work per
        batch is one enqueue and one write (the per-task dispatch
        task/future/executor hop dominated small-task throughput on
        few-core hosts)."""
        self._task_executor.submit(
            self._run_task_batch, msg["a"]["blobs"], msg.get("i"), conn,
            asyncio.get_running_loop())

    def _post_reply(self, loop, conn, frame: bytes) -> None:
        """Ship one pre-packed reply from an execution thread: enqueued
        immediately (never held behind a later execution), with coalesced
        loop wakes — one self-pipe write covers every reply buffered until
        the drain runs."""
        with self._reply_lock:
            self._reply_buf.append((conn, frame))
            wake = not self._reply_wake
            self._reply_wake = True
        if wake:
            loop.call_soon_threadsafe(self._drain_replies)

    def _drain_replies(self) -> None:
        with self._reply_lock:
            items = list(self._reply_buf)
            self._reply_buf.clear()
            self._reply_wake = False
        for conn, frame in items:
            conn.post(frame)

    def _run_task_batch(self, blobs: list, rid, conn, loop) -> None:
        try:
            specs = [serialization.loads_spec(b) for b in blobs]
            replies = self._execute_batch(specs)
            data = pack_reply(rid, {"replies": replies})
        except BaseException as e:  # noqa: BLE001 - client must not hang
            data = pack_reply(rid, err=f"{type(e).__name__}: {e}")
        self._post_reply(loop, conn, data)

    def _execute_batch(self, specs) -> list:
        return _run_batch_contained(
            specs, lambda spec: self._execute_task(spec, None))

    def _stream_emitter(self, conn, loop, spec):
        """Item pump for streaming tasks: each yield goes back to the owner
        as a notify frame on the submitting connection (TCP ordering puts
        every item before the final reply — reference: streamed generator
        returns report each dynamic return to the owner as produced)."""
        cfg = get_config()

        def emit(index: int, value) -> None:
            from ray_tpu.utils.ids import ObjectID

            blob = serialization.serialize(value)
            tid = spec.task_id.hex()
            if len(blob) <= cfg.inline_object_max_bytes:
                coro = conn.notify("stream_item", task_id=tid, index=index,
                                   data=blob)
            else:
                oid = ObjectID.for_task_return(spec.task_id, index)
                self.runtime._store_blob(
                    oid, blob, spec.owner_id or self.runtime.worker_id)
                coro = conn.notify("stream_item", task_id=tid, index=index,
                                   location=self.runtime.worker_id.hex(),
                                   size=len(blob))
            asyncio.run_coroutine_threadsafe(coro, loop).result(timeout=60)

        return emit

    def _run_stream(self, spec, result, emit) -> dict:
        """Drive a streaming task's generator; returns the end-of-stream
        reply ({"stream_count": N} or the error for the end marker).
        Registered in _running_tasks for the whole drive so cancel_task can
        interrupt mid-stream (the generator body runs HERE, not in the
        user-function call that produced the generator object)."""
        tid_hex = spec.task_id.hex()
        self._running_tasks[tid_hex] = threading.get_ident()
        i = 0
        try:
            for v in result:
                if tid_hex in self._cancelled_tasks:
                    raise TaskCancelledError()
                emit(i, v)
                i += 1
        except BaseException as e:  # noqa: BLE001
            err = e if isinstance(e, (TaskError, ActorDiedError,
                                      TaskCancelledError,
                                      OutOfMemoryError)) \
                else TaskError(e, task_desc=spec.name)
            return {"results": [{"data": serialization.serialize(err)}],
                    "stream_error": True}
        finally:
            self._running_tasks.pop(tid_hex, None)
            self._cancelled_tasks.discard(tid_hex)
        return {"stream_count": i}

    async def _cancel_task(self, conn, task_id: str, force: bool = False):
        """Best-effort cancel (reference: CoreWorker::HandleCancelTask —
        interrupt the running task or drop it from the queue). A running
        task is interrupted by raising TaskCancelledError asynchronously in
        its executing thread."""
        self._cancelled_tasks.add(task_id)
        tident = self._running_tasks.get(task_id)
        if tident is not None:
            import ctypes

            ctypes.pythonapi.PyThreadState_SetAsyncExc(
                ctypes.c_ulong(tident), ctypes.py_object(TaskCancelledError))
        return {"ok": True, "was_running": tident is not None}

    def _execute_task(self, spec: TaskSpec, stream_emit=None) -> dict:
        from ray_tpu.core.events import task_execution
        from ray_tpu.core.worker import set_task_context

        return_ids = spec.return_ids()
        tid_hex = spec.task_id.hex()
        if tid_hex in self._cancelled_tasks:
            self._cancelled_tasks.discard(tid_hex)
            blob = serialization.serialize(TaskCancelledError())
            return {"results": [{"data": blob} for _ in return_ids]}
        self._running_tasks[tid_hex] = threading.get_ident()
        try:
            if spec.runtime_env:
                from ray_tpu.runtime_env import get_manager

                get_manager().ensure(spec.runtime_env, self.runtime)
            fn = self._load_definition(spec.fn_id, spec.fn_blob)
            args, kwargs = serialization.deserialize(spec.args_blob)
            args = self._resolve(args)
            kwargs = self._resolve(kwargs)
            set_task_context(spec.task_id, spec.actor_id, spec.resources)
            try:
                with task_execution(spec, self.runtime.worker_id.hex(),
                                    node_id=self.node_id_hex):
                    result = fn(*args, **kwargs)
            finally:
                set_task_context(None, None, None)
        except BaseException as e:  # noqa: BLE001
            err = e if isinstance(e, (TaskError, ActorDiedError,
                                      TaskCancelledError,
                                      OutOfMemoryError)) \
                else TaskError(e, task_desc=spec.name)
            if not isinstance(e, TaskCancelledError):
                # Application exceptions are terminal in cluster mode: the
                # submitter's retry budget only covers SYSTEM failures
                # (worker death — RpcError/OSError on the push), so this
                # path never fires for an attempt that will be retried.
                from ray_tpu.core import flight_recorder

                flight_recorder.record(
                    "task_failure", reason=repr(e), task_id=tid_hex,
                    node_id=self.node_id_hex,
                    extra={"task": spec.name,
                           "worker_id": self.runtime.worker_id.hex()})
            blob = serialization.serialize(err)
            return {"results": [{"data": blob} for _ in return_ids]}
        finally:
            self._running_tasks.pop(tid_hex, None)
            self._cancelled_tasks.discard(tid_hex)
        if stream_emit is not None:
            return self._run_stream(spec, result, stream_emit)
        return {"results": self._package_results(spec, return_ids, result)}

    def _load_definition(self, fn_id: str, fn_blob: bytes):
        """Resolve a task's callable: registry cache hit, registry fetch on
        miss (exactly once per definition per worker), or the embedded-blob
        legacy path for registry-less submitters."""
        if fn_id:
            from ray_tpu.core.cluster.runtime import observe_ctrl_fn

            fn = self._registry_cache.get(fn_id)
            if fn is not None:
                observe_ctrl_fn("hit", 0)
                return fn
            blob = fn_blob or self.runtime.fetch_function(fn_id)
            fn = serialization.loads_function(blob)
            self._registry_cache.put(fn_id, fn, len(blob))
            return fn
        fn = self._fn_cache.get(fn_blob)
        if fn is None:
            fn = serialization.loads_function(fn_blob)
            if len(self._fn_cache) > 256:
                self._fn_cache.clear()
            self._fn_cache[fn_blob] = fn
        return fn

    def _resolve(self, obj):
        if isinstance(obj, ObjectRef):
            return self.runtime.get([obj])[0]
        if isinstance(obj, tuple):
            return tuple(self._resolve(o) if isinstance(o, ObjectRef) else o for o in obj)
        if isinstance(obj, list):
            return obj
        if isinstance(obj, dict):
            return {k: (self._resolve(v) if isinstance(v, ObjectRef) else v)
                    for k, v in obj.items()}
        return obj

    def _package_results(self, spec: TaskSpec, return_ids, result) -> list[dict]:
        cfg = get_config()
        values = [result] if spec.num_returns == 1 else list(result)
        if len(values) != spec.num_returns:
            err = TaskError(
                ValueError(f"declared num_returns={spec.num_returns}, got {len(values)}"),
                task_desc=spec.name)
            blob = serialization.serialize(err)
            return [{"data": blob} for _ in return_ids]
        out = []
        for oid, v in zip(return_ids, values):
            if isinstance(v, ObjectRef):
                v = self.runtime.get([v])[0]
            blob = serialization.serialize(v)
            if len(blob) <= cfg.inline_object_max_bytes:
                out.append({"data": blob})
            else:
                # Large result: goes to the node shm arena when available
                # (same-node readers get it zero-copy without an RPC), else
                # stays in our process store; either way the owner records
                # our location for cross-node fetches (reference: results
                # over max_direct_call_object_size go to plasma at the
                # executor).
                self.runtime._store_blob(
                    oid, blob, spec.owner_id or self.runtime.worker_id)
                out.append({"location": self.runtime.worker_id.hex(),
                            "size": len(blob)})
        return out

    # ------------------------------------------------------------------ actors
    async def _init_actor(self, conn, actor_id: str, spec_blob: bytes):
        spec: ActorCreationSpec = cloudpickle.loads(spec_blob)
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(None, self._do_init_actor, actor_id, spec)

    def _do_init_actor(self, actor_id: str, spec: ActorCreationSpec) -> dict:
        try:
            if spec.runtime_env:
                from ray_tpu.runtime_env import get_manager

                get_manager().ensure(spec.runtime_env, self.runtime)
            cls = self._load_definition(getattr(spec, "cls_id", ""),
                                        spec.cls_blob)
            args, kwargs = serialization.deserialize(spec.args_blob)
            self._actor_instance = cls(*self._resolve(args), **self._resolve(kwargs))
            self._actor_id_hex = actor_id
            if any(
                inspect.iscoroutinefunction(getattr(type(self._actor_instance), m, None))
                for m in dir(type(self._actor_instance)) if not m.startswith("__")
            ):
                self._actor_loop = asyncio.new_event_loop()
                threading.Thread(target=self._actor_loop.run_forever, daemon=True).start()
            if spec.max_concurrency > 1:
                from concurrent.futures import ThreadPoolExecutor

                self._actor_pool = ThreadPoolExecutor(max_workers=spec.max_concurrency)
            # Ordered mailbox consumer (reference: ordered actor execution queue).
            threading.Thread(target=self._actor_consumer, daemon=True).start()
            return {"ok": True}
        except BaseException as e:  # noqa: BLE001
            return {"ok": False, "error": f"__init__ failed: {e!r}"}

    def _actor_consumer(self):
        while True:
            item = self._actor_mailbox.get()
            if item is None:
                return
            if item[0] == "__call__":
                # Fast-path call (raw-dispatched push_actor_call(s) frame):
                # decode the spec HERE (off the io loop), execute in
                # mailbox order, serialize the reply on this thread, and
                # post pre-packed bytes — the loop's only per-call work is
                # one write, and each reply ships the moment its call
                # finishes (a later slow method never holds an earlier
                # result hostage; the coalescing writer still merges
                # replies landing in the same loop tick into one syscall).
                # Concurrent execution modes (async methods, concurrency
                # pools, injected fns) run on their own threads and post
                # their replies the same way when THEY finish, so replies
                # correlate out-of-order by request id.
                _, spec_blob, rid, conn, loop = item
                try:
                    spec: TaskSpec = serialization.loads_spec(spec_blob)
                except BaseException as e:  # noqa: BLE001
                    loop.call_soon_threadsafe(conn.post, pack_reply(
                        rid, err=f"{type(e).__name__}: {e}"))
                    continue
                if not self._dispatch_concurrent(spec, rid, conn, loop):
                    self._run_actor_call(spec, rid, conn, loop)
                continue

    def _dispatch_concurrent(self, spec: TaskSpec, rid, conn, loop) -> bool:
        """Route a fast-path call that must NOT run on the ordered consumer
        thread (async methods, concurrency pools, injected long-running
        fns) to its executor. Returns False for plain sync methods — the
        consumer runs those inline, preserving mailbox order."""
        if spec.method_name == "__rtpu_call_fn__":
            threading.Thread(target=self._run_actor_call,
                             args=(spec, rid, conn, loop),
                             daemon=True).start()
            return True
        method = getattr(type(self._actor_instance), spec.method_name, None)
        if inspect.iscoroutinefunction(method) or self._actor_pool is not None:
            if self._actor_pool is not None:
                self._actor_pool.submit(self._run_actor_call,
                                        spec, rid, conn, loop)
            else:
                threading.Thread(target=self._run_actor_call,
                                 args=(spec, rid, conn, loop),
                                 daemon=True).start()
            return True
        return False

    def _run_actor_call(self, spec: TaskSpec, rid, conn, loop) -> None:
        """Execute one fast-path call and post its reply: serialization on
        the execution thread, coalesced loop wakes (_post_reply), and the
        coalescing writer merges frames shipped in one tick into one
        syscall."""
        reply = self._exec_actor_reply(spec, loop, conn)
        try:
            data = pack_reply(rid, reply)
        except BaseException as e:  # noqa: BLE001 - unpackable reply value
            data = pack_reply(rid, err=f"{type(e).__name__}: {e}")
        self._post_reply(loop, conn, data)

    def _exec_actor_reply(self, spec: TaskSpec, loop, conn=None) -> dict:
        from ray_tpu.core.events import task_execution
        from ray_tpu.core.worker import set_task_context

        return_ids = spec.return_ids()
        try:
            args, kwargs = serialization.deserialize(spec.args_blob)
            args, kwargs = self._resolve(args), self._resolve(kwargs)
            if spec.method_name == "__rtpu_call_fn__":
                # Internal hook: fn(instance, *args) in actor context
                # (reference: __ray_call__; compiled-graph loop installer).
                import functools

                method = functools.partial(args[0], self._actor_instance)
                args = args[1:]
            else:
                method = getattr(self._actor_instance, spec.method_name)
            set_task_context(spec.task_id, spec.actor_id, spec.resources)
            try:
                with task_execution(spec, self.runtime.worker_id.hex(),
                                    node_id=self.node_id_hex):
                    if inspect.iscoroutinefunction(method):
                        fut = asyncio.run_coroutine_threadsafe(
                            method(*args, **kwargs), self._actor_loop)
                        result = fut.result()
                    else:
                        result = method(*args, **kwargs)
            finally:
                set_task_context(None, None, None)
            if spec.num_returns == "streaming" and conn is not None:
                emit = self._stream_emitter(conn, loop, spec)
                reply = self._run_stream(spec, result, emit)
            else:
                reply = {"results": self._package_results(spec, return_ids,
                                                          result)}
        except BaseException as e:  # noqa: BLE001
            err = e if isinstance(e, (TaskError, ActorDiedError,
                                      TaskCancelledError,
                                      OutOfMemoryError)) \
                else TaskError(e, task_desc=spec.method_name or "")
            reply = {"results": [{"data": serialization.serialize(err)}
                                 for _ in return_ids]}
        return reply

    def _push_actor_call_raw(self, conn, msg: dict):
        """Direct actor call (raw-dispatched): the read loop's entire work
        is one mailbox enqueue. Replies correlate by request id, so calls
        finishing out of order (async actors, pools) answer out of order —
        a sync 1:1 call is one RPC round trip with no reply future, no
        dispatch task, and no loop hop between execution and reply
        serialization. Streaming calls (legacy push_actor_task frames)
        take the same route: _exec_actor_reply drives the generator and
        the stream-end reply posts like any other."""
        rid = msg.get("i")
        if self._actor_instance is None:
            conn.post(pack_reply(rid, {
                "dead": True, "reason": "no actor hosted in this worker"}))
            return
        self._actor_mailbox.put((
            "__call__", msg["a"]["spec_blob"], rid, conn,
            asyncio.get_running_loop()))

    def _push_actor_calls_raw(self, conn, msg: dict):
        """Multi-call frame: N individually-correlated calls ride one frame
        (one decode, N mailbox items); replies flow back per call, batched
        per consumer sweep (see _actor_consumer's reply flushing)."""
        calls = msg.get("c") or []
        if self._actor_instance is None:
            conn.post([pack_reply(rid, {
                "dead": True, "reason": "no actor hosted in this worker"})
                for rid, _ in calls])
            return
        loop = asyncio.get_running_loop()
        put = self._actor_mailbox.put
        for rid, blob in calls:
            put(("__call__", blob, rid, conn, loop))

    # ------------------------------------------------------------- profiling
    async def _profile(self, conn, seconds: float = 1.0,
                       sample_hz: float = 0.0):
        """One capture of THIS worker: stack samples + (guarded) XLA trace +
        memory snapshot. Runs on the default executor — the serial task
        executor keeps executing, which is the whole point of sampling it."""
        import functools

        from ray_tpu.profiling import capture_profile

        loop = asyncio.get_running_loop()
        meta = {"kind": "worker", "worker_id": self.runtime.worker_id.hex(),
                "node_id": self.node_id_hex,
                "actor_id": self._actor_id_hex or ""}
        return await loop.run_in_executor(None, functools.partial(
            capture_profile, seconds, sample_hz=sample_hz or None,
            meta=meta))

    async def _exit_worker(self, conn):
        self._exit_event.set()
        return {"ok": True}

    def serve_forever(self):
        self._exit_event.wait()


def _parent_watchdog():
    """Exit if the spawning daemon process dies (orphan prevention —
    reference: workers die with their raylet via the IPC socket)."""
    parent = int(os.environ.get("RTPU_PARENT_PID", "0"))
    if not parent:
        return
    import time as _t

    def watch():
        while True:
            try:
                os.kill(parent, 0)
            except OSError:
                os._exit(0)
            _t.sleep(1.0)

    threading.Thread(target=watch, daemon=True).start()


def _install_sigusr2_dump():
    """Hung-worker last resort: SIGUSR2 dumps every thread's stack into a
    flight-recorder bundle (kind ``worker_stacks``) that survives the
    process. The node daemon sends it before escalating to SIGKILL on
    unresponsive workers, so a post-mortem always has the final stacks even
    when the RPC plane is wedged."""
    import signal

    def _dump(signum, frame):  # noqa: ARG001 - signal handler signature
        try:
            from ray_tpu.core import flight_recorder
            from ray_tpu.profiling.sampler import dump_stacks

            # local_only: the dump must not block on a head RPC — the RPC
            # plane being wedged (or the kill-grace window expiring) is
            # exactly when this handler fires.
            flight_recorder.record(
                "worker_stacks", reason="SIGUSR2 stack dump",
                node_id=os.environ.get("RTPU_NODE_ID", ""),
                extra={"stacks": dump_stacks(), "pid": os.getpid()},
                local_only=True)
        except Exception:
            pass  # a dump must never make a dying worker die harder

    signal.signal(signal.SIGUSR2, _dump)


def main():
    # SIGUSR1 dumps all thread stacks to the worker log — the first tool to
    # reach for when a worker wedges (reference: ray stack / py-spy dump).
    import faulthandler
    import signal

    faulthandler.register(signal.SIGUSR1, all_threads=True)
    _install_sigusr2_dump()
    # Honor a platform pin for jax-using task/actor code. The env var
    # JAX_PLATFORMS alone is NOT enough in environments whose
    # sitecustomize pre-imports jax with a device-tunnel platform
    # registered (its init can hang without a live device); the config
    # update must land before any backend initialization.
    plat = os.environ.get("RTPU_JAX_PLATFORMS")
    if plat:
        try:
            import jax

            jax.config.update("jax_platforms", plat)
        except Exception:
            pass
    _parent_watchdog()
    wp = WorkerProcess()
    wp.serve_forever()


if __name__ == "__main__":
    main()
