"""Bisect the serve-stack degradation: drive the deployment HANDLE directly
(router → replica actor → engine via the runtime's streaming generator) with
bench-shaped load, skipping the HTTP proxy. Compare with
prof_serve_frames.py (full stack) and prof_engine.py (engine only).

RTPU_PROF_TINY=1 JAX_PLATFORMS=cpu PYTHONPATH=. python devbench/prof_serve_handle.py
"""
import os
import threading
import time

import ray_tpu
import ray_tpu.core.worker
from ray_tpu import serve
from ray_tpu.llm import LLMConfig
from ray_tpu.llm.serving import build_openai_app
from ray_tpu.serve.http_proxy import Request
import json

if os.environ.get("RTPU_PROF_TINY") == "1":
    cfg = LLMConfig(model="tiny", max_num_seqs=8, max_seq_len=256)
else:
    cfg = LLMConfig(model="llama3_1b", max_num_seqs=8, max_seq_len=1024,
                    dtype="bfloat16")

N = int(os.environ.get("RTPU_PROF_N", "100"))
CONC, MAXTOK = 8, 32

ray_tpu.init()
app = serve.run(build_openai_app(cfg), route_prefix="/", http=False)
handle = serve.get_deployment_handle("LLMServer")


def one(i, stats=None):
    body = json.dumps({
        "messages": [{"role": "user", "content": f"benchmark prompt {i} " * 4}],
        "max_tokens": MAXTOK, "temperature": 0.0, "stream": True,
    }).encode()
    req = Request(method="POST", path="/v1/chat/completions",
                  query_params={}, headers={}, body=body)
    t0 = time.perf_counter()
    gen = handle.options(stream=True).remote(req)
    assert gen.streaming  # forces the meta fetch
    t_meta = time.perf_counter() - t0
    first, n = None, 0
    for chunk in gen:
        if isinstance(chunk, str) and '"content"' in chunk:
            if first is None:
                first = time.perf_counter() - t0
            n += 1
    return first, n, t_meta


print("warm:", one(991)[:2])

sem = threading.Semaphore(CONC)
lock = threading.Lock()
out = []


def _sizes():
    rt = ray_tpu.core.worker.global_worker.runtime
    store = getattr(rt, "store", None)
    data = getattr(store, "_data", None) or getattr(store, "_objects", {})
    refs = getattr(rt, "refs", None)
    counts = {}
    for attr in dir(refs):
        v = getattr(refs, attr, None)
        if isinstance(v, (dict, set)) and not attr.startswith("__"):
            counts[attr] = len(v)
    return len(data), counts, len(getattr(rt, "_released", []))


def worker(i):
    with sem:
        try:
            ttft, n, t_meta = one(i)
        except Exception as e:  # noqa: BLE001
            print("fail", i, repr(e)[:120])
            return
        with lock:
            out.append((ttft, n))
            if len(out) % 20 == 0:
                r = handle._router
                inflight = dict(getattr(r, "_inflight", {}))
                print(f"[done={len(out)}] ttft={ttft*1e3:.0f}ms "
                      f"meta={t_meta*1e3:.0f}ms "
                      f"threads={threading.active_count()} "
                      f"router_inflight={inflight}", flush=True)


ts = [threading.Thread(target=worker, args=(i,)) for i in range(N)]
t0 = time.perf_counter()
for t in ts:
    t.start()
for t in ts:
    t.join()
wall = time.perf_counter() - t0
tot = sum(n for _, n in out)
qt = max(1, len(out) // 4)
early = [t for t, _ in out[:qt] if t]
late = [t for t, _ in out[-qt:] if t]
print(f"handle-direct: {tot} tokens / {wall:.1f}s = {tot/wall:.0f} tok/s; "
      f"ttft first-q {sum(early)/len(early)*1e3:.0f} ms, "
      f"last-q {sum(late)/len(late)*1e3:.0f} ms")
serve.shutdown()
ray_tpu.shutdown()
