import jax, jax.numpy as jnp, numpy as np
from jax import lax
NEG_INF=-1e30
rng = np.random.default_rng(0)
B,H,S,D,KB = 2,4,2048,64,512
def blockwise(q, k, v):
    b, h, sq, d = q.shape
    skv = k.shape[2]; nb = skv // KB
    scale = 1.0/np.sqrt(d)
    kb = k.reshape(b,h,nb,KB,d).transpose(2,0,1,3,4)
    vb = v.reshape(b,h,nb,KB,d).transpose(2,0,1,3,4)
    def step(carry, inputs):
        o, m, l = carry
        kblk, vblk = inputs
        s = jnp.einsum("bhqd,bhkd->bhqk", q, kblk,
                       preferred_element_type=jnp.float32) * scale
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + p.sum(axis=-1)
        o_new = o * alpha[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p.astype(vblk.dtype), vblk,
            preferred_element_type=jnp.float32)
        return (o_new, m_new, l_new), None
    o0 = jnp.zeros((b,h,sq,d), jnp.float32); m0 = jnp.full((b,h,sq), NEG_INF, jnp.float32); l0 = jnp.zeros((b,h,sq), jnp.float32)
    (o, m, l), _ = lax.scan(step, (o0,m0,l0), (kb, vb))
    return (o / jnp.maximum(l,1e-30)[..., None]).astype(q.dtype)

q = jnp.asarray(rng.standard_normal((B,H,S,D)), jnp.bfloat16)
k = jnp.asarray(rng.standard_normal((B,H,S,D)), jnp.bfloat16)
v = jnp.asarray(rng.standard_normal((B,H,S,D)), jnp.bfloat16)
f = lambda q,k,v: blockwise(q,k,v).astype(jnp.float32).sum()
_, g = jax.jit(jax.value_and_grad(f, argnums=(0,1,2)))(q,k,v)
print("preferred-f32: nan:", [bool(jnp.isnan(x.astype(jnp.float32)).any()) for x in g], flush=True)
