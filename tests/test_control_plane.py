"""Control-plane fast path: function registry, batched lease grants,
out-of-order actor replies, batched placement-group placement.

Coverage modeled on the reference's function-manager and lease-path tests
(reference: python/ray/tests/test_advanced.py task-spec wire behavior;
worker_pool_test.cc lease grant accounting; gcs_placement_group tests for
batch prepare/commit).
"""

import os
import threading
import time

import pytest

import ray_tpu
from ray_tpu import remote
from ray_tpu.cluster_utils import Cluster
from ray_tpu.core.fn_registry import FN_NS, FnCache, fn_id
from ray_tpu.core.worker import global_worker
from ray_tpu.utils.ids import JobID


@pytest.fixture(scope="module")
def cluster():
    os.environ["RTPU_WORKER_IDLE_TTL_S"] = "120"
    os.environ["RTPU_HEALTH_CHECK_PERIOD_S"] = "0.2"
    from ray_tpu.utils import config as config_mod

    config_mod.set_config(config_mod.Config.load())
    c = Cluster()
    c.add_node(num_cpus=4)
    rt = c.connect()
    global_worker.runtime = rt
    global_worker.worker_id = rt.worker_id
    global_worker.node_id = rt.node_id
    global_worker.job_id = JobID.from_random()
    global_worker.mode = "cluster"
    yield c
    rt.shutdown()
    c.shutdown()
    global_worker.runtime = None
    config_mod.set_config(config_mod.Config.load())


# ---------------------------------------------------------------- registry
def test_fn_cache_hit_miss_eviction():
    cache = FnCache(max_bytes=100)
    assert cache.get("a") is None  # miss
    cache.put("a", "fa", 40)
    cache.put("b", "fb", 40)
    assert cache.get("a") == "fa"  # hit refreshes LRU position
    cache.put("c", "fc", 40)  # over budget: evicts LRU ("b", not "a")
    assert cache.get("b") is None
    assert cache.get("a") == "fa"
    assert cache.get("c") == "fc"
    assert cache.evictions == 1
    # A single definition larger than the whole budget is still usable.
    cache.put("huge", "fh", 10_000)
    assert cache.get("huge") == "fh"
    assert len(cache) == 1


def test_fn_id_is_content_addressed():
    assert fn_id(b"same") == fn_id(b"same")
    assert fn_id(b"same") != fn_id(b"different")


def test_definition_exported_once_across_submits_and_options(cluster):
    """N submissions of one @remote function — including .options() copies
    that only change resources — export the definition to the head exactly
    once (the per-task spec carries only the content id)."""
    head = cluster.head
    puts_before = head.fn_stats["puts"]

    @remote
    def reg_probe(x):
        return x * 7

    refs = [reg_probe.remote(i) for i in range(10)]
    # .options() copies share the cached blob AND its registry id: no
    # re-export of an identical definition under a new id.
    refs += [reg_probe.options(num_cpus=2).remote(i) for i in range(5)]
    assert ray_tpu.get(refs, timeout=120) == \
        [i * 7 for i in range(10)] + [i * 7 for i in range(5)]
    assert head.fn_stats["puts"] == puts_before + 1
    # The definition landed in the persistent KV namespace.
    blob_id = reg_probe._fn_id
    assert head.kv[FN_NS][blob_id] == reg_probe._fn_blob


def test_worker_fetches_definition_once_per_worker(cluster):
    """Across N tasks of one function, each executing worker fetches the
    definition at most once (cache hits afterwards) — per-task wire bytes
    stay O(spec header)."""
    head = cluster.head
    gets_before = head.fn_stats["gets"]

    @remote
    def fetch_probe(_i):
        return os.getpid()

    pids = set(ray_tpu.get([fetch_probe.remote(i) for i in range(30)],
                           timeout=120))
    fetches = head.fn_stats["gets"] - gets_before
    assert fetches <= len(pids), (fetches, pids)
    assert fetches < 30  # definitively NOT once per task
    # Per-task wire bytes are O(spec header): a repeat-submitted spec no
    # longer embeds the definition, so it serializes far smaller than the
    # pickled function it names.
    from ray_tpu.core.task_spec import TaskSpec
    from ray_tpu.utils import serialization
    from ray_tpu.utils.ids import TaskID

    spec = TaskSpec(
        task_id=TaskID.of(global_worker.job_id),
        job_id=global_worker.job_id, fn_blob=b"",
        fn_id=fetch_probe._fn_id,
        args_blob=serialization.serialize(((1,), {})))
    assert len(serialization.dumps_spec(spec)) < len(fetch_probe._fn_blob)


def test_local_mode_registry_roundtrip():
    """LocalRuntime honors the same export/lookup contract (and unpickles
    a definition once per process, not once per task). Uses a private
    LocalRuntime so the module's cluster fixture stays untouched."""
    from ray_tpu.core.local_runtime import LocalRuntime

    rt = LocalRuntime(num_cpus=4)
    old = (global_worker.runtime, global_worker.worker_id, global_worker.mode)
    global_worker.runtime = rt
    global_worker.worker_id = rt.worker_id
    global_worker.mode = "local"
    try:
        @remote
        def local_probe(x):
            return x + 100

        assert ray_tpu.get([local_probe.remote(i) for i in range(5)],
                           timeout=60) == [100 + i for i in range(5)]
        assert local_probe._fn_id in rt._fn_defs
        assert local_probe._fn_id in rt._fns
    finally:
        rt.shutdown()
        (global_worker.runtime, global_worker.worker_id,
         global_worker.mode) = old


# ---------------------------------------------------------------- leases
def test_batched_lease_grant_accounting(cluster):
    """One lease_workers RPC grants K leases; returning them restores the
    daemon's availability."""
    from ray_tpu.core.cluster.protocol import RpcClient

    daemon = cluster.nodes[0]
    # Warm the pool so grants come from idle workers, not forks, and wait
    # out any leases earlier tests' driver still caches (keepalive ~2 s) so
    # the full CPU capacity is grantable.
    cli = RpcClient(daemon.rpc.host, daemon.rpc.port)
    cli.call("prestart_workers", n=3, timeout=30)
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        idle = [w for w in daemon.workers.values()
                if w.lease_id is None and w.actor_id is None
                and w.addr is not None]
        if len(idle) >= 3 and daemon.available.get("CPU", 0.0) >= 3:
            break
        time.sleep(0.1)
    else:
        pytest.skip("worker pool did not warm in time")
    avail_before = dict(daemon.available)
    res = cli.call("lease_workers", resources={"CPU": 1.0}, count=3,
                   env_hash="", owner="test", timeout=30)
    grants = res.get("grants") or []
    try:
        assert len(grants) == 3, res
        assert len({g["lease_id"] for g in grants}) == 3
        assert daemon.available["CPU"] == avail_before["CPU"] - 3
    finally:
        for g in grants:
            cli.call("return_lease", lease_id=g["lease_id"], timeout=10)
    assert daemon.available["CPU"] == avail_before["CPU"]
    cli.close()


def test_lease_batch_partial_grant(cluster):
    """A batch bigger than the idle pool returns the grants in hand rather
    than blocking for forks (the submitter re-requests the remainder)."""
    from ray_tpu.core.cluster.protocol import RpcClient

    daemon = cluster.nodes[0]
    cli = RpcClient(daemon.rpc.host, daemon.rpc.port)
    res = cli.call("lease_workers", resources={"CPU": 0.25}, count=16,
                   env_hash="", owner="test", timeout=30)
    grants = res.get("grants") or []
    assert 1 <= len(grants) <= 16
    for g in grants:
        cli.call("return_lease", lease_id=g["lease_id"], timeout=10)
    cli.close()


# ---------------------------------------------------------------- actors
def test_out_of_order_actor_replies(cluster):
    """A slow async method must not block the reply of a later fast one:
    replies correlate per-call, not per connection order."""
    @remote
    class OOO:
        async def slow(self):
            import asyncio

            await asyncio.sleep(1.0)
            return "slow"

        async def fast(self):
            return "fast"

    a = OOO.remote()
    ray_tpu.get(a.fast.remote(), timeout=120)  # actor started
    slow_ref = a.slow.remote()
    t0 = time.monotonic()
    fast_ref = a.fast.remote()
    assert ray_tpu.get(fast_ref, timeout=30) == "fast"
    assert time.monotonic() - t0 < 0.8  # did not wait behind slow
    assert ray_tpu.get(slow_ref, timeout=30) == "slow"
    ray_tpu.kill(a)


def test_concurrent_submitters_resolve_right_futures(cluster):
    """Interleaved submissions from several threads each get their own
    results back (correlation ids route every reply to its future)."""
    @remote
    class Echo:
        def echo(self, v):
            return v

    a = Echo.remote()
    ray_tpu.get(a.echo.remote(0), timeout=120)
    errors = []

    def client(tid):
        try:
            vals = [(tid, i) for i in range(25)]
            refs = [a.echo.remote(v) for v in vals]
            got = ray_tpu.get(refs, timeout=60)
            if got != vals:
                errors.append((tid, got[:3]))
        except Exception as e:  # noqa: BLE001
            errors.append((tid, repr(e)))

    threads = [threading.Thread(target=client, args=(t,)) for t in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    ray_tpu.kill(a)


def test_sync_actor_call_roundtrip(cluster):
    """The 1:1 sync path still returns correct results call after call."""
    @remote
    class Counter:
        def __init__(self):
            self.n = 0

        def tick(self):
            self.n += 1
            return self.n

    a = Counter.remote()
    for i in range(1, 21):
        assert ray_tpu.get(a.tick.remote(), timeout=120) == i
    ray_tpu.kill(a)


# ---------------------------------------------------------------- placement groups
def test_pg_batch_create_remove(cluster):
    """Multi-bundle PG: one prepare/commit RPC per node places every
    bundle; removal returns them all and releases base resources."""
    from ray_tpu.util.placement_group import (
        placement_group,
        remove_placement_group,
    )

    daemon = cluster.nodes[0]
    avail_before = daemon.available.get("CPU", 0.0)
    pg = placement_group([{"CPU": 1}, {"CPU": 1}, {"CPU": 1}],
                         strategy="PACK")
    assert pg.wait(timeout=60)
    committed = [k for k in daemon._committed_bundles if k[0] == pg.id.hex()]
    assert len(committed) == 3
    assert daemon.available["CPU"] == avail_before - 3
    # Tasks scheduled into a bundle land on the bundle's derived resources.
    from ray_tpu.util.placement_group import PlacementGroupSchedulingStrategy

    @remote
    def inside():
        return "placed"

    ref = inside.options(scheduling_strategy=PlacementGroupSchedulingStrategy(
        placement_group=pg, placement_group_bundle_index=1)).remote()
    assert ray_tpu.get(ref, timeout=120) == "placed"
    remove_placement_group(pg)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if not [k for k in daemon._committed_bundles
                if k[0] == pg.id.hex()] and \
                daemon.available.get("CPU", 0.0) >= avail_before:
            break
        time.sleep(0.05)
    assert not [k for k in daemon._committed_bundles if k[0] == pg.id.hex()]
    assert daemon.available["CPU"] == avail_before


def test_wal_group_commit_burst_survives_crash(tmp_path):
    """A burst of mutations group-committed in one tick is fully durable
    across a hard head crash (kill -9 semantics)."""
    os.environ["RTPU_HEALTH_CHECK_PERIOD_S"] = "0.2"
    from ray_tpu.utils import config as config_mod

    config_mod.set_config(config_mod.Config.load())
    c = Cluster(persist_path=str(tmp_path / "snap.pkl"))
    c.add_node(num_cpus=2)
    rt = c.connect()
    old = (global_worker.runtime, global_worker.worker_id,
           global_worker.node_id, global_worker.mode)
    global_worker.runtime = rt
    global_worker.worker_id = rt.worker_id
    global_worker.node_id = rt.node_id
    global_worker.job_id = JobID.from_random()
    global_worker.mode = "cluster"
    try:
        for i in range(25):
            rt.kv_put(f"burst-{i}", f"v{i}".encode())
        c.crash_head()
        time.sleep(0.5)
        for i in range(25):
            assert rt.kv_get(f"burst-{i}") == f"v{i}".encode()
    finally:
        rt.shutdown()
        c.shutdown()
        (global_worker.runtime, global_worker.worker_id,
         global_worker.node_id, global_worker.mode) = old
        config_mod.set_config(config_mod.Config.load())
