"""Trial schedulers: FIFO, ASHA, median stopping, PBT.

Capability parity with the reference's scheduler layer (reference:
python/ray/tune/schedulers/ — trial_scheduler.py FIFOScheduler ABC,
async_hyperband.py AsyncHyperBandScheduler, median_stopping_rule.py,
pbt.py PopulationBasedTraining). Decisions are made per reported result:
CONTINUE, STOP, or PAUSE.
"""

from __future__ import annotations

import math
import random
from typing import TYPE_CHECKING, Callable

if TYPE_CHECKING:
    from ray_tpu.tune.trial import Trial


class TrialScheduler:
    CONTINUE = "CONTINUE"
    STOP = "STOP"
    PAUSE = "PAUSE"

    def set_search_properties(self, metric: str | None, mode: str | None) -> None:
        self.metric, self.mode = metric, mode

    def _score(self, result: dict) -> float:
        v = result[self.metric]
        return v if self.mode == "max" else -v

    def on_trial_result(self, trial: "Trial", result: dict) -> str:
        return self.CONTINUE

    def on_trial_complete(self, trial: "Trial", result: dict | None) -> None:
        pass


class FIFOScheduler(TrialScheduler):
    """Run every trial to completion (the default)."""


class AsyncHyperBandScheduler(TrialScheduler):
    """ASHA: asynchronous successive halving (reference:
    schedulers/async_hyperband.py). Rungs at grace_period ·
    reduction_factor^k; a trial reaching a rung is stopped unless its score
    is in the top 1/reduction_factor of results recorded at that rung."""

    def __init__(self, time_attr: str = "training_iteration",
                 grace_period: int = 1, reduction_factor: float = 4,
                 max_t: int = 100):
        self._time_attr = time_attr
        self._rf = reduction_factor
        self._max_t = max_t
        self._cut_at: dict[float, set[str]] = {}
        self._rungs: list[tuple[float, list[float]]] = []
        t = grace_period
        while t < max_t:
            self._rungs.append((t, []))
            t = int(math.ceil(t * reduction_factor))
        self._rungs.reverse()  # largest rung first, reference layout

    def on_trial_result(self, trial: "Trial", result: dict) -> str:
        t = result.get(self._time_attr, 0)
        if self.metric not in result:
            return self.CONTINUE
        if t >= self._max_t:
            return self.STOP
        score = self._score(result)
        decision = self.CONTINUE
        for milestone, recorded in self._rungs:
            if t < milestone:
                continue
            if trial.trial_id in self._cut_at.get(milestone, set()):
                continue
            self._cut_at.setdefault(milestone, set()).add(trial.trial_id)
            recorded.append(score)
            if len(recorded) >= self._rf:
                cutoff = sorted(recorded, reverse=True)[
                    max(0, int(len(recorded) / self._rf) - 1)]
                if score < cutoff:
                    decision = self.STOP
            break
        return decision


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose best score so far is below the median of other
    trials' running averages at the same step (reference:
    schedulers/median_stopping_rule.py)."""

    def __init__(self, time_attr: str = "training_iteration",
                 grace_period: int = 1, min_samples_required: int = 3):
        self._time_attr = time_attr
        self._grace = grace_period
        self._min_samples = min_samples_required
        self._scores: dict[str, list[float]] = {}

    def on_trial_result(self, trial: "Trial", result: dict) -> str:
        if self.metric not in result:
            return self.CONTINUE
        t = result.get(self._time_attr, 0)
        s = self._score(result)
        self._scores.setdefault(trial.trial_id, []).append(s)
        if t < self._grace or len(self._scores) < self._min_samples:
            return self.CONTINUE
        others = [sum(v) / len(v) for k, v in self._scores.items()
                  if k != trial.trial_id]
        if not others:
            return self.CONTINUE
        others.sort()
        median = others[len(others) // 2]
        best = max(self._scores[trial.trial_id])
        return self.STOP if best < median else self.CONTINUE


class PopulationBasedTraining(TrialScheduler):
    """PBT (reference: schedulers/pbt.py): every perturbation_interval,
    bottom-quantile trials exploit (clone weights+config from a top-quantile
    trial) and explore (perturb hyperparams by 1.2×/0.8× or resample)."""

    def __init__(self, time_attr: str = "training_iteration",
                 perturbation_interval: int = 5,
                 hyperparam_mutations: dict[str, Callable | list] | None = None,
                 quantile_fraction: float = 0.25,
                 resample_probability: float = 0.25,
                 seed: int | None = None):
        self._time_attr = time_attr
        self._interval = perturbation_interval
        self._mutations = hyperparam_mutations or {}
        self._quantile = quantile_fraction
        self._resample_p = resample_probability
        self._rng = random.Random(seed)
        self._last_perturb: dict[str, float] = {}
        self._latest: dict[str, tuple[float, "Trial"]] = {}

    def on_trial_result(self, trial: "Trial", result: dict) -> str:
        if self.metric not in result:
            return self.CONTINUE
        t = result.get(self._time_attr, 0)
        self._latest[trial.trial_id] = (self._score(result), trial)
        if t - self._last_perturb.get(trial.trial_id, 0) < self._interval:
            return self.CONTINUE
        self._last_perturb[trial.trial_id] = t

        ranked = sorted(self._latest.values(), key=lambda sv: sv[0])
        n = len(ranked)
        if n < 2:
            return self.CONTINUE
        k = max(1, int(n * self._quantile))
        bottom = [tr for _, tr in ranked[:k]]
        top = [tr for _, tr in ranked[-k:]]
        if trial in bottom and trial not in top:
            donor = self._rng.choice(top)
            new_config = self._explore(donor.config)
            # The controller performs the actual clone+restart.
            trial.pbt_request = {"donor": donor, "config": new_config}
        return self.CONTINUE

    def on_trial_complete(self, trial: "Trial", result: dict | None) -> None:
        self._latest.pop(trial.trial_id, None)
        self._last_perturb.pop(trial.trial_id, None)

    def _explore(self, config: dict) -> dict:
        new = dict(config)
        for key, spec in self._mutations.items():
            if self._rng.random() < self._resample_p or key not in new:
                new[key] = (self._rng.choice(spec) if isinstance(spec, list)
                            else spec())
            else:
                factor = 1.2 if self._rng.random() > 0.5 else 0.8
                if isinstance(spec, list):
                    new[key] = self._rng.choice(spec)
                else:
                    new[key] = new[key] * factor
        return new
