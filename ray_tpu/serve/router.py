"""Router: assigns requests to replicas (power-of-two-choices).

Capability parity with the reference's router (reference:
python/ray/serve/_private/router.py:510 Router.assign_request :1028 →
request_router/pow_2_router.py:27 PowerOfTwoChoicesRequestRouter
.choose_replicas :52 — sample two replicas, pick the one with the smaller
queue; requests queue router-side when all replicas are saturated).
"""

from __future__ import annotations

import random
import threading
from typing import Callable

import ray_tpu
from ray_tpu.serve.config import ReplicaInfo
from ray_tpu.util import tracing

_router_metrics = None
_router_metrics_lock = threading.Lock()


def _get_router_metrics():
    """Process-wide router metrics: admission wait, parked-caller depth,
    and request count per deployment (reference: serve's
    ray_serve_num_router_requests / queued gauges). Lock-guarded creation:
    two racing first-requests must not register two metric objects and
    strand increments on the one the exporter can't see."""
    global _router_metrics
    with _router_metrics_lock:
        if _router_metrics is not None:
            return _router_metrics
        from ray_tpu.util.metrics import Counter, Gauge, Histogram

        _router_metrics = {
            "queue_wait": Histogram(
                "serve_router_queue_wait_s",
                "time a request waited in the router for a replica slot",
                tag_keys=("deployment",)),
            "queue_depth": Gauge(
                "serve_router_queue_depth",
                "callers currently parked waiting for replica capacity",
                tag_keys=("deployment",)),
            "requests": Counter(
                "serve_router_requests_total",
                "requests assigned to replicas", tag_keys=("deployment",)),
        }
    return _router_metrics


class Router:
    def __init__(self, deployment_name: str,
                 get_replicas: Callable[[], list[ReplicaInfo]]):
        self._deployment = deployment_name
        self._get_replicas = get_replicas
        self._inflight: dict[str, int] = {}  # replica_id -> local in-flight
        self._lock = threading.Lock()
        self._not_saturated = threading.Condition(self._lock)
        self._rng = random.Random()
        self._waiting = 0  # callers parked for capacity (queue-depth gauge)

    def assign_request(self, method_name: str, args: tuple, kwargs: dict,
                       timeout: float = 30.0, stream: bool = False,
                       route_hint: str | None = None):
        """Pick a replica (pow-2 on local in-flight counts), submit, and
        return the result ObjectRef. Blocks while every replica is at
        max_ongoing_requests (router-side queuing, reference behavior).

        ``route_hint`` biases placement for cache locality: the same hint
        routes to the same replica while that replica's load stays within a
        bounded delta of the least-loaded one (reference: multiplexed-model
        routing, request_router/multiplex + the prefix-aware policy in llm
        routing_policies/prefix_aware — affinity-by-key with a balance
        threshold, so a shared system prompt can't pin a whole deployment
        to one replica).

        Admission is event-driven: when every replica is saturated the
        caller parks on a Condition that is notified on request completion
        and on replica-set changes — no sleep-poll (reference:
        serve/_private/router.py:510 wakes assign loops on config/ongoing-
        request events)."""
        import time as _time

        mtr = _get_router_metrics()
        dep_tag = {"deployment": self._deployment}
        t_enter = _time.monotonic()
        deadline = t_enter + timeout
        with self._lock:
            parked = False
            try:
                while True:
                    replicas = self._get_replicas()
                    chosen = (self._choose_locked(replicas, route_hint)
                              if replicas else None)
                    if chosen is not None:
                        self._inflight[chosen.replica_id] = \
                            self._inflight.get(chosen.replica_id, 0) + 1
                        break
                    remaining = deadline - _time.monotonic()
                    if remaining <= 0:
                        raise TimeoutError(
                            f"no available replica for {self._deployment!r} "
                            f"within {timeout}s")
                    if not parked:
                        parked = True
                        self._waiting += 1
                        mtr["queue_depth"].set(self._waiting, tags=dep_tag)
                    # Bounded wait: replica-set changes arrive via
                    # notify_replicas_changed(), completions via _release();
                    # the 0.5 s cap only covers lost-notify edge cases.
                    self._not_saturated.wait(timeout=min(remaining, 0.5))
            finally:
                if parked:
                    self._waiting -= 1
                    mtr["queue_depth"].set(self._waiting, tags=dep_tag)
        mtr["queue_wait"].observe(_time.monotonic() - t_enter, tags=dep_tag)
        mtr["requests"].inc(tags=dep_tag)

        try:
            handle = ray_tpu.get_actor(chosen.actor_name, namespace="serve")
        except Exception:
            # Replica vanished between the long-poll snapshot and submission:
            # give the slot back (a leaked increment would read as permanent
            # saturation) and surface the error to the caller.
            self._release(chosen.replica_id)
            raise
        if stream:
            try:
                # Client span around submission: inject() rides the
                # TaskSpec, so the replica's execution shows up as a child
                # of serve.request — one trace across processes.
                with tracing.span(f"serve.request.{self._deployment}",
                                  kind="client",
                                  attributes={"method": method_name,
                                              "replica": chosen.replica_id,
                                              "stream": "true"}):
                    gen = handle.handle_request_streaming.options(
                        num_returns="streaming").remote(
                            method_name, args, kwargs)
            except Exception:
                self._release(chosen.replica_id)
                raise

            done = threading.Event()

            def on_stream_done():
                # In-flight until the consumer exhausts/abandons the stream
                # (keeps max_ongoing_requests honest for long-lived SSE).
                if not done.is_set():
                    done.set()
                    self._release(chosen.replica_id)

            return gen, on_stream_done
        try:
            with tracing.span(f"serve.request.{self._deployment}",
                              kind="client",
                              attributes={"method": method_name,
                                          "replica": chosen.replica_id}):
                ref = handle.handle_request.remote(method_name, args, kwargs)
        except Exception:
            self._release(chosen.replica_id)
            raise

        def _done():
            try:
                ray_tpu.wait([ref], num_returns=1, timeout=None,
                             fetch_local=False)
            finally:
                self._release(chosen.replica_id)
        threading.Thread(target=_done, daemon=True).start()
        return ref

    def _release(self, replica_id: str) -> None:
        with self._lock:
            self._inflight[replica_id] -= 1
            self._not_saturated.notify_all()

    def notify_replicas_changed(self) -> None:
        """Wake parked assign loops after a replica-set update (called from
        the long-poll callback in DeploymentHandle)."""
        with self._lock:
            self._not_saturated.notify_all()

    # How far above the least-loaded replica a hint-preferred replica may
    # be before load balancing overrides cache locality.
    HINT_BALANCE_DELTA = 2

    def _choose_locked(self, replicas: list[ReplicaInfo],
                       route_hint: str | None = None) -> ReplicaInfo | None:
        if route_hint is not None:
            # Rendezvous hashing: every router maps the same hint to the
            # same replica without coordination — but only while the hinted
            # replica's load stays within HINT_BALANCE_DELTA of the
            # least-loaded replica. Beyond that, locality yields to pow-2
            # balancing (a deployment-wide shared prefix must not pin all
            # traffic to one replica while siblings idle).
            import zlib

            min_load = min(self._inflight.get(r.replica_id, 0)
                           for r in replicas)
            ranked = sorted(
                replicas,
                key=lambda r: zlib.crc32(
                    f"{route_hint}:{r.replica_id}".encode()),
            )
            for r in ranked:
                load = self._inflight.get(r.replica_id, 0)
                if load >= r.max_ongoing_requests:
                    continue
                if load - min_load <= self.HINT_BALANCE_DELTA:
                    return r
                break  # hinted replica overloaded — balance instead
        candidates = (self._rng.sample(replicas, 2)
                      if len(replicas) >= 2 else list(replicas))
        best, best_load = None, None
        for r in candidates:
            load = self._inflight.get(r.replica_id, 0)
            if load >= r.max_ongoing_requests:
                continue
            if best_load is None or load < best_load:
                best, best_load = r, load
        return best

    def metrics(self) -> dict[str, int]:
        with self._lock:
            return dict(self._inflight)
