"""Simulated fleet: hundreds of real node daemons in one process.

Scale harness for the control plane (reference: Ray's `fake_multi_node`
test utilities, python/ray/autoscaler/_private/fake_multi_node/ — many
raylets faked on one box to exercise GCS-side behavior without a real
cluster). Every daemon here is a REAL :class:`NodeDaemon` speaking the
real RPC protocol to a real head — registration, delta heartbeats,
leases, 2PC bundles, fencing are all the production code paths — but:

- the device inventory is fabricated from a geometry string ("v5e-8" →
  8 TPU chips + CPUs, labeled so placement/affinity tests can target it);
- ``sim=True`` strips the per-node cost that makes a thousand daemons
  impossible in one process: no shm arena (1000 arenas would exhaust
  /dev/shm), no forked workers (leases grant synthetic in-process
  records), no per-daemon timer tasks;
- one :class:`TimerWheel` drives every daemon's ``_heartbeat_once`` on a
  shared schedule with phases spread across the period, so 1000 nodes
  cost one timer task instead of 6000.

What the harness measures is therefore the HEAD: where its heartbeat
ingest, scheduling scans, and pubsub fan-out saturate as node count
grows (devbench/scale_bench.py sweeps this and records the knees).
"""

from __future__ import annotations

import asyncio
import heapq
import logging
import uuid

from ray_tpu.core.cluster.node_daemon import NodeDaemon
from ray_tpu.core.cluster.protocol import EventLoopThread
from ray_tpu.devtools.annotations import loop_confined
from ray_tpu.utils.config import get_config

logger = logging.getLogger(__name__)

# Chips per host for known accelerator generations (geometry "<gen>-<N>"
# may name any chip count; this only seeds the CPU guess below).
_CPUS_PER_CHIP = 14.0  # v5e host: 112 vCPU / 8 chips


def parse_geometry(geometry: str) -> tuple[dict[str, float], dict[str, str]]:
    """``"v5e-8"`` → per-node resource totals + placement labels.

    The resource map is what a real daemon on such a host would register:
    TPU chips plus a proportional CPU count (fractional-CPU tasks and PG
    bundles need headroom to pack against). Labels carry the accelerator
    generation and topology so label-affinity scheduling is exercisable
    against the sim fleet, plus ``sim: "1"`` so operators can tell fake
    capacity from real in ``list_nodes``/status output.
    """
    gen, _, chips_s = geometry.rpartition("-")
    try:
        chips = float(chips_s)
    except ValueError:
        gen, chips = geometry, 0.0
    if not gen:
        gen, chips = geometry, 0.0
    resources = {"CPU": max(1.0, chips * _CPUS_PER_CHIP)}
    if chips > 0:
        resources["TPU"] = chips
    labels = {"accelerator": gen, "topology": geometry, "sim": "1"}
    return resources, labels


@loop_confined
class TimerWheel:
    """One timer task multiplexing periodic callbacks for N daemons.

    Each daemon gets a stable phase offset so beats spread uniformly
    across the period instead of arriving as an N-wide thundering herd
    every period (which would measure burst absorption, not steady-state
    ingest). Rescheduling is anchored at ``due + period``, not
    ``now + period``, so phases don't drift when a beat runs late.
    Concurrent beats are bounded by a semaphore: a slow head makes beats
    queue here (visibly, as wheel lag) rather than stacking unbounded
    tasks in the loop.
    """

    def __init__(self, period_s: float, concurrency: int = 64):
        self.period_s = period_s
        self._sem = asyncio.Semaphore(concurrency)
        self._heap: list[tuple[float, int, NodeDaemon]] = []
        self._seq = 0
        self._dead: set[int] = set()  # seq of entries to drop at pop
        self._seq_of: dict[str, int] = {}  # node_id -> live seq
        self._task: asyncio.Task | None = None
        self._stopped = False
        self.fired = 0
        self.max_lag_s = 0.0  # worst (now - due) observed at dispatch

    def add(self, daemon: NodeDaemon, phase_s: float) -> None:
        loop = asyncio.get_running_loop()
        self._seq += 1
        self._seq_of[daemon.node_id] = self._seq
        heapq.heappush(self._heap, (loop.time() + phase_s, self._seq, daemon))

    def remove(self, node_id: str) -> None:
        seq = self._seq_of.pop(node_id, None)
        if seq is not None:
            self._dead.add(seq)

    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._run())

    async def stop(self) -> None:
        self._stopped = True
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
            self._task = None

    async def _run(self) -> None:
        loop = asyncio.get_running_loop()
        while not self._stopped:
            if not self._heap:
                await asyncio.sleep(self.period_s / 4 or 0.05)
                continue
            due, seq, daemon = self._heap[0]
            now = loop.time()
            if due > now:
                await asyncio.sleep(min(due - now, self.period_s))
                continue
            heapq.heappop(self._heap)
            if seq in self._dead:
                self._dead.discard(seq)
                continue
            self.max_lag_s = max(self.max_lag_s, now - due)
            loop.create_task(self._fire(daemon, seq))
            heapq.heappush(self._heap, (due + self.period_s, seq, daemon))

    async def _fire(self, daemon: NodeDaemon, seq: int) -> None:
        async with self._sem:
            if self._stopped or seq in self._dead:
                return
            self.fired += 1
            try:
                alive = await daemon._heartbeat_once()
            except Exception:  # noqa: BLE001 - a bug must not kill the wheel
                logger.exception("sim heartbeat failed for %s",
                                 daemon.node_id[:12])
                return
            if not alive:
                # Fenced or chaos-killed: the daemon stood down — stop
                # beating for it (exactly what a dead real daemon does).
                self.remove(daemon.node_id)


@loop_confined
class SimFleet:
    """N sim daemons registered against one head, driven by one wheel.

    Async API for use on an existing loop (the bench), plus sync
    wrappers (``launch``/``shutdown``) over the process io-loop thread
    for scripts and tests — the wrappers only construct and delegate
    via ``EventLoopThread.run``, so all state mutation stays on the
    io loop (hence ``@loop_confined``).
    """

    def __init__(self, head_host: str, head_port: int,
                 n_nodes: int | None = None, geometry: str | None = None,
                 heartbeat_period_s: float | None = None,
                 register_concurrency: int = 32,
                 node_prefix: str = "sim",
                 extra_resources: dict[str, float] | None = None):
        cfg = get_config()
        self.head_addr = (head_host, head_port)
        self.n_nodes = int(n_nodes if n_nodes is not None
                           else cfg.sim_fleet_nodes)
        self.geometry = geometry or cfg.sim_fleet_geometry
        self.resources, self.labels = parse_geometry(self.geometry)
        # Production inventories carry more than CPU/TPU (memory,
        # object_store_memory, PG-bundle-derived keys); benches pass
        # extras so full-vs-delta heartbeat costs are measured against a
        # realistic map width, not a 2-key toy.
        self.resources.update(extra_resources or {})
        period = (heartbeat_period_s if heartbeat_period_s is not None
                  else cfg.health_check_period_s / 2)
        self.wheel = TimerWheel(max(period, 0.01))
        self._register_concurrency = max(1, register_concurrency)
        self._prefix = node_prefix
        self.daemons: list[NodeDaemon] = []
        self.register_failures = 0
        self.register_wall_s = 0.0

    # ------------------------------------------------------------ async
    async def start(self) -> "SimFleet":
        """Registration storm: boot all daemons with bounded concurrency
        (each boot is a real TCP connect + register_node round trip; the
        bound keeps the storm from exhausting ephemeral sockets faster
        than the head can accept) then arm the heartbeat wheel with
        phases spread across the period."""
        loop = asyncio.get_running_loop()
        sem = asyncio.Semaphore(self._register_concurrency)
        run_id = uuid.uuid4().hex[:6]

        async def boot(i: int) -> NodeDaemon | None:
            node_id = f"{self._prefix}-{run_id}-{i:04d}"
            d = NodeDaemon(self.head_addr[0], self.head_addr[1], node_id,
                           dict(self.resources), dict(self.labels), sim=True)
            async with sem:
                try:
                    await d.start()
                except Exception:  # noqa: BLE001 - counted, bench gates on it
                    self.register_failures += 1
                    try:
                        await d.rpc.stop()
                    except Exception:
                        pass
                    return None
            return d

        t0 = loop.time()
        results = await asyncio.gather(*[boot(i) for i in range(self.n_nodes)])
        self.register_wall_s = loop.time() - t0
        self.daemons = [d for d in results if d is not None]
        for i, d in enumerate(self.daemons):
            phase = (i / max(1, len(self.daemons))) * self.wheel.period_s
            self.wheel.add(d, phase)
        self.wheel.start()
        return self

    async def stop(self) -> None:
        await self.wheel.stop()
        for d in self.daemons:
            try:
                await d.stop()
            except Exception:  # noqa: BLE001 - teardown best-effort
                pass
            # NodeDaemon.stop leaves the head client open (real daemons
            # die with their process); 1000 leaked sockets matter here.
            if d._head is not None:
                try:
                    await d._head.close()
                except Exception:
                    pass
        self.daemons = []

    async def kill(self, count: int, stride: int = 7) -> list[str]:
        """Chaos helper: hard-kill ``count`` daemons (same death as the
        injector's ``daemon.tick`` kill — sockets drop, no dereg). The
        stride spreads the kills across the fleet instead of taking a
        contiguous block. Returns killed node ids."""
        killed: list[str] = []
        alive = [d for d in self.daemons if not d._fenced]
        for j in range(min(count, len(alive))):
            d = alive[(j * stride) % len(alive)]
            if d.node_id in killed:
                continue
            self.wheel.remove(d.node_id)
            try:
                await d._chaos_die()
            except Exception:  # noqa: BLE001
                pass
            killed.append(d.node_id)
        return killed

    def hb_stats(self) -> dict:
        """Fleet-aggregate heartbeat wire stats (feeds the bench's
        heartbeat-loss gate and the delta-vs-full byte accounting)."""
        agg = {"sent": 0, "full": 0, "delta": 0, "empty": 0,
               "skipped": 0, "failed": 0, "resync": 0}
        for d in self.daemons:
            for k in agg:
                agg[k] += d._hb_stats.get(k, 0)
        agg["nodes"] = len(self.daemons)
        agg["loss_rate"] = (agg["failed"] / agg["sent"]) if agg["sent"] else 0.0
        agg["wheel_fired"] = self.wheel.fired
        agg["wheel_max_lag_s"] = round(self.wheel.max_lag_s, 6)
        return agg

    # ------------------------------------------------------------- sync
    @classmethod
    def launch(cls, head_host: str, head_port: int, **kw) -> "SimFleet":
        """Sync wrapper: build + start on the process io-loop thread."""
        fleet = cls(head_host, head_port, **kw)
        EventLoopThread.get().run(fleet.start(), timeout=300)
        return fleet

    def shutdown(self) -> None:
        EventLoopThread.get().run(self.stop(), timeout=120)
