"""ViT: vision transformer classifier family, TPU-first.

New work relative to the reference framework (Ray delegates model code to
torch; a TPU-native framework ships its model families — SURVEY.md §2.3
"model family" axis). Same idiom as models/llama.py: stacked-layer params
scanned with lax.scan, logical-axis table consumed by
parallel/sharding.py, flash attention (non-causal) from ops/attention.py
on the MXU, jax.checkpoint remat modes.

Patchify is a reshape (not a conv): [B, H, W, C] -> [B, (H/p)(W/p), p*p*C]
then one matmul — exactly what XLA lowers a stride-p conv to, minus the
conv. Pairs with data.read_images(size=...) for multimodal ingest.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp

from ray_tpu.ops.attention import flash_attention
from ray_tpu.ops.norms import rms_norm


@dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    num_channels: int = 3
    hidden_size: int = 768
    intermediate_size: int = 3072
    num_layers: int = 12
    num_heads: int = 12
    num_classes: int = 1000
    norm_eps: float = 1e-6
    dtype: str = "float32"

    @staticmethod
    def tiny() -> "ViTConfig":
        return ViTConfig(image_size=16, patch_size=4, hidden_size=32,
                         intermediate_size=64, num_layers=2, num_heads=2,
                         num_classes=10)

    @staticmethod
    def base16() -> "ViTConfig":
        return ViTConfig()  # ViT-B/16

    @property
    def jnp_dtype(self):
        return jnp.dtype(self.dtype)

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    def num_params(self) -> int:
        h, i, L = self.hidden_size, self.intermediate_size, self.num_layers
        patch_in = self.patch_size**2 * self.num_channels
        per_layer = 4 * h * h + 2 * h * i + 2 * h
        return (patch_in * h + (self.num_patches + 1) * h + h
                + L * per_layer + h + h * self.num_classes)


def param_logical_axes(cfg: ViTConfig) -> dict:
    """Logical-axis names per param leaf (see parallel/sharding.py rules):
    attention projections shard over heads (tp), MLP over mlp (tp),
    layers stack on the pp-able leading axis — the same table shape the
    generic make_train_step consumes for llama."""
    return {
        "patch_embed": ("patch_in", "embed"),
        "pos_embed": (None, "embed"),
        "cls_token": ("embed",),
        "final_norm": ("embed",),
        "head": ("embed", "classes"),
        "layers": {
            "wq": ("layers", "embed", "heads"),
            "wk": ("layers", "embed", "heads"),
            "wv": ("layers", "embed", "heads"),
            "wo": ("layers", "heads", "embed"),
            "w_up": ("layers", "embed", "mlp"),
            "w_down": ("layers", "mlp", "embed"),
            "attn_norm": ("layers", "embed"),
            "mlp_norm": ("layers", "embed"),
        },
    }


def init_params(cfg: ViTConfig, key: jax.Array) -> dict:
    h, L = cfg.hidden_size, cfg.num_layers
    i = cfg.intermediate_size
    patch_in = cfg.patch_size**2 * cfg.num_channels
    dt = cfg.jnp_dtype
    keys = jax.random.split(key, 9)

    def norm_init(k, *shape, scale=None):
        scale = scale if scale is not None else 1.0 / math.sqrt(shape[-2])
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(dt)

    return {
        "patch_embed": norm_init(keys[0], patch_in, h),
        "pos_embed": (jax.random.normal(
            keys[1], (cfg.num_patches + 1, h), jnp.float32) * 0.02
        ).astype(dt),
        "cls_token": jnp.zeros((h,), dt),
        "final_norm": jnp.ones((h,), dt),
        "head": norm_init(keys[2], h, cfg.num_classes,
                          scale=1.0 / math.sqrt(h)),
        "layers": {
            "wq": norm_init(keys[3], L, h, h),
            "wk": norm_init(keys[4], L, h, h),
            "wv": norm_init(keys[5], L, h, h),
            "wo": norm_init(keys[6], L, h, h,
                            scale=1.0 / math.sqrt(h * 2 * L)),
            "w_up": norm_init(keys[7], L, h, i),
            "w_down": norm_init(keys[8], L, i, h,
                                scale=1.0 / math.sqrt(i * 2 * L)),
            "attn_norm": jnp.ones((L, h), dt),
            "mlp_norm": jnp.ones((L, h), dt),
        },
    }


def patchify(cfg: ViTConfig, images: jax.Array) -> jax.Array:
    """[B, H, W, C] -> [B, N, p*p*C] patch rows (pure reshape/transpose)."""
    b, hh, ww, c = images.shape
    p = cfg.patch_size
    x = images.reshape(b, hh // p, p, ww // p, p, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(b, (hh // p) * (ww // p), p * p * c)


def _layer(cfg: ViTConfig, x, lp, attn_impl: str):
    b, s, h = x.shape
    nh, hd = cfg.num_heads, cfg.head_dim
    xn = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
    q = (xn @ lp["wq"]).reshape(b, s, nh, hd).transpose(0, 2, 1, 3)
    k = (xn @ lp["wk"]).reshape(b, s, nh, hd).transpose(0, 2, 1, 3)
    v = (xn @ lp["wv"]).reshape(b, s, nh, hd).transpose(0, 2, 1, 3)
    use_pallas = attn_impl == "flash"
    attn = flash_attention(q, k, v, False, None, use_pallas)  # bidirectional
    attn = attn.transpose(0, 2, 1, 3).reshape(b, s, nh * hd)
    x = x + attn @ lp["wo"]
    xn = rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
    return x + (jax.nn.gelu(xn @ lp["w_up"]) @ lp["w_down"])


def forward(cfg: ViTConfig, params: dict, images: jax.Array,
            attn_impl: str = "flash", remat: bool | str = False) -> jax.Array:
    """[B, H, W, C] images (float in [0, 1]) -> [B, num_classes] logits."""
    dt = cfg.jnp_dtype
    x = patchify(cfg, images.astype(dt)) @ params["patch_embed"]
    cls = jnp.broadcast_to(params["cls_token"], (x.shape[0], 1,
                                                 cfg.hidden_size))
    x = jnp.concatenate([cls, x], axis=1) + params["pos_embed"][None]

    # Same remat policy machinery as llama ('dots'/'dots+' save matmul
    # outputs + flash residuals; True/'full' recomputes everything).
    from ray_tpu.models.llama import _remat_wrap

    layer_fn = _remat_wrap(partial(_layer, cfg, attn_impl=attn_impl), remat)

    def scan_body(x, lp):
        return layer_fn(x, lp), None

    x, _ = jax.lax.scan(scan_body, x, params["layers"])
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    return (x[:, 0, :] @ params["head"]).astype(jnp.float32)  # cls token


def loss_fn(cfg: ViTConfig, params: dict, images: jax.Array,
            labels: jax.Array, attn_impl: str = "flash",
            remat: bool | str = False) -> jax.Array:
    logits = forward(cfg, params, images, attn_impl=attn_impl, remat=remat)
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.take_along_axis(logp, labels[:, None], axis=-1).mean()


def make_vit_train_step(*args, **kwargs):
    """Moved to train/spmd.py beside the llama/mixtral factories."""
    from ray_tpu.train.spmd import make_vit_train_step as factory

    return factory(*args, **kwargs)
