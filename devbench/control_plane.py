"""Control-plane stage-latency breakdown + wire-byte accounting.

Drives the submit → lease → push → reply path and emits PERF_CONTROL.json:
- stage percentiles (p50/p90/p99) for sync task RTT, sync actor-call RTT,
  lease grants (driver-side ``lease_grant`` spans), and worker-side task
  execution (spans federated at the head — PR 1 telemetry),
- per-task wire bytes from the ``ctrl_push_*`` counters, demonstrating the
  function-registry contract: a repeat-submitted function's definition
  crosses the wire once per WORKER (``ctrl_fn_count{op=fetch}``), not once
  per task — per-task bytes stay O(spec header).

Run: python devbench/control_plane.py [--tasks N]
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("RTPU_WORKER_IDLE_TTL_S", "300")

import ray_tpu  # noqa: E402
from ray_tpu import remote  # noqa: E402
from ray_tpu.cluster_utils import Cluster  # noqa: E402
from ray_tpu.core.worker import global_worker  # noqa: E402
from ray_tpu.util import tracing  # noqa: E402
from ray_tpu.utils.ids import JobID  # noqa: E402


def pct(samples: list[float]) -> dict:
    if not samples:
        return {}
    s = sorted(samples)

    def at(q):
        return s[min(len(s) - 1, int(q * len(s)))]

    return {"n": len(s), "p50_ms": round(at(0.50) * 1e3, 3),
            "p90_ms": round(at(0.90) * 1e3, 3),
            "p99_ms": round(at(0.99) * 1e3, 3)}


def counter_points(snapshot: dict, name: str) -> dict[tuple, float]:
    for entry in snapshot["metrics"]:
        if entry["name"] == name and "points" in entry:
            return {tuple(k): v for k, v in entry["points"]}
    return {}


# A deliberately heavy definition (~128 KB closure): before the registry,
# every TaskSpec shipped these bytes; now they move once per worker.
_BALLAST = bytes(128 * 1024)


@remote
def probe(x):
    return x if _BALLAST else None


@remote
class Pinger:
    def ping(self):
        return 0


def main():
    n_tasks = 400
    if "--tasks" in sys.argv:
        n_tasks = int(sys.argv[sys.argv.index("--tasks") + 1])
    from ray_tpu.utils import config as config_mod

    config_mod.set_config(config_mod.Config.load())
    tracing.enable_tracing()
    c = Cluster()
    c.add_node(num_cpus=4)
    rt = c.connect()
    global_worker.runtime = rt
    global_worker.worker_id = rt.worker_id
    global_worker.node_id = rt.node_id
    global_worker.job_id = JobID.from_random()
    global_worker.mode = "cluster"
    try:
        rt._daemon.call("prestart_workers", n=4, timeout=10)
    except Exception:
        pass
    # Warm: definitions exported, workers forked+registered, leases cached.
    ray_tpu.get([probe.remote(i) for i in range(100)], timeout=120)

    from ray_tpu.util.metrics import registry

    base = registry().snapshot()
    base_push = counter_points(base, "ctrl_push_bytes").get(("task",), 0.0)
    base_cnt = counter_points(base, "ctrl_push_count").get(("task",), 0.0)

    # --- stage: async fan-out (lease grants appear as spans) ---
    t0 = time.perf_counter()
    ray_tpu.get([probe.remote(i) for i in range(n_tasks)], timeout=300)
    async_wall = time.perf_counter() - t0

    # --- stage: sync task RTT ---
    sync_rtt = []
    for i in range(min(n_tasks, 200)):
        t0 = time.perf_counter()
        ray_tpu.get(probe.remote(i))
        sync_rtt.append(time.perf_counter() - t0)

    # --- stage: sync actor-call RTT ---
    a = Pinger.remote()
    ray_tpu.get(a.ping.remote(), timeout=120)
    actor_rtt = []
    for _ in range(min(n_tasks, 200)):
        t0 = time.perf_counter()
        ray_tpu.get(a.ping.remote())
        actor_rtt.append(time.perf_counter() - t0)

    snap = registry().snapshot()
    push_bytes = counter_points(snap, "ctrl_push_bytes").get(("task",), 0.0) \
        - base_push
    push_cnt = counter_points(snap, "ctrl_push_count").get(("task",), 0.0) \
        - base_cnt

    # Driver-side spans: lease grants. Head-federated spans: worker-side
    # task execution (the PR 1 telemetry path).
    grant = [s.end_ts - s.start_ts for s in tracing.spans()
             if s.name == "lease_grant"]
    time.sleep(1.2)  # one telemetry flush period: workers ship their spans
    head_spans = rt.cluster_spans()
    exec_spans = [s["end_ts"] - s["start_ts"] for s in head_spans
                  if s.get("name") == "probe" and s.get("kind") == "worker"]

    # Registry accounting, cluster-wide (driver exports + worker fetches).
    tel = rt.get_telemetry()["sources"]
    fn_ops: dict[str, float] = {}
    fn_bytes: dict[str, float] = {}
    me = f":{os.getpid()}"
    for src, row in tel.items():
        if src.endswith(me):
            continue  # this process reports below from its live registry
        for key, val in counter_points(row["snapshot"], "ctrl_fn_count").items():
            fn_ops[key[0]] = fn_ops.get(key[0], 0.0) + val
        for key, val in counter_points(row["snapshot"], "ctrl_fn_bytes").items():
            fn_bytes[key[0]] = fn_bytes.get(key[0], 0.0) + val
    for key, val in counter_points(snap, "ctrl_fn_count").items():
        fn_ops[key[0]] = fn_ops.get(key[0], 0.0) + val
    for key, val in counter_points(snap, "ctrl_fn_bytes").items():
        fn_bytes[key[0]] = fn_bytes.get(key[0], 0.0) + val

    fn_blob_bytes = len(probe._fn_blob or b"")
    out = {
        "note": ("per-task wire bytes for a repeat-submitted function: the "
                 "spec names the definition by content id; the pickled "
                 "definition moves once per worker (op=fetch), not per task"),
        "hardware": {"nproc": os.cpu_count()},
        "tasks_measured": int(push_cnt),
        "fn_definition_bytes": fn_blob_bytes,
        "per_task_push_bytes": round(push_bytes / max(push_cnt, 1), 1),
        "fn_registry": {
            "exports": int(fn_ops.get("export", 0)),
            "fetches": int(fn_ops.get("fetch", 0)),
            "cache_hits": int(fn_ops.get("hit", 0)),
            "export_bytes": int(fn_bytes.get("export", 0)),
            "fetch_bytes": int(fn_bytes.get("fetch", 0)),
        },
        "head_fn_stats": dict(c.head.fn_stats),
        "stages": {
            "sync_task_rtt": pct(sync_rtt),
            "sync_actor_call_rtt": pct(actor_rtt),
            "lease_grant": pct(grant),
            "worker_exec_span": pct(exec_spans),
        },
        "async_tasks_per_s": round(n_tasks / async_wall, 1),
    }
    ray_tpu.kill(a)
    rt.shutdown()
    c.shutdown()
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "PERF_CONTROL.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps(out, indent=2))


if __name__ == "__main__":
    main()
