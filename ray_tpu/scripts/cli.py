"""Command-line interface: cluster status, state listings, timeline, logs.

Capability parity with the reference's CLI surface (reference:
python/ray/scripts/scripts.py `ray status`; util/state/state_cli.py
`ray list tasks|actors|...`, `ray summary tasks`, `ray timeline`,
`ray logs`): `python -m ray_tpu <command> [--address host:port]`.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _connect(address: str | None):
    import ray_tpu

    ray_tpu.init(address=address)
    return ray_tpu


def _fmt_table(rows: list[dict], columns: list[str]) -> str:
    if not rows:
        return "(empty)"
    widths = {c: max(len(c), *(len(str(r.get(c, ""))) for r in rows))
              for c in columns}
    line = "  ".join(c.ljust(widths[c]) for c in columns)
    out = [line, "-" * len(line)]
    for r in rows:
        out.append("  ".join(str(r.get(c, "")).ljust(widths[c])
                             for c in columns))
    return "\n".join(out)


NODE_TABLE_CAP = 50  # past this, status prints the summary aggregate only


def cmd_status(args) -> int:
    api = _connect(args.address)
    from ray_tpu.util.state import head_status, list_nodes, node_summary

    try:
        hs = head_status()
    except Exception:  # noqa: BLE001 - head facts are best-effort
        hs = {}
    if hs:
        up = hs.get("uptime_s")
        line = (f"Head: incarnation {hs.get('incarnation', '?')} "
                f"(restarts {hs.get('restart_count', '?')})")
        if isinstance(up, (int, float)):
            line += f", up {up:.0f}s"
        print(line)
        lag = hs.get("loop_lag_s")
        if isinstance(lag, (int, float)):
            print(f"  head loop lag: {lag * 1000:.1f}ms "
                  f"(max {hs.get('loop_lag_max_s', 0.0) * 1000:.1f}ms)")
        rpc = hs.get("rpc") or {}
        if rpc:
            top = sorted(rpc.items(),
                         key=lambda kv: -kv[1].get("rate_hz", 0.0))[:5]
            print("  busiest RPCs: " + ", ".join(
                f"{m} {row.get('rate_hz', 0.0):g}/s"
                + (f" ({row['mean_ms']:g}ms)" if "mean_ms" in row else "")
                for m, row in top))
        if hs.get("fenced_registrations") or hs.get("wal_tail_dropped"):
            print(f"  fenced registrations: "
                  f"{hs.get('fenced_registrations', 0)}, torn WAL tail "
                  f"records dropped: {hs.get('wal_tail_dropped', 0)}")
        if hs.get("reconcile"):
            print(f"  reconcile repairs: {hs['reconcile']}")
    total = api.cluster_resources()
    avail = api.available_resources()
    print("Cluster resources:")
    for k in sorted(total):
        print(f"  {k}: {avail.get(k, 0.0):g} / {total[k]:g} available")
    n_total = hs.get("nodes_total")
    if isinstance(n_total, int) and n_total > NODE_TABLE_CAP:
        # Fleet scale: the O(cluster) node table would drown the terminal
        # (and the head would pay to serialize it) — aggregate instead.
        try:
            s = node_summary()
            print(f"\nNodes: {s.get('nodes_alive', '?')} alive "
                  f"/ {s.get('nodes_total', '?')} total "
                  f"(table suppressed past {NODE_TABLE_CAP} nodes; "
                  f"use `ray_tpu list nodes`)")
            return 0
        except Exception:  # noqa: BLE001 - fall through to the table
            pass
    nodes = list_nodes()
    print(f"\nNodes ({len(nodes)}):")
    print(_fmt_table(nodes, ["node_id", "alive", "resources"]))
    return 0


def cmd_list(args) -> int:
    from ray_tpu.util import state

    _connect(args.address)
    fns = {
        "tasks": state.list_tasks, "actors": state.list_actors,
        "nodes": state.list_nodes, "workers": state.list_workers,
        "objects": state.list_objects,
        "placement-groups": state.list_placement_groups,
    }
    rows = fns[args.resource]()
    if args.json:
        print(json.dumps(rows, default=str))
    else:
        cols = list(rows[0].keys()) if rows else []
        print(_fmt_table(rows, cols[:6]))
    return 0


def cmd_summary(args) -> int:
    from ray_tpu.util.state import summarize_tasks

    _connect(args.address)
    print(json.dumps(summarize_tasks(), indent=2, default=str))
    return 0


def cmd_timeline(args) -> int:
    """Chrome-trace JSON of task execution + spans (reference: ray
    timeline). Object format ({"traceEvents": [...]}) so span rows and
    metadata records can ride alongside the task slices."""
    api = _connect(args.address)
    from ray_tpu.core.events import TaskEvent, chrome_trace
    from ray_tpu.core.worker import global_worker
    from ray_tpu.util import tracing

    events = api.timeline() if hasattr(api, "timeline") else None
    if events is None:
        raw = global_worker.runtime.task_events()["events"]
        events = chrome_trace([TaskEvent(**e) for e in raw])
    # Spans (local + cluster-flushed) as their own rows, deduped on
    # (trace_id, span_id) — span ids are per-process, so cross-process
    # collisions on span_id alone must not swallow rows.
    by_id = {(s.get("trace_id"), s["span_id"]): s for s in tracing.export()}
    rt = global_worker.runtime
    if rt is not None and hasattr(rt, "cluster_spans"):
        try:
            for s in rt.cluster_spans():
                by_id.setdefault((s.get("trace_id"), s.get("span_id")), s)
        except Exception:
            pass
    for s in by_id.values():
        events.append({
            "name": s["name"], "cat": f"span:{s['kind']}", "ph": "X",
            "ts": s["start_ts"] * 1e6,
            "dur": max(0.0, (s["end_ts"] - s["start_ts"]) * 1e6),
            "pid": "spans", "tid": s["trace_id"][:8],
            "args": {"trace_id": s["trace_id"], "span_id": s["span_id"],
                     "status": s["status"], **s.get("attributes", {})},
        })
    # Always at least the process-name metadata record: the file must load
    # in chrome://tracing / Perfetto even when nothing ran yet.
    events.append({"name": "process_name", "ph": "M", "pid": "spans",
                   "args": {"name": "ray_tpu spans"}})
    with open(args.out, "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
    print(f"wrote {len(events)} trace events to {args.out}")
    return 0


def cmd_trace(args) -> int:
    """Waterfall of ONE request's spans across every process that touched
    it (handle root, router attempt, replica, engine phases, DAG hops,
    transfer pulls), assembled from the local buffer + head-flushed spans.
    --out additionally writes a chrome://tracing file scoped to the trace."""
    _connect(args.address)
    from ray_tpu.core.worker import global_worker
    from ray_tpu.util import tracing

    want = args.trace_id
    by_id = {(s.get("trace_id"), s["span_id"]): s for s in tracing.export()}
    rt = global_worker.runtime
    if rt is not None and hasattr(rt, "cluster_spans"):
        try:
            for s in rt.cluster_spans():
                by_id.setdefault((s.get("trace_id"), s.get("span_id")), s)
        except Exception:
            pass  # head unreachable: local spans still render
    spans = [s for s in by_id.values()
             if s.get("trace_id", "").startswith(want)]
    if not spans:
        print(f"no spans for trace {want!r} (sampled out, expired from "
              "the buffer, or not flushed yet)")
        return 1
    spans.sort(key=lambda s: s.get("start_ts", 0.0))
    tid = spans[0]["trace_id"]
    t0 = min(s["start_ts"] for s in spans)
    t_end = max(s.get("end_ts") or s["start_ts"] for s in spans)
    total = max(t_end - t0, 1e-9)
    if args.json:
        print(json.dumps(spans, indent=2, default=str))
        return 0
    # Parent-chain indentation; orphan parents (span not captured — e.g. a
    # process that never flushed) render at depth 0, so a partial trace
    # still lays out.
    ids = {s["span_id"] for s in spans}
    depth: dict[str, int] = {}

    def _depth(s) -> int:
        d, seen = 0, set()
        cur = s
        while cur.get("parent_id") in ids and cur["span_id"] not in seen:
            seen.add(cur["span_id"])
            d += 1
            cur = next(x for x in spans
                       if x["span_id"] == cur["parent_id"])
        return d

    for s in spans:
        depth[s["span_id"]] = _depth(s)
    width = 40
    print(f"trace {tid}  ({len(spans)} spans, "
          f"{total * 1e3:.1f} ms end-to-end)")
    for s in spans:
        start = s["start_ts"] - t0
        dur = max(0.0, (s.get("end_ts") or s["start_ts"]) - s["start_ts"])
        lo = int(start / total * width)
        hi = max(lo + 1, int((start + dur) / total * width))
        bar = " " * lo + "#" * (hi - lo) + " " * (width - hi)
        name = "  " * depth[s["span_id"]] + s["name"]
        status = "" if s.get("status") == "OK" else f"  [{s['status']}]"
        print(f"  {name:<36.36} |{bar}| {start * 1e3:7.1f}ms "
              f"+{dur * 1e3:.1f}ms{status}")
        for ev in s.get("events") or []:
            extras = {k: v for k, v in ev.items() if k not in ("name", "ts")}
            ets = (float(ev.get("ts", s["start_ts"])) - t0) * 1e3
            print(f"  {'  ' * depth[s['span_id']]}  · {ev.get('name')}"
                  f" @{ets:.1f}ms"
                  + (f" {extras}" if extras else ""))
    if args.out:
        events = [{
            "name": s["name"], "cat": f"span:{s.get('kind', 'internal')}",
            "ph": "X", "ts": s["start_ts"] * 1e6,
            "dur": max(0.0, ((s.get("end_ts") or s["start_ts"])
                             - s["start_ts"]) * 1e6),
            "pid": "trace", "tid": s.get("kind", "internal"),
            "args": {"trace_id": tid, "span_id": s["span_id"],
                     "status": s.get("status", ""),
                     **(s.get("attributes") or {})},
        } for s in spans]
        events.append({"name": "process_name", "ph": "M", "pid": "trace",
                       "args": {"name": f"trace {tid[:16]}"}})
        with open(args.out, "w") as f:
            json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
        print(f"wrote {len(events)} trace events to {args.out}")
    return 0


def cmd_flight_records(args) -> int:
    """List (or dump one of) the failure flight-recorder bundles."""
    from ray_tpu.util.state import get_flight_record, list_flight_records

    if args.get:
        print(json.dumps(get_flight_record(args.get), indent=2,
                         default=str))
        return 0
    rows = list_flight_records(kind=args.kind)
    if args.json:
        print(json.dumps(rows, default=str))
    else:
        print(_fmt_table(rows, ["name", "kind", "ts_ns"]))
    return 0


def cmd_logs(args) -> int:
    """Tail worker logs (reference: ray logs)."""
    from ray_tpu.utils.config import get_config

    log_dir = os.path.join(get_config().temp_dir, "logs")
    if not os.path.isdir(log_dir):
        print(f"no logs at {log_dir}")
        return 1
    names = sorted(os.listdir(log_dir))
    if args.glob:
        import fnmatch

        names = [n for n in names if fnmatch.fnmatch(n, args.glob)]
    if args.list:
        for n in names:
            print(n)
        return 0
    for n in names:
        path = os.path.join(log_dir, n)
        try:
            with open(path, "rb") as f:
                f.seek(0, os.SEEK_END)
                f.seek(max(0, f.tell() - args.tail), os.SEEK_SET)
                data = f.read().decode(errors="replace")
        except OSError:
            continue
        if data.strip():
            print(f"==== {n} ====")
            print(data)
    return 0


def cmd_memory(args) -> int:
    """Object store usage (reference: ray memory); with --device, the
    per-node device/host memory snapshot (live jax buffer bytes per device,
    RSS, shm-arena occupancy)."""
    api = _connect(args.address)
    from ray_tpu.core.worker import global_worker

    if getattr(args, "device", False):
        from ray_tpu.util.state import device_memory

        print(json.dumps(device_memory(), indent=2, default=str))
        return 0
    snap = global_worker.runtime.state_snapshot()
    print(json.dumps(snap.get("objects", {}), indent=2))
    return 0


def cmd_profile(args) -> int:
    """On-demand cluster profile: stack samples + guarded XLA traces +
    memory snapshots from every process, merged with the span timeline into
    a chrome-trace and a fleet flamegraph under --out."""
    _connect(args.address)
    from ray_tpu.util.state import profile_cluster

    res = profile_cluster(seconds=args.seconds, sample_hz=args.hz,
                          out_dir=args.out)
    n = len(res["captures"])
    total = sum(c.get("samples", 0) for c in res["captures"])
    print(f"captured {n} process(es), {total} stack samples")
    for target, err in sorted(res["errors"].items()):
        print(f"  error {target[:16]}: {err}")
    for name, path in sorted(res.get("paths", {}).items()):
        print(f"  {name}: {path}")
    return 0 if n else 1


def cmd_stack(args) -> int:
    """Thread stacks: one worker (id prefix), or — with no target — every
    process in the cluster (daemons + workers; in-process runtimes degrade
    to this process)."""
    _connect(args.address)
    if args.worker:
        from ray_tpu.util.state import get_stack

        res = get_stack(args.worker)
        print(f"=== worker {res.get('worker_id', '')[:16]} "
              f"pid {res.get('pid')} ===")
        print(res.get("stacks", ""))
        return 0
    from ray_tpu.util.state import stack_cluster

    res = stack_cluster()
    for nid, node in sorted(res.get("nodes", {}).items()):
        d = node.get("daemon") or {}
        print(f"=== node {nid[:16]} daemon pid {d.get('pid')} ===")
        print(d.get("stacks", ""))
        for wid, w in sorted((node.get("workers") or {}).items()):
            print(f"=== worker {wid[:16]} pid {w.get('pid')} ===")
            print(w.get("stacks", ""))
        for wid, err in sorted((node.get("errors") or {}).items()):
            print(f"=== worker {wid[:16]} unreachable: {err} ===")
    return 0


def cmd_chaos(args) -> int:
    """Fault injection (chaos drills): install kill/delay/drop rules
    fleet-wide, clear them, or show the current schedule + firing log.
    The same rule schema drives tests, devbench, and live clusters
    (ray_tpu/chaos/injector.py documents it)."""
    _connect(args.address)
    from ray_tpu.util.state import inject_chaos

    if args.verb == "status":
        print(json.dumps(inject_chaos(), indent=2, default=str))
        return 0
    if args.verb == "clear":
        res = inject_chaos(clear=True)
        print(f"cleared chaos rules on "
              f"{1 + len(res.get('nodes', {}))} target group(s)")
        return 0

    rules: list[dict] = []
    if args.file:
        with open(args.file) as f:
            loaded = json.load(f)
        rules.extend(loaded if isinstance(loaded, list) else [loaded])
    if args.rules:
        loaded = json.loads(args.rules)
        rules.extend(loaded if isinstance(loaded, list) else [loaded])
    common = {}
    if args.after is not None:
        common["after_s"] = args.after
    if args.count is not None:
        common["count"] = args.count
    elif args.verb != "partition":
        # Targeted kill/rpc drills are single events by default; a
        # partition severs EVERY matched frame until healed, so it keeps
        # the injector's unlimited default.
        common["count"] = 1
    if args.prob is not None:
        common["prob"] = args.prob
    if args.at_step is not None:
        common["at_step"] = args.at_step
    # Each targeted verb REQUIRES its selector: a None selector would
    # install a rule that can never match, printing success while the
    # drill silently does nothing.
    def _need(value, flag):
        if value is None:
            print(f"chaos {args.verb} requires {flag}", file=sys.stderr)
            raise SystemExit(2)
        return value

    if args.verb == "kill-worker":
        rules.append({"point": "train.step", "action": "kill",
                      "match": {"rank": _need(args.rank, "--rank")},
                      **common})
    elif args.verb == "kill-slice":
        rules.append({"point": "train.step", "action": "kill",
                      "match": {"slice": _need(args.slice, "--slice")},
                      **common})
    elif args.verb == "kill-daemon":
        rules.append({"point": "daemon.tick", "action": "kill",
                      "match": {"node": _need(args.node, "--node")},
                      **common})
    elif args.verb == "kill-head":
        rules.append({"point": "head.tick", "action": "kill", **common})
    elif args.verb == "partition":
        rule = {"point": "partition",
                "action": "drop" if args.drop else "delay",
                "match": {"node": _need(args.node, "--node")},
                "direction": args.direction, **common}
        if not args.drop:
            rule["delay_s"] = args.delay_s
        rules.append(rule)
    elif args.verb == "rpc":
        action = "drop" if args.drop else "delay"
        rule = {"point": "rpc.server", "action": action,
                "match": {"method": _need(args.method, "--method")},
                **common}
        if not args.drop:
            rule["delay_s"] = args.delay_s
        rules.append(rule)
    elif args.verb != "install":
        print(f"unknown chaos verb {args.verb!r}", file=sys.stderr)
        return 2
    if not rules:
        print("no rules to install (use --file/--rules or a kill-*/rpc "
              "verb)", file=sys.stderr)
        return 2
    res = inject_chaos(rules=rules)
    nodes = res.get("nodes", {})
    workers = sum(len((n or {}).get("workers", ())) for n in nodes.values())
    errors = res.get("errors", {})
    print(f"installed {len(rules)} rule(s) on {len(nodes)} node(s), "
          f"{workers} worker(s)"
          + (f"; {len(errors)} error(s): {errors}" if errors else ""))
    return 0


def cmd_incidents(args) -> int:
    """Health-watchdog incidents: what the cluster noticed about itself
    (rule, implicated entity, evidence bundle). --get <id> dumps one
    incident in full (series window, flight-record path, profile
    summary)."""
    _connect(args.address)
    from ray_tpu.util.state import incidents

    if args.get:
        rows = incidents(incident_id=args.get)
        if not rows:
            print(f"no incident {args.get!r}", file=sys.stderr)
            return 1
        print(json.dumps(rows[-1], indent=2, default=str))
        return 0
    rows = incidents(since=args.since, limit=args.limit)
    if args.json:
        print(json.dumps(rows, default=str))
        return 0
    import time as _time

    table = []
    for inc in reversed(rows):  # newest first
        prof = (inc.get("profile") or {}).get("status", "")
        table.append({
            "id": inc["id"],
            "rule": inc["rule"],
            # wall_ts is the HEAD's clock; clamp so client skew can't
            # print a negative age.
            "age_s": f"{max(0.0, _time.time() - inc['wall_ts']):.0f}",
            "node": (inc.get("implicated") or {}).get("node_id", "")[:12],
            "profile": prof.split(":")[0],
            "reason": inc.get("reason", "")[:60],
        })
    if not table:
        print("no incidents")
        return 0
    print(_fmt_table(table, ["id", "rule", "age_s", "node", "profile",
                             "reason"]))
    return 0


def cmd_goodput(args) -> int:
    """Fleet goodput ledger: where every chip-second went. One row per
    run (goodput %, chip-seconds, top badput phases), a fleet summary
    line, and serve request-goodput per deployment. --run narrows to one
    run; --json dumps the full rollup (phase_chip_s, events, residuals)."""
    _connect(args.address)
    from ray_tpu.util.state import get_goodput

    rollup = get_goodput(run=args.run)
    if args.json:
        print(json.dumps(rollup, indent=2, default=str))
        return 0
    if not rollup.get("enabled", False):
        print("goodput ledger disabled on this runtime "
              "(set RTPU_GOODPUT_ENABLED=1 and use a cluster head)")
        return 1
    runs = rollup.get("runs", {})
    table = []
    for name, row in sorted(runs.items()):
        bad = sorted((row.get("badput_chip_s") or {}).items(),
                     key=lambda kv: kv[1], reverse=True)
        top = ", ".join(f"{p} {s:.1f}s" for p, s in bad[:3] if s > 0)
        table.append({
            "run": name,
            "ranks": row.get("ranks", 0),
            "chip_s": f"{row.get('chip_seconds', 0.0):.1f}",
            "goodput_pct": f"{row.get('goodput_pct', 0.0):.1f}",
            "unattributed_s": f"{row.get('unattributed_s', 0.0):.1f}",
            "top_badput": top or "-",
        })
    if table:
        print(_fmt_table(table, ["run", "ranks", "chip_s", "goodput_pct",
                                 "unattributed_s", "top_badput"]))
    else:
        print("no runs reporting")
    fleet = rollup.get("fleet") or {}
    if fleet:
        print(f"\nfleet: {fleet.get('chip_seconds', 0.0):.1f} chip-s, "
              f"goodput {fleet.get('goodput_pct', 0.0):.1f}%, "
              f"unattributed {fleet.get('unattributed_s', 0.0):.1f}s")
    serve = (rollup.get("serve") or {}).get("deployments") or {}
    for dep, row in sorted(serve.items()):
        print(f"serve/{dep}: {row.get('slo_tokens_per_s', 0.0):.1f} "
              f"SLO-tokens/s over {row.get('replicas', 0)} replica(s) "
              f"({row.get('request_goodput', 0.0):.1f}/replica)")
    return 0


def cmd_watch(args) -> int:
    """Live health line: poll the watchdog store + incident deque and
    print one compact status line per interval (new incidents are printed
    in full as they appear). --once prints a single snapshot (scripts,
    tests); bounded by --seconds."""
    _connect(args.address)
    import time as _time

    from ray_tpu.util.state import incidents, timeseries, watchdog_status

    def _latest(name: str, max_age_s: float = 60.0):
        # Staleness gate: the store keeps a finished job's rings around —
        # a run that ended an hour ago must not display as live health.
        # Filtered HEAD-side (max_age_s, judged on the head's clock) so
        # CLI/head clock skew can't blank or falsify the line.
        # max_points=1: the head ships one point per series instead of
        # serializing whole rings on every poll.
        rows = timeseries(name=name, max_age_s=max_age_s, max_points=1)
        vals = [r["points"][-1][1] for r in rows if r.get("points")]
        return (max(vals), len(vals)) if vals else (None, 0)

    seen: set = set()
    deadline = _time.monotonic() + args.seconds
    first = True
    while True:
        status = watchdog_status()
        if not status.get("enabled", False):
            print("watchdog disabled on this runtime")
            return 1
        parts = [f"series={status.get('store', {}).get('series', 0)}"]
        step, n_ranks = _latest("train_step_time_s")
        if step is not None:
            parts.append(f"step={step * 1e3:.0f}ms/{n_ranks}r")
        p99, _ = _latest("serve_ttft_s:p99")
        if p99 is not None:
            parts.append(f"ttft_p99={p99 * 1e3:.0f}ms")
        depth, _ = _latest("serve_router_queue_depth")
        if depth is not None:
            parts.append(f"queue={depth:g}")
        shed, _ = _latest("serve_shed_total:rate")
        if shed:
            parts.append(f"shed={shed:.1f}/s")
        rows = incidents(limit=status.get("incidents", 0) or 100)
        parts.append(f"incidents={len(rows)}")
        print(f"[watch {_time.strftime('%H:%M:%S')}] " + " ".join(parts),
              flush=True)
        for inc in rows:
            if inc["id"] in seen:
                continue
            seen.add(inc["id"])
            if first and not args.once:
                continue  # backlog: count it, don't spam the scrollback
            print(f"  incident {inc['id']} [{inc['rule']}] "
                  f"{inc.get('reason', '')} -> "
                  f"node {(inc.get('implicated') or {}).get('node_id', '')}"
                  f" profile={(inc.get('profile') or {}).get('status', '')}",
                  flush=True)
        first = False
        if args.once or _time.monotonic() >= deadline:
            return 0
        _time.sleep(args.interval)


def cmd_stragglers(args) -> int:
    """Straggler report: workers ranked by step time vs the fleet, lagging
    host named."""
    _connect(args.address)
    from ray_tpu.profiling.straggler import format_report
    from ray_tpu.util.state import stragglers

    report = stragglers(threshold=args.threshold)
    if args.json:
        print(json.dumps(report, indent=2, default=str))
    else:
        print(format_report(report))
    return 0


def cmd_lint(args) -> int:
    """rtlint: framework-aware static analysis (ray_tpu/devtools). Runs
    entirely locally — no cluster connection. Exit 0 iff every finding is
    fixed or allowlisted with a justification."""
    from ray_tpu.devtools.engine import (
        DEFAULT_ALLOWLIST,
        AllowlistError,
        LintUsageError,
        format_findings,
        run_lint,
    )

    allowlist = None if args.no_allowlist else (
        args.allowlist or DEFAULT_ALLOWLIST)
    if args.allowlist and not os.path.exists(args.allowlist):
        # An explicitly-given allowlist that doesn't exist must be loud:
        # silently linting with an empty baseline would resurface every
        # accepted finding as if it were new.
        print(f"rtlint: no such allowlist file: {args.allowlist}",
              file=sys.stderr)
        return 2
    paths = args.paths or None
    if paths:
        missing = [p for p in paths if not os.path.exists(p)]
        if missing:
            print(f"rtlint: no such path(s): {', '.join(missing)}",
                  file=sys.stderr)
            return 2
    try:
        res = run_lint(paths, allowlist=allowlist,
                       rules=args.rules.split(",") if args.rules else None)
    except (AllowlistError, LintUsageError) as e:
        print(f"rtlint: {e}", file=sys.stderr)
        return 2
    if paths and res.files == 0 and not res.findings:
        # Explicit targets that contained no parseable Python: a typo'd
        # path must not produce a green "checked nothing" run.
        print(f"rtlint: no Python files found under: {', '.join(paths)}",
              file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps({
            "findings": [{"rule": f.rule, "file": f.relpath,
                          "line": f.line, "symbol": f.symbol,
                          "message": f.message} for f in res.findings],
            "allowlisted": len(res.allowlisted),
            "stale_allowlist_entries": len(res.stale_entries),
            "files": res.files,
            "counts": res.counts,
            "rule_seconds": res.rule_seconds,
            "wall_seconds": res.wall_seconds,
        }, indent=2))
    else:
        print(format_findings(res, verbose=args.verbose))
    # Stale allowlist rows fail the run too — the cannot-rot invariant
    # must hold from the CLI, not only from the dryrun gate.
    return 0 if res.ok and not res.stale_entries else 1


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="ray_tpu")
    p.add_argument("--address", default=None,
                   help="head address (host:port), client://host:port, or "
                        "omit for an in-process runtime")
    sub = p.add_subparsers(dest="command", required=True)

    sub.add_parser("status")
    lp = sub.add_parser("list")
    lp.add_argument("resource", choices=["tasks", "actors", "nodes",
                                         "workers", "objects",
                                         "placement-groups"])
    lp.add_argument("--json", action="store_true")
    sp = sub.add_parser("summary")
    sp.add_argument("resource", choices=["tasks"])
    tp = sub.add_parser("timeline")
    tp.add_argument("--out", default="timeline.json")
    tr = sub.add_parser(
        "trace", help="waterfall of one request's spans across every "
                      "process (handle/router/replica/engine/DAG/transfer)")
    tr.add_argument("trace_id", help="trace id (or unique prefix) — from "
                                     "an SLO exemplar, incident, or log")
    tr.add_argument("--out", default=None,
                    help="also write a chrome://tracing JSON for this trace")
    tr.add_argument("--json", action="store_true")
    fp = sub.add_parser("flight-records")
    fp.add_argument("--get", default=None, help="dump one bundle by name")
    fp.add_argument("--kind", default=None,
                    help="filter: task_failure | worker_death | actor_death")
    fp.add_argument("--json", action="store_true")
    gp = sub.add_parser("logs")
    gp.add_argument("glob", nargs="?", default=None)
    gp.add_argument("--list", action="store_true")
    gp.add_argument("--tail", type=int, default=20_000)
    mp = sub.add_parser("memory")
    mp.add_argument("--device", action="store_true",
                    help="per-node device/host memory snapshot")
    prof = sub.add_parser("profile")
    prof.add_argument("--seconds", type=float, default=5.0)
    prof.add_argument("--hz", type=float, default=0.0,
                      help="sampling rate (default: config "
                           "profiler_sample_hz)")
    prof.add_argument("--out", default="prof",
                      help="artifact directory (trace.json, flame.txt, "
                           "memory.json, captures.json)")
    stk = sub.add_parser("stack")
    stk.add_argument("worker", nargs="?", default="",
                     help="worker id (or unique prefix); omit for a "
                          "fleet-wide dump of every daemon and worker")
    strag = sub.add_parser("stragglers")
    strag.add_argument("--threshold", type=float, default=1.15)
    strag.add_argument("--json", action="store_true")
    inc = sub.add_parser(
        "incidents", help="health-watchdog incidents: auto-detected "
                          "anomalies with captured evidence bundles")
    inc.add_argument("--get", default=None, help="dump one incident by id")
    inc.add_argument("--since", type=float, default=0.0,
                     help="only incidents after this unix timestamp")
    inc.add_argument("--limit", type=int, default=100)
    inc.add_argument("--json", action="store_true")
    gdp = sub.add_parser(
        "goodput", help="fleet goodput ledger: per-run and fleet goodput %% "
                        "with the badput breakdown in chip-seconds")
    gdp.add_argument("--run", default=None, help="narrow to one run name")
    gdp.add_argument("--json", action="store_true")
    wt = sub.add_parser(
        "watch", help="live cluster-health line off the watchdog series "
                      "store (step time, serve p99, queue, sheds, "
                      "incidents)")
    wt.add_argument("--interval", type=float, default=2.0)
    wt.add_argument("--seconds", type=float, default=300.0,
                    help="stop after this long")
    wt.add_argument("--once", action="store_true",
                    help="print one snapshot and exit")
    lint = sub.add_parser(
        "lint", help="rtlint static analysis: race/lock-order/event-loop/"
                     "metrics/knob-registry checks over ray_tpu (or given "
                     "paths); exit 1 on unallowlisted findings")
    lint.add_argument("paths", nargs="*", default=None,
                      help="files/dirs to lint (default: the installed "
                           "ray_tpu package)")
    lint.add_argument("--rules", default=None,
                      help="comma-separated rule subset, e.g. R1,R4")
    lint.add_argument("--allowlist", default=None,
                      help="allowlist file (default: "
                           "ray_tpu/devtools/rtlint_allow.txt)")
    lint.add_argument("--no-allowlist", action="store_true",
                      help="report every finding, allowlisted or not")
    lint.add_argument("--json", action="store_true")
    lint.add_argument("--verbose", action="store_true",
                      help="include per-rule timings in the summary")
    ch = sub.add_parser(
        "chaos", help="fault injection: kill workers/slices/daemons/the "
                      "head, delay/drop RPCs, partition nodes from the "
                      "head (see ray_tpu/chaos/injector.py)")
    ch.add_argument("verb", choices=["status", "clear", "install",
                                     "kill-worker", "kill-slice",
                                     "kill-daemon", "kill-head",
                                     "partition", "rpc"])
    ch.add_argument("--file", default=None, help="JSON rule file")
    ch.add_argument("--rules", default=None, help="inline JSON rule list")
    ch.add_argument("--rank", type=int, default=None,
                    help="kill-worker: world rank to kill")
    ch.add_argument("--slice", type=int, default=None,
                    help="kill-slice: slice id to kill")
    ch.add_argument("--node", default=None,
                    help="kill-daemon/partition: node id regex")
    ch.add_argument("--method", default=None,
                    help="rpc: RPC method regex to delay/drop")
    ch.add_argument("--direction", default="both",
                    choices=["both", "to_head", "from_head"],
                    help="partition: which head⇄node direction to sever")
    ch.add_argument("--delay-s", type=float, default=0.1, dest="delay_s")
    ch.add_argument("--drop", action="store_true",
                    help="rpc/partition: drop matching frames instead of "
                         "delaying")
    ch.add_argument("--at-step", type=int, default=None, dest="at_step")
    ch.add_argument("--after", type=float, default=None,
                    help="arm the rule this many seconds after install")
    ch.add_argument("--count", type=int, default=None,
                    help="max firings (-1 = unlimited; default 1, except "
                         "partition which defaults unlimited)")
    ch.add_argument("--prob", type=float, default=None)

    from ray_tpu.scripts.start import add_parsers as _add_start_parsers

    _add_start_parsers(sub)

    args = p.parse_args(argv)
    if hasattr(args, "_fn"):  # start/stop/serve-* carry their handler
        return args._fn(args)
    cmds = {"status": cmd_status, "list": cmd_list, "summary": cmd_summary,
            "timeline": cmd_timeline, "trace": cmd_trace,
            "logs": cmd_logs, "memory": cmd_memory,
            "flight-records": cmd_flight_records, "profile": cmd_profile,
            "stack": cmd_stack, "stragglers": cmd_stragglers,
            "chaos": cmd_chaos, "incidents": cmd_incidents,
            "goodput": cmd_goodput, "watch": cmd_watch, "lint": cmd_lint}
    return cmds[args.command](args)


if __name__ == "__main__":
    sys.exit(main())
