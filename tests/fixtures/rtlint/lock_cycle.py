"""R2 fixture: lock-order cycle + await-while-holding-lock.

Two threads taking ``_alock``→``_block`` and ``_block``→``_alock``
deadlock the moment their critical sections overlap; and an ``async def``
that awaits while holding a *threading* lock parks every other acquirer
for the full suspension (the serve-router review has rejected both
shapes by hand — this mechanizes the check)."""

import asyncio
import threading


class Transfer:
    def __init__(self):
        self._alock = threading.Lock()
        self._block = threading.Lock()

    def debit_then_credit(self):
        with self._alock:
            with self._block:  # order: A -> B
                pass

    def credit_then_debit(self):
        with self._block:
            with self._alock:  # BUG: order B -> A closes the cycle
                pass

    async def publish(self):
        with self._alock:
            # BUG: suspends the coroutine with a threading lock held.
            await asyncio.sleep(0.1)
