"""Watchdog bench: prove the always-on health loop closed, end to end.

Four phases on real multi-process clusters (subprocess workers, in-process
head/daemons), writing PERF_WATCHDOG.json:

- ``clean``        — steady train reporting + steady serve traffic for the
  whole window on a fresh cluster: the watchdog must open ZERO incidents
  (false-positive gate), while the series store visibly carries the
  hot-path series.
- ``straggler``    — a chaos ``train.step`` delay rule stretches ONE
  rank's steps mid-run: the step-drift detector must trip, attribute the
  implicated rank/host (PR-5 straggler attribution), and capture the full
  evidence bundle (series window + flight record + targeted profile).
- ``rpc_delay``    — a chaos ``rpc.server`` delay on the head's
  ``heartbeat`` handler jitters one node's heartbeat gaps: the
  heartbeat-jitter detector must trip and implicate that node.
- ``slow_serve``   — a chaos ``serve.replica`` delay turns one replica
  into a latency outlier: the serve-p99 detector must trip.

Detection latency is measured chaos-mark -> incident wall_ts (the mark file
is written inside the injected process at the FIRST firing instant) and
gated at <= 5 s per fault. Duty cycle is read off the self-metrics:
``watchdog_eval_seconds`` (head ingest+eval) and
``watchdog_sample_seconds`` (per-reporter sampling), each divided by the
phase wall time and gated < 1 %.

The train/serve workloads are the real metric paths (session.report ->
train gauges + train_stats; ServeReplica.handle_request -> serve
histograms + the serve.replica chaos probe) driven by plain actors — the
full Trainer/serve control planes are proven by their own benches
(PERF_RECOVERY/PERF_SERVE_LOAD); this bench isolates the watchdog loop.

Run: python devbench/watchdog_bench.py [--quick]
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REQUIRED_EVIDENCE = ("implicated", "window", "flight_record", "profile")


def _mk_cluster(tag: str):
    import ray_tpu
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.core.worker import global_worker
    from ray_tpu.utils import config as config_mod
    from ray_tpu.utils.ids import JobID

    ray_tpu.shutdown()
    config_mod.set_config(config_mod.Config.load())
    cluster = Cluster()
    cluster.add_node(num_cpus=3, resources={"wslot0": 2.0},
                     node_id=f"wd{tag}a")
    cluster.add_node(num_cpus=2, resources={"wslot1": 2.0},
                     node_id=f"wd{tag}b")
    rt = cluster.connect()
    old = (global_worker.runtime, global_worker.worker_id,
           global_worker.node_id, global_worker.mode, global_worker.job_id)
    global_worker.runtime = rt
    global_worker.worker_id = rt.worker_id
    global_worker.node_id = rt.node_id
    global_worker.job_id = JobID.from_random()
    global_worker.mode = "cluster"
    try:
        rt._daemon.call("prestart_workers", n=3, timeout=10)
    except Exception:
        pass
    return cluster, rt, old


def _teardown(cluster, rt, old):
    from ray_tpu.core.worker import global_worker

    try:
        rt.shutdown()
        cluster.shutdown()
    except Exception:
        pass
    (global_worker.runtime, global_worker.worker_id, global_worker.node_id,
     global_worker.mode, global_worker.job_id) = old


def _stepper_cls():
    import ray_tpu

    @ray_tpu.remote(num_cpus=1)
    class Stepper:
        """Steady train reporter: real session.report path (train gauges +
        straggler train_stats stream to the head)."""

        def run(self, rank, world, seconds, step_s):
            import random
            import time as _t

            from ray_tpu.train import session

            ctx = session.TrainContext(world_rank=rank, world_size=world)
            session.set_context(ctx)
            deadline = _t.monotonic() + seconds
            step = 0
            try:
                while _t.monotonic() < deadline:
                    _t.sleep(step_s * random.uniform(0.85, 1.15))
                    session.report({"step": step, "tokens": 256})
                    step += 1
            finally:
                session.set_context(None)
            return step

    return Stepper


def _server_cls():
    import ray_tpu

    @ray_tpu.remote(num_cpus=1)
    class Server:
        """Steady serve replica: real ServeReplica.handle_request path
        (TTFT/TPOT histograms + the serve.replica chaos probe)."""

        def __init__(self, replica_id):
            from ray_tpu.serve.replica import ServeReplica
            from ray_tpu.utils import serialization as ser

            def infer(x):
                import time as _t

                _t.sleep(0.004)
                return x

            self.rep = ServeReplica("wdllm", replica_id,
                                    ser.serialize(infer),
                                    ser.serialize(((), {})))

        def serve_for(self, seconds, rps):
            import time as _t

            deadline = _t.monotonic() + seconds
            n = 0
            gap = 1.0 / max(rps, 1)
            while _t.monotonic() < deadline:
                self.rep.handle_request("__call__", (n,), {})
                n += 1
                _t.sleep(gap)
            return n

    return Server


def _poll_incident(rt, rule, after_wall, timeout_s):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        for inc in rt.incidents().get("incidents", []):
            if inc["rule"] == rule and inc["wall_ts"] >= after_wall:
                return inc
        time.sleep(0.25)
    return None


def _mark_ts(marks_dir: str) -> float | None:
    ts = []
    try:
        for name in os.listdir(marks_dir):
            try:
                ts.append(json.load(open(os.path.join(marks_dir, name)))["ts"])
            except Exception:
                pass
    except OSError:
        return None
    return min(ts) if ts else None


def _evidence(inc: dict) -> dict:
    prof = (inc.get("profile") or {}).get("status", "")
    return {
        "implicated": bool((inc.get("implicated") or {}).get("node_id")),
        "window": len(inc.get("window") or []) >= 3,
        "flight_record": bool(inc.get("flight_record")),
        "profile": prof == "captured",
        "profile_status": prof,
        "profile_samples": (inc.get("profile") or {}).get("samples", 0),
    }


def _fault_row(name, inc, inject_ts):
    if inc is None:
        return {"fault": name, "detected": False}
    ev = _evidence(inc)
    row = {
        "fault": name,
        "detected": True,
        "rule": inc["rule"],
        "reason": inc["reason"],
        "implicated": inc.get("implicated"),
        "detection_latency_s": (round(inc["wall_ts"] - inject_ts, 2)
                                if inject_ts else None),
        "evidence": ev,
        "evidence_complete": all(ev[k] for k in REQUIRED_EVIDENCE),
        "assembly_s": inc.get("assembly_s"),
    }
    return row


def _duty_cycle(rt, wall_s: float) -> dict:
    """Watchdog cost off the self-metrics: head eval seconds from
    watchdog_status, per-reporter sampling seconds from the telemetry
    table (max across sources = the worst process)."""
    status = rt.watchdog_status()
    head_pct = 100.0 * status.get("eval_seconds", 0.0) / max(wall_s, 1e-9)
    worst_sample = 0.0
    for row in rt.get_telemetry().get("sources", {}).values():
        for entry in (row.get("snapshot") or {}).get("metrics", []):
            if entry.get("name") == "watchdog_sample_seconds":
                for _tags, v in entry.get("points", []):
                    worst_sample = max(worst_sample, float(v))
    sample_pct = 100.0 * worst_sample / max(wall_s, 1e-9)
    return {
        "wall_s": round(wall_s, 2),
        "head_eval_seconds": status.get("eval_seconds"),
        "head_duty_pct": round(head_pct, 4),
        "worst_reporter_sample_seconds": round(worst_sample, 4),
        "worst_reporter_duty_pct": round(sample_pct, 4),
        "store": status.get("store"),
    }


def run_bench(quick: bool = False, out_path: str | None = None) -> dict:
    import ray_tpu
    from ray_tpu.chaos import injector
    from ray_tpu.util.state import inject_chaos

    injector.reset_for_tests()
    # Bench-friendly cadences, production detector thresholds: faster
    # heartbeats shorten the jitter phase, a smaller warmup shortens the
    # baseline windows — neither changes what counts as an anomaly.
    os.environ["RTPU_HEALTH_CHECK_PERIOD_S"] = "0.5"
    os.environ["RTPU_WATCHDOG_WARMUP_SAMPLES"] = "6" if quick else "10"
    os.environ["RTPU_WATCHDOG_CAPTURE_COOLDOWN_S"] = "5"
    os.environ["RTPU_WATCHDOG_COOLDOWN_S"] = "20"
    baseline_s = 6.0 if quick else 10.0
    fault_s = 14.0 if quick else 20.0
    report: dict = {"bench": "watchdog", "quick": quick}

    # ---------------------------------------------------------- clean run
    cluster, rt, old = _mk_cluster("cln")
    try:
        t0 = time.time()
        Stepper = _stepper_cls()
        Server = _server_cls()
        steppers = [
            Stepper.options(resources={"wslot0": 1.0}).remote(),
            Stepper.options(resources={"wslot1": 1.0}).remote(),
        ]
        server = Server.options(resources={"wslot0": 1.0}).remote("r0")
        window = baseline_s + (6.0 if quick else 10.0)
        refs = [s.run.remote(r, 2, window, 0.08)
                for r, s in enumerate(steppers)]
        refs.append(server.serve_for.remote(window, 25))
        ray_tpu.get(refs, timeout=window + 120)
        time.sleep(1.5)  # final flush + eval tick
        wall = time.time() - t0
        incs = rt.incidents().get("incidents", [])
        series = rt.get_timeseries().get("series", [])
        names = {s["name"] for s in series}
        report["clean"] = {
            "seconds": round(wall, 1),
            "incidents": len(incs),
            "incident_rules": sorted({i["rule"] for i in incs}),
            "series": len(series),
            "series_names": sorted(names),
            "has_core_series": bool(
                {"train_step_time_s", "serve_ttft_s:p99",
                 "proc_rss_bytes", "node_heartbeat_gap_s"} <= names),
        }
        report["duty_cycle"] = _duty_cycle(rt, wall)
    finally:
        _teardown(cluster, rt, old)
        injector.reset_for_tests()

    # --------------------------------------------------------- fault runs
    cluster, rt, old = _mk_cluster("flt")
    marks_root = tempfile.mkdtemp(prefix="rtpu-wd-marks-")
    faults: dict[str, dict] = {}
    try:
        t_faults0 = time.time()
        Stepper = _stepper_cls()
        Server = _server_cls()

        # -- straggler: rank 1 (pinned to node b) gets +1.0s per step
        steppers = [
            Stepper.options(resources={"wslot0": 1.0}).remote(),
            Stepper.options(resources={"wslot1": 1.0}).remote(),
        ]
        marks = os.path.join(marks_root, "straggler")
        refs = [s.run.remote(r, 2, baseline_s + fault_s, 0.08)
                for r, s in enumerate(steppers)]
        time.sleep(baseline_s)  # build the step-time baseline
        inject_chaos(rules=[{
            "point": "train.step", "action": "delay", "delay_s": 1.0,
            "match": {"rank": 1}, "count": -1, "mark": marks}])
        inc = _poll_incident(rt, "train_step_drift", time.time() - 1.0,
                             fault_s + 10)
        ray_tpu.get(refs, timeout=baseline_s + fault_s + 120)
        inject_chaos(clear=True)
        row = _fault_row("straggler", inc, _mark_ts(marks))
        if inc is not None:
            imp = inc.get("implicated") or {}
            row["implicated_rank_1"] = (imp.get("rank") == 1)
        faults["straggler"] = row

        # -- rpc delay: head-side heartbeat handler +1.0s for one node
        marks = os.path.join(marks_root, "rpcdelay")
        inject_chaos(rules=[{
            "point": "rpc.server", "action": "delay", "delay_s": 1.0,
            "match": {"method": "^heartbeat$"}, "count": 12,
            "mark": marks}])
        inc = _poll_incident(rt, "heartbeat_jitter", time.time() - 1.0,
                             fault_s + 10)
        inject_chaos(clear=True)
        faults["rpc_delay"] = _fault_row("rpc_delay", inc, _mark_ts(marks))

        # -- slow serve replica: r1 becomes a latency outlier
        servers = [
            Server.options(resources={"wslot0": 1.0}).remote("r0"),
            Server.options(resources={"wslot1": 1.0}).remote("r1"),
        ]
        marks = os.path.join(marks_root, "slowserve")
        refs = [s.serve_for.remote(baseline_s + fault_s, 25)
                for s in servers]
        time.sleep(baseline_s)  # build the p99 baseline
        inject_chaos(rules=[{
            "point": "serve.replica", "action": "delay", "delay_s": 0.8,
            "match": {"deployment": "wdllm", "replica": "r1"},
            "count": -1, "mark": marks}])
        inc = _poll_incident(rt, "serve_latency", time.time() - 1.0,
                             fault_s + 10)
        ray_tpu.get(refs, timeout=baseline_s + fault_s + 120)
        inject_chaos(clear=True)
        faults["slow_serve"] = _fault_row("slow_serve", inc,
                                          _mark_ts(marks))
        report["fault_wall_s"] = round(time.time() - t_faults0, 1)
        report["watchdog_status"] = rt.watchdog_status()
    finally:
        _teardown(cluster, rt, old)
        injector.reset_for_tests()
        shutil.rmtree(marks_root, ignore_errors=True)
        for key in ("RTPU_HEALTH_CHECK_PERIOD_S",
                    "RTPU_WATCHDOG_WARMUP_SAMPLES",
                    "RTPU_WATCHDOG_CAPTURE_COOLDOWN_S",
                    "RTPU_WATCHDOG_COOLDOWN_S"):
            os.environ.pop(key, None)
        from ray_tpu.utils import config as config_mod

        config_mod.set_config(config_mod.Config.load())

    report["faults"] = faults
    lat = [f.get("detection_latency_s") for f in faults.values()]
    dc = report["duty_cycle"]
    report["acceptance"] = {
        "all_faults_detected": all(f.get("detected") for f in
                                   faults.values()) and len(faults) == 3,
        "all_within_5s": all(
            v is not None and v <= 5.0 for v in lat),
        "all_evidence_complete": all(f.get("evidence_complete")
                                     for f in faults.values()),
        "zero_false_incidents": report["clean"]["incidents"] == 0,
        "duty_cycle_under_1pct": (dc["head_duty_pct"] < 1.0
                                  and dc["worst_reporter_duty_pct"] < 1.0),
    }
    report["provenance"] = {
        "date": time.strftime("%Y-%m-%d %H:%M:%S"),
        "cpus": os.cpu_count(),
        "loadavg": list(os.getloadavg()),
        "box_note": (
            "single-host multi-process clusters (2 in-process daemons, "
            "subprocess workers). Detection latency = chaos mark instant "
            "(written inside the injected process at first firing) -> "
            "incident wall_ts; the budget spans telemetry flush (0.5s), "
            "streaming detection with debounce, and the evidence-assembly "
            "tick. Duty cycle = watchdog self-metric seconds / phase "
            "wall."),
    }

    out_path = out_path or os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "PERF_WATCHDOG.json")
    # Same namespacing contract as the other PERF files: a quick dryrun
    # refresh lands under "quick_refresh", never overwriting full-run
    # provenance.
    doc = report
    if quick and os.path.exists(out_path):
        try:
            with open(out_path) as f:
                existing = json.load(f)
            if not existing.get("quick"):
                existing["quick_refresh"] = report
                doc = existing
        except Exception:
            pass
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=2)
    return report


if __name__ == "__main__":
    rep = run_bench(quick="--quick" in sys.argv[1:])
    print(json.dumps(rep, indent=2))
    acc = rep["acceptance"]
    sys.exit(0 if all(acc.values()) else 1)
