"""Logical-axis sharding rules: how tensors map onto the mesh.

TPU-native replacement for the reference's per-framework sharding (reference:
ray.train torch path wraps DDP/FSDP per-parameter at runtime,
train_loop_utils.py:153; vLLM owns TP layout): here sharding is declarative —
params/activations carry *logical* axis names and a rule table maps logical →
mesh axes; XLA inserts the collectives. Swapping dp↔fsdp↔tp strategy is a
rule-table change, not a model change.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Default rule table for transformer training (MaxText-style conventions):
# logical axis name -> mesh axis (or tuple of mesh axes, or None = replicate).
DEFAULT_RULES: dict[str, object] = {
    # params
    "vocab": "tp",
    "embed": ("fsdp",),          # weight-shard over fsdp
    "mlp": "tp",
    "heads": "tp",
    "kv_heads": "tp",
    "head_dim": None,
    "layers": None,              # stacked-layer leading axis (scan over layers)
    "expert": "ep",
    # activations
    "batch": ("dp", "fsdp"),     # global batch split over both data axes
    "seq": "sp",
    "act_embed": None,
    "act_heads": "tp",
}


@dataclass
class ShardingRules:
    rules: dict[str, object] = field(default_factory=lambda: dict(DEFAULT_RULES))

    def spec(self, *logical_axes: str | None) -> P:
        """PartitionSpec for a tensor whose dims have these logical names."""
        out = []
        used: set[str] = set()
        for ax in logical_axes:
            if ax is None:
                out.append(None)
                continue
            mesh_ax = self.rules.get(ax)
            if mesh_ax is None:
                out.append(None)
            elif isinstance(mesh_ax, tuple):
                fresh = tuple(m for m in mesh_ax if m not in used)
                used.update(fresh)
                out.append(fresh if len(fresh) > 1 else (fresh[0] if fresh else None))
            else:
                if mesh_ax in used:
                    out.append(None)
                else:
                    used.add(mesh_ax)
                    out.append(mesh_ax)
        return P(*out)

    def sharding(self, mesh: Mesh, *logical_axes: str | None) -> NamedSharding:
        return NamedSharding(mesh, self.spec(*logical_axes))

    def override(self, **updates) -> "ShardingRules":
        return ShardingRules({**self.rules, **updates})


def normalize_spec(spec: P | None) -> P:
    """Canonical PartitionSpec form: 1-tuples collapse to their bare axis
    and empty tuples to None, so specs compare by MEANING across jax
    versions (jax >= 0.5 normalizes at construction; 0.4.x keeps
    ``P(("fsdp",),)`` and ``P("fsdp")`` distinct-but-equivalent objects,
    which breaks naive equality)."""
    if spec is None:
        return P()
    out = []
    for e in spec:
        if isinstance(e, tuple):
            e = e if len(e) > 1 else (e[0] if e else None)
        out.append(e)
    return P(*out)


def tree_shardings(mesh: Mesh, logical_tree, rules: ShardingRules | None = None):
    """Map a pytree of logical-axis tuples to a pytree of NamedShardings."""
    rules = rules or ShardingRules()
    return jax.tree.map(
        lambda axes: rules.sharding(mesh, *axes),
        logical_tree,
        is_leaf=lambda x: isinstance(x, tuple) and all(
            isinstance(a, (str, type(None))) for a in x
        ),
    )


def shard_params(params, mesh: Mesh, logical_tree, rules: ShardingRules | None = None):
    """Device_put a param pytree with shardings derived from logical axes."""
    shardings = tree_shardings(mesh, logical_tree, rules)
    return jax.tree.map(jax.device_put, params, shardings)


# -- cross-replica weight-update sharding (ZeRO-1, arxiv 2004.13336) --------

def batch_axes(rules: ShardingRules | None = None) -> tuple[str, ...]:
    """The mesh axes the global batch shards over — the data-parallel domain
    a ZeRO-1 update can shard optimizer state across."""
    rules = rules or ShardingRules()
    ax = rules.rules.get("batch")
    if ax is None:
        return ()
    return tuple(ax) if isinstance(ax, tuple) else (ax,)


# Logical dims a ZeRO-1 update must NOT shard: "layers" is the scan-stacked
# dim (sharding it would slice the layer loop itself, forcing per-iteration
# resharding inside the backward while-loop), and "vocab" is gather/scatter-
# indexed on the embedding table (a data-dependent-sharded scatter makes the
# partitioner fall back to full gathers of the one-hot activations).
ZERO1_SKIP_LOGICAL = ("layers", "vocab")


def zero1_spec(spec: P, shape: tuple[int, ...], mesh: Mesh,
               axes: tuple[str, ...],
               logical: tuple[str | None, ...] | None = None) -> P:
    """Extend a param leaf's PartitionSpec so one dim is additionally
    sharded over ``axes`` (the data-parallel mesh axes), when divisible.

    This is the ZeRO-1 layout: optimizer moments (and the weight update)
    keyed off this spec live 1/N-sized per data-parallel replica. The dim is
    the largest one divisible by the extra factor whose logical name (when
    ``logical`` is given) isn't in :data:`ZERO1_SKIP_LOGICAL` — matmul-style
    dims lower to clean (reduce-)scatter collectives, scan/index dims don't.
    Axes already used elsewhere in the spec are skipped; leaves with no
    suitable dim keep their original spec (their update stays replicated —
    correct, just not sharded)."""
    spec = P(*spec) if spec is not None else P()
    entries = list(spec) + [None] * (len(shape) - len(spec))
    if not entries or not shape:
        return spec
    used = set()
    for e in entries:
        used.update(e if isinstance(e, tuple) else ((e,) if e else ()))
    extra = tuple(a for a in axes if a not in used and mesh.shape[a] > 1)
    if not extra:
        return spec
    extra_n = math.prod(mesh.shape[a] for a in extra)

    def _entry_axes(e):
        return e if isinstance(e, tuple) else ((e,) if e else ())

    best = None
    for dim, size in enumerate(shape):
        if logical is not None and dim < len(logical) and \
                logical[dim] in ZERO1_SKIP_LOGICAL:
            continue
        factor = extra_n * math.prod(
            mesh.shape[a] for a in _entry_axes(entries[dim]))
        if size % factor:
            continue
        if best is None or size > shape[best]:
            best = dim
    if best is None:
        return spec
    merged = tuple(_entry_axes(entries[best])) + extra  # existing axes major
    entries[best] = merged if len(merged) > 1 else merged[0]
    while entries and entries[-1] is None:
        entries.pop()
    return P(*entries)


def zero1_shardings(mesh: Mesh, shapes, shardings, axes: tuple[str, ...],
                    logical_axes=None):
    """Map param-leaf shardings to their ZeRO-1 counterparts: each leaf's
    spec extended over the data-parallel ``axes`` via :func:`zero1_spec`.
    ``shapes`` is any pytree of objects with ``.shape`` matching
    ``shardings``' structure; ``logical_axes`` (the same pytree of
    logical-dim-name tuples the rule table consumes) steers dim choice away
    from scan/index dims."""
    leaves, treedef = jax.tree.flatten(shapes)
    sh_leaves = jax.tree.flatten(shardings)[0]
    if logical_axes is None:
        log_leaves = [None] * len(leaves)
    else:
        # is_leaf must also catch None entries ("no logical names for this
        # leaf") — tree.flatten would otherwise DROP them, misaligning
        # log_leaves against the param leaves.
        log_leaves = jax.tree.flatten(
            logical_axes,
            is_leaf=lambda x: x is None or (
                isinstance(x, tuple) and all(
                    isinstance(a, (str, type(None))) for a in x)))[0]
        if len(log_leaves) != len(leaves):
            raise ValueError(
                f"logical_axes tree has {len(log_leaves)} leaves, params "
                f"have {len(leaves)}")
    out = [
        NamedSharding(mesh, zero1_spec(sh.spec, tuple(leaf.shape), mesh,
                                       axes, logical=log))
        for leaf, sh, log in zip(leaves, sh_leaves, log_leaves)
    ]
    return jax.tree.unflatten(treedef, out)
