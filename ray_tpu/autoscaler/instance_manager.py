"""Instance lifecycle FSM.

Capability parity with the reference's autoscaler v2 instance manager
(reference: python/ray/autoscaler/v2/instance_manager/instance_manager.py:29
InstanceManager — instances move QUEUED → REQUESTED → ALLOCATED →
RAY_RUNNING → RAY_STOPPING → TERMINATED with status-transition asserts
:186-202, reconciling cloud state against demand): each instance tracks one
cloud node from launch request to termination; invalid transitions raise.
"""

from __future__ import annotations

import itertools
import time
from dataclasses import dataclass, field


class InstanceStatus:
    QUEUED = "QUEUED"
    REQUESTED = "REQUESTED"
    ALLOCATED = "ALLOCATED"
    RAY_RUNNING = "RAY_RUNNING"
    RAY_STOPPING = "RAY_STOPPING"
    TERMINATED = "TERMINATED"
    ALLOCATION_FAILED = "ALLOCATION_FAILED"


# Legal transitions (reference: the v2 FSM asserts; same shape minus the
# install states — node setup here is the provider's launch).
_TRANSITIONS: dict[str, set[str]] = {
    InstanceStatus.QUEUED: {InstanceStatus.REQUESTED},
    InstanceStatus.REQUESTED: {InstanceStatus.ALLOCATED,
                               InstanceStatus.ALLOCATION_FAILED},
    InstanceStatus.ALLOCATED: {InstanceStatus.RAY_RUNNING,
                               InstanceStatus.RAY_STOPPING,
                               InstanceStatus.TERMINATED},
    InstanceStatus.RAY_RUNNING: {InstanceStatus.RAY_STOPPING,
                                 InstanceStatus.TERMINATED},
    InstanceStatus.RAY_STOPPING: {InstanceStatus.TERMINATED},
    InstanceStatus.ALLOCATION_FAILED: set(),
    InstanceStatus.TERMINATED: set(),
}

_ids = itertools.count(1)


@dataclass
class Instance:
    instance_id: str
    node_type: str
    status: str = InstanceStatus.QUEUED
    cloud_id: str | None = None  # provider-side node id once allocated
    node_id: str | None = None  # runtime node id once RAY_RUNNING
    created_at: float = field(default_factory=time.monotonic)
    status_history: list[tuple[str, float]] = field(default_factory=list)


class InstanceManager:
    def __init__(self):
        self._instances: dict[str, Instance] = {}

    def create(self, node_type: str) -> Instance:
        inst = Instance(instance_id=f"inst-{next(_ids)}", node_type=node_type)
        inst.status_history.append((inst.status, time.monotonic()))
        self._instances[inst.instance_id] = inst
        return inst

    def transition(self, instance_id: str, new_status: str, **updates) -> Instance:
        inst = self._instances[instance_id]
        allowed = _TRANSITIONS[inst.status]
        if new_status not in allowed:
            raise ValueError(
                f"illegal instance transition {inst.status} -> {new_status} "
                f"for {instance_id} (allowed: {sorted(allowed)})")
        inst.status = new_status
        inst.status_history.append((new_status, time.monotonic()))
        for k, v in updates.items():
            setattr(inst, k, v)
        return inst

    def instances(self, statuses: tuple[str, ...] | None = None) -> list[Instance]:
        out = list(self._instances.values())
        if statuses:
            out = [i for i in out if i.status in statuses]
        return out

    def get(self, instance_id: str) -> Instance:
        return self._instances[instance_id]

    def active(self) -> list[Instance]:
        """Instances that count toward capacity (launched or launching)."""
        return self.instances((InstanceStatus.QUEUED, InstanceStatus.REQUESTED,
                               InstanceStatus.ALLOCATED,
                               InstanceStatus.RAY_RUNNING))
