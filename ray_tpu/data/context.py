"""Execution context/knobs (reference capability:
python/ray/data/context.py DataContext)."""

from __future__ import annotations

import threading
from dataclasses import dataclass


@dataclass
class DataContext:
    # rows per output block a read aims for when parallelism=-1
    target_min_rows_per_block: int = 1000
    # default read parallelism when unknown
    default_parallelism: int = 8
    # per map-stage cap on concurrently running tasks
    max_tasks_in_flight_per_stage: int = 8
    # cap on produced-but-unconsumed blocks per stage (backpressure)
    max_output_blocks_buffered: int = 16
    # cap on produced-but-unconsumed BYTES per stage (backpressure budget —
    # reference: ResourceManager object-store memory budgets). The
    # effective per-stage budget is the MIN of this and the arena-derived
    # share: object_store_capacity × object_store_budget_fraction / stages.
    max_output_bytes_buffered: int = 256 * 1024 * 1024
    # Fraction of the node's object-store arena the executor's buffered
    # outputs may collectively occupy (reference: ResourceManager
    # op-resource budgets against object_store_memory).
    object_store_budget_fraction: float = 0.5
    # shuffle fan-out (floor; see target_shuffle_partition_bytes)
    default_shuffle_partitions: int = 8
    # Spill-aware shuffle sizing (reference: push-based shuffle splits by
    # target partition size): all-to-all partition count grows with total
    # bytes so each reduce task materializes at most ~this much data in
    # worker memory — the blocks themselves live in the spilling arena, so
    # datasets larger than the object store sort without OOM.
    target_shuffle_partition_bytes: int = 64 * 1024 * 1024
    max_shuffle_partitions: int = 256
    # task resource demand for data tasks (0 CPU => don't starve trainers)
    task_num_cpus: float = 0.25

    _local = threading.local()

    @staticmethod
    def get_current() -> "DataContext":
        ctx = getattr(DataContext._local, "ctx", None)
        if ctx is None:
            ctx = DataContext()
            DataContext._local.ctx = ctx
        return ctx
