"""Collective ops: XLA backend over the 8-device CPU mesh + host backend
through actors.

Coverage modeled on the reference's collective suites (reference:
python/ray/util/collective/tests/ — allreduce/allgather/reducescatter/
broadcast/sendrecv across backends).
"""

import numpy as np
import pytest

import ray_tpu.collective as col
from ray_tpu.collective.xla_backend import XlaCollectiveGroup

multidevice = pytest.mark.multidevice


@pytest.fixture
def xla_group(cpu_mesh_devices):
    g = XlaCollectiveGroup(world_size=8, devices=cpu_mesh_devices)
    yield g
    g.destroy()


@multidevice
def test_xla_allreduce_replicated(xla_group):
    x = np.ones((8, 16), np.float32)
    out = np.asarray(xla_group.allreduce(x))
    np.testing.assert_allclose(out, x * 8)


@multidevice
def test_xla_allreduce_sharded(xla_group):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    x = np.arange(32, dtype=np.float32).reshape(8, 4)
    xs = jax.device_put(x, NamedSharding(xla_group.mesh, P("dp")))
    out = np.asarray(xla_group.allreduce(xs))
    # psum over shards: every row becomes the column-sum of all shards
    expected = np.tile(x.reshape(8, 1, 4).sum(axis=0), (8, 1))
    np.testing.assert_allclose(out, expected)


@multidevice
def test_xla_allgather(xla_group):
    x = np.arange(16, dtype=np.float32).reshape(8, 2)
    out = np.asarray(xla_group.allgather(x))
    np.testing.assert_allclose(out, x)  # gather of shards == original


@multidevice
def test_xla_reducescatter(xla_group):
    x = np.ones((8, 4), np.float32)
    out = np.asarray(xla_group.reducescatter(x))
    assert out.shape == (8, 4)
    np.testing.assert_allclose(out, 8.0 * np.ones((8, 4)))


@multidevice
def test_xla_alltoall(xla_group):
    # 8 members × 8 rows each; member i ends with chunk i from every member
    x = np.arange(64, dtype=np.float32).reshape(64, 1)
    out = np.asarray(xla_group.alltoall(x))
    expected = x.reshape(8, 8, 1).transpose(1, 0, 2).reshape(64, 1)
    np.testing.assert_allclose(out, expected)


@multidevice
def test_xla_broadcast(xla_group):
    x = np.arange(8, dtype=np.float32).reshape(8, 1)
    out = np.asarray(xla_group.broadcast(x, src_rank=3))
    np.testing.assert_allclose(out, np.full((8, 1), 3.0))


@multidevice
def test_xla_ppermute_ring(xla_group):
    x = np.arange(8, dtype=np.float32).reshape(8, 1)
    perm = [(i, (i + 1) % 8) for i in range(8)]
    out = np.asarray(xla_group.ppermute(x, perm))
    np.testing.assert_allclose(out.ravel(), np.roll(np.arange(8), 1))


@multidevice
def test_xla_barrier(xla_group):
    xla_group.barrier()  # must not hang


@multidevice
def test_api_surface(cpu_mesh_devices):
    col.init_collective_group(backend="xla", group_name="api_test",
                              devices=cpu_mesh_devices, world_size=8)
    out = np.asarray(col.allreduce(np.ones(8, np.float32), group_name="api_test"))
    np.testing.assert_allclose(out, 8 * np.ones(8))
    col.destroy_collective_group("api_test")
    with pytest.raises(ValueError):
        col.get_group("api_test")


def test_host_backend_through_actors(rt_start):
    import ray_tpu

    @ray_tpu.remote(num_cpus=1)
    def worker(rank, world):
        import ray_tpu.collective as col

        g = col.init_collective_group(world_size=world, rank=rank,
                                      backend="host", group_name=f"hg")
        s = g.allreduce(np.full(4, rank + 1, np.float32))
        gathered = g.allgather(np.full(2, rank, np.float32))
        bcast = g.broadcast(np.full(2, rank, np.float32), src_rank=1)
        g.barrier()
        return s.tolist(), gathered.tolist(), bcast.tolist()

    results = ray_tpu.get([worker.remote(r, 3) for r in range(3)], timeout=60)
    for s, gathered, bcast in results:
        assert s == [6.0, 6.0, 6.0, 6.0]  # 1+2+3
        assert gathered == [0.0, 0.0, 1.0, 1.0, 2.0, 2.0]
        assert bcast == [1.0, 1.0]


def test_host_sendrecv(rt_start):
    import ray_tpu

    @ray_tpu.remote(num_cpus=1)
    def worker(rank):
        import ray_tpu.collective as col

        g = col.init_collective_group(world_size=2, rank=rank,
                                      backend="host", group_name="p2p")
        if rank == 0:
            g.send(np.array([42.0]), dst_rank=1)
            return None
        return g.recv((1,), np.float32, src_rank=0).tolist()

    out = ray_tpu.get([worker.remote(r) for r in range(2)], timeout=60)
    assert out[1] == [42.0]


# ---------------------------------------------------------------------------
# hierarchical (multi-slice) allreduce: ICI reduce-scatter -> DCN sum ->
# ICI all-gather, with optional quantized DCN wire format
# ---------------------------------------------------------------------------

@multidevice
def test_hierarchical_allreduce_fp32_exact(cpu_mesh_devices):
    """fp32 hierarchy must match the flat allreduce bit-for-bit-tolerance-
    free: reduce-scatter + all-gather reorder sums within a slice only."""
    flat = XlaCollectiveGroup(world_size=8, devices=cpu_mesh_devices)
    hier = XlaCollectiveGroup(world_size=8, devices=cpu_mesh_devices,
                              num_slices=2)
    try:
        assert hier.hier_mesh is not None
        assert hier.hier_mesh.shape == {"dcn": 2, "ici": 4}
        for shape in ((33, 7), (128,), (5, 3, 2)):
            x = np.random.default_rng(0).standard_normal(shape)
            x = x.astype(np.float32)
            np.testing.assert_allclose(np.asarray(hier.allreduce(x)),
                                       np.asarray(flat.allreduce(x)),
                                       rtol=1e-6, atol=1e-6)
    finally:
        flat.destroy()
        hier.destroy()


@multidevice
@pytest.mark.parametrize("quant,tol", [("bf16", 5e-3), ("int8", 1e-2)])
def test_hierarchical_allreduce_quantized_tolerance(cpu_mesh_devices, quant,
                                                    tol):
    """Measured-accuracy parity for the quantized DCN stage: the summed
    result stays within the documented relative error of the exact sum
    (bf16 ~2.5e-3, int8 per-bucket ~4e-3 on gaussian payloads)."""
    flat = XlaCollectiveGroup(world_size=8, devices=cpu_mesh_devices)
    g = XlaCollectiveGroup(world_size=8, devices=cpu_mesh_devices,
                           num_slices=2, dcn_quant=quant,
                           dcn_quant_bucket=64)
    try:
        x = np.random.default_rng(1).standard_normal((57, 9)).astype(
            np.float32)
        ref = np.asarray(flat.allreduce(x))
        out = np.asarray(g.allreduce(x))
        rel = np.abs(out - ref).max() / np.abs(ref).max()
        assert rel < tol, f"{quant} rel err {rel} over budget {tol}"
        # quantization is actually happening (not silently exact)
        assert rel > 0
    finally:
        flat.destroy()
        g.destroy()


@multidevice
def test_hierarchical_group_requires_full_mesh_axis(cpu_mesh_devices):
    """A group whose axis covers only part of a multi-axis mesh must refuse
    num_slices > 1: hier_mesh re-levels the whole mesh, which would silently
    sum over non-members."""
    from ray_tpu.parallel.mesh import MeshSpec, build_mesh

    mesh = build_mesh(MeshSpec(dp=4, tp=2), cpu_mesh_devices)
    with pytest.raises(ValueError, match="span the whole mesh"):
        XlaCollectiveGroup(mesh=mesh, axis="dp", num_slices=2)


@multidevice
def test_hierarchical_group_fallbacks(cpu_mesh_devices):
    """Non-sum reductions and integer payloads keep the flat path; barrier
    still works on a hierarchical group."""
    g = XlaCollectiveGroup(world_size=8, devices=cpu_mesh_devices,
                           num_slices=2, dcn_quant="int8")
    try:
        x = np.arange(8, dtype=np.float32)
        np.testing.assert_allclose(np.asarray(g.allreduce(x, op="max")), x)
        out = np.asarray(g.allreduce(np.ones(4, np.int32)))
        np.testing.assert_array_equal(out, np.full(4, 8, np.int32))
        g.barrier()
    finally:
        g.destroy()


@multidevice
def test_hierarchy_group_via_api(cpu_mesh_devices):
    """init_collective_group forwards the multi-slice options."""
    col.init_collective_group(backend="xla", group_name="hier_api",
                              devices=cpu_mesh_devices, world_size=8,
                              num_slices=2, hierarchy=("ici", "dcn"))
    try:
        out = np.asarray(col.allreduce(np.ones(16, np.float32),
                                       group_name="hier_api"))
        np.testing.assert_allclose(out, 8 * np.ones(16))
    finally:
        col.destroy_collective_group("hier_api")


@multidevice
def test_xla_reduce_to_dst(xla_group):
    """reduce: dst member holds the reduction, others keep their input
    (per-member stack result — see XlaCollectiveGroup.reduce)."""
    x = np.full((4,), 2.0, np.float32)
    out = np.asarray(xla_group.reduce(x, dst_rank=3))
    assert out.shape == (8, 4)
    np.testing.assert_allclose(out[3], x * 8)
    for r in (0, 1, 2, 4, 5, 6, 7):
        np.testing.assert_allclose(out[r], x)


@multidevice
def test_xla_send_recv_pair(xla_group):
    x = np.arange(16, dtype=np.float32).reshape(8, 2)  # shard r = row r
    sent = xla_group.send(x, dst_rank=5, src_rank=2)
    got = np.asarray(xla_group.recv((8, 2), np.float32, src_rank=2))
    np.testing.assert_allclose(got, np.asarray(sent))
    np.testing.assert_allclose(got[5], x[2])  # dst now holds src's shard
    with pytest.raises(RuntimeError):
        xla_group.recv((8, 2), np.float32, src_rank=2)  # buffer drained
