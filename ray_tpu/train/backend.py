"""Framework backends: per-worker process-group bring-up.

Capability parity with the reference's Backend ABC + JAX backend (reference:
python/ray/train/backend.py Backend ABC; v2/jax/config.py:112 _JaxBackend —
worker 0 becomes the coordinator, every worker runs
jax.distributed.initialize(coordinator, num_procs, proc_id) :84, multi-slice
env via ray.util.tpu.get_tpu_coordinator_env_vars :147).
"""

from __future__ import annotations

import socket
from dataclasses import dataclass


@dataclass
class BackendConfig:
    backend_name: str = "noop"


class Backend:
    def on_start(self, worker_group, coordinator_addr: str | None) -> None:
        pass

    def on_shutdown(self, worker_group) -> None:
        pass


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _init_jax_distributed(coordinator_addr: str, num_processes: int,
                          process_id: int) -> None:
    """Runs ON each worker. Idempotent per process."""
    import jax

    if getattr(jax.distributed, "is_initialized", lambda: False)():
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_addr,
        num_processes=num_processes,
        process_id=process_id,
    )


@dataclass
class JaxBackendConfig(BackendConfig):
    """Bring up a jax.distributed world across the worker group.

    ``distributed=False`` (default for single-host tests) skips
    jax.distributed and leaves each worker with its local devices — gradient
    sync then goes through ray_tpu.collective's host backend instead.
    """

    backend_name: str = "jax"
    distributed: bool = False

    def make_backend(self) -> "JaxBackend":
        return JaxBackend(self)


class JaxBackend(Backend):
    def __init__(self, cfg: JaxBackendConfig):
        self.cfg = cfg

    def on_start(self, worker_group, coordinator_addr: str | None) -> None:
        if not self.cfg.distributed:
            return
        import ray_tpu

        n = len(worker_group.workers)
        # Every worker initializes against worker 0's coordinator address
        # (reference: v2/jax/config.py:84).
        ray_tpu.get([
            w._exec.remote(_init_jax_distributed, coordinator_addr, n, rank)
            for rank, w in enumerate(worker_group.workers)
        ], timeout=300)
