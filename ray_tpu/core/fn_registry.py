"""Content-addressed function/actor-class registry.

Capability parity with the reference's FunctionManager + GCS function table
(reference: python/ray/_private/function_manager.py — `export()` publishes a
pickled function under its content hash to the GCS KV once per definition;
workers `fetch_and_execute` on first sight and cache the import): a task spec
names its function by ``fn_id = sha256(fn_blob)`` instead of embedding the
cloudpickled definition, so repeat submissions ship an O(spec-header) frame
and every worker unpickles a given definition exactly once.

Three pieces live here:
- ``fn_id()``: the content address (submitters cache it next to the blob).
- ``FnCache``: the worker-side deserialized-definition cache, LRU-bounded by
  ``fn_cache_max_bytes`` (reference: function_manager's per-job function
  tables are dropped with the job; here a byte budget bounds a long-lived
  pooled worker serving many jobs).
- ``FN_NS``: the head KV namespace definitions are exported into (the head
  persists it like any KV namespace, so definitions survive head restarts).
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Any

# Head-KV namespace for exported definitions (reference: RemoteFunction
# exports land under a RemoteFunction:<job>:<hash> key in the GCS KV).
FN_NS = "__fn__"


def fn_id(fn_blob: bytes) -> str:
    """Content address of a serialized definition."""
    return hashlib.sha256(fn_blob).hexdigest()


class FnCache:
    """LRU cache of deserialized definitions, bounded by a byte budget.

    Thread-safe: worker execution threads hit it concurrently. The byte
    accounting charges each entry its serialized size (the deserialized
    callable's footprint is unknowable; the blob size is the stable proxy).
    """

    def __init__(self, max_bytes: int):
        self.max_bytes = max_bytes
        self._entries: "OrderedDict[str, tuple[Any, int]]" = OrderedDict()
        self._bytes = 0
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def get(self, key: str) -> Any | None:
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            return entry[0]

    def put(self, key: str, value: Any, nbytes: int) -> None:
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
            self._entries[key] = (value, nbytes)
            self._bytes += nbytes
            # Evict LRU-first, but never the entry just inserted (a single
            # definition larger than the whole budget must still be usable
            # for the task that fetched it).
            while self._bytes > self.max_bytes and len(self._entries) > 1:
                _, (_, n) = self._entries.popitem(last=False)
                self._bytes -= n
                self.evictions += 1

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    @property
    def nbytes(self) -> int:
        with self._lock:
            return self._bytes

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._entries), "bytes": self._bytes,
                    "hits": self.hits, "misses": self.misses,
                    "evictions": self.evictions}
