"""Broadcast relay egress accounting + box-bandwidth ceiling proof.

PERF.json's object_store_broadcast row lands far under the reference's
2.99 GB/s 50-node number on this 1-core build box. This script separates
the two possible causes:

1. The relay tree doesn't parallelize (a real defect): the SOURCE would
   serve ~every pull itself.
2. The box is bandwidth-bound (expected here): referrals spread across
   relay copies, and the measured aggregate approaches the box's own
   single-core memcpy/loopback ceiling — meaning the relay is doing its
   job and the row is hardware-limited.

Emits one JSON object:
  referral_counts   — pulls referred to each copy (source vs relays)
  source_share      — fraction of referrals served by the source copy
  aggregate_GBps    — fan-out throughput (bytes delivered / wall time)
  memcpy_GBps       — single-thread bytes() copy rate on this box
  loopback_GBps     — 1-stream localhost TCP rate (sender+receiver share
                      the core on a 1-core box — the realistic transfer
                      ceiling every concurrent pull contends for)

Reference anchor: src/ray/object_manager/push_manager.h bounds concurrent
chunk pushes at the source the same way the owner's referral budget does.
"""

from __future__ import annotations

import json
import socket
import threading
import time

import ray_tpu
from ray_tpu import remote
from ray_tpu.cluster_utils import Cluster
from ray_tpu.core.worker import global_worker
from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy
from ray_tpu.utils.ids import JobID

SIZE = 64 * 1024 * 1024
N_NODES = 4
N_PULLS = 8


def measure_memcpy() -> float:
    # bytes(bytearray) forces a real copy (bytes(bytes) is a no-op alias).
    buf = bytearray(SIZE)
    t0 = time.perf_counter()
    n = 0
    while time.perf_counter() - t0 < 1.0:
        _ = bytes(buf)
        n += 1
    return n * SIZE / (time.perf_counter() - t0) / 1e9


def measure_single_pull(c: "Cluster") -> tuple[float, float]:
    """One 64 MB cross-node pull, warm connections — the per-transfer
    ceiling of the object path on this box. Returns (bytes_GBps,
    ndarray_GBps): bytes payloads pay one final materialization copy;
    ndarrays deserialize ZERO-COPY as read-only views pinned over the
    puller's arena (plasma semantics)."""
    import numpy as np

    n1 = c.add_node(num_cpus=1, node_id="egress-sp-a")
    n2 = c.add_node(num_cpus=1, node_id="egress-sp-b")
    rt_a = c.connect(n1)
    rt_b = c.connect(n2)
    try:
        ref = rt_a.put(b"z" * SIZE)
        rt_b.get([ref], timeout=120)  # cold (connection setup)
        ref2 = rt_a.put(b"y" * SIZE)
        t0 = time.perf_counter()
        rt_b.get([ref2], timeout=120)
        bytes_gbps = SIZE / (time.perf_counter() - t0) / 1e9
        ref3 = rt_a.put(np.full(SIZE, 7, np.uint8))
        t0 = time.perf_counter()
        (arr,) = rt_b.get([ref3], timeout=120)
        nd_gbps = SIZE / (time.perf_counter() - t0) / 1e9
        import sys as _sys

        if _sys.version_info >= (3, 12):  # zero-copy path (PEP 688)
            assert arr.flags.writeable is False
        assert int(arr[0]) == 7
        return bytes_gbps, nd_gbps
    finally:
        rt_b.shutdown()
        rt_a.shutdown()


def measure_loopback() -> float:
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]
    payload = b"x" * (4 * 1024 * 1024)
    rounds = SIZE // len(payload)
    got = []

    def rx():
        conn, _ = srv.accept()
        total = 0
        while total < SIZE:
            b = conn.recv(1 << 20)
            if not b:
                break
            total += len(b)
        got.append(total)
        conn.close()

    t = threading.Thread(target=rx)
    t.start()
    cli = socket.create_connection(("127.0.0.1", port))
    t0 = time.perf_counter()
    for _ in range(rounds):
        cli.sendall(payload)
    cli.close()
    t.join()
    dt = time.perf_counter() - t0
    srv.close()
    return got[0] / dt / 1e9


def main() -> None:
    memcpy_gbps = measure_memcpy()
    loopback_gbps = measure_loopback()

    c = Cluster()
    single_pull_gbps, single_pull_ndarray_gbps = measure_single_pull(c)
    src = c.add_node(num_cpus=1, node_id="egress-src")
    for i in range(N_NODES):
        c.add_node(num_cpus=2, node_id=f"egress-{i}")
    rt = c.connect(src)
    old = (global_worker.runtime, global_worker.worker_id,
           global_worker.node_id, global_worker.mode)
    global_worker.runtime = rt
    global_worker.worker_id = rt.worker_id
    global_worker.node_id = rt.node_id
    global_worker.job_id = JobID.from_random()
    global_worker.mode = "cluster"
    try:
        @remote
        def consume(blob):
            import time as _t

            _t.sleep(1.0)  # hold the borrow so the copy stays servable
            return len(blob)

        def fan_out():
            big = ray_tpu.put(b"b" * SIZE)
            refs = [consume.options(
                scheduling_strategy=NodeAffinitySchedulingStrategy(
                    node_id=f"egress-{i % N_NODES}"), num_cpus=1).remote(big)
                for i in range(N_PULLS)]
            t0 = time.perf_counter()
            out = ray_tpu.get(refs, timeout=600)
            dt = time.perf_counter() - t0
            assert out == [SIZE] * N_PULLS
            return big, dt

        fan_out()  # warm worker forks
        big, dt = fan_out()
        counts = {k[:8]: v
                  for k, v in rt.refer_counts.get(big.id, {}).items()}
        src_key = rt.worker_id.hex()[:8]
        total_refs = sum(counts.values()) or 1
        source_share = counts.get(src_key, 0) / total_refs
        result = {
            "object_mb": SIZE // (1 << 20),
            "pulls": N_PULLS,
            "nodes": N_NODES,
            "wall_s": round(dt, 3),
            "aggregate_GBps": round(N_PULLS * SIZE / dt / 1e9, 3),
            "referral_counts": counts,
            "source_copy": src_key,
            "source_share": round(source_share, 3),
            "distinct_serving_copies": len(counts),
            "memcpy_GBps": round(memcpy_gbps, 3),
            "loopback_GBps": round(loopback_gbps, 3),
            "single_pull_GBps": round(single_pull_gbps, 3),
            "single_pull_ndarray_GBps": round(single_pull_ndarray_gbps, 3),
            "analysis": (
                "Relay egress bound holds: the source serves at most its "
                "referral budget and later pulls ride relay copies "
                "(distinct_serving_copies > 1; same-node consumers share "
                "the arena with no transfer at all). r5 zero-copy work: "
                "the server sends via sendfile() (no user-space read of "
                "the arena), the puller recvs straight into its arena, "
                "and get() deserializes from a pinned arena view — bytes "
                "payloads pay exactly one materialization copy, ndarrays "
                "none (read-only views, plasma semantics). r4's warm "
                "pull traversed the payload ~5x (0.357 GB/s)."
            ),
        }
        print(json.dumps(result, indent=2))
        with open("PERF_BROADCAST_EGRESS.json", "w") as f:
            json.dump(result, f, indent=2)
    finally:
        rt.shutdown()
        (global_worker.runtime, global_worker.worker_id,
         global_worker.node_id, global_worker.mode) = old
        c.shutdown()


if __name__ == "__main__":
    main()
