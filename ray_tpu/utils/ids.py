"""Unique identifiers for cluster entities.

Semantics modeled on the reference's ID scheme (reference: src/ray/common/id.h):
every object has exactly one *owner* (the worker that created it), and the
owner's identity is embedded in the ObjectID so any holder of a ref can reach
the owner without a directory lookup. Task-return objects additionally embed
the creating task and a return index, which is what makes lineage
reconstruction possible (re-running the task deterministically re-creates the
same ObjectIDs).

This is a fresh implementation: fixed-width random ids with structured
ObjectIDs, hex round-tripping, and msgpack-friendly bytes representation.
"""

from __future__ import annotations

import itertools
import os
import threading
from typing import ClassVar

_UNIQUE_LEN = 16  # bytes of entropy for standalone ids

# Fast unique-id source: one urandom draw per process, then a counter.
# os.urandom is a syscall per call — measurable on the task-submission hot
# path (reference keeps id generation cheap for the same reason). The 8-byte
# random prefix keeps cross-process collision odds at 2^-64 per pair;
# itertools.count is atomic under the GIL.
_RAND_BASE = os.urandom(16)
_RAND64 = int.from_bytes(_RAND_BASE[8:], "little")
_COUNTER = itertools.count(int.from_bytes(os.urandom(6), "little"))
_MASK64 = (1 << 64) - 1


def _reseed_after_fork() -> None:
    # A fork()ed child inherits _RAND_BASE and the counter position and
    # would emit the parent's exact id stream — silent ObjectID/TaskID
    # collisions. Redraw the per-process entropy in the child.
    global _RAND_BASE, _RAND64, _COUNTER
    _RAND_BASE = os.urandom(16)
    _RAND64 = int.from_bytes(_RAND_BASE[8:], "little")
    _COUNTER = itertools.count(int.from_bytes(os.urandom(6), "little"))


os.register_at_fork(after_in_child=_reseed_after_fork)


def _unique_bytes(n: int) -> bytes:
    c = next(_COUNTER) & _MASK64
    if n <= 8:
        # Small ids (JobID): fold per-process entropy into the counter —
        # bare counter bits would collide across processes at ~2^-(8n/2).
        return ((c ^ _RAND64) & _MASK64).to_bytes(8, "little")[:n]
    return _RAND_BASE[: n - 8] + c.to_bytes(8, "little")


class BaseID:
    """A fixed-length binary id with hex printing and value equality."""

    SIZE: ClassVar[int] = _UNIQUE_LEN
    __slots__ = ("_bytes", "_hash")

    def __init__(self, binary: bytes):
        if len(binary) != self.SIZE:
            raise ValueError(
                f"{type(self).__name__} requires {self.SIZE} bytes, got {len(binary)}"
            )
        self._bytes = binary
        self._hash = None

    @classmethod
    def from_random(cls):
        return cls(_unique_bytes(cls.SIZE))

    @classmethod
    def from_hex(cls, hex_str: str):
        return cls(bytes.fromhex(hex_str))

    @classmethod
    def nil(cls):
        return cls(b"\x00" * cls.SIZE)

    def is_nil(self) -> bool:
        return self._bytes == b"\x00" * self.SIZE

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    def __eq__(self, other) -> bool:
        return type(other) is type(self) and other._bytes == self._bytes

    def __hash__(self) -> int:
        h = self._hash
        if h is None:
            h = self._hash = hash((type(self).__name__, self._bytes))
        return h

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self.hex()[:12]}…)"

    def __reduce__(self):
        return (type(self), (self._bytes,))


class JobID(BaseID):
    SIZE = 4


class NodeID(BaseID):
    SIZE = 16


class WorkerID(BaseID):
    SIZE = 16


class PlacementGroupID(BaseID):
    SIZE = 16


class ActorID(BaseID):
    """JobID (4) + unique (12)."""

    SIZE = 16

    @classmethod
    def of(cls, job_id: JobID) -> "ActorID":
        return cls(job_id.binary() + _unique_bytes(cls.SIZE - JobID.SIZE))

    def job_id(self) -> JobID:
        return JobID(self._bytes[: JobID.SIZE])


class TaskID(BaseID):
    """JobID (4) + unique (12). Actor-creation/method tasks derive from ActorID."""

    SIZE = 16

    @classmethod
    def of(cls, job_id: JobID) -> "TaskID":
        return cls(job_id.binary() + _unique_bytes(cls.SIZE - JobID.SIZE))

    @classmethod
    def for_actor_task(cls, actor_id: ActorID, seq_no: int, handle_nonce: bytes = b"") -> "TaskID":
        # Deterministic per (actor, handle, seq) so retries regenerate the same
        # id, while distinct handles (e.g. via get_actor) never collide.
        nonce = (handle_nonce + b"\x00" * 4)[:4]
        suffix = seq_no.to_bytes(8, "little")
        return cls(actor_id.binary()[:4] + nonce + suffix)

    def job_id(self) -> JobID:
        return JobID(self._bytes[: JobID.SIZE])


class ObjectID(BaseID):
    """TaskID (16) + return-index (4): identifies the idx'th return of a task.

    Objects created by ``put`` use a synthetic "put task" counter per worker.
    The owner address is tracked alongside in the reference-table entry rather
    than packed into the id (the reference packs a flag; we keep the id pure
    and carry the owner in object metadata — simpler and equally capable).
    """

    SIZE = 20
    _put_lock = threading.Lock()
    _put_index = 0

    @classmethod
    def for_task_return(cls, task_id: TaskID, index: int) -> "ObjectID":
        return cls(task_id.binary() + index.to_bytes(4, "little"))

    @classmethod
    def for_put(cls, worker_id: WorkerID) -> "ObjectID":
        with cls._put_lock:
            cls._put_index += 1
            idx = cls._put_index
        # Put-ids embed the worker (owner) plus a monotone counter.
        return cls(worker_id.binary()[:12] + idx.to_bytes(8, "little"))

    def task_id(self) -> TaskID:
        return TaskID(self._bytes[: TaskID.SIZE])

    def return_index(self) -> int:
        return int.from_bytes(self._bytes[TaskID.SIZE :], "little")


NIL_JOB_ID = JobID.nil()
NIL_NODE_ID = NodeID.nil()
NIL_ACTOR_ID = ActorID.nil()
