"""Memory-model-guided train-step autotuner.

Replaces hand-enumerated bench config sweeps: generate candidates across
the full configuration space the training stack supports (batch size x
remat policy — including per-layer save-lists — x ZeRO-1 sharded update x
gradient accumulation x flash/CE kernel block and chunk sizes), predict
each candidate's peak HBM with an analytic memory model (no compilation,
no execution), prune the ones that cannot fit, rank the survivors, and
measure only the top few on hardware. Predicted-vs-actual HBM is recorded
per measured candidate (actual from AOT ``memory_analysis()`` or the
``hlo_stats`` liveness estimator), and measurements persist in a JSON
cache keyed by device kind + model geometry so later rounds start from
the recorded frontier instead of re-measuring the whole space.

Grounding: "Automatic Cross-Replica Sharding of Weight Update" (ZeRO-1)
for the update-sharding dimension; EQuARX's price-from-compiled-HLO
methodology, extended from comms bytes to peak HBM (parallel/hlo_stats).
"""

from ray_tpu.autotune.model import (
    HbmPrediction,
    device_hbm_budget_bytes,
    predict_hbm,
)
from ray_tpu.autotune.search import (
    AutotuneCache,
    SearchResult,
    autotune_train_configs,
)
from ray_tpu.autotune.space import Candidate, candidate_space

__all__ = [
    "AutotuneCache",
    "Candidate",
    "HbmPrediction",
    "SearchResult",
    "autotune_train_configs",
    "candidate_space",
    "device_hbm_budget_bytes",
    "predict_hbm",
]
