"""ray_tpu.data: streaming distributed datasets (reference capability:
python/ray/data — lazy logical plan, streaming block executor, blocks as
object-store refs, per-train-worker streaming_split)."""

from __future__ import annotations

import builtins

from typing import Any

# Eagerly finish every heavy IO import while single-threaded: pyarrow and
# pandas lazily import C-extension submodules at call time (read_table pulls
# pyarrow.dataset, etc.), and concurrent first-imports of C extensions from
# parallel task threads segfault CPython's import machinery.
try:
    import pandas as _pd  # noqa: F401
    import pyarrow as _pa  # noqa: F401
    import pyarrow.csv as _pa_csv  # noqa: F401
    import pyarrow.dataset as _pa_ds  # noqa: F401
    import pyarrow.parquet as _pa_pq  # noqa: F401
except ImportError:  # pragma: no cover - optional IO deps
    pass

from ray_tpu.data.block import Block, BlockAccessor
from ray_tpu.data.context import DataContext
from ray_tpu.data.dataset import Dataset, GroupedData, MaterializedDataset
from ray_tpu.data.executor import ActorPoolStrategy
from ray_tpu.data.iterator import DataIterator
from ray_tpu.data.plan import InputData, Read
from ray_tpu.data.shuffle import (
    AggregateFn,
    Count,
    Max,
    Mean,
    Min,
    Std,
    Sum,
)
from ray_tpu.data.datasource import (
    BinaryDatasource,
    CSVDatasource,
    Datasource,
    ImageDatasource,
    ItemsDatasource,
    JSONDatasource,
    NumpyDatasource,
    ParquetDatasource,
    RangeDatasource,
    ReadTask,
    SQLDatasource,
    TFRecordDatasource,
    WebDatasetDatasource,
)


def range(n: int, *, parallelism: int = -1) -> Dataset:  # noqa: A001
    return Dataset([Read(RangeDatasource(n), parallelism)])


def from_items(items: list, *, parallelism: int = -1) -> Dataset:
    return Dataset([Read(ItemsDatasource(items), parallelism)])


def read_datasource(ds: Datasource, *, parallelism: int = -1) -> Dataset:
    return Dataset([Read(ds, parallelism)])


def read_parquet(paths, *, parallelism: int = -1, **kwargs) -> Dataset:
    return Dataset([Read(ParquetDatasource(paths, **kwargs), parallelism)])


def read_csv(paths, *, parallelism: int = -1, **kwargs) -> Dataset:
    return Dataset([Read(CSVDatasource(paths, **kwargs), parallelism)])


def read_json(paths, *, parallelism: int = -1, **kwargs) -> Dataset:
    return Dataset([Read(JSONDatasource(paths, **kwargs), parallelism)])


def read_numpy(paths, *, parallelism: int = -1, **kwargs) -> Dataset:
    return Dataset([Read(NumpyDatasource(paths, **kwargs), parallelism)])


def read_binary_files(paths, *, parallelism: int = -1) -> Dataset:
    return Dataset([Read(BinaryDatasource(paths), parallelism)])


def read_images(paths, *, size: tuple[int, int] | None = None,
                mode: str = "RGB", parallelism: int = -1) -> Dataset:
    """Decoded images as an ``image`` column (reference:
    ray.data.read_images / datasource/image_datasource.py)."""
    return Dataset([Read(ImageDatasource(paths, size=size, mode=mode),
                         parallelism)])


def read_tfrecords(paths, *, raw: bool = False,
                   validate_data_crc: bool = False,
                   parallelism: int = -1) -> Dataset:
    """tf.train.Example records as columns (reference:
    ray.data.read_tfrecords) — decoded without a tensorflow dependency."""
    return Dataset([Read(TFRecordDatasource(
        paths, raw=raw, validate_data_crc=validate_data_crc), parallelism)])


def read_sql(sql: str, connection_factory, *,
             shard_column: str | None = None, num_shards: int = 1,
             parallelism: int = -1) -> Dataset:
    """Rows from any DB-API 2.0 database (reference: ray.data.read_sql).
    ``connection_factory`` is a zero-arg callable returning a fresh
    connection; with ``shard_column``/``num_shards`` the query range-
    partitions into parallel read tasks."""
    return Dataset([Read(SQLDatasource(
        sql, connection_factory, shard_column=shard_column,
        num_shards=num_shards), parallelism)])


def read_webdataset(paths, *, decode_images: bool = True,
                    parallelism: int = -1) -> Dataset:
    """WebDataset tar shards, one sample per key (reference:
    ray.data.read_webdataset). Columns named by member extension."""
    return Dataset([Read(WebDatasetDatasource(
        paths, decode_images=decode_images), parallelism)])


def read_mongo(uri: str, database: str, collection: str, *,
               pipeline: list | None = None, client_factory=None,
               num_shards: int = 1, parallelism: int = -1) -> Dataset:
    """Documents from MongoDB (reference: ray.data.read_mongo).
    ``client_factory`` injects a pymongo-shaped client; omitted, pymongo
    connects to ``uri``."""
    from ray_tpu.data.datasource import MongoDatasource

    return Dataset([Read(MongoDatasource(
        uri, database, collection, pipeline=pipeline,
        client_factory=client_factory, num_shards=num_shards), parallelism)])


def read_bigquery(table: str, *, client_factory, max_streams: int = 8,
                  parallelism: int = -1) -> Dataset:
    """BigQuery table via Storage-API-shaped read streams (reference:
    ray.data.read_bigquery); one read task per stream."""
    from ray_tpu.data.datasource import BigQueryDatasource

    return Dataset([Read(BigQueryDatasource(
        table, client_factory, max_streams=max_streams), parallelism)])


def read_delta(table_path: str, *, parallelism: int = -1) -> Dataset:
    """A Delta Lake table by replaying its _delta_log transaction log
    (reference: table-format lakes via delta-rs); one task per live file."""
    from ray_tpu.data.datasource import DeltaLakeDatasource

    return Dataset([Read(DeltaLakeDatasource(table_path), parallelism)])


def from_pandas(df) -> Dataset:
    from ray_tpu.data.block import block_from_pandas

    return from_blocks([block_from_pandas(df)])


def from_numpy(arr) -> Dataset:
    from ray_tpu.data.block import block_from_numpy

    return from_blocks([block_from_numpy(arr)])


def from_arrow(table) -> Dataset:
    from ray_tpu.data.block import block_from_arrow

    return from_blocks([block_from_arrow(table)])


def from_huggingface(hf_dataset, *, rows_per_block: int = 4096) -> Dataset:
    """A Dataset over a HuggingFace ``datasets.Dataset`` (reference:
    ray.data.from_huggingface). Rows are chunked into column-dict blocks."""
    import numpy as np

    blocks = []
    n = len(hf_dataset)
    cols = hf_dataset.column_names
    for start in builtins.range(0, n, rows_per_block):
        sl = hf_dataset[start:start + rows_per_block]
        blocks.append({c: np.asarray(sl[c]) for c in cols})
    if not blocks:
        blocks = [{c: np.asarray([]) for c in cols}]
    return from_blocks(blocks)


def from_blocks(blocks: list[Block]) -> MaterializedDataset:
    import ray_tpu

    from ray_tpu.data.shuffle import _meta

    refs_meta = [(ray_tpu.put(b), _meta(b)) for b in blocks]
    return MaterializedDataset(refs_meta)


__all__ = [
    "ActorPoolStrategy",
    "AggregateFn",
    "Block",
    "BlockAccessor",
    "Count",
    "DataContext",
    "DataIterator",
    "Dataset",
    "Datasource",
    "GroupedData",
    "MaterializedDataset",
    "Max",
    "Mean",
    "Min",
    "ReadTask",
    "Std",
    "Sum",
    "from_arrow",
    "from_blocks",
    "from_huggingface",
    "from_items",
    "from_numpy",
    "from_pandas",
    "range",
    "read_binary_files",
    "read_csv",
    "read_images",
    "read_sql",
    "read_tfrecords",
    "read_datasource",
    "read_json",
    "read_numpy",
    "read_webdataset",
    "read_mongo",
    "read_bigquery",
    "read_delta",
    "read_parquet",
]

# usage telemetry (local-only, opt-out — reference: usage_lib auto-records
# library imports)
try:
    from ray_tpu.usage import record_library_usage as _rec
    _rec("data")
except Exception:
    pass
