"""ObjectRef: a first-class distributed future.

Capability parity with the reference's ObjectRef (reference:
python/ray/includes/object_ref.pxi + src/ray/core_worker/reference_counter.h):
a ref names an object owned by exactly one worker; refs are cheap to copy and
pickle; passing a ref across process boundaries registers a *borrow* with the
owner so distributed refcounting keeps the value alive.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Any

from ray_tpu.utils.ids import ObjectID, WorkerID

if TYPE_CHECKING:
    pass


import contextlib
import threading

_refcount_off = threading.local()

# Lazily-bound process worker (import machinery is measurable at
# refs-per-task rates; worker.py imports this module, so bind on first use).
_worker_singleton = None


def _current_runtime():
    global _worker_singleton
    if _worker_singleton is None:
        from ray_tpu.core.worker import global_worker

        _worker_singleton = global_worker
    return _worker_singleton.runtime


def refcounting_suppressed() -> bool:
    """Whether this thread is inside refcount_disabled() — fused-count
    submit paths must then neither pre-take local refs nor hand out
    counted ObjectRefs (their __del__ would decrement a DIFFERENT
    runtime's counter when a proxy hosts one runtime on behalf of
    another)."""
    return getattr(_refcount_off, "on", False)


@contextlib.contextmanager
def refcount_disabled():
    """Suppress ObjectRef local-ref accounting on this thread. Used by proxy
    layers (client server) whose transient refs are pure transport — their
    pinning is explicit, and ctor/dtor accounting against the process-global
    runtime would release objects out from under the real owner."""
    _refcount_off.on = True
    try:
        yield
    finally:
        _refcount_off.on = False


class ObjectRef:
    __slots__ = ("id", "owner_id", "_worker", "_counted")

    def __init__(self, object_id: ObjectID, owner_id: WorkerID | None = None):
        self.id = object_id
        self.owner_id = owner_id
        self._worker = None  # bound lazily to the current worker
        self._counted = False
        if getattr(_refcount_off, "on", False):
            return
        # Distributed GC: every live ObjectRef instance holds one local ref;
        # release in __del__ (reference: _raylet ObjectRef dealloc decrements
        # the local count in the reference counter).
        try:
            rt = _current_runtime()
            if rt is not None:
                rt.refs.add_local_ref(object_id)
                self._counted = True
        except Exception:
            pass

    @classmethod
    def counted(cls, object_id: ObjectID,
                owner_id: WorkerID | None) -> "ObjectRef":
        """Construct a ref whose local count was ALREADY taken (fused into
        the owner registration — one refcounter lock round trip per return
        instead of two on the submit hot path). __del__ still releases."""
        ref = cls.__new__(cls)
        ref.id = object_id
        ref.owner_id = owner_id
        ref._worker = None
        ref._counted = True
        return ref

    def __del__(self):
        if not self._counted:
            return
        try:
            rt = _current_runtime()
            if rt is not None:
                rt.refs.remove_local_ref(self.id)
        except Exception:
            pass

    def hex(self) -> str:
        return self.id.hex()

    def binary(self) -> bytes:
        return self.id.binary()

    # -- future-like sugar -------------------------------------------------
    def get(self, timeout: float | None = None) -> Any:
        import ray_tpu

        return ray_tpu.get(self, timeout=timeout)

    def wait(self, timeout: float | None = None) -> bool:
        import ray_tpu

        ready, _ = ray_tpu.wait([self], num_returns=1, timeout=timeout)
        return bool(ready)

    def future(self):
        """Return a concurrent.futures.Future resolving to the value."""
        import concurrent.futures

        import ray_tpu

        fut: concurrent.futures.Future = concurrent.futures.Future()

        def _resolve():
            try:
                fut.set_result(ray_tpu.get(self))
            except BaseException as e:  # noqa: BLE001
                fut.set_exception(e)

        import threading

        threading.Thread(target=_resolve, daemon=True).start()
        return fut

    def __await__(self):
        import asyncio

        loop = asyncio.get_event_loop()
        return loop.run_in_executor(None, self.get).__await__()

    def __eq__(self, other) -> bool:
        return isinstance(other, ObjectRef) and other.id == self.id

    def __hash__(self) -> int:
        return hash(self.id)

    def __repr__(self) -> str:
        return f"ObjectRef({self.id.hex()[:16]})"

    def __reduce__(self):
        # Crossing a process boundary: the deserializing side becomes a
        # borrower (registered on arrival by the worker's deserializer).
        return (ObjectRef, (self.id, self.owner_id))


# Stream-end sentinel index: the item count of a finished streaming task is
# stored under this return index (far above any real item index).
STREAM_END_INDEX = 0xFFFFFFFE


class ObjectRefGenerator:
    """Iterator over the yields of a streaming task
    (``num_returns="streaming"``).

    Capability parity with the reference's streaming generators (reference:
    python/ray/_raylet.pyx ObjectRefGenerator; used by serve response
    streaming and ray.data blocks): each ``__next__`` blocks until the next
    yielded item is available at the owner and returns its ObjectRef. The
    stream ends when the executor stores the item count under
    STREAM_END_INDEX.
    """

    def __init__(self, task_id, owner_id: WorkerID, end_ref=None):
        from ray_tpu.utils.ids import TaskID  # noqa: F401 - typing only

        self._task_id = task_id
        self._owner_id = owner_id
        self._index = 0
        self._total: int | None = None
        # Pin the stream-end marker for the generator's lifetime — it's the
        # task's only pre-declared return, and dropping its last ObjectRef
        # would GC the sealed marker out from under the iteration.
        self._end_ref = end_ref

    def _runtime(self):
        from ray_tpu.core.worker import global_worker

        return global_worker.runtime

    def __iter__(self):
        return self

    def __next__(self) -> "ObjectRef":
        return self._next(timeout=300.0)

    def _next(self, timeout: float) -> "ObjectRef":
        import time as _time

        rt = self._runtime()
        local = getattr(rt, "_local_contains", None) or rt.store.contains
        locations = getattr(rt, "_locations", {})  # remote holders count too
        contains = lambda oid: local(oid) or oid in locations  # noqa: E731
        oid = ObjectID.for_task_return(self._task_id, self._index)
        end_oid = ObjectID.for_task_return(self._task_id, STREAM_END_INDEX)
        deadline = _time.monotonic() + timeout
        while True:
            if self._total is not None and self._index >= self._total:
                raise StopIteration
            if contains(oid):
                self._index += 1
                return ObjectRef(oid, self._owner_id)
            if self._total is None and contains(end_oid):
                end = rt.get([ObjectRef(end_oid, self._owner_id)])[0]
                if isinstance(end, BaseException):
                    raise end
                self._total = int(end)
                continue
            if _time.monotonic() >= deadline:
                raise TimeoutError(
                    f"streaming task {self._task_id.hex()[:12]} produced no "
                    f"item {self._index} in time")
            # Plain polling — constructing ObjectRefs here to use wait()
            # would add/drop local refs on ids the producer hasn't sealed
            # yet, releasing (and deleting) items as they land.
            cond = getattr(rt, "_wait_cond", None)
            if cond is not None:
                with cond:
                    cond.wait(timeout=0.02)
            else:
                _time.sleep(0.01)

    def completed(self) -> bool:
        return self._total is not None and self._index >= self._total

    def __del__(self):
        # Best-effort: release items the consumer never took (constructing
        # then dropping a ref runs the normal release path). Items produced
        # after this GC are cleaned when the owner runtime shuts down.
        try:
            rt = self._runtime()
            if rt is None:
                return
            contains = getattr(rt, "_local_contains", None) or rt.store.contains
            i = self._index
            while (self._total is None or i < self._total) and i < 1 << 20:
                oid = ObjectID.for_task_return(self._task_id, i)
                if not contains(oid):
                    break
                ObjectRef(oid, self._owner_id)  # ctor+drop => release
                i += 1
        except Exception:
            pass
