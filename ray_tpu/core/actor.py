"""Actor classes and handles.

Capability parity with the reference's actor API (reference:
python/ray/actor.py ActorClass/ActorHandle :92-240; creation via
_raylet.pyx:3590 create_actor → gcs actor FSM): ``@remote`` on a class yields
an ActorClass; ``.remote(...)`` creates the actor and returns an ActorHandle
whose method accessors submit ordered actor tasks. Named/detached actors,
max_restarts, max_concurrency, and options() per-instantiation overrides.
"""

from __future__ import annotations

from typing import Any

from ray_tpu.core.remote_function import _build_resources, extract_arg_refs
from ray_tpu.core.task_spec import ActorCreationSpec, TaskSpec
from ray_tpu.core.worker import global_worker
from ray_tpu.util import tracing
from ray_tpu.utils import serialization
from ray_tpu.utils.ids import ActorID, TaskID


_DEFAULT_ACTOR_OPTIONS = dict(
    # Actors default to ZERO lifetime CPUs (reference: actors without an
    # explicit num_cpus use 1 CPU for placement but 0 while running, so any
    # number of actors can share a node). A default of 1 starves task
    # submission: a handful of long-lived actors would hold every CPU lease
    # on the node and later tasks would wait on leases forever.
    num_cpus=0,
    num_tpus=0,
    resources=None,
    max_restarts=0,
    max_task_retries=0,
    max_concurrency=1,
    name=None,
    namespace="default",
    lifetime="non_detached",
    scheduling_strategy=None,
    runtime_env=None,
)


class ActorMethod:
    def __init__(self, handle: "ActorHandle", method_name: str, num_returns: int = 1):
        self._handle = handle
        self._method_name = method_name
        self._num_returns = num_returns

    def options(self, num_returns: int = 1):
        return ActorMethod(self._handle, self._method_name, num_returns)

    def remote(self, *args, **kwargs):
        return self._handle._submit_method(
            self._method_name, args, kwargs, num_returns=self._num_returns
        )

    def bind(self, *args, **kwargs):
        """Lazy DAG node for this method call (reference: python/ray/dag/ —
        actor.method.bind builds a ClassMethodNode instead of executing)."""
        from ray_tpu.dag.dag_node import ClassMethodNode

        return ClassMethodNode(self._handle, self._method_name, args, kwargs)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor method {self._method_name!r} cannot be called directly; use .remote()"
        )


class ActorHandle:
    def __init__(self, actor_id: ActorID, method_names: list[str] | None = None):
        import itertools
        import os as _os

        self._actor_id = actor_id
        self._method_names = method_names or []
        # Atomic under the GIL: handles are shared across threads on hot
        # paths (the serve router caches one handle per replica), and a
        # racy `+= 1` would mint duplicate seq_nos — i.e. duplicate task
        # ids and colliding return object ids.
        self._seq = itertools.count(1)
        # Distinguishes task ids from different handles to the same actor
        # (each handle has its own ordered call sequence).
        self._handle_nonce = _os.urandom(4)

    @property
    def actor_id(self) -> ActorID:
        return self._actor_id

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return ActorMethod(self, name)

    def _submit_method(self, method_name: str, args: tuple, kwargs: dict, num_returns: int = 1):
        worker = global_worker
        worker.check_connected()
        seq_no = next(self._seq)
        args_blob, arg_refs = serialization.serialize_args((args, kwargs))
        spec = TaskSpec(
            task_id=TaskID.for_actor_task(self._actor_id, seq_no, self._handle_nonce),
            job_id=worker.job_id,
            fn_blob=b"",
            args_blob=args_blob,
            arg_ref_ids=[r.id for r in arg_refs],
            arg_owner_ids=[r.owner_id for r in arg_refs],
            num_returns=num_returns,
            actor_id=self._actor_id,
            method_name=method_name,
            seq_no=seq_no,
            name=f"{method_name}",
            owner_id=worker.worker_id,
            trace_ctx=tracing.inject(),
        )
        refs = worker.runtime.submit_actor_task(spec)
        if num_returns == "streaming":
            from ray_tpu.core.object_ref import ObjectRefGenerator

            return ObjectRefGenerator(spec.task_id, worker.worker_id,
                                      end_ref=refs[0])
        return refs[0] if num_returns == 1 else refs

    def _call_fn(self, fn, *args, num_returns: int = 1):
        """Run ``fn(actor_instance, *args)`` inside the actor (internal;
        reference: __ray_call__). Used to install compiled-graph loops."""
        return self._submit_method("__rtpu_call_fn__", (fn, *args), {},
                                   num_returns=num_returns)

    def __reduce__(self):
        return (ActorHandle, (self._actor_id, self._method_names))

    def __repr__(self) -> str:
        return f"ActorHandle({self._actor_id.hex()[:12]})"


class ActorClass:
    def __init__(self, cls: type, options: dict[str, Any]):
        self._cls = cls
        self._options = {**_DEFAULT_ACTOR_OPTIONS, **options}
        self._cls_blob: bytes | None = None
        self._cls_id: str | None = None  # content address of _cls_blob

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor class {self._cls.__name__!r} cannot be instantiated directly; "
            f"use {self._cls.__name__}.remote(...)"
        )

    def options(self, **overrides) -> "ActorClass":
        # Share the serialized definition and its registry id with the copy:
        # options() that only changes resources must not re-pickle or
        # re-export an identical cls_blob (same hash → same registry entry).
        new = ActorClass(self._cls, {**self._options, **overrides})
        new._cls_blob = self._cls_blob
        new._cls_id = self._cls_id
        return new

    def remote(self, *args, **kwargs) -> ActorHandle:
        worker = global_worker
        worker.check_connected()
        if self._cls_blob is None:
            self._cls_blob = serialization.dumps_function(self._cls)
        if self._cls_id is None:
            from ray_tpu.core.fn_registry import fn_id

            self._cls_id = fn_id(self._cls_blob)
        cls_blob, cls_id = self._cls_blob, self._cls_id
        export = getattr(worker.runtime, "export_function", None)
        if export is not None:
            export(cls_id, cls_blob)
            cls_blob = b""
        else:
            cls_id = ""
        opts = self._options
        actor_id = ActorID.of(worker.job_id)
        arg_refs = extract_arg_refs(args, kwargs)
        from ray_tpu.core.remote_function import (
            _prepare_runtime_env,
            resolve_strategy,
        )

        resources, strategy = resolve_strategy(
            _build_resources(opts), opts["scheduling_strategy"])
        runtime_env = _prepare_runtime_env(worker.runtime, opts["runtime_env"])
        spec = ActorCreationSpec(
            actor_id=actor_id,
            job_id=worker.job_id,
            cls_blob=cls_blob,
            cls_id=cls_id,
            args_blob=serialization.serialize((args, kwargs)),
            arg_ref_ids=[r.id for r in arg_refs],
            resources=resources,
            max_restarts=opts["max_restarts"],
            max_task_retries=opts["max_task_retries"],
            max_concurrency=opts["max_concurrency"],
            name=opts["name"],
            namespace=opts["namespace"],
            lifetime=opts["lifetime"],
            scheduling_strategy=strategy,
            runtime_env=runtime_env,
            owner_id=worker.worker_id,
        )
        worker.runtime.create_actor(spec)
        method_names = [m for m in dir(self._cls) if not m.startswith("_")]
        return ActorHandle(actor_id, method_names)
