// Shared-memory object store: the node-local arena every worker process on a
// host attaches to.
//
// Capability parity with the reference's plasma store (reference:
// src/ray/object_manager/plasma/store.h PlasmaStore, dlmalloc.cc shm arena,
// eviction_policy.cc LRU, fling.cc fd passing). TPU-native simplifications:
// one POSIX shm segment per node (named, so clients attach by path instead of
// fd passing); all metadata lives inside the segment (robust process-shared
// mutex, open-addressed object table, boundary-tag heap) so any process can
// operate on it; eviction exposes LRU candidates to the caller, which spills
// to disk before deleting (reference: local_object_manager.h spill flow).
//
// C ABI throughout - consumed from Python via ctypes
// (ray_tpu/core/shm_store.py).

#include <cerrno>
#include <cstdint>
#include <cstring>

#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x52545055534852ULL;  // "RTPUSHR"
constexpr uint32_t kIdSize = 20;
constexpr uint64_t kAlign = 64;
constexpr uint64_t kMinSplit = 128;

enum EntryState : uint32_t {
  kEmpty = 0,
  kCreated = 1,
  kSealed = 2,
  kTombstone = 3,
  // A failed in-progress transfer that could not be freed because readers
  // still pin it (cut-through serving): memory is reclaimed by the last
  // store_release, and every new reader sees "not found".
  kAborted = 4,
};

// Return codes (keep in sync with shm_store.py).
enum Rc : int {
  kOk = 0,
  kErrExists = -1,
  kErrNotFound = -2,
  kErrOom = -3,
  kErrNotSealed = -4,
  kErrBusy = -5,
  kErrSys = -6,
  kErrTooSmall = -7,
};

struct Header {
  uint64_t magic;
  uint64_t total_size;
  uint64_t table_offset;
  uint64_t num_slots;
  uint64_t arena_offset;
  uint64_t arena_size;
  uint64_t used_bytes;     // payload bytes in live objects
  uint64_t num_objects;
  uint64_t lru_clock;
  pthread_mutex_t mutex;
};

struct Entry {
  uint8_t id[kIdSize];
  uint32_t state;
  int32_t refcount;
  uint64_t offset;  // payload offset from segment base
  uint64_t size;    // payload size
  uint64_t last_access;
  // Sealed-range watermark: bytes [0, progress) are valid while the entry
  // is still kCreated (a chunked transfer landing ranges in order). Cut-
  // through serving reads against this instead of waiting for seal; the
  // writer advances it monotonically under the store mutex, which is the
  // cross-process memory barrier making the landed bytes visible.
  uint64_t progress;
};

// Boundary-tag heap block. Payload follows the header; prev_size enables
// backward coalescing.
struct Block {
  uint64_t size;       // total block size incl. header
  uint64_t prev_size;  // size of the block immediately before (0 = first)
  uint32_t free;
  uint32_t pad_;
};

struct Store {
  uint8_t* base;
  uint64_t mapped_size;
  Header* hdr;
  Entry* table;
  char name[256];
};

inline uint64_t AlignUp(uint64_t v, uint64_t a) { return (v + a - 1) / a * a; }

inline uint64_t HashId(const uint8_t* id) {
  uint64_t h = 1469598103934665603ULL;  // FNV-1a
  for (uint32_t i = 0; i < kIdSize; ++i) {
    h ^= id[i];
    h *= 1099511628211ULL;
  }
  return h;
}

class Locker {
 public:
  explicit Locker(Store* s) : s_(s) {
    int rc = pthread_mutex_lock(&s_->hdr->mutex);
    if (rc == EOWNERDEAD) {
      // A client died holding the lock; state is still consistent for our
      // purposes (every mutation below is applied under the lock and is
      // idempotent at the object level).
      pthread_mutex_consistent(&s_->hdr->mutex);
    }
  }
  ~Locker() { pthread_mutex_unlock(&s_->hdr->mutex); }

 private:
  Store* s_;
};

Block* FirstBlock(Store* s) {
  return reinterpret_cast<Block*>(s->base + s->hdr->arena_offset);
}

Block* NextBlock(Store* s, Block* b) {
  uint8_t* nxt = reinterpret_cast<uint8_t*>(b) + b->size;
  if (nxt >= s->base + s->hdr->arena_offset + s->hdr->arena_size) return nullptr;
  return reinterpret_cast<Block*>(nxt);
}

Block* PrevBlock(Store* s, Block* b) {
  if (b->prev_size == 0) return nullptr;
  return reinterpret_cast<Block*>(reinterpret_cast<uint8_t*>(b) - b->prev_size);
}

Entry* FindEntry(Store* s, const uint8_t* id) {
  uint64_t mask = s->hdr->num_slots - 1;
  uint64_t slot = HashId(id) & mask;
  for (uint64_t probe = 0; probe < s->hdr->num_slots; ++probe) {
    Entry* e = &s->table[(slot + probe) & mask];
    if (e->state == kEmpty) return nullptr;
    if (e->state != kTombstone && memcmp(e->id, id, kIdSize) == 0) return e;
  }
  return nullptr;
}

Entry* AllocEntry(Store* s, const uint8_t* id) {
  uint64_t mask = s->hdr->num_slots - 1;
  uint64_t slot = HashId(id) & mask;
  for (uint64_t probe = 0; probe < s->hdr->num_slots; ++probe) {
    Entry* e = &s->table[(slot + probe) & mask];
    if (e->state == kEmpty || e->state == kTombstone) {
      memcpy(e->id, id, kIdSize);
      return e;
    }
  }
  return nullptr;  // table full
}

// First-fit allocate `payload` bytes; returns payload offset or 0 on OOM.
uint64_t HeapAlloc(Store* s, uint64_t payload) {
  uint64_t need = AlignUp(payload + sizeof(Block), kAlign);
  for (Block* b = FirstBlock(s); b != nullptr; b = NextBlock(s, b)) {
    if (!b->free || b->size < need) continue;
    uint64_t remainder = b->size - need;
    if (remainder >= kMinSplit + sizeof(Block)) {
      b->size = need;
      Block* split = NextBlock(s, b);
      split->size = remainder;
      split->prev_size = need;
      split->free = 1;
      Block* after = NextBlock(s, split);
      if (after != nullptr) after->prev_size = remainder;
    }
    b->free = 0;
    return reinterpret_cast<uint8_t*>(b) + sizeof(Block) - s->base;
  }
  return 0;
}

void HeapFree(Store* s, uint64_t payload_offset) {
  Block* b = reinterpret_cast<Block*>(s->base + payload_offset - sizeof(Block));
  b->free = 1;
  // Coalesce forward.
  Block* nxt = NextBlock(s, b);
  if (nxt != nullptr && nxt->free) {
    b->size += nxt->size;
    Block* after = NextBlock(s, b);
    if (after != nullptr) after->prev_size = b->size;
  }
  // Coalesce backward.
  Block* prv = PrevBlock(s, b);
  if (prv != nullptr && prv->free) {
    prv->size += b->size;
    Block* after = NextBlock(s, prv);
    if (after != nullptr) after->prev_size = prv->size;
  }
}

}  // namespace

extern "C" {

// Create (or overwrite) a store segment. Returns handle or null.
Store* store_create(const char* name, uint64_t capacity, uint64_t num_slots) {
  if (num_slots == 0) num_slots = 4096;
  // Round slots to a power of two.
  uint64_t slots = 1;
  while (slots < num_slots) slots <<= 1;

  uint64_t table_off = AlignUp(sizeof(Header), kAlign);
  uint64_t arena_off = AlignUp(table_off + slots * sizeof(Entry), kAlign);
  uint64_t total = arena_off + AlignUp(capacity, kAlign);

  shm_unlink(name);
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  if (ftruncate(fd, static_cast<off_t>(total)) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* base =
      mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (base == MAP_FAILED) {
    shm_unlink(name);
    return nullptr;
  }

  Store* s = new Store();
  s->base = static_cast<uint8_t*>(base);
  s->mapped_size = total;
  s->hdr = reinterpret_cast<Header*>(s->base);
  s->table = reinterpret_cast<Entry*>(s->base + table_off);
  strncpy(s->name, name, sizeof(s->name) - 1);

  Header* h = s->hdr;
  memset(h, 0, sizeof(Header));
  h->total_size = total;
  h->table_offset = table_off;
  h->num_slots = slots;
  h->arena_offset = arena_off;
  h->arena_size = total - arena_off;
  memset(s->table, 0, slots * sizeof(Entry));

  Block* first = FirstBlock(s);
  first->size = h->arena_size;
  first->prev_size = 0;
  first->free = 1;

  pthread_mutexattr_t attr;
  pthread_mutexattr_init(&attr);
  pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&h->mutex, &attr);
  pthread_mutexattr_destroy(&attr);

  h->magic = kMagic;  // last: marks the segment initialized
  return s;
}

// Attach to an existing segment.
Store* store_open(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return nullptr;
  }
  void* base = mmap(nullptr, static_cast<uint64_t>(st.st_size),
                    PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (base == MAP_FAILED) return nullptr;
  Store* s = new Store();
  s->base = static_cast<uint8_t*>(base);
  s->mapped_size = static_cast<uint64_t>(st.st_size);
  s->hdr = reinterpret_cast<Header*>(s->base);
  if (s->hdr->magic != kMagic) {
    munmap(base, s->mapped_size);
    delete s;
    return nullptr;
  }
  s->table = reinterpret_cast<Entry*>(s->base + s->hdr->table_offset);
  strncpy(s->name, name, sizeof(s->name) - 1);
  return s;
}

void store_close(Store* s) {
  if (s == nullptr) return;
  munmap(s->base, s->mapped_size);
  delete s;
}

int store_destroy(const char* name) { return shm_unlink(name); }

// Reserve space for an object; payload offset written to *offset_out. The
// caller memcpys into base+offset and then seals.
int store_create_object(Store* s, const uint8_t* id, uint64_t size,
                        uint64_t* offset_out) {
  Locker l(s);
  Entry* prior = FindEntry(s, id);
  if (prior != nullptr) {
    if (prior->state != kAborted || prior->refcount > 0) return kErrExists;
    // Fully-released aborted transfer: reclaim the slot for the re-pull.
    HeapFree(s, prior->offset);
    s->hdr->used_bytes -= prior->size;
    s->hdr->num_objects -= 1;
    prior->state = kTombstone;
  }
  uint64_t off = HeapAlloc(s, size == 0 ? 1 : size);
  if (off == 0) return kErrOom;
  Entry* e = AllocEntry(s, id);
  if (e == nullptr) {
    HeapFree(s, off);
    return kErrOom;
  }
  e->state = kCreated;
  e->refcount = 0;
  e->offset = off;
  e->size = size;
  e->last_access = ++s->hdr->lru_clock;
  e->progress = 0;
  s->hdr->used_bytes += size;
  s->hdr->num_objects += 1;
  *offset_out = off;
  return kOk;
}

int store_seal(Store* s, const uint8_t* id) {
  Locker l(s);
  Entry* e = FindEntry(s, id);
  if (e == nullptr || e->state == kAborted) return kErrNotFound;
  if (e->state == kSealed) return kOk;
  e->state = kSealed;
  e->progress = e->size;
  return kOk;
}

// Advance the sealed-range watermark of an in-progress (kCreated) entry.
// Monotone max; sealing sets it to the full size. The store mutex is the
// cross-process barrier: the writer memcpys the range FIRST, then publishes
// it here, so any reader that observes the watermark sees the bytes.
int store_set_progress(Store* s, const uint8_t* id, uint64_t watermark) {
  Locker l(s);
  Entry* e = FindEntry(s, id);
  if (e == nullptr || e->state == kAborted || e->state == kTombstone)
    return kErrNotFound;
  if (watermark > e->size) watermark = e->size;
  if (watermark > e->progress) e->progress = watermark;
  return kOk;
}

// Pin + locate a sealed object.
int store_get(Store* s, const uint8_t* id, uint64_t* offset_out,
              uint64_t* size_out) {
  Locker l(s);
  Entry* e = FindEntry(s, id);
  if (e == nullptr || e->state == kAborted) return kErrNotFound;
  if (e->state != kSealed) return kErrNotSealed;
  e->refcount += 1;
  e->last_access = ++s->hdr->lru_clock;
  *offset_out = e->offset;
  *size_out = e->size;
  return kOk;
}

// Pin + locate an object that may still be mid-transfer (cut-through read).
// Succeeds for kCreated and kSealed entries; *progress_out is the valid
// contiguous prefix ([0, progress) readable; == size when sealed).
int store_get_partial(Store* s, const uint8_t* id, uint64_t* offset_out,
                      uint64_t* size_out, uint64_t* progress_out) {
  Locker l(s);
  Entry* e = FindEntry(s, id);
  if (e == nullptr || e->state == kAborted || e->state == kTombstone)
    return kErrNotFound;
  e->refcount += 1;
  e->last_access = ++s->hdr->lru_clock;
  *offset_out = e->offset;
  *size_out = e->size;
  *progress_out = e->progress;
  return kOk;
}

int store_release(Store* s, const uint8_t* id) {
  Locker l(s);
  Entry* e = FindEntry(s, id);
  if (e == nullptr) return kErrNotFound;
  if (e->refcount > 0) e->refcount -= 1;
  if (e->state == kAborted && e->refcount == 0) {
    // Last cut-through reader of a failed transfer: reclaim now.
    HeapFree(s, e->offset);
    s->hdr->used_bytes -= e->size;
    s->hdr->num_objects -= 1;
    e->state = kTombstone;
  }
  return kOk;
}

// Abort an in-progress transfer: free immediately when unpinned, else mark
// kAborted so cut-through readers drain (last release frees) and every new
// lookup sees "not found".
int store_abort(Store* s, const uint8_t* id) {
  Locker l(s);
  Entry* e = FindEntry(s, id);
  if (e == nullptr || e->state == kTombstone) return kErrNotFound;
  if (e->refcount > 0) {
    e->state = kAborted;
    return kOk;
  }
  HeapFree(s, e->offset);
  s->hdr->used_bytes -= e->size;
  s->hdr->num_objects -= 1;
  e->state = kTombstone;
  return kOk;
}

int store_contains(Store* s, const uint8_t* id) {
  Locker l(s);
  Entry* e = FindEntry(s, id);
  return (e != nullptr && e->state == kSealed) ? 1 : 0;
}

int store_delete(Store* s, const uint8_t* id) {
  Locker l(s);
  Entry* e = FindEntry(s, id);
  if (e == nullptr) return kErrNotFound;
  if (e->refcount > 0) return kErrBusy;
  HeapFree(s, e->offset);
  s->hdr->used_bytes -= e->size;
  s->hdr->num_objects -= 1;
  e->state = kTombstone;
  return kOk;
}

// LRU spill candidates: sealed, unpinned objects, oldest-access first, until
// their cumulative payload covers `bytes_needed`. Writes ids consecutively
// into out_ids (capacity max_out); returns the count.
int store_evict_candidates(Store* s, uint64_t bytes_needed, uint8_t* out_ids,
                           int max_out) {
  Locker l(s);
  int count = 0;
  uint64_t gathered = 0;
  uint64_t last_taken = 0;
  while (count < max_out && gathered < bytes_needed) {
    Entry* best = nullptr;
    for (uint64_t i = 0; i < s->hdr->num_slots; ++i) {
      Entry* e = &s->table[i];
      if (e->state != kSealed || e->refcount != 0) continue;
      if (e->last_access <= last_taken) continue;  // already picked
      if (best == nullptr || e->last_access < best->last_access) best = e;
    }
    if (best == nullptr) break;
    memcpy(out_ids + count * kIdSize, best->id, kIdSize);
    last_taken = best->last_access;
    gathered += best->size;
    ++count;
  }
  return count;
}

void store_stats(Store* s, uint64_t* capacity, uint64_t* used,
                 uint64_t* num_objects) {
  Locker l(s);
  *capacity = s->hdr->arena_size;
  *used = s->hdr->used_bytes;
  *num_objects = s->hdr->num_objects;
}

uint8_t* store_base(Store* s) { return s->base; }
uint64_t store_capacity(Store* s) { return s->hdr->arena_size; }

}  // extern "C"
