"""Scaling policies: how many workers each (re)start of training gets.

Capability parity with the reference's ScalingPolicy (reference:
python/ray/train/v2/_internal/execution/scaling_policy/ — fixed.py:13
FixedScalingPolicy, elastic.py:29 ElasticScalingPolicy): fixed always asks
for ScalingConfig.num_workers; elastic re-evaluates cluster capacity on
every (re)start and picks the largest feasible world size in
[min_workers, max_workers] — after a node loss, training resumes smaller
from the latest checkpoint instead of deadlocking on unsatisfiable
placement.
"""

from __future__ import annotations

import math
from typing import Callable

from ray_tpu.train.config import ScalingConfig


class ScalingPolicy:
    def decide_world_size(self, restart_count: int) -> int:
        raise NotImplementedError


class FixedScalingPolicy(ScalingPolicy):
    def __init__(self, scaling: ScalingConfig):
        self.scaling = scaling

    def decide_world_size(self, restart_count: int) -> int:
        return self.scaling.num_workers


class ElasticScalingPolicy(ScalingPolicy):
    """Largest feasible world size within [min_workers, max_workers].

    Feasibility = how many copies of ``worker_resources()`` fit in the
    cluster's available resources right now. ``resources_fn`` is injectable
    for tests; default asks the live cluster.
    """

    def __init__(self, scaling: ScalingConfig,
                 resources_fn: Callable[[], dict] | None = None):
        self.scaling = scaling
        self.min_workers = scaling.min_workers or 1
        self.max_workers = scaling.max_workers or scaling.num_workers
        self._resources_fn = resources_fn

    def _available(self) -> dict:
        if self._resources_fn is not None:
            return self._resources_fn()
        import ray_tpu

        return ray_tpu.available_resources()

    def decide_world_size(self, restart_count: int) -> int:
        per_worker = self.scaling.worker_resources()
        avail = self._available()
        feasible = self.max_workers
        for res, need in per_worker.items():
            if need <= 0:
                continue
            feasible = min(feasible, int(math.floor(
                avail.get(res, 0.0) / need)))
        world = max(self.min_workers, min(self.max_workers, feasible))
        return world


def make_scaling_policy(scaling: ScalingConfig,
                        resources_fn=None) -> ScalingPolicy:
    if scaling.min_workers is not None or scaling.max_workers is not None:
        return ElasticScalingPolicy(scaling, resources_fn)
    return FixedScalingPolicy(scaling)
