"""R1 fixture: the PR-12 ActorHandle.seq_no bug, minimized.

A handle shared across threads minted task sequence numbers with a bare
``self._seq_no += 1`` — two racing calls could read the same value and
mint duplicate task ids. The fix in-tree was itertools.count; the rule
must flag the original shape as a non-atomic read-modify-write.
"""

import threading


class Handle:
    def __init__(self):
        self._seq_no = 0
        self._sent = []
        self._flusher = threading.Thread(target=self._flush_loop,
                                         daemon=True)
        self._flusher.start()

    def call(self, payload):
        # BUG (PR-12): non-atomic += on an attribute the flusher thread
        # also reads/mutates — duplicate seq_nos under concurrent callers.
        self._seq_no += 1
        self._sent.append((self._seq_no, payload))
        return self._seq_no

    def _flush_loop(self):
        while True:
            if self._sent:
                self._sent.pop()
                self._seq_no += 0  # touches the counter from the thread
