"""Packaging: zip local dirs, store in the cluster KV, cache per node.

Capability parity with the reference's runtime-env packaging (reference:
python/ray/_private/runtime_env/packaging.py — zip working_dir/py_modules,
content-addressed URIs stored in GCS KV, per-node URI cache
python/ray/_private/runtime_env/uri_cache.py): the driver uploads each
directory once (content hash dedupes), workers download+extract once per URI
and reuse the extraction across tasks.
"""

from __future__ import annotations

import hashlib
import io
import os
import threading
import zipfile

_KV_NS = "runtime_env_packages"
_EXCLUDE_DIRS = {"__pycache__", ".git", ".venv", "node_modules"}
MAX_PACKAGE_BYTES = 512 * 1024 * 1024


def _zip_dir(path: str, keep_base_name: bool = False) -> bytes:
    buf = io.BytesIO()
    base = os.path.abspath(path)
    # py_modules keep their top-level directory name so the extracted tree is
    # importable as the module; working_dir contents sit at the archive root.
    prefix = os.path.basename(base) if keep_base_name else ""
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        if os.path.isfile(base):
            zf.write(base, os.path.basename(base))
        else:
            for root, dirs, files in os.walk(base):
                dirs[:] = [d for d in dirs if d not in _EXCLUDE_DIRS]
                for f in files:
                    full = os.path.join(root, f)
                    rel = os.path.relpath(full, base)
                    zf.write(full, os.path.join(prefix, rel) if prefix else rel)
    data = buf.getvalue()
    if len(data) > MAX_PACKAGE_BYTES:
        raise ValueError(
            f"packaged {path!r} is {len(data)} bytes, over the "
            f"{MAX_PACKAGE_BYTES} limit")
    return data


# Driver-side memo: (abspath, keep_base, tree signature) -> uri. Repeat
# submissions with an unchanged tree skip the zip+hash entirely; a stat walk
# detects changes (reference: packaging caches by content hash per env).
_upload_memo: dict[tuple, str] = {}
_memo_lock = threading.Lock()


def _tree_signature(path: str) -> tuple:
    base = os.path.abspath(path)
    if os.path.isfile(base):
        st = os.stat(base)
        return ((os.path.basename(base), st.st_size, st.st_mtime_ns),)
    sig = []
    for root, dirs, files in os.walk(base):
        dirs[:] = [d for d in dirs if d not in _EXCLUDE_DIRS]
        for f in sorted(files):
            full = os.path.join(root, f)
            st = os.stat(full)
            sig.append((os.path.relpath(full, base), st.st_size, st.st_mtime_ns))
    return tuple(sig)


def upload_package(runtime, path: str, keep_base_name: bool = False) -> str:
    """Zip ``path`` and store it in the cluster KV; returns a ``kv://`` URI.
    Content-addressed: identical trees share one package."""
    memo_key = (os.path.abspath(path), keep_base_name, _tree_signature(path))
    with _memo_lock:
        cached = _upload_memo.get(memo_key)
    if cached is not None:
        return cached
    data = _zip_dir(path, keep_base_name=keep_base_name)
    digest = hashlib.sha256(data).hexdigest()[:32]
    uri = f"kv://{digest}"
    # Existence probe via key listing (kv_get would pull the whole blob back).
    if digest not in runtime.kv_keys(prefix=digest, ns=_KV_NS):
        runtime.kv_put(digest, data, ns=_KV_NS)
    with _memo_lock:
        _upload_memo[memo_key] = uri
    return uri


def upload_runtime_env(runtime, env: dict) -> dict:
    """Driver-side: replace local paths in the env with packaged URIs
    (no-op for entries already packaged)."""
    out = dict(env)
    wd = out.get("working_dir")
    if wd and not wd.startswith("kv://"):
        out["working_dir"] = upload_package(runtime, wd)
    mods = out.get("py_modules")
    if mods:
        out["py_modules"] = [
            m if m.startswith("kv://")
            else upload_package(runtime, m, keep_base_name=True)
            for m in mods
        ]
    return out


class UriCache:
    """Per-process extract cache: one extraction per URI (reference:
    uri_cache.py — per-node cache keyed by URI)."""

    def __init__(self, cache_dir: str | None = None):
        from ray_tpu.utils.config import get_config

        # Node-shared cache dir: every worker process on the node reuses one
        # extraction per digest (the digest names the directory, so a
        # completed extraction is valid for any process).
        self._dir = cache_dir or os.path.join(
            get_config().temp_dir, "runtime_env", "pkgs")
        self._lock = threading.Lock()
        self._extracted: dict[str, str] = {}

    def get_or_extract(self, runtime, uri: str) -> str:
        """Returns the extracted directory for a kv:// URI."""
        with self._lock:
            cached = self._extracted.get(uri)
            if cached is not None:
                return cached
        digest = uri.removeprefix("kv://")
        target = os.path.join(self._dir, digest)
        if not os.path.isdir(target):
            data = runtime.kv_get(digest, ns=_KV_NS)
            if data is None:
                raise FileNotFoundError(f"runtime_env package {uri} not in cluster KV")
            # Per-process tmp name: concurrent extractors of the same digest
            # (different workers) must not write into each other's tree.
            tmp = f"{target}.tmp.{os.getpid()}"
            os.makedirs(tmp, exist_ok=True)
            with zipfile.ZipFile(io.BytesIO(data)) as zf:
                zf.extractall(tmp)
            try:
                os.rename(tmp, target)
            except OSError:
                # Raced with another extractor of the same digest: theirs won.
                import shutil

                shutil.rmtree(tmp, ignore_errors=True)
        with self._lock:
            self._extracted[uri] = target
        return target
