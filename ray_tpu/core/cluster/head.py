"""Head control plane: node registry, actor directory/FSM, KV, pubsub,
cluster resource view, worker directory.

Capability parity with the reference's GCS server (reference:
src/ray/gcs/gcs_server.cc GcsServer::DoStart :267 wiring GcsNodeManager,
GcsActorManager (actor FSM, gcs_actor_manager.cc:308 HandleRegisterActor),
GcsHealthCheckManager (gcs_health_check_manager.h:45), internal KV
(gcs_kv_manager.cc), pubsub, GcsResourceManager): one asyncio process that is
the source of truth for cluster membership, actor placement/lifetime, and
named entities. Fault-tolerance backing store is pluggable later (the
reference optionally persists to Redis); this build keeps tables in memory.
"""

from __future__ import annotations

import asyncio
import heapq
import os
import pickle
import struct
import time
import uuid
import zlib
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Any

from ray_tpu.chaos import injector as _chaos
from ray_tpu.devtools.annotations import loop_confined
from ray_tpu.core.cluster.protocol import RpcServer, ServerConnection, spawn_task
from ray_tpu.core.fn_registry import FN_NS
from ray_tpu.util import tracing
from ray_tpu.utils.config import get_config

# WAL record header: payload length + CRC32 of the payload. The CRC is what
# makes a torn tail DETECTABLE: a power loss can land any byte prefix of the
# final write on disk, and a bare length prefix would happily frame a
# half-written or bit-rotted record for pickle to choke on (or worse,
# quietly accept). Replay stops cleanly at the first record whose checksum
# or framing fails — everything before it is intact by construction.
# Files open with a magic version header; a file WITHOUT it is a
# pre-CRC-format log (bare 4-byte length prefixes) and replays through the
# legacy parser instead of being silently mis-framed and discarded.
_WAL_HDR = struct.Struct("<II")
_WAL_MAGIC = b"RTPUWAL2"
_WAL_HDR_V1 = struct.Struct("<I")


@dataclass
class NodeInfo:
    node_id: str
    addr: tuple[str, int]  # node daemon RPC address
    resources: dict[str, float]
    labels: dict[str, str] = field(default_factory=dict)
    available: dict[str, float] = field(default_factory=dict)
    last_heartbeat: float = field(default_factory=time.monotonic)
    alive: bool = True
    pending_demands: list = field(default_factory=list)  # autoscaler feed
    transfer_addr: tuple | None = None  # native object-transfer server
    # Daemon incarnation fence: the registration epoch (daemon boot wall
    # clock) of the incarnation currently owning this node id. A register
    # carrying an OLDER epoch is a stale daemon resurrecting (partition
    # heal, paused process) and is fenced instead of double-allocated.
    epoch: float = 0.0
    # Same-host zero-copy descriptor: {"shm_name": ..., "boot_id": ...}.
    # A puller whose boot_id matches maps the node's arena directly and
    # reads objects with no wire transfer at all (plasma-style same-host
    # sharing, extended across co-hosted node daemons).
    object_plane: dict | None = None
    # Optimistic per-resource holds for placements issued within the
    # current heartbeat window (back-to-back placements must not all see
    # the node as free). Kept OUT of ``available`` so the resource views
    # the autoscaler/elastic policies read stay truthful; the next
    # heartbeat replaces them with the daemon's own accounting.
    optimistic: dict = field(default_factory=dict)

    def effective(self, key: str) -> float:
        return self.available.get(key, 0.0) - self.optimistic.get(key, 0.0)


@dataclass
class ActorInfo:
    actor_id: str
    state: str = "PENDING"  # PENDING | ALIVE | RESTARTING | DEAD
    node_id: str | None = None
    worker_addr: tuple[str, int] | None = None
    name: str | None = None
    namespace: str = "default"
    spec_blob: bytes | None = None
    resources: dict[str, float] = field(default_factory=dict)
    max_restarts: int = 0
    restarts_used: int = 0
    death_reason: str = ""
    owner_node: str | None = None
    lifetime: str = "non_detached"
    # Scheduling constraints, kept for restarts (reference: the GCS actor
    # scheduler re-applies the creation spec's strategy on reconstruction).
    node_affinity: str | None = None
    affinity_soft: bool = False
    labels: dict | None = None
    # Serialized runtime_env (JSON): the placing daemon needs it BEFORE
    # unpickling the spec — a container env changes how the worker is forked
    # (runtime_env/container.py), and the daemon must not unpickle user code.
    env_json: str = ""


@loop_confined
class HeadServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 persist_path: str | None = None):
        self.rpc = RpcServer(host, port)
        self.nodes: dict[str, NodeInfo] = {}
        self.actors: dict[str, ActorInfo] = {}
        self.named_actors: dict[tuple[str, str], str] = {}
        self.kv: dict[str, dict[str, bytes]] = {}  # namespace -> key -> value
        # worker_id -> (host, port, node_id) — node_id routes large-object
        # pulls to the holder node's native transfer server.
        self.workers: dict[str, tuple] = {}
        # Control-plane fault tolerance: durable tables reload on restart
        # (reference: GCS backed by redis_store_client.cc; raylets
        # reconnect via HandleNotifyGCSRestart, node_manager.cc:1050).
        self._persist_path = persist_path
        # Bumped on every node add/death/drain; heartbeat replies ship the
        # peer map only to daemons whose seen version is stale.
        self._membership_version = 0
        self._dirty = False
        self._persist_task: asyncio.Task | None = None
        self._write_fut = None  # in-flight executor write, if any
        self._wal_f = None  # append handle for the mutation log
        # Group-commit buffer: packed records awaiting one coalesced
        # write+flush (scheduled same-tick, or wal_group_commit_ms later).
        self._wal_buf: list[bytes] = []
        self._wal_flush_scheduled = False
        self._wal_tail_dropped = 0  # torn/corrupt tail records skipped
        self.pgs: dict[str, dict] = {}
        # Crash-consistent session identity: ``incarnation`` counts head
        # boots over this persist path (bumped + WAL-logged each boot);
        # ``boot_id`` identifies THIS process even without persistence, so
        # daemons can fence traffic from a superseded (stale) head and
        # detect an amnesiac restart. Reference: the GCS restart path the
        # raylets handle via HandleNotifyGCSRestart (node_manager.cc:1050).
        self.boot_id = uuid.uuid4().hex
        self.incarnation = 0
        self.started_ts = time.time()
        # Exactly-once head mutations: completed request ids -> recorded
        # reply, bounded (head_dedup_max), WAL-logged and snapshotted with
        # the tables they guard — a client retry after crash-before-ACK is
        # answered from the record instead of re-applied.
        self._dedup: "OrderedDict[str, Any]" = OrderedDict()
        self._fenced_registrations = 0
        self._reconcile_totals: dict[str, int] = {}
        # Head-outage estimate for the goodput ledger: the freshest
        # persisted-state mtime BEFORE this boot touches the files is the
        # last instant the previous incarnation was provably alive —
        # capture it ahead of _load_snapshot/_open_wal (opening the WAL
        # for append rewrites the mtime).
        self._down_since = self._persist_mtime() if persist_path else None
        if persist_path:
            self._load_snapshot()
            self._open_wal()
            # Group-commit ordering guarantee (default mode): drain buffered
            # WAL records before ANY response frame is written, so an ACKed
            # mutation is always at the OS first. With a timer window
            # (wal_group_commit_ms > 0) the bounded-durability trade is
            # explicit and the hook stands down.
            self.rpc.pre_reply = self._wal_pre_reply
        self.incarnation += 1
        self.restart_count = max(0, self.incarnation - 1)
        if self._wal_f is not None:
            self._log_mutation("meta", {"incarnation": self.incarnation})
        # Cluster-wide task events flushed from workers (reference:
        # GcsTaskManager bounded task-event store).
        self.task_events: deque = deque(maxlen=100_000)
        self._task_events_total = 0  # monotone append count (cursor base)
        self._events_epoch = uuid.uuid4().hex  # head incarnation id
        # Cluster telemetry (reference: the metrics agents pushing to the
        # dashboard aggregator + GcsTaskManager's span-ish task attempts):
        # per-source metric snapshots keyed by the reporter's stable source
        # id (one per process), each tagged with its node; finished spans in
        # a bounded ring. The dashboard renders /metrics from this table as
        # a federated export with a node_id label per series.
        self.telemetry: dict[str, dict] = {}  # source -> {node_id, ts, snapshot}
        self.spans: deque = deque(maxlen=50_000)
        # Tail-sampling keep gossip: trace ids any process promoted from its
        # tail ring (ended slow / shed / errored / breaker-implicated),
        # versioned so each reporter pulls only the ids minted since its
        # cursor. Bounded — an id that falls off the deque was gossiped for
        # its whole useful life (tail rings expire in ~trace_tail_ttl_s).
        self._keeps: deque = deque(maxlen=4096)  # (seq, trace_id)
        self._keep_seq = 0
        self._keep_ids: set[str] = set()  # dedup across reporters
        # Recent exemplar trace ids per (metric, deployment) tag, harvested
        # from reporter snapshots — the watchdog attaches these to serve
        # incidents so a tripped SLO rule links straight to kept traces.
        self._exemplars: dict[tuple, list] = {}
        # Per-worker train step-time/sync-time summaries (straggler
        # attribution): source -> {node_id, ts, stats: {rank: {...}}},
        # streamed inside the same report_telemetry pushes.
        self.train_stats: dict[str, dict] = {}
        # Function-registry observability (puts/gets/misses/dup_puts) —
        # the definitions themselves live in the KV under FN_NS.
        self.fn_stats: dict[str, int] = {
            "puts": 0, "dup_puts": 0, "gets": 0, "misses": 0}
        self._subs: dict[str, set[ServerConnection]] = {}  # channel -> conns
        # Coalesced pubsub fan-out (pubsub_batch_window_s): events buffer
        # per subscriber connection and one flush task ships them as a
        # single ``pub_batch`` notify per connection per window — one
        # write per subscriber per window instead of one per event.
        self._pub_buf: dict[ServerConnection, list] = {}
        self._pub_flush_task: asyncio.Task | None = None
        self._node_conns: dict[str, ServerConnection] = {}
        # Scheduler fast path (thousand-node head): indexed views of the
        # node table so placement and bundle assignment stop linearly
        # scanning self.nodes per decision. ``_cpu_heap`` is a LAZY max-heap
        # of (-effective_cpu, node_id); ``_cpu_free`` holds each node's
        # current key, so stale heap entries (superseded key, dead node)
        # are detected and discarded at pop time. ``_free_sum`` caches
        # sum(available.values()) for _assign_bundles' PACK ordering;
        # ``_label_index`` is the inverted (key, value) -> node_ids map
        # behind label-constrained placement. All maintained by
        # _sched_touch at every mutation site; reads are gated by
        # indexed_scheduler_enabled (linear scan kept for parity tests).
        self._cpu_heap: list[tuple[float, str]] = []
        self._cpu_free: dict[str, float] = {}
        self._free_sum: dict[str, float] = {}
        self._label_index: dict[tuple[str, str], set[str]] = {}
        # Head self-metrics (saturation diagnosis at fleet scale): event
        # loop lag sampled by _self_metrics_loop, plus per-RPC-method
        # rate/latency computed from rpc.counts/rpc.stats deltas.
        self.loop_lag_s = 0.0
        self.loop_lag_max_s = 0.0
        self._rpc_rates: dict[str, dict] = {}
        self._self_metrics_task: asyncio.Task | None = None
        self._register_handlers()
        self._health_task: asyncio.Task | None = None
        self.placement_groups = None  # attached by placement_group module
        # Always-on health watchdog (ray_tpu/observability): rolling
        # time-series store fed by the delta samples piggybacked on
        # report_telemetry, streaming detectors, incident assembly with
        # targeted profile captures. None when the gate is off.
        self.watchdog = None
        if get_config().watchdog_enabled:
            from ray_tpu.observability.watchdog import Watchdog

            self.watchdog = Watchdog(
                train_stats_fn=lambda: self.train_stats,
                nodes_fn=lambda: self.nodes,
                profile_fn=self._watchdog_profile,
                exemplars_fn=self.exemplar_traces)
        # Goodput rollup store (observability/goodput.py): ingests the
        # run-level event legs piggybacked on report_telemetry, rolls the
        # fleet up from the train-stats rows above, exports goodput_*
        # gauges, and runs the badput-over-threshold rule.
        self.goodput = None
        if get_config().goodput_enabled:
            from ray_tpu.observability.goodput import GoodputStore

            self.goodput = GoodputStore()

    def _persist_mtime(self) -> float | None:
        """Freshest mtime across the snapshot + WAL segments (the
        previous incarnation's last observable write), None when nothing
        persisted yet (first boot)."""
        newest = None
        for path in (self._persist_path, self._persist_path + ".wal",
                     self._persist_path + ".wal.old"):
            try:
                ts = os.path.getmtime(path)
            except OSError:
                continue
            if newest is None or ts > newest:
                newest = ts
        return newest

    # ------------------------------------------------------------------ wiring
    def _register_handlers(self):
        r = self.rpc.register
        r("register_node", self._register_node)
        r("heartbeat", self._heartbeat)
        r("drain_node", self._drain_node)
        r("list_nodes", self._list_nodes)
        r("register_worker", self._register_worker)
        r("resolve_worker", self._resolve_worker)
        r("resolve_workers", self._resolve_workers)
        r("register_actor", self._register_actor)
        r("actor_ready", self._actor_ready)
        r("actor_failed", self._actor_failed)
        r("get_actor_info", self._get_actor_info)
        r("get_named_actor", self._get_named_actor)
        r("kill_actor", self._kill_actor)
        r("fn_put", self._fn_put)
        r("fn_get", self._fn_get)
        r("kv_put", self._kv_put)
        r("kv_get", self._kv_get)
        r("kv_del", self._kv_del)
        r("kv_keys", self._kv_keys)
        r("subscribe", self._subscribe)
        r("cluster_resources", self._cluster_resources)
        r("available_resources", self._available_resources)
        r("state_snapshot", self._state_snapshot)
        r("report_task_events", self._report_task_events)
        r("get_task_events", self._get_task_events)
        r("report_telemetry", self._report_telemetry)
        r("get_telemetry", self._get_telemetry)
        r("get_spans", self._get_spans)
        r("get_timeseries", self._get_timeseries)
        r("get_incidents", self._get_incidents)
        r("watchdog_status", self._watchdog_status)
        r("profile_cluster", self._profile_cluster)
        r("chaos", self._chaos_cluster)
        r("stack_cluster", self._stack_cluster)
        r("device_memory", self._device_memory)
        r("get_train_stats", self._get_train_stats)
        r("get_goodput", self._get_goodput)
        r("cluster_load", self._cluster_load)
        r("create_placement_group", self._create_pg)
        r("remove_placement_group", self._remove_pg)
        r("placement_group_state", self._pg_state)
        r("head_status", self._head_status)
        r("rpc_counts", self._rpc_counts)
        r("placement_fenced", self._placement_fenced)
        self.rpc.on_disconnect = self._on_disconnect
        self._daemon_clients: dict[str, Any] = {}

    async def start(self) -> tuple[str, int]:
        addr = await self.rpc.start()
        loop = asyncio.get_running_loop()
        self._health_task = loop.create_task(self._health_loop())
        if get_config().head_metrics_period_s > 0:
            self._self_metrics_task = loop.create_task(
                self._self_metrics_loop())
        if self._persist_path:
            self._persist_task = loop.create_task(self._persist_loop())
        if self.watchdog is not None:
            self.watchdog.start()
            if self.restart_count > 0:
                # A control-plane restart is an incident an operator wants
                # in the same timeline as the anomalies it may explain —
                # lightweight (no profile capture), never a detector trip.
                self.watchdog.record_event(
                    "head_restart",
                    f"head restarted (incarnation {self.incarnation}, "
                    f"{self._wal_tail_dropped} torn WAL tail record(s) "
                    "dropped)",
                    detail={"incarnation": self.incarnation,
                            "boot_id": self.boot_id,
                            "restart_count": self.restart_count})
        if self.goodput is not None and self.restart_count > 0:
            # Fleet-level head_outage badput: the gap between the previous
            # incarnation's last persisted write and this boot. Workers
            # keep stepping through a head outage, so this is stamped with
            # run=None (fleet rollup only) rather than charged to a run.
            outage = 0.0
            if self._down_since is not None:
                outage = max(0.0, self.started_ts - self._down_since)
            self.goodput.stamp(
                "head_outage", None, outage,
                chips=float(max(1, len(self.nodes))),
                start_ts=self._down_since,
                detail={"incarnation": self.incarnation,
                        "boot_id": self.boot_id,
                        "restart_count": self.restart_count})
        return addr

    async def stop(self):
        self._flush_wal()  # no buffered mutation outlives the server
        if self.watchdog is not None:
            self.watchdog.stop()
        if self._health_task:
            self._health_task.cancel()
        if self._self_metrics_task:
            self._self_metrics_task.cancel()
        if self._pub_flush_task is not None:
            self._pub_flush_task.cancel()
        if self._persist_task:
            self._persist_task.cancel()
            if self._write_fut is not None:
                # Never two writers on the same .tmp path: wait out the
                # in-flight executor write before the final flush.
                try:
                    await self._write_fut
                except Exception:
                    pass
            if self._dirty:
                self._dirty = False
                self._write_snapshot(self._snapshot_state())
        await self.rpc.stop()

    # ---------------------------------------------------------- persistence
    # Durability model (reference: the GCS persists PER MUTATION through
    # redis_store_client.cc; a crash between writes loses nothing): every
    # mutation appends a record to a write-ahead log, and the periodic
    # snapshot compacts it. Records are GROUP-COMMITTED: a mutation buffers
    # its record and one coalesced write+flush covers every record buffered
    # since the last flush. In the default mode the rpc layer's pre_reply
    # hook (_wal_pre_reply) drains the buffer BEFORE any response frame is
    # written, so a client never observes an ACK whose record isn't at the
    # OS — and a burst of mutations answered in one tick still pays one
    # write. wal_group_commit_ms > 0 switches to a timer window for
    # write-bound churn: ACKs may then precede their records by up to the
    # window (redis appendfsync-everysec makes the same trade). Only a
    # whole-machine power loss can drop the un-fsynced tail. Restart =
    # load snapshot, then replay <path>.wal.old + .wal.
    def mark_dirty(self) -> None:
        self._dirty = True

    def _log_mutation(self, kind: str, *args) -> None:
        """Buffer one durable mutation record and mark the snapshot stale."""
        self._dirty = True
        if self._wal_f is None:
            return
        try:
            rec = pickle.dumps((kind, args))
        except Exception:
            return  # durability is best-effort; the snapshot still lands
        self._wal_buf.append(_WAL_HDR.pack(len(rec), zlib.crc32(rec)) + rec)
        if self._wal_flush_scheduled:
            return
        self._wal_flush_scheduled = True
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            self._flush_wal()  # off-loop caller (init replay): write now
            return
        ms = get_config().wal_group_commit_ms
        if ms > 0:
            loop.call_later(ms / 1000.0, self._flush_wal)
        else:
            loop.call_soon(self._flush_wal)

    def _wal_pre_reply(self) -> None:
        if self._wal_buf and get_config().wal_group_commit_ms <= 0:
            self._flush_wal()

    def _flush_wal(self) -> None:
        """One coalesced append for every record buffered since the last
        flush (the group commit)."""
        self._wal_flush_scheduled = False
        if not self._wal_buf:
            return
        data = b"".join(self._wal_buf)
        self._wal_buf.clear()
        if self._wal_f is None:
            return
        try:
            self._wal_f.write(data)
            self._wal_f.flush()
        except Exception:
            pass  # best-effort; the snapshot still lands

    def _open_wal(self) -> None:
        import os

        os.makedirs(os.path.dirname(os.path.abspath(self._persist_path)),
                    exist_ok=True)
        cur = self._persist_path + ".wal"
        # Upgrade-in-place: never APPEND current-format records to a
        # legacy (pre-magic) log — a mixed file would mis-frame on
        # replay. Retire the legacy segment into .wal.old (already
        # replayed by _load_snapshot; the next snapshot compacts it away)
        # and start a fresh current-format log.
        try:
            with open(cur, "rb") as f:
                head8 = f.read(len(_WAL_MAGIC))
            if head8 and head8 != _WAL_MAGIC:
                old = self._persist_path + ".wal.old"
                if os.path.exists(old):
                    with open(old, "ab") as dst, open(cur, "rb") as src:
                        dst.write(src.read())
                    os.remove(cur)
                else:
                    os.replace(cur, old)
        except FileNotFoundError:
            pass
        except Exception:
            pass  # unreadable: the append below starts a fresh segment
        self._wal_f = open(cur, "ab")
        if self._wal_f.tell() == 0:
            self._wal_f.write(_WAL_MAGIC)
            self._wal_f.flush()

    def _rotate_wal(self) -> None:
        """Called at snapshot-copy time ON THE LOOP THREAD: the snapshot
        absorbs all state up to this instant, so records before it move to
        .wal.old (deleted once the snapshot write succeeds; still replayed
        after a crash mid-write)."""
        import os

        if self._wal_f is None:
            return
        self._flush_wal()  # buffered records belong to the closing segment
        try:
            self._wal_f.close()
            old = self._persist_path + ".wal.old"
            cur = self._persist_path + ".wal"
            if os.path.exists(old):
                # A previous snapshot write FAILED: .wal.old still holds
                # mutations covered by no snapshot. Append, never clobber —
                # os.replace here would silently drop them.
                with open(old, "ab") as dst, open(cur, "rb") as src:
                    dst.write(src.read())
                os.remove(cur)
            else:
                os.replace(cur, old)
        except Exception:
            pass
        self._open_wal()

    def _replay_wal(self) -> None:
        """Roll the mutation log forward over the loaded snapshot. Torn-
        tail tolerant: a power loss can leave any byte prefix of the final
        group-commit write (truncated header, truncated payload, or a
        bit-rotted record) — replay verifies each record's CRC and stops
        CLEANLY at the first bad one instead of raising mid-load, keeping
        the intact prefix. Skipped tail records are counted in
        ``_wal_tail_dropped`` (surfaced via head_status)."""
        import os

        for suffix in (".wal.old", ".wal"):
            path = self._persist_path + suffix
            if not os.path.exists(path):
                continue
            try:
                with open(path, "rb") as f:
                    data = f.read()
            except Exception:
                continue
            if data.startswith(_WAL_MAGIC):
                self._replay_records(data, len(_WAL_MAGIC))
            else:
                # Pre-magic log written before the CRC format landed:
                # replay it with the legacy framing rather than silently
                # discarding every post-snapshot mutation as a "torn
                # tail". _open_wal retires the file so nothing current-
                # format is ever appended to it.
                self._replay_records_v1(data)

    def _replay_records(self, data: bytes, off: int) -> None:
        hdr = _WAL_HDR.size
        while off + hdr <= len(data):
            n, crc = _WAL_HDR.unpack_from(data, off)
            start = off + hdr
            if start + n > len(data):
                self._wal_tail_dropped += 1
                break  # truncated tail record (crash mid-append)
            payload = data[start:start + n]
            if zlib.crc32(payload) != crc:
                # Bit-flipped / torn record: nothing after it can be
                # trusted to frame correctly either — stop here.
                self._wal_tail_dropped += 1
                break
            try:
                kind, args = pickle.loads(payload)
                self._apply_mutation(kind, args)
            except Exception:
                self._wal_tail_dropped += 1
                break  # corrupt tail: stop replay, keep what we have
            off = start + n

    def _replay_records_v1(self, data: bytes) -> None:
        """Legacy (pre-CRC) framing: ``<I len><pickle>``. Same clean-stop
        discipline, minus the checksum the old format never had. A
        failed-snapshot rotation can append a current-format segment onto
        a legacy ``.wal.old`` — the embedded magic switches parsers."""
        off = 0
        hdr = _WAL_HDR_V1.size
        while off + hdr <= len(data):
            if data[off:off + len(_WAL_MAGIC)] == _WAL_MAGIC:
                return self._replay_records(data, off + len(_WAL_MAGIC))
            (n,) = _WAL_HDR_V1.unpack_from(data, off)
            start = off + hdr
            if start + n > len(data):
                self._wal_tail_dropped += 1
                break
            try:
                kind, args = pickle.loads(data[start:start + n])
                self._apply_mutation(kind, args)
            except Exception:
                self._wal_tail_dropped += 1
                break
            off = start + n

    def _apply_mutation(self, kind: str, args: tuple) -> None:
        if kind == "actor":
            aid, info = args
            self.actors[aid] = info
            if info.name:
                key = (info.namespace, info.name)
                if info.state == "DEAD":
                    self.named_actors.pop(key, None)
                else:
                    self.named_actors[key] = aid
        elif kind == "worker":
            wid, row = args
            self.workers[wid] = tuple(row)
        elif kind == "kv_put":
            ns, key, value = args
            self.kv.setdefault(ns, {})[key] = value
        elif kind == "kv_del":
            ns, key = args
            self.kv.get(ns, {}).pop(key, None)
        elif kind == "pg":
            pg_id, pg = args
            self.pgs[pg_id] = pg
        elif kind == "pg_del":
            self.pgs.pop(args[0], None)
        elif kind == "worker_del":
            self.workers.pop(args[0], None)
        elif kind == "dedup":
            req_id, reply = args
            self._dedup[req_id] = reply
            self._bound_dedup()
        elif kind == "meta":
            self.incarnation = int(args[0].get(
                "incarnation", self.incarnation))

    def _snapshot_state(self) -> dict:
        """Copy on the loop thread — the executor pickles the copy while the
        loop keeps mutating the live tables. Rotating the WAL here (same
        instant, same thread) keeps log and snapshot exactly aligned."""
        import copy

        self._rotate_wal()
        return {
            "actors": dict(self.actors),
            "named_actors": dict(self.named_actors),
            "kv": copy.deepcopy(self.kv),
            "workers": dict(self.workers),
            "pgs": copy.deepcopy(self.pgs),
            # Session + dedup state compact with the tables they guard: a
            # post-snapshot retry of a pre-snapshot mutation must still
            # find its record.
            "incarnation": self.incarnation,
            "dedup": list(self._dedup.items()),
        }

    def _write_snapshot(self, state: dict) -> None:
        import os
        import pickle

        tmp = self._persist_path + ".tmp"
        os.makedirs(os.path.dirname(os.path.abspath(self._persist_path)),
                    exist_ok=True)
        with open(tmp, "wb") as f:
            pickle.dump(state, f)
        os.replace(tmp, self._persist_path)  # atomic swap
        # The snapshot now covers every record rotated into .wal.old.
        try:
            os.remove(self._persist_path + ".wal.old")
        except OSError:
            pass

    def _load_snapshot(self) -> None:
        import os
        import pickle

        if not os.path.exists(self._persist_path):
            # No snapshot yet — but a WAL may exist (crash before the first
            # compaction); replay it into the empty tables.
            self._replay_wal()
            return
        try:
            with open(self._persist_path, "rb") as f:
                snap = pickle.load(f)
        except Exception:
            # A corrupt snapshot must not crash-loop the control plane:
            # start empty (nodes/workers re-register) and overwrite it.
            self._dirty = True
            self._replay_wal()
            return
        self.actors = snap.get("actors", {})
        self.named_actors = snap.get("named_actors", {})
        self.kv = snap.get("kv", {})
        self.workers = snap.get("workers", {})
        self.pgs = snap.get("pgs", {})
        self.incarnation = int(snap.get("incarnation", 0))
        self._dedup = OrderedDict(snap.get("dedup") or ())
        # Restored actors keep their last known addresses; nodes re-register
        # and the health loop culls anything whose node never returns.
        # Then roll forward mutations logged after the snapshot was cut.
        self._replay_wal()

    async def _persist_loop(self):
        while True:
            await asyncio.sleep(0.2)
            if self._dirty:
                # Clear BEFORE snapshotting: a mutation landing during the
                # write re-marks dirty and gets the next tick (clearing
                # after would erase that mark and lose the mutation).
                self._dirty = False
                state = self._snapshot_state()
                try:
                    self._write_fut = asyncio.get_running_loop().\
                        run_in_executor(None, self._write_snapshot, state)
                    await self._write_fut
                except Exception:
                    self._dirty = True  # next tick retries
                finally:
                    self._write_fut = None

    # ------------------------------------------------------- mutation dedup
    # Exactly-once retries (reference: the GCS answers retried idempotent
    # mutations from its persisted tables): clients stamp state-changing
    # RPCs with a request id; the completed reply is recorded in a bounded
    # OrderedDict that is WAL-logged + snapshotted ALONGSIDE the mutation
    # it guards, so a retry after crash-before-ACK — against the restarted
    # head — is answered from the record instead of re-applied. The record
    # rides the same group-commit flush as the mutation, and the pre-reply
    # hook guarantees both are at the OS before the client can see an ACK.
    def _bound_dedup(self) -> None:
        bound = max(16, get_config().head_dedup_max)
        while len(self._dedup) > bound:
            self._dedup.popitem(last=False)

    def _dedup_get(self, req_id: str):
        """Recorded reply for a completed mutation request id, or None."""
        if not req_id:
            return None
        return self._dedup.get(req_id)

    def _dedup_put(self, req_id: str, reply):
        """Record (and WAL-log) the final reply for ``req_id``; returns
        the reply so handlers can ``return self._dedup_put(rid, out)``."""
        if req_id:
            self._dedup[req_id] = reply
            self._bound_dedup()
            self._log_mutation("dedup", req_id, reply)
        return reply

    # --------------------------------------------------------------- chaos
    async def _chaos_die(self) -> None:
        """Abrupt control-plane death (chaos ``head.tick`` kill): cancel
        the background loops and drop off the network with NO final WAL
        or snapshot flush — un-ACKed buffered records die here, exactly
        like kill -9. Everything a client ever saw ACKed is already at
        the OS (group commit pre-reply ordering), so a restart over the
        same persist path must come back complete from snapshot + WAL
        replay. Works for in-process heads (tests/devbench, where
        os._exit would take the whole interpreter) and real head
        processes alike."""
        if self.watchdog is not None:
            try:
                self.watchdog.stop()
            except Exception:
                pass
        for t in (self._health_task, self._persist_task,
                  self._self_metrics_task, self._pub_flush_task):
            if t is not None and t is not asyncio.current_task():
                t.cancel()
        self._wal_buf.clear()  # un-ACKed records: lost, as in a crash
        self._wal_flush_scheduled = True  # disarm any queued flush callback
        self._wal_f = None
        for node_id in list(self._daemon_clients):
            self._drop_daemon_client(node_id)
        try:
            await self.rpc.stop()
        except Exception:
            pass

    async def _head_status(self, conn: ServerConnection):
        """Control-plane session facts for `ray_tpu status` / the state
        API: who this head is (incarnation/boot), how long it has been up,
        how many times it has come back, and the fault-tolerance odometers
        (dedup table, torn-tail drops, fenced registrations, reconcile
        repairs)."""
        return {
            "incarnation": self.incarnation,
            "boot_id": self.boot_id,
            "started_ts": self.started_ts,
            "uptime_s": round(time.time() - self.started_ts, 3),
            "restart_count": self.restart_count,
            "persistent": self._persist_path is not None,
            "dedup_entries": len(self._dedup),
            "wal_tail_dropped": self._wal_tail_dropped,
            "fenced_registrations": self._fenced_registrations,
            "reconcile": dict(self._reconcile_totals),
            "nodes_alive": sum(1 for n in self.nodes.values() if n.alive),
            "nodes_total": len(self.nodes),
            "actors": len(self.actors),
            # Saturation self-metrics (see _self_metrics_loop): how far
            # behind the event loop is running, and which RPC methods are
            # eating it (rate + mean/max handler latency over the last
            # sample window).
            "loop_lag_s": round(self.loop_lag_s, 6),
            "loop_lag_max_s": round(self.loop_lag_max_s, 6),
            "rpc": dict(self._rpc_rates),
        }

    async def _self_metrics_loop(self):
        """Head saturation self-observation: samples event-loop lag (the
        gap between when a timer should fire and when it actually does —
        the first thing that degrades when the head saturates) and turns
        rpc.counts/rpc.stats deltas into per-method rate + latency. Lag
        lands in the watchdog series store as ``head_loop_lag_s`` so the
        scale bench (and incident timelines) can chart it; the per-method
        table is served from _head_status for `ray_tpu status`."""
        loop = asyncio.get_running_loop()
        period = get_config().head_metrics_period_s
        prev_counts: dict[str, int] = dict(self.rpc.counts)
        prev_stats = {m: list(s) for m, s in self.rpc.stats.items()}
        prev_t = loop.time()
        while True:
            target = loop.time() + period
            await asyncio.sleep(period)
            now = loop.time()
            self.loop_lag_s = max(0.0, now - target)
            if self.loop_lag_s > self.loop_lag_max_s:
                self.loop_lag_max_s = self.loop_lag_s
            window = max(1e-9, now - prev_t)
            prev_t = now
            rates: dict[str, dict] = {}
            counts = dict(self.rpc.counts)
            stats = {m: list(s) for m, s in self.rpc.stats.items()}
            for m, c in counts.items():
                dc = c - prev_counts.get(m, 0)
                if dc <= 0:
                    continue
                row = {"rate_hz": round(dc / window, 3)}
                st, pv = stats.get(m), prev_stats.get(m, [0, 0.0, 0.0])
                if st is not None and st[0] > pv[0]:
                    row["mean_ms"] = round(
                        (st[1] - pv[1]) / (st[0] - pv[0]) * 1000.0, 3)
                    row["max_ms"] = round(st[2] * 1000.0, 3)
                rates[m] = row
            prev_counts, prev_stats = counts, stats
            self._rpc_rates = rates
            if self.watchdog is not None:
                try:
                    self.watchdog.store.append(
                        "head", "head_loop_lag_s", {}, self.loop_lag_s)
                except Exception:
                    pass

    async def _rpc_counts(self, conn: ServerConnection):
        """Per-method inbound frame odometer of this head's RPC server.
        Benches diff two snapshots to attribute control-plane load — e.g.
        the compiled-graph bench proves direct channels stop issuing
        ``kv_*`` traffic per step (this very call shows up in the delta, so
        diff-takers subtract their own probes)."""
        return dict(self.rpc.counts)

    # ------------------------------------------------------------------ pubsub
    # (reference: src/ray/pubsub long-poll channels; here: server-push over the
    # persistent connection — same delivery guarantees for connected subs)
    async def _subscribe(self, conn: ServerConnection, channel: str):
        self._subs.setdefault(channel, set()).add(conn)
        return True

    async def publish(self, channel: str, **payload):
        subs = self._subs.get(channel)
        if not subs:
            return
        window = get_config().pubsub_batch_window_s
        if window <= 0:
            # Unbatched path: one awaited notify per subscriber per event.
            dead = []
            for conn in list(subs):
                try:
                    await conn.notify("pub", channel=channel, payload=payload)
                except Exception:
                    dead.append(conn)
            for c in dead:
                subs.discard(c)
            return
        # Coalesced fan-out: buffer per subscriber; ONE flush task per
        # window ships each connection's events as a single ``pub_batch``
        # notify, connections in parallel. An event burst (lease storm
        # killing a node → n actor_events) costs each subscriber one
        # write instead of one per event — and the head's loop one
        # gather instead of n serial drains.
        for conn in subs:
            self._pub_buf.setdefault(conn, []).append(
                {"channel": channel, "payload": payload})
        if self._pub_flush_task is None or self._pub_flush_task.done():
            self._pub_flush_task = spawn_task(self._pub_flush(window))

    async def _pub_flush(self, window: float):
        await asyncio.sleep(window)
        buf, self._pub_buf = self._pub_buf, {}
        if not buf:
            return
        conns = list(buf)
        results = await asyncio.gather(
            *(c.notify("pub_batch", events=buf[c]) for c in conns),
            return_exceptions=True)
        for conn, res in zip(conns, results):
            if isinstance(res, BaseException):
                for subs in self._subs.values():
                    subs.discard(conn)

    def _on_disconnect(self, conn: ServerConnection):
        for subs in self._subs.values():
            subs.discard(conn)
        self._pub_buf.pop(conn, None)
        node_id = conn.meta.get("node_id")
        if node_id and self._node_conns.get(node_id) is conn:
            # Node daemon connection dropped: mark suspect; health loop decides.
            info = self.nodes.get(node_id)
            if info:
                info.last_heartbeat = -1e18  # force failure at next check
                # Failure-detection fast path: a dead daemon process closes
                # its sockets immediately, so after a short grace (absorbing
                # reconnect blips) declare the node dead NOW instead of
                # waiting out heartbeat aging — cuts node-death detection
                # from up to health_check_period_s * threshold to the grace.
                grace = get_config().node_disconnect_grace_s
                if grace >= 0:
                    spawn_task(self._confirm_node_death(node_id, conn, grace))

    async def _confirm_node_death(self, node_id: str,
                                  conn: ServerConnection,
                                  grace: float) -> None:
        await asyncio.sleep(grace)
        info = self.nodes.get(node_id)
        if (
            info is None or not info.alive
            or self._node_conns.get(node_id) is not conn
            or info.last_heartbeat > 0  # re-registered / heartbeat landed
        ):
            return
        await self._declare_node_dead(node_id)

    # ------------------------------------------------------------------ nodes
    async def _register_node(
        self, conn: ServerConnection, node_id: str, host: str, port: int,
        resources: dict, labels: dict | None = None,
        transfer_addr: list | None = None,
        object_plane: dict | None = None,
        epoch: float = 0.0, state: dict | None = None,
    ):
        """Node (re-)registration with fencing + reconciliation. ``epoch``
        is the daemon incarnation's boot stamp; ``state`` is its live
        inventory (workers/actors/leases/bundles/available) so a head that
        replayed its WAL — or lost everything (amnesiac, no persistence) —
        cross-checks its tables against daemon truth and repairs the
        divergence instead of scheduling into a fiction."""
        prev = self.nodes.get(node_id)
        if prev is not None and prev.alive and epoch and prev.epoch \
                and epoch < prev.epoch:
            # A daemon incarnation OLDER than the one that already owns
            # this node id is resurrecting (partition heal, un-paused
            # process) while the owner is still ALIVE. Accepting it would
            # hand the node's resources to two daemons at once — fence
            # it; the stale daemon stands down
            # (node_daemon._register_with_head). The alive guard keeps
            # the fence off a legitimate replacement whose host clock
            # stepped backwards across a restart (epochs are wall-clock):
            # once the owner is gone, any incarnation may take the id.
            self._fenced_registrations += 1
            return {"ok": False, "fenced": True,
                    "incarnation": self.incarnation, "boot_id": self.boot_id}
        self._drop_daemon_client(node_id)  # re-registration: stale address
        info = NodeInfo(
            node_id=node_id, addr=(host, port), resources=dict(resources),
            available=dict(resources), labels=labels or {},
            transfer_addr=tuple(transfer_addr) if transfer_addr else None,
            object_plane=dict(object_plane) if object_plane else None,
            epoch=epoch or (prev.epoch if prev else 0.0),
        )
        if state and state.get("available") is not None:
            # Daemon truth beats the fresh-node assumption: leases granted
            # or returned during a head outage are already reflected here
            # (the next heartbeat would fix it too; seeding avoids a
            # window of phantom availability the scheduler could act on).
            info.available = dict(state["available"])
        self.nodes[node_id] = info
        conn.meta["node_id"] = node_id
        # Delta-heartbeat base: a registration carrying the daemon's live
        # ``available`` IS the full sync — later delta beats on this conn
        # apply against it. Without one, the first delta gets a resync.
        conn.meta["hb_synced"] = bool(state and state.get("available")
                                      is not None)
        self._node_conns[node_id] = conn
        self._membership_version += 1
        if prev is not None and prev.labels != info.labels:
            for k, v in prev.labels.items():
                s = self._label_index.get((k, v))
                if s is not None:
                    s.discard(node_id)
        for k, v in info.labels.items():
            self._label_index.setdefault((k, v), set()).add(node_id)
        self._sched_touch(info)
        reconcile = None
        if state is not None:
            reconcile = await self._reconcile_node(conn, node_id, state)
        await self.publish("node_events", event="added", node_id=node_id)
        out = {"ok": True, "incarnation": self.incarnation,
               "boot_id": self.boot_id}
        if reconcile is not None:
            out["reconcile"] = reconcile
        return out

    async def _reconcile_node(self, conn: ServerConnection, node_id: str,
                              state: dict) -> dict:
        """Repair head-vs-daemon divergence accumulated during an outage.
        Four repairs (reference: the GCS rebuilding actor/node state from
        raylet reports after restart):

        - **reap**: the head believes an actor lives here, the daemon
          doesn't — the worker died while the head was down. Run the
          normal death path NOW (restart budget / DEAD) instead of letting
          a caller discover it by timeout.
        - **re-pin / adopt**: the daemon hosts a live actor the head has
          as PENDING/RESTARTING (the ``actor_ready`` ACK died with the old
          head — the placed-but-unACKed crash window) or doesn't know at
          all (amnesiac head): mark it ALIVE at the reported address.
          Adopted actors stay addressable and resource-accounted; their
          name/spec died with the old head's tables.
        - **orphan kill**: the head decided death (kill_actor, restart
          budget) while the daemon was unreachable — reap the orphan.
        - **prune + re-pend**: drop worker-directory rows the daemon
          positively reports dead, and re-schedule CREATED placement
          groups whose bundles this daemon no longer holds (a restarted
          daemon's bundles evaporated with it)."""
        summary = {"reaped": 0, "repinned": 0, "adopted": 0,
                   "orphans_killed": 0, "workers_pruned": 0,
                   "pgs_repending": 0}
        reported = dict(state.get("actors") or {})
        # Placements still IN FLIGHT on the daemon (worker forking, actor
        # not yet in its table) are neither dead nor alive — leave them to
        # resolve through actor_ready/actor_failed on the fresh session
        # instead of reaping a booting actor.
        placing = set(state.get("placing") or ())
        for actor in list(self.actors.values()):
            if actor.node_id != node_id:
                continue
            if actor.state in ("ALIVE", "PENDING", "RESTARTING") and \
                    actor.actor_id not in reported and \
                    actor.actor_id not in placing:
                summary["reaped"] += 1
                # DEFERRED: the death path may restart the actor, and its
                # place_actor notify must hit the wire AFTER this
                # register's reply — the daemon adopts the new head's
                # boot id from that reply, and a placement arriving first
                # would be fenced as stale-head traffic.
                spawn_task(self._handle_actor_death(
                    actor, "worker died during head outage"))
        for aid, row in reported.items():
            info = self.actors.get(aid)
            addr = tuple(row.get("addr")) if row.get("addr") else None
            if info is None:
                info = ActorInfo(actor_id=aid, state="ALIVE",
                                 node_id=node_id, worker_addr=addr)
                self.actors[aid] = info
                self._log_mutation("actor", aid, info)
                summary["adopted"] += 1
                continue
            if info.state == "DEAD":
                try:
                    await conn.notify("kill_actor", actor_id=aid)
                except Exception:
                    pass
                summary["orphans_killed"] += 1
                continue
            if info.state != "ALIVE" or (addr and info.worker_addr != addr):
                info.node_id = node_id
                if addr:
                    info.worker_addr = addr
                info.state = "ALIVE"
                self._log_mutation("actor", aid, info)
                await self.publish(
                    "actor_events", actor_id=aid, state="ALIVE",
                    addr=list(info.worker_addr) if info.worker_addr else None)
                summary["repinned"] += 1
        # Worker-directory rows are WAL-durable; rows for workers the
        # daemon POSITIVELY knows died (its fate table) would otherwise
        # serve stale pull referrals forever. Only positive knowledge
        # prunes — the daemon can't enumerate driver processes on its
        # node, so absence from its worker table proves nothing.
        for wid in state.get("dead_workers") or ():
            row = self.workers.get(wid)
            if row is not None and (len(row) <= 2 or row[2] == node_id):
                self.workers.pop(wid, None)
                self._log_mutation("worker_del", wid)
                summary["workers_pruned"] += 1
        reported_bundles = {(b[0], int(b[1]))
                            for b in (state.get("bundles") or ())}
        for pg_id, pg in list(self.pgs.items()):
            if pg.get("state") != "CREATED" or not pg.get("assignment"):
                continue
            assignment = pg["assignment"]
            missing = [i for i, nid in enumerate(assignment)
                       if nid == node_id
                       and (pg_id, i) not in reported_bundles]
            if not missing:
                continue
            pg["state"] = "PENDING"
            pg["assignment"] = None
            self._log_mutation("pg", pg_id, dict(pg))
            summary["pgs_repending"] += 1
            survivors = [i for i, nid in enumerate(assignment)
                         if nid != node_id]
            if survivors:
                spawn_task(self._rollback_bundles(pg_id, assignment,
                                                  survivors))
            spawn_task(self._schedule_pg(pg_id))
        for k, v in summary.items():
            if v:
                self._reconcile_totals[k] = \
                    self._reconcile_totals.get(k, 0) + v
        return summary

    async def _heartbeat(self, conn: ServerConnection, node_id: str,
                         available: dict | None = None,
                         resources: dict | None = None,
                         pending_demands: list | None = None,
                         peers_version: int = -1,
                         available_delta: dict | None = None,
                         available_removed: list | None = None,
                         demands_unchanged: bool = False):
        """Node liveness + resource-view sync. Two wire forms (reference:
        ray_syncer.h ships resource-view DELTAS, not snapshots):

        - **full**: ``available`` is the complete map — replaces the view
          and marks this connection synced.
        - **delta**: ``available`` is None; ``available_delta`` carries
          only keys whose value changed and ``available_removed`` keys
          that vanished (both usually empty — an idle node's beat is just
          the liveness stamp). A delta against a connection that never
          shipped a full map (head restarted mid-stream and the register
          predates the delta base) gets ``resync`` back: the daemon's
          next beat is full. At fleet scale this turns the per-period
          heartbeat storm from O(nodes x resource keys) payload into
          O(changed keys)."""
        info = self.nodes.get(node_id)
        if info is None or not info.alive or \
                self._node_conns.get(node_id) is not conn:
            # Unknown node (head restarted and lost membership), a node
            # this head declared dead that turns out to be heartbeating
            # again (partition healed before the daemon noticed anything),
            # OR a heartbeat from a connection that is not the registered
            # one — i.e. a daemon incarnation that never passed the
            # register-time epoch fence (a superseded daemon un-pausing
            # must not keep writing the node's resource view through the
            # heartbeat side door). Either way: a plain heartbeat must NOT
            # update state — the full registration path carries the epoch
            # fence and the reconcile payload, so route the daemon there.
            return {"ok": False, "reregister": True}
        info.last_heartbeat = time.monotonic()
        if available is not None:
            info.available = available
            conn.meta["hb_synced"] = True
        elif not conn.meta.get("hb_synced"):
            # Delta with no base on this head: don't guess — ask for a
            # full map and leave the (stale but internally consistent)
            # registered view in place until it lands.
            return {"ok": True, "resync": True,
                    "membership_version": self._membership_version}
        else:
            if available_delta:
                info.available.update(available_delta)
            for k in available_removed or ():
                info.available.pop(k, None)
        info.optimistic.clear()
        if resources is not None:
            info.resources = resources  # totals change as PG bundles commit
        if not demands_unchanged:
            info.pending_demands = pending_demands or []
        self._sched_touch(info)
        # Membership piggyback, VERSIONED: daemons seed their peer-gossip
        # rings from this (the head stays the membership authority; VIEW
        # dissemination rides daemon-to-daemon gossip — reference:
        # ray_syncer.h bidi streams take resource-view fan-out off the
        # GCS's back). The peer map is only shipped when membership
        # actually changed — otherwise every heartbeat would carry an
        # O(n) map, O(n^2) head egress per period.
        out = {"ok": True, "membership_version": self._membership_version}
        if peers_version != self._membership_version:
            out["peers"] = {
                nid: list(n.addr) for nid, n in self.nodes.items()
                if n.alive and nid != node_id
            }
        return out

    async def _drain_node(self, conn: ServerConnection, node_id: str):
        # Graceful removal (reference: NodeManager::HandleDrainRaylet :2129).
        info = self.nodes.get(node_id)
        if info:
            info.alive = False
            self._sched_touch(info)
            self._drop_daemon_client(node_id)
            self._membership_version += 1
            await self.publish("node_events", event="removed", node_id=node_id)
        return {"ok": True}

    async def _list_nodes(self, conn: ServerConnection,
                          summary: bool = False,
                          alive_only: bool = False,
                          labels: dict | None = None,
                          limit: int = 0):
        """Node listing. The default (no kwargs) keeps the full O(cluster)
        per-node payload for existing callers; at fleet size the state
        API/CLI pass ``summary=True`` (aggregate counts + resource totals,
        no per-node rows — O(1) payload at 1000 nodes) or filter with
        ``alive_only``/``labels``/``limit`` so a dashboard poll stops
        shipping the whole node table."""
        if summary:
            totals: dict[str, float] = {}
            avail: dict[str, float] = {}
            n_alive = 0
            for n in self.nodes.values():
                if not n.alive:
                    continue
                n_alive += 1
                for k, v in n.resources.items():
                    totals[k] = totals.get(k, 0.0) + v
                for k, v in n.available.items():
                    avail[k] = avail.get(k, 0.0) + v
            return {"summary": {
                "nodes_total": len(self.nodes), "nodes_alive": n_alive,
                "resources": totals, "available": avail,
            }}
        out = {}
        for nid, n in self.nodes.items():
            if alive_only and not n.alive:
                continue
            if labels and any(n.labels.get(k) != v
                              for k, v in labels.items()):
                continue
            out[nid] = {
                "addr": list(n.addr), "resources": n.resources,
                "available": n.available, "alive": n.alive, "labels": n.labels,
                "transfer_addr": (list(n.transfer_addr)
                                  if n.transfer_addr else None),
                "object_plane": n.object_plane,
            }
            if limit and len(out) >= limit:
                break
        return out

    async def _health_loop(self):
        # reference: GcsHealthCheckManager periodic pings; here heartbeat ages.
        cfg = get_config()
        while True:
            await asyncio.sleep(cfg.health_check_period_s)
            if _chaos.ACTIVE:
                # ``boot`` scopes the drill to ONE head when several share
                # an interpreter (in-process test clusters); an unscoped
                # kill-head rule matches whichever head ticks first.
                rule = _chaos.decide("head.tick", boot=self.boot_id)
                if rule is not None and rule.action == "kill":
                    _chaos.write_mark(rule, "head.tick",
                                      {"boot": self.boot_id})
                    await self._chaos_die()
                    return
            now = time.monotonic()
            threshold = cfg.health_check_period_s * cfg.health_check_failure_threshold
            for node in list(self.nodes.values()):
                if node.alive and now - node.last_heartbeat > threshold:
                    await self._declare_node_dead(node.node_id)

    async def _declare_node_dead(self, node_id: str) -> None:
        """The ONE node-death sequence (heartbeat aging and the disconnect
        fast path both land here): flip alive, drop the cached daemon
        client, bump membership, publish, fail the node's actors."""
        info = self.nodes.get(node_id)
        if info is None or not info.alive:
            return
        info.alive = False
        self._sched_touch(info)
        self._drop_daemon_client(node_id)
        self._membership_version += 1
        await self.publish("node_events", event="died", node_id=node_id)
        await self._fail_actors_on_node(node_id)

    async def _fail_actors_on_node(self, node_id: str):
        for actor in list(self.actors.values()):
            if actor.node_id == node_id and actor.state in ("ALIVE", "PENDING"):
                await self._handle_actor_death(actor, f"node {node_id[:8]} died")

    # ------------------------------------------------------------------ workers
    async def _register_worker(self, conn: ServerConnection, worker_id: str,
                               host: str, port: int, node_id: str = ""):
        self.workers[worker_id] = (host, port, node_id)
        self._log_mutation("worker", worker_id, (host, port, node_id))
        return {"ok": True}

    async def _resolve_worker(self, conn: ServerConnection, worker_id: str):
        row = self.workers.get(worker_id)
        if row is None:
            return {"addr": None}
        host, port = row[0], row[1]
        node_id = row[2] if len(row) > 2 else ""
        return {"addr": [host, port], "node_id": node_id}

    async def _resolve_workers(self, conn: ServerConnection,
                               worker_ids: list):
        """Batch directory lookup: one round trip resolves every serving
        copy a multi-source referral named (the pull scheduler maps worker
        hexes to node transfer endpoints before splitting ranges)."""
        out = {}
        for worker_id in worker_ids or ():
            row = self.workers.get(worker_id)
            if row is None:
                out[worker_id] = None
                continue
            out[worker_id] = {"addr": [row[0], row[1]],
                              "node_id": row[2] if len(row) > 2 else ""}
        return {"workers": out}

    # ------------------------------------------------------------------ actors
    # FSM parity: reference gcs_actor_manager.cc — REGISTER → schedule (lease
    # on a node) → ALIVE; on failure RESTARTING (≤ max_restarts) or DEAD.
    async def _register_actor(
        self, conn: ServerConnection, actor_id: str, spec_blob: bytes,
        resources: dict, name: str | None, namespace: str, max_restarts: int,
        lifetime: str = "non_detached",
        node_affinity: str | None = None, labels: dict | None = None,
        affinity_soft: bool = False, env_json: str = "",
        req_id: str = "",
    ):
        hit = self._dedup_get(req_id)
        if hit is not None:
            return hit
        if actor_id in self.actors:
            # Belt under the dedup braces: actor ids are client-unique, so
            # a re-registration whose first attempt was WAL-logged but
            # whose ACK died with the old head (and whose req_id aged out)
            # must read as success, not as its own name squatting.
            return self._dedup_put(req_id, {"ok": True, "existed": True})
        if name:
            key = (namespace, name)
            if key in self.named_actors:
                return self._dedup_put(req_id, {
                    "ok": False,
                    "error": f"name {name!r} taken in {namespace!r}"})
        info = ActorInfo(
            actor_id=actor_id, spec_blob=spec_blob, resources=dict(resources),
            name=name, namespace=namespace, max_restarts=max_restarts,
            lifetime=lifetime, node_affinity=node_affinity,
            affinity_soft=affinity_soft, labels=labels, env_json=env_json,
        )
        self.actors[actor_id] = info
        if name:
            self.named_actors[(namespace, name)] = actor_id
        self._log_mutation("actor", actor_id, info)
        ok = await self._schedule_actor(info)
        if not ok:
            info.state = "DEAD"
            info.death_reason = "no feasible node"
            if name:
                self.named_actors.pop((namespace, name), None)
            # Log the death too — replaying only the PENDING registration
            # after a crash would resurrect an actor that can never run
            # (and leave its name squatting in named_actors).
            self._log_mutation("actor", actor_id, info)
            return self._dedup_put(req_id, {
                "ok": False, "error": "no feasible node for actor resources"})
        return self._dedup_put(req_id, {"ok": True})

    def _sched_touch(self, info: NodeInfo) -> None:
        """Refresh a node's scheduler-index entries after ANY mutation of
        its available/optimistic/alive state (register, heartbeat,
        optimistic hold, drain, death). O(log n): the heap is lazy — a
        changed key pushes a fresh entry and the superseded one is
        detected (key mismatch vs _cpu_free) and discarded at pop time.
        Unchanged keys push nothing, so the idle-fleet heartbeat storm —
        the common case at 1000 nodes — costs the index two dict reads."""
        nid = info.node_id
        if not info.alive:
            self._cpu_free.pop(nid, None)
            self._free_sum.pop(nid, None)
            return
        self._free_sum[nid] = sum(info.available.values())
        key = info.effective("CPU")
        if self._cpu_free.get(nid) == key:
            return
        self._cpu_free[nid] = key
        heapq.heappush(self._cpu_heap, (-key, nid))
        if len(self._cpu_heap) > 4 * len(self._cpu_free) + 64:
            # Compact: rebuild from live keys once stale entries dominate.
            self._cpu_heap = [(-v, n) for n, v in self._cpu_free.items()]
            heapq.heapify(self._cpu_heap)

    def _pick_node(self, resources: dict[str, float], node_affinity: str | None = None,
                   labels: dict | None = None) -> NodeInfo | None:
        # Least-loaded feasible node (reference default is hybrid pack/spread;
        # actors spread by load — gcs_actor_scheduler picks via cluster view).
        # Three indexed paths replace the full node-table scan (the linear
        # walk survives below as the parity oracle + config fallback):
        # affinity is a dict hit, labels intersect the inverted index, and
        # the general case walks the (-effective CPU, node_id) heap — the
        # EXACT order the linear version sorts by — so the first node that
        # passes the ready check is the same node the scan would pick.
        if not get_config().indexed_scheduler_enabled:
            return self._pick_node_linear(resources, node_affinity, labels)
        if node_affinity:
            n = self.nodes.get(node_affinity)
            if (n is None or not n.alive
                    or (labels and any(n.labels.get(k) != v
                                       for k, v in labels.items()))
                    or not all(n.resources.get(k, 0.0) >= v
                               for k, v in resources.items())):
                return None
            return n
        if labels:
            cands: set[str] | None = None
            for k, v in labels.items():
                s = self._label_index.get((k, v))
                if not s:
                    return None
                cands = set(s) if cands is None else cands & s
                if not cands:
                    return None
            return self._pick_node_linear(resources, None, labels,
                                          node_ids=cands)
        heap = self._cpu_heap
        popped: list[tuple[float, str]] = []
        best_feasible: NodeInfo | None = None
        found: NodeInfo | None = None
        while heap:
            entry = heapq.heappop(heap)
            key, nid = entry
            if self._cpu_free.get(nid) != -key:
                continue  # stale: superseded key or dead node — drop it
            popped.append(entry)
            n = self.nodes.get(nid)
            if n is None or not n.alive:
                continue
            if not all(n.resources.get(k, 0.0) >= v
                       for k, v in resources.items()):
                continue
            if best_feasible is None:
                best_feasible = n
            # Prefer nodes that can host the actor NOW — picking by totals
            # alone stacks same-resource actors onto one node while its
            # twin sits idle (the daemon would park the extra actor in its
            # wait-for-resources loop). "Now" includes the optimistic holds
            # of placements already issued this heartbeat window.
            if all(n.effective(k) >= v for k, v in resources.items()):
                found = n
                break
        for entry in popped:
            heapq.heappush(heap, entry)
        return found or best_feasible

    def _pick_node_linear(self, resources: dict[str, float],
                          node_affinity: str | None = None,
                          labels: dict | None = None,
                          node_ids: set[str] | None = None) -> NodeInfo | None:
        """The original full-scan picker. Still load-bearing: the indexed
        path routes label-constrained picks here over the (small) inverted
        -index candidate set, the config kill-switch falls back to it, and
        test_scale proves indexed-vs-linear parity against it."""
        nodes = (self.nodes[nid] for nid in node_ids
                 if nid in self.nodes) if node_ids is not None \
            else self.nodes.values()
        ready, feasible = [], []
        for n in nodes:
            if not n.alive:
                continue
            if node_affinity and n.node_id != node_affinity:
                continue
            if labels and any(n.labels.get(k) != v for k, v in labels.items()):
                continue
            if not all(n.resources.get(k, 0.0) >= v
                       for k, v in resources.items()):
                continue
            free = sum(n.effective(k) for k in ("CPU",))
            feasible.append((-free, n.node_id, n))
            if all(n.effective(k) >= v for k, v in resources.items()):
                ready.append((-free, n.node_id, n))
        pool = ready or feasible
        if not pool:
            return None
        pool.sort(key=lambda t: (t[0], t[1]))
        return pool[0][2]

    async def _schedule_actor(self, info: ActorInfo) -> bool:
        # Placement demand: an actor with no lifetime resources still
        # weighs one CPU for the placement DECISION (reference: default
        # actors cost 1 CPU to place, 0 while running) — otherwise every
        # zero-resource actor looks free everywhere, the optimistic
        # decrement below is a no-op, and default actors all stack on the
        # single most-free node.
        placement = dict(info.resources) if any(info.resources.values()) \
            else {"CPU": 1.0}
        while True:
            node = self._pick_node(placement, info.node_affinity,
                                   info.labels)
            if node is None and info.node_affinity and info.affinity_soft:
                # Soft affinity: target gone/infeasible → default placement.
                node = self._pick_node(placement, None, info.labels)
            if node is None:
                return False
            conn = self._node_conns.get(node.node_id)
            if conn is None:
                # Registered-but-connectionless: the socket dropped and the
                # disconnect fast path hasn't flipped ``alive`` yet. Run
                # the one death sequence now (idempotent) and re-pick —
                # failing the registration while feasible nodes remain
                # would mark the actor DEAD over a transient race.
                await self._declare_node_dead(node.node_id)
                continue
            info.node_id = node.node_id
            # Optimistic per-resource hold: back-to-back placements must not
            # all see the same node as free. Never mutates ``available``
            # (truthful resource views matter to the elastic/autoscaler
            # policies); the next heartbeat replaces it with daemon truth.
            for k, v in placement.items():
                node.optimistic[k] = node.optimistic.get(k, 0.0) + v
            self._sched_touch(node)
            # Ask the node daemon to place the actor in a fresh/pooled worker
            # (reference: GcsActorScheduler leases a worker from the raylet).
            # head_boot rides along so a daemon that has since registered with
            # a NEWER head can fence a stale head's placement instead of
            # double-allocating the worker.
            try:
                await conn.notify(
                    "place_actor", actor_id=info.actor_id,
                    spec_blob=info.spec_blob, resources=info.resources,
                    env_json=info.env_json, head_boot=self.boot_id,
                )
            except (ConnectionResetError, BrokenPipeError, OSError):
                # The daemon died between the pick and the push (chaos
                # kill / crash race): a failed write is the same positive
                # death evidence the disconnect fast path acts on. Unpin
                # FIRST so _fail_actors_on_node doesn't burn a restart on
                # an actor that never reached the node, then re-pick —
                # the caller must never see a transport error for a
                # placement the head can still satisfy elsewhere.
                info.node_id = None
                await self._declare_node_dead(node.node_id)
                continue
            return True

    async def _actor_ready(self, conn: ServerConnection, actor_id: str, worker_id: str,
                           host: str, port: int):
        info = self.actors.get(actor_id)
        if info is None:
            return {"ok": False}
        if info.state == "DEAD":
            # A placement that lost its race: the actor was killed or
            # reaped (reconcile, kill_actor) while its worker was still
            # booting. Resurrecting here would run a DEAD actor — whose
            # name may already be released — on a zombie worker. Reap it.
            node_id = conn.meta.get("node_id") or info.node_id
            nconn = self._node_conns.get(node_id) if node_id else None
            if nconn is not None:
                try:
                    await nconn.notify("kill_actor", actor_id=actor_id)
                except Exception:
                    pass
            return {"ok": False, "dead": True}
        info.worker_addr = (host, port)
        info.state = "ALIVE"
        self._log_mutation("actor", actor_id, info)
        await self.publish("actor_events", actor_id=actor_id, state="ALIVE",
                           addr=[host, port])
        return {"ok": True}

    async def _placement_fenced(self, conn: ServerConnection,
                                actor_id: str):
        """A daemon refused a place_actor as stale-head traffic. If the
        placement was actually OURS — a reconcile-restart's notify racing
        the daemon's boot-id adoption on its register reply — the actor
        is still PENDING/RESTARTING here: re-issue it now that the daemon
        knows our boot id. A placement from a genuinely dead head finds
        no matching pending actor and is a no-op."""
        info = self.actors.get(actor_id)
        node_id = conn.meta.get("node_id")
        if info is None or info.state not in ("PENDING", "RESTARTING") or \
                (node_id and info.node_id and info.node_id != node_id):
            return {"ok": False}
        ok = await self._schedule_actor(info)
        if not ok:
            await self._handle_actor_death(
                info, "placement fenced and no feasible node remained")
        return {"ok": ok}

    async def _actor_failed(self, conn: ServerConnection, actor_id: str, reason: str):
        info = self.actors.get(actor_id)
        if info is None:
            return {"ok": False}
        await self._handle_actor_death(info, reason)
        return {"ok": True}

    async def _handle_actor_death(self, info: ActorInfo, reason: str):
        if info.restarts_used < info.max_restarts:
            info.restarts_used += 1
            info.state = "RESTARTING"
            await self.publish("actor_events", actor_id=info.actor_id, state="RESTARTING")
            if await self._schedule_actor(info):
                return
            reason = f"{reason}; restart found no feasible node"
        info.state = "DEAD"
        info.death_reason = reason
        if info.name:
            self.named_actors.pop((info.namespace, info.name), None)
        self._log_mutation("actor", info.actor_id, info)
        await self.publish("actor_events", actor_id=info.actor_id, state="DEAD",
                           reason=reason)

    async def _get_actor_info(self, conn: ServerConnection, actor_id: str):
        info = self.actors.get(actor_id)
        if info is None:
            return None
        return {
            "state": info.state,
            "addr": list(info.worker_addr) if info.worker_addr else None,
            "reason": info.death_reason,
        }

    async def _get_named_actor(self, conn: ServerConnection, name: str, namespace: str):
        actor_id = self.named_actors.get((namespace, name))
        return {"actor_id": actor_id}

    async def _kill_actor(self, conn: ServerConnection, actor_id: str, no_restart: bool):
        info = self.actors.get(actor_id)
        if info is None or info.state == "DEAD":
            return {"ok": True}
        if no_restart:
            info.max_restarts = info.restarts_used  # suppress further restarts
        if info.worker_addr:
            # Tell the hosting worker to tear the actor down.
            node = self.nodes.get(info.node_id)
            nconn = self._node_conns.get(info.node_id) if node else None
            if nconn is not None:
                await nconn.notify("kill_actor", actor_id=actor_id)
        await self._handle_actor_death(info, "killed via kill()")
        return {"ok": True}

    # ------------------------------------------------------------------ placement groups
    # 2PC coordinator (reference: GcsPlacementGroupScheduler — compute
    # bundle→node mapping with the bundle policies, prepare all, commit only
    # after every prepare succeeds; SchedulePendingPlacementGroups retries —
    # gcs_placement_group_manager.cc:241).
    async def _daemon_rpc(self, node_id: str):
        from ray_tpu.core.cluster.protocol import AsyncRpcClient

        info = self.nodes[node_id]
        cached = self._daemon_clients.get(node_id)
        if cached is not None:
            addr, cli = cached
            if addr == info.addr:
                return cli
            # node re-registered at a new address: drop the stale client
            try:
                await cli.close()
            except Exception:
                pass
            self._daemon_clients.pop(node_id, None)
        cli = AsyncRpcClient(*info.addr)
        # Chaos partition probe: this client carries head→node traffic.
        cli.partition_node = node_id
        cli.partition_send = "from_head"
        await cli.connect()
        self._daemon_clients[node_id] = (info.addr, cli)
        return cli

    def _drop_daemon_client(self, node_id: str) -> None:
        cached = self._daemon_clients.pop(node_id, None)
        if cached is not None:
            _, cli = cached
            try:
                close = cli.close()
                if asyncio.iscoroutine(close):
                    spawn_task(close)
            except Exception:
                pass

    def _assign_bundles(self, bundles: list[dict], strategy: str) -> list[str] | None:
        """bundle index → node_id, honoring the strategy; None if infeasible.

        Fleet-scale shape: the old version copied every alive node's
        available dict up front — O(nodes x keys) allocation per attempt,
        and _schedule_pg retries this in a loop. Now reads go straight to
        the NodeInfo maps with a lazy per-call overlay that only
        materializes for nodes a bundle actually landed on, and the PACK
        ordering reuses the _sched_touch-maintained _free_sum cache.
        Iteration stays in self.nodes order (the stable-sort tie-break
        the old dict build inherited), so assignments are bit-identical."""
        alive = [n for n in self.nodes.values() if n.alive]
        avail = {n.node_id: n.available for n in alive}
        overlay: dict[str, dict] = {}

        def _get(nid, k):
            d = overlay.get(nid)
            if d is not None and k in d:
                return d[k]
            return avail[nid].get(k, 0.0)

        def fits(nid, b):
            return all(_get(nid, k) >= v for k, v in b.items())

        def take(nid, b):
            d = overlay.setdefault(nid, {})
            for k, v in b.items():
                d[k] = _get(nid, k) - v

        def free_sum(nid):
            s = self._free_sum.get(nid)
            return s if s is not None else sum(avail[nid].values())

        free = avail  # candidate ids, self.nodes iteration order
        assignment: list[str] = []
        if strategy in ("PACK", "STRICT_PACK"):
            order = sorted(free, key=lambda nid: -free_sum(nid))
            for b in bundles:
                if strategy == "STRICT_PACK" and assignment:
                    cands = [assignment[0]]
                else:
                    # PACK: prefer already-used nodes, then most-free first
                    cands = list(dict.fromkeys(assignment))
                    cands += [n for n in order if n not in cands]
                placed = next((nid for nid in cands if fits(nid, b)), None)
                if placed is None:
                    return None
                take(placed, b)
                assignment.append(placed)
            return assignment
        # SPREAD / STRICT_SPREAD: round-robin over distinct nodes
        used: list[str] = []
        for b in bundles:
            candidates = [nid for nid in free
                          if fits(nid, b) and (nid not in used or strategy == "SPREAD")]
            fresh = [nid for nid in candidates if nid not in used]
            pick = (fresh or candidates or [None])[0]
            if pick is None:
                return None
            take(pick, b)
            used.append(pick)
            assignment.append(pick)
        if strategy == "STRICT_SPREAD" and len(set(assignment)) != len(bundles):
            return None
        return assignment

    async def _create_pg(self, conn: ServerConnection, pg_id: str,
                         bundles: list, strategy: str, name: str | None = None,
                         req_id: str = ""):
        hit = self._dedup_get(req_id)
        if hit is not None:
            return hit
        if pg_id in self.pgs:
            # Retried creation (pg ids are client-unique): report current
            # state instead of resetting a PG that may already be CREATED.
            return self._dedup_put(
                req_id, {"ok": True, "state": self.pgs[pg_id]["state"]})
        self.pgs[pg_id] = {"state": "PENDING", "bundles": bundles,
                           "strategy": strategy, "assignment": None,
                           "name": name}
        self._log_mutation("pg", pg_id, dict(self.pgs[pg_id]))
        # Inline the FIRST placement attempt, briefly: on an uncontended
        # cluster the PG is CREATED before this reply, so the client's
        # first ready() poll succeeds (PG churn previously paid poll
        # backoff sleeps + extra state RPCs per group). A busy cluster
        # falls back to background retries without delaying the reply.
        task = spawn_task(self._schedule_pg(pg_id))
        try:
            await asyncio.wait_for(asyncio.shield(task), timeout=0.25)
        except Exception:  # noqa: BLE001 - timeout: scheduling continues
            pass
        return self._dedup_put(
            req_id, {"ok": True, "state": self.pgs[pg_id]["state"]})

    async def _schedule_pg(self, pg_id: str, retries: int = 120):
        pg = self.pgs[pg_id]
        for _ in range(retries):
            if pg["state"] == "REMOVED":
                return
            assignment = self._assign_bundles(pg["bundles"], pg["strategy"])
            if assignment is not None:
                # One grant RPC per NODE per phase, nodes in parallel
                # (reference 2PC semantics — CommitAllBundles batches per
                # raylet; per-bundle RPCs made PG churn latency scale with
                # bundle count).
                by_node: dict[str, list[int]] = {}
                for idx, nid in enumerate(assignment):
                    by_node.setdefault(nid, []).append(idx)

                if len(by_node) == 1:
                    # Single participant: 2PC collapses to one RPC (the
                    # daemon prepares+commits atomically on its own loop).
                    nid, idxs = next(iter(by_node.items()))
                    ok = False
                    try:
                        cli = await self._daemon_rpc(nid)
                        res = await cli.call(
                            "prepare_commit_bundles", timeout=30,
                            pg_id=pg_id,
                            bundle_indices=idxs,
                            resources_list=[pg["bundles"][i] for i in idxs])
                        ok = bool(res.get("ok"))
                    except Exception:  # noqa: BLE001 - node/RPC failure
                        ok = False
                    if ok:
                        if pg["state"] == "REMOVED":  # raced a remove()
                            await self._rollback_bundles(
                                pg_id, assignment, idxs)
                            return
                        pg["assignment"] = assignment
                        pg["state"] = "CREATED"
                        self._log_mutation("pg", pg_id, dict(pg))
                        await self.publish("pg_events", pg_id=pg_id,
                                           state="CREATED")
                        return
                    await asyncio.sleep(0.5)
                    continue

                async def _prepare_node(nid: str, idxs: list[int]):
                    # Never raises: a partial failure still reports the
                    # bundles that DID prepare so rollback can return them.
                    try:
                        cli = await self._daemon_rpc(nid)
                        res = await cli.call(
                            "prepare_bundles", timeout=30, pg_id=pg_id,
                            bundle_indices=idxs,
                            resources_list=[pg["bundles"][i] for i in idxs])
                        return list(res.get("prepared") or []), \
                            bool(res.get("ok"))
                    except Exception:  # noqa: BLE001 - node/RPC failure
                        return [], False

                prepared: list[int] = []
                ok = True
                results = await asyncio.gather(
                    *(_prepare_node(nid, idxs)
                      for nid, idxs in by_node.items()))
                for got, node_ok in results:
                    prepared.extend(got)
                    ok = ok and node_ok
                # A remove() may have arrived while prepares were in flight —
                # honor it before committing anything.
                if pg["state"] == "REMOVED":
                    await self._rollback_bundles(pg_id, assignment, prepared)
                    return
                if ok:
                    try:
                        async def _commit_node(nid: str, idxs: list[int]):
                            cli = await self._daemon_rpc(nid)
                            await cli.call("commit_bundles", timeout=30,
                                           pg_id=pg_id,
                                           bundle_indices=idxs)

                        # return_exceptions: every node's coroutine runs to
                        # completion BEFORE any rollback decision — a plain
                        # gather would roll back while a surviving node is
                        # still committing, leaking its bundle afterwards.
                        cres = await asyncio.gather(
                            *(_commit_node(nid, idxs)
                              for nid, idxs in by_node.items()),
                            return_exceptions=True)
                        for c in cres:
                            if isinstance(c, BaseException):
                                raise c
                    except Exception:
                        # A node died mid-commit: roll back everything (bundle
                        # return works for both prepared and committed) and
                        # retry the whole placement from scratch.
                        await self._rollback_bundles(pg_id, assignment, prepared)
                        await asyncio.sleep(0.5)
                        continue
                    if pg["state"] == "REMOVED":  # removed during commit
                        # Bundle return handles prepared AND committed.
                        await self._rollback_bundles(pg_id, assignment, prepared)
                        return
                    pg["assignment"] = assignment
                    pg["state"] = "CREATED"
                    self._log_mutation("pg", pg_id, dict(pg))
                    await self.publish("pg_events", pg_id=pg_id, state="CREATED")
                    return
                # rollback prepared bundles, retry later
                await self._rollback_bundles(pg_id, assignment, prepared)
            await asyncio.sleep(0.5)
        if pg["state"] != "REMOVED":
            pg["state"] = "FAILED"

    async def _rollback_bundles(self, pg_id: str, assignment: list[str],
                                indices: list[int]) -> None:
        by_node: dict[str, list[int]] = {}
        for idx in indices:
            by_node.setdefault(assignment[idx], []).append(idx)
        for nid, idxs in by_node.items():
            try:
                cli = await self._daemon_rpc(nid)
                await cli.call("return_bundles", timeout=30, pg_id=pg_id,
                               bundle_indices=idxs)
            except Exception:
                pass

    async def _remove_pg(self, conn: ServerConnection, pg_id: str,
                         req_id: str = ""):
        # No dedup-table read needed: removal is naturally idempotent
        # (a second remove of a gone PG is a no-op success) — but the
        # req_id still rides in so the retry wrapper may stamp it.
        pg = self.pgs.get(pg_id)
        if pg is None:
            return {"ok": True}
        # Mark REMOVED first: a mid-flight _schedule_pg checks this before and
        # after its commit phase, so either it rolls its bundles back itself or
        # we return the already-committed assignment here.
        pg["state"] = "REMOVED"
        self._log_mutation("pg_del", pg_id)
        assignment = pg.get("assignment")
        pg["assignment"] = None
        if assignment:
            # Bundle return rides in the background: the REMOVED state is
            # already authoritative (no new bundle tasks schedule), and the
            # client needn't wait out a daemon round trip per node.
            spawn_task(self._rollback_bundles(
                pg_id, assignment, list(range(len(assignment)))))
        return {"ok": True}

    async def _pg_state(self, conn: ServerConnection, pg_id: str):
        pg = self.pgs.get(pg_id)
        return {"state": pg["state"] if pg else "REMOVED"}

    # ------------------------------------------------------------------ function registry
    # Content-addressed definition table (reference: the GCS function table
    # behind function_manager.py exports). Backed by a KV namespace so the
    # WAL/snapshot persistence covers it like any other KV data; fn_stats
    # makes the once-per-definition / once-per-worker contract observable.
    # KNOWN BOUND: the table grows with DISTINCT definitions for the head's
    # lifetime (the reference's per-job function tables have the same shape
    # until job GC). Eviction is deliberately absent — submitters cache
    # "already exported" per process, so dropping a blob would permanently
    # fail their in-flight specs. Job-scoped GC is the right future fix.
    async def _fn_put(self, conn: ServerConnection, fn_id: str, blob: bytes,
                      req_id: str = ""):
        hit = self._dedup_get(req_id)
        if hit is not None:
            return hit
        table = self.kv.setdefault(FN_NS, {})
        if fn_id in table:
            self.fn_stats["dup_puts"] += 1
            return self._dedup_put(req_id, {"ok": True, "existed": True})
        table[fn_id] = blob
        self.fn_stats["puts"] += 1
        self._log_mutation("kv_put", FN_NS, fn_id, blob)
        return self._dedup_put(req_id, {"ok": True, "existed": False})

    async def _fn_get(self, conn: ServerConnection, fn_id: str):
        blob = self.kv.get(FN_NS, {}).get(fn_id)
        self.fn_stats["gets"] += 1
        if blob is None:
            self.fn_stats["misses"] += 1
        return {"blob": blob}

    # ------------------------------------------------------------------ KV
    # (reference: gcs_kv_manager.cc internal KV — function/code storage, serve
    # config, usage flags all live here)
    async def _kv_put(self, conn: ServerConnection, ns: str, key: str, value: bytes,
                      overwrite: bool = True, req_id: str = ""):
        hit = self._dedup_get(req_id)
        if hit is not None:
            return hit
        table = self.kv.setdefault(ns, {})
        if not overwrite and key in table:
            return self._dedup_put(req_id, {"ok": False})
        table[key] = value
        self._log_mutation("kv_put", ns, key, value)
        return self._dedup_put(req_id, {"ok": True})

    async def _kv_get(self, conn: ServerConnection, ns: str, key: str):
        return {"value": self.kv.get(ns, {}).get(key)}

    async def _kv_del(self, conn: ServerConnection, ns: str, key: str,
                      req_id: str = ""):
        hit = self._dedup_get(req_id)
        if hit is not None:
            return hit
        existed = self.kv.get(ns, {}).pop(key, None) is not None
        if existed:
            self._log_mutation("kv_del", ns, key)
        return self._dedup_put(req_id, {"ok": existed})

    async def _kv_keys(self, conn: ServerConnection, ns: str, prefix: str = ""):
        return {"keys": [k for k in self.kv.get(ns, {}) if k.startswith(prefix)]}

    # ------------------------------------------------------------------ state API
    async def _report_task_events(self, conn: ServerConnection, events: list):
        """Workers flush their task-event batches here (reference:
        GcsTaskManager as the cluster-wide task-event store)."""
        self.task_events.extend(events)
        self._task_events_total += len(events)
        return {"ok": True}

    async def _report_telemetry(self, conn: ServerConnection,
                                source: str, node_id: str = "",
                                snapshot: dict | None = None,
                                spans: list | None = None,
                                events: list | None = None,
                                dropped: int = 0,
                                train_stats: dict | None = None,
                                series: dict | None = None,
                                goodput: dict | None = None,
                                keeps: list | None = None,
                                keep_cursor: int = 0):
        """One batched push from a process's telemetry flusher: its metrics
        snapshot (replaces the previous one for this source), finished
        spans, drained task events, and the delta-encoded watchdog series
        samples (reference: per-worker TaskEventBuffer + metrics agent,
        federated at the GCS/dashboard). ``dropped`` is the reporter's
        cumulative dropped-event count, surfaced per source in the
        get_telemetry table. The reply carries ``series_resync`` when the
        watchdog store doesn't know a referenced series id (head restart /
        source eviction) — the reporter re-declares on its next flush.

        Tail-sampling keep gossip rides the same push: ``keeps`` lists trace
        ids this reporter promoted from its tail ring, and the reply returns
        every cluster-wide kept id minted since the reporter's
        ``keep_cursor`` (plus the new cursor), so a trace kept on one node
        retroactively promotes its spans buffered on every other node — no
        dedicated RPC."""
        out = {"ok": True}
        for k in keeps or ():
            tid = k.get("trace_id") if isinstance(k, dict) else k
            if tid and tid not in self._keep_ids:
                self._keep_seq += 1
                self._keeps.append((self._keep_seq, tid))
                self._keep_ids.add(tid)
                while len(self._keep_ids) > 2 * self._keeps.maxlen:
                    self._keep_ids.clear()
                    self._keep_ids.update(t for _, t in self._keeps)
        if self._keeps and keep_cursor < self._keep_seq:
            out["keeps"] = [t for seq, t in self._keeps if seq > keep_cursor]
            out["keep_cursor"] = self._keep_seq
            # Promote matching spans already buffered in the head's own
            # tail ring (e.g. handed straight to head-process tracing).
            tracing.apply_keeps(out["keeps"])
        if series and self.watchdog is not None:
            if self.watchdog.ingest(source, node_id, series):
                out["series_resync"] = True
        if snapshot is not None:
            self.telemetry[source] = {
                "node_id": node_id, "ts": time.time(),
                "snapshot": snapshot, "dropped": int(dropped),
            }
            self._harvest_exemplars(snapshot)
            # Bounded: a churny cluster must not grow this forever. Evict
            # DEAD sources first (silent past the liveness window — they've
            # already fallen out of the export); only shed live reporters
            # when the cap is still exceeded, stalest first.
            if len(self.telemetry) > 512:
                cutoff = time.time() - 60.0
                for src, row in sorted(self.telemetry.items(),
                                       key=lambda kv: kv[1]["ts"]):
                    if len(self.telemetry) <= 512:
                        break
                    if row["ts"] < cutoff:
                        self._evict_telemetry_source(src)
                while len(self.telemetry) > 1024:  # hard cap: shed live rows
                    src = min(self.telemetry,
                              key=lambda s: self.telemetry[s]["ts"])
                    self._evict_telemetry_source(src)
        if spans:
            self.spans.extend(spans)
        if events:
            self.task_events.extend(events)
            self._task_events_total += len(events)
        if train_stats:
            self.train_stats[source] = {
                "node_id": node_id, "ts": time.time(), "stats": train_stats,
            }
            if len(self.train_stats) > 4096:  # churny clusters stay bounded
                src = min(self.train_stats,
                          key=lambda s: self.train_stats[s]["ts"])
                self.train_stats.pop(src, None)
        if self.goodput is not None:
            if goodput:
                self.goodput.ingest(source, node_id, goodput)
            if train_stats or goodput:
                # Throttled internally (goodput_check_interval_s): rolls up
                # the ledger, refreshes goodput_* gauges, and runs the
                # badput-over-threshold rule against the watchdog.
                self.goodput.maybe_check(self.train_stats, self.watchdog)
        return out

    def _harvest_exemplars(self, snapshot: dict) -> None:
        """Pull histogram exemplars out of a reporter snapshot into the
        (metric, deployment) -> [(trace_id, value, ts), ...] stash the
        watchdog reads when assembling serve incidents. Newest-N per key,
        same bound as one process's ring; stale keys age out wholesale at a
        soft cap (exemplars are a hint, not a ledger)."""
        for entry in snapshot.get("metrics", ()):
            rows = entry.get("exemplars")
            if not rows:
                continue
            tag_keys = entry.get("tag_keys") or []
            try:
                dep_i = tag_keys.index("deployment")
            except ValueError:
                dep_i = -1
            for series_key, exs in rows:
                dep = series_key[dep_i] if 0 <= dep_i < len(series_key) \
                    else ""
                key = (entry["name"], dep)
                merged = self._exemplars.get(key, []) + [list(e) for e in exs]
                merged.sort(key=lambda e: e[2] if len(e) > 2 else 0.0)
                self._exemplars[key] = merged[-8:]
        if len(self._exemplars) > 1024:
            for key in sorted(self._exemplars,
                              key=lambda k: self._exemplars[k][-1][2]
                              if self._exemplars[k] else 0.0)[:256]:
                self._exemplars.pop(key, None)

    def exemplar_traces(self, metric: str = "",
                        deployment: str = "") -> list:
        """Recent exemplar rows for the watchdog: filter by metric prefix
        and/or deployment; each row is (trace_id, value, ts), newest last."""
        rows = []
        for (name, dep), exs in self._exemplars.items():
            if metric and not name.startswith(metric):
                continue
            if deployment and dep != deployment:
                continue
            rows.extend(exs)
        rows.sort(key=lambda e: e[2] if len(e) > 2 else 0.0)
        return rows[-8:]

    def _evict_telemetry_source(self, source: str) -> None:
        """Shed one reporter from the snapshot table AND its watchdog
        series + detector state (a dead worker's rings and baselines must
        not pin store slots forever)."""
        self.telemetry.pop(source, None)
        if self.watchdog is not None:
            self.watchdog.drop_source(source)

    async def _get_telemetry(self, conn: ServerConnection,
                             max_age_s: float = 60.0):
        """The per-node telemetry table: every live source's snapshot,
        grouped by node. Sources silent for ``max_age_s`` are omitted
        (dead workers must fall out of the export)."""
        cutoff = time.time() - max_age_s
        return {"sources": {
            src: row for src, row in self.telemetry.items()
            if row["ts"] >= cutoff
        }}

    async def _get_spans(self, conn: ServerConnection, limit: int = 50_000):
        spans = list(self.spans)
        return {"spans": spans[-limit:]}

    # ------------------------------------------------------------- watchdog
    async def _get_timeseries(self, conn: ServerConnection,
                              name: str | None = None,
                              source: str | None = None,
                              node_id: str | None = None,
                              tags: dict | None = None,
                              since: float = 0.0, max_points: int = 0,
                              max_age_s: float = 0.0):
        if self.watchdog is None:
            return {"series": [], "enabled": False}
        return {"series": self.watchdog.store.query(
            name=name, source=source, node_id=node_id, tags=tags,
            since=since, max_points=max_points, max_age_s=max_age_s),
            "enabled": True}

    async def _get_incidents(self, conn: ServerConnection,
                             since: float = 0.0, limit: int = 100,
                             incident_id: str | None = None):
        if self.watchdog is None:
            return {"incidents": [], "enabled": False}
        return {"incidents": self.watchdog.list_incidents(
            since=since, limit=limit, incident_id=incident_id),
            "enabled": True}

    async def _watchdog_status(self, conn: ServerConnection):
        if self.watchdog is None:
            return {"enabled": False}
        return self.watchdog.status()

    async def _get_goodput(self, conn: ServerConnection,
                           run: str | None = None):
        """Fleet goodput rollup: every rank's phase ledger (riding the
        train-stats rows) joined with the run-level badput events, rolled
        into per-run and fleet goodput %, badput breakdown, and the serve
        request-goodput leg (SLO-attained tokens/chip-second)."""
        if self.goodput is None:
            return {"enabled": False, "runs": {}, "fleet": {}, "serve": {}}
        store = self.watchdog.store if self.watchdog is not None else None
        return self.goodput.rollup(self.train_stats, run=run,
                                   series_store=store)

    async def _watchdog_profile(self, node_id: str, seconds: float) -> dict:
        """Targeted capture for incident evidence: ONE node's daemon fans
        to its workers (the PR-5 profile_node leg, same guardrails on the
        daemon side). Raises on a dead/unknown node — the watchdog records
        the error as partial evidence."""
        if node_id not in self.nodes or not self.nodes[node_id].alive:
            raise ValueError(f"implicated node {node_id[:16]!r} not alive")
        cli = await self._daemon_rpc(node_id)
        return await cli.call(
            "profile_node", timeout=seconds + 30.0, seconds=seconds,
            sample_hz=0.0, include_daemon=True,
            capture_id=f"watchdog-{uuid.uuid4().hex}")

    # ------------------------------------------------------------- profiling
    # Cluster leg of the `profile` control RPC: fan the capture out to every
    # alive node daemon (which fans out to its workers), then hand back the
    # per-process captures TOGETHER with the span timeline so the caller
    # merges one chrome-trace + one fleet flamegraph (profiling/merge.py).
    # A node dying mid-capture contributes an error entry, never a hang.

    async def _fan_to_daemons(self, method: str, timeout: float, **kwargs):
        async def one(nid: str):
            try:
                cli = await self._daemon_rpc(nid)
                return nid, await cli.call(method, timeout=timeout, **kwargs)
            except Exception as e:  # noqa: BLE001 - partial results win
                return nid, {"errors": {nid: f"{type(e).__name__}: {e}"}}

        alive = [nid for nid, n in self.nodes.items() if n.alive]
        return await asyncio.gather(*(one(nid) for nid in alive))

    async def _profile_cluster(self, conn: ServerConnection,
                               seconds: float = 5.0,
                               sample_hz: float = 0.0,
                               include_daemons: bool = True):
        seconds = max(0.05, min(float(seconds),
                                get_config().profiler_max_capture_s))
        captures: list[dict] = []
        errors: dict[str, str] = {}
        # One capture_id for the whole request: co-hosted daemons (several
        # NodeDaemons in one interpreter) dedupe their self-capture on it.
        capture_id = uuid.uuid4().hex
        for nid, res in await self._fan_to_daemons(
                "profile_node", seconds + 60.0, seconds=seconds,
                sample_hz=sample_hz, include_daemon=include_daemons,
                capture_id=capture_id):
            captures.extend(res.get("captures") or [])
            errors.update(res.get("errors") or {})
        return {"captures": captures, "errors": errors,
                "spans": list(self.spans)[-20_000:]}

    async def _chaos_cluster(self, conn: ServerConnection, rules=None,
                             clear: bool = False):
        """Chaos plane: fan fault-injection rules (or a clear) to every
        alive daemon, which installs locally and fans to its workers. The
        head itself also installs — rpc.server rules can target head RPCs
        (lease/heartbeat delay drills)."""
        from ray_tpu.chaos import injector

        if clear:
            injector.clear()
        if rules:
            injector.install(rules, replace=False)
        nodes = {}
        errors: dict[str, str] = {}
        for nid, res in await self._fan_to_daemons(
                "chaos_node", 30.0, rules=rules, clear=clear):
            nodes[nid] = res
            errors.update((res or {}).get("errors") or {})
        return {"head": injector.status(), "nodes": nodes, "errors": errors}

    async def _stack_cluster(self, conn: ServerConnection):
        nodes = {}
        for nid, res in await self._fan_to_daemons("stack_node", 30.0):
            nodes[nid] = res
        return {"nodes": nodes}

    async def _device_memory(self, conn: ServerConnection):
        nodes = {}
        for nid, res in await self._fan_to_daemons("memory_node", 30.0):
            nodes[nid] = res
        return {"nodes": nodes}

    async def _get_train_stats(self, conn: ServerConnection,
                               max_age_s: float = 300.0):
        """The straggler table: every source's per-rank step summaries,
        sources silent past ``max_age_s`` omitted (finished/dead trainers
        must fall out of the report)."""
        cutoff = time.time() - max_age_s
        return {"sources": {
            src: row for src, row in self.train_stats.items()
            if row["ts"] >= cutoff
        }}

    async def _state_snapshot(self, conn: ServerConnection,
                              parts: list | None = None):
        """Whole-cluster view for the state API (reference: the GCS tables
        behind python/ray/util/state/api.py list_nodes/list_actors/...).
        ``parts`` names the tables to build (["nodes"], ["actors"], ...);
        None keeps the full dump — at 1000 nodes a list_actors call must
        not pay for serializing the node table it throws away."""
        want = set(parts) if parts else None
        out: dict[str, dict] = {}
        if want is not None:
            if "nodes" in want:
                out["nodes"] = {
                    nid: {
                        "alive": n.alive, "resources": n.resources,
                        "available": n.available, "labels": n.labels,
                        "addr": list(n.addr),
                        "transfer_addr": (list(n.transfer_addr)
                                          if n.transfer_addr else None),
                    }
                    for nid, n in self.nodes.items()
                }
            if "actors" in want:
                out["actors"] = {
                    aid: {
                        "state": a.state, "name": a.name,
                        "namespace": a.namespace,
                        "node_id": a.node_id, "resources": a.resources,
                        "restarts": a.restarts_used,
                        "death_reason": a.death_reason,
                    }
                    for aid, a in self.actors.items()
                }
            if "placement_groups" in want:
                out["placement_groups"] = {
                    pid: {"state": pg["state"], "strategy": pg["strategy"],
                          "bundles": pg["bundles"], "name": pg.get("name")}
                    for pid, pg in self.pgs.items()
                }
            if "workers" in want:
                out["workers"] = {
                    wid: {"addr": [row[0], row[1]]}
                    for wid, row in self.workers.items()
                }
            return out
        return {
            "nodes": {
                nid: {
                    "alive": n.alive, "resources": n.resources,
                    "available": n.available, "labels": n.labels,
                    "addr": list(n.addr),
                    "transfer_addr": (list(n.transfer_addr)
                                      if n.transfer_addr else None),
                }
                for nid, n in self.nodes.items()
            },
            "actors": {
                aid: {
                    "state": a.state, "name": a.name, "namespace": a.namespace,
                    "node_id": a.node_id, "resources": a.resources,
                    "restarts": a.restarts_used, "death_reason": a.death_reason,
                }
                for aid, a in self.actors.items()
            },
            "placement_groups": {
                pid: {"state": pg["state"], "strategy": pg["strategy"],
                      "bundles": pg["bundles"], "name": pg.get("name")}
                for pid, pg in self.pgs.items()
            },
            "workers": {
                wid: {"addr": [row[0], row[1]]}
                for wid, row in self.workers.items()
            },
        }

    async def _get_task_events(self, conn: ServerConnection, since: int = 0,
                               limit: int = 100_000, epoch: str = ""):
        """Cursored task-event read: ``since`` is the monotone count of events
        the caller has already seen, so state-API polls ship only the delta
        instead of the full 100k-event history on every snapshot (reference:
        GcsTaskManager serves task events separately from the entity tables).
        ``epoch`` identifies this head incarnation — a mismatch tells the
        client its cursor (and cache) belong to a dead head and must reset.
        Events older than the deque cap are dropped silently."""
        import itertools

        if epoch and epoch != self._events_epoch:
            since = 0
        dropped = self._task_events_total - len(self.task_events)
        start = max(0, min(since, self._task_events_total) - dropped)
        events = list(itertools.islice(self.task_events, start, start + limit))
        return {"events": events, "cursor": dropped + start + len(events),
                "epoch": self._events_epoch}

    async def _cluster_load(self, conn: ServerConnection):
        """Autoscaler demand feed (reference: GcsAutoscalerStateManager's
        cluster resource state — per-node usage + pending demands + pending
        placement groups)."""
        return {
            "nodes": {
                nid: {"resources": n.resources, "available": n.available,
                      "alive": n.alive, "labels": n.labels,
                      "pending": len(n.pending_demands)}
                for nid, n in self.nodes.items()
            },
            "pending_demands": [
                d for n in self.nodes.values() if n.alive
                for d in n.pending_demands
            ],
            "pending_pg_bundles": [
                b for pg in self.pgs.values() if pg["state"] == "PENDING"
                for b in pg["bundles"]
            ],
        }

    # ------------------------------------------------------------------ resources
    async def _cluster_resources(self, conn: ServerConnection):
        out: dict[str, float] = {}
        for n in self.nodes.values():
            if n.alive:
                for k, v in n.resources.items():
                    out[k] = out.get(k, 0.0) + v
        return out

    async def _available_resources(self, conn: ServerConnection):
        out: dict[str, float] = {}
        for n in self.nodes.values():
            if n.alive:
                for k, v in n.available.items():
                    out[k] = out.get(k, 0.0) + v
        return out


async def run_head(host: str = "127.0.0.1", port: int = 0) -> HeadServer:
    head = HeadServer(host, port)
    await head.start()
    return head
