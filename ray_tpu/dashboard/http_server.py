"""Dashboard: HTTP JSON API over cluster state + Prometheus metrics.

Capability parity with the reference's dashboard head (reference:
python/ray/dashboard/head.py:49 DashboardHead with pluggable modules in
dashboard/modules/ — state, metrics, job; the reference adds a React client on
top of the same JSON API): a threaded HTTP server exposing the state API,
the task timeline, and the metrics registry. Extra modules (e.g. job
submission) register routes via ``add_route``.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable
from urllib.parse import parse_qs, urlparse


class DashboardServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._routes: dict[tuple[str, str], Callable] = {}
        self._register_builtin()
        dashboard = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _dispatch(self, method: str):
                parsed = urlparse(self.path)
                handler = dashboard._routes.get((method, parsed.path))
                if handler is None:
                    self.send_error(404, "no such route")
                    return
                try:
                    length = int(self.headers.get("Content-Length") or 0)
                    body = self.rfile.read(length) if length else b""
                    params = {k: v[0] for k, v in parse_qs(parsed.query).items()}
                    result = handler(params, body)
                except Exception as e:  # noqa: BLE001
                    self.send_response(500)
                    self.send_header("Content-Type", "application/json")
                    self.end_headers()
                    self.wfile.write(json.dumps({"error": repr(e)}).encode())
                    return
                if isinstance(result, tuple) and len(result) == 2:
                    payload, ctype = result  # (bytes|str, content-type)
                    payload = payload.encode() if isinstance(payload, str) else payload
                elif isinstance(result, (bytes, str)):
                    payload = result.encode() if isinstance(result, str) else result
                    ctype = "text/plain; version=0.0.4"
                else:
                    payload = json.dumps(result).encode()
                    ctype = "application/json"
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def do_GET(self):
                self._dispatch("GET")

            def do_POST(self):
                self._dispatch("POST")

            def do_DELETE(self):
                self._dispatch("DELETE")

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.addr = self._httpd.server_address
        self._thread: threading.Thread | None = None

    # ------------------------------------------------------------------ routes
    def add_route(self, method: str, path: str, handler: Callable) -> None:
        """handler(params: dict, body: bytes) -> json-able | str | bytes."""
        self._routes[(method, path)] = handler

    def _register_builtin(self):
        from ray_tpu import __version__
        from ray_tpu.core import events
        from ray_tpu.util import metrics, tracing
        from ray_tpu.util.state import api as state_api

        def listing(fn):
            # Query params become equality filters; ?limit=N caps the result
            # (e.g. /api/tasks?state=FAILED&limit=10).
            def handler(params, body):
                params = dict(params)
                limit = int(params.pop("limit", 10_000))
                filters = [(k, "=", v) for k, v in params.items()]
                return fn(filters=filters or None, limit=limit)

            return handler

        from ray_tpu.dashboard.ui import static_asset

        self.add_route("GET", "/", lambda p, b: static_asset("index.html"))
        self.add_route("GET", "/app.js", lambda p, b: static_asset("app.js"))
        self.add_route("GET", "/app.css",
                       lambda p, b: static_asset("app.css"))
        self.add_route("GET", "/api/version", lambda p, b: {"version": __version__})
        self.add_route("GET", "/api/nodes", listing(state_api.list_nodes))
        self.add_route("GET", "/api/actors", listing(state_api.list_actors))
        self.add_route("GET", "/api/tasks", listing(state_api.list_tasks))
        self.add_route("GET", "/api/task_summary", lambda p, b: state_api.summarize_tasks())
        self.add_route("GET", "/api/placement_groups",
                       listing(state_api.list_placement_groups))
        self.add_route("GET", "/api/objects", listing(state_api.list_objects))
        self.add_route("GET", "/api/timeline", lambda p, b: events.timeline())

        def traces(p, b):
            # Local spans plus, in cluster mode, every node's spans flushed
            # to the head. Deduped on (trace_id, span_id) — span ids are
            # minted per process, so two processes CAN collide on span_id
            # alone while a span replayed via both the local buffer and the
            # head must still collapse to one row. ?trace_id=<id> narrows
            # to one request's spans (the `ray_tpu trace` CLI's source);
            # ?exemplars=1 returns the histogram exemplar index instead —
            # the metrics→traces entry point.
            from ray_tpu.core.worker import global_worker

            if p.get("exemplars"):
                out = []
                for m in metrics.registry().snapshot().get("metrics", ()):
                    if m.get("exemplars"):
                        out.append({"metric": m["name"],
                                    "tag_keys": m.get("tag_keys", []),
                                    "exemplars": m["exemplars"]})
                return out
            want = p.get("trace_id")
            by_id = {(s.get("trace_id"), s["span_id"]): s
                     for s in tracing.export()}
            rt = global_worker.runtime
            if rt is not None and hasattr(rt, "cluster_spans"):
                try:
                    for s in rt.cluster_spans():
                        by_id.setdefault(
                            (s.get("trace_id"), s.get("span_id")), s)
                except Exception:
                    pass  # head unreachable: local view still useful
            rows = list(by_id.values())
            if want:
                rows = [s for s in rows if s.get("trace_id") == want]
            return rows

        self.add_route("GET", "/api/traces", traces)

        def metrics_export(p, b):
            # Federated Prometheus export (reference: the dashboard serving
            # the aggregate of every node's metrics agent): in cluster mode
            # each series carries a node_id label; per-node snapshots from
            # several processes merge (counters/histograms sum, gauges keep
            # the freshest). Local-only runtimes keep the plain export.
            from ray_tpu.core.worker import global_worker

            rt = global_worker.runtime
            if rt is None or not hasattr(rt, "get_telemetry"):
                return metrics.registry().export_prometheus()
            try:
                sources = rt.get_telemetry().get("sources", {})
            except Exception:
                return metrics.registry().export_prometheus()
            by_node: dict[str, list] = {}
            # Oldest-report-first per node: merge_snapshots keeps the LAST
            # reporter's value for gauges, so sorting by report ts makes
            # that the freshest one, as documented.
            for row in sorted(sources.values(),
                              key=lambda r: r.get("ts", 0.0)):
                nid = row.get("node_id") or "head"
                by_node.setdefault(nid, []).append(row.get("snapshot") or {})
            if not by_node:
                return metrics.registry().export_prometheus()
            per_node = {nid: metrics.merge_snapshots(snaps)
                        for nid, snaps in sorted(by_node.items())}
            return metrics.export_prometheus_federated(per_node)

        self.add_route("GET", "/metrics", metrics_export)

        def flight_records(p, b):
            from ray_tpu.core import flight_recorder

            name = p.get("name")
            if name:
                return flight_recorder.get_record(name)
            return flight_recorder.list_records()

        self.add_route("GET", "/api/flight_records", flight_records)

        # On-demand profiler (reference capability: `ray stack`/timeline +
        # jax.profiler, driven over HTTP). /api/profile blocks for the
        # capture window — the threaded server keeps the other routes live.
        self.add_route(
            "GET", "/api/profile",
            lambda p, b: state_api.profile_cluster(
                seconds=float(p.get("seconds", 2.0)),
                sample_hz=float(p.get("hz", 0.0))))
        self.add_route(
            "GET", "/api/stack",
            lambda p, b: (state_api.get_stack(p["worker"])
                          if p.get("worker")
                          else state_api.stack_cluster()))
        self.add_route("GET", "/api/memory/device",
                       lambda p, b: state_api.device_memory())
        self.add_route(
            "GET", "/api/stragglers",
            lambda p, b: state_api.stragglers(
                threshold=float(p.get("threshold", 1.15))))

        # Health watchdog: incident deque + rolling hot-path series
        # (?name=serve_ttft_s:p99 or a prefix like ?name=train_*).
        self.add_route(
            "GET", "/api/incidents",
            lambda p, b: state_api.incidents(
                since=float(p.get("since", 0.0)),
                limit=int(p.get("limit", 100)),
                incident_id=p.get("id")))
        self.add_route(
            "GET", "/api/timeseries",
            lambda p, b: state_api.timeseries(
                name=p.get("name"), source=p.get("source"),
                node_id=p.get("node_id"),
                tags=(json.loads(p["tags"]) if p.get("tags") else None),
                since=float(p.get("since", 0.0)),
                max_points=int(p.get("max_points", 0)),
                max_age_s=float(p.get("max_age_s", 0.0))))
        self.add_route("GET", "/api/watchdog",
                       lambda p, b: state_api.watchdog_status())
        # Goodput ledger rollup: per-run/fleet goodput % + badput
        # breakdown in chip-seconds (?run=<name> narrows).
        self.add_route("GET", "/api/goodput",
                       lambda p, b: state_api.get_goodput(run=p.get("run")))
        # Control-plane session facts: incarnation, uptime, restart count,
        # dedup/fence/reconcile odometers (head fault tolerance).
        self.add_route("GET", "/api/head",
                       lambda p, b: state_api.head_status())

        def cluster_status(p, b):
            from ray_tpu.core.worker import global_worker

            global_worker.check_connected()
            return {
                "cluster_resources": global_worker.runtime.cluster_resources(),
                "available_resources": global_worker.runtime.available_resources(),
            }

        self.add_route("GET", "/api/cluster_status", cluster_status)

        # Per-node log browsing (reference: dashboard log_manager endpoints
        # over the agent; here the state API proxies to node daemons).
        self.add_route(
            "GET", "/api/logs",
            lambda p, b: state_api.list_logs(node_id=p.get("node_id")))
        self.add_route(
            "GET", "/api/logs/get",
            lambda p, b: (state_api.get_log(
                p["filename"], p["node_id"],
                tail_bytes=int(p.get("tail_bytes", 65536))),
                "text/plain; charset=utf-8"))

    # ------------------------------------------------------------------ lifecycle
    def start(self) -> tuple[str, int]:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True, name="dashboard-http")
        self._thread.start()
        return self.addr

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()


_server: DashboardServer | None = None


def start_dashboard(host: str = "127.0.0.1", port: int = 0) -> DashboardServer:
    """Start (or return) the process dashboard server."""
    global _server
    if _server is None:
        _server = DashboardServer(host, port)
        _server.start()
    return _server
