"""Sebulba: CPU actor nodes streaming trajectory blocks to the learner.

The second Podracer shape (PAPERS.md): when envs cannot share a chip with
the learner (too many, or CPU-bound), dedicated actor nodes run batched
env steps and STREAM fixed-shape trajectory blocks to the learner through
the object plane. The decoupling is what buys throughput — the learner
never waits for a specific actor, actors never wait for the learner:

- ``SebulbaRunner`` actors hold ``num_envs_per_runner`` vectorized JAX
  envs and the jitted rollout from rl/anakin.py; each ``collect()``
  returns a small payload whose big arrays are ``ray_tpu.put`` store
  refs (zero-copy ndarrays, the llm/pd.py hand-off pattern), so the
  actor->learner frame stays tiny and the bytes move lazily.
- Submission is ``.remote()`` — the PR-3 control-plane fast path
  (raw-dispatched push_actor_call frames, call_nowait underneath), so
  keeping every runner busy costs the learner no round-trips.
- A learner-side prefetch THREAD waits on in-flight collects, batch-gets
  ready blocks into host memory, resubmits the runner (pushing fresh
  weights first when the learner has advanced), and feeds a bounded
  ``queue.Queue`` — the staleness window (``cfg.sebulba_staleness``
  weight versions) is enforced at consume time, and the bounded queue is
  the backpressure that keeps memory flat when actors outrun the
  learner.

Learner-side shared state is exactly the shape rtlint R1/R3 exist for:
``_latest_weights`` (written by ``step()``, read by the prefetch thread)
sits behind ``_lock``; block hand-off rides the thread-safe queue; the
in-flight map is touched only by the prefetch thread after start().
"""

from __future__ import annotations

import queue
import threading

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.devtools.annotations import guarded_by
from ray_tpu.rl.anakin import make_rollout_fn
from ray_tpu.rl.ppo import (compute_gae_jit, init_policy, mlp_apply,
                            ppo_update)
from ray_tpu.rl.vec_env import make_jax_env


class SebulbaRunner:
    """One actor node: N vectorized JAX envs + the current policy, all
    stepping inside one jitted scan per ``collect()``."""

    def __init__(self, env_name: str, num_envs: int, unroll_len: int,
                 hidden: int, seed: int, params_seed: int):
        env = make_jax_env(env_name)
        self.env = env
        self.num_envs = num_envs
        apply_pi = lambda p, o: mlp_apply(p["pi"], o)
        self._apply_vf = jax.jit(lambda p, o: mlp_apply(p["vf"], o)[..., 0])
        self._rollout = jax.jit(
            make_rollout_fn(env, apply_pi,
                            lambda p, o: mlp_apply(p["vf"], o)[..., 0],
                            unroll_len))
        key = jax.random.PRNGKey(seed)
        key, ke = jax.random.split(key)
        self._env_states, self._obs = jax.vmap(env.reset)(
            jax.random.split(ke, num_envs))
        self._ep_ret = jnp.zeros((num_envs,))
        self._key = key
        # Actors also act before the first weight push lands — from the
        # learner's own init seed, so the version-0 behavior policy (and
        # the logp it stamps into blocks) is exactly the learner's.
        self.params = init_policy(jax.random.PRNGKey(params_seed),
                                  env.observation_size, env.num_actions,
                                  hidden)
        self.version = 0

    def set_weights(self, params, version: int) -> None:
        self.params = jax.tree.map(jnp.asarray, params)
        self.version = version

    def collect(self) -> dict:
        """One fixed-shape [T, N, ...] trajectory block. Big arrays go
        through the object plane as store-backed refs; the returned
        payload itself stays small."""
        import ray_tpu

        (self._env_states, self._obs, self._ep_ret, self._key), traj, \
            ep_stats = self._rollout(self.params, self._env_states,
                                     self._obs, self._ep_ret, self._key)
        last_values = self._apply_vf(self.params, self._obs)
        refs = {k: ray_tpu.put(np.asarray(v)) for k, v in traj.items()}
        return {
            "version": self.version,
            "refs": refs,
            "last_values": np.asarray(last_values),
            "ep_ret_sum": float(ep_stats["ret_sum"]),
            "ep_count": float(ep_stats["count"]),
        }

    def ping(self) -> bool:
        return True


@guarded_by("_lock", "_latest_weights", "_pushed_version")
class SebulbaPPO:
    """Learner driving a fleet of SebulbaRunner actors; rl/ppo.py's PPO
    delegates here for ``vectorized=True`` + ``num_env_runners > 0``."""

    def __init__(self, cfg):
        import ray_tpu

        self.cfg = cfg
        self.unroll_len = cfg.unroll_len or cfg.rollout_len
        self.rollouts_per_step = int(
            cfg.extra.get("rollouts_per_step", cfg.num_env_runners))
        env = make_jax_env(cfg.env)
        self.params = init_policy(jax.random.PRNGKey(cfg.seed),
                                  env.observation_size, env.num_actions,
                                  cfg.hidden)
        self.optimizer = optax.adam(cfg.lr)
        self.opt_state = self.optimizer.init(self.params)
        self.weight_version = 0
        self.dropped_stale = 0
        self._return_window: list[float] = []

        RunnerActor = ray_tpu.remote(SebulbaRunner)
        self._actors = [
            RunnerActor.options(num_cpus=0).remote(
                cfg.env, cfg.num_envs_per_runner, self.unroll_len,
                cfg.hidden, cfg.seed + 1000 * i + 17, cfg.seed)
            for i in range(cfg.num_env_runners)]
        ray_tpu.get([a.ping.remote() for a in self._actors], timeout=120)

        self._lock = threading.Lock()
        self._latest_weights = (None, 0)   # (weights ref, version)
        self._pushed_version = [0] * len(self._actors)
        # Bounded hand-off: depth 2 per runner ~= double buffering; when
        # the learner lags, the prefetch thread blocks here and ready
        # blocks wait in the store instead of accumulating on the heap.
        self._queue: queue.Queue = queue.Queue(
            maxsize=2 * max(1, len(self._actors)))
        self._stop = threading.Event()
        # In-flight map is prefetch-thread-owned after start (initial
        # submission happens before the thread exists).
        self._inflight = {a.collect.remote(): i
                          for i, a in enumerate(self._actors)}
        self._prefetch = threading.Thread(
            target=self._prefetch_loop, daemon=True,
            name="sebulba-prefetch")
        self._prefetch.start()

    # -- prefetch thread --------------------------------------------------
    def _prefetch_loop(self) -> None:
        import ray_tpu

        while not self._stop.is_set():
            try:
                ready, _ = ray_tpu.wait(list(self._inflight),
                                        num_returns=1, timeout=0.2)
            except Exception:
                if self._stop.is_set():
                    return
                continue
            if not ready:
                continue
            ref = ready[0]
            idx = self._inflight.pop(ref)
            try:
                payload = ray_tpu.get(ref, timeout=60)
                # ONE batched materialize for the whole block (llm/pd.py
                # pattern), not a get per array.
                names = list(payload["refs"])
                arrays = ray_tpu.get([payload["refs"][n] for n in names],
                                     timeout=60)
            except ray_tpu.ActorDiedError:
                continue  # runner fleet is fixed-size; drop its slot
            block = dict(zip(names, arrays))
            block["last_values"] = payload["last_values"]
            item = {"version": payload["version"], "block": block,
                    "ep_ret_sum": payload["ep_ret_sum"],
                    "ep_count": payload["ep_count"]}
            actor = self._actors[idx]
            with self._lock:
                w_ref, w_ver = self._latest_weights
                need_push = w_ref is not None and \
                    self._pushed_version[idx] < w_ver
                if need_push:
                    self._pushed_version[idx] = w_ver
            if need_push:
                # Fire-and-forget: .remote() rides the push-frame fast
                # path; actor mailbox FIFO means the next collect() uses
                # these weights.
                actor.set_weights.remote(w_ref, w_ver)
            self._inflight[actor.collect.remote()] = idx
            while not self._stop.is_set():
                try:
                    self._queue.put(item, timeout=0.2)
                    break
                except queue.Full:
                    continue

    # -- learner ----------------------------------------------------------
    def step(self) -> dict:
        import ray_tpu

        cfg = self.cfg
        blocks = []
        attempts = 0
        while len(blocks) < self.rollouts_per_step:
            item = self._queue.get(timeout=120)
            attempts += 1
            if self.weight_version - item["version"] > cfg.sebulba_staleness:
                self.dropped_stale += 1
                if attempts > 20 * self.rollouts_per_step:
                    raise RuntimeError("sebulba: only stale blocks arriving")
                continue
            blocks.append(item)
        # Introspection hook (tests assert the staleness bound on what was
        # actually consumed, not just on the drop counter).
        self.last_consumed_versions = [b["version"] for b in blocks]
        flats = []
        for item in blocks:
            b = item["block"]
            adv, ret = compute_gae_jit(
                jnp.asarray(b["rewards"]), jnp.asarray(b["values"]),
                jnp.asarray(b["dones"]), jnp.asarray(b["last_values"]),
                cfg.gamma, cfg.gae_lambda)
            flats.append({
                "obs": b["obs"].reshape(-1, b["obs"].shape[-1]),
                "actions": b["actions"].reshape(-1),
                "logp": b["logp"].reshape(-1),
                "advantages": np.asarray(adv).reshape(-1),
                "returns": np.asarray(ret).reshape(-1),
            })
            if item["ep_count"]:
                self._return_window.append(
                    item["ep_ret_sum"] / item["ep_count"])
        batch = {k: jnp.asarray(np.concatenate([f[k] for f in flats]))
                 for k in flats[0]}
        static = (cfg.clip, cfg.vf_coef, cfg.ent_coef, cfg.num_minibatches,
                  cfg.num_epochs)
        self.params, self.opt_state, stats = ppo_update(
            self.optimizer, static, self.params, self.opt_state, batch,
            cfg.seed + self.weight_version)
        self.weight_version += 1
        host = jax.tree.map(np.asarray, self.params)
        w_ref = ray_tpu.put(host)   # one broadcast object for the fleet
        with self._lock:
            self._latest_weights = (w_ref, self.weight_version)
        self._return_window = self._return_window[-100:]
        mean_ret = (float(np.mean(self._return_window))
                    if self._return_window else 0.0)
        return {
            "episode_return_mean": mean_ret,
            "num_env_steps_sampled": int(batch["obs"].shape[0]),
            "weight_version": self.weight_version,
            "dropped_stale": self.dropped_stale,
            **{k: float(v) for k, v in stats.items()},
        }

    # -- checkpoint plumbing ----------------------------------------------
    def host_params(self):
        return jax.tree.map(np.asarray, self.params)

    def set_params(self, params) -> None:
        self.params = jax.tree.map(jnp.asarray, params)

    def shutdown(self) -> None:
        import ray_tpu

        self._stop.set()
        self._prefetch.join(timeout=5)
        for a in self._actors:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass
