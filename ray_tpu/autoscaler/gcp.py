"""GCE/GKE cloud provider: real machine provisioning for the autoscaler.

Capability parity with the reference's GCP provider (reference:
python/ray/autoscaler/_private/gcp/node_provider.py — GCE instances
labeled with the cluster/node-type, created/terminated through the
compute REST API, status polled and mapped to the autoscaler's states;
TPU pods provisioned as whole slices). This build is TPU-first: besides
plain GCE VMs (CPU worker nodes), TPU slices provision through the Cloud
TPU *queued resources* API as atomic multi-host units and surface through
TpuSliceProvider (node_provider.py), matching SURVEY.md §8.8 ("a TPU
GCE/GKE provider slots in as a cloud provider that launches whole slices
rather than single VMs").

Networking is injectable: every REST call goes through ``request_fn``
(method, url, body-dict|None) -> response-dict. The default uses urllib
with a metadata-server token; air-gapped tests inject a mock. No GCP
dependency is imported.
"""

from __future__ import annotations

import json
from typing import Callable

from ray_tpu.autoscaler.node_provider import NodeProvider, TpuSliceProvider

COMPUTE_API = "https://compute.googleapis.com/compute/v1"
TPU_API = "https://tpu.googleapis.com/v2"
METADATA_TOKEN_URL = ("http://metadata.google.internal/computeMetadata/v1/"
                      "instance/service-accounts/default/token")

# GCE instance status -> autoscaler provider status
_GCE_STATUS = {
    "PROVISIONING": "pending",
    "STAGING": "pending",
    "RUNNING": "running",
    "STOPPING": "terminated",
    "SUSPENDED": "terminated",
    "TERMINATED": "terminated",
}

# Cloud TPU queued-resource state -> autoscaler provider status
_TPU_STATE = {
    "ACCEPTED": "pending",
    "PROVISIONING": "pending",
    "WAITING_FOR_RESOURCES": "pending",
    "CREATING": "pending",
    "ACTIVE": "running",
    "DELETING": "terminated",
    "SUSPENDED": "terminated",
    "FAILED": "failed",
}


class NotFoundError(Exception):
    """The resource is gone at the API (HTTP 404)."""


_token_cache: list = [0.0, None]  # (expiry_monotonic, token)


def _metadata_token() -> str:
    """Metadata-server OAuth token, cached for its lifetime (a status poll
    per node per reconcile tick must not hammer the metadata server)."""
    import time as _time
    import urllib.request

    now = _time.monotonic()
    if _token_cache[1] is not None and now < _token_cache[0]:
        return _token_cache[1]
    tok_req = urllib.request.Request(
        METADATA_TOKEN_URL, headers={"Metadata-Flavor": "Google"})
    with urllib.request.urlopen(tok_req, timeout=10) as r:
        payload = json.loads(r.read())
    _token_cache[0] = now + max(60.0, payload.get("expires_in", 3600) - 120)
    _token_cache[1] = payload["access_token"]
    return _token_cache[1]


def _default_request_fn(method: str, url: str,
                        body: dict | None = None) -> dict:
    """urllib transport with a cached metadata-server bearer token.
    Raises NotFoundError on 404 so status polls can distinguish "gone"
    from a transient API hiccup."""
    import urllib.error
    import urllib.request

    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        url, data=data, method=method,
        headers={"Authorization": f"Bearer {_metadata_token()}",
                 "Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            payload = r.read()
    except urllib.error.HTTPError as e:
        if e.code == 404:
            raise NotFoundError(url) from None
        raise
    return json.loads(payload) if payload else {}


class GceNodeProvider(NodeProvider):
    """CPU worker nodes as labeled GCE instances (reference:
    gcp/node_provider.py instance lifecycle). ``node_configs`` maps the
    autoscaler's node_type to the GCE machine config (machine_type, disk,
    image, ...); every instance gets ray-cluster/ray-node-type labels and
    a startup script that joins the head, registering with the instance
    name as its cluster node id (which is how runtime_node_id resolves)."""

    def __init__(self, project: str, zone: str, cluster_name: str,
                 head_addr: str, node_configs: dict[str, dict],
                 request_fn: Callable[..., dict] | None = None):
        self.project = project
        self.zone = zone
        self.cluster_name = cluster_name
        self.head_addr = head_addr
        self.node_configs = node_configs
        self._request = request_fn or _default_request_fn
        self._instances: dict[str, str] = {}  # cloud_id -> instance name

    # -- REST helpers -------------------------------------------------------
    def _url(self, path: str) -> str:
        return (f"{COMPUTE_API}/projects/{self.project}/zones/{self.zone}"
                f"/{path}")

    def _startup_script(self) -> str:
        # The booted VM joins the cluster under its own instance name so
        # the provider can correlate cloud instance <-> cluster node.
        return ("#!/bin/bash\n"
                f"python -m ray_tpu start --address={self.head_addr} "
                "--node-id=$(hostname)\n")

    # -- NodeProvider surface ----------------------------------------------
    def launch_node(self, node_type: str, resources: dict[str, float],
                    labels: dict[str, str] | None = None) -> str:
        import uuid

        cfg = self.node_configs[node_type]
        # uuid suffix: a counter would reset across provider restarts and
        # collide (409) with instances the previous incarnation launched.
        name = (f"rtpu-{self.cluster_name}-{node_type}-"
                f"{uuid.uuid4().hex[:8]}")
        body = {
            "name": name,
            "machineType": (f"zones/{self.zone}/machineTypes/"
                            f"{cfg.get('machine_type', 'n2-standard-8')}"),
            "labels": {
                "ray-cluster": self.cluster_name,
                "ray-node-type": node_type,
                **(labels or {}),
            },
            "disks": [{
                "boot": True,
                "initializeParams": {
                    "sourceImage": cfg.get(
                        "source_image",
                        "projects/debian-cloud/global/images/family/"
                        "debian-12"),
                    "diskSizeGb": str(cfg.get("disk_gb", 100)),
                },
            }],
            "networkInterfaces": [
                {"network": cfg.get("network", "global/networks/default")}],
            "metadata": {"items": [
                {"key": "startup-script", "value": self._startup_script()},
            ]},
        }
        self._request("POST", self._url("instances"), body)
        cloud_id = f"gce-{name}"
        self._instances[cloud_id] = name
        return cloud_id

    def terminate_node(self, cloud_id: str) -> None:
        name = self._instances.pop(cloud_id, None)
        if name is not None:
            self._request("DELETE", self._url(f"instances/{name}"))

    def node_status(self, cloud_id: str) -> str:
        name = self._instances.get(cloud_id)
        if name is None:
            return "terminated"
        try:
            info = self._request("GET", self._url(f"instances/{name}"))
        except (NotFoundError, KeyError):
            return "terminated"  # deleted out-of-band (e.g. preempted)
        except Exception:  # noqa: BLE001 - transient API hiccup
            return "pending"
        return _GCE_STATUS.get(info.get("status", ""), "pending")

    def runtime_node_id(self, cloud_id: str) -> str | None:
        # The startup script registers under the instance hostname; once
        # RUNNING the cluster node id IS the instance name.
        name = self._instances.get(cloud_id)
        if name is None or self.node_status(cloud_id) != "running":
            return None
        return name


class GcpTpuQueuedResourceClient:
    """Whole-TPU-slice provisioning through the Cloud TPU queued-resources
    API (reference: the slice reservation path behind
    python/ray/_private/accelerators/tpu.py reserve_tpu_slice — queued
    resources are how multi-host slices are atomically requested)."""

    def __init__(self, project: str, zone: str, runtime_version: str =
                 "tpu-ubuntu2204-base",
                 request_fn: Callable[..., dict] | None = None):
        self.project = project
        self.zone = zone
        self.runtime_version = runtime_version
        self._request = request_fn or _default_request_fn

    def _base(self) -> str:
        return (f"{TPU_API}/projects/{self.project}/locations/{self.zone}"
                f"/queuedResources")

    def create_slice(self, name: str, accelerator_type: str,
                     topology: str) -> None:
        body = {
            "tpu": {"nodeSpec": [{
                "parent": f"projects/{self.project}/locations/{self.zone}",
                "nodeId": name,
                "node": {
                    "acceleratorConfig": {
                        "type": accelerator_type.upper(),
                        "topology": topology,
                    },
                    "runtimeVersion": self.runtime_version,
                },
            }]},
        }
        self._request("POST", f"{self._base()}?queuedResourceId={name}", body)

    def delete_slice(self, name: str) -> None:
        self._request("DELETE", f"{self._base()}/{name}?force=true")

    def slice_status(self, name: str) -> str:
        try:
            info = self._request("GET", f"{self._base()}/{name}")
        except (NotFoundError, KeyError):
            return "terminated"  # deleted out-of-band
        except Exception:  # noqa: BLE001 - transient API hiccup
            return "pending"
        state = info.get("state", {})
        if isinstance(state, dict):
            state = state.get("state", "")
        return _TPU_STATE.get(state, "pending")


def tpu_slice_provider_from_gcp(project: str, zone: str,
                                accelerator_type: str, topology: str,
                                request_fn: Callable[..., dict] | None = None,
                                node_id_fn: Callable[[str], str | None]
                                | None = None) -> TpuSliceProvider:
    """TpuSliceProvider wired to the real GCP queued-resources API: the
    autoscaler's atomic slice unit backed by actual cloud calls
    (injectable transport for tests/air-gapped use)."""
    client = GcpTpuQueuedResourceClient(project, zone,
                                        request_fn=request_fn)
    return TpuSliceProvider(
        accelerator_type, topology,
        create_slice_fn=client.create_slice,
        delete_slice_fn=client.delete_slice,
        status_fn=client.slice_status,
        node_id_fn=node_id_fn,
    )


__all__ = [
    "GceNodeProvider",
    "GcpTpuQueuedResourceClient",
    "tpu_slice_provider_from_gcp",
]
