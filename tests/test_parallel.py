"""Mesh construction and sharding-rule tables."""

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from ray_tpu.parallel.mesh import MeshSpec, build_mesh, hybrid_mesh
from ray_tpu.parallel.sharding import (
    ShardingRules,
    normalize_spec,
    shard_params,
    tree_shardings,
)


def test_mesh_spec_sizes():
    spec = MeshSpec(dp=2, tp=4)
    assert spec.num_devices == 8
    assert spec.axis_sizes()["dp"] == 2
    assert spec.with_total(16, grow="dp").dp == 4


def test_build_mesh(cpu_mesh_devices):
    mesh = build_mesh(MeshSpec(dp=2, fsdp=2, tp=2), cpu_mesh_devices)
    assert mesh.shape["dp"] == 2
    assert mesh.shape["tp"] == 2
    assert mesh.devices.size == 8


def test_mesh_too_big_raises(cpu_mesh_devices):
    with pytest.raises(ValueError):
        build_mesh(MeshSpec(dp=100), cpu_mesh_devices)


def test_hybrid_mesh_dcn_outermost(cpu_mesh_devices):
    spec = MeshSpec(dp=2, fsdp=4, dcn_axes=("dp",))
    mesh = hybrid_mesh(spec, num_slices=2, devices_per_slice=4,
                       devices=cpu_mesh_devices)
    # each dp row (slice) must hold a contiguous run of devices
    ids = np.vectorize(lambda d: d.id)(mesh.devices)
    flat = ids.reshape(2, -1)
    for s in range(2):
        assert set(flat[s]) == set(range(s * 4, (s + 1) * 4))


def test_sharding_rules_spec():
    rules = ShardingRules()
    assert rules.spec("batch", "seq", "act_embed") == P(("dp", "fsdp"), "sp", None)
    # normalize both sides: jax 0.4.x keeps P(("fsdp",)) and P("fsdp")
    # distinct objects; >=0.5 normalizes at construction
    assert normalize_spec(rules.spec("embed", "mlp")) == \
        normalize_spec(P(("fsdp",), "tp"))
    assert rules.spec(None, "heads") == P(None, "tp")


def test_sharding_rules_no_duplicate_axis():
    rules = ShardingRules()
    # same mesh axis twice in one spec must not repeat
    s = rules.spec("mlp", "heads")  # both map to tp
    assert s == P("tp", None)


def test_rules_override():
    rules = ShardingRules().override(embed="tp")
    assert rules.spec("embed") == P("tp")


def test_shard_params_places_on_mesh(cpu_mesh_devices):
    mesh = build_mesh(MeshSpec(fsdp=2, tp=4), cpu_mesh_devices)
    params = {
        "wq": np.ones((16, 32), np.float32),
        "wo": np.ones((32, 16), np.float32),
    }
    logical = {"wq": ("embed", "heads"), "wo": ("heads", "embed")}
    sharded = shard_params(params, mesh, logical)
    assert normalize_spec(sharded["wq"].sharding.spec) == \
        normalize_spec(P(("fsdp",), "tp"))
    # value preserved
    np.testing.assert_allclose(np.asarray(sharded["wq"]), params["wq"])


def test_tree_shardings_structure(cpu_mesh_devices):
    mesh = build_mesh(MeshSpec(dp=8), cpu_mesh_devices)
    tree = {"a": ("batch", None), "b": {"c": ("embed",)}}
    sh = tree_shardings(mesh, tree)
    assert sh["a"].spec == P(("dp", "fsdp"), None)
    assert sh["b"]["c"].spec == P("fsdp")


# ---------------------------------------------------------------------------
# pipeline parallelism (parallel/pipeline.py)
# ---------------------------------------------------------------------------

def test_pp_matches_single_device(cpu_mesh_devices):
    """pp=2 (x dp=2) pipeline loss/step must match the plain single-device
    step numerically (same init, same batch)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from ray_tpu.models.llama import LlamaConfig, init_params, loss_fn
    from ray_tpu.parallel.mesh import MeshSpec, build_mesh
    from ray_tpu.parallel.pipeline import make_pp_train_step

    cfg = LlamaConfig.tiny()  # 2 layers -> 2 stages of 1
    mesh = build_mesh(MeshSpec(pp=2, dp=2), cpu_mesh_devices[:4])
    opt = optax.sgd(0.1)
    step_fn, init_state, shard = make_pp_train_step(
        cfg, mesh, num_microbatches=2, optimizer=opt, attn_impl="blockwise")
    state = init_state()

    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size, (4, 16), dtype=np.int32)
    targets = np.roll(tokens, -1, axis=1)

    state, metrics = step_fn(state, shard(tokens), shard(targets))
    pp_loss = float(metrics["loss"])

    # Reference: plain loss on one device with identical params.
    params = init_params(cfg, jax.random.PRNGKey(0))
    ref_loss = float(loss_fn(cfg, params, jnp.asarray(tokens),
                             jnp.asarray(targets), attn_impl="blockwise",
                             remat=False, fused_ce=False))
    # 5e-4: jax 0.4.x CPU accumulation order drifts the pipeline's f32 sum
    # ~2e-4 relative from the single-device reference (measured 2.15e-4 on
    # 0.4.37); real grad bugs show up orders of magnitude larger (the
    # trajectory check below would also catch them).
    np.testing.assert_allclose(pp_loss, ref_loss, rtol=5e-4, atol=5e-4)

    # And training makes progress over a few steps.
    for _ in range(3):
        state, metrics = step_fn(state, shard(tokens), shard(targets))
    assert float(metrics["loss"]) < ref_loss


def test_pp_grads_match_single_device(cpu_mesh_devices):
    """One SGD step under the pipeline must produce the same loss trajectory
    as the plain step (grad correctness incl. tied-embedding psum)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from ray_tpu.models.llama import LlamaConfig, init_params, loss_fn
    from ray_tpu.parallel.mesh import MeshSpec, build_mesh
    from ray_tpu.parallel.pipeline import make_pp_train_step
    from ray_tpu.train.spmd import make_llama_train_step

    cfg = LlamaConfig.tiny()
    rng = np.random.default_rng(1)
    tokens = rng.integers(0, cfg.vocab_size, (4, 16), dtype=np.int32)
    targets = np.roll(tokens, -1, axis=1)

    # pipeline step
    mesh_pp = build_mesh(MeshSpec(pp=2), cpu_mesh_devices[:2])
    opt = optax.sgd(0.1)
    pstep, pinit, pshard = make_pp_train_step(
        cfg, mesh_pp, num_microbatches=2, optimizer=opt,
        attn_impl="blockwise")
    pstate = pinit()
    pstate, _ = pstep(pstate, pshard(tokens), pshard(targets))
    pstate, pm = pstep(pstate, pshard(tokens), pshard(targets))

    # plain step
    mesh_1 = build_mesh(MeshSpec(dp=1), cpu_mesh_devices[:1])
    sstep, sinit, sshard = make_llama_train_step(
        cfg, mesh_1, optimizer=optax.sgd(0.1), attn_impl="blockwise",
        remat=False)
    sstate = sinit()
    sstate, _ = sstep(sstate, sshard(tokens), sshard(targets))
    sstate, sm = sstep(sstate, sshard(tokens), sshard(targets))

    # after one identical update, the second-step losses must agree
    np.testing.assert_allclose(float(pm["loss"]), float(sm["loss"]),
                               rtol=2e-3, atol=2e-3)


# ---------------------------------------------------------------------------
# multi-slice fast path: ZeRO-1 sharded update, hierarchical/quantized DCN
# gradient sync, microbatch accumulation (train/spmd.py + parallel/sharding)
# ---------------------------------------------------------------------------

def test_hlo_stats_cost_model():
    """collective_stats prices sync and async (-start, tuple-result) forms
    identically, counts reduce-scatter against its full INPUT (the output is
    the 1/group shard), and zeroes intra-slice ops."""
    from ray_tpu.parallel.hlo_stats import collective_stats, mesh_slice_map

    slice_of = mesh_slice_map(8, 2)  # partitions 0-3 slice 0, 4-7 slice 1
    groups = "replica_groups={{0,1,2,3,4,5,6,7}}"
    sync = f"%r = f32[256]{{0}} all-reduce(f32[256]{{0}} %p), {groups}"
    async_ = (f"%r = (f32[256]{{0}}, f32[256]{{0}}) all-reduce-start("
              f"f32[256]{{0}} %p), {groups}")
    s_sync = collective_stats(sync, slice_of)
    s_async = collective_stats(async_, slice_of)
    # ring all-reduce over m=2 slices: 2*(m-1)/m*1024B*8 members = 8192B;
    # the async tuple's operand alias must not double it
    assert s_sync.dcn_bytes == s_async.dcn_bytes == 8192
    # reduce-scatter: output is the 1/8 shard (128B) but the ring moves
    # (m-1)/m of the full 1024B input per member
    rs = collective_stats(
        f"%r = f32[32]{{0}} reduce-scatter(f32[256]{{0}} %p), {groups}",
        slice_of)
    assert rs.dcn_bytes == int(0.5 * 128 * 8) * 8
    # previously-unmatched async spellings are now counted
    rs2 = collective_stats(
        f"%r = (f32[256]{{0}}, f32[32]{{0}}) reduce-scatter-start("
        f"f32[256]{{0}} %p), {groups}", slice_of)
    assert rs2.dcn_bytes == rs.dcn_bytes
    # multi-operand async start: nested ((operands...), (results...)) tuple
    # prices the results, same as two sync ops would
    multi = collective_stats(
        f"%r = ((f32[256]{{0}}, f32[128]{{0}}), (f32[256]{{0}}, "
        f"f32[128]{{0}})) all-reduce-start(f32[256]{{0}} %p0, "
        f"f32[128]{{0}} %p1), {groups}", slice_of)
    assert multi.dcn_bytes == 12288 and multi.skipped_ops == 0
    # TPU tiled layouts put parens INSIDE shapes ({0:T(8,128)}); operand
    # subtraction must span the whole call, not stop at the first ")"
    tiled = collective_stats(
        f"%r = ((f32[256]{{0:T(8,128)}}, f32[128]{{0:T(8,128)}}), "
        f"(f32[256]{{0:T(8,128)}}, f32[128]{{0:T(8,128)}})) all-reduce-start("
        f"f32[256]{{0:T(8,128)}} %p0, f32[128]{{0:T(8,128)}} %p1), {groups}",
        slice_of)
    assert tiled.dcn_bytes == 12288 and tiled.skipped_ops == 0
    # intra-slice group: no DCN bytes
    intra = collective_stats(
        "%r = f32[256]{0} all-reduce(f32[256]{0} %p), "
        "replica_groups={{0,1,2,3},{4,5,6,7}}", slice_of)
    assert intra.dcn_bytes == 0 and not intra.ops[0].crosses_slices
    # iota form spans slices the same way the explicit list does
    iota = collective_stats(
        "%r = f32[256]{0} all-reduce(f32[256]{0} %p), "
        "replica_groups=[1,8]<=[8]", slice_of)
    assert iota.dcn_bytes == 8192
    # replica_groups={} = one group of everyone: priced when n_partitions
    # is known, surfaced as skipped (never silently dropped) when not
    empty = "%r = f32[256]{0} all-reduce(f32[256]{0} %p), replica_groups={}"
    priced = collective_stats(empty, slice_of, n_partitions=8)
    assert priced.dcn_bytes == 8192 and priced.skipped_ops == 0
    unpriced = collective_stats(empty, slice_of)
    assert unpriced.dcn_bytes == 0 and unpriced.skipped_ops == 1


@pytest.mark.multidevice
def test_zero1_spec_dim_choice():
    """zero1_spec shards the largest divisible dim, skipping scan ("layers")
    and gather-indexed ("vocab") dims, and leaves non-divisible leaves
    replicated."""
    from jax.sharding import PartitionSpec as P

    from ray_tpu.parallel.mesh import MeshSpec, build_mesh
    from ray_tpu.parallel.sharding import zero1_spec

    mesh = build_mesh(MeshSpec(dp=2, fsdp=4))
    axes = ("dp", "fsdp")
    # stacked layer leaf: layers dim skipped, embed (largest) sharded
    assert zero1_spec(P(), (2, 128, 8, 16), mesh, axes,
                      logical=("layers", "embed", "heads", "head_dim")) == \
        P(None, ("dp", "fsdp"))
    # embedding: vocab skipped even though largest
    assert zero1_spec(P(), (512, 64), mesh, axes,
                      logical=("vocab", "embed")) == P(None, ("dp", "fsdp"))
    # existing sharded axis is kept and extended on its dim when divisible
    assert zero1_spec(P("tp"), (64, 16), mesh, axes) == P(("tp", "dp", "fsdp"))
    # nothing divisible -> unchanged (update stays replicated)
    assert zero1_spec(P(), (3, 5), mesh, axes) == P()
    # without logical info: plain largest-divisible-dim choice
    assert zero1_spec(P(), (16, 64), mesh, axes) == P(None, ("dp", "fsdp"))


@pytest.mark.multidevice
def test_multislice_step_parity_and_sharded_state(cpu_mesh_devices):
    """The sync modes on the 2-slice hybrid mesh: hier and zero1 match the
    flat step exactly (fp32 hierarchy is a pure reorder), the int8 DCN
    stage stays within its documented tolerance, microbatch accumulation
    matches the one-shot step, grad_norm_every gates the norm metric, and
    zero1 moments live 1/8-sized per device sharded over the whole dp
    world."""
    import jax
    import numpy as np
    import optax

    from ray_tpu.models.llama import LlamaConfig
    from ray_tpu.parallel.mesh import MeshSpec, hybrid_mesh
    from ray_tpu.parallel.sharding import ShardingRules
    from ray_tpu.train.optim import optimizer_state_bytes
    from ray_tpu.train.spmd import make_llama_train_step

    spec = MeshSpec(dp=2, fsdp=4, dcn_axes=("dp",))
    mesh = hybrid_mesh(spec, num_slices=2, devices_per_slice=4,
                       devices=cpu_mesh_devices)
    ddp = ShardingRules().override(vocab=None, embed=None, mlp=None,
                                   heads=None, kv_heads=None)
    cfg = LlamaConfig.tiny()
    opt = optax.adamw(1e-2)
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size, (16, 16), dtype=np.int32)
    targets = np.roll(tokens, -1, axis=1)

    losses = {}
    states = {}
    for name, kw in [
        ("flat", {}),
        ("hier", dict(dcn_axes=("dp",))),
        ("zero1", dict(zero1=True, dcn_axes=("dp",))),
        ("zero1_q8", dict(zero1=True, dcn_axes=("dp",), dcn_quant="int8")),
        ("accum", dict(zero1=True, dcn_axes=("dp",), grad_accum=2,
                       grad_norm_every=2)),
    ]:
        step, init, shard = make_llama_train_step(
            cfg, mesh, rules=ddp, optimizer=opt, attn_impl="blockwise",
            remat=False, **kw)
        state = init()
        tr, gn = [], []
        for _ in range(3):
            state, m = step(state, shard(tokens), shard(targets))
            tr.append(float(m["loss"]))
            gn.append(float(m["grad_norm"]))
        losses[name] = tr
        states[name] = state
        if name == "accum":
            # grad_norm_every=2: step counter 0 computes, 1 skips (-1), 2
            # computes again.
            assert gn[0] > 0 and gn[2] > 0
            assert gn[1] == -1.0
        else:
            assert all(v > 0 for v in gn)

    # fp32 hierarchy + zero1: exact parity with the flat allreduce path
    np.testing.assert_allclose(losses["hier"], losses["flat"],
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(losses["zero1"], losses["flat"],
                               rtol=1e-6, atol=1e-6)
    # microbatch accumulation: same math as the one-shot zero1 step
    np.testing.assert_allclose(losses["accum"], losses["zero1"],
                               rtol=1e-5, atol=1e-5)
    # int8 DCN stage: documented tolerance, and visibly quantized
    np.testing.assert_allclose(losses["zero1_q8"], losses["flat"],
                               rtol=0, atol=2e-2)
    assert losses["zero1_q8"][1] != losses["flat"][1]

    # zero1 optimizer moments: every leaf sharded over the full dp world
    # (dp x fsdp = 8), so per-device state is 1/8 of the replicated one.
    mu = states["zero1"].opt_state[0].mu
    for leaf in jax.tree.leaves(mu):
        used = set()
        for entry in leaf.sharding.spec:
            used.update(entry if isinstance(entry, tuple) else (entry,))
        assert {"dp", "fsdp"} <= used, leaf.sharding.spec
    z1_bytes = optimizer_state_bytes(
        opt, states["zero1"].params,
        shardings=jax.tree.map(lambda l: l.sharding,
                               states["zero1"].opt_state))
    flat_bytes = optimizer_state_bytes(opt, states["flat"].params)
    assert z1_bytes < flat_bytes / 6  # ~1/8 plus padding

    # params come back identical across replicas (fully replicated)
    p0 = jax.tree.leaves(states["zero1"].params)[0]
    assert p0.sharding.is_fully_replicated


def test_llama_train_step_lowmem_optimizer(cpu_mesh_devices):
    """adamw_lowmem (compact-moment AdamW, train/optim.py) drops into the
    SPMD step factory: moments come back in bf16, shardings mirror params,
    and a few steps reduce the loss like stock adamw does."""
    import numpy as np
    import optax

    from ray_tpu.models.llama import LlamaConfig
    from ray_tpu.parallel.mesh import MeshSpec, build_mesh
    from ray_tpu.train.optim import adamw_lowmem
    from ray_tpu.train.spmd import make_llama_train_step

    cfg = LlamaConfig.tiny()
    rng = np.random.default_rng(2)
    tokens = rng.integers(0, cfg.vocab_size, (4, 16), dtype=np.int32)
    targets = np.roll(tokens, -1, axis=1)
    mesh = build_mesh(MeshSpec(dp=2, fsdp=2), cpu_mesh_devices[:4])

    losses = {}
    for name, opt in [("lowmem", adamw_lowmem(1e-2, weight_decay=0.1)),
                      ("adamw", optax.adamw(1e-2, weight_decay=0.1))]:
        step, init, shard = make_llama_train_step(
            cfg, mesh, optimizer=opt, attn_impl="blockwise", remat=False)
        state = init()
        tr = []
        for _ in range(6):
            state, m = step(state, shard(tokens), shard(targets))
            tr.append(float(m["loss"]))
        losses[name] = tr
        if name == "lowmem":
            import jax
            import jax.numpy as jnp

            mu_leaf = jax.tree.leaves(state.opt_state[0].mu)[0]
            nu_leaf = jax.tree.leaves(state.opt_state[0].nu)[0]
            assert mu_leaf.dtype == jnp.bfloat16
            assert nu_leaf.dtype == jnp.bfloat16
    assert losses["lowmem"][-1] < losses["lowmem"][0]
    # Tracks stock adamw closely over a short horizon.
    assert abs(losses["lowmem"][-1] - losses["adamw"][-1]) < 0.35
