"""Device-mesh construction: the substrate every collective and sharding
rides on.

TPU-native replacement for the reference's process-group world (reference:
python/ray/util/collective — NCCL groups are flat rank lists; torch.distributed
worlds are 1-D): on TPU the communication domain is a *mesh* over the slice's
ICI torus, with named axes for each parallelism dimension, and a slower DCN
dimension between slices (reference multi-slice env plumbing:
python/ray/util/tpu.py get_tpu_coordinator_env_vars :199). Axis order matters:
ICI-adjacent axes get the torus bandwidth; the DCN axis must be outermost.

Canonical axis names (used by sharding rules, collectives, and models):
  dp    — data parallel (gradient allreduce)
  fsdp  — fully-sharded data parallel (param/optimizer sharding)
  tp    — tensor parallel (Megatron-style)
  sp    — sequence/context parallel (ring attention)
  ep    — expert parallel (MoE all-to-all)
  pp    — pipeline parallel (stages)
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import numpy as np
from jax.sharding import Mesh

AXIS_ORDER = ("pp", "dp", "fsdp", "ep", "sp", "tp")  # outermost (DCN-most) first


@dataclass(frozen=True)
class MeshSpec:
    """Named parallelism degrees. Unspecified axes default to 1.

    ``dcn_axes`` marks axes that cross slice boundaries (data/pipeline
    parallelism between pods); they are laid out outermost so XLA routes
    their collectives over DCN and everything else over ICI.
    """

    dp: int = 1
    fsdp: int = 1
    tp: int = 1
    sp: int = 1
    ep: int = 1
    pp: int = 1
    dcn_axes: tuple[str, ...] = ()

    def axis_sizes(self) -> dict[str, int]:
        return {name: getattr(self, name) for name in AXIS_ORDER}

    @property
    def num_devices(self) -> int:
        return math.prod(self.axis_sizes().values())

    def with_total(self, n_devices: int, grow: str = "dp") -> "MeshSpec":
        """Scale the ``grow`` axis so the mesh covers ``n_devices``."""
        fixed = self.num_devices // getattr(self, grow)
        if n_devices % fixed != 0:
            raise ValueError(
                f"{n_devices} devices not divisible by fixed degree {fixed}"
            )
        return MeshSpec(**{**self._asdict(), grow: n_devices // fixed})

    def _asdict(self) -> dict:
        return {
            "dp": self.dp, "fsdp": self.fsdp, "tp": self.tp,
            "sp": self.sp, "ep": self.ep, "pp": self.pp,
            "dcn_axes": self.dcn_axes,
        }


def build_mesh(spec: MeshSpec, devices: list | None = None) -> Mesh:
    """Arrange devices into the named mesh.

    Axis order follows AXIS_ORDER so that the innermost (last) axes map to
    ICI-nearest neighbors — jax device order on TPU enumerates the torus so
    contiguous device runs share links; tp/sp sit innermost for the
    bandwidth-hungriest collectives.
    """
    devices = devices if devices is not None else jax.devices()
    sizes = spec.axis_sizes()
    n = math.prod(sizes.values())
    if n > len(devices):
        raise ValueError(f"mesh needs {n} devices, only {len(devices)} available")
    arr = np.array(devices[:n]).reshape(*sizes.values())
    return Mesh(arr, axis_names=tuple(sizes.keys()))


def single_device_mesh() -> Mesh:
    return build_mesh(MeshSpec())


def mesh_shape_for_slice(accelerator_type: str, num_chips: int) -> dict[str, int]:
    """Suggest a default (dp × fsdp) split for a slice of the given size.

    Mirrors common practice: fsdp within a host's ICI domain, dp across.
    """
    if num_chips <= 4:
        return {"fsdp": num_chips}
    return {"dp": num_chips // 4, "fsdp": 4}


def hybrid_mesh(spec: MeshSpec, num_slices: int, devices_per_slice: int,
                devices: list | None = None) -> Mesh:
    """Multi-slice mesh: DCN axes span slices, ICI axes stay inside a slice.

    With jax.distributed initialized across hosts of several slices, device
    order groups by slice; reshaping with the DCN axis outermost keeps each
    slice's devices contiguous on the ICI axes.
    """
    devices = devices if devices is not None else jax.devices()
    sizes = spec.axis_sizes()
    dcn_degree = math.prod(sizes[a] for a in spec.dcn_axes) if spec.dcn_axes else 1
    if dcn_degree != num_slices:
        raise ValueError(
            f"product of dcn_axes degrees ({dcn_degree}) must equal num_slices "
            f"({num_slices})"
        )
    ici_degree = math.prod(v for a, v in sizes.items() if a not in spec.dcn_axes)
    if ici_degree != devices_per_slice:
        raise ValueError(
            f"ICI axes product ({ici_degree}) must equal devices_per_slice "
            f"({devices_per_slice})"
        )
    # Order: dcn axes first (slice-major), then ici axes.
    dcn = [a for a in AXIS_ORDER if a in spec.dcn_axes]
    ici = [a for a in AXIS_ORDER if a not in spec.dcn_axes]
    arr = np.array(devices[: num_slices * devices_per_slice]).reshape(
        *[sizes[a] for a in dcn], *[sizes[a] for a in ici]
    )
    # Transpose back to canonical AXIS_ORDER.
    perm = [(dcn + ici).index(a) for a in AXIS_ORDER]
    arr = arr.transpose(perm)
    return Mesh(arr.reshape(*[sizes[a] for a in AXIS_ORDER]),
                axis_names=AXIS_ORDER)
