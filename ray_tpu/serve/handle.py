"""DeploymentHandle: the client-side composition/request API.

Capability parity with the reference's handle (reference:
python/ray/serve/handle.py — DeploymentHandle.remote() → DeploymentResponse;
handles are picklable and rebuild their router lazily in the receiving
process, so deployments compose by passing handles through init args).
"""

from __future__ import annotations

import threading
from typing import Any

import ray_tpu
from ray_tpu.serve.long_poll import LongPollClient
from ray_tpu.serve.router import Router

CONTROLLER_NAME = "SERVE_CONTROLLER"
SERVE_NAMESPACE = "serve"


class DeploymentResponse:
    """Future-like result of a handle call."""

    def __init__(self, ref):
        self._ref = ref

    def result(self, timeout: float | None = 60.0) -> Any:
        return ray_tpu.get(self._ref, timeout=timeout)

    def _to_object_ref(self):
        return self._ref


class DeploymentHandle:
    def __init__(self, deployment_name: str, app_name: str = "default",
                 method_name: str = "__call__"):
        self.deployment_name = deployment_name
        self.app_name = app_name
        self._method_name = method_name
        self._lock = threading.Lock()
        self._router: Router | None = None
        self._poll: LongPollClient | None = None

    # -- composition --

    def options(self, method_name: str | None = None) -> "DeploymentHandle":
        return DeploymentHandle(self.deployment_name, self.app_name,
                                method_name or self._method_name)

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        # handle.method.remote(...) sugar (reference handle API)
        return DeploymentHandle(self.deployment_name, self.app_name, name)

    # -- data plane --

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        router = self._ensure_router()
        args = tuple(a._to_object_ref() if isinstance(a, DeploymentResponse)
                     else a for a in args)
        kwargs = {k: (v._to_object_ref() if isinstance(v, DeploymentResponse)
                      else v) for k, v in kwargs.items()}
        ref = router.assign_request(self._method_name, args, kwargs)
        return DeploymentResponse(ref)

    def _ensure_router(self) -> Router:
        with self._lock:
            if self._router is None:
                controller = ray_tpu.get_actor(CONTROLLER_NAME,
                                               namespace=SERVE_NAMESPACE)
                key = f"replicas:{self.deployment_name}"

                def listen(kv: dict, timeout: float) -> dict:
                    return ray_tpu.get(controller.listen.remote(kv, timeout),
                                       timeout=timeout + 30)

                self._poll = LongPollClient(listen, [key])
                # Seed synchronously so the first request doesn't race the
                # poll thread.
                seed = ray_tpu.get(
                    controller.get_replicas.remote(self.deployment_name))
                self._poll._cache.setdefault(key, seed)

                def get_replicas():
                    return self._poll.get(key) or []

                self._router = Router(self.deployment_name, get_replicas)
            return self._router

    def __reduce__(self):
        return (DeploymentHandle,
                (self.deployment_name, self.app_name, self._method_name))

    def __repr__(self) -> str:
        return f"DeploymentHandle({self.deployment_name!r})"
