import jax, jax.numpy as jnp, numpy as np
import ray_tpu.ops.attention as A
rng = np.random.default_rng(0)
def chk(name, causal, neg):
    old = A.NEG_INF; A.NEG_INF = neg
    try:
        q = jnp.asarray(rng.standard_normal((2,4,2048,64)), jnp.bfloat16)
        k = jnp.asarray(rng.standard_normal((2,4,2048,64)), jnp.bfloat16)
        v = jnp.asarray(rng.standard_normal((2,4,2048,64)), jnp.bfloat16)
        f = lambda q,k,v: A.blockwise_attention(q,k,v,causal=causal,kv_block=512).astype(jnp.float32).sum()
        _, grads = jax.jit(jax.value_and_grad(f, argnums=(0,1,2)))(q,k,v)
        nan = [bool(jnp.isnan(g.astype(jnp.float32)).any()) for g in grads]
        print(f"{name}: causal={causal} neg={neg}: nan={nan}", flush=True)
    finally:
        A.NEG_INF = old
chk("causal -1e30", True, -1e30)
chk("noncausal -1e30", False, -1e30)
chk("causal -1e9", True, -1e9)
chk("causal -3e38", True, -3e38)
