"""Plain timing of async-task batches (no profiler)."""
import os
import sys
import time

import ray_tpu
from ray_tpu import remote
from ray_tpu.cluster_utils import Cluster
from ray_tpu.core.worker import global_worker
from ray_tpu.utils.ids import JobID

os.environ.setdefault("RTPU_WORKER_IDLE_TTL_S", "300")
from ray_tpu.utils import config as config_mod

config_mod.set_config(config_mod.Config.load())


@remote
def noop(*_args):
    return None


c = Cluster()
c.add_node(num_cpus=4)
rt = c.connect()
global_worker.runtime = rt
global_worker.worker_id = rt.worker_id
global_worker.node_id = rt.node_id
global_worker.job_id = JobID.from_random()
global_worker.mode = "cluster"

batch = 500
ray_tpu.get(noop.remote(), timeout=60)
ray_tpu.get([noop.remote() for _ in range(batch)])
for i in range(6):
    t0 = time.perf_counter()
    ray_tpu.get([noop.remote() for _ in range(batch)])
    ks = list(rt._key_states.values())
    nworkers = sum(len(k.workers) for k in ks)
    print(f"round {i}: {batch/(time.perf_counter()-t0):.0f} tasks/s "
          f"workers={nworkers} pending={sum(k.pending_leases for k in ks)}",
          file=sys.stderr, flush=True)
rt.shutdown()
c.shutdown()
