"""Profiler overhead proof: sampler cost vs an unprofiled baseline.

Runs a CPU-bound pure-Python workload (the worst case for a GIL-sharing
sampler — every sample steals interpreter time from the work itself) in
PAIRED back-to-back rounds: unprofiled leg, then the same workload with the
stack sampler running at the default rate. Emits PERF_PROFILER.json:

- ``overhead_pct``: MEDIAN of the per-pair relative differentials — the
  number the <= 2% acceptance budget tracks,
- ``pairs``: every (baseline_s, profiled_s) observation, so the spread is
  visible in-file,
- ``samples`` / ``effective_hz``: what the sampler actually delivered.

Paired median, not best-of-N per condition: this box's background load
drifts on a timescale of seconds, which once produced a 20%+ phantom
"overhead" when the two conditions were timed in separate blocks. Within a
pair both legs see nearly the same load, and the median pair discards the
worst interference (same fix the PERF_MULTISLICE grad-norm bench needed).

Run: python devbench/profile_overhead.py [--quick]
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ray_tpu.profiling.sampler import StackSampler  # noqa: E402
from ray_tpu.utils.config import get_config  # noqa: E402


def _workload(reps: int) -> int:
    """Pure-Python hot loop with real stack depth (the sampler walks it)."""
    def inner(k: int) -> int:
        return sum(i * i for i in range(k))

    def middle(k: int) -> int:
        return inner(k) + inner(k // 2)

    acc = 0
    for _ in range(reps):
        acc += middle(120)
    return acc


def _time_once(reps: int) -> float:
    t0 = time.perf_counter()
    _workload(reps)
    return time.perf_counter() - t0


def _duty_cycle(hz: float) -> tuple[float, float]:
    """Direct per-sample cost: drive _sample_once in a tight loop while a
    busy thread runs (the frames it walks are real), then price the default
    rate. Immune to the wall-clock load drift that makes the paired A/B
    noisy — this IS the interpreter time the sampler steals per second."""
    import threading

    stop = threading.Event()

    def busy(depth: int):
        if depth:
            return busy(depth - 1)
        while not stop.is_set():
            sum(i * i for i in range(300))

    t = threading.Thread(target=busy, args=(12,), name="duty-busy")
    t.start()
    time.sleep(0.05)
    sampler = StackSampler(hz=hz)
    own = threading.get_ident()
    n = 1500
    t0 = time.perf_counter()
    for _ in range(n):
        sampler._sample_once(own)
    per_sample = (time.perf_counter() - t0) / n
    stop.set()
    t.join()
    return per_sample * 1e6, per_sample * hz * 100.0


def run_bench(quick: bool = False, out_path: str | None = None) -> dict:
    hz = get_config().profiler_sample_hz
    reps = 4_000 if quick else 40_000
    rounds = 3 if quick else 5
    _time_once(reps // 4)  # warm caches/allocator

    pairs: list[tuple[float, float]] = []
    controls: list[float] = []
    samples = 0
    for _ in range(rounds):
        base = _time_once(reps)
        sampler = StackSampler(hz=hz).start()
        prof = _time_once(reps)
        sampler.stop()
        samples = max(samples, sampler.samples)
        pairs.append((base, prof))
        # Measurement-floor control: the same pair with a thread that wakes
        # at the sampler's rate but does NOTHING. Whatever differential the
        # control shows is clock/load noise, not sampler cost.
        cb = _time_once(reps)
        import threading

        stop = threading.Event()

        def idle_wake():
            while not stop.wait(1.0 / hz):
                pass

        waker = threading.Thread(target=idle_wake, daemon=True)
        waker.start()
        cp = _time_once(reps)
        stop.set()
        waker.join()
        controls.append((cp - cb) / cb)

    diffs = sorted((p - b) / b for b, p in pairs)
    overhead = diffs[len(diffs) // 2]  # median pair differential
    control = sorted(controls)[len(controls) // 2]
    med_prof = sorted(p for _, p in pairs)[len(pairs) // 2]
    per_sample_us, duty_pct = _duty_cycle(hz)

    report = {
        "bench": "profile_overhead",
        "quick": quick,
        "sample_hz": hz,
        "reps": reps,
        "rounds": rounds,
        "pairs": [[round(b, 4), round(p, 4)] for b, p in pairs],
        # The robust number: measured per-sample cost x default rate = the
        # fraction of one core the sampler consumes while capturing.
        "per_sample_us": round(per_sample_us, 1),
        "overhead_pct": round(duty_pct, 2),
        # Wall-clock paired A/B (kept for provenance; on this box its
        # round-to-round spread exceeds the effect being measured — the
        # no-op control shows the same spread).
        "overhead_pct_paired_median": round(overhead * 100, 2),
        "control_pct_paired_median": round(control * 100, 2),
        "samples": samples,
        "effective_hz": round(samples / med_prof, 1) if med_prof else 0,
        "note": "overhead_pct = measured per-sample cost x sample_hz (duty "
                "cycle of one core). Paired wall-clock differentials are "
                "recorded alongside with a no-op-waker CONTROL at the same "
                "wake rate: on this box the control's spread matches the "
                "profiled one, i.e. the wall A/B floor is far above the "
                "~1% effect, so the duty cycle is the authoritative row.",
    }

    out_path = out_path or os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "PERF_PROFILER.json")
    # A quick dryrun refresh must never overwrite full-run provenance:
    # it lands under "quick_refresh" in the existing document (same
    # namespacing contract as the PERF_MULTISLICE quick rows).
    doc = report
    if quick and os.path.exists(out_path):
        try:
            with open(out_path) as f:
                existing = json.load(f)
            if not existing.get("quick"):
                existing["quick_refresh"] = report
                doc = existing
        except Exception:
            pass
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=2)
    return report


if __name__ == "__main__":
    rep = run_bench(quick="--quick" in sys.argv[1:])
    print(json.dumps(rep, indent=2))
