"""Node memory defense: the daemon's memory watcher kills runaway workers.

Capability parity with the reference's OOM protection (reference:
python/ray/_private/memory_monitor.py:97 +
src/ray/raylet/worker_killing_policy_group_by_owner.cc, tested by
python/ray/tests/test_memory_pressure.py): a task that allocates without
bound is SIGKILLed by the daemon, the job fails with a typed
OutOfMemoryError, and the daemon itself survives to run more work.
"""

import os
import time

import pytest

from ray_tpu.cluster_utils import Cluster


@pytest.fixture(scope="module")
def oom_cluster():
    # Small worker-memory budget so the watcher trips fast; aggressive poll.
    os.environ["RTPU_MEMORY_LIMIT_BYTES"] = str(1200 * 1024 * 1024)
    os.environ["RTPU_MEMORY_USAGE_THRESHOLD"] = "0.9"
    os.environ["RTPU_MEMORY_MONITOR_INTERVAL_S"] = "0.2"
    from ray_tpu.utils import config as config_mod

    config_mod.set_config(config_mod.Config.load())
    c = Cluster()
    c.add_node(num_cpus=4)
    rt = c.connect()
    import ray_tpu
    from ray_tpu.core.worker import global_worker

    global_worker.runtime = rt
    global_worker.worker_id = rt.worker_id
    global_worker.node_id = rt.node_id
    global_worker.mode = "cluster"
    try:
        yield c
    finally:
        ray_tpu.shutdown()
        for k in ("RTPU_MEMORY_LIMIT_BYTES", "RTPU_MEMORY_USAGE_THRESHOLD",
                  "RTPU_MEMORY_MONITOR_INTERVAL_S"):
            os.environ.pop(k, None)
        config_mod.set_config(config_mod.Config.load())


def test_unbounded_malloc_killed_with_oom_error(oom_cluster):
    import ray_tpu

    @ray_tpu.remote(max_retries=0)
    def hog():
        x = []
        while True:
            # Touch the pages so RSS actually grows.
            x.append(bytearray(b"\xff" * (64 * 1024 * 1024)))
            time.sleep(0.01)

    with pytest.raises(ray_tpu.OutOfMemoryError, match="memory monitor"):
        ray_tpu.get(hog.remote(), timeout=120)

    # The daemon survived the kill: fresh work still runs.
    @ray_tpu.remote
    def ok():
        return 42

    assert ray_tpu.get(ok.remote(), timeout=60) == 42


def test_oom_retry_budget_then_typed_error(oom_cluster):
    """OOM kills consume the task's retry budget; the terminal error is
    still the typed OutOfMemoryError, not a generic system failure."""
    import ray_tpu

    @ray_tpu.remote(max_retries=1)
    def hog():
        x = []
        while True:
            x.append(bytearray(b"\xff" * (64 * 1024 * 1024)))
            time.sleep(0.01)

    t0 = time.monotonic()
    with pytest.raises(ray_tpu.OutOfMemoryError):
        ray_tpu.get(hog.remote(), timeout=240)
    assert time.monotonic() - t0 < 240


def test_group_by_owner_policy_unit():
    """Victim selection: newest task from the largest owner group;
    actors only as fallback (reference:
    worker_killing_policy_group_by_owner.cc)."""
    from ray_tpu.core.cluster.node_daemon import NodeDaemon, WorkerProc

    class _P:  # fake Popen
        def __init__(self, pid):
            self.pid = pid

    def wp(wid, owner="", lease=None, actor=None, granted=0.0):
        w = WorkerProc(worker_id=wid, proc=_P(os.getpid()))
        w.owner = owner
        w.lease_id = lease
        w.actor_id = actor
        w.lease_granted_at = granted
        return w

    daemon = NodeDaemon.__new__(NodeDaemon)  # policy is state-free
    daemon.workers = {
        "a1": wp("a1", owner="A", lease="l1", granted=1.0),
        "a2": wp("a2", owner="A", lease="l2", granted=3.0),
        "b1": wp("b1", owner="B", lease="l3", granted=9.0),
        "c1": wp("c1", actor="act-1"),
    }
    # Owner A has the most tasks; its newest (a2) is the victim — not B's
    # newer task, not the actor.
    assert daemon._pick_oom_victim().worker_id == "a2"

    # No task workers: actor becomes the victim.
    daemon.workers = {"c1": wp("c1", actor="act-1")}
    assert daemon._pick_oom_victim().worker_id == "c1"

    # Nothing at all: no victim.
    daemon.workers = {}
    assert daemon._pick_oom_victim() is None
