from ray_tpu.scripts.cli import main

raise SystemExit(main())
