"""Prefill/decode disaggregation serving pattern.

Capability parity with the reference's P/D pattern (reference:
python/ray/llm/_internal/serve/serving_patterns/prefill_decode/pd_server.py
— a prefill deployment computes the prompt KV, a KV connector ships it, and
a decode deployment continues generation): here the KV slice travels as a
plain object through the handle call (the object store moves it; intra-node
it rides the shm arena), and the decode engine imports it into a slot.

Prefill replicas never decode (their slots turn over at prompt rate) and
decode replicas never prefill (steady small-batch decode steps) — the
latency isolation that motivates the pattern.
"""

from __future__ import annotations

import json
import time
import uuid
from typing import Any

from ray_tpu import serve
from ray_tpu.llm.config import LLMConfig, SamplingParams
from ray_tpu.llm.engine import LLMEngine
from ray_tpu.llm.serving import _sampling_from


class PrefillServer:
    """Computes prompt KV + the first token; no decode loop runs here."""

    def __init__(self, llm_config: LLMConfig):
        self.engine = LLMEngine(llm_config)

    def prefill(self, prompt_ids: list[int], sampling_kw: dict) -> dict:
        return self.engine.prefill_only(prompt_ids,
                                        _sampling_from(sampling_kw))

    def check_health(self) -> None:
        if not self.engine._thread.is_alive():
            raise RuntimeError("prefill engine died")


class DecodeServer:
    """Continues generation from shipped KV; never prefills."""

    def __init__(self, llm_config: LLMConfig):
        self.engine = LLMEngine(llm_config)

    def decode(self, payload: dict, sampling_kw: dict) -> dict:
        req = self.engine.submit_prefilled(
            payload, _sampling_from(sampling_kw))
        if not req.done.wait(300):
            raise TimeoutError("decode timed out")
        if req.error:
            raise RuntimeError(req.error)
        res = self.engine._result(req)
        return {"token_ids": res.token_ids, "text": res.text,
                "finish_reason": res.finish_reason}

    def decode_stream(self, payload: dict, sampling_kw: dict):
        req = self.engine.submit_prefilled(
            payload, _sampling_from(sampling_kw), stream=True)
        while True:
            item = req.stream_queue.get()
            if item is None:
                break
            yield self.engine.tokenizer.decode([item])
        yield ("__finish__", req.finish_reason or "stop")

    def check_health(self) -> None:
        if not self.engine._thread.is_alive():
            raise RuntimeError("decode engine died")


class PDServer:
    """OpenAI-style ingress orchestrating prefill → KV hand-off → decode."""

    def __init__(self, prefill_handle, decode_handle, llm_config: LLMConfig):
        # Bind method handles ONCE: options() creates a fresh handle whose
        # first call builds a router + long-poll client — per-request
        # options() would leak a polling thread per chat call.
        self.prefill = prefill_handle.options(method_name="prefill")
        self.decode = decode_handle.options(method_name="decode")
        self.decode_stream_h = decode_handle.options(
            method_name="decode_stream", stream=True)
        from ray_tpu.llm.tokenizer import get_tokenizer

        self.tokenizer = get_tokenizer(llm_config.tokenizer)
        self._model_id = (llm_config.model
                         if isinstance(llm_config.model, str) else "llama")

    def chat(self, messages: list[dict], **kw) -> dict:
        prompt = self.tokenizer.encode(
            self.tokenizer.apply_chat_template(messages))
        payload = self.prefill.remote(prompt, kw).result(timeout=300)
        out = self.decode.remote(payload, kw).result(timeout=300)
        # token_ids already starts with first_token (the decode engine
        # emits the imported token as its first output) and the engine
        # already stripped/decoded eos — out["text"] is authoritative.
        toks = list(out["token_ids"])
        return {
            "id": f"chatcmpl-{uuid.uuid4().hex[:12]}",
            "object": "chat.completion",
            "model": self._model_id,
            "choices": [{"index": 0,
                         "message": {"role": "assistant",
                                     "content": out["text"]},
                         "finish_reason": out["finish_reason"]}],
            "usage": {"prompt_tokens": len(prompt),
                      "completion_tokens": len(toks),
                      "total_tokens": len(prompt) + len(toks)},
        }

    def chat_stream(self, messages: list[dict], **kw):
        prompt = self.tokenizer.encode(
            self.tokenizer.apply_chat_template(messages))
        payload = self.prefill.remote(prompt, kw).result(timeout=300)
        first = self.tokenizer.decode([payload["first_token"]])
        rid = f"chatcmpl-{uuid.uuid4().hex[:12]}"
        # Frames carry per-request id/model like the single-server OpenAI
        # path (serving.py chat_stream) so strict SDK clients parse both.
        yield ("data: " + json.dumps({
            "id": rid, "object": "chat.completion.chunk",
            "model": self._model_id,
            "choices": [{"index": 0, "delta": {"content": first},
                         "finish_reason": None}]}) + "\n\n")
        gen = self.decode_stream_h.remote(payload, kw)
        skipped_first = False
        finish = "stop"
        for delta in gen:
            if isinstance(delta, (tuple, list)) and delta \
                    and delta[0] == "__finish__":
                finish = delta[1] or "stop"
                continue
            if not skipped_first:
                skipped_first = True  # already streamed as the TTFT chunk
                continue
            yield ("data: " + json.dumps({
                "id": rid, "object": "chat.completion.chunk",
                "model": self._model_id,
                "choices": [{"index": 0, "delta": {"content": delta},
                             "finish_reason": None}]}) + "\n\n")
        # Terminal frame carrying finish_reason — the same contract as the
        # single-server OpenAI streaming path.
        yield ("data: " + json.dumps({
            "id": rid, "object": "chat.completion.chunk",
            "model": self._model_id,
            "choices": [{"index": 0, "delta": {},
                         "finish_reason": finish}]}) + "\n\n")
        yield "data: [DONE]\n\n"

    def __call__(self, request: "serve.Request") -> Any:
        body = request.json() or {}
        stream = bool(body.pop("stream", False))
        messages = body.pop("messages", [])
        if stream:
            return self.chat_stream(messages, **body)
        return self.chat(messages, **body)


def build_pd_openai_app(llm_config: LLMConfig, *,
                        num_prefill_replicas: int = 1,
                        num_decode_replicas: int = 1):
    """serve.run(build_pd_openai_app(cfg), route_prefix="/", http=True)."""
    prefill_dep = serve.deployment(
        name="PrefillServer", num_replicas=num_prefill_replicas,
        max_ongoing_requests=llm_config.max_num_seqs,
        health_check_period_s=2.0)(PrefillServer)
    decode_dep = serve.deployment(
        name="DecodeServer", num_replicas=num_decode_replicas,
        max_ongoing_requests=llm_config.max_num_seqs,
        health_check_period_s=2.0)(DecodeServer)
    pd_dep = serve.deployment(name="PDServer", num_replicas=1,
                              max_ongoing_requests=64)(PDServer)
    return pd_dep.bind(prefill_dep.bind(llm_config),
                       decode_dep.bind(llm_config), llm_config)
