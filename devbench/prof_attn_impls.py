"""Compare attention implementations on the real chip at the bench geometry.

Contenders: our Pallas flash kernel, jax's bundled pallas flash_attention,
jax's splash attention, and plain XLA dot attention (materialized scores).
Slope-timed (see prof_blocks.py protocol).
"""
import functools
import time

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.ops.attention import flash_attention

B, S, H, KV, HD = 4, 2048, 32, 8, 64
L1, L2 = 8, 40


def timed_slope_chain(make_step, carry0, reps=5):
    def run_for(length):
        @jax.jit
        def run(c):
            def body(c, _):
                return make_step(c), None
            c, _ = lax.scan(body, c, None, length=length)
            return jax.tree_util.tree_reduce(
                lambda a, x: a + x.ravel()[0].astype(jnp.float32), c, 0.0)
        return run

    r1, r2 = run_for(L1), run_for(L2)
    float(r1(carry0)); float(r2(carry0))
    slopes = []
    for _ in range(reps):
        t0 = time.perf_counter(); float(r1(carry0)); t1 = time.perf_counter() - t0
        t0 = time.perf_counter(); float(r2(carry0)); t2 = time.perf_counter() - t0
        slopes.append((t2 - t1) / (L2 - L1))
    slopes.sort()
    return slopes[len(slopes) // 2]


key = jax.random.PRNGKey(0)
q = jax.random.normal(key, (B, H, S, HD), jnp.bfloat16)
k = jax.random.normal(key, (B, KV, S, HD), jnp.bfloat16)
v = jax.random.normal(key, (B, KV, S, HD), jnp.bfloat16)
cot = jax.random.normal(jax.random.PRNGKey(1), (B, H, S, HD), jnp.bfloat16)
fl = 2 * 2 * B * H * S * S * HD / 2


def bench(name, fn, grow_kv=True):
    def fwd_step(c):
        qq, kk, vv = c
        o = fn(qq, kk, vv)
        return (qq + 1e-30 * o, kk, vv)

    def bwd_step(c):
        qq, kk, vv = c
        _, vjp = jax.vjp(fn, qq, kk, vv)
        dq, dk, dv = vjp(cot)
        return (qq + 1e-30 * dq, kk + 1e-30 * dk, vv + 1e-30 * dv)

    try:
        tf = timed_slope_chain(fwd_step, (q, k, v))
        print(f"{name:24s} fwd {tf*1e3:7.2f} ms {fl/tf/1e12:6.1f} TF/s",
              flush=True, end="  ")
    except Exception as e:  # noqa: BLE001
        print(f"{name:24s} fwd FAILED: {str(e)[:90]}", flush=True)
        return
    try:
        tb = timed_slope_chain(bwd_step, (q, k, v))
        print(f"| fwd+bwd {tb*1e3:7.2f} ms {3.5*fl/tb/1e12:6.1f} TF/s",
              flush=True)
    except Exception as e:  # noqa: BLE001
        print(f"| bwd FAILED: {str(e)[:90]}", flush=True)


import sys
WHICH = set(sys.argv[1:]) or {"ours", "dot", "jaxflash", "splash"}

if "ours" in WHICH:
    bench("ours(flash)", lambda a, b, c: flash_attention(a, b, c, causal=True))


def plain(qq, kk, vv):
    rep = H // KV
    kk = jnp.repeat(kk, rep, axis=1)
    vv = jnp.repeat(vv, rep, axis=1)
    s = jnp.einsum("bhqd,bhkd->bhqk", qq, kk,
                   preferred_element_type=jnp.float32) / (HD ** 0.5)
    mask = jnp.tril(jnp.ones((S, S), bool))
    s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(vv.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", p, vv)


if "dot" in WHICH:
    bench("xla dot (materialized)", plain)

try:
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        BlockSizes, flash_attention as jax_flash)

    def jf(qq, kk, vv):
        rep = H // KV
        kk = jnp.repeat(kk, rep, axis=1)
        vv = jnp.repeat(vv, rep, axis=1)
        return jax_flash(qq, kk, vv, causal=True, sm_scale=1.0 / HD ** 0.5)

    if "jaxflash" in WHICH:
        bench("jax pallas flash", jf)
except Exception as e:  # noqa: BLE001
    print("jax pallas flash unavailable:", str(e)[:90])

try:
    from jax.experimental.pallas.ops.tpu.splash_attention import (
        splash_attention_kernel as sk, splash_attention_mask as sm)

    mask = sm.MultiHeadMask(
        [sm.CausalMask((S, S)) for _ in range(H)])
    kernel = sk.make_splash_mha(mask=mask, head_shards=1, q_seq_shards=1)

    def spl(qq, kk, vv):
        rep = H // KV
        kk = jnp.repeat(kk, rep, axis=1)
        vv = jnp.repeat(vv, rep, axis=1)
        return jax.vmap(kernel)(qq, kk, vv)

    if "splash" in WHICH:
        bench("jax splash", spl)
except Exception as e:  # noqa: BLE001
    print("jax splash unavailable:", str(e)[:120])
