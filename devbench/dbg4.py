import sys, jax, jax.numpy as jnp, numpy as np
from ray_tpu.models.llama import LlamaConfig, init_params, loss_fn

attn = sys.argv[1]; dtype = sys.argv[2]; remat = sys.argv[3] == "remat"; seq = int(sys.argv[4])
cfg = LlamaConfig(vocab_size=32128, hidden_size=2048, intermediate_size=8192,
    num_layers=2, num_heads=32, num_kv_heads=8, head_dim=64,
    max_seq_len=max(seq,2048), tie_embeddings=True, dtype=dtype)
params = jax.jit(lambda k: init_params(cfg, k))(jax.random.PRNGKey(0))
rng = np.random.default_rng(0)
tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, seq), dtype=np.int32))
targets = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, seq), dtype=np.int32))
val, grads = jax.jit(jax.value_and_grad(
    lambda p,t,y: loss_fn(cfg,p,t,y,attn_impl=attn,remat=remat)))(params, tokens, targets)
nans = [jax.tree_util.keystr(p) for p,g in jax.tree_util.tree_flatten_with_path(grads)[0]
        if bool(jnp.isnan(g.astype(jnp.float32)).any())]
print(f"attn={attn} dtype={dtype} remat={remat} seq={seq}: loss={float(val):.4f} nans={nans}", flush=True)
