"""ray_tpu: a TPU-native distributed AI framework.

Tasks/actors/objects core (reference capability: Ray Core) with a JAX/XLA/
Pallas ML stack — collectives over ICI/DCN via shard_map, TPU chips and
slices as first-class schedulable resources, Train/Serve/Data/Tune on top.
"""

from ray_tpu.api import (
    available_resources,
    cancel,
    cluster_resources,
    get,
    get_actor,
    init,
    is_initialized,
    kill,
    put,
    shutdown,
    wait,
)
from ray_tpu.core.exceptions import (
    ActorDiedError,
    ActorUnavailableError,
    GetTimeoutError,
    ObjectLostError,
    OutOfMemoryError,
    RayTpuError,
    TaskCancelledError,
    TaskError,
)
from ray_tpu.core.events import timeline
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.remote_function import remote
from ray_tpu.core.worker import get_runtime_context

__version__ = "0.1.0"

__all__ = [
    "init",
    "shutdown",
    "is_initialized",
    "remote",
    "get",
    "put",
    "wait",
    "kill",
    "cancel",
    "get_actor",
    "cluster_resources",
    "available_resources",
    "get_runtime_context",
    "timeline",
    "ObjectRef",
    "RayTpuError",
    "TaskError",
    "TaskCancelledError",
    "ActorDiedError",
    "ActorUnavailableError",
    "ObjectLostError",
    "OutOfMemoryError",
    "GetTimeoutError",
    "__version__",
]
