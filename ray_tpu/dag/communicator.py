"""Communicator registry: pluggable accelerator transports for DAG nodes.

Capability parity with the reference's pluggable channel accelerators
(reference: python/ray/experimental/channel/communicator.py:18 Communicator
ABC; accelerator_context.py:19 / register_accelerator_context :222 — the hook
a device backend uses to provide p2p/collective transport to compiled graphs;
the reference registers an NCCL communicator for CUDA).

The TPU-native default is the XLA collective backend: compiled-graph
collective nodes delegate to ``ray_tpu.collective`` groups, whose TPU path
lowers to jax.lax collectives over ICI inside shard_map
(ray_tpu/collective/xla_backend.py) and whose CPU test path uses the host
backend — same insertion point as the reference's NCCL registration.

Not to be confused with the *channel* transport: per-edge payload movement
between stage actors (activations/grads) is DirectChannel
(ray_tpu/dag/direct.py) riding the object plane, regardless of
communicator. Communicators cover in-program collectives/p2p BETWEEN
device meshes, the analogue of the reference's NCCL channel types.
"""

from __future__ import annotations


class Communicator:
    """Transport for collective/p2p ops between the actors of a compiled DAG."""

    name = "base"

    def allreduce(self, group_name: str, value, op: str = "sum"):
        raise NotImplementedError

    def send(self, group_name: str, value, dst_rank: int):
        raise NotImplementedError

    def recv(self, group_name: str, src_rank: int, **kwargs):
        raise NotImplementedError


class CollectiveCommunicator(Communicator):
    """Default: delegates to ray_tpu.collective (XLA on TPU, host otherwise)."""

    name = "collective"

    def allreduce(self, group_name: str, value, op: str = "sum"):
        from ray_tpu.collective import collective

        return collective.allreduce(value, group_name=group_name, op=op)

    def send(self, group_name: str, value, dst_rank: int):
        from ray_tpu.collective import collective

        return collective.send(value, dst_rank, group_name=group_name)

    def recv(self, group_name: str, src_rank: int, *, tensor_shape=None,
             dtype=None):
        from ray_tpu.collective import collective

        return collective.recv(tensor_shape, dtype, src_rank,
                               group_name=group_name)


class JaxDeviceCommunicator(CollectiveCommunicator):
    """Device transport for jax arrays (the TPU analogue of the
    reference's NCCL communicator registration,
    accelerator_context.py:222): p2p send/recv lower to the collective
    layer's XLA backend (ICI send/recv inside shard_map on TPU,
    xla_backend.py:209/:229; host fallback off-mesh) — inherited from
    CollectiveCommunicator — with recv landing on device and channel
    traffic wrapped in DeviceChannel (device_put at the reader)."""

    name = "jax_device"

    def recv(self, group_name: str, src_rank: int, *, tensor_shape=None,
             dtype=None):
        import jax

        out = super().recv(group_name, src_rank, tensor_shape=tensor_shape,
                           dtype=dtype)
        return jax.device_put(out)

    def wrap_channel(self, chan):
        from ray_tpu.dag.channel import DeviceChannel

        return DeviceChannel(chan)


_communicators: dict[str, Communicator] = {
    "collective": CollectiveCommunicator(),
    "jax_device": JaxDeviceCommunicator(),
}
_default = "collective"


def register_accelerator_communicator(comm: Communicator,
                                      make_default: bool = False) -> None:
    """Register a device transport (reference: register_accelerator_context)."""
    global _default
    _communicators[comm.name] = comm
    if make_default:
        _default = comm.name


def get_accelerator_communicator(name: str | None = None) -> Communicator:
    return _communicators[name or _default]
