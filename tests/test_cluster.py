"""Distributed runtime: multiprocess tasks/actors across real process
boundaries, node membership, failure handling.

Coverage modeled on the reference's cluster fixtures + chaos shapes
(reference: python/ray/tests/conftest.py ray_start_cluster :647;
test_utils.py ResourceKillerActor :1279 for kill-based fault injection).
The head + node daemons run in-process (1-core box); workers are real
subprocesses.
"""

import os
import time

import pytest

import ray_tpu
from ray_tpu import remote
from ray_tpu.cluster_utils import Cluster
from ray_tpu.core.worker import global_worker
from ray_tpu.utils.ids import JobID


@pytest.fixture(scope="module")
def cluster():
    os.environ["RTPU_WORKER_IDLE_TTL_S"] = "120"
    os.environ["RTPU_HEALTH_CHECK_PERIOD_S"] = "0.2"
    from ray_tpu.utils import config as config_mod

    config_mod.set_config(config_mod.Config.load())
    c = Cluster()
    c.add_node(num_cpus=4, resources={"TPU": 4.0}, labels={"zone": "a"})
    rt = c.connect()
    global_worker.runtime = rt
    global_worker.worker_id = rt.worker_id
    global_worker.node_id = rt.node_id
    global_worker.job_id = JobID.from_random()
    global_worker.mode = "cluster"
    yield c
    rt.shutdown()
    c.shutdown()
    global_worker.runtime = None
    config_mod.set_config(config_mod.Config.load())


def test_task_crosses_process_boundary(cluster):
    @remote
    def whoami():
        return os.getpid()

    pid = ray_tpu.get(whoami.remote(), timeout=60)
    assert pid != os.getpid()


def test_task_args_and_refs(cluster):
    @remote
    def add(a, b):
        return a + b

    ref = ray_tpu.put(10)
    assert ray_tpu.get(add.remote(ref, 5), timeout=60) == 15


def test_parallel_tasks_reuse_lease(cluster):
    @remote
    def sq(x):
        return x * x

    refs = [sq.remote(i) for i in range(20)]
    assert ray_tpu.get(refs, timeout=60) == [i * i for i in range(20)]


def test_large_object_location_fetch(cluster):
    import numpy as np

    @remote
    def big():
        return np.ones(300_000, dtype=np.float32)  # > inline threshold

    arr = ray_tpu.get(big.remote(), timeout=60)
    assert arr.shape == (300_000,)
    assert float(arr[0]) == 1.0


def test_shm_arena_carries_large_objects(cluster):
    """Large results/puts ride the node's native shm arena (zero-copy
    intra-node path) when the native store built."""
    import numpy as np

    rt = global_worker.runtime
    if rt.shm is None:
        pytest.skip("native shm store unavailable")
    before = rt.shm.stats()["num_objects"]

    ref = ray_tpu.put(np.arange(200_000, dtype=np.float32))
    assert rt.shm.stats()["num_objects"] == before + 1

    @remote
    def consume(a):
        return float(a.sum())

    total = ray_tpu.get(consume.remote(ref), timeout=60)
    assert total == float(np.arange(200_000, dtype=np.float32).sum())

    @remote
    def produce():
        return np.full(150_000, 2.0, dtype=np.float32)

    out_ref = produce.remote()  # keep the ref alive: GC deletes on release
    out = ray_tpu.get(out_ref, timeout=60)
    assert float(out[0]) == 2.0
    # The worker deposited its large result into the shared arena.
    assert rt.shm.stats()["num_objects"] >= before + 2

    # And releasing the refs GCs the arena entries (owner-driven delete).
    del ref, out_ref
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and \
            rt.shm.stats()["num_objects"] > before:
        time.sleep(0.05)
    assert rt.shm.stats()["num_objects"] == before


def test_task_error_remote_traceback(cluster):
    @remote
    def boom():
        raise ValueError("cluster kaboom")

    with pytest.raises(ray_tpu.TaskError) as ei:
        ray_tpu.get(boom.remote(), timeout=60)
    assert "cluster kaboom" in str(ei.value)


def test_nested_task_submission(cluster):
    @remote
    def inner(x):
        return x + 1

    @remote
    def outer(x):
        return ray_tpu.get(inner.remote(x)) + 10

    assert ray_tpu.get(outer.remote(1), timeout=60) == 12


def test_actor_lifecycle(cluster):
    @remote
    class Counter:
        def __init__(self, start):
            self.n = start

        def inc(self):
            self.n += 1
            return self.n

    c = Counter.options(name="c1").remote(0)
    assert ray_tpu.get([c.inc.remote() for _ in range(5)], timeout=60) == [1, 2, 3, 4, 5]
    h = ray_tpu.get_actor("c1")
    assert ray_tpu.get(h.inc.remote(), timeout=30) == 6
    ray_tpu.kill(c)
    time.sleep(0.5)
    with pytest.raises(ray_tpu.ActorDiedError):
        ray_tpu.get(c.inc.remote(), timeout=30)


def test_actor_restart_on_worker_crash(cluster):
    @remote(max_restarts=1)
    class Phoenix:
        def __init__(self):
            self.calls = 0

        def count(self):
            self.calls += 1
            return self.calls

        def die(self):
            os._exit(1)

    p = Phoenix.options(name="phx").remote()
    assert ray_tpu.get(p.count.remote(), timeout=60) == 1
    p.die.remote()  # kills the worker process
    time.sleep(1.0)
    # restarted incarnation: state reset, calls work again
    deadline = time.monotonic() + 30
    val = None
    while time.monotonic() < deadline:
        try:
            val = ray_tpu.get(p.count.remote(), timeout=30)
            break
        except ray_tpu.ActorDiedError:
            time.sleep(0.5)
    assert val == 1  # fresh state after restart


def test_multi_node_spillback(cluster):
    # second node with a resource only it has; task must spill to it
    cluster.add_node(num_cpus=2, resources={"special": 1.0}, labels={"zone": "b"})
    time.sleep(0.3)

    @remote(resources={"special": 1.0})
    def on_special():
        return "spilled"

    assert ray_tpu.get(on_special.remote(), timeout=60) == "spilled"


def test_cluster_resources_aggregate(cluster):
    total = ray_tpu.cluster_resources()
    assert total["CPU"] >= 4.0
    assert total["TPU"] == 4.0


def test_kv_store(cluster):
    rt = global_worker.runtime
    rt.kv_put("k1", b"v1")
    assert rt.kv_get("k1") == b"v1"
    rt.kv_del("k1")
    assert rt.kv_get("k1") is None


def test_node_death_detection(cluster):
    node = cluster.add_node(num_cpus=1, labels={"doomed": "yes"})
    time.sleep(0.3)
    nodes = global_worker.runtime.head.call("list_nodes")
    nid = node.node_id
    assert nodes[nid]["alive"]
    cluster.remove_node(node)
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        nodes = global_worker.runtime.head.call("list_nodes")
        if not nodes[nid]["alive"]:
            break
        time.sleep(0.2)
    assert not nodes[nid]["alive"]


def test_cancel_running_task(cluster):
    """A long-running task is interrupted in its worker (reference:
    CoreWorker::CancelTask raises in the executing thread)."""

    @remote
    def spin():
        t0 = time.monotonic()
        while time.monotonic() - t0 < 30:
            time.sleep(0.01)
        return "finished"

    ref = spin.remote()
    time.sleep(1.0)  # let it start executing
    ray_tpu.cancel(ref)
    with pytest.raises(ray_tpu.TaskCancelledError):
        ray_tpu.get(ref, timeout=20)


def test_cancel_queued_task(cluster):
    """A task cancelled while queued behind a busy resource never runs."""

    @remote(resources={"TPU": 4.0})
    def hold(sec):
        time.sleep(sec)
        return "held"

    holder = hold.remote(3.0)
    time.sleep(0.5)  # holder now occupies all 4 TPU
    victim = hold.remote(0.0)  # queued: no TPU available
    ray_tpu.cancel(victim)
    with pytest.raises(ray_tpu.TaskCancelledError):
        ray_tpu.get(victim, timeout=20)
    assert ray_tpu.get(holder, timeout=20) == "held"
