"""Thin client runtime: the full driver API forwarded over one connection
(reference: python/ray/util/client/ worker.py — every api call becomes a
gRPC request against the proxy; refs are ids scoped to the server)."""

from __future__ import annotations

from typing import Any

from ray_tpu.core.cluster.protocol import RpcClient
from ray_tpu.core.exceptions import ActorDiedError, TaskCancelledError, TaskError
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.store import ReferenceCounter
from ray_tpu.core.task_spec import ActorCreationSpec, TaskSpec
from ray_tpu.utils import serialization
from ray_tpu.utils.ids import ActorID, NodeID, ObjectID, WorkerID


class ClientRuntime:
    """Implements the runtime interface by proxying to a ClientServer."""

    def __init__(self, host: str, port: int):
        self._rpc = RpcClient(host, port)
        self.worker_id = WorkerID.from_random()  # local identity (client-side)
        self.node_id = NodeID.from_random()
        self._server_worker: WorkerID | None = None
        # Local refcounting: when the last local ref to a proxied object
        # drops, tell the server to unpin it (reference: client refs release
        # server-side state on del).
        self.refs = ReferenceCounter(on_release=self._release_remote)
        self._exported_fns: set[str] = set()  # registry idempotence cache

    def _owner(self, owner_hex: str) -> WorkerID:
        w = WorkerID.from_hex(owner_hex)
        self._server_worker = w
        return w

    def _release_remote(self, oid: ObjectID, rec=None) -> None:
        # Fire-and-forget from __del__ context: a blocking RPC here can run
        # on the io-loop thread during GC (deadlock) and holds the
        # refcounter lock for the duration. Schedule the release onto the
        # loop instead.
        from ray_tpu.core.cluster.protocol import EventLoopThread, spawn_task

        aio = self._rpc.aio
        oid_hex = oid.hex()

        def on_loop():
            async def send():
                try:
                    await aio.call("c_release", oids=[oid_hex], timeout=10)
                except Exception:
                    pass  # server disconnect cleans residual pins

            spawn_task(send())

        try:
            EventLoopThread.get().loop.call_soon_threadsafe(on_loop)
        except Exception:
            pass

    # ---- objects ----
    def put(self, value: Any) -> ObjectRef:
        res = self._rpc.call("c_put",
                             blob=serialization.serialize(value))
        return ObjectRef(ObjectID.from_hex(res["oid"]),
                         self._owner(res["owner"]))

    def get(self, refs: list[ObjectRef], timeout: float | None = None):
        wire = None if timeout is None else timeout + 15
        res = self._rpc.call("c_get", oids=[r.hex() for r in refs],
                             api_timeout=timeout, timeout=wire)
        if isinstance(res, dict) and res.get("error") is not None:
            raise serialization.deserialize(res["error"])
        out = []
        for item in res:
            value = serialization.deserialize(item["blob"])
            if isinstance(value, (TaskError, ActorDiedError,
                                  TaskCancelledError)):
                raise value
            out.append(value)
        return out

    def wait(self, refs, num_returns=1, timeout=None, fetch_local=True):
        wire = None if timeout is None else timeout + 15
        res = self._rpc.call("c_wait", oids=[r.hex() for r in refs],
                             num_returns=num_returns, api_timeout=timeout,
                             timeout=wire)
        by_hex = {r.hex(): r for r in refs}
        return ([by_hex[h] for h in res["ready"]],
                [by_hex[h] for h in res["pending"]])

    # ---- tasks ----
    def export_function(self, fn_id: str, fn_blob: bytes) -> None:
        """Registry export through the proxy's KV: the definition crosses
        the client connection once; every subsequent spec names it by id."""
        if fn_id in self._exported_fns:
            return
        from ray_tpu.core.fn_registry import FN_NS

        self._rpc.call("c_kv", op="put", ns=FN_NS, key=fn_id, value=fn_blob)
        self._exported_fns.add(fn_id)

    def submit_task(self, spec: TaskSpec) -> list[ObjectRef]:
        res = self._rpc.call("c_submit_task",
                             spec_blob=serialization.dumps_spec(spec))
        owner = self._owner(res["owner"])
        return [ObjectRef(ObjectID.from_hex(h), owner) for h in res["oids"]]

    def cancel(self, ref: ObjectRef, force: bool = False) -> None:
        self._rpc.call("c_cancel", oid=ref.hex(), force=force)

    # ---- actors ----
    def create_actor(self, spec: ActorCreationSpec) -> None:
        res = self._rpc.call("c_create_actor",
                             spec_blob=serialization.dumps_spec(spec))
        if not res.get("ok"):
            raise ValueError(res.get("error", "actor registration failed"))

    def submit_actor_task(self, spec: TaskSpec) -> list[ObjectRef]:
        res = self._rpc.call("c_submit_actor_task",
                             spec_blob=serialization.dumps_spec(spec))
        owner = self._owner(res["owner"])
        return [ObjectRef(ObjectID.from_hex(h), owner) for h in res["oids"]]

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True) -> None:
        self._rpc.call("c_kill_actor", actor_id=actor_id.hex(),
                       no_restart=no_restart)

    def get_named_actor(self, name: str, namespace: str = "default"):
        res = self._rpc.call("c_get_named_actor", name=name,
                             namespace=namespace)
        return ActorID.from_hex(res["actor_id"]) if res.get("actor_id") \
            else None

    def actor_is_alive(self, actor_id: ActorID) -> bool:
        return bool(self._rpc.call("c_actor_is_alive",
                                   actor_id=actor_id.hex())["alive"])

    # ---- cluster / kv ----
    def cluster_resources(self) -> dict[str, float]:
        return self._rpc.call("c_cluster_resources")

    def available_resources(self) -> dict[str, float]:
        return self._rpc.call("c_available_resources")

    def kv_put(self, key: str, value: bytes, ns: str = "default") -> None:
        self._rpc.call("c_kv", op="put", ns=ns, key=key, value=value)

    def kv_get(self, key: str, ns: str = "default"):
        return self._rpc.call("c_kv", op="get", ns=ns, key=key).get("value")

    def kv_del(self, key: str, ns: str = "default") -> None:
        self._rpc.call("c_kv", op="del", ns=ns, key=key)

    def kv_keys(self, prefix: str = "", ns: str = "default"):
        return self._rpc.call("c_kv", op="keys", ns=ns, prefix=prefix)["keys"]

    def shutdown(self) -> None:
        self._rpc.close()


def connect(address: str) -> ClientRuntime:
    """address: "host:port" of a ClientServer."""
    host, port = address.rsplit(":", 1)
    return ClientRuntime(host, int(port))
