"""Slice-granular TPU scheduling: reserve whole multi-host slices atomically.

Capability parity with the reference's ray.util.tpu (reference:
python/ray/util/tpu.py — SlicePlacementGroup :351, slice_placement_group
:581, multi-slice coordinator env get_tpu_coordinator_env_vars :199,
get_tpu_nodes_for_slice :239): a slice is the atomic scheduling unit — one
bundle per TPU host, STRICT_SPREAD so each bundle lands on a distinct host,
with the slice-head marker resource pinning bundle 0 to the slice's worker 0.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ray_tpu.accelerators.tpu import (
    chips_per_host,
    num_hosts,
    slice_head_resource,
)
from ray_tpu.util.placement_group import (
    PlacementGroup,
    PlacementGroupSchedulingStrategy,
    placement_group,
    remove_placement_group,
)


@dataclass
class SlicePlacementGroup:
    """Reserves every host of one TPU slice as one placement group."""

    pod_type: str  # e.g. "v5p-64"
    num_slices: int = 1
    pg: PlacementGroup | None = field(default=None, repr=False)

    @property
    def hosts_per_slice(self) -> int:
        return num_hosts(self.pod_type)

    @property
    def chips_per_host(self) -> int:
        return chips_per_host(self.pod_type)

    @property
    def total_bundles(self) -> int:
        return self.hosts_per_slice * self.num_slices

    def bundles(self) -> list[dict[str, float]]:
        out = []
        for s in range(self.num_slices):
            for h in range(self.hosts_per_slice):
                b = {"TPU": float(self.chips_per_host)}
                if h == 0:
                    # pin to the slice's worker 0 via the head marker
                    b[slice_head_resource(self.pod_type)] = 1.0
                out.append(b)
        return out

    def reserve(self) -> "SlicePlacementGroup":
        strategy = "STRICT_SPREAD" if self.total_bundles > 1 else "PACK"
        self.pg = placement_group(self.bundles(), strategy=strategy)
        return self

    def ready(self, timeout: float | None = 120.0) -> bool:
        return self.pg.ready(timeout) if self.pg else False

    def worker_strategy(self, slice_index: int, host_index: int
                        ) -> PlacementGroupSchedulingStrategy:
        """Scheduling strategy for the train worker of (slice, host)."""
        idx = slice_index * self.hosts_per_slice + host_index
        return PlacementGroupSchedulingStrategy(
            placement_group=self.pg, placement_group_bundle_index=idx)

    def remove(self) -> None:
        if self.pg:
            remove_placement_group(self.pg)


def slice_placement_group(pod_type: str, num_slices: int = 1
                          ) -> SlicePlacementGroup:
    """Reserve ``num_slices`` whole slices of ``pod_type`` (reference:
    slice_placement_group util/tpu.py:581)."""
    return SlicePlacementGroup(pod_type, num_slices).reserve()


def get_tpu_coordinator_env_vars(coordinator_addr: str, num_slices: int,
                                 slice_id: int) -> dict[str, str]:
    """Multi-slice (DCN) runtime env for each host process (reference:
    get_tpu_coordinator_env_vars util/tpu.py:199 — the MEGASCALE_* variables
    are the public libtpu multi-slice interface)."""
    return {
        "MEGASCALE_COORDINATOR_ADDRESS": coordinator_addr,
        "MEGASCALE_NUM_SLICES": str(num_slices),
        "MEGASCALE_SLICE_ID": str(slice_id),
    }
