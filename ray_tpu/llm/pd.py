"""Prefill/decode disaggregation serving pattern.

Capability parity with the reference's P/D pattern (reference:
python/ray/llm/_internal/serve/serving_patterns/prefill_decode/pd_server.py
— a prefill deployment computes the prompt KV, a KV connector ships it, and
a decode deployment continues generation).

KV hand-off (``LLMConfig.pd_transfer_mode``): in the default ``"store"``
mode the prompt KV never touches a pickler — the prefill server exports the
two device slices as store-backed ndarrays (``ray_tpu.put`` scatter-writes
the raw buffer into the object plane) and the payload carries only
ObjectRefs; the decode server materializes them straight from the plane
(same-host: pinned read-only arena views; cross-host: cut-through transfer
pulls) and imports into a slot. ``"inline"`` keeps the legacy
pickle-through-the-handle-call path for A/B comparison.

Prefill replicas never decode (their slots turn over at prompt rate) and
decode replicas never prefill (steady small-batch decode steps) — the
latency isolation that motivates the pattern.
"""

from __future__ import annotations

import json
import threading
import uuid
from typing import Any

from ray_tpu import serve
from ray_tpu.llm.config import LLMConfig
from ray_tpu.llm.engine import LLMEngine
from ray_tpu.llm.serving import _sampling_from
from ray_tpu.util import tracing

_kv_metrics = None
_kv_metrics_lock = threading.Lock()


def kv_metrics():
    """KV hand-off accounting, the bench/test proof surface for the
    zero-copy path: ``llm_kv_handoff_bytes{path}`` counts payload tensor
    bytes by transport ("store" = object-plane ndarrays, "inline" =
    pickled through the handle call) and ``llm_kv_serialized_bytes`` counts
    ONLY bytes that took a serialize/deserialize copy — zero on the store
    path by construction."""
    global _kv_metrics
    with _kv_metrics_lock:
        if _kv_metrics is None:
            from ray_tpu.util.metrics import Counter

            _kv_metrics = {
                "bytes": Counter(
                    "llm_kv_handoff_bytes",
                    "prompt-KV bytes handed from prefill to decode engines",
                    tag_keys=("path",)),
                "serialized": Counter(
                    "llm_kv_serialized_bytes",
                    "prompt-KV bytes that crossed a serialize/deserialize "
                    "copy during hand-off (zero on the store path)"),
                "handoffs": Counter(
                    "llm_kv_handoffs_total",
                    "disaggregated prefill->decode hand-offs",
                    tag_keys=("path",)),
            }
    return _kv_metrics


_kv_bound: dict = {}


def kv_bound(mode: str) -> dict:
    """Per-path pre-bound KV hand-off series: the hand-off is on the TTFT
    path, so the tag merge is paid once per process per mode, not per
    request (rtlint R4)."""
    bound = _kv_bound.get(mode)
    if bound is None:
        mtr = kv_metrics()
        bound = _kv_bound[mode] = {
            "bytes": mtr["bytes"].bound({"path": mode}),
            "handoffs": mtr["handoffs"].bound({"path": mode}),
            "serialized": mtr["serialized"].bound(),
        }
    return bound


def export_kv_payload(payload: dict, mode: str) -> dict:
    """Swap the raw KV ndarrays for store-backed ObjectRefs (store mode).

    The put() path tags the arrays as raw-buffer objects (_TAG_NDARRAY):
    the store scatter-writes the memoryview — no pickle framing, and the
    consumer's get() is an arena view (same host) or a transfer-plane pull
    (cross host), never an unpickle."""
    import ray_tpu

    if mode not in ("store", "inline"):
        # A typo'd mode must not silently pickle multi-MB KV per request
        # (the zero-copy path would be off with no error anywhere).
        raise ValueError(
            f"unknown pd_transfer_mode {mode!r}: expected 'store' or "
            f"'inline'")
    mtr = kv_bound(mode)
    nbytes = payload["kv_k"].nbytes + payload["kv_v"].nbytes
    # KV hand-off phase span: nests under the prefill replica's worker
    # span (same thread), so the trace shows how long the export side of
    # the P/D hop took and over which transport.
    with tracing.span("llm.kv_export",
                      attributes={"path": mode, "bytes": nbytes}):
        if mode == "store":
            out = dict(payload)
            kv_k, kv_v = out.pop("kv_k"), out.pop("kv_v")
            out["kv_ref_k"] = ray_tpu.put(kv_k)
            out["kv_ref_v"] = ray_tpu.put(kv_v)
            mtr["bytes"].inc(nbytes)
            mtr["handoffs"].inc()
            return out
        mtr["bytes"].inc(nbytes)
        mtr["serialized"].inc(nbytes)  # will ride the handle call pickled
        mtr["handoffs"].inc()
        return payload


def resolve_kv_payload(payload: dict) -> dict:
    """Materialize a store-mode payload's KV refs into (read-only,
    store-backed) ndarrays; inline payloads pass through unchanged."""
    if "kv_ref_k" not in payload:
        return payload
    import ray_tpu

    out = dict(payload)
    # One batched get: cross-host, the two transfer-plane pulls overlap
    # instead of serializing two multi-MB fetches on the TTFT path.
    with tracing.span("llm.kv_resolve", attributes={"path": "store"}) as s:
        out["kv_k"], out["kv_v"] = ray_tpu.get(
            [out.pop("kv_ref_k"), out.pop("kv_ref_v")])
        if s is not None:
            s.attributes["bytes"] = \
                out["kv_k"].nbytes + out["kv_v"].nbytes
    return out


class PrefillServer:
    """Computes prompt KV + the first token; no decode loop runs here."""

    def __init__(self, llm_config: LLMConfig):
        self.engine = LLMEngine(llm_config)
        self._mode = getattr(llm_config, "pd_transfer_mode", "store")

    def prefill(self, prompt_ids: list[int], sampling_kw: dict) -> dict:
        payload = self.engine.prefill_only(prompt_ids,
                                           _sampling_from(sampling_kw))
        return export_kv_payload(payload, self._mode)

    def router_prefix_blocks(self) -> dict | None:
        """Publish the engine's cached-prefix block hashes so the serve
        router can land shared-prefix bursts here (serve/prefix.py)."""
        return self.engine.router_prefix_blocks()

    def check_health(self) -> None:
        if not self.engine._thread.is_alive():
            raise RuntimeError("prefill engine died")


class DecodeServer:
    """Continues generation from shipped KV; never prefills."""

    def __init__(self, llm_config: LLMConfig):
        self.engine = LLMEngine(llm_config)

    def decode(self, payload: dict, sampling_kw: dict) -> dict:
        req = self.engine.submit_prefilled(
            resolve_kv_payload(payload), _sampling_from(sampling_kw))
        if not req.done.wait(300):
            raise TimeoutError("decode timed out")
        if req.error:
            raise RuntimeError(req.error)
        res = self.engine._result(req)
        return {"token_ids": res.token_ids, "text": res.text,
                "finish_reason": res.finish_reason}

    def decode_stream(self, payload: dict, sampling_kw: dict):
        req = self.engine.submit_prefilled(
            resolve_kv_payload(payload), _sampling_from(sampling_kw),
            stream=True)
        while True:
            item = req.stream_queue.get()
            if item is None:
                break
            yield self.engine.tokenizer.decode([item])
        yield ("__finish__", req.finish_reason or "stop")

    def check_health(self) -> None:
        if not self.engine._thread.is_alive():
            raise RuntimeError("decode engine died")


class PDServer:
    """OpenAI-style ingress orchestrating prefill → KV hand-off → decode."""

    def __init__(self, prefill_handle, decode_handle, llm_config: LLMConfig):
        # Bind method handles ONCE: routers/long-poll clients are shared
        # per (runtime, deployment) behind the handle, but binding here
        # keeps the per-request path to a cheap options() copy.
        self.prefill = prefill_handle.options(method_name="prefill")
        self.decode = decode_handle.options(method_name="decode")
        self.decode_stream_h = decode_handle.options(
            method_name="decode_stream", stream=True)
        from ray_tpu.llm.tokenizer import get_tokenizer

        self.tokenizer = get_tokenizer(llm_config.tokenizer)
        self._model_id = (llm_config.model
                         if isinstance(llm_config.model, str) else "llama")
        self._block = int(getattr(llm_config, "prefix_block_tokens", 32)
                          or 0)

    def _prefill_handle(self, prompt: list[int]):
        """Prefill handle with this prompt's token-block chain hashes: the
        router lands a shared-prefix burst on the prefill replica whose
        engine already caches those blocks (serve/prefix.py)."""
        if not self._block:
            return self.prefill
        from ray_tpu.serve.prefix import block_hashes

        hashes = block_hashes(prompt, self._block)
        return self.prefill.options(prefix_hashes=hashes) if hashes \
            else self.prefill

    def chat(self, messages: list[dict], **kw) -> dict:
        prompt = self.tokenizer.encode(
            self.tokenizer.apply_chat_template(messages))
        payload = self._prefill_handle(prompt).remote(
            prompt, kw).result(timeout=300)
        out = self.decode.remote(payload, kw).result(timeout=300)
        # token_ids already starts with first_token (the decode engine
        # emits the imported token as its first output) and the engine
        # already stripped/decoded eos — out["text"] is authoritative.
        toks = list(out["token_ids"])
        return {
            "id": f"chatcmpl-{uuid.uuid4().hex[:12]}",
            "object": "chat.completion",
            "model": self._model_id,
            "choices": [{"index": 0,
                         "message": {"role": "assistant",
                                     "content": out["text"]},
                         "finish_reason": out["finish_reason"]}],
            "usage": {"prompt_tokens": len(prompt),
                      "completion_tokens": len(toks),
                      "total_tokens": len(prompt) + len(toks)},
        }

    def chat_stream(self, messages: list[dict], **kw):
        prompt = self.tokenizer.encode(
            self.tokenizer.apply_chat_template(messages))
        payload = self._prefill_handle(prompt).remote(
            prompt, kw).result(timeout=300)
        first = self.tokenizer.decode([payload["first_token"]])
        rid = f"chatcmpl-{uuid.uuid4().hex[:12]}"
        # Frames carry per-request id/model like the single-server OpenAI
        # path (serving.py chat_stream) so strict SDK clients parse both.
        yield ("data: " + json.dumps({
            "id": rid, "object": "chat.completion.chunk",
            "model": self._model_id,
            "choices": [{"index": 0, "delta": {"content": first},
                         "finish_reason": None}]}) + "\n\n")
        gen = self.decode_stream_h.remote(payload, kw)
        skipped_first = False
        finish = "stop"
        for delta in gen:
            if isinstance(delta, (tuple, list)) and delta \
                    and delta[0] == "__finish__":
                finish = delta[1] or "stop"
                continue
            if not skipped_first:
                skipped_first = True  # already streamed as the TTFT chunk
                continue
            yield ("data: " + json.dumps({
                "id": rid, "object": "chat.completion.chunk",
                "model": self._model_id,
                "choices": [{"index": 0, "delta": {"content": delta},
                             "finish_reason": None}]}) + "\n\n")
        # Terminal frame carrying finish_reason — the same contract as the
        # single-server OpenAI streaming path.
        yield ("data: " + json.dumps({
            "id": rid, "object": "chat.completion.chunk",
            "model": self._model_id,
            "choices": [{"index": 0, "delta": {},
                         "finish_reason": finish}]}) + "\n\n")
        yield "data: [DONE]\n\n"

    def __call__(self, request: "serve.Request") -> Any:
        body = request.json() or {}
        stream = bool(body.pop("stream", False))
        messages = body.pop("messages", [])
        if stream:
            return self.chat_stream(messages, **body)
        return self.chat(messages, **body)


def build_pd_openai_app(llm_config: LLMConfig, *,
                        num_prefill_replicas: int = 1,
                        num_decode_replicas: int = 1,
                        name_prefix: str = ""):
    """serve.run(build_pd_openai_app(cfg), route_prefix="/", http=True).

    ``name_prefix`` namespaces the three deployment names so several PD
    apps can coexist in one serve instance (deployment names are global
    — e.g. an A/B bench running both transfer modes side by side)."""
    prefill_dep = serve.deployment(
        name=f"{name_prefix}PrefillServer",
        num_replicas=num_prefill_replicas,
        max_ongoing_requests=llm_config.max_num_seqs,
        health_check_period_s=2.0)(PrefillServer)
    decode_dep = serve.deployment(
        name=f"{name_prefix}DecodeServer", num_replicas=num_decode_replicas,
        max_ongoing_requests=llm_config.max_num_seqs,
        health_check_period_s=2.0)(DecodeServer)
    pd_dep = serve.deployment(name=f"{name_prefix}PDServer", num_replicas=1,
                              max_ongoing_requests=64)(PDServer)
    return pd_dep.bind(prefill_dep.bind(llm_config),
                       decode_dep.bind(llm_config), llm_config)
