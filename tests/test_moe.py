"""Mixtral MoE model + expert parallelism.

The reference has no first-class MoE (SURVEY.md §2.4 EP row: vLLM kwargs +
collective all-to-all); these tests pin down the TPU-native one: routing
semantics, training convergence, and numerical equivalence between the
single-device and expert-parallel (ep) sharded runs.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ray_tpu.models.mixtral import (
    MixtralConfig,
    forward,
    init_params,
    loss_fn,
    moe_block,
    param_logical_axes,
)


@pytest.fixture(scope="module")
def cfg():
    return MixtralConfig.tiny()


@pytest.fixture(scope="module")
def params(cfg):
    return init_params(cfg, jax.random.PRNGKey(0))


class TestMoeBlock:
    def test_routing_capacity_and_shapes(self, cfg, params):
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, cfg.hidden_size),
                              jnp.float32)
        lp = jax.tree.map(lambda a: a[0], params["layers"])
        y, aux = moe_block(cfg, x, lp)
        assert y.shape == x.shape
        assert jnp.isfinite(y).all()
        # Balanced-ish router on random init: aux loss near 1.0 (its minimum
        # for a uniform router is exactly 1.0), never below.
        assert 0.99 <= float(aux) < float(cfg.num_experts)

    def test_topk_gates_renormalized(self, cfg):
        """With ample capacity, each kept token's combine weights over all
        (expert, slot) pairs sum to exactly 1 (renormalized top-k), and each
        token occupies exactly top_k dispatch slots."""
        from ray_tpu.models.mixtral import compute_routing

        T, E = 16, cfg.num_experts
        logits = jax.random.normal(jax.random.PRNGKey(3), (T, E))
        dispatch, combine, aux = compute_routing(cfg, logits, capacity=T)
        np.testing.assert_allclose(np.asarray(combine.sum((1, 2))),
                                   np.ones(T), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(dispatch.sum((1, 2))),
                                   np.full(T, cfg.top_k), rtol=1e-6)
        assert float(aux) >= 0.99

    def test_capacity_drops_overflow(self, cfg):
        """With capacity 1, at most one token per expert is dispatched."""
        from ray_tpu.models.mixtral import compute_routing

        T = 16
        logits = jnp.zeros((T, cfg.num_experts))  # uniform router
        dispatch, combine, _ = compute_routing(cfg, logits, capacity=1)
        per_expert = np.asarray(dispatch.sum((0, 2)))
        assert (per_expert <= 1.0 + 1e-6).all()
        # dropped tokens contribute zero combine weight
        assert (np.asarray(combine.sum((1, 2))) <= 1.0 + 1e-5).all()

    def test_forward_and_loss(self, cfg, params):
        tokens = jnp.arange(16, dtype=jnp.int32).reshape(1, 16) % cfg.vocab_size
        logits, aux = forward(cfg, params, tokens, attn_impl="blockwise",
                              remat=False)
        assert logits.shape == (1, 16, cfg.vocab_size)
        loss = loss_fn(cfg, params, tokens, tokens, attn_impl="blockwise",
                       remat=False)
        assert jnp.isfinite(loss)


class TestMoeTraining:
    def test_loss_decreases(self, cfg):
        from ray_tpu.parallel.mesh import MeshSpec, build_mesh
        from ray_tpu.train.spmd import make_mixtral_train_step

        mesh = build_mesh(MeshSpec(), jax.devices("cpu")[:1])
        step_fn, init_state, shard = make_mixtral_train_step(
            cfg, mesh, optimizer=optax.adamw(3e-3), attn_impl="blockwise",
            remat=False)
        state = init_state()
        tokens = shard(np.random.randint(0, cfg.vocab_size, (4, 16)))
        targets = shard(np.roll(np.asarray(tokens), -1, axis=1))
        state, m0 = step_fn(state, tokens, targets)
        for _ in range(5):
            state, m = step_fn(state, tokens, targets)
        assert float(m["loss"]) < float(m0["loss"])

    def test_expert_parallel_matches_single_device(self, cfg):
        """ep-sharded forward must be numerically equivalent to one device —
        the all-to-all introduced by sharding is a layout change, not math."""
        from ray_tpu.parallel.mesh import MeshSpec, build_mesh
        from ray_tpu.parallel.sharding import ShardingRules, tree_shardings

        devs = jax.devices("cpu")
        params = init_params(cfg, jax.random.PRNGKey(0))
        tokens = jnp.arange(32, dtype=jnp.int32).reshape(2, 16) % cfg.vocab_size

        ref_logits, ref_aux = jax.jit(
            lambda p, t: forward(cfg, p, t, attn_impl="blockwise", remat=False)
        )(params, tokens)

        mesh = build_mesh(MeshSpec(ep=4), devs[:4])
        sh = tree_shardings(mesh, param_logical_axes(cfg), ShardingRules())
        sharded = jax.tree.map(jax.device_put, params, sh)
        ep_logits, ep_aux = jax.jit(
            lambda p, t: forward(cfg, p, t, attn_impl="blockwise", remat=False)
        )(sharded, tokens)

        np.testing.assert_allclose(np.asarray(ref_logits),
                                   np.asarray(ep_logits), rtol=2e-4, atol=2e-4)
        np.testing.assert_allclose(float(ref_aux), float(ep_aux), rtol=1e-4)

    def test_ep_plus_dp_train_step(self, cfg):
        """Combined dp×ep mesh runs a full train step and improves."""
        from ray_tpu.parallel.mesh import MeshSpec, build_mesh
        from ray_tpu.train.spmd import make_mixtral_train_step

        mesh = build_mesh(MeshSpec(dp=2, ep=2, tp=2), jax.devices("cpu")[:8])
        step_fn, init_state, shard = make_mixtral_train_step(
            cfg, mesh, optimizer=optax.adamw(3e-3), attn_impl="blockwise",
            remat=False)
        state = init_state()
        tokens = shard(np.random.randint(0, cfg.vocab_size, (4, 16)))
        targets = shard(np.roll(np.asarray(tokens), -1, axis=1))
        state, m0 = step_fn(state, tokens, targets)
        state, m1 = step_fn(state, tokens, targets)
        assert float(m1["loss"]) < float(m0["loss"])
        assert np.isfinite(float(m1["grad_norm"]))
