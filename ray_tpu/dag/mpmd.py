"""MPMD pipeline parallelism over compiled graphs.

The SPMD pipeline (ray_tpu/parallel/pipeline.py) compiles the WHOLE
pipeline into one program and moves activations with ``lax.ppermute`` over
a mesh axis — right when every stage lives in one jit on one mesh. This
module is the complementary MPMD form (reference: the pipeline-parallel
examples built on compiled graphs — each stage its own actor + its own
compiled program, activations flowing over channels): stage k is an actor
owning its parameter shard and TWO jitted programs (forward, backward);
one ``CompiledDAG.execute()`` is one optimizer step over
``num_microbatches`` microbatches. The per-stage op order (GPipe fill/
drain by default, 1F1B selectable — ray_tpu/dag/schedule.py) is stamped
onto the DAG as ``schedule_rank``, and the microbatch overlap falls out of
the static schedules: stage k runs microbatch m's forward while stage k+1
runs m-1's.

Numerics are EXACTLY the SPMD pipeline's (tests/test_mpmd.py proves loss
parity): grads accumulate per microbatch as d(nll_sum), are normalized
once by the step's total token count at apply time (linearity — matches
normalizing inside the grad), and each stage applies its own optimizer
partition (per-leaf transforms like adamw make the partitioned update
identical to the full one). Embeddings belong to stage 0 and
final-norm/lm-head to the last stage, which is exactly where the SPMD
psum leaves their gradients.

Payloads cross stages as (activation, targets) tuples of host ndarrays:
channels carry ndarrays zero-copy (store-backed buffers / arena views in
cluster mode), and targets ride along to the last stage instead of taking
a second driver route.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import numpy as np

from ray_tpu.dag.dag_node import InputNode, MultiOutputNode
from ray_tpu.dag.schedule import PipelineSchedule, get_schedule


class StageProgram:
    """What one pipeline stage computes. Built ON the stage actor by a
    picklable factory ``factory(stage_index, num_stages) -> StageProgram``.

    Non-last stages implement ``forward``/``backward``; the last stage
    implements ``loss_forward`` (loss + its backward fused — the loss
    gradient seeds there, so a separate backward op would just stash and
    reload the residual)."""

    def init_params(self) -> Any:
        raise NotImplementedError

    def optimizer(self):
        import optax

        return optax.adamw(3e-4, weight_decay=0.1)

    def forward(self, params, x) -> tuple[Any, Any]:
        """x -> (y, residual). The residual is whatever backward needs —
        storing the stage INPUT and rematerializing in backward keeps the
        channel payloads activation-sized."""
        raise NotImplementedError

    def loss_forward(self, params, x, targets) -> tuple[float, Any, Any]:
        """Last stage: -> (loss_sum, param_grads, dx). Unnormalized sum —
        the framework divides by the step's token count at apply."""
        raise NotImplementedError

    def backward(self, params, residual, dy) -> tuple[Any, Any]:
        """-> (param_grads, dx); dx may be None on the first stage."""
        raise NotImplementedError

    def count(self, x, targets) -> int:
        """This microbatch's contribution to the loss normalizer."""
        return int(np.size(targets))


class _PipelineStage:
    """Actor framework around a StageProgram: microbatch slicing, residual
    stash, gradient accumulation, optimizer apply. One compiled-DAG
    execution runs ingest → M forwards → M backwards → apply, in the
    schedule's order."""

    def __init__(self, factory, stage_index: int, num_stages: int,
                 num_microbatches: int):
        self.stage = stage_index
        self.num_stages = num_stages
        self.M = num_microbatches
        self.is_first = stage_index == 0
        self.is_last = stage_index == num_stages - 1
        self.program = factory(stage_index, num_stages)
        self.params = self.program.init_params()
        self.opt = self.program.optimizer()
        self.opt_state = self.opt.init(self.params)
        self._resid: dict[int, Any] = {}
        self._gacc = None
        self._loss_sum = 0.0
        self._count = 0
        self._step = 0
        self._mb_x: list | None = None
        self._mb_t: list | None = None

    # -- schedule ops -------------------------------------------------------
    def ingest(self, batch):
        x, targets = batch
        if np.shape(x)[0] % self.M:
            raise ValueError(
                f"batch dim {np.shape(x)[0]} must divide "
                f"num_microbatches={self.M}")
        self._mb_x = np.split(np.asarray(x), self.M)
        self._mb_t = np.split(np.asarray(targets), self.M)
        return self._step  # tiny marker fanned out to the forward ops

    def forward(self, payload, mb: int):
        if self.is_first:
            x, tgt = self._mb_x[mb], self._mb_t[mb]
        else:
            x, tgt = payload
        y, resid = self.program.forward(self.params, x)
        self._resid[mb] = resid
        self._count += self.program.count(x, tgt)
        return (np.asarray(y), tgt)

    def forward_loss(self, payload, mb: int):
        x, tgt = payload
        loss_sum, grads, dx = self.program.loss_forward(self.params, x, tgt)
        self._accumulate(grads)
        self._loss_sum += float(loss_sum)
        self._count += self.program.count(x, tgt)
        return np.asarray(dx)

    def backward(self, dy, mb: int):
        resid = self._resid.pop(mb)
        grads, dx = self.program.backward(self.params, resid, dy)
        self._accumulate(grads)
        # First stage ends the chain: a tiny marker instead of a dx nobody
        # consumes (the driver reads it to anchor the microbatch chains).
        return mb if dx is None else np.asarray(dx)

    def apply_grads(self, _trigger):
        import jax
        import optax

        norm = float(max(self._count, 1))
        grads = jax.tree.map(lambda g: g / norm, self._gacc)
        updates, self.opt_state = self.opt.update(grads, self.opt_state,
                                                  self.params)
        self.params = optax.apply_updates(self.params, updates)
        self._step += 1
        metrics = {
            "stage": self.stage,
            "step": self._step,
            "tokens": self._count,
            "loss": (self._loss_sum / norm) if self.is_last else None,
        }
        self._gacc = None
        self._loss_sum = 0.0
        self._count = 0
        self._resid.clear()
        return metrics

    def _accumulate(self, grads):
        import jax
        import jax.numpy as jnp

        if self._gacc is None:
            self._gacc = grads
        else:
            self._gacc = jax.tree.map(jnp.add, self._gacc, grads)


def build_pipeline_dag(stage_handles: list, num_microbatches: int,
                       schedule: str | PipelineSchedule = "gpipe"):
    """Unroll one training step (M microbatch chains, forward then
    backward, then per-stage apply) into a DAG over ``_PipelineStage``
    actors, with per-stage op order stamped as ``schedule_rank``."""
    P = len(stage_handles)
    M = num_microbatches
    if P < 2:
        raise ValueError("MPMD pipelines need at least 2 stages "
                         "(use train/spmd.py for a single program)")
    sched = get_schedule(schedule) if isinstance(schedule, str) else schedule

    with InputNode() as inp:
        ingest = stage_handles[0].ingest.bind(inp)
        ingest.schedule_rank = 0
        anchors = []  # first-stage backward markers: chain endpoints
        last_op = [None] * P  # highest-ranked data op per stage
        for mb in range(M):
            # forward chain: stage 0 reads the ingest marker, later stages
            # read (activation, targets) from the previous stage.
            prev = ingest
            for s in range(P - 1):
                node = stage_handles[s].forward.bind(prev, mb)
                node.schedule_rank = sched.forward_rank(mb, s, P, M)
                prev = node
            node = stage_handles[P - 1].forward_loss.bind(prev, mb)
            node.schedule_rank = sched.forward_rank(mb, P - 1, P, M)
            last_op[P - 1] = node
            # backward chain: dx flows back down to stage 0.
            dy = node
            for s in range(P - 2, -1, -1):
                bnode = stage_handles[s].backward.bind(dy, mb)
                bnode.schedule_rank = sched.backward_rank(mb, s, P, M)
                last_op[s] = bnode
                dy = bnode
            anchors.append(dy)
        applies = []
        for s in range(P):
            # The read dependency just anchors apply into the graph; the
            # rank (sorted last) is what actually orders it after every
            # forward/backward of this stage.
            anode = stage_handles[s].apply_grads.bind(last_op[s])
            anode.schedule_rank = sched.apply_rank(s, P, M)
            applies.append(anode)
        # Chains 0..M-2 end at unread first-stage markers; routing them to
        # the driver makes every node reachable from the root. (Chain M-1's
        # marker is apply_0's trigger and already reachable.)
        return MultiOutputNode(anchors[:-1] + applies)


class MPMDPipeline:
    """Driver-facing wrapper: stage actors + the compiled step DAG.

    ``step()`` runs one synchronous optimizer step; ``step_async()``
    returns a future so the driver can keep ``dag_max_inflight`` steps in
    flight (fill/drain across steps composes with the intra-step microbatch
    overlap). ``compile_kwargs`` pass through to ``experimental_compile``
    (e.g. ``_channel_kind="kv"`` or ``_max_inflight``)."""

    def __init__(self, stage_factory: Callable, num_stages: int,
                 num_microbatches: int, *,
                 schedule: str | PipelineSchedule = "gpipe",
                 actor_options: dict | None = None,
                 **compile_kwargs):
        import ray_tpu

        actor_cls = ray_tpu.remote(_PipelineStage)
        if actor_options:
            actor_cls = actor_cls.options(**actor_options)
        self.num_stages = num_stages
        self.num_microbatches = num_microbatches
        self.stages = [
            actor_cls.remote(stage_factory, i, num_stages, num_microbatches)
            for i in range(num_stages)
        ]
        self._dag = build_pipeline_dag(self.stages, num_microbatches,
                                       schedule)
        self.compiled = self._dag.experimental_compile(**compile_kwargs)

    def step(self, x, targets, timeout: float | None = 120.0) -> dict:
        raw = self.compiled.execute((np.asarray(x), np.asarray(targets)),
                                    timeout=timeout)
        return self.parse_result(raw)

    def step_async(self, x, targets):
        return self.compiled.execute_async(
            (np.asarray(x), np.asarray(targets)))

    def parse_result(self, raw: list) -> dict:
        stage_metrics = raw[-self.num_stages:]
        last = stage_metrics[-1]
        return {"loss": last["loss"], "step": last["step"],
                "stage_metrics": stage_metrics}

    def shutdown(self, kill_stages: bool = True) -> None:
        """Tear down the compiled DAG and (by default) the stage actors the
        pipeline spawned. Explicit kills beat leaking the handles to GC:
        the deferred worker churn lands in whatever runs next."""
        self.compiled.teardown()
        if kill_stages:
            import ray_tpu

            for stage in self.stages:
                try:
                    ray_tpu.kill(stage, no_restart=True)
                except Exception:
                    pass


# --------------------------------------------------------------------------
# Llama stage programs: the SPMD pipeline's exact math, partitioned MPMD.
# --------------------------------------------------------------------------

class LlamaStageProgram(StageProgram):
    """One pipeline stage of the llama model (models/llama.py), bitwise-
    faithful to parallel/pipeline.py's stage_loss: stage 0 owns
    embed_tokens + its layer slice, the last stage owns its slice +
    final_norm + lm_head — the same placement the SPMD psum reduces
    shared-param grads to (embed cotangents only arise on rank 0's inject,
    head/final-norm cotangents only on the last rank's valid loss).
    Backward rematerializes from the stashed stage INPUT (jax.vjp of the
    jitted stage program)."""

    def __init__(self, cfg, stage_index: int, num_stages: int,
                 attn_impl: str = "blockwise", seed: int = 0,
                 optimizer_factory: Callable | None = None):
        import jax
        import jax.numpy as jnp
        from jax import lax

        from ray_tpu.models.llama import (
            _layer,
            init_params,
            rms_norm,
            rope_frequencies,
        )

        if cfg.tie_embeddings:
            raise ValueError(
                "MPMD stages need untied embeddings (embed on stage 0, head "
                "on the last stage); tied weights would need a cross-stage "
                "grad exchange")
        if cfg.num_layers % num_stages:
            raise ValueError("num_layers must divide num_stages")
        self.cfg = cfg
        self.is_first = stage_index == 0
        self.is_last = stage_index == num_stages - 1
        self._opt_factory = optimizer_factory
        per = cfg.num_layers // num_stages
        lo = stage_index * per
        self._slice = (lo, lo + per)
        self._seed = seed
        inv_freq = rope_frequencies(cfg.head_dim, cfg.rope_theta,
                                    cfg.rope_scaling)

        def run_layers(layers, x):
            positions = jnp.arange(x.shape[1])

            def body(x, lp):
                return _layer(cfg, x, lp, inv_freq, positions,
                              attn_impl, None), None

            out, _ = lax.scan(body, x, layers)
            return out

        if self.is_first:
            def apply_fn(p, tokens):
                return run_layers(p["layers"], p["embed_tokens"][tokens])
        else:
            def apply_fn(p, x):
                return run_layers(p["layers"], x)

        if self.is_last:
            def nll_sum(p, x, targets):
                h = run_layers(p["layers"], x)
                xn = rms_norm(h, p["final_norm"], cfg.norm_eps)
                logits = jnp.einsum("bsh,hv->bsv", xn, p["lm_head"],
                                    preferred_element_type=jnp.float32)
                logp = jax.nn.log_softmax(logits, axis=-1)
                nll = -jnp.take_along_axis(
                    logp, targets[..., None], axis=-1)[..., 0]
                return nll.sum()

            self._loss_fwd = jax.jit(
                jax.value_and_grad(nll_sum, argnums=(0, 1)))
        else:
            self._fwd = jax.jit(apply_fn)
            if self.is_first:
                def bwd_fn(p, tokens, dy):
                    _, vjp = jax.vjp(lambda pp: apply_fn(pp, tokens), p)
                    return vjp(dy)[0]

                self._bwd = jax.jit(bwd_fn)
            else:
                def bwd_fn(p, x, dy):
                    _, vjp = jax.vjp(apply_fn, p, x)
                    return vjp(dy)

                self._bwd = jax.jit(bwd_fn)

    def init_params(self):
        import jax

        from ray_tpu.models.llama import init_params

        # Full init on every stage, then slice: deterministic and identical
        # to the SPMD init without a cross-stage broadcast (tiny configs;
        # checkpoint loading would replace this for real sizes).
        full = init_params(self.cfg, jax.random.PRNGKey(self._seed))
        lo, hi = self._slice
        p = {"layers": jax.tree.map(lambda a: a[lo:hi], full["layers"])}
        if self.is_first:
            p["embed_tokens"] = full["embed_tokens"]
        if self.is_last:
            p["final_norm"] = full["final_norm"]
            p["lm_head"] = full["lm_head"]
        return p

    def optimizer(self):
        if self._opt_factory is not None:
            return self._opt_factory()
        return super().optimizer()

    def forward(self, params, x):
        import jax.numpy as jnp

        x = jnp.asarray(x)
        return self._fwd(params, x), x

    def loss_forward(self, params, x, targets):
        import jax.numpy as jnp

        loss, (gp, gx) = self._loss_fwd(params, jnp.asarray(x),
                                        jnp.asarray(targets))
        return float(loss), gp, gx

    def backward(self, params, residual, dy):
        import jax.numpy as jnp

        dy = jnp.asarray(dy)
        if self.is_first:
            return self._bwd(params, jnp.asarray(residual), dy), None
        gp, gx = self._bwd(params, jnp.asarray(residual), dy)
        return gp, gx


def _llama_stage(cfg, attn_impl, seed, optimizer_factory, stage_index,
                 num_stages):
    return LlamaStageProgram(cfg, stage_index, num_stages,
                             attn_impl=attn_impl, seed=seed,
                             optimizer_factory=optimizer_factory)


def make_llama_stage_factory(cfg, attn_impl: str = "blockwise",
                             seed: int = 0,
                             optimizer_factory: Callable | None = None):
    """Picklable ``factory(stage_index, num_stages)`` for MPMDPipeline."""
    return partial(_llama_stage, cfg, attn_impl, seed, optimizer_factory)


# --------------------------------------------------------------------------
# Toy stage program: small jitted matmul stages for benches/tests. On a
# CPU-only box pure compute cannot overlap across actors (one physical
# core), so ``sleep_s`` emulates per-stage device dwell — the pipelining
# win the bench measures is schedule overlap, which sleep exhibits exactly.
# --------------------------------------------------------------------------

class ToyStageProgram(StageProgram):
    def __init__(self, stage_index: int, num_stages: int, width: int = 32,
                 sleep_s: float = 0.0, seed: int = 0):
        import jax
        import jax.numpy as jnp

        self.is_first = stage_index == 0
        self.is_last = stage_index == num_stages - 1
        self._sleep = sleep_s
        self._width = width
        self._seed = seed + stage_index

        def apply_fn(p, x):
            return jnp.tanh(x @ p["w"])

        if self.is_last:
            def loss_fn(p, x, targets):
                y = apply_fn(p, x)
                return 0.5 * jnp.sum((y - targets) ** 2)

            self._loss_fwd = jax.jit(
                jax.value_and_grad(loss_fn, argnums=(0, 1)))
        else:
            self._fwd = jax.jit(apply_fn)

            def bwd_fn(p, x, dy):
                _, vjp = jax.vjp(apply_fn, p, x)
                return vjp(dy)

            self._bwd = jax.jit(bwd_fn)

    def init_params(self):
        import jax
        import jax.numpy as jnp

        w = jax.random.normal(jax.random.PRNGKey(self._seed),
                              (self._width, self._width), jnp.float32)
        return {"w": w / np.sqrt(self._width)}

    def forward(self, params, x):
        import time

        import jax.numpy as jnp

        if self._sleep:
            time.sleep(self._sleep)
        x = jnp.asarray(x)
        return self._fwd(params, x), x

    def loss_forward(self, params, x, targets):
        import time

        import jax.numpy as jnp

        if self._sleep:
            time.sleep(self._sleep)
        loss, (gp, gx) = self._loss_fwd(params, jnp.asarray(x),
                                        jnp.asarray(targets))
        return float(loss), gp, gx

    def backward(self, params, residual, dy):
        import time

        import jax.numpy as jnp

        if self._sleep:
            time.sleep(self._sleep)
        gp, gx = self._bwd(params, jnp.asarray(residual), jnp.asarray(dy))
        return gp, (None if self.is_first else gx)

    def count(self, x, targets):
        return int(np.shape(targets)[0])


def make_toy_stage_factory(width: int = 32, sleep_s: float = 0.0,
                           seed: int = 0):
    return partial(ToyStageProgram, width=width, sleep_s=sleep_s, seed=seed)
