"""Goodput ledger: exhaustive wall-clock attribution (observability/goodput).

Property tests assert the tentpole invariant — every classified interval's
phases are non-overlapping and sum exactly to the interval, across fresh
starts, restarts, and explicit tails — plus fixture tests per badput
classifier, the event-leg transport (drain / requeue / head-side dedup),
the rollup's overlap resolution, the peak-FLOPs registry, the sampler's
monotonic rate denominator, and the tracing flush-cursor wraparound.
"""

import random
import time
from collections import deque

import pytest

from ray_tpu.observability import goodput
from ray_tpu.observability.goodput import (
    GOOD_PHASE,
    PHASES,
    GoodputStore,
    RankLedger,
    classify_interval,
)

pytestmark = pytest.mark.goodput


@pytest.fixture(autouse=True)
def _reset():
    goodput._reset_for_tests()
    yield
    goodput._reset_for_tests()


# --------------------------------------------------------------- classifier
class TestClassifyInterval:
    def test_property_exhaustive_nonoverlapping(self):
        """The invariant the whole ledger rests on: for ANY mix of
        measured parts (including overcommitted ones), the classified
        phases partition the interval — each second lands in exactly one
        phase and the parts sum to the wall duration."""
        rng = random.Random(1234)
        candidates = ("compile", "input_wait", "collective_wait",
                      "checkpoint", "replication_push", "step_compute")
        for trial in range(500):
            dur = rng.uniform(0.0, 20.0)
            parts = {}
            for phase in candidates:
                if rng.random() < 0.5:
                    # up to 2x the interval: clamping must still hold
                    parts[phase] = rng.uniform(0.0, 2.0 * dur)
            first = rng.random() < 0.3
            remainder = rng.choice([None, None, "idle", "restart_downtime"])
            out = classify_interval(
                dur, parts, first=first,
                first_phase=rng.choice(["init", "restart_downtime"]),
                remainder=remainder)
            assert all(k in PHASES for k in out), (trial, out)
            assert all(v >= 0.0 for v in out.values()), (trial, out)
            assert sum(out.values()) == pytest.approx(dur, abs=1e-9), \
                (trial, dur, parts, out)

    def test_measured_parts_pass_through(self):
        out = classify_interval(10.0, {"input_wait": 3.0, "compile": 2.0})
        assert out["input_wait"] == pytest.approx(3.0)
        assert out["compile"] == pytest.approx(2.0)
        assert out[GOOD_PHASE] == pytest.approx(5.0)

    def test_overcommit_clamps_in_priority_order(self):
        # compile is consumed before input_wait; nothing exceeds the wall
        out = classify_interval(4.0, {"compile": 3.0, "input_wait": 9.0})
        assert out == {"compile": pytest.approx(3.0),
                       "input_wait": pytest.approx(1.0)}

    def test_first_interval_is_init(self):
        out = classify_interval(5.0, {"compile": 2.0}, first=True)
        assert out["init"] == pytest.approx(3.0)

    def test_restarted_first_interval_is_restart_downtime(self):
        out = classify_interval(5.0, None, first=True,
                                first_phase="restart_downtime")
        assert out == {"restart_downtime": pytest.approx(5.0)}

    def test_measured_compute_pushes_excess_to_idle(self):
        """When compute_time_s is reported (PR-5 share stream), the gap
        between step wall and measured compute is straggler-induced
        idle, not goodput."""
        out = classify_interval(10.0, {"collective_wait": 2.0,
                                       "step_compute": 5.0})
        assert out["collective_wait"] == pytest.approx(2.0)
        assert out[GOOD_PHASE] == pytest.approx(5.0)
        assert out["idle"] == pytest.approx(3.0)

    def test_explicit_remainder_overrides(self):
        out = classify_interval(2.0, {"step_compute": 99.0},
                                remainder="idle")
        assert out == {"idle": pytest.approx(2.0)}

    def test_zero_and_negative_durations(self):
        assert classify_interval(0.0, {"compile": 1.0}) == {}
        assert classify_interval(-3.0, None) == {}


# -------------------------------------------------------------- rank ledger
class TestRankLedger:
    def test_close_and_finish_account_everything(self):
        led = RankLedger("exp", rank=2, chips=4.0)
        led.add_pending("input_wait", 0.002)
        time.sleep(0.01)
        led.close_interval(parts={"collective_wait": 0.001})
        time.sleep(0.01)
        led.close_interval()
        led.finish()
        snap = led.snapshot()
        assert snap["run"] == "exp" and snap["rank"] == 2
        assert snap["chips"] == 4.0
        assert snap["finished"] is True
        assert snap["open_s"] == 0.0
        # Exhaustive: classified phases cover the ledger's whole lifetime.
        assert snap["unattributed_s"] == pytest.approx(0.0, abs=1e-6)
        assert snap["phase_s"]["input_wait"] == pytest.approx(0.002)
        assert snap["phase_s"]["collective_wait"] == pytest.approx(0.001)

    def test_restart_boundary_first_interval(self):
        led = RankLedger("exp", rank=0, restarted=True)
        time.sleep(0.005)
        led.close_interval()
        snap = led.snapshot()
        assert "restart_downtime" in snap["phase_s"]
        assert "init" not in snap["phase_s"]
        assert snap["unattributed_s"] == pytest.approx(0.0, abs=1e-6)

    def test_unknown_pending_phase_dropped(self):
        led = RankLedger("exp", rank=0)
        led.add_pending("nonsense", 5.0)
        led.add_pending("input_wait", -1.0)
        led.finish()
        assert "nonsense" not in led.snapshot()["phase_s"]

    def test_open_snapshot_has_no_residual(self):
        led = RankLedger("exp", rank=0)
        led.close_interval()
        time.sleep(0.005)
        snap = led.snapshot()  # mid-interval: tail counts as open, not lost
        assert snap["open_s"] > 0.0
        assert snap["unattributed_s"] == pytest.approx(0.0, abs=1e-3)

    def test_closes_after_finish_noop(self):
        led = RankLedger("exp", rank=0)
        led.finish()
        total = sum(led.snapshot()["phase_s"].values())
        time.sleep(0.005)
        assert led.close_interval() is None
        assert sum(led.snapshot()["phase_s"].values()) == total

    def test_active_ledger_hooks(self):
        led = RankLedger("exp", rank=0)
        goodput.set_active(led)
        try:
            goodput.add_active_pending("checkpoint", 0.5)
            with goodput.input_wait():
                pass
            assert led._pending["checkpoint"] == pytest.approx(0.5)
            assert led._pending.get("input_wait", 0.0) >= 0.0
        finally:
            goodput.set_active(None)


# ------------------------------------------------------- event leg transport
class TestEventLeg:
    def test_drain_requeue_and_dedup(self):
        goodput.record_event("restart_downtime", "exp", 7.5, chips=8.0,
                             detail={"tier": "restore"})
        leg = goodput.collect_for_flush()
        assert leg is not None and len(leg["events"]) == 1
        assert goodput.collect_for_flush() is None  # drained
        # Push failed: requeue, next flush re-ships the SAME event ids.
        goodput.flush_failed(leg)
        leg2 = goodput.collect_for_flush()
        assert [e["id"] for e in leg2["events"]] == \
            [e["id"] for e in leg["events"]]
        # Head-side dedup: the same leg delivered twice lands once.
        store = GoodputStore()
        store.ingest("src", "node", leg2)
        store.ingest("src", "node", leg2)
        evs = store.events()
        assert len(evs) == 1
        assert evs[0]["seconds"] == pytest.approx(7.5)
        assert evs[0]["source"] == "src"

    def test_disabled_gate_buffers_nothing_out(self, monkeypatch):
        import ray_tpu.utils.config as config_mod

        goodput.record_event("restart_downtime", "exp", 1.0)
        monkeypatch.setenv("RTPU_GOODPUT_ENABLED", "0")
        config_mod.set_config(config_mod.Config.load())
        try:
            assert goodput.collect_for_flush() is None
        finally:
            monkeypatch.delenv("RTPU_GOODPUT_ENABLED")
            config_mod.set_config(config_mod.Config.load())

    def test_stamp_and_run_filter(self):
        store = GoodputStore()
        store.stamp("head_outage", None, 12.0, chips=2.0)
        store.ingest("c", "n", {"events": [
            {"id": "e1", "kind": "restart_downtime", "run": "exp",
             "seconds": 3.0, "chips": 1.0, "ts": 0.0, "detail": {}}]})
        assert len(store.events()) == 2
        # run filter keeps fleet-scoped (run=None) events visible
        assert {e["kind"] for e in store.events(run="exp")} == \
            {"head_outage", "restart_downtime"}
        assert [e["kind"] for e in store.events(run="other")] == \
            ["head_outage"]


# ------------------------------------------------------------------- rollup
def _train_stats(rows):
    """Head train_stats table from a list of rank-ledger snapshot dicts."""
    table = {}
    for i, gp in enumerate(rows):
        table[f"src{i}"] = {"node_id": f"n{i}", "ts": time.time(),
                            "stats": {gp["rank"]: {"goodput": gp}}}
    return table


def _snap(run="exp", rank=0, chips=1.0, phase_s=None, unattributed=0.0):
    return {"run": run, "rank": rank, "chips": chips, "t0": 0.0,
            "ts": time.time(), "phase_s": dict(phase_s or {}),
            "open_s": 0.0, "unattributed_s": unattributed,
            "spent_s": 0.001, "finished": False}


class TestRollup:
    def test_chip_second_weighting_and_goodput_pct(self):
        stats = _train_stats([
            _snap(rank=0, chips=4.0,
                  phase_s={GOOD_PHASE: 9.0, "input_wait": 1.0}),
            _snap(rank=1, chips=4.0,
                  phase_s={GOOD_PHASE: 8.0, "collective_wait": 2.0}),
        ])
        out = GoodputStore().rollup(stats)
        run = out["runs"]["exp"]
        assert run["ranks"] == 2 and run["chips"] == 8.0
        assert run["chip_seconds"] == pytest.approx(80.0)
        assert run["good_chip_s"] == pytest.approx(68.0)
        assert run["goodput_pct"] == pytest.approx(85.0)
        assert run["badput_chip_s"]["collective_wait"] == pytest.approx(8.0)
        assert out["fleet"]["goodput_pct"] == pytest.approx(85.0)

    def test_restart_event_overlap_takes_max(self):
        """The controller's restart event window CONTAINS the restarted
        context's first (rank-side) restart_downtime interval — the
        rollup must not sum the two."""
        store = GoodputStore()
        store.ingest("c", "n", {"events": [
            {"id": "r1", "kind": "restart_downtime", "run": "exp",
             "seconds": 8.0, "chips": 1.0, "ts": 0.0, "detail": {}}]})
        stats = _train_stats([
            _snap(phase_s={GOOD_PHASE: 10.0, "restart_downtime": 5.0})])
        run = store.rollup(stats)["runs"]["exp"]
        assert run["phase_chip_s"]["restart_downtime"] == pytest.approx(8.0)
        assert run["chip_seconds"] == pytest.approx(18.0)

    def test_rank_side_larger_than_event_side(self):
        store = GoodputStore()
        store.ingest("c", "n", {"events": [
            {"id": "r1", "kind": "restart_downtime", "run": "exp",
             "seconds": 2.0, "chips": 1.0, "ts": 0.0, "detail": {}}]})
        stats = _train_stats([_snap(phase_s={"restart_downtime": 6.0})])
        run = store.rollup(stats)["runs"]["exp"]
        assert run["phase_chip_s"]["restart_downtime"] == pytest.approx(6.0)

    def test_fleet_events_stay_fleet_scoped(self):
        store = GoodputStore()
        store.stamp("head_outage", None, 30.0, chips=2.0)
        out = store.rollup(_train_stats(
            [_snap(phase_s={GOOD_PHASE: 10.0})]))
        assert "head_outage" not in out["runs"]["exp"]["phase_chip_s"]
        assert out["fleet"]["phase_chip_s"]["head_outage"] == \
            pytest.approx(60.0)
        assert [e["kind"] for e in out["fleet"]["events"]] == ["head_outage"]

    def test_run_filter_and_unattributed_rollup(self):
        stats = _train_stats([
            _snap(run="a", phase_s={GOOD_PHASE: 1.0}, unattributed=0.25),
            _snap(run="b", phase_s={GOOD_PHASE: 1.0}),
        ])
        out = GoodputStore().rollup(stats, run="a")
        assert list(out["runs"]) == ["a"]
        assert out["runs"]["a"]["unattributed_s"] == pytest.approx(0.25)
        assert out["fleet"]["unattributed_s"] == pytest.approx(0.25)

    def test_serve_request_goodput_from_series(self):
        class FakeStore:
            def query(self, name=None, max_age_s=0.0):
                assert name == "serve_slo_tokens_total:rate"
                return [
                    {"name": name, "tags": {"deployment": "d"},
                     "source": "s1", "node_id": "n",
                     "points": [[1.0, 40.0]]},
                    {"name": name, "tags": {"deployment": "d"},
                     "source": "s2", "node_id": "n",
                     "points": [[1.0, 20.0]]},
                ]

        out = GoodputStore().rollup({}, series_store=FakeStore())
        dep = out["serve"]["d"]
        assert dep["slo_tokens_per_s"] == pytest.approx(60.0)
        assert dep["replicas"] == 2
        assert dep["request_goodput"] == pytest.approx(30.0)


# --------------------------------------------------------- badput watchdog
class _FakeWatchdog:
    def __init__(self):
        self.fired = []

    def record_event(self, rule, reason, detail=None):
        self.fired.append((rule, reason, detail))


class TestBadputRule:
    def test_fires_over_threshold_with_cooldown(self):
        store = GoodputStore()
        wd = _FakeWatchdog()
        stats = _train_stats([
            _snap(phase_s={GOOD_PHASE: 2.0, "input_wait": 18.0})])
        store.maybe_check(stats, wd)
        assert len(wd.fired) == 1
        rule, reason, detail = wd.fired[0]
        assert rule == "badput_over_threshold"
        assert detail["phase"] == "input_wait"
        assert detail["share_pct"] == pytest.approx(90.0)
        # Cooldown: an immediate re-check must not spam a second incident.
        store._last_check = 0.0  # defeat the ingest throttle only
        store.maybe_check(stats, wd)
        assert len(wd.fired) == 1

    def test_quiet_below_threshold_or_short_window(self):
        store = GoodputStore()
        wd = _FakeWatchdog()
        store.maybe_check(_train_stats([
            _snap(phase_s={GOOD_PHASE: 18.0, "input_wait": 2.0})]), wd)
        store2 = GoodputStore()
        store2.maybe_check(_train_stats([
            _snap(phase_s={"input_wait": 1.0})]), wd)  # < min_wall_s
        assert wd.fired == []


# -------------------------------------------------------- peak-FLOPs table
class TestPeakFlops:
    def test_table_and_aliases(self):
        from ray_tpu.accelerators import flops

        assert flops.peak_flops("v5e") == pytest.approx(197e12)
        assert flops.peak_flops("v5p", "int8") == pytest.approx(918e12)
        assert flops.peak_flops("v5litepod") == pytest.approx(197e12)
        assert flops.peak_flops("V6E") == pytest.approx(918e12)
        assert flops.peak_flops("v999") == 0.0
        assert flops.peak_flops("v4", "fp8") == 0.0

    def test_env_override_wins(self, monkeypatch):
        from ray_tpu.accelerators import flops

        monkeypatch.setenv("RTPU_PEAK_FLOPS", "1.5e14")
        assert flops.resolve_peak_flops() == pytest.approx(1.5e14)
        monkeypatch.setenv("RTPU_PEAK_FLOPS", "junk")
        flops._reset_for_tests()
        assert flops.resolve_peak_flops() == 0.0  # cpu backend: no TPU kind

    def test_session_report_uses_registry(self, monkeypatch):
        """session.report's MFU path resolves peak FLOPs through the
        registry (env override included) instead of an ad-hoc lookup."""
        import ray_tpu.train.session as session_mod

        monkeypatch.setenv("RTPU_PEAK_FLOPS", "2e14")
        src = open(session_mod.__file__).read()
        assert "resolve_peak_flops" in src
        from ray_tpu.accelerators.flops import resolve_peak_flops

        assert resolve_peak_flops() == pytest.approx(2e14)


# ------------------------------------------- sampler monotonic denominator
class TestSamplerMonotonicRates:
    def test_wall_clock_step_backwards_keeps_rates_sane(self, monkeypatch):
        """NTP steps the wall clock backwards between two flushes: the
        payload timestamp follows the wall clock, but the rate must be
        derived from the monotonic interval — never negative, never
        scaled by the step."""
        from ray_tpu.observability.sampler import SeriesSampler

        wall = [1000.0]
        mono = [50.0]
        monkeypatch.setattr(time, "time", lambda: wall[0])
        monkeypatch.setattr(time, "monotonic", lambda: mono[0])

        def snap(count):
            return {"metrics": [{
                "name": "serve_slo_tokens_total", "type": "counter",
                "tag_keys": ["deployment"],
                "points": [[["d"], float(count)]]}]}

        s = SeriesSampler()
        s.collect(snap(0))  # declare + establish cumulative state
        mono[0] += 10.0
        wall[0] -= 500.0  # the NTP step
        payload = s.collect(snap(30))
        assert payload is not None
        assert payload["t"] == pytest.approx(500.0)  # wall, as shipped
        rate_samples = [v for sid, v in payload["s"]
                        for d_sid, name, _ in payload["defs"]
                        if sid == d_sid and name.endswith(":rate")]
        assert rate_samples == [pytest.approx(3.0)]  # 30 / 10 mono-seconds

    def test_injected_clock_path_unchanged(self):
        from ray_tpu.observability.sampler import SeriesSampler

        s = SeriesSampler()
        snap = {"metrics": [{
            "name": "serve_slo_tokens_total", "type": "counter",
            "tag_keys": [], "points": [[[], 0.0]]}]}
        s.collect(snap, now=100.0)
        snap2 = {"metrics": [{
            "name": "serve_slo_tokens_total", "type": "counter",
            "tag_keys": [], "points": [[[], 5.0]]}]}
        payload = s.collect(snap2, now=110.0)
        vals = [v for _, v in payload["s"]]
        assert vals == [pytest.approx(0.5)]


# --------------------------------------------- tracing wraparound + spans
class TestTracingDrops:
    def test_flush_cursor_wraparound_meters_drops(self, monkeypatch):
        from ray_tpu.util import metrics, tracing

        tracing.clear()
        monkeypatch.setattr(tracing, "_spans", deque(maxlen=4))
        monkeypatch.setattr(tracing, "_spans_total", 0)
        monkeypatch.setattr(tracing, "_dropped_metered", 0)
        tracing.enable_tracing()
        try:
            for i in range(6):
                tracing.record_span(f"goodput.idle{i}", 1.0, 2.0,
                                    kind="goodput")
            spans, cursor = tracing.flush_new(0)
            # Ring wrapped: the flusher gets the surviving tail, the
            # cursor lands past everything, and the loss is counted.
            assert len(spans) == 4
            assert cursor == 6
            assert tracing.dropped_spans() == 2
            assert [s["name"] for s in spans] == \
                [f"goodput.idle{i}" for i in range(2, 6)]
            # Idempotent metering: a second flush adds no phantom drops.
            _, cursor = tracing.flush_new(cursor)
            assert tracing.dropped_spans() == 2
            for e in metrics.registry().snapshot()["metrics"]:
                if e["name"] == "tracing_spans_dropped":
                    assert e["points"][0][1] == pytest.approx(2.0)
                    break
            else:
                pytest.fail("tracing_spans_dropped not exported")
        finally:
            tracing.disable_tracing()
            tracing.clear()

    def test_record_span_shape(self):
        from ray_tpu.util import tracing

        tracing.clear()
        tracing.enable_tracing()
        try:
            tracing.record_span("goodput.compile", 10.0, 12.5,
                                kind="goodput",
                                attributes={"run": "exp", "rank": 3})
            spans, _ = tracing.flush_new(0)
            (s,) = [x for x in spans if x["name"] == "goodput.compile"]
            assert s["kind"] == "goodput"
            assert s["end_ts"] - s["start_ts"] == pytest.approx(2.5)
            # attribute values are stringified on the wire (span schema)
            assert s["attributes"] == {"run": "exp", "rank": "3"}
        finally:
            tracing.disable_tracing()
            tracing.clear()

    def test_goodput_lane_in_chrome_trace(self):
        from ray_tpu.profiling.merge import merge_chrome_trace

        doc = merge_chrome_trace([], spans=[
            {"span_id": "a", "trace_id": "t1", "name": "goodput.compile",
             "kind": "goodput", "start_ts": 1.0, "end_ts": 2.0,
             "attributes": {"run": "exp", "rank": 0}},
            {"span_id": "b", "trace_id": "t2", "name": "rpc.call",
             "kind": "client", "start_ts": 1.0, "end_ts": 2.0},
        ])
        rows = {e["name"]: e for e in doc["traceEvents"] if e["ph"] == "X"}
        assert rows["goodput.compile"]["pid"] == "goodput"
        assert rows["goodput.compile"]["tid"] == "exp/r0"
        assert rows["rpc.call"]["pid"] == "spans"
        meta_pids = {e["pid"] for e in doc["traceEvents"]
                     if e.get("name") == "process_name"}
        assert {"spans", "goodput"} <= meta_pids


# ---------------------------------------------------- serve SLO token gate
class TestServeSloTokens:
    def test_deadline_gates_token_counting(self):
        from ray_tpu.serve.replica import ServeReplica

        class Stub:
            def __init__(self):
                self.n = 0

            def inc(self, v):
                self.n += v

        stub = Stub()
        fake = type("F", (), {"_b": {"slo_tokens": stub}})()
        ServeReplica._count_slo_tokens(fake, 1, None)
        ServeReplica._count_slo_tokens(fake, 2, time.time() + 60.0)
        ServeReplica._count_slo_tokens(fake, 4, time.time() - 1.0)  # blown
        assert stub.n == 3


# ------------------------------------------------------- CLI table render
class TestCliGoodputTable:
    def test_table_path_renders_top_badput(self, monkeypatch, capsys):
        # badput_chip_s is a DICT (phase -> chip-seconds); the table path
        # must rank its items, not slice it (regression: dict[:3] raised).
        from ray_tpu.scripts import cli

        rollup = {
            "enabled": True,
            "runs": {"r1": {
                "ranks": 2, "chip_seconds": 10.0, "goodput_pct": 62.5,
                "unattributed_s": 0.0,
                "badput_chip_s": {"input_wait": 2.0, "compile": 1.0,
                                  "checkpoint": 0.5, "idle": 0.25},
            }},
            "fleet": {"chip_seconds": 10.0, "goodput_pct": 62.5,
                      "unattributed_s": 0.0},
            "serve": {},
        }
        monkeypatch.setattr(cli, "_connect", lambda address: None)
        monkeypatch.setattr("ray_tpu.util.state.get_goodput",
                            lambda run=None: rollup)
        args = type("A", (), {"address": None, "run": None, "json": False})()
        assert cli.cmd_goodput(args) == 0
        out = capsys.readouterr().out
        assert "r1" in out and "62.5" in out
        assert "input_wait 2.0s, compile 1.0s, checkpoint 0.5s" in out
