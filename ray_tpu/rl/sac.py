"""SAC: maximum-entropy off-policy RL for continuous control, in pure JAX.

Capability parity with the reference's SAC family (reference:
rllib/algorithms/sac/sac.py + torch learner — squashed-Gaussian actor, twin
Q critics with polyak-averaged targets, automatic entropy-temperature
tuning; Algorithm is a Tune Trainable): rollouts come from the same
EnvRunnerGroup as PPO/DQN (continuous actions ride the runner's generic
action batch), the update is one jitted lax.scan over minibatches, and the
Algorithm plugs into ray_tpu.tune unchanged.

This fills the continuous-control archetype of the algorithm matrix
(sync on-policy = PPO, off-policy replay = DQN, async actor-learner =
IMPALA, offline = BC, multi-agent = MultiAgentPPO, max-entropy continuous
= SAC).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rl.env import make_env
from ray_tpu.rl.env_runner import EnvRunnerGroup
from ray_tpu.rl.ppo import init_mlp, mlp_apply
from ray_tpu.rl.replay import ReplayBuffer
from ray_tpu.tune.trainable import Trainable

LOG_STD_MIN, LOG_STD_MAX = -5.0, 2.0


def _actor_dist(params, obs):
    out = mlp_apply(params, obs)
    mean, log_std = jnp.split(out, 2, axis=-1)
    log_std = jnp.clip(log_std, LOG_STD_MIN, LOG_STD_MAX)
    return mean, log_std


def _sample_action(params, obs, key, max_action):
    """Squashed-Gaussian sample + its log-prob (tanh change of variables)."""
    mean, log_std = _actor_dist(params, obs)
    std = jnp.exp(log_std)
    eps = jax.random.normal(key, mean.shape)
    pre = mean + std * eps
    a = jnp.tanh(pre)
    # log N(pre; mean, std) - sum log |d tanh/d pre| - log max_action
    logp = (-0.5 * (eps**2 + 2 * log_std + jnp.log(2 * jnp.pi))).sum(-1)
    logp -= (2 * (jnp.log(2.0) - pre - jax.nn.softplus(-2 * pre))).sum(-1)
    logp -= a.shape[-1] * jnp.log(max_action)
    return a * max_action, logp


def _q_apply(q_params, obs, act):
    x = jnp.concatenate([obs, act], axis=-1)
    return mlp_apply(q_params, x)[..., 0]


@partial(jax.jit, static_argnums=(0, 1, 2))
def sac_update(optimizers, gamma, target_entropy, params, target_q, opt_states,
               batches, keys, max_action, tau):
    """K SGD steps in ONE dispatch (lax.scan over stacked [K, B, ...]
    minibatches): critics on the entropy-regularized TD target, actor on
    min-Q + entropy, log-alpha toward the entropy target, polyak targets."""
    actor_opt, q_opt, alpha_opt = optimizers

    def one(carry, inp):
        p, tq, os_ = carry
        batch, key = inp
        k1, k2 = jax.random.split(key)
        alpha = jnp.exp(p["log_alpha"])

        # --- critics -------------------------------------------------
        def q_loss_fn(q_pair):
            a_next, logp_next = _sample_action(p["actor"],
                                               batch["next_obs"], k1,
                                               max_action)
            tq1 = _q_apply(tq[0], batch["next_obs"], a_next)
            tq2 = _q_apply(tq[1], batch["next_obs"], a_next)
            soft_v = jnp.minimum(tq1, tq2) - \
                jax.lax.stop_gradient(alpha) * logp_next
            target = batch["rewards"] + gamma * (1.0 - batch["dones"]) * \
                jax.lax.stop_gradient(soft_v)
            q1 = _q_apply(q_pair[0], batch["obs"], batch["actions"])
            q2 = _q_apply(q_pair[1], batch["obs"], batch["actions"])
            return ((q1 - target) ** 2 + (q2 - target) ** 2).mean()

        q_loss, q_grads = jax.value_and_grad(q_loss_fn)(p["q"])
        q_updates, q_os = q_opt.update(q_grads, os_["q"], p["q"])
        new_q = optax.apply_updates(p["q"], q_updates)

        # --- actor ---------------------------------------------------
        def actor_loss_fn(actor_p):
            a, logp = _sample_action(actor_p, batch["obs"], k2, max_action)
            q1 = _q_apply(new_q[0], batch["obs"], a)
            q2 = _q_apply(new_q[1], batch["obs"], a)
            return (jax.lax.stop_gradient(alpha) * logp
                    - jnp.minimum(q1, q2)).mean(), logp

        (a_loss, logp), a_grads = jax.value_and_grad(
            actor_loss_fn, has_aux=True)(p["actor"])
        a_updates, a_os = actor_opt.update(a_grads, os_["actor"], p["actor"])
        new_actor = optax.apply_updates(p["actor"], a_updates)

        # --- temperature --------------------------------------------
        def alpha_loss_fn(log_alpha):
            return -(log_alpha * jax.lax.stop_gradient(
                logp + target_entropy)).mean()

        al_loss, al_grad = jax.value_and_grad(alpha_loss_fn)(p["log_alpha"])
        al_updates, al_os = alpha_opt.update(al_grad, os_["alpha"])
        new_log_alpha = optax.apply_updates(p["log_alpha"], al_updates)

        new_tq = jax.tree.map(lambda t, q: (1 - tau) * t + tau * q,
                              tq, new_q)
        new_p = {"actor": new_actor, "q": new_q,
                 "log_alpha": new_log_alpha}
        new_os = {"actor": a_os, "q": q_os, "alpha": al_os}
        return (new_p, new_tq, new_os), (q_loss, a_loss, alpha)

    (params, target_q, opt_states), (q_losses, a_losses, alphas) = \
        jax.lax.scan(one, (params, target_q, opt_states), (batches, keys))
    return params, target_q, opt_states, q_losses[-1], a_losses[-1], \
        alphas[-1]


@dataclass
class SACConfig:
    env: str = "Pendulum-v1"
    num_env_runners: int = 0
    num_envs_per_runner: int = 8
    rollout_len: int = 16
    actor_lr: float = 3e-4
    critic_lr: float = 3e-4
    alpha_lr: float = 3e-4
    gamma: float = 0.99
    tau: float = 0.01
    buffer_size: int = 100_000
    batch_size: int = 256
    learning_starts: int = 1_000
    train_batches_per_step: int = 16
    hidden: int = 128
    init_alpha: float = 0.2
    seed: int = 0
    extra: dict = field(default_factory=dict)

    def build(self) -> "SAC":
        return SAC({"sac_config": self})


class SAC(Trainable):
    """EnvRunnerGroup sampling (stochastic squashed-Gaussian exploration) +
    replay + one jitted twin-critic/actor/temperature scan per step()
    (reference: sac.py training_step shape)."""

    def setup(self, config: dict) -> None:
        cfg = config.get("sac_config") or SACConfig(
            **{k: v for k, v in config.items()
               if k in SACConfig.__dataclass_fields__})
        self.cfg = cfg
        probe = make_env(cfg.env, seed=cfg.seed)
        if not getattr(probe, "continuous", False):
            raise ValueError(f"SAC needs a continuous-action env, "
                             f"got {cfg.env!r}")
        obs_size = probe.observation_size
        act_size = probe.action_size
        # The env protocol's action bound (not any env-specific constant):
        # continuous envs declare action_limit alongside action_size.
        self.max_action = float(getattr(probe, "action_limit", 1.0))
        key = jax.random.PRNGKey(cfg.seed)
        ka, k1, k2 = jax.random.split(key, 3)
        self.params = {
            "actor": init_mlp(ka, [obs_size, cfg.hidden, cfg.hidden,
                                   2 * act_size]),
            "q": (init_mlp(k1, [obs_size + act_size, cfg.hidden, cfg.hidden,
                                1], scale_last=1.0),
                  init_mlp(k2, [obs_size + act_size, cfg.hidden, cfg.hidden,
                                1], scale_last=1.0)),
            "log_alpha": jnp.asarray(np.log(cfg.init_alpha), jnp.float32),
        }
        self.target_q = jax.tree.map(jnp.copy, self.params["q"])
        self.optimizers = (optax.adam(cfg.actor_lr), optax.adam(cfg.critic_lr),
                           optax.adam(cfg.alpha_lr))
        self.opt_states = {
            "actor": self.optimizers[0].init(self.params["actor"]),
            "q": self.optimizers[1].init(self.params["q"]),
            "alpha": self.optimizers[2].init(self.params["log_alpha"]),
        }
        self.buffer = ReplayBuffer(cfg.buffer_size, obs_size, seed=cfg.seed,
                                   action_size=act_size)
        self.target_entropy = -float(act_size)
        self.env_steps = 0
        self._rng = np.random.default_rng(cfg.seed)
        max_action = self.max_action

        @jax.jit
        def _act(actor_params, obs, key):
            return _sample_action(actor_params, obs, key, max_action)

        def policy_factory(params=None):
            def act(actor_params, obs, seed):
                a, logp = _act(actor_params, jnp.asarray(obs),
                               jax.random.PRNGKey(seed))
                a = np.asarray(a, np.float32)
                return a, np.asarray(logp, np.float32), \
                    np.zeros(len(a), np.float32)
            return act, None

        self.runners = EnvRunnerGroup(
            cfg.env, num_runners=cfg.num_env_runners,
            num_envs_per_runner=cfg.num_envs_per_runner,
            rollout_len=cfg.rollout_len, policy_factory=policy_factory,
            seed=cfg.seed)
        self._return_window: list[float] = []

    def step(self) -> dict:
        cfg = self.cfg
        samples = self.runners.sample(self.params["actor"])
        for s in samples:
            T, N = s["rewards"].shape
            # next_obs carries the TRUE pre-reset successors (truncation
            # bootstrapping must target V(final state), not V(reset state)).
            self.buffer.add_batch(
                s["obs"].reshape(T * N, -1),
                s["actions"].reshape(T * N, -1),
                s["rewards"].reshape(-1),
                s["next_obs"].reshape(T * N, -1),
                # Bootstrap through time-limit truncation: only TRUE
                # terminations zero the future value (Pendulum never
                # terminates, so dones here would poison every episode end).
                s["terminals"].reshape(-1).astype(np.float32))
            self.env_steps += T * N
            self._return_window.extend(s["episode_returns"])

        q_loss = a_loss = alpha = 0.0
        if self.env_steps >= cfg.learning_starts:
            raw = [self.buffer.sample(cfg.batch_size)
                   for _ in range(cfg.train_batches_per_step)]
            batches = {k: jnp.asarray(np.stack([b[k] for b in raw]))
                       for k in raw[0]}
            keys = jax.random.split(
                jax.random.PRNGKey(self._rng.integers(1 << 31)),
                cfg.train_batches_per_step)
            (self.params, self.target_q, self.opt_states, q_l, a_l,
             al) = sac_update(
                self.optimizers, cfg.gamma, self.target_entropy,
                self.params, self.target_q, self.opt_states, batches, keys,
                self.max_action, cfg.tau)
            q_loss, a_loss, alpha = float(q_l), float(a_l), float(al)

        self._return_window = self._return_window[-100:]
        mean_ret = (float(np.mean(self._return_window))
                    if self._return_window else 0.0)
        return {
            "episode_return_mean": mean_ret,
            "num_env_steps_sampled": self.env_steps,
            "q_loss": q_loss, "actor_loss": a_loss, "alpha": alpha,
            "buffer_size": len(self.buffer),
        }

    def save_checkpoint(self) -> Any:
        return {"params": jax.tree.map(np.asarray, self.params),
                "target_q": jax.tree.map(np.asarray, self.target_q),
                "env_steps": self.env_steps, "iteration": self.iteration}

    def load_checkpoint(self, checkpoint: Any) -> None:
        self.params = jax.tree.map(jnp.asarray, checkpoint["params"])
        self.target_q = jax.tree.map(jnp.asarray, checkpoint["target_q"])
        self.env_steps = checkpoint["env_steps"]
        self.iteration = checkpoint["iteration"]

    def cleanup(self) -> None:
        self.runners.shutdown()
