"""Head fault tolerance: crash-consistent control plane.

Covers the four legs of head-outage survival (reference shapes: GCS fault
tolerance — redis-backed mutation persistence, HandleNotifyGCSRestart and
the raylet reconnect path, node_manager.cc:1050):

- torn-WAL-tail tolerance: replay stops CLEANLY at a truncated or
  bit-flipped trailing record instead of raising mid-load;
- exactly-once mutations: request-id dedup across a crash-before-ACK,
  plus the natural-idempotence belts under it;
- reconciliation + fencing on (re-)register: died-during-outage workers
  reaped, un-ACKed grants re-pinned, amnesiac-head adoption, orphan
  kills, stale daemon epochs and stale head boots fenced;
- the chaos plane's ``kill_head`` / directional ``partition`` rules and
  the retry wrapper that rides an outage out.
"""

import asyncio
import os
import threading
import time

import pytest

import ray_tpu
from ray_tpu.chaos import injector
from ray_tpu.core.cluster.head import HeadServer
from ray_tpu.core.worker import global_worker
from ray_tpu.utils.ids import JobID

from _test_util import load_factor as _load_factor  # noqa: F401 - parity


@pytest.fixture(autouse=True)
def _chaos_reset():
    injector.reset_for_tests()
    yield
    os.environ.pop("RTPU_CHAOS", None)
    injector.reset_for_tests()


class FakeConn:
    """Stand-in ServerConnection for direct head-handler calls."""

    def __init__(self):
        self.meta = {}
        self.notifies = []

    async def notify(self, method, **kw):
        self.notifies.append((method, kw))


def _mk_head(tmp_path) -> HeadServer:
    return HeadServer("127.0.0.1", 0, persist_path=str(tmp_path / "head.db"))


def _run(coro):
    return asyncio.run(coro)


# ------------------------------------------------------------- WAL torn tail
def test_wal_truncated_tail_replays_clean_prefix(tmp_path):
    """Power-loss tail: any byte prefix of the final append must load —
    replay keeps everything before the torn record, drops the tail, and
    never raises."""
    head = _mk_head(tmp_path)
    _run(head._kv_put(None, "ns", "k1", b"v1"))
    _run(head._kv_put(None, "ns", "k2", b"v2"))
    head._flush_wal()
    wal = str(tmp_path / "head.db.wal")
    data = open(wal, "rb").read()
    for cut in (3, 1):  # mid-payload and mid-header truncations
        with open(wal, "wb") as f:
            f.write(data[:-cut])
        h2 = _mk_head(tmp_path)
        assert h2.kv["ns"]["k1"] == b"v1"
        assert "k2" not in h2.kv.get("ns", {})
        assert h2._wal_tail_dropped >= 1
        # the new head appended its own boot record; restore the original
        with open(wal, "wb") as f:
            f.write(data)


def test_wal_bit_flip_detected_by_crc(tmp_path):
    head = _mk_head(tmp_path)
    _run(head._kv_put(None, "ns", "k1", b"v1"))
    _run(head._kv_put(None, "ns", "k2", b"v2"))
    _run(head._kv_put(None, "ns", "k3", b"v3"))
    head._flush_wal()
    wal = str(tmp_path / "head.db.wal")
    data = bytearray(open(wal, "rb").read())
    # Flip one bit inside the LAST record's payload: the length prefix
    # still frames it, only the CRC can tell.
    data[-2] ^= 0x40
    with open(wal, "wb") as f:
        f.write(bytes(data))
    h2 = _mk_head(tmp_path)
    assert h2.kv["ns"]["k1"] == b"v1"
    assert h2.kv["ns"]["k2"] == b"v2"
    assert "k3" not in h2.kv.get("ns", {})
    assert h2._wal_tail_dropped == 1


def test_wal_mid_file_corruption_stops_at_first_bad_record(tmp_path):
    """Nothing after a corrupt record can be trusted to frame correctly;
    replay keeps the intact prefix only — and still never raises."""
    head = _mk_head(tmp_path)
    _run(head._kv_put(None, "ns", "k1", b"value-one"))
    _run(head._kv_put(None, "ns", "k2", b"value-two"))
    head._flush_wal()
    wal = str(tmp_path / "head.db.wal")
    data = bytearray(open(wal, "rb").read())
    idx = bytes(data).find(b"value-one")
    data[idx] ^= 0xFF
    with open(wal, "wb") as f:
        f.write(bytes(data))
    h2 = _mk_head(tmp_path)
    assert "k1" not in h2.kv.get("ns", {})
    assert "k2" not in h2.kv.get("ns", {})
    assert h2._wal_tail_dropped == 1


def test_wal_legacy_format_replays_via_v1_parser(tmp_path):
    """A pre-CRC-format WAL (bare 4-byte length prefixes, no magic) must
    replay through the legacy parser on upgrade — not be discarded as one
    giant torn tail — and then be retired so current-format records never
    land in a legacy file."""
    import pickle
    import struct

    wal = str(tmp_path / "head.db.wal")
    with open(wal, "wb") as f:  # hand-written legacy segment
        for args in (("kv_put", ("ns", "k1", b"v1")),
                     ("kv_put", ("ns", "k2", b"v2"))):
            rec = pickle.dumps(args)
            f.write(struct.pack("<I", len(rec)) + rec)
    head = _mk_head(tmp_path)
    assert head.kv["ns"]["k1"] == b"v1"
    assert head.kv["ns"]["k2"] == b"v2"
    # the legacy segment was retired to .wal.old; the fresh .wal opens
    # with the version magic
    from ray_tpu.core.cluster.head import _WAL_MAGIC

    assert open(wal, "rb").read(len(_WAL_MAGIC)) == _WAL_MAGIC
    assert (tmp_path / "head.db.wal.old").exists()
    # and a SECOND boot (snapshot-less) replays legacy .wal.old + new .wal
    _run(head._kv_put(None, "ns", "k3", b"v3"))
    head._flush_wal()
    h2 = _mk_head(tmp_path)
    assert h2.kv["ns"]["k1"] == b"v1" and h2.kv["ns"]["k3"] == b"v3"


def test_actor_ready_does_not_resurrect_dead_actor(tmp_path):
    """A placement losing its race (actor reaped/killed while the worker
    was booting) must not resurrect the DEAD actor when actor_ready
    finally lands — it gets a kill back instead."""
    from ray_tpu.core.cluster.head import ActorInfo

    head = _mk_head(tmp_path)
    conn = FakeConn()
    _run(head._register_node(conn, **_register_kw()))
    head.actors["a9"] = ActorInfo(actor_id="a9", state="DEAD",
                                  node_id="nodeA", death_reason="reaped")
    conn.meta["node_id"] = "nodeA"
    res = _run(head._actor_ready(conn, "a9", "w1", "127.0.0.1", 700))
    assert res == {"ok": False, "dead": True}
    assert head.actors["a9"].state == "DEAD"
    assert ("kill_actor", {"actor_id": "a9"}) in conn.notifies


def test_reconcile_skips_in_flight_placements(tmp_path):
    """An actor the daemon reports as PLACING (worker still forking) is
    neither reaped nor re-pinned — it resolves through actor_ready/
    actor_failed on the fresh session."""
    from ray_tpu.core.cluster.head import ActorInfo

    head = _mk_head(tmp_path)
    head.actors["boot1"] = ActorInfo(actor_id="boot1", state="PENDING",
                                     node_id="nodeA")
    state = _register_kw()["state"]
    state["placing"] = ["boot1"]
    res = _run(head._register_node(FakeConn(),
                                   **_register_kw(state=state)))
    assert res["reconcile"]["reaped"] == 0
    assert head.actors["boot1"].state == "PENDING"


def test_heartbeat_from_unregistered_conn_routed_to_register(tmp_path):
    """A heartbeat arriving on a connection that never passed the
    register fence (a superseded daemon un-pausing) must not update the
    node's resource view through the side door."""
    head = _mk_head(tmp_path)
    owner = FakeConn()
    _run(head._register_node(owner, **_register_kw(epoch=5.0)))
    stale = FakeConn()  # different connection: never registered
    res = _run(head._heartbeat(stale, "nodeA", available={"CPU": 99.0}))
    assert res.get("reregister") and not res.get("ok")
    assert head.nodes["nodeA"].available == {"CPU": 8.0}  # untouched
    # the OWNING connection's heartbeat still lands
    head._node_conns["nodeA"] = owner
    res2 = _run(head._heartbeat(owner, "nodeA", available={"CPU": 7.0}))
    assert res2.get("ok")
    assert head.nodes["nodeA"].available == {"CPU": 7.0}


def test_fence_yields_when_owner_is_dead(tmp_path):
    """Epochs are wall-clock: a replacement daemon whose host clock
    stepped backwards must still be able to take a node id whose owning
    incarnation is GONE — the fence only defends a live owner."""
    head = _mk_head(tmp_path)
    _run(head._register_node(FakeConn(), **_register_kw(epoch=5.0)))
    head.nodes["nodeA"].alive = False  # owner died / was declared dead
    res = _run(head._register_node(FakeConn(), **_register_kw(epoch=3.0)))
    assert res["ok"] and not res.get("fenced")
    assert head.nodes["nodeA"].epoch == 3.0


# ------------------------------------------------------------ mutation dedup
def test_dedup_retried_mutation_across_restart(tmp_path):
    """Crash between applying a mutation and ACKing it: the client
    retries the SAME req_id against the restarted head and must get the
    recorded first answer, not a second application."""
    head = _mk_head(tmp_path)
    r1 = _run(head._kv_put(None, "ns", "k", b"v", overwrite=False,
                           req_id="rid-1"))
    assert r1["ok"] is True
    head._flush_wal()
    h2 = _mk_head(tmp_path)  # crash + restart: replay snapshot-less WAL
    r2 = _run(h2._kv_put(None, "ns", "k", b"clobber", overwrite=False,
                         req_id="rid-1"))
    assert r2["ok"] is True, "retry must replay the recorded reply"
    assert h2.kv["ns"]["k"] == b"v", "retry must not re-apply"
    # a genuinely NEW no-overwrite put still refuses
    r3 = _run(h2._kv_put(None, "ns", "k", b"x", overwrite=False,
                         req_id="rid-2"))
    assert r3["ok"] is False


def test_dedup_retried_create_actor_not_name_taken(tmp_path):
    """The crash window the dedup table exists for: register_actor logged
    + crashed before ACK; the retried registration must not collide with
    its own first attempt's name."""
    head = _mk_head(tmp_path)
    kw = dict(actor_id="a" * 32, spec_blob=b"blob", resources={},
              name="svc", namespace="default", max_restarts=0)
    r1 = _run(head._register_actor(FakeConn(), req_id="rid-a", **kw))
    # no nodes: scheduling failed deterministically — reply recorded
    assert r1["ok"] is False and "no feasible node" in r1["error"]
    head._flush_wal()
    h2 = _mk_head(tmp_path)
    r2 = _run(h2._register_actor(FakeConn(), req_id="rid-a", **kw))
    assert r2 == r1, "same req_id: the recorded reply, verbatim"
    # Natural-idempotence belt: req_id aged out of the dedup table, but
    # the actor_id (client-unique) is already in the replayed table.
    assert kw["actor_id"] in h2.actors
    r3 = _run(h2._register_actor(FakeConn(), req_id="rid-zzz", **kw))
    assert r3["ok"] is True and r3.get("existed")


def test_dedup_table_bounded_and_snapshotted(tmp_path):
    from ray_tpu.utils import config as config_mod

    os.environ["RTPU_HEAD_DEDUP_MAX"] = "32"
    config_mod.set_config(config_mod.Config.load())
    try:
        head = _mk_head(tmp_path)
        for i in range(80):
            _run(head._kv_put(None, "ns", f"k{i}", b"v", req_id=f"r{i}"))
        assert len(head._dedup) == 32
        assert "r0" not in head._dedup and "r79" in head._dedup
        # survives a snapshot+restart round trip
        head._flush_wal()
        head._write_snapshot(head._snapshot_state())
        h2 = _mk_head(tmp_path)
        assert "r79" in h2._dedup and len(h2._dedup) == 32
    finally:
        os.environ.pop("RTPU_HEAD_DEDUP_MAX", None)
        config_mod.set_config(config_mod.Config.load())


# ------------------------------------------------- reconciliation + fencing
def _register_kw(node_id="nodeA", epoch=1.0, state=None, cpu=8.0):
    return dict(node_id=node_id, host="127.0.0.1", port=1,
                resources={"CPU": cpu}, epoch=epoch,
                state=state if state is not None else {
                    "available": {"CPU": cpu}, "workers": [],
                    "dead_workers": [], "actors": {}, "leases": [],
                    "bundles": []})


def test_reconcile_worker_died_during_outage(tmp_path):
    from ray_tpu.core.cluster.head import ActorInfo

    head = _mk_head(tmp_path)
    head.actors["a1"] = ActorInfo(actor_id="a1", state="ALIVE",
                                  node_id="nodeA",
                                  worker_addr=("127.0.0.1", 999))

    async def scenario():
        res = await head._register_node(FakeConn(), **_register_kw())
        # The reap's death path is DELIBERATELY deferred behind the
        # register reply (a restart placement must not outrun the boot-id
        # adoption); give the loop a couple of ticks to run it.
        for _ in range(3):
            await asyncio.sleep(0)
        return res

    res = _run(scenario())
    assert res["ok"] and res["reconcile"]["reaped"] == 1
    assert head.actors["a1"].state == "DEAD"
    assert "died during head outage" in head.actors["a1"].death_reason


def test_reconcile_unacked_grant_repinned(tmp_path):
    """Actor placed, grant un-ACKed at the crash instant: the head
    replayed PENDING, the daemon reports it alive — re-pin, don't
    re-place."""
    from ray_tpu.core.cluster.head import ActorInfo

    head = _mk_head(tmp_path)
    head.actors["a2"] = ActorInfo(actor_id="a2", state="PENDING",
                                  node_id="nodeA")
    state = _register_kw()["state"]
    state["actors"] = {"a2": {"worker_id": "w1",
                              "addr": ["127.0.0.1", 777]}}
    res = _run(head._register_node(FakeConn(),
                                   **_register_kw(state=state)))
    assert res["reconcile"]["repinned"] == 1
    info = head.actors["a2"]
    assert info.state == "ALIVE" and info.worker_addr == ("127.0.0.1", 777)


def test_reconcile_amnesiac_adoption_and_orphan_kill(tmp_path):
    from ray_tpu.core.cluster.head import ActorInfo

    head = _mk_head(tmp_path)
    head.actors["dead1"] = ActorInfo(actor_id="dead1", state="DEAD",
                                     node_id="nodeA")
    conn = FakeConn()
    state = _register_kw()["state"]
    state["actors"] = {
        "orphanless": {"worker_id": "w9", "addr": ["127.0.0.1", 555]},
        "dead1": {"worker_id": "w2", "addr": ["127.0.0.1", 556]},
    }
    res = _run(head._register_node(conn, **_register_kw(state=state)))
    assert res["reconcile"]["adopted"] == 1
    assert res["reconcile"]["orphans_killed"] == 1
    assert head.actors["orphanless"].state == "ALIVE"
    assert ("kill_actor", {"actor_id": "dead1"}) in conn.notifies


def test_reconcile_lease_returned_during_outage(tmp_path):
    """Leases granted/returned while the head was down: the register
    payload's availability is daemon truth and seeds the head's view (a
    fresh-node assumption would advertise phantom capacity)."""
    head = _mk_head(tmp_path)
    state = _register_kw()["state"]
    state["available"] = {"CPU": 3.0}  # 5 of 8 CPUs leased out right now
    _run(head._register_node(FakeConn(), **_register_kw(state=state)))
    assert head.nodes["nodeA"].available == {"CPU": 3.0}
    assert head.nodes["nodeA"].resources == {"CPU": 8.0}


def test_reconcile_prunes_dead_worker_rows(tmp_path):
    head = _mk_head(tmp_path)
    head.workers["wdead"] = ("127.0.0.1", 123, "nodeA")
    head.workers["wother"] = ("127.0.0.1", 124, "nodeB")
    state = _register_kw()["state"]
    state["dead_workers"] = ["wdead", "wother"]  # wother ≠ this node
    res = _run(head._register_node(FakeConn(), **_register_kw(state=state)))
    assert res["reconcile"]["workers_pruned"] == 1
    assert "wdead" not in head.workers and "wother" in head.workers


def test_reconcile_repends_pg_with_evaporated_bundles(tmp_path):
    head = _mk_head(tmp_path)

    async def scenario():
        await head._register_node(FakeConn(), **_register_kw())
        head.pgs["pg1"] = {"state": "CREATED", "bundles": [{"CPU": 1.0}],
                           "strategy": "PACK", "assignment": ["nodeA"],
                           "name": None}
        # daemon restarted: reports NO bundles for pg1
        res = await head._register_node(FakeConn(),
                                        **_register_kw(epoch=2.0))
        assert res["reconcile"]["pgs_repending"] == 1
        assert head.pgs["pg1"]["state"] == "PENDING"
        head.pgs["pg1"]["state"] = "REMOVED"  # stop the background retry
        await asyncio.sleep(0)

    _run(scenario())


def test_fence_stale_daemon_epoch(tmp_path):
    head = _mk_head(tmp_path)
    r1 = _run(head._register_node(FakeConn(), **_register_kw(epoch=5.0)))
    assert r1["ok"]
    r2 = _run(head._register_node(FakeConn(), **_register_kw(epoch=3.0)))
    assert r2.get("fenced") and not r2.get("ok")
    assert head._fenced_registrations == 1
    assert head.nodes["nodeA"].epoch == 5.0
    # same epoch (reconnect of the registered incarnation) is fine
    r3 = _run(head._register_node(FakeConn(), **_register_kw(epoch=5.0)))
    assert r3["ok"]


def test_fence_stale_head_place_actor(tmp_path):
    """A superseded head's place_actor must not allocate a worker on a
    daemon that already registered with the replacement head."""
    from ray_tpu.core.cluster.node_daemon import NodeDaemon
    from ray_tpu.core.cluster.protocol import EventLoopThread

    d = NodeDaemon("127.0.0.1", 1, "fencenode", {"CPU": 1.0})
    try:
        d._head_boot_id = "boot-new"
        # _head is None: an unfenced call would crash dereferencing it,
        # so returning quietly proves the fence fired first.
        _run(d._place_actor("someactor", b"", {}, head_boot="boot-old"))
        assert "someactor" not in d._actor_workers
    finally:
        EventLoopThread.get().run(d.stop())


def test_lease_dedup_replays_grants(tmp_path):
    """A retried lease RPC (reply died with the connection) must get the
    FIRST batch back, not leak it and grant fresh workers."""
    from ray_tpu.core.cluster.node_daemon import NodeDaemon, WorkerProc
    from ray_tpu.core.cluster.protocol import EventLoopThread

    d = NodeDaemon("127.0.0.1", 1, "leasenode", {"CPU": 4.0})
    try:
        for i in range(2):
            wp = WorkerProc(worker_id=f"w{i}", proc=None,
                            addr=("127.0.0.1", 100 + i))
            d.workers[wp.worker_id] = wp

        async def scenario():
            r1 = await d._lease_workers(None, {"CPU": 1.0}, count=2,
                                        req_id="lease-1")
            r2 = await d._lease_workers(None, {"CPU": 1.0}, count=2,
                                        req_id="lease-1")
            return r1, r2

        r1, r2 = _run(scenario())
        assert r1.get("grants")
        assert r2 == r1, "retry must replay the recorded grants"
        assert len(d._leases) == 2, "retry granted no extra workers"
    finally:
        EventLoopThread.get().run(d.stop())


# ------------------------------------------------------------ chaos points
def test_partition_rule_matching_and_direction():
    injector.install([
        {"point": "partition", "action": "drop",
         "match": {"node": "^abc"}, "direction": "to_head"},
        {"point": "partition", "action": "delay", "delay_s": 0.3,
         "match": {"node": "xyz"}, "direction": "both"},
    ], replace=True)
    assert injector.partition_action("abcdef", "to_head") == ("drop", 0.0)
    assert injector.partition_action("abcdef", "from_head") is None
    assert injector.partition_action("zzz", "to_head") is None
    assert injector.partition_action("xyz1", "from_head") == ("delay", 0.3)
    assert injector.partition_action("xyz1", "to_head") == ("delay", 0.3)
    # unknown direction value is rejected at parse time
    with pytest.raises(ValueError, match="direction"):
        injector.ChaosRule.from_dict(
            {"point": "partition", "direction": "sideways"})


def test_head_tick_point_accepted():
    injector.install([{"point": "head.tick", "action": "kill", "count": 1}],
                     replace=True)
    rule = injector.decide("head.tick")
    assert rule is not None and rule.action == "kill"
    assert injector.decide("head.tick") is None  # budget spent


# --------------------------------------------------------------- e2e drills
@pytest.fixture
def ft_cluster(tmp_path):
    """Fresh persistent-head cluster per test (the drills mutate/kill the
    head, so nothing is shared)."""
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.utils import config as config_mod

    os.environ["RTPU_HEALTH_CHECK_PERIOD_S"] = "0.2"
    os.environ["RTPU_DAEMON_HEARTBEAT_TIMEOUT_S"] = "1.0"
    os.environ["RTPU_HEAD_RETRY_BUDGET_S"] = "30.0"
    config_mod.set_config(config_mod.Config.load())
    ray_tpu.shutdown()
    c = Cluster(persist_path=str(tmp_path / "head.db"))
    c.add_node(num_cpus=4)
    rt = c.connect()
    old = (global_worker.runtime, global_worker.worker_id,
           global_worker.node_id, global_worker.mode, global_worker.job_id)
    global_worker.runtime = rt
    global_worker.worker_id = rt.worker_id
    global_worker.node_id = rt.node_id
    global_worker.job_id = JobID.from_random()
    global_worker.mode = "cluster"
    yield c, rt
    try:
        rt.shutdown()
        c.shutdown()
    except Exception:
        pass
    (global_worker.runtime, global_worker.worker_id, global_worker.node_id,
     global_worker.mode, global_worker.job_id) = old
    for k in ("RTPU_HEALTH_CHECK_PERIOD_S", "RTPU_DAEMON_HEARTBEAT_TIMEOUT_S",
              "RTPU_HEAD_RETRY_BUDGET_S"):
        os.environ.pop(k, None)
    from ray_tpu.utils import config as config_mod

    config_mod.set_config(config_mod.Config.load())


@pytest.mark.chaos
def test_retry_wrapper_rides_out_head_outage(ft_cluster):
    c, rt = ft_cluster
    rt.kv_put("pre", b"1")

    def outage():
        c.kill_head()
        time.sleep(0.8)
        c.revive_head()

    t = threading.Thread(target=outage)
    t.start()
    time.sleep(0.2)  # land the put inside the outage window
    rt.kv_put("durable", b"value")  # must retry, not raise
    t.join()
    assert rt.kv_get("durable") == b"value"
    assert rt.kv_get("pre") == b"1"
    hs = rt.head_status()
    assert hs["incarnation"] == 2 and hs["restart_count"] == 1


@pytest.mark.chaos
def test_kill_head_chaos_rule_and_recovery(ft_cluster, wait_for):
    c, rt = ft_cluster
    rt.kv_put("k", b"v")
    # Scoped to THIS head's boot id: earlier tests can leak in-process
    # clusters whose heads would otherwise race for the firing budget.
    res = rt.chaos_cluster(rules=[{"point": "head.tick", "action": "kill",
                                   "count": 1,
                                   "match": {"boot": c.head.boot_id}}])
    assert res["head"]["active"]
    # the health loop fires within one period and the head goes dark
    wait_for(lambda: self_head_dead(c), timeout=10,
             desc="head died from chaos rule")
    c.revive_head()
    injector.clear()  # the in-process injector survives the head object
    # daemons re-register on their heartbeat; durable state is back
    wait_for(lambda: any(n.alive for n in c.head.nodes.values()),
             timeout=15, desc="daemon re-registered")
    assert rt.kv_get("k") == b"v"
    assert rt.head_status()["restart_count"] == 1


def self_head_dead(c) -> bool:
    srv = c.head.rpc._server
    return srv is None or not srv.is_serving()


@pytest.mark.chaos
def test_partition_drill_heals_without_double_allocation(ft_cluster,
                                                         wait_for):
    """Directional partition: the head declares the node dead, the daemon
    rides its reconnect path blind, and on heal it re-registers under the
    SAME epoch — accepted, not fenced, and nothing double-allocated."""
    c, rt = ft_cluster
    victim = c.nodes[0].node_id
    before_fenced = c.head._fenced_registrations
    c.partition_from_head(victim, direction="both", action="drop")
    wait_for(lambda: not c.head.nodes[victim].alive, timeout=20,
             desc="head declared the partitioned node dead")
    c.heal_partition()
    wait_for(lambda: c.head.nodes[victim].alive, timeout=20,
             desc="daemon re-registered after heal")
    assert c.head._fenced_registrations == before_fenced
    # exactly one live registration for the node id; resources sane
    assert len([n for n in c.head.nodes.values()
                if n.node_id == victim and n.alive]) == 1
    # the link-state metrics saw the flap
    from ray_tpu.core.cluster.node_daemon import _head_metrics

    pts = _head_metrics()["reconnects"]._points()
    assert sum(pts.values()) >= 1


@pytest.mark.chaos
def test_actor_survives_head_restart_with_reconcile(ft_cluster, wait_for):
    """An actor keeps serving through a head crash; after restart the
    reconcile re-pins it (the head's WAL had it, the daemon confirms)."""
    from ray_tpu import remote

    c, rt = ft_cluster

    @remote
    class Counter:
        def __init__(self):
            self.n = 0

        def bump(self):
            self.n += 1
            return self.n

    h = Counter.options(name="ctr").remote()
    assert ray_tpu.get(h.bump.remote(), timeout=60) == 1
    c.crash_head()
    wait_for(lambda: any(n.alive for n in c.head.nodes.values()),
             timeout=15, desc="daemon re-registered")
    # actor state intact, name resolvable, calls still flow
    h2 = ray_tpu.get_actor("ctr")
    assert ray_tpu.get(h2.bump.remote(), timeout=60) == 2
    assert c.head.actors and all(
        a.state in ("ALIVE", "DEAD") for a in c.head.actors.values())
    hs = rt.head_status()
    assert hs["incarnation"] == 2
