"""Top-level public API: init/shutdown, get/put/wait, remote, actors.

Capability parity with the reference's driver API (reference:
python/ray/_private/worker.py — ray.init :1406, ray.get/put/wait/kill/cancel,
ray.get_actor): ``init`` with no address starts a standalone runtime;
``init(address=...)`` connects to a running cluster head.
"""

from __future__ import annotations

from typing import Any, Sequence

from ray_tpu.core.actor import ActorHandle
from ray_tpu.core.exceptions import RayTpuError
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.worker import global_worker
from ray_tpu.utils.ids import JobID, NodeID


def init(
    address: str | None = None,
    *,
    num_cpus: float | None = None,
    resources: dict[str, float] | None = None,
    ignore_reinit_error: bool = True,
    _node_id: NodeID | None = None,
) -> None:
    """Start (or connect to) the runtime.

    - ``address=None``: in-process runtime (full semantics, threads as workers).
    - ``address="local-cluster"``: start a head + node daemon on this host and
      connect (multiprocess).
    - ``address="host:port"``: connect to an existing head.
    """
    if global_worker.connected:
        if ignore_reinit_error:
            return
        raise RayTpuError("already initialized; call shutdown() first")

    import os

    if address is None:
        # Reference: RAY_ADDRESS env steers a bare ray.init() to a running
        # cluster (python/ray/_private/services.py canonicalize_bootstrap).
        address = os.environ.get("RAY_TPU_ADDRESS") or None
    if address == "auto":
        # Reference: ray.init("auto") finds the cluster started by
        # `ray start` on this host. Our `start` writes head.addr.
        from ray_tpu.scripts.start import default_temp_dir

        addr_file = os.path.join(default_temp_dir(), "head.addr")
        try:
            with open(addr_file) as f:
                address = f.read().strip()
        except OSError:
            raise RayTpuError(
                "address='auto' but no running cluster found (no "
                f"{addr_file}); run `python -m ray_tpu start --head` first, "
                "and if it was started with a custom --temp-dir, set "
                "RAY_TPU_TEMP_DIR to that directory"
            ) from None

    global_worker.job_id = JobID.from_random()
    if address is None:
        from ray_tpu.core.local_runtime import LocalRuntime

        cpus = num_cpus if num_cpus is not None else 8
        global_worker.runtime = LocalRuntime(num_cpus=cpus, resources=resources)
        global_worker.worker_id = global_worker.runtime.worker_id
        global_worker.node_id = _node_id or NodeID.from_random()
        global_worker.mode = "local"
    elif address.startswith("client://"):
        # Remote-driver mode (reference: ray.init("ray://...") through the
        # Ray Client proxy, python/ray/util/client/client_builder.py).
        from ray_tpu.util.client import connect as client_connect

        global_worker.runtime = client_connect(address[len("client://"):])
        global_worker.worker_id = global_worker.runtime.worker_id
        global_worker.node_id = global_worker.runtime.node_id
        global_worker.mode = "client"
    else:
        try:
            from ray_tpu.core.cluster.client import connect_cluster
        except ImportError as e:
            raise NotImplementedError(
                "cluster mode is not available in this build"
            ) from e

        global_worker.runtime = connect_cluster(
            address, num_cpus=num_cpus, resources=resources
        )
        global_worker.worker_id = global_worker.runtime.worker_id
        global_worker.node_id = global_worker.runtime.node_id
        global_worker.mode = "cluster"


def is_initialized() -> bool:
    return global_worker.connected


def shutdown() -> None:
    if global_worker.runtime is not None:
        global_worker.runtime.shutdown()
    global_worker.runtime = None
    global_worker.mode = None


def put(value: Any) -> ObjectRef:
    if isinstance(value, ObjectRef):
        raise TypeError("put() of an ObjectRef is not allowed")
    return global_worker.put(value)


def get(refs: ObjectRef | Sequence[ObjectRef], *, timeout: float | None = None):
    single = isinstance(refs, ObjectRef)
    try:
        ref_list = [refs] if single else list(refs)
    except TypeError:
        raise TypeError(
            f"get() expects an ObjectRef or a sequence of ObjectRefs, got {type(refs).__name__}"
        ) from None
    for r in ref_list:
        if not isinstance(r, ObjectRef):
            raise TypeError(f"get() expects ObjectRef(s), got {type(r)}")
    values = global_worker.get(ref_list, timeout=timeout)
    return values[0] if single else values


def wait(
    refs: Sequence[ObjectRef],
    *,
    num_returns: int = 1,
    timeout: float | None = None,
    fetch_local: bool = True,
):
    global_worker.check_connected()
    if num_returns > len(refs):
        raise ValueError("num_returns cannot exceed the number of refs")
    return global_worker.runtime.wait(
        list(refs), num_returns=num_returns, timeout=timeout, fetch_local=fetch_local
    )


def kill(actor: ActorHandle, *, no_restart: bool = True) -> None:
    global_worker.check_connected()
    global_worker.runtime.kill_actor(actor.actor_id, no_restart=no_restart)


def cancel(ref: ObjectRef, *, force: bool = False) -> None:
    global_worker.check_connected()
    global_worker.runtime.cancel(ref, force=force)


def get_actor(name: str, namespace: str = "default") -> ActorHandle:
    global_worker.check_connected()
    actor_id = global_worker.runtime.get_named_actor(name, namespace)
    if actor_id is None:
        raise ValueError(f"no actor named {name!r} in namespace {namespace!r}")
    return ActorHandle(actor_id)


def cluster_resources() -> dict[str, float]:
    global_worker.check_connected()
    return global_worker.runtime.cluster_resources()


def available_resources() -> dict[str, float]:
    global_worker.check_connected()
    return global_worker.runtime.available_resources()
