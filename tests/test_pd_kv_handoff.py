"""Zero-copy KV hand-off for disaggregated prefill/decode (llm/pd.py):
store-mode export/import moves ZERO serialized KV bytes (the bytes-moved
assertion), continuations match the single-engine ground truth whichever
transport carried the KV, and chunked-prefill export → import round-trips
survive odd lengths and slot reuse after eviction."""

import numpy as np
import pytest

import ray_tpu


# ------------------------------------------------- zero-copy KV hand-off
def _metric_total(name: str) -> float:
    from ray_tpu.util.metrics import registry

    for m in registry().metrics():
        if m.name == name:
            return float(sum(m._points().values()))
    return 0.0


class TestKvHandoff:
    def test_store_mode_moves_zero_serialized_bytes(self):
        """The bytes-moved assertion: a store-mode hand-off ships ONLY
        ObjectRefs through the handle payload; every KV byte crosses as a
        raw store buffer and the serialized-bytes counter stays flat,
        while the inline path counts every byte."""
        from ray_tpu.core.object_ref import ObjectRef
        from ray_tpu.llm import LLMConfig, LLMEngine
        from ray_tpu.llm.pd import export_kv_payload, resolve_kv_payload

        ray_tpu.shutdown()
        ray_tpu.init()
        try:
            eng = LLMEngine(LLMConfig(model="tiny", max_num_seqs=2,
                                      max_seq_len=96))
            try:
                raw = eng.prefill_only(list(range(1, 20)))
                kv_bytes = raw["kv_k"].nbytes + raw["kv_v"].nbytes
                assert kv_bytes > 0

                ser0 = _metric_total("llm_kv_serialized_bytes")

                payload = export_kv_payload(dict(raw), "store")
                # no ndarray rides the handle call — refs only
                assert isinstance(payload["kv_ref_k"], ObjectRef)
                assert isinstance(payload["kv_ref_v"], ObjectRef)
                assert "kv_k" not in payload and "kv_v" not in payload
                assert _metric_total("llm_kv_serialized_bytes") == ser0, \
                    "store-mode hand-off serialized KV bytes"

                back = resolve_kv_payload(payload)
                np.testing.assert_array_equal(back["kv_k"], raw["kv_k"])
                np.testing.assert_array_equal(back["kv_v"], raw["kv_v"])

                # inline mode: every KV byte is counted as serialized
                export_kv_payload(dict(raw), "inline")
                assert _metric_total("llm_kv_serialized_bytes") \
                    == ser0 + kv_bytes
            finally:
                eng.shutdown()
        finally:
            ray_tpu.shutdown()

    def test_store_mode_decode_continuation_matches_inline(self):
        """Same tokens whichever transport carried the KV."""
        from ray_tpu.llm import LLMConfig, LLMEngine, SamplingParams
        from ray_tpu.llm.pd import export_kv_payload, resolve_kv_payload

        ray_tpu.shutdown()
        ray_tpu.init()
        cfg = LLMConfig(model="tiny", max_num_seqs=2, max_seq_len=96,
                        seed=3)
        try:
            prompt = list(np.random.default_rng(2).integers(1, 200, 15))
            single = LLMEngine(cfg)
            want = single.generate(
                prompt, SamplingParams(max_tokens=6, temperature=0.0),
                timeout=120).token_ids
            single.shutdown()

            pre, dec = LLMEngine(cfg), LLMEngine(cfg)
            try:
                payload = export_kv_payload(
                    pre.prefill_only(prompt), "store")
                req = dec.submit_prefilled(
                    resolve_kv_payload(payload),
                    SamplingParams(max_tokens=5, temperature=0.0))
                assert req.done.wait(120) and not req.error
                assert req.out_tokens == want[:len(req.out_tokens)]
            finally:
                pre.shutdown()
                dec.shutdown()
        finally:
            ray_tpu.shutdown()


def test_prefill_only_retires_prefix_for_publication():
    """A dedicated prefill engine must ACCUMULATE prefix-cache state from
    prefill_only traffic: the exported slot's KV retires as a cached
    prefix line (not discarded with the hold_slot release), so the
    replica publishes real block hashes for KV-block-aware routing and a
    shared-prefix follow-up prefills only the tail."""
    import time

    from ray_tpu.llm import LLMConfig, LLMEngine
    from ray_tpu.serve.prefix import block_hashes, match_len

    cfg = LLMConfig(model="tiny", max_num_seqs=2, max_seq_len=96,
                    prefix_block_tokens=8)
    eng = LLMEngine(cfg)
    try:
        prompt = list(range(1, 34))  # 33 tokens -> 4 full blocks of 8
        eng.prefill_only(prompt)
        # the release (and retire) happens on the next scheduler tick
        want = block_hashes(prompt, 8)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if match_len(want, set(eng.prefix_block_hashes())) == len(want):
                break
            time.sleep(0.02)
        assert match_len(want, set(eng.prefix_block_hashes())) \
            == len(want), "prefill_only slot was not retired for publication"
        # a shared-prefix follow-up adopts the cached prefix
        saved = eng.prefix_tokens_saved
        out = eng.prefill_only(prompt + [77, 78, 79])
        assert out["kv_k"].shape[2] == len(prompt) + 3
        assert eng.prefix_hits >= 1 and eng.prefix_tokens_saved > saved, \
            "shared-prefix prefill_only recomputed the cached prefix"
    finally:
        eng.shutdown()


# -------------------------------------- prefill_chunk KV round-trip drill
@pytest.mark.parametrize("prompt_len", [13, 33, 47])
def test_prefill_chunk_kv_roundtrip_odd_lengths(prompt_len):
    """Chunked-prefill KV export → import continuation at lengths that
    leave partial last chunks/buckets (13 < bucket_min, 33 crosses one
    16-bucket, 47 leaves a 15-token tail), against the single-engine
    greedy ground truth."""
    from ray_tpu.llm import LLMConfig, LLMEngine, SamplingParams

    cfg = LLMConfig(model="tiny", max_num_seqs=2, max_seq_len=128, seed=7,
                    prefill_bucket_min=16, prefill_chunk=16)
    prompt = list(np.random.default_rng(prompt_len).integers(1, 200,
                                                             prompt_len))
    single = LLMEngine(cfg)
    want = single.generate(prompt, SamplingParams(max_tokens=6,
                                                  temperature=0.0),
                           timeout=120).token_ids
    single.shutdown()

    pre, dec = LLMEngine(cfg), LLMEngine(cfg)
    try:
        payload = pre.prefill_only(prompt)
        assert payload["kv_k"].shape[2] == prompt_len
        assert payload["first_token"] == want[0]
        req = dec.submit_prefilled(payload, SamplingParams(
            max_tokens=5, temperature=0.0))
        assert req.done.wait(120) and not req.error
        assert req.out_tokens == want[:len(req.out_tokens)]
    finally:
        pre.shutdown()
        dec.shutdown()


def test_kv_import_into_reused_slot_after_eviction():
    """A KV import must not read the previous tenant's stale tail: a
    1-slot decode engine first runs a LONG sequence, then imports a
    SHORTER prefill into the same slot — positions beyond the imported
    length hold the old sequence's KV and must be masked."""
    from ray_tpu.llm import LLMConfig, LLMEngine, SamplingParams

    cfg = LLMConfig(model="tiny", max_num_seqs=1, max_seq_len=96, seed=11)
    long_prompt = list(np.random.default_rng(3).integers(1, 200, 40))
    short_prompt = list(np.random.default_rng(4).integers(1, 200, 9))

    single = LLMEngine(cfg)
    want = single.generate(short_prompt, SamplingParams(
        max_tokens=6, temperature=0.0), timeout=120).token_ids
    single.shutdown()

    pre, dec = LLMEngine(cfg), LLMEngine(cfg)
    try:
        # occupy and retire the only slot with the long sequence
        dec.generate(long_prompt, SamplingParams(max_tokens=8,
                                                 temperature=0.0),
                     timeout=120)
        payload = pre.prefill_only(short_prompt)
        req = dec.submit_prefilled(payload, SamplingParams(
            max_tokens=5, temperature=0.0))
        assert req.done.wait(120) and not req.error
        assert req.out_tokens == want[:len(req.out_tokens)], \
            "stale KV from the evicted tenant leaked into the import"
    finally:
        pre.shutdown()
        dec.shutdown()
