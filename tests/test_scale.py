"""Fleet-scale control plane: sim fleet, delta sync, indexed scheduling.

Four legs of the thousand-node harness (devbench/scale_bench.py sweeps
the same machinery to its knees; these tests pin the correctness
contracts at tier-1 size):

- **sim-fleet lifecycle**: dozens of REAL :class:`NodeDaemon` instances
  (``sim=True`` — no shm arena, no forked workers) register against a
  real head over the real RPC stack, one TimerWheel drives their beats,
  the summary/filtered ``list_nodes`` forms see them, and shutdown is
  clean.
- **delta-sync round trip**: full-on-register → delta → removed keys →
  idle skip (no RPC at all) → forced liveness beat → resync when the
  head loses its base — including a full head restart on the same port.
- **indexed-vs-linear parity**: the heap/label-index ``_pick_node`` must
  return exactly what the full-scan oracle returns over randomized
  inventories, mutations, optimistic holds, affinity and label
  constraints.
- **chaos kill during a lease/actor storm**: daemons die mid-placement
  (one via the injector's ``daemon.tick`` probe, the rest via the fleet
  chaos helper); the head declares them dead, stays responsive, strands
  no actor in a non-terminal state, and still schedules new work.
"""

import asyncio
import os
import random
import time
import uuid

import pytest

from ray_tpu.chaos import injector
from ray_tpu.core.cluster.head import HeadServer, NodeInfo
from ray_tpu.core.cluster.node_daemon import NodeDaemon
from ray_tpu.core.cluster.protocol import AsyncRpcClient, EventLoopThread
from ray_tpu.core.cluster.sim_fleet import SimFleet, TimerWheel, parse_geometry
from ray_tpu.utils.config import Config, get_config, set_config

pytestmark = pytest.mark.scale


# ----------------------------------------------------------------- plumbing
@pytest.fixture(autouse=True)
def _chaos_reset():
    injector.reset_for_tests()
    yield
    os.environ.pop("RTPU_CHAOS", None)
    injector.reset_for_tests()


@pytest.fixture
def fast_beats():
    """Shrink the health-check period so delta/liveness behavior (idle
    gap = period * threshold / 3) is observable in test time."""
    old = os.environ.get("RTPU_HEALTH_CHECK_PERIOD_S")
    os.environ["RTPU_HEALTH_CHECK_PERIOD_S"] = "0.2"
    set_config(Config.load())
    yield get_config()
    if old is None:
        os.environ.pop("RTPU_HEALTH_CHECK_PERIOD_S", None)
    else:
        os.environ["RTPU_HEALTH_CHECK_PERIOD_S"] = old
    set_config(Config.load())


class FakeConn:
    """Stand-in ServerConnection for direct head-handler calls."""

    def __init__(self):
        self.meta = {}
        self.notifies = []

    async def notify(self, method, **kw):
        self.notifies.append((method, kw))


def _io() -> EventLoopThread:
    return EventLoopThread.get()


def _poll(predicate, timeout: float = 15.0, interval: float = 0.02,
          desc: str = "condition"):
    deadline = time.monotonic() + timeout
    while True:
        value = predicate()
        if value:
            return value
        assert time.monotonic() < deadline, f"timed out waiting for {desc}"
        time.sleep(interval)


def _start_head(tmp_path, name="head.db", port=0):
    head = HeadServer("127.0.0.1", port, persist_path=str(tmp_path / name))
    _, bound = _io().run(head.start())
    return head, bound


def _stop_head(head):
    _io().run(head.stop())


def _head_view(head, node_id):
    """(available copy, last_heartbeat) read ON the head's loop — head
    state is single-threaded by design; tests must not race it."""
    async def peek():
        n = head.nodes[node_id]
        return dict(n.available), n.last_heartbeat
    return _io().run(peek())


async def _close_daemon(d):
    await d.stop()
    if d._head is not None:
        try:
            await d._head.close()
        except Exception:
            pass


# ------------------------------------------------------- sim-fleet lifecycle
def test_sim_fleet_lifecycle(tmp_path):
    head, port = _start_head(tmp_path)
    fleet = None
    try:
        fleet = SimFleet.launch("127.0.0.1", port, n_nodes=24,
                                heartbeat_period_s=0.05)
        assert fleet.register_failures == 0
        assert len(fleet.daemons) == 24

        per_node, labels = parse_geometry(fleet.geometry)
        assert labels["sim"] == "1"
        summ = _io().run(head._list_nodes(None, summary=True))["summary"]
        assert summ["nodes_total"] == 24 and summ["nodes_alive"] == 24
        assert summ["resources"]["TPU"] == per_node["TPU"] * 24
        assert summ["resources"]["CPU"] == per_node["CPU"] * 24

        # Filtered + capped listing keeps the per-node row shape.
        rows = _io().run(head._list_nodes(None, labels={"sim": "1"},
                                          alive_only=True, limit=5))
        assert len(rows) == 5
        assert all(r["labels"]["topology"] == fleet.geometry
                   for r in rows.values())
        assert not _io().run(head._list_nodes(None, labels={"sim": "0"}))

        # The wheel actually beats every daemon, and nothing is lost:
        # registration seeded the delta base, so idle beats ride the
        # empty/skipped wire — never full, never failed.
        _poll(lambda: fleet.wheel.fired >= 48, desc="two wheel revolutions")
        st = fleet.hb_stats()
        assert st["failed"] == 0 and st["resync"] == 0 and st["full"] == 0
    finally:
        if fleet is not None:
            fleet.shutdown()
        _stop_head(head)
    assert fleet.daemons == []


def test_timer_wheel_remove_and_dead_daemon_unschedules():
    """Wheel bookkeeping: removed entries never fire again, and a daemon
    whose beat reports death (fenced/killed) is dropped from rotation."""
    async def scenario():
        wheel = TimerWheel(0.02)

        class Beater:
            def __init__(self, node_id, alive=True):
                self.node_id, self.alive, self.beats = node_id, alive, 0

            async def _heartbeat_once(self):
                self.beats += 1
                return self.alive

        live, doomed = Beater("live"), Beater("doomed", alive=False)
        wheel.add(live, 0.0)
        wheel.add(doomed, 0.0)
        wheel.start()
        try:
            for _ in range(200):
                if live.beats >= 5 and doomed.beats:
                    break
                await asyncio.sleep(0.01)
            assert live.beats >= 5
            assert doomed.beats == 1, "dead daemon must leave the rotation"
            wheel.remove("live")
            frozen = live.beats
            await asyncio.sleep(0.1)
            assert live.beats <= frozen + 1, "removed entry kept firing"
        finally:
            await wheel.stop()

    _io().run(scenario())


# ------------------------------------------------------ delta-sync round trip
def test_delta_sync_round_trip(tmp_path, fast_beats):
    head, port = _start_head(tmp_path)
    d = NodeDaemon("127.0.0.1", port, "deltanode",
                   {"CPU": 8.0, "TPU": 4.0, "memory": 1024.0}, sim=True)
    io = _io()
    try:
        io.run(d.start())
        # Registration ships the live inventory: it IS the full sync.
        assert d._hb_synced and not d._hb_force_full
        avail, _ = _head_view(head, "deltanode")
        assert avail == {"CPU": 8.0, "TPU": 4.0, "memory": 1024.0}

        # 1) Changed + removed keys ride one delta beat.
        async def mutate_and_beat():
            d.available["CPU"] -= 3.0
            d.available.pop("memory")
            return await d._heartbeat_once()
        assert io.run(mutate_and_beat())
        assert d._hb_stats["delta"] == 1 and d._hb_stats["full"] == 0
        avail, _ = _head_view(head, "deltanode")
        assert avail == {"CPU": 5.0, "TPU": 4.0}

        # 2) An unchanged view inside the idle gap sends NOTHING (the
        # ray_syncer contract: no change, no message).
        sent_before = d._hb_stats["sent"] if "sent" in d._hb_stats else None
        assert io.run(d._heartbeat_once())
        assert d._hb_stats["skipped"] == 1
        if sent_before is not None:
            assert d._hb_stats["sent"] == sent_before

        # 3) ...but liveness still flows: past the gap the beat goes out
        # as an empty delta and stamps last_heartbeat on the head.
        _, hb_before = _head_view(head, "deltanode")
        d._hb_last_sent = 0.0
        assert io.run(d._heartbeat_once())
        assert d._hb_stats["empty"] == 1
        _, hb_after = _head_view(head, "deltanode")
        assert hb_after > hb_before

        # 4) Head loses the base (restart-mid-stream surrogate): the next
        # delta gets resync — the head must NOT apply it against a view
        # it never fully received.
        async def drop_base():
            head._node_conns["deltanode"].meta["hb_synced"] = False
        io.run(drop_base())

        async def mutate_and_beat2():
            d.available["CPU"] = 1.0
            return await d._heartbeat_once()
        assert io.run(mutate_and_beat2())
        assert d._hb_stats["resync"] == 1 and d._hb_force_full
        avail, _ = _head_view(head, "deltanode")
        assert avail["CPU"] == 5.0, "head must keep the stale-but-consistent view"

        # 5) The forced full beat converges the views and re-arms deltas.
        assert io.run(d._heartbeat_once())
        assert d._hb_stats["full"] == 1 and d._hb_synced
        assert not d._hb_force_full
        avail, _ = _head_view(head, "deltanode")
        assert avail == {"CPU": 1.0, "TPU": 4.0}
    finally:
        io.run(_close_daemon(d))
        _stop_head(head)


def test_head_restart_resync(tmp_path, fast_beats):
    """Kill the head, boot a replacement on the same port: the daemon's
    beats ride out the outage (failed → reconnect → full re-register)
    and the NEW head converges on daemon truth, not registration-time
    fiction."""
    head, port = _start_head(tmp_path, name="h1.db")
    d = NodeDaemon("127.0.0.1", port, "restartnode",
                   {"CPU": 8.0, "TPU": 4.0}, sim=True)
    io = _io()
    head2 = None
    try:
        io.run(d.start())
        # Resources moved while the head was up; then the head dies.
        async def consume():
            d.available["CPU"] = 2.5
            return await d._heartbeat_once()
        assert io.run(consume())
        _stop_head(head)

        head2 = HeadServer("127.0.0.1", port,
                           persist_path=str(tmp_path / "h2.db"))
        _io().run(head2.start())

        # Drive beats until the daemon has re-registered with the new
        # head. The first beat(s) fail on the dead conn (counted, full
        # forced), _reconnect_head runs the real registration path.
        def beaten():
            ok = io.run(d._heartbeat_once())
            assert ok, "daemon must survive a head outage"
            return ("restartnode" in io.run(_alive_ids(head2))
                    and d._hb_synced)

        async def _alive_ids(h):
            return [nid for nid, n in h.nodes.items() if n.alive]
        _poll(beaten, timeout=20.0, interval=0.05, desc="re-registration")

        assert d._hb_stats["failed"] >= 1
        avail, _ = _head_view(head2, "restartnode")
        assert avail["CPU"] == 2.5, "replacement head must see daemon truth"

        # And the delta stream is re-armed against the new head.
        async def mutate_and_beat():
            d.available["CPU"] = 7.0
            d._hb_last_sent = 0.0
            return await d._heartbeat_once()
        assert io.run(mutate_and_beat())
        avail, _ = _head_view(head2, "restartnode")
        assert avail["CPU"] == 7.0
    finally:
        io.run(_close_daemon(d))
        if head2 is not None:
            _stop_head(head2)


# -------------------------------------------------- indexed-vs-linear parity
def _seed_random_nodes(head, rng, n):
    gens = ["v5e", "v6e", "cpuonly"]
    node_ids = []

    async def seed():
        for i in range(n):
            res = {"CPU": float(rng.randint(1, 64))}
            if rng.random() < 0.7:
                res["TPU"] = float(rng.choice([4, 8]))
            labels = {"accelerator": rng.choice(gens)}
            if rng.random() < 0.3:
                labels["pool"] = rng.choice(["a", "b"])
            nid = f"n{i:03d}"
            r = await head._register_node(FakeConn(), nid, "127.0.0.1",
                                          7000 + i, res, labels=labels,
                                          epoch=float(i + 1))
            assert r["ok"]
            node_ids.append(nid)
    asyncio.run(seed())
    return node_ids


def test_indexed_linear_parity_randomized(tmp_path):
    """The indexed picker (heap + label inverted index + affinity dict
    hit) must agree with the full-scan oracle on EVERY randomized
    inventory/demand pair, across availability mutations, optimistic
    holds, label churn, and node deaths — all applied through the
    _sched_touch contract."""
    assert get_config().indexed_scheduler_enabled
    rng = random.Random(0xF1EE7)
    head = HeadServer("127.0.0.1", 0, persist_path=str(tmp_path / "p.db"))
    node_ids = _seed_random_nodes(head, rng, 40)

    gens = ["v5e", "v6e", "cpuonly", "ghost"]
    for trial in range(400):
        # Mutate a handful of nodes the way heartbeats/placement would.
        for nid in rng.sample(node_ids, 6):
            n = head.nodes[nid]
            n.available["CPU"] = float(rng.randint(0, int(n.resources["CPU"])))
            if "TPU" in n.resources and rng.random() < 0.3:
                n.available["TPU"] = float(
                    rng.randint(0, int(n.resources["TPU"])))
            if rng.random() < 0.15:
                n.optimistic["CPU"] = float(rng.randint(0, 4))
            elif n.optimistic:
                n.optimistic.clear()
            if rng.random() < 0.08:
                n.alive = not n.alive
            head._sched_touch(n)

        res = {"CPU": float(rng.randint(0, 16))}
        if rng.random() < 0.4:
            res["TPU"] = float(rng.choice([4.0, 8.0]))
        affinity = rng.choice(node_ids) if rng.random() < 0.15 else None
        labels = None
        if rng.random() < 0.35:
            labels = {"accelerator": rng.choice(gens)}
            if rng.random() < 0.25:
                labels["pool"] = rng.choice(["a", "b", "c"])

        fast = head._pick_node(res, affinity, labels)
        slow = head._pick_node_linear(res, affinity, labels)
        assert (fast.node_id if fast else None) == \
            (slow.node_id if slow else None), (
                f"trial {trial}: indexed={fast and fast.node_id} "
                f"linear={slow and slow.node_id} for res={res} "
                f"affinity={affinity} labels={labels}")


def test_assign_bundles_valid_and_strategy_correct(tmp_path):
    """_assign_bundles over the index caches: assignments must fit real
    availability, honor strategy semantics, and be deterministic."""
    rng = random.Random(31337)
    head = HeadServer("127.0.0.1", 0, persist_path=str(tmp_path / "b.db"))
    node_ids = _seed_random_nodes(head, rng, 12)
    for nid in node_ids:  # drain some nodes so feasibility is non-trivial
        n = head.nodes[nid]
        n.available["CPU"] = float(rng.randint(0, int(n.resources["CPU"])))
        head._sched_touch(n)

    bundles = [{"CPU": 2.0} for _ in range(5)] + [{"CPU": 1.0, "TPU": 4.0}]
    for strategy in ("PACK", "SPREAD", "STRICT_SPREAD", "STRICT_PACK"):
        asg = head._assign_bundles(list(bundles), strategy)
        assert asg == head._assign_bundles(list(bundles), strategy)
        if asg is None:
            continue
        assert len(asg) == len(bundles)
        # Every node's total take fits its availability.
        take: dict[str, dict[str, float]] = {}
        for nid, b in zip(asg, bundles):
            t = take.setdefault(nid, {})
            for k, v in b.items():
                t[k] = t.get(k, 0.0) + v
        for nid, t in take.items():
            n = head.nodes[nid]
            assert n.alive
            for k, v in t.items():
                assert n.available.get(k, 0.0) >= v, \
                    f"{strategy}: {nid} over-packed on {k}"
        if strategy == "STRICT_SPREAD":
            assert len(set(asg)) == len(bundles)
        if strategy == "STRICT_PACK":
            assert len(set(asg)) == 1
    # Infeasible demand answers None, not a bogus assignment.
    assert head._assign_bundles([{"CPU": 1e9}], "PACK") is None


# --------------------------------------------- chaos kill during lease storm
def test_chaos_kill_during_actor_storm(tmp_path, fast_beats):
    """Daemons die mid actor-placement storm — one through the injector's
    daemon.tick probe (the production chaos path), three via the fleet
    helper. The head must declare all four dead, leave no actor stuck in
    a non-terminal state, answer control RPCs throughout, and still
    place NEW work on the survivors."""
    head, port = _start_head(tmp_path)
    fleet = None
    io = _io()
    client = None
    try:
        fleet = SimFleet.launch("127.0.0.1", port, n_nodes=16,
                                heartbeat_period_s=0.05)
        # Victim index 1: fleet.kill(3, stride=5) below takes indices
        # 0/5/10, so the injector victim stays distinct — four deaths.
        victim = fleet.daemons[1].node_id
        injector.install([{"point": "daemon.tick", "action": "kill",
                           "match": {"node": f"^{victim}$"}, "count": 1}])

        async def connect():
            cl = AsyncRpcClient("127.0.0.1", port)
            await cl.connect()
            return cl
        client = io.run(connect())

        n_actors = 36
        ids = [uuid.uuid4().hex for _ in range(n_actors)]

        async def storm():
            for i, aid in enumerate(ids):
                r = await client.call(
                    "register_actor", actor_id=aid, spec_blob=b"",
                    resources={"CPU": 1.0}, name=None, namespace="default",
                    max_restarts=2, req_id=f"scale-storm-{i}")
                assert r["ok"]
        io.run(storm())

        # Kill three more daemons while placements are in flight.
        killed = io.run(fleet.kill(3, stride=5))
        assert victim not in killed

        # Head declares all four dead (conn-drop fast path + the
        # injector victim once its next wheel tick fires the probe).
        async def alive_count():
            return sum(1 for n in head.nodes.values() if n.alive)
        _poll(lambda: io.run(alive_count()) == 12, timeout=20.0,
              desc="4 chaos-killed nodes declared dead")

        # Head keeps answering control RPCs while bodies are still warm.
        status = io.run(client.call("head_status"))
        assert status

        # No actor may wedge: every one of the 36 ends ALIVE or DEAD
        # (restarts off dead nodes included), none PENDING/RESTARTING.
        def states():
            snap = io.run(client.call("state_snapshot", parts=["actors"]))
            rows = [a for a in snap["actors"].values()]
            got = [a["state"] for a in rows]
            return got if (len(got) == n_actors
                           and all(s in ("ALIVE", "DEAD") for s in got)) \
                else None
        got = _poll(states, timeout=30.0, interval=0.1,
                    desc="all actors terminal after chaos")
        assert got.count("ALIVE") >= n_actors - 4, (
            "survivor capacity dwarfs the storm; restarts must have "
            f"rescheduled the orphans, got {got.count('ALIVE')} ALIVE")

        # Survivors keep beating at zero loss after the drill...
        stats = fleet.hb_stats()
        assert stats["sent"] == 0 or stats["loss_rate"] < 0.01

        # ...and the head still schedules NEW work (no wedge): a PG
        # created after the kills must reach CREATED.
        async def pg_round():
            r = await client.call("create_placement_group", pg_id="chaospg",
                                  bundles=[{"CPU": 1.0}] * 4,
                                  strategy="SPREAD", req_id="scale-chaos-pg")
            assert r["ok"]
            for _ in range(200):
                st = await client.call("placement_group_state",
                                       pg_id="chaospg")
                if st.get("state") == "CREATED":
                    return True
                await asyncio.sleep(0.05)
            return False
        assert io.run(pg_round()), "post-chaos PG never reached CREATED"
    finally:
        if client is not None:
            io.run(client.close())
        if fleet is not None:
            fleet.shutdown()
        _stop_head(head)
