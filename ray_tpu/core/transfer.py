"""ctypes bindings for the native transfer data plane (src/transfer/
transfer.cc): a per-node TCP server that streams object bytes directly out
of the shm arena, and a parallel-range puller that lands them directly in
the puller's arena.

Capability parity with the reference's object-manager data path (reference:
src/ray/object_manager/object_manager.h + pull_manager.h:50 — chunked,
bounded-parallel node-to-node transfer); here the entire byte path is
native, with Python only exchanging (host, port) endpoints.
"""

from __future__ import annotations

import ctypes
import time

from ray_tpu._native import load_library

_lib = None

import threading as _threading

_transfer_metrics = None
_transfer_metrics_lock = _threading.Lock()


def _get_transfer_metrics():
    global _transfer_metrics
    with _transfer_metrics_lock:
        if _transfer_metrics is not None:
            return _transfer_metrics
        from ray_tpu.util.metrics import Histogram

        _transfer_metrics = (
            Histogram("transfer_latency_s",
                      "object transfer wall time per pull",
                      tag_keys=("path",)),
            Histogram("transfer_bytes",
                      "object transfer size in bytes per pull",
                      boundaries=[1e3, 1e4, 1e5, 1e6, 1e7, 1e8, 1e9, 1e10],
                      tag_keys=("path",)),
        )
    return _transfer_metrics


def observe_transfer(path: str, nbytes: int, seconds: float) -> None:
    """Record one completed object pull. ``path`` names the data plane:
    native_pull / native_fetch here, rpc_chunk / rpc_inline from the
    runtime's fallback paths — the label that shows whether bytes are
    riding the native plane or the slow path."""
    try:
        lat, size = _get_transfer_metrics()
        tags = {"path": path}
        lat.observe(seconds, tags=tags)
        size.observe(float(nbytes), tags=tags)
    except Exception:
        pass  # metrics must never fail a transfer


def lib() -> ctypes.CDLL:
    global _lib
    if _lib is None:
        l = load_library("transfer",
                         ["transfer/transfer.cc", "objstore/objstore.cc"])
        l.transfer_server_start2.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int)]
        l.transfer_server_start2.restype = ctypes.c_void_p
        l.transfer_server_stop.argtypes = [ctypes.c_void_p]
        l.transfer_server_stop.restype = None
        l.transfer_size.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                    ctypes.c_char_p]
        l.transfer_size.restype = ctypes.c_int64
        l.transfer_pull.argtypes = [ctypes.c_char_p, ctypes.c_char_p,
                                    ctypes.c_char_p, ctypes.c_int,
                                    ctypes.c_uint64, ctypes.c_int]
        l.transfer_pull.restype = ctypes.c_int64
        l.transfer_fetch_buf.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                         ctypes.c_char_p, ctypes.c_char_p,
                                         ctypes.c_uint64, ctypes.c_uint64,
                                         ctypes.c_int]
        l.transfer_fetch_buf.restype = ctypes.c_int
        _lib = l
    return _lib


def start_server(shm_name: str, host: str = "127.0.0.1",
                 port: int = 0) -> tuple[int, int]:
    """Serve shm_name's objects; returns (handle, bound_port). Pass the
    handle to stop_server when the daemon shuts down (the server drains
    in-flight connections, then unmaps its arena view)."""
    bound = ctypes.c_int(0)
    handle = lib().transfer_server_start2(shm_name.encode(), host.encode(),
                                          port, ctypes.byref(bound))
    if not handle:
        raise OSError(f"transfer server failed to start for {shm_name}")
    return handle, bound.value


def stop_server(handle: int) -> None:
    lib().transfer_server_stop(handle)


def pull_to_store(local_shm: str, object_id: bytes, host: str,
                  port: int, *, chunk: int = 8 * 1024 * 1024,
                  conns: int = 4) -> int | None:
    """Pull object_id from (host, port) straight into the local arena.
    Returns total bytes, or None if the holder doesn't have it (caller
    falls back to the RPC chunk path)."""
    t0 = time.perf_counter()
    rc = lib().transfer_pull(local_shm.encode(), object_id, host.encode(),
                             port, chunk, conns)
    if rc == -2:
        return None  # not in the holder's arena
    if rc < 0:
        raise OSError(f"native pull failed (rc {rc})")
    observe_transfer("native_pull", int(rc), time.perf_counter() - t0)
    return int(rc)


def fetch_to_buffer(object_id: bytes, host: str, port: int,
                    *, chunk: int = 8 * 1024 * 1024,
                    conns: int = 4) -> bytes | None:
    """Pull into process memory (puller without an arena). None if the
    holder doesn't have the object in its arena."""
    l = lib()
    t0 = time.perf_counter()
    total = l.transfer_size(host.encode(), port, object_id)
    if total == -2:
        return None
    if total < 0:
        raise OSError("transfer_size failed")
    buf = ctypes.create_string_buffer(int(total))
    if l.transfer_fetch_buf(host.encode(), port, object_id, buf,
                            total, chunk, conns) != 0:
        raise OSError("native fetch failed")
    observe_transfer("native_fetch", int(total), time.perf_counter() - t0)
    return buf.raw
