"""Collective communication API.

Capability parity with the reference's ``ray.util.collective`` (reference:
python/ray/util/collective/collective.py — init_collective_group :146,
allreduce :303, barrier :343, reduce :356, broadcast :418, allgather :468,
reducescatter :517, send/recv :576/:639; GroupManager :66), with the backend
inverted for TPU: instead of NCCL rings between GPU actors, the default
backend lowers every collective to XLA ops (`lax.psum` / `all_gather` /
`ppermute` / `all_to_all`) compiled over a device mesh, riding ICI. A host
backend (gloo-equivalent, reference: torch_gloo_collective_group.py) covers
CPU actors and tests: rendezvous + reduction through a named actor, the same
shape as the reference's NCCLUniqueID exchange via a named Ray actor
(nccl_collective_group.py Rendezvous :29).
"""

from __future__ import annotations

import threading
from typing import Any

from ray_tpu.collective.host_backend import HostCollectiveGroup
from ray_tpu.collective.xla_backend import XlaCollectiveGroup


class GroupManager:
    """Per-process registry of live collective groups (reference:
    collective.py GroupManager :66)."""

    def __init__(self):
        self._groups: dict[str, Any] = {}
        self._lock = threading.Lock()

    @staticmethod
    def _key(group_name: str) -> tuple:
        # Registry is keyed per (group, rank-context): in cluster mode each
        # rank is its own process; in local mode ranks are threads sharing
        # this module, so the executing train-session or task id
        # disambiguates.
        try:
            from ray_tpu.train import session as train_session

            ctx = getattr(train_session._local, "ctx", None)
            if ctx is not None:
                return (group_name, f"train:{ctx.world_rank}:{ctx.restart_count}")
        except Exception:
            pass
        from ray_tpu.core.worker import _task_context

        tid = getattr(_task_context, "task_id", None)
        return (group_name, tid.hex() if tid else None)

    def create(self, backend: str, world_size: int, rank: int, group_name: str,
               **kwargs):
        key = self._key(group_name)
        with self._lock:
            if key in self._groups:
                raise ValueError(f"collective group {group_name!r} already exists")
            if backend in ("xla", "ici", "tpu"):
                group = XlaCollectiveGroup(group_name=group_name, **kwargs)
            elif backend in ("host", "cpu", "gloo"):
                group = HostCollectiveGroup(world_size, rank, group_name)
            else:
                raise ValueError(f"unknown collective backend {backend!r}")
            self._groups[key] = group
            return group

    def get(self, group_name: str):
        with self._lock:
            g = self._groups.get(self._key(group_name))
        if g is None:
            raise ValueError(f"no collective group {group_name!r}; call init_collective_group")
        return g

    def destroy(self, group_name: str):
        with self._lock:
            g = self._groups.pop(self._key(group_name), None)
        if g is not None and hasattr(g, "destroy"):
            g.destroy()


_manager = GroupManager()


def init_collective_group(world_size: int = 1, rank: int = 0,
                          backend: str = "xla", group_name: str = "default",
                          **kwargs):
    """Create a named group in this process. XLA groups ignore world_size/rank
    (membership is the device mesh); host groups use them for rendezvous.

    XLA groups accept multi-slice options (forwarded to
    :class:`~ray_tpu.collective.xla_backend.XlaCollectiveGroup`):
    ``num_slices=N`` lays members out on a 2-level mesh and lowers allreduce
    hierarchically (ICI reduce-scatter → DCN sum → ICI all-gather) — used
    automatically whenever the group spans slices; ``hierarchy=("ici",
    "dcn")`` names the two levels; ``dcn_quant="bf16"|"int8"`` quantizes the
    cross-slice stage (default from config ``collective_dcn_quant``)."""
    return _manager.create(backend, world_size, rank, group_name, **kwargs)


def destroy_collective_group(group_name: str = "default") -> None:
    _manager.destroy(group_name)


def get_group(group_name: str = "default"):
    return _manager.get(group_name)


# -- op surface (matches reference call signatures) ------------------------
# Every op goes through _timed(): per-op latency + payload-bytes histograms
# labeled (op, group). Latency is dispatch-to-return — for the host backend
# that is the full collective; XLA ops dispatch asynchronously, so their
# number reads as issue latency, not ICI completion (XProf owns that).

_op_metrics = None
_op_metrics_lock = threading.Lock()


def _get_op_metrics():
    global _op_metrics
    with _op_metrics_lock:
        if _op_metrics is not None:
            return _op_metrics
        from ray_tpu.util.metrics import Histogram

        _op_metrics = (
            Histogram("collective_op_latency_s",
                      "collective op wall time (dispatch to return)",
                      tag_keys=("op", "group")),
            Histogram("collective_op_bytes",
                      "collective op payload size in bytes",
                      boundaries=[1e2, 1e3, 1e4, 1e5, 1e6, 1e7, 1e8, 1e9],
                      tag_keys=("op", "group")),
        )
    return _op_metrics


def _timed(op: str, group_name: str, tensor, fn):
    import time

    t0 = time.perf_counter()
    try:
        return fn()
    finally:
        try:
            lat, size = _get_op_metrics()
            tags = {"op": op, "group": group_name}
            lat.observe(time.perf_counter() - t0, tags=tags)
            nbytes = getattr(tensor, "nbytes", None)
            if nbytes:
                size.observe(float(nbytes), tags=tags)
        except Exception:
            pass  # metrics must never fail a collective


def allreduce(tensor, group_name: str = "default", op: str = "sum"):
    return _timed("allreduce", group_name, tensor,
                  lambda: _manager.get(group_name).allreduce(tensor, op=op))


def allgather(tensor, group_name: str = "default"):
    return _timed("allgather", group_name, tensor,
                  lambda: _manager.get(group_name).allgather(tensor))


def reducescatter(tensor, group_name: str = "default", op: str = "sum"):
    return _timed(
        "reducescatter", group_name, tensor,
        lambda: _manager.get(group_name).reducescatter(tensor, op=op))


def alltoall(tensor, group_name: str = "default"):
    return _timed("alltoall", group_name, tensor,
                  lambda: _manager.get(group_name).alltoall(tensor))


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    return _timed(
        "broadcast", group_name, tensor,
        lambda: _manager.get(group_name).broadcast(tensor,
                                                   src_rank=src_rank))


def reduce(tensor, dst_rank: int = 0, group_name: str = "default", op: str = "sum"):
    return _timed(
        "reduce", group_name, tensor,
        lambda: _manager.get(group_name).reduce(tensor, dst_rank=dst_rank,
                                                op=op))


def barrier(group_name: str = "default"):
    return _timed("barrier", group_name, None,
                  lambda: _manager.get(group_name).barrier())


def send(tensor, dst_rank: int, group_name: str = "default"):
    return _timed("send", group_name, tensor,
                  lambda: _manager.get(group_name).send(tensor, dst_rank))


def recv(tensor_shape, dtype, src_rank: int, group_name: str = "default"):
    return _timed(
        "recv", group_name, None,
        lambda: _manager.get(group_name).recv(tensor_shape, dtype, src_rank))
