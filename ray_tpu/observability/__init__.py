"""Always-on health watchdog: head time-series store, streaming anomaly
detectors, anomaly-triggered evidence capture.

- :mod:`~ray_tpu.observability.timeseries` — bounded ring-buffer store on
  the head, fed by delta-encoded samples piggybacked on ``report_telemetry``;
- :mod:`~ray_tpu.observability.sampler` — reporter-side derivation of the
  hot-path series (train step/tokens/MFU, collective latency+bytes, serve
  TTFT/TPOT/queue/shed, transfer bytes, per-process RSS/HBM);
- :mod:`~ray_tpu.observability.detectors` — streaming O(1) rules with
  warmup/debounce/cooldown;
- :mod:`~ray_tpu.observability.watchdog` — the head loop that turns a trip
  into an incident (attribution + series window + flight record + targeted
  profile under guardrails);
- :mod:`~ray_tpu.observability.goodput` — the goodput ledger: every rank's
  wall clock classified into an exhaustive phase taxonomy, rolled up
  head-side into goodput % / badput breakdown in chip-seconds.
"""

from ray_tpu.observability.detectors import (  # noqa: F401
    DerivativeRule,
    Rule,
    SlopeRule,
    SpikeRule,
    ThresholdRule,
    Trip,
    build_rules,
)
from ray_tpu.observability.goodput import (  # noqa: F401
    GOOD_PHASE,
    PHASES,
    GoodputStore,
    RankLedger,
)
from ray_tpu.observability.sampler import SeriesSampler  # noqa: F401
from ray_tpu.observability.timeseries import (  # noqa: F401
    Series,
    SeriesKey,
    SeriesStore,
)
from ray_tpu.observability.watchdog import Watchdog  # noqa: F401
