"""Runtime-env plugin API.

Capability parity with the reference's plugin system (reference:
python/ray/_private/runtime_env/plugin.py RuntimeEnvPlugin — named plugins
with validate/create/modify_context hooks, discovered per field name): a
plugin owns one runtime_env field; ``setup`` runs on the worker before the
first task of that env executes and returns an undo callable (or None).
"""

from __future__ import annotations

from typing import Callable


class RuntimeEnvPlugin:
    """Subclass and register to handle a custom runtime_env field."""

    name: str = ""
    priority: int = 10  # lower runs earlier

    def validate(self, value) -> None:  # raise on bad config
        pass

    def setup(self, value, runtime) -> Callable[[], None] | None:
        """Apply the field on this worker; optionally return a teardown."""
        raise NotImplementedError


_plugins: dict[str, RuntimeEnvPlugin] = {}


def register_plugin(plugin: RuntimeEnvPlugin) -> None:
    if not plugin.name:
        raise ValueError("plugin must set a field name")
    _plugins[plugin.name] = plugin


def get_plugins() -> dict[str, RuntimeEnvPlugin]:
    return dict(_plugins)
