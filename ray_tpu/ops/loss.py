"""Fused softmax cross-entropy over a large vocabulary.

The final ``hidden @ lm_head`` projection followed by log-softmax is the
memory hog of causal-LM training: materializing fp32 logits for a [B, S, V]
batch costs B·S·V·4 bytes (2+ GB for a 1B model at B=8, S=2048, V=32k) and
that tensor is written and re-read by XLA's softmax/CE fusion. This op never
materializes the full logits:

- forward: lax.scan over sequence chunks; per chunk compute logits with a
  bfloat16 MXU matmul (f32 accumulation via preferred_element_type), reduce
  to logsumexp + target logit, discard the chunk logits.
- backward (custom_vjp): recompute each chunk's logits from the saved
  activations (cheaper than saving them — same trade remat makes), form
  dlogits = (softmax - onehot)·w and accumulate dx and dhead.

This is new work relative to the reference framework (Ray delegates model
math to torch; a TPU-native framework owns its loss kernels — the technique
is the standard chunked-vocab CE used by high-MFU JAX trainers).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax


def default_ce_chunk(default: int = 512) -> int:
    """Sequence-chunk size for :func:`fused_cross_entropy`, overridable via
    RTPU_CE_CHUNK (the train-step autotuner sets it per candidate: chunk is
    a static argument, so each value compiles a distinct scan — larger
    chunks = fewer scan steps but a bigger [B, chunk, V] logits workspace,
    the dominant transient of the loss)."""
    from ray_tpu.ops.attention import _env_int

    return _env_int("RTPU_CE_CHUNK", default)


def _chunked(x, chunk):
    """[B, S, ...] -> [S/chunk, B, chunk, ...]."""
    b, s = x.shape[0], x.shape[1]
    n = s // chunk
    rest = x.shape[2:]
    return x.reshape(b, n, chunk, *rest).swapaxes(0, 1)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def fused_cross_entropy(x, head_w, targets, mask, chunk: int = 512):
    """Mean next-token NLL without materializing [B, S, V] logits.

    x:       [B, S, H] final hidden states (any float dtype).
    head_w:  [H, V] unembedding matrix.
    targets: [B, S] int32 target ids.
    mask:    [B, S] float weights (None => all ones).
    """
    nll, _ = _fwd_impl(x, head_w, targets, mask, chunk)
    return nll


def _fwd_impl(x, head_w, targets, mask, chunk):
    b, s, h = x.shape
    chunk = min(chunk, s)
    if s % chunk != 0:  # fall back to one chunk (static shapes only)
        chunk = s
    xc = _chunked(x, chunk)                    # [N, B, C, H]
    tc = _chunked(targets, chunk)              # [N, B, C]

    def step(carry, inp):
        xb, tb = inp
        logits = jnp.einsum("bch,hv->bcv", xb, head_w,
                            preferred_element_type=jnp.float32)
        m = logits.max(axis=-1)
        lse = m + jnp.log(jnp.exp(logits - m[..., None]).sum(-1))
        tgt = jnp.take_along_axis(logits, tb[..., None], axis=-1)[..., 0]
        return carry, (lse, tgt)

    _, (lse, tgt) = lax.scan(step, 0.0, (xc, tc))
    lse = lse.swapaxes(0, 1).reshape(b, s)     # [B, S]
    tgt = tgt.swapaxes(0, 1).reshape(b, s)
    if mask is None:
        mask = jnp.ones((b, s), jnp.float32)
    mask = mask.astype(jnp.float32)
    denom = jnp.maximum(mask.sum(), 1.0)
    nll = ((lse - tgt) * mask).sum() / denom
    return nll, (lse, mask, denom)


def _fused_ce_fwd(x, head_w, targets, mask, chunk):
    nll, (lse, mask_f, denom) = _fwd_impl(x, head_w, targets, mask, chunk)
    return nll, (x, head_w, targets, lse, mask_f, denom)


def _fused_ce_bwd(chunk, res, g):
    x, head_w, targets, lse, mask_f, denom = res
    b, s, h = x.shape
    chunk = min(chunk, s)
    if s % chunk != 0:
        chunk = s
    scale = (g / denom)
    xc = _chunked(x, chunk)
    tc = _chunked(targets, chunk)
    lc = _chunked(lse, chunk)
    mc = _chunked(mask_f, chunk)

    def step(dhead, inp):
        xb, tb, lb, mb = inp
        logits = jnp.einsum("bch,hv->bcv", xb, head_w,
                            preferred_element_type=jnp.float32)
        p = jnp.exp(logits - lb[..., None])            # softmax [B, C, V]
        onehot = jax.nn.one_hot(tb, logits.shape[-1], dtype=jnp.float32)
        dlogits = (p - onehot) * (mb * scale)[..., None]
        dxb = jnp.einsum("bcv,hv->bch", dlogits.astype(head_w.dtype), head_w,
                         preferred_element_type=jnp.float32)
        dhead = dhead + jnp.einsum("bch,bcv->hv", xb,
                                   dlogits.astype(xb.dtype),
                                   preferred_element_type=jnp.float32)
        return dhead, dxb

    dhead0 = jnp.zeros(head_w.shape, jnp.float32)
    dhead, dxc = lax.scan(step, dhead0, (xc, tc, lc, mc))
    dx = dxc.swapaxes(0, 1).reshape(b, s, h).astype(x.dtype)
    return dx, dhead.astype(head_w.dtype), None, None


fused_cross_entropy.defvjp(_fused_ce_fwd, _fused_ce_bwd)
