"""Recovery bench: kill workers/slices mid-step under the chaos layer and
price every restart tier.

Three scenarios on the 2-slice / 4-worker shape, one injected failure
each (a worker process SIGKILL-dying mid-step, or a whole node daemon
dropping dead), measured on a real multi-process cluster (subprocess
workers, in-process head/daemon). The kill is armed from the driver once
every rank passed the kill step — steady state, as production failures
land — and delivered through the chaos control plane:

- ``replica``   — replication on (session.replicate every step, sparse
  backstop checkpoints) + a full warmed spare set: the fast-restart tier.
  State comes back from the buddy slice's ReplicaStore through the object
  plane; the group rebuilds by promoting the spares.
- ``checkpoint`` — the reference behavior: no replication, no spares;
  rank 0 write-behind-checkpoints every other step; the restart pays cold
  worker forks + orbax restore.
- ``elastic_shrink`` — a node daemon is chaos-killed, taking one slice's
  capacity with it; the elastic policy resumes at half world size from
  the latest checkpoint.

Per scenario the bench reports (into PERF_RECOVERY.json):

- ``detection_latency_s``  — chaos mark timestamp (written inside the dying
  process the instant before os._exit) → the controller's restart decision.
- ``ttfs_s``               — time-to-first-step-after-failure: mark → first
  completed step reported by the restarted group.
- ``steps_lost``           — steps re-executed: last step finished before
  the failure minus the resume point.
- the tier the controller actually chose (asserted per scenario), world
  before/after, spares promoted.

Acceptance: replica-tier ttfs at least 5x lower than checkpoint-tier ttfs
on the same injected failure (``speedup_fast_vs_checkpoint``).

Run: python devbench/recovery_bench.py [--quick]
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _make_train_fn():
    def train_fn(config):
        import os as _os
        import time as _time

        import numpy as np

        import orbax.checkpoint  # noqa: F401 - warm the import (seconds on
        # this box) BEFORE the step loop, as a long-lived trainer would have

        from ray_tpu.train import get_context, replicate, report
        from ray_tpu.train.checkpoint import (
            AsyncCheckpointWriter,
            restore_pytree,
        )

        ctx = get_context()
        rank = ctx.get_world_rank()
        steps = config["steps"]
        ckpt_every = config.get("ckpt_every", 0)
        start, source = 0, "fresh"
        w = np.zeros(config.get("state_elems", 4096), np.float32)
        rs = ctx.get_replica_state()
        if rs is not None:
            start, w, source = rs.step + 1, rs.state["w"], "replica"
        elif ctx.get_checkpoint():
            tree = restore_pytree(ctx.get_checkpoint())
            start = int(tree["step"]) + 1
            w = np.asarray(tree["w"], np.float32)
            source = "checkpoint"
        writer = AsyncCheckpointWriter()  # write-behind: saves don't stall
        for step in range(start, steps):
            t0 = _time.time()
            _time.sleep(config.get("step_s", 0.25))  # the "compute"
            w = w + 1.0
            replicate({"w": w, "step": step}, step)
            ck = None
            if rank == 0 and ckpt_every and step % ckpt_every == 0:
                writer.save(
                    {"w": w, "step": step},
                    _os.path.join(ctx.storage_path,
                                  f"ck_{step}_{ctx.restart_count}"),
                    step=step)
            if rank == 0:
                done = writer.completed()
                ck = done[-1] if done else None
            report({"step": step, "rank": rank,
                    "restart": ctx.restart_count, "source": source,
                    "ts": _time.time(), "step_start_ts": t0}, checkpoint=ck)
        if rank == 0:
            writer.wait()
            done = writer.completed()
            if done:
                report({"step": steps - 1, "rank": rank, "final_ck": True,
                        "restart": ctx.restart_count, "source": source,
                        "ts": _time.time()}, checkpoint=done[-1])
        return float(w.sum())

    return train_fn


def _run_scenario(name: str, *, steps: int, kill_step: int,
                  replicate_every: int, hot_spares: int, ckpt_every: int,
                  daemon_kill: bool = False, step_s: float = 0.25,
                  world: int = 4, num_slices: int = 2) -> dict:
    """One failure drill on a fresh cluster; returns the measured row."""
    import ray_tpu
    from ray_tpu.chaos import injector
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.core.worker import global_worker
    from ray_tpu.train import (
        CheckpointConfig,
        FailureConfig,
        RunConfig,
        ScalingConfig,
    )
    from ray_tpu.train.backend import JaxBackendConfig
    from ray_tpu.train.controller import TrainController
    from ray_tpu.utils import config as config_mod
    from ray_tpu.utils.ids import JobID

    marks = tempfile.mkdtemp(prefix=f"rtpu-chaos-{name}-")
    injector.reset_for_tests()
    os.environ["RTPU_HEALTH_CHECK_PERIOD_S"] = "0.5"
    config_mod.set_config(config_mod.Config.load())
    ray_tpu.shutdown()
    cluster = Cluster()
    if daemon_kill:
        # Worker placement pinned per node via a marker resource so the
        # doomed node provably hosts one slice's workers.
        cluster.add_node(num_cpus=8, resources={"trainslot": world / 2})
        doomed = cluster.add_node(num_cpus=4, resources={"trainslot": world / 2},
                                  node_id="benchdoomednode")
    else:
        cluster.add_node(num_cpus=8)
    rt = cluster.connect()
    old = (global_worker.runtime, global_worker.worker_id,
           global_worker.node_id, global_worker.mode, global_worker.job_id)
    global_worker.runtime = rt
    global_worker.worker_id = rt.worker_id
    global_worker.node_id = rt.node_id
    global_worker.job_id = JobID.from_random()
    global_worker.mode = "cluster"
    killer = None
    try:
        try:
            rt._daemon.call("prestart_workers", n=world + hot_spares +
                            (num_slices if replicate_every else 0),
                            timeout=10)
        except Exception:
            pass
        storage = tempfile.mkdtemp(prefix=f"rtpu-recovery-{name}-")

        def make_warmup():
            def warmup():
                # What a reserve slice pre-warms: the training stack (and,
                # on real hardware, the compiled step program).
                import numpy  # noqa: F401
                import orbax.checkpoint  # noqa: F401

                import ray_tpu.train  # noqa: F401
                return True

            return warmup

        scaling = ScalingConfig(num_workers=world, hot_spares=hot_spares,
                                hot_spare_warmup=make_warmup())
        if daemon_kill:
            scaling = ScalingConfig(
                num_workers=world, min_workers=world // 2, max_workers=world,
                hot_spares=hot_spares, hot_spare_warmup=make_warmup(),
                resources_per_worker={"trainslot": 1.0, "CPU": 0.5})
        ctl = TrainController(
            _make_train_fn(),
            {"steps": steps, "ckpt_every": ckpt_every, "step_s": step_s},
            scaling,
            RunConfig(name=f"recovery-{name}", storage_path=storage,
                      failure_config=FailureConfig(max_failures=2),
                      checkpoint_config=CheckpointConfig(
                          replicate_every=replicate_every)),
            JaxBackendConfig(num_slices=num_slices),
        )
        # Arm the kill from the DRIVER on observed progress: inject only
        # once EVERY rank has reported kill_step (steady state — spares
        # warmed, replication caught up), delivered through the chaos
        # control plane (head → daemons → live workers, ~ms). This is what
        # a production chaos drill does; worker-side at_step schedules stay
        # covered by tests/test_chaos.py.
        arm_info: dict = {"installs": 0}

        def arm():
            deadline = time.monotonic() + 180
            while time.monotonic() < deadline:
                ranks_at = {m["rank"] for m in list(ctl.metrics_history)
                            if m.get("step", -1) >= kill_step
                            and m.get("restart") == 0}
                if ranks_at >= set(range(world)):
                    break
                time.sleep(0.05)
            arm_info["armed_ts"] = time.time()
            if daemon_kill:
                rule = {"point": "daemon.tick", "action": "kill",
                        "match": {"node": "^benchdoomed"}, "mark": marks}
            else:
                # Kill ONE worker of slice 1 (rank world//2) mid-step.
                rule = {"point": "train.step", "action": "kill",
                        "match": {"rank": world // 2, "restart": 0},
                        "mark": marks}
            # Re-deliver until the mark proves the rule fired: on this
            # contended 1-core box the install fan can lag behind a busy
            # spare's GIL (the injector dedups repeated installs, so the
            # firing budget stays single).
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline and not os.listdir(marks):
                try:
                    rt.chaos_cluster(rules=[rule])
                    arm_info["installs"] += 1
                except Exception as e:  # noqa: BLE001 - run already over
                    arm_info["install_error"] = repr(e)
                time.sleep(0.5)

        killer = threading.Thread(target=arm)
        killer.start()
        t_run0 = time.time()
        result = ctl.run()
        wall = time.time() - t_run0
        if killer is not None:
            killer.join()
        if not result.ok:
            return {"scenario": name, "error": result.error[-2000:]}
        if not result.restarts:
            return {"scenario": name,
                    "error": "no restart observed (injection missed?)",
                    "arm_info": arm_info,
                    "marks": sorted(os.listdir(marks))}
        mark_files = sorted(os.listdir(marks))
        inject_ts = min(json.load(open(os.path.join(marks, f)))["ts"]
                        for f in mark_files) if mark_files else None
        decision = result.restarts[0] if result.restarts else {}
        before = [m for m in result.metrics_history if m["restart"] == 0]
        after = [m for m in result.metrics_history if m["restart"] == 1]
        first_after = min((m["ts"] for m in after), default=None)
        resume_step = min((m["step"] for m in after), default=None)
        last_before = max((m["step"] for m in before), default=None)
        row = {
            "scenario": name,
            "tier": decision.get("tier"),
            "trigger": decision.get("trigger"),
            "world_before": decision.get("world_before"),
            "world_after": decision.get("world_after"),
            "restore_step": decision.get("restore_step"),
            "spares_promoted": decision.get("spares_promoted"),
            "detection_latency_s": (
                round(decision["detected_ts"] - inject_ts, 3)
                if inject_ts and decision else None),
            "ttfs_s": (round(first_after - inject_ts, 3)
                       if inject_ts and first_after else None),
            "steps_lost": (last_before - resume_step + 1
                           if None not in (last_before, resume_step)
                           else None),
            "resume_step": resume_step,
            "resume_source": (after[0].get("source") if after else None),
            "run_wall_s": round(wall, 2),
        }
        return row
    finally:
        try:
            rt.shutdown()
            cluster.shutdown()
        except Exception:
            pass
        (global_worker.runtime, global_worker.worker_id,
         global_worker.node_id, global_worker.mode,
         global_worker.job_id) = old
        os.environ.pop("RTPU_CHAOS", None)
        os.environ.pop("RTPU_HEALTH_CHECK_PERIOD_S", None)
        config_mod.set_config(config_mod.Config.load())
        injector.reset_for_tests()
        shutil.rmtree(marks, ignore_errors=True)


def run_bench(quick: bool = False, out_path: str | None = None) -> dict:
    # The kill lands several seconds into the run: failures in production
    # hit steady state — spares long warmed, replication caught up — and on
    # this 1-core box the spare warmup (orbax import) needs those seconds
    # to stop competing with the train step for the core.
    steps = 10 if quick else 14
    kill_step = 5 if quick else 7
    step_s = 0.4 if quick else 0.5
    scenarios = {}
    # The replica scenario keeps sparse backstop checkpoints (the
    # production shape: checkpoint every minutes, replicate every step);
    # the checkpoint scenario's denser cadence is its best case.
    scenarios["replica"] = _run_scenario(
        "replica", steps=steps, kill_step=kill_step, step_s=step_s,
        replicate_every=1, hot_spares=4, ckpt_every=4)
    scenarios["checkpoint"] = _run_scenario(
        "checkpoint", steps=steps, kill_step=kill_step, step_s=step_s,
        replicate_every=0, hot_spares=0, ckpt_every=2)
    if not quick:
        scenarios["elastic_shrink"] = _run_scenario(
            "elastic_shrink", steps=steps, kill_step=kill_step,
            step_s=step_s, replicate_every=0, hot_spares=0, ckpt_every=2,
            daemon_kill=True)

    fast = scenarios["replica"].get("ttfs_s")
    slow = scenarios["checkpoint"].get("ttfs_s")
    speedup = round(slow / fast, 2) if fast and slow else None
    report = {
        "bench": "recovery",
        "quick": quick,
        "scenarios": scenarios,
        "speedup_fast_vs_checkpoint": speedup,
        "meets_5x": bool(speedup and speedup >= 5.0),
        "provenance": {
            "date": time.strftime("%Y-%m-%d %H:%M:%S"),
            "cpus": os.cpu_count(),
            "loadavg": list(os.getloadavg()),
            "box_note": (
                "single-host multi-process cluster on a 1-core CPU box: "
                "checkpoint-tier ttfs is dominated by cold worker "
                "fork+import (seconds each, serialized on one core) plus "
                "orbax restore; replica-tier ttfs is spare promotion + an "
                "object-plane shard fetch. On a TPU fleet the gap widens — "
                "checkpoint restore adds storage I/O and re-compile, while "
                "replica restore stays in-cluster and the hot spare holds "
                "the compiled program."),
        },
    }
    out_path = out_path or os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "PERF_RECOVERY.json")
    # Same namespacing contract as the other PERF files: a quick dryrun
    # refresh lands under "quick_refresh", never overwriting full-run
    # provenance.
    doc = report
    if quick and os.path.exists(out_path):
        try:
            with open(out_path) as f:
                existing = json.load(f)
            if not existing.get("quick"):
                existing["quick_refresh"] = report
                doc = existing
        except Exception:
            pass
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=2)
    return report


if __name__ == "__main__":
    rep = run_bench(quick="--quick" in sys.argv[1:])
    print(json.dumps(rep, indent=2))
