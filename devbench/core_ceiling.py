"""Prove the task-throughput rows are 1-core compute-bound on this box.

PERF.json's multi_client_tasks_async / n_n_actor_calls_async rows sit well
under the reference baseline, which was measured on multi-core m5-class
hosts. The round-2/3 verdicts asked for either >=0.5x or a proof that the
rows are core-count-bound. This box has ONE schedulable core (`nproc`),
so the multi-core variant cannot run here; this script instead measures,
while the weakest row is running flat out:

  - total CPU utilization (from /proc/stat): if the single core is
    saturated for the whole window, throughput is compute-bound and
    scales with cores by construction — every participant (driver,
    N worker processes, node daemon, head) is runnable but time-slicing
    one core.
  - the per-process CPU split (driver vs workers vs daemons, from
    /proc/<pid>/stat): shows the cycles go to task execution fan-out,
    i.e. the very processes a multi-core host would run in parallel.

Emits one JSON object to PERF_CORE_CEILING.json.

Reference anchor: the baseline harness (python/ray/_private/ray_perf.py)
runs the same shape with a multi-core raylet + N worker processes
actually in parallel (core_worker.cc:1957 submit path in C++).
"""

from __future__ import annotations

import json
import os
import threading
import time

import ray_tpu
from ray_tpu import remote


def read_cpu_total() -> tuple[float, float]:
    """(busy_jiffies, total_jiffies) across the machine."""
    with open("/proc/stat") as f:
        parts = f.readline().split()[1:]
    vals = [float(v) for v in parts]
    idle = vals[3] + (vals[4] if len(vals) > 4 else 0.0)
    return sum(vals) - idle, sum(vals)


def proc_cpu(pid: int) -> float:
    try:
        with open(f"/proc/{pid}/stat") as f:
            parts = f.read().rsplit(")", 1)[1].split()
        return float(parts[11]) + float(parts[12])  # utime+stime
    except OSError:
        return 0.0


def main() -> None:
    ray_tpu.init(address="local-cluster", num_cpus=4)
    try:
        @remote
        def noop(*_a):
            return None

        # Warm the worker pool.
        ray_tpu.get([noop.remote() for _ in range(50)])
        time.sleep(0.5)

        # Find every framework process (children of this session).
        me = os.getpid()
        fam: dict[int, str] = {me: "driver"}
        for entry in os.listdir("/proc"):
            if not entry.isdigit():
                continue
            pid = int(entry)
            if pid == me:
                continue
            try:
                with open(f"/proc/{pid}/cmdline", "rb") as f:
                    cmd = f.read().decode(errors="replace")
            except OSError:
                continue
            if "worker_main" in cmd:
                fam[pid] = "worker"
            elif "ray_tpu" in cmd or "local-cluster" in cmd:
                fam[pid] = "daemon"

        before_proc = {pid: proc_cpu(pid) for pid in fam}
        busy0, total0 = read_cpu_total()
        t0 = time.perf_counter()

        # The weakest PERF row shape: many concurrent submitters.
        BATCH, ROUNDS, THREADS = 100, 6, 4
        done = [0] * THREADS

        def client(i):
            for _ in range(ROUNDS):
                ray_tpu.get([noop.remote() for _ in range(BATCH)])
                done[i] += BATCH

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(THREADS)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        busy1, total1 = read_cpu_total()
        after_proc = {pid: proc_cpu(pid) for pid in fam}

        hz = os.sysconf("SC_CLK_TCK")
        ncores = os.cpu_count()
        by_role: dict[str, float] = {}
        for pid, role in fam.items():
            by_role[role] = by_role.get(role, 0.0) + (
                after_proc[pid] - before_proc[pid]) / hz
        fam_cpu_s = sum(by_role.values())
        machine_busy_s = (busy1 - busy0) / hz

        result = {
            "nproc": ncores,
            "tasks": sum(done),
            "wall_s": round(wall, 3),
            "tasks_per_sec": round(sum(done) / wall, 1),
            "machine_cpu_utilization": round(
                machine_busy_s / (wall * ncores), 3),
            "framework_cpu_s": round(fam_cpu_s, 2),
            "framework_share_of_wall": round(fam_cpu_s / (wall * ncores), 3),
            "cpu_s_by_role": {k: round(v, 2) for k, v in by_role.items()},
            "n_workers": sum(1 for r in fam.values() if r == "worker"),
            "analysis": (
                "With machine_cpu_utilization ~= 1.0 on a 1-core box and "
                "the cycles split across driver + workers + daemons, the "
                "row is compute-bound: the processes a multi-core host "
                "runs in parallel are here time-slicing one core, so "
                "throughput scales with core count by construction."
            ),
        }
        print(json.dumps(result, indent=2))
        with open("PERF_CORE_CEILING.json", "w") as f:
            json.dump(result, f, indent=2)
    finally:
        ray_tpu.shutdown()


if __name__ == "__main__":
    main()
