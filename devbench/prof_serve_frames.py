"""Timestamp every SSE frame of a few concurrent requests through the full
serve stack, to localize where the TPU serve path loses time
(bench_serve ~41 tok/s vs engine-direct ~130 tok/s).

PYTHONPATH=. python devbench/prof_serve_frames.py
"""
import json
import threading
import time
import urllib.request

import ray_tpu
from ray_tpu import serve
from ray_tpu.llm import LLMConfig
from ray_tpu.llm.serving import build_openai_app

import os as _os
if _os.environ.get("RTPU_PROF_TINY") == "1":
    cfg = LLMConfig(model="tiny", max_num_seqs=8, max_seq_len=256)
else:
    cfg = LLMConfig(model="llama3_1b", max_num_seqs=8, max_seq_len=1024,
                    dtype="bfloat16")
url = None
import sys as _sys
if "sustained" not in _sys.argv:
    ray_tpu.init()
    serve.run(build_openai_app(cfg), route_prefix="/", http=True)
    url = f"http://127.0.0.1:{serve.http_port()}/v1/chat/completions"


def req(i, frames, max_tokens=24):
    body = json.dumps({
        "messages": [{"role": "user", "content": f"benchmark prompt {i} " * 4}],
        "max_tokens": max_tokens, "temperature": 0.0, "stream": True,
    }).encode()
    r = urllib.request.Request(url, data=body,
                               headers={"Content-Type": "application/json"})
    t0 = time.perf_counter()
    buf = b""
    with urllib.request.urlopen(r, timeout=300) as resp:
        while True:
            chunk = resp.read1(8192)
            if not chunk:
                break
            buf += chunk
            fs = buf.split(b"\n\n")
            buf = fs.pop()
            now = time.perf_counter() - t0
            for f in fs:
                if f.startswith(b"data:") and b'"content"' in f:
                    frames.append(now)


_MAIN = "sustained" not in _sys.argv

# warm
def _light_probe():
    w = []
    req(990, w, max_tokens=15)
    print(f"warm: {len(w)} frames, last at {w[-1]:.2f}s")

    f1 = []
    req(1, f1)
    gaps = [f1[i] - f1[i - 1] for i in range(1, len(f1))]
    print(f"single: ttft {f1[0]*1e3:.0f} ms, {len(f1)} frames, "
          f"gaps ms: {[round(g*1e3) for g in gaps]}")

    all_frames = [[] for _ in range(4)]
    ts = [threading.Thread(target=req, args=(10 + i, all_frames[i]))
          for i in range(4)]
    t0 = time.perf_counter()
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    wall = time.perf_counter() - t0
    tot = sum(len(f) for f in all_frames)
    print(f"4-conc: {tot} tokens in {wall:.1f}s = {tot/wall:.0f} tok/s")
    for i, f in enumerate(all_frames):
        gaps = [round((f[j] - f[j-1]) * 1e3) for j in range(1, len(f))]
        print(f"  r{i}: ttft {f[0]*1e3:.0f} ms gaps {gaps}")

    serve.shutdown()
    ray_tpu.shutdown()


def sustained(n=40, conc=8, max_tokens=32, prefix_warm=False):
    import numpy as np
    ray_tpu.init()
    serve.run(build_openai_app(cfg), route_prefix="/", http=True)
    u = f"http://127.0.0.1:{serve.http_port()}/v1/chat/completions"
    globals()["url"] = u
    w = []
    req(991, w, max_tokens=15)
    if prefix_warm:  # replicate bench_serve's long-prefix warm requests
        shared = "Xou are a careful assistant. " * 40
        body = json.dumps({"messages": [
            {"role": "user", "content": shared + "question 980"}],
            "max_tokens": 8, "temperature": 0.0, "stream": True}).encode()
        for _ in range(2):
            rq = urllib.request.Request(
                u, data=body, headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(rq, timeout=300) as resp:
                while resp.read1(8192):
                    pass
        print("prefix warm done")
    sem = threading.Semaphore(conc)
    out = []
    lock = threading.Lock()

    def worker(i):
        with sem:
            frames = []
            t0 = time.perf_counter()
            try:
                req(i, frames, max_tokens=max_tokens)
            except Exception as e:
                print("fail", i, e)
                return
            with lock:
                out.append((frames, time.perf_counter() - t0))

    ts = [threading.Thread(target=worker, args=(i,)) for i in range(n)]
    t0 = time.perf_counter()
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    wall = time.perf_counter() - t0
    tot = sum(len(f) for f, _ in out)
    # degradation curve: completion order TTFT, first vs last quartile
    qt = max(1, len(out) // 4)
    early = [f[0] for f, _ in out[:qt] if f]
    late = [f[0] for f, _ in out[-qt:] if f]
    print(f"ttft first-quartile mean {sum(early)/len(early)*1e3:.0f} ms, "
          f"last-quartile mean {sum(late)/len(late)*1e3:.0f} ms")
    ttfts = sorted(f[0] for f, _ in out if f)
    print(f"sustained: {tot} tokens / {wall:.1f}s = {tot/wall:.0f} tok/s, "
          f"ttft p50 {ttfts[len(ttfts)//2]*1e3:.0f} ms "
          f"p90 {ttfts[int(len(ttfts)*0.9)]*1e3:.0f} ms")
    # biggest inter-frame gaps across all requests
    gaps = []
    for f, _ in out:
        gaps += [f[i] - f[i-1] for i in range(1, len(f))]
    gaps.sort()
    print(f"frame gaps ms: p50 {gaps[len(gaps)//2]*1e3:.0f} "
          f"p90 {gaps[int(len(gaps)*.9)]*1e3:.0f} "
          f"p99 {gaps[int(len(gaps)*.99)]*1e3:.0f} max {gaps[-1]*1e3:.0f}")
    serve.shutdown()
    ray_tpu.shutdown()


if __name__ == "__main__":
    if "sustained" in _sys.argv:
        n = 100 if "n100" in _sys.argv else 40
        sustained(n=n, prefix_warm="prefixwarm" in _sys.argv)
    else:
        _light_probe()
