"""Dashboard web client assets.

Capability parity with the reference's dashboard client
(reference: python/ray/dashboard/client/ — a React SPA over the dashboard's
JSON API): here a hand-written single-page app with zero build toolchain —
``static/index.html`` + ``static/app.js`` + ``static/app.css`` — serving
live nodes/actors/tasks/placement-group/job tables with auto-refresh, a
per-node log viewer, and overview stat tiles with sparklines. The server
(http_server.py) serves these files and the same /api endpoints the
reference client consumes.
"""

from __future__ import annotations

import os

_STATIC_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "static")

_CONTENT_TYPES = {
    ".html": "text/html; charset=utf-8",
    ".js": "text/javascript; charset=utf-8",
    ".css": "text/css; charset=utf-8",
}


def static_asset(name: str) -> tuple[str, str]:
    """(body, content_type) for a bundled client asset."""
    base = os.path.basename(name)  # no traversal
    path = os.path.join(_STATIC_DIR, base)
    with open(path, encoding="utf-8") as f:
        body = f.read()
    ext = os.path.splitext(base)[1]
    return body, _CONTENT_TYPES.get(ext, "application/octet-stream")


def index_html() -> str:
    return static_asset("index.html")[0]


# Back-compat alias (older callers imported the template constant).
def __getattr__(name: str):
    if name == "INDEX_HTML":
        return index_html()
    raise AttributeError(name)
