"""Compiled DAG execution: static per-actor schedules over channels.

Capability parity with the reference's Compiled Graphs (reference:
python/ray/dag/compiled_dag_node.py:805 CompiledDAG — _get_or_compile :1550
allocates channels between actors; _build_execution_schedule :2002 emits a
static per-actor op list (READ → COMPUTE → WRITE per node,
dag_node_operation.py:14-24) run in a loop on each actor, replacing per-call
RPC with channel reads/writes).

Compilation here: walk the graph, allocate one channel per produced value
(readers = consuming actors and/or the driver), install a loop in every
participating actor via the ``__rtpu_call_fn__`` hook, and drive executions by
writing the input channel and reading the terminal channels. Teardown closes
the input channel; ChannelClosed unwinds every actor loop.
"""

from __future__ import annotations

from typing import Any

from ray_tpu.dag.channel import ChannelClosed, LocalChannel, StoreChannel
from ray_tpu.dag.dag_node import (
    ClassMethodNode,
    DAGNode,
    InputNode,
    MultiOutputNode,
)

_DRIVER = "__driver__"


def _overlap_plan(ops: list[dict]) -> list[tuple[int, int]]:
    """The overlapped-execution schedule pass (reference:
    compiled_dag_node.py:2042 _generate_overlapped_execution_schedule —
    reorders communication ops ahead of compute so transfers run while
    earlier ops compute).

    Returns the channel reads (op_index, arg_position) that are SAFE to
    post at schedule start: those with NO intra-schedule producer (an
    earlier op of THIS actor writing the same channel). Dependent reads
    stay inline in the loop — posting them to a bounded transfer pool
    could starve a read the loop's own progress needs (FIFO worker
    assignment deadlock), while start-posted reads only wait on OTHER
    actors, whose progress this actor's compute never gates through the
    transfer pool."""
    start_posts: list[tuple[int, int]] = []
    for i, op in enumerate(ops):
        for pos, (kind, chan, _idx) in enumerate(op["reads"]):
            if kind != "chan":
                continue
            if not any(ops[k]["write"] is chan for k in range(i)):
                start_posts.append((i, pos))
    return start_posts


def _actor_loop(instance, ops: list[dict], error_channel,
                overlap: bool = False):
    """Installed into each participating actor: runs its static schedule
    until the upstream channels close (reference: the per-actor loop a
    compiled DAG executes instead of per-call RPC). With ``overlap``, the
    _overlap_plan pass posts channel reads early on a transfer thread so
    inbound byte movement runs concurrently with compute."""
    from ray_tpu.core.worker import global_worker

    rt = global_worker.runtime
    for op in ops:
        for kind, chan, _ in op["reads"]:
            if kind == "chan":
                chan.connect(rt)
        if op["write"] is not None:
            op["write"].connect(rt)
    error_channel.connect(rt)

    posts = _overlap_plan(ops) if overlap else None
    executor = None
    if overlap:
        from concurrent.futures import ThreadPoolExecutor

        # One worker per posted read: every posted read gets a thread, so
        # no read the loop waits on can be starved behind another blocked
        # read (posted reads block only on OTHER actors' progress).
        executor = ThreadPoolExecutor(max_workers=max(1, len(posts)),
                                      thread_name_prefix="dag-xfer")

    def cascade_close():
        # This loop is the writer of its output channels: closing them here
        # (with this process's write cursor) unwinds downstream loops in turn.
        for op in ops:
            if op["write"] is not None:
                try:
                    op["write"].close()
                except BaseException:
                    pass
        if executor is not None:
            executor.shutdown(wait=False)

    futs: dict[tuple[int, int], Any] = {}

    def post_all() -> None:
        for (i, pos) in posts:
            kind, chan, reader_idx = ops[i]["reads"][pos]
            futs[(i, pos)] = executor.submit(chan.read, reader_idx)

    while True:
        try:
            if overlap:
                post_all()
            for i, op in enumerate(ops):
                args = []
                for pos, (kind, chan_or_val, reader_idx) in \
                        enumerate(op["reads"]):
                    if kind != "chan":
                        args.append(chan_or_val)
                    elif overlap and (i, pos) in futs:
                        args.append(futs.pop((i, pos)).result())
                    else:
                        args.append(chan_or_val.read(reader_idx))
                kwargs = {k: v for k, v in op["const_kwargs"].items()}
                result = getattr(instance, op["method"])(*args, **kwargs)
                if op["write"] is not None:
                    op["write"].write(result)
        except ChannelClosed:
            cascade_close()
            return "closed"
        except BaseException as e:  # noqa: BLE001
            # Surface the failure to the driver, then stop this loop — the
            # schedule is static; a failed step poisons the whole execution.
            try:
                error_channel.write(("error", repr(e)))
            except BaseException:
                pass
            cascade_close()
            return f"error: {e!r}"


class CompiledDAG:
    def __init__(self, root: DAGNode, *, _overlap_execution: bool = False,
                 _device_channels: bool = False):
        """``_overlap_execution`` turns on the overlapped schedule pass
        (reference: compiled_dag_node.py:2042) — channel reads post early
        on a transfer thread so inbound bytes move while earlier ops
        compute. ``_device_channels`` wraps every channel in DeviceChannel
        so jax arrays land on the reader's device (reference: the
        accelerator channel registered via accelerator_context.py:222)."""
        import ray_tpu
        from ray_tpu.core.worker import global_worker

        import uuid

        ray_tpu.init(ignore_reinit_error=True)
        self._root = root
        self._rt = global_worker.runtime
        self._local = global_worker.mode == "local"
        self._overlap = _overlap_execution
        self._device_channels = _device_channels
        self._torn_down = False
        self._dag_id = uuid.uuid4().hex[:12]  # globally unique channel prefix
        self._compile()

    # ------------------------------------------------------------------ compile
    def _make_channel(self, name: str, num_readers: int):
        chan = (LocalChannel(name, num_readers) if self._local
                else StoreChannel(name, num_readers))
        if self._device_channels:
            from ray_tpu.dag.communicator import (
                get_accelerator_communicator,
            )

            chan = get_accelerator_communicator("jax_device").wrap_channel(
                chan)
        return chan

    def _compile(self):
        nodes = self._root.walk()
        self._input_node = next(
            (n for n in nodes if isinstance(n, InputNode)), None)
        if self._input_node is None:
            raise ValueError(
                "compiled DAGs require an InputNode (teardown propagates by "
                "closing the input channel)")
        terminal = self._root

        if isinstance(terminal, InputNode):
            raise ValueError("DAG must contain at least one actor-method node")

        # Pass A: count read sites per producer. Every consuming arg-use gets
        # its OWN reader slot — one actor reading a value in two ops is two
        # readers (each slot queues/deletes independently; sharing a slot
        # would lose one of the reads).
        reader_counts: dict[int, int] = {}

        def count_edges(node: DAGNode):
            if isinstance(node, ClassMethodNode):
                for arg in node.args:
                    if isinstance(arg, DAGNode):
                        reader_counts[arg.node_id] = (
                            reader_counts.get(arg.node_id, 0) + 1)
            elif isinstance(node, MultiOutputNode):
                for up in node.outputs:
                    reader_counts[up.node_id] = (
                        reader_counts.get(up.node_id, 0) + 1)

        for node in nodes:
            count_edges(node)
        if isinstance(terminal, ClassMethodNode):
            reader_counts[terminal.node_id] = (
                reader_counts.get(terminal.node_id, 0) + 1)

        self._channels: dict[int, Any] = {}
        for node in nodes:
            n = reader_counts.get(node.node_id, 0)
            if n:
                self._channels[node.node_id] = self._make_channel(
                    f"dag{self._dag_id}/n{node.node_id}", n)

        # Pass B: build schedules, assigning reader indices in the SAME node
        # order as pass A so every read site gets a unique slot.
        next_reader: dict[int, int] = {}

        def claim(producer_id: int) -> int:
            idx = next_reader.get(producer_id, 0)
            next_reader[producer_id] = idx + 1
            return idx

        schedules: dict[str, list[dict]] = {}
        self._handles: dict[str, Any] = {}
        self._output_plan = []
        self._multi_output = isinstance(terminal, MultiOutputNode)
        for node in nodes:
            if isinstance(node, ClassMethodNode):
                key = node.handle.actor_id.hex()
                self._handles[key] = node.handle
                reads = []
                for arg in node.args:
                    if isinstance(arg, DAGNode):
                        reads.append(("chan", self._channels[arg.node_id],
                                      claim(arg.node_id)))
                    else:
                        reads.append(("const", arg, -1))
                const_kwargs = {}
                for k, v in node.kwargs.items():
                    if isinstance(v, DAGNode):
                        raise ValueError(
                            "DAG deps must be positional args in compiled graphs")
                    const_kwargs[k] = v
                schedules.setdefault(key, []).append({
                    "node_id": node.node_id,
                    "method": node.method_name,
                    "reads": reads,
                    "const_kwargs": const_kwargs,
                    "write": self._channels.get(node.node_id),
                })
            elif isinstance(node, MultiOutputNode):
                for up in node.outputs:
                    self._output_plan.append(
                        (self._channels[up.node_id], claim(up.node_id)))
        if isinstance(terminal, ClassMethodNode):
            self._output_plan.append(
                (self._channels[terminal.node_id], claim(terminal.node_id)))

        # One error channel per actor: channels are single-writer, and a
        # shared one would interleave writers' sequence numbers.
        self._error_channels = {
            key: self._make_channel(f"dag{self._dag_id}/err/{key}", 1)
            for key in schedules
        }

        # Install the loops.
        self._loop_refs = []
        for key, ops in schedules.items():
            handle = self._handles[key]
            self._loop_refs.append(
                handle._call_fn(_actor_loop, ops, self._error_channels[key],
                                self._overlap))
        for chan in self._error_channels.values():
            chan.connect(self._rt)

        # Driver connects its ends.
        self._in_chan = self._channels[self._input_node.node_id].connect(self._rt)
        for chan, _ in self._output_plan:
            chan.connect(self._rt)

    # ------------------------------------------------------------------ execute
    def execute(self, *input_values, timeout: float | None = 60.0):
        """One synchronous execution through the compiled pipeline."""
        if self._torn_down:
            raise RuntimeError("compiled DAG has been torn down")
        value = input_values[0] if len(input_values) == 1 else input_values
        self._in_chan.write(value)
        outs = []
        for chan, reader_idx in self._output_plan:
            try:
                outs.append(chan.read(reader_idx, timeout=timeout))
            except (TimeoutError, ChannelClosed):
                # A failed step closes its channels after reporting; surface
                # the actor's error rather than the secondary symptom.
                err = self._poll_error(timeout=0.5)
                if err is not None:
                    raise RuntimeError(
                        f"compiled DAG execution failed: {err}") from None
                raise
        return outs if self._multi_output else outs[0]

    def _poll_error(self, timeout: float = 0.001):
        for chan in self._error_channels.values():
            try:
                kind, msg = chan.read(0, timeout=timeout)
                if kind == "error":
                    return msg
            except Exception:
                continue
        return None

    # ------------------------------------------------------------------ teardown
    def teardown(self):
        """Close the input channel; each actor loop cascades the close to its
        own output channels and exits."""
        if self._torn_down:
            return
        self._torn_down = True
        try:
            self._in_chan.close()
        except Exception:
            pass
        # The loop results confirm shutdown (and surface loop errors in tests).
        import ray_tpu

        try:
            ray_tpu.wait(self._loop_refs, num_returns=len(self._loop_refs),
                         timeout=10.0)
        except Exception:
            pass
        # Reclaim channel resources (registry entries locally; KV slots and
        # cursors in cluster mode) now that every loop has exited.
        for chan in list(self._channels.values()) + list(
                self._error_channels.values()):
            try:
                chan.connect(self._rt).destroy()
            except Exception:
                pass

    def __del__(self):
        try:
            self.teardown()
        except Exception:
            pass
