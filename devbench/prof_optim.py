"""Slope-time the optimizer update alone at the bench model geometry.

Isolates the ~50 ms/step "optimizer_ms" residual from PERF_STEP.json:
is it HBM-bandwidth (expected ~18 ms for bf16 moments at 819 GB/s) or
fusion/launch overhead? Usage:
  PYTHONPATH=. python devbench/prof_optim.py [variant ...]
variants: compact (bench default), adamw (stock optax), fused (pallas).
"""
import functools
import sys
import time

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.models.llama import LlamaConfig, init_params
from ray_tpu.train.optim import adamw_lowmem

L1, L2 = 3, 10


def timed_slope(step, state0, reps=5):
    """Donating slope timer: each call consumes the previous state (no
    input copies — the 1B state + moments barely fit HBM twice)."""
    def run_for(n):
        @functools.partial(jax.jit, donate_argnums=0)
        def run(s):
            def body(s, _):
                return step(s), None
            s, _ = lax.scan(body, s, None, length=n)
            # Scalar probe: fetching it host-side is what actually waits for
            # the computation on the axon tunnel (block_until_ready on the
            # remote arrays returns early).
            probe = jax.tree_util.tree_reduce(
                lambda a, x: a + x.ravel()[0].astype(jnp.float32), s, 0.0)
            return s, probe
        return run

    def call(r, s):
        s, probe = r(s)
        float(probe)
        return s

    r1, r2 = run_for(L1), run_for(L2)
    s = call(r1, state0)
    s = call(r2, s)
    slopes = []
    for _ in range(reps):
        t0 = time.perf_counter()
        s = call(r1, s)
        t1 = time.perf_counter() - t0
        t0 = time.perf_counter()
        s = call(r2, s)
        t2 = time.perf_counter() - t0
        slopes.append((t2 - t1) / (L2 - L1))
    slopes.sort()
    return slopes[len(slopes) // 2]


cfg = LlamaConfig.llama3_1b()
params = jax.jit(lambda k: init_params(cfg, k))(jax.random.PRNGKey(0))
nbytes = sum(x.nbytes for x in jax.tree.leaves(params))
nparams = sum(x.size for x in jax.tree.leaves(params))
print(f"params: {nparams/1e9:.2f}B, {nbytes/1e9:.2f} GB, "
      f"{len(jax.tree.leaves(params))} tensors")
# Masters live on HOST — each bench() materializes a fresh device copy and
# the device state is donated away; keeping device masters alive would not
# leave room for params + moments + grads twice in 15.75 GB HBM.
params = jax.tree.map(lambda x: jax.device_get(x), params)
grads = jax.tree.map(lambda p: (p * 1e-3).astype(p.dtype), params)

import optax


def bench(name, opt):
    # Fresh device copies — timed_slope donates (consumes) its state.
    p0 = jax.device_put(params)
    g0 = jax.device_put(grads)
    opt_state = jax.jit(opt.init)(p0)
    mom_bytes = sum(x.nbytes for x in jax.tree.leaves(opt_state))
    state0 = (p0, opt_state, g0)

    def step(s):
        p, os_, g = s
        updates, os2 = opt.update(g, os_, p)
        p2 = optax.apply_updates(p, updates)
        return (p2, os2, g)

    t = timed_slope(step, state0)
    # traffic: read g + read/write p + read/write moments
    traffic = nbytes * 2 + nbytes + mom_bytes * 2
    print(f"{name:12s} {t*1e3:7.2f} ms  opt_state {mom_bytes/1e9:.2f} GB  "
          f"~{traffic/1e9:.1f} GB traffic -> {traffic/t/1e9:.0f} GB/s eff",
          flush=True)


WHICH = set(sys.argv[1:]) or {"compact", "adamw"}
if "compact" in WHICH:
    bench("compact", adamw_lowmem(3e-4, weight_decay=0.1))
if "adamw" in WHICH:
    bench("stock adamw", optax.adamw(3e-4, weight_decay=0.1,
                                     mu_dtype=jnp.bfloat16))
if "fused" in WHICH:
    from ray_tpu.train.optim import adamw_fused
    bench("fused", adamw_fused(3e-4, weight_decay=0.1))
