"""Collective communication: XLA/ICI backend (default) + host fallback.

Reference capability: python/ray/util/collective (NCCL/gloo backends).
"""

from ray_tpu.collective.collective import (
    allgather,
    allreduce,
    alltoall,
    barrier,
    broadcast,
    destroy_collective_group,
    get_group,
    init_collective_group,
    recv,
    reduce,
    reducescatter,
    send,
)
from ray_tpu.collective.host_backend import HostCollectiveGroup
from ray_tpu.collective.xla_backend import XlaCollectiveGroup

__all__ = [
    "init_collective_group", "destroy_collective_group", "get_group",
    "allreduce", "allgather", "reducescatter", "alltoall", "broadcast",
    "reduce", "barrier", "send", "recv",
    "XlaCollectiveGroup", "HostCollectiveGroup",
]
