"""Tokenizers for the LLM engine.

The reference delegates tokenization to HF/vLLM (reference:
ray.llm._internal.batch stages — chat-template → tokenize →  engine →
detokenize). Here: a dependency-free byte-level tokenizer for tests/dev and
an optional HF loader when a local tokenizer path is provided (no network
egress in this environment).
"""

from __future__ import annotations


class ByteTokenizer:
    """Byte-level: ids 0..255 are bytes; specials above."""

    def __init__(self, vocab_size: int = 512):
        assert vocab_size >= 259
        self.vocab_size = vocab_size
        self.bos_id = 256
        self.eos_id = 257
        self.pad_id = 258

    def encode(self, text: str, add_bos: bool = True) -> list[int]:
        ids = list(text.encode("utf-8"))
        return ([self.bos_id] + ids) if add_bos else ids

    def decode(self, ids: list[int]) -> str:
        return bytes(i for i in ids if i < 256).decode("utf-8",
                                                       errors="replace")

    def apply_chat_template(self, messages: list[dict]) -> str:
        parts = []
        for m in messages:
            parts.append(f"<|{m['role']}|>\n{m['content']}\n")
        parts.append("<|assistant|>\n")
        return "".join(parts)


class HFTokenizer:
    """Wraps a locally available HF tokenizer directory."""

    def __init__(self, path: str):
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(path)
        self.vocab_size = self._tok.vocab_size
        self.bos_id = self._tok.bos_token_id
        self.eos_id = self._tok.eos_token_id
        self.pad_id = self._tok.pad_token_id or self.eos_id

    def encode(self, text: str, add_bos: bool = True) -> list[int]:
        return self._tok.encode(text, add_special_tokens=add_bos)

    def decode(self, ids: list[int]) -> str:
        return self._tok.decode(ids, skip_special_tokens=True)

    def apply_chat_template(self, messages: list[dict]) -> str:
        try:
            return self._tok.apply_chat_template(messages, tokenize=False,
                                                 add_generation_prompt=True)
        except Exception:
            return ByteTokenizer.apply_chat_template(self, messages)  # type: ignore[arg-type]


def get_tokenizer(spec: str):
    if spec == "byte":
        return ByteTokenizer()
    return HFTokenizer(spec)
