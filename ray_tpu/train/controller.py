"""Train controller: the off-driver control loop.

Capability parity with the reference's TrainController (reference:
python/ray/train/v2/_internal/execution/controller/controller.py:105 — async
control loop `run` :634, one iteration :612: poll worker group → scaling
decision → failure decision; FailurePolicy restart-from-latest-checkpoint;
runs as an actor so driver death doesn't kill training).
"""

from __future__ import annotations

import time
import traceback
from dataclasses import dataclass, field
from typing import Any, Callable

from ray_tpu.train.backend import JaxBackendConfig, free_port
from ray_tpu.train.checkpoint import CheckpointManager
from ray_tpu.train.config import RunConfig, ScalingConfig
from ray_tpu.train.worker_group import WorkerGroup


import threading as _threading

_metrics = None
_metrics_lock = _threading.Lock()


def _controller_metrics():
    """Process-wide singletons: a fresh controller must extend these
    counters, not re-register and zero them (lock-guarded so concurrent
    controller constructions can't register duplicates)."""
    global _metrics
    with _metrics_lock:
        if _metrics is not None:
            return _metrics
        from ray_tpu.util.metrics import Counter, Gauge

        _metrics = {
            "restarts": Counter(
                "train_restarts_total",
                "worker-group restarts after failures", tag_keys=("run",)),
            "failures": Counter(
                "train_worker_failures_total",
                "train workers that reported an error", tag_keys=("run",)),
            "world": Gauge(
                "train_world_size", "current worker-group world size",
                tag_keys=("run",)),
        }
    return _metrics


@dataclass
class Result:
    metrics: dict[str, Any] = field(default_factory=dict)
    checkpoint: Any = None
    error: str | None = None
    metrics_history: list[dict] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return self.error is None


class TrainController:
    """Runs as an actor (created by the Trainer); drives the worker group."""

    def __init__(self, train_fn: Callable, train_loop_config: dict | None,
                 scaling_config: ScalingConfig, run_config: RunConfig,
                 backend_config: JaxBackendConfig | None = None,
                 datasets: dict | None = None):
        self.train_fn = train_fn
        self.train_loop_config = train_loop_config
        self.datasets = datasets or {}
        self.scaling = scaling_config
        self.run_config = run_config
        self.backend_config = backend_config or JaxBackendConfig()
        storage = run_config.storage_path or "/tmp/ray_tpu/train"
        name = run_config.name or f"train-{int(time.time())}"
        self.ckpt_manager = CheckpointManager(
            f"{storage}/{name}",
            num_to_keep=run_config.checkpoint_config.num_to_keep,
        )
        self.metrics_history: list[dict] = []
        self._status = "PENDING"
        self._callbacks = list(run_config.callbacks)
        self._run_name = name
        self._rank0_reports = 0  # callback iteration counter (rank-0 only)
        # Controller-side run health (the worker-side throughput gauges live
        # in train/session.py): restarts and failures as counters, the live
        # world size as a gauge — the first things to look at when a run's
        # tokens/sec sags.
        m = _controller_metrics()
        self._m_restarts = m["restarts"]
        self._m_failures = m["failures"]
        self._m_world = m["world"]

    def _cb(self, hook: str, *args) -> None:
        for cb in self._callbacks:
            try:
                getattr(cb, hook)(*args)
            except Exception:  # noqa: BLE001 - a tracker must not kill a run
                traceback.print_exc()

    def status(self) -> str:
        return self._status

    def run(self) -> Result:
        """The control loop (reference: controller.py:634). Each (re)start
        consults the scaling policy — elastic configs resume at a smaller
        world size after capacity loss (reference: elastic.py:29)."""
        from ray_tpu.train.scaling_policy import make_scaling_policy

        self._status = "RUNNING"
        self._cb("on_run_start", self._run_name, self.train_loop_config)
        max_failures = self.run_config.failure_config.max_failures
        policy = make_scaling_policy(self.scaling,
                                     getattr(self, "_resources_fn", None))
        restart_count = 0
        while True:
            group = None
            try:
                world = policy.decide_world_size(restart_count)
                self._m_world.set(world, tags={"run": self._run_name})
                group = WorkerGroup(
                    self.scaling, self.run_config.name or "train",
                    self.ckpt_manager.storage_path, num_workers=world,
                )
                coordinator = f"127.0.0.1:{free_port()}" \
                    if self.backend_config.distributed else None
                latest = self.ckpt_manager.latest()
                group.setup(coordinator, restart_count,
                            latest.path if latest else None,
                            num_slices=getattr(self.backend_config,
                                               "num_slices", 1))
                self.backend_config.make_backend().on_start(group, coordinator)
                if self.datasets:
                    # Split per (re)start so elastic world-size changes get
                    # fresh equal splits (reference: datasets= are
                    # streaming_split across the current worker group).
                    splits = {name: ds.streaming_split(world, equal=True)
                              for name, ds in self.datasets.items()}
                    group.assign_dataset_shards([
                        {name: its[rank] for name, its in splits.items()}
                        for rank in range(world)])
                group.run(self.train_fn, self.train_loop_config)
                result = self._poll_until_done(group)
                self._status = "FINISHED" if result.ok else "ERRORED"
                self._cb("on_run_end", result)
                return result
            except Exception:  # noqa: BLE001 - worker/actor failures
                restart_count += 1
                self._m_restarts.inc(tags={"run": self._run_name})
                if max_failures >= 0 and restart_count > max_failures:
                    self._status = "ERRORED"
                    result = Result(error=traceback.format_exc(),
                                    checkpoint=self.ckpt_manager.latest(),
                                    metrics_history=self.metrics_history)
                    self._cb("on_run_end", result)
                    return result
                # else: loop → new worker group restored from latest checkpoint
            finally:
                if group is not None:
                    group.shutdown()

    def _poll_until_done(self, group: WorkerGroup) -> Result:
        max_failures = self.run_config.failure_config.max_failures
        failures_left = float("inf") if max_failures < 0 else max_failures
        while True:
            status = group.poll_status(timeout=60)
            for rep in status.reports:
                self.metrics_history.append(rep["metrics"])
                if rep.get("rank", 0) == 0:
                    self._rank0_reports += 1
                    self._cb("on_result", rep["metrics"], self._rank0_reports)
                if rep.get("checkpoint") and rep.get("rank", 0) == 0:
                    self.ckpt_manager.register(rep["checkpoint"], rep["metrics"])
                    self._cb("on_checkpoint", rep["checkpoint"], rep["metrics"])
            if status.errors:
                self._m_failures.inc(len(status.errors),
                                     tags={"run": self._run_name})
                err = "\n".join(f"rank {r}: {e}"
                                for r, e in status.errors.items())
                if failures_left > 0:
                    raise RuntimeError(f"worker failure (will restart): {err}")
                return Result(error=err, checkpoint=self.ckpt_manager.latest(),
                              metrics_history=self.metrics_history)
            if status.finished:
                last = self.metrics_history[-1] if self.metrics_history else {}
                return Result(metrics=last,
                              checkpoint=self.ckpt_manager.latest(),
                              metrics_history=self.metrics_history)
            time.sleep(0.05)
