"""Llama-3 family: pure-functional JAX transformer with declarative sharding.

The flagship model of the framework (the reference delegates models to
torch/vLLM; a TPU-native framework owns them — BASELINE config 2: Llama-3-8B
DDP fine-tune is the north-star workload).

Design points (TPU-first):
- Params are a flat pytree of arrays; every leaf has a logical-axis tuple in
  ``param_logical_axes`` consumed by ray_tpu.parallel.sharding rules, so the
  same model runs pure-DP, FSDP, TP, or any mix by changing the rule table.
- Layers are stacked on a leading ``layers`` axis and iterated with
  ``lax.scan`` → one compiled layer body regardless of depth (fast compiles,
  XLA-friendly).
- Attention goes through ray_tpu.ops (flash kernel on TPU, blockwise
  elsewhere, ring attention when the mesh has an ``sp`` axis).
- bfloat16 activations/params by default, fp32 RMSNorm statistics and logits.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.ad_checkpoint import checkpoint_name

from ray_tpu.ops.attention import blockwise_attention, flash_attention
from ray_tpu.ops.norms import rms_norm
from ray_tpu.ops.ring_attention import ring_attention_local
from ray_tpu.ops.rope import apply_rope, rope_frequencies


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128256
    hidden_size: int = 4096
    intermediate_size: int = 14336
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    head_dim: int = 128
    max_seq_len: int = 8192
    rope_theta: float = 500000.0
    rope_scaling: dict | None = None
    norm_eps: float = 1e-5
    tie_embeddings: bool = False
    dtype: str = "bfloat16"

    @staticmethod
    def llama3_8b() -> "LlamaConfig":
        return LlamaConfig()

    @staticmethod
    def llama3_1b() -> "LlamaConfig":
        # Llama-3.2-1B geometry
        return LlamaConfig(hidden_size=2048, intermediate_size=8192,
                           num_layers=16, num_heads=32, num_kv_heads=8,
                           head_dim=64, tie_embeddings=True)

    @staticmethod
    def tiny() -> "LlamaConfig":
        """Test-size config: compiles in seconds, exercises every code path."""
        return LlamaConfig(vocab_size=256, hidden_size=64,
                           intermediate_size=128, num_layers=2, num_heads=4,
                           num_kv_heads=2, head_dim=16, max_seq_len=256,
                           dtype="float32")

    @property
    def jnp_dtype(self):
        return jnp.dtype(self.dtype)

    def num_params(self) -> int:
        h, v, i, L = self.hidden_size, self.vocab_size, self.intermediate_size, self.num_layers
        qkv = h * self.num_heads * self.head_dim + 2 * h * self.num_kv_heads * self.head_dim
        o = self.num_heads * self.head_dim * h
        mlp = 3 * h * i
        embed = v * h * (1 if self.tie_embeddings else 2)
        return embed + L * (qkv + o + mlp + 2 * h) + h


def param_logical_axes(cfg: LlamaConfig) -> dict:
    """Logical-axis names per param leaf (see parallel/sharding.py rules)."""
    axes = {
        "embed_tokens": ("vocab", "embed"),
        "final_norm": ("embed",),
        "layers": {
            "wq": ("layers", "embed", "heads"),
            "wk": ("layers", "embed", "kv_heads"),
            "wv": ("layers", "embed", "kv_heads"),
            "wo": ("layers", "heads", "embed"),
            "w_gate": ("layers", "embed", "mlp"),
            "w_up": ("layers", "embed", "mlp"),
            "w_down": ("layers", "mlp", "embed"),
            "attn_norm": ("layers", "embed"),
            "mlp_norm": ("layers", "embed"),
        },
    }
    if not cfg.tie_embeddings:
        axes["lm_head"] = ("embed", "vocab")
    return axes


def init_params(cfg: LlamaConfig, key: jax.Array) -> dict:
    """Scaled-normal init; layer params stacked on the leading axis."""
    h, L = cfg.hidden_size, cfg.num_layers
    qd = cfg.num_heads * cfg.head_dim
    kvd = cfg.num_kv_heads * cfg.head_dim
    i = cfg.intermediate_size
    dt = cfg.jnp_dtype
    keys = jax.random.split(key, 10)

    def norm_init(k, *shape, scale=None):
        scale = scale if scale is not None else 1.0 / math.sqrt(shape[-2])
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(dt)

    params = {
        "embed_tokens": (jax.random.normal(keys[0], (cfg.vocab_size, h),
                                           jnp.float32) * 0.02).astype(dt),
        "final_norm": jnp.ones((h,), dt),
        "layers": {
            "wq": norm_init(keys[1], L, h, qd),
            "wk": norm_init(keys[2], L, h, kvd),
            "wv": norm_init(keys[3], L, h, kvd),
            "wo": norm_init(keys[4], L, qd, h, scale=1.0 / math.sqrt(qd * 2 * L)),
            "w_gate": norm_init(keys[5], L, h, i),
            "w_up": norm_init(keys[6], L, h, i),
            "w_down": norm_init(keys[7], L, i, h, scale=1.0 / math.sqrt(i * 2 * L)),
            "attn_norm": jnp.ones((L, h), dt),
            "mlp_norm": jnp.ones((L, h), dt),
        },
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = norm_init(keys[8], h, cfg.vocab_size,
                                      scale=1.0 / math.sqrt(h))
    return params


def _attention(cfg: LlamaConfig, q, k, v, attn_impl: str, sp_axis: str | None):
    """q: [B, H, S, D], k/v: [B, Hkv, S, D] (already rope'd)."""
    if sp_axis is not None:
        # Context parallel: sequence is sharded over sp_axis (we are inside
        # shard_map); the ring handles cross-shard causality.
        return ring_attention_local(q, k, v, axis_name=sp_axis, causal=True)
    if attn_impl == "flash":
        return flash_attention(q, k, v, True, None, True)
    return blockwise_attention(q, k, v, causal=True)


def _layer(cfg: LlamaConfig, x, layer_params, inv_freq, positions,
           attn_impl: str, sp_axis: str | None):
    """One transformer block. x: [B, S, H]."""
    b, s, h = x.shape
    lp = layer_params
    dt = x.dtype

    # -- attention ----------------------------------------------------------
    xn = checkpoint_name(rms_norm(x, lp["attn_norm"], cfg.norm_eps),
                         "norm_out")
    q = (xn @ lp["wq"]).reshape(b, s, cfg.num_heads, cfg.head_dim)
    k = (xn @ lp["wk"]).reshape(b, s, cfg.num_kv_heads, cfg.head_dim)
    v = (xn @ lp["wv"]).reshape(b, s, cfg.num_kv_heads, cfg.head_dim)
    q = q.transpose(0, 2, 1, 3)
    k = k.transpose(0, 2, 1, 3)
    v = v.transpose(0, 2, 1, 3)
    q = checkpoint_name(apply_rope(q, positions, inv_freq), "rope_out")
    k = checkpoint_name(apply_rope(k, positions, inv_freq), "rope_out")
    v = checkpoint_name(v, "v_out")
    o = _attention(cfg, q, k, v, attn_impl, sp_axis)
    o = o.transpose(0, 2, 1, 3).reshape(b, s, cfg.num_heads * cfg.head_dim)
    x = x + checkpoint_name((o @ lp["wo"]).astype(dt), "attn_proj")

    # -- mlp (SwiGLU) -------------------------------------------------------
    xn = checkpoint_name(rms_norm(x, lp["mlp_norm"], cfg.norm_eps),
                         "norm_out")
    gate = checkpoint_name(
        jax.nn.silu((xn @ lp["w_gate"]).astype(jnp.float32)).astype(dt),
        "mlp_gate")
    up = xn @ lp["w_up"]
    x = x + ((gate * up) @ lp["w_down"]).astype(dt)
    return x


def normalize_remat(remat, num_layers: int):
    """Canonicalize a remat spec: a scalar policy stays scalar; a per-layer
    sequence (one policy string per layer — the autotuner's save-lists
    keyed by layer index) is length-checked and collapsed back to a scalar
    when uniform, so the single-scan fast path still applies. Strings with
    commas ("attn:8,dots:8" or "attn,attn,dots,...") expand to per-layer
    form; "policy:N" runs N consecutive layers under that policy."""
    if isinstance(remat, str) and ("," in remat or ":" in remat):
        out = []
        for part in remat.split(","):
            part = part.strip()
            if ":" in part:
                pol, n = part.rsplit(":", 1)
                out.extend([pol] * int(n))
            elif part:
                out.append(part)
        remat = tuple(out)
    if isinstance(remat, (list, tuple)):
        if len(remat) != num_layers:
            raise ValueError(
                f"per-layer remat has {len(remat)} entries for "
                f"{num_layers} layers")
        if len(set(remat)) == 1:
            return remat[0]
        return tuple(remat)
    return remat


def _remat_runs(remat: tuple) -> list[tuple]:
    """Consecutive equal-policy runs of a per-layer remat spec:
    ('attn','attn','dots') -> [('attn', 0, 2), ('dots', 2, 3)]. Each run
    scans with ONE compiled layer body (same compile-size economics as the
    uniform case; the number of distinct bodies = number of runs)."""
    runs = []
    start = 0
    for i in range(1, len(remat) + 1):
        if i == len(remat) or remat[i] != remat[start]:
            runs.append((remat[start], start, i))
            start = i
    return runs


def _remat_wrap(layer_fn, remat):
    """remat policy: True/'full' = recompute everything (min memory),
    'attn' = save ONLY the attention residuals (rope'd q/k, v, flash
    out+lse) and the attention output projection — the backward pass
    never re-runs the attention kernel, but the wide SwiGLU activations
    ([B,S,intermediate], the two biggest per-layer tensors) are
    recomputed from the saved attn_proj (one cheap residual-add + norm +
    two matmuls). ~3x less activation HBM than 'dots' for ~18% more
    step FLOPs — the fit-enabling mode for HBM-bound configs,
    'dots' = save matmul outputs (jax.checkpoint_policies.checkpoint_dots)
    plus the flash-attention residuals (out, lse) — so the backward pass
    neither recomputes the matmuls nor re-runs the attention kernel,
    'dots+' = 'dots' plus the rms_norm/rope outputs (no elementwise
    recompute at all — highest memory short of 'none'),
    False/'none' = save all."""
    if remat in (False, "none"):
        return layer_fn
    if remat == "attn":
        policy = jax.checkpoint_policies.save_only_these_names(
            "flash_resid", "rope_out", "v_out", "attn_proj")
        return jax.checkpoint(layer_fn, policy=policy)
    if remat == "attn+":
        # 'attn' plus the post-silu gate ([B,S,intermediate] bf16, ~134 MB
        # per layer at b4/s2048): the backward re-runs only the w_up matmul
        # (up, and gate·up from the saved gate) instead of the full SwiGLU
        # re-forward — trades ~2.1 GB of HBM for roughly half the 'attn'
        # MLP recompute. (Saving gate·up itself would be useless: d(gate)
        # and d(up) each need the OTHER factor, so both matmuls would still
        # re-run.)
        policy = jax.checkpoint_policies.save_only_these_names(
            "flash_resid", "rope_out", "v_out", "attn_proj", "mlp_gate")
        return jax.checkpoint(layer_fn, policy=policy)
    if remat in ("dots", "dots+"):
        names = ("flash_resid",) if remat == "dots" else (
            "flash_resid", "norm_out", "rope_out")
        policy = jax.checkpoint_policies.save_from_both_policies(
            jax.checkpoint_policies.checkpoint_dots,
            jax.checkpoint_policies.save_only_these_names(*names),
        )
        return jax.checkpoint(layer_fn, policy=policy)
    return jax.checkpoint(layer_fn)


def forward_hidden(cfg: LlamaConfig, params: dict, tokens: jax.Array,
                   positions: jax.Array | None = None,
                   attn_impl: str = "flash", sp_axis: str | None = None,
                   remat: bool | str | tuple = True) -> jax.Array:
    """tokens [B, S] → final-norm hidden states [B, S, H].

    ``remat`` is a single policy (see :func:`_remat_wrap`) or a per-layer
    spec (tuple of policies / "pol:N,pol:N" string — see
    :func:`normalize_remat`): e.g. the autotuner's mixed save-lists spend
    HBM on cheap-to-save early layers while the deep layers stay lean."""
    b, s = tokens.shape
    if positions is None:
        positions = jnp.arange(s)
    x = params["embed_tokens"][tokens]
    inv_freq = rope_frequencies(cfg.head_dim, cfg.rope_theta, cfg.rope_scaling)

    base_fn = partial(_layer, cfg, inv_freq=inv_freq, positions=positions,
                      attn_impl=attn_impl, sp_axis=sp_axis)
    remat = normalize_remat(remat, cfg.num_layers)

    if isinstance(remat, tuple):
        # Per-layer policies: scan each equal-policy run over its slice of
        # the stacked layer params (still one compiled body per run).
        for policy, start, end in _remat_runs(remat):
            layer_fn = _remat_wrap(base_fn, policy)

            def scan_body(x, lp, _fn=layer_fn):
                return _fn(x, lp), None

            run_params = jax.tree.map(lambda a: a[start:end],
                                      params["layers"])
            x, _ = lax.scan(scan_body, x, run_params)
        return rms_norm(x, params["final_norm"], cfg.norm_eps)

    layer_fn = _remat_wrap(base_fn, remat)

    def scan_body(x, lp):
        return layer_fn(x, lp), None

    x, _ = lax.scan(scan_body, x, params["layers"])
    return rms_norm(x, params["final_norm"], cfg.norm_eps)


def unembed_weights(cfg: LlamaConfig, params: dict) -> jax.Array:
    """[H, V] head matrix (transpose of tied embeddings stays a lazy dot
    permutation under XLA — never materialized)."""
    return params["embed_tokens"].T if cfg.tie_embeddings else params["lm_head"]


def forward(cfg: LlamaConfig, params: dict, tokens: jax.Array,
            positions: jax.Array | None = None, attn_impl: str = "flash",
            sp_axis: str | None = None, remat: bool | str = True) -> jax.Array:
    """tokens [B, S] → logits [B, S, V] (fp32). bf16 MXU matmul with fp32
    accumulation — a fp32×fp32 dot would run off the MXU fast path."""
    x = forward_hidden(cfg, params, tokens, positions, attn_impl, sp_axis,
                       remat)
    head = unembed_weights(cfg, params)
    return jnp.einsum("bsh,hv->bsv", x, head,
                      preferred_element_type=jnp.float32)


def loss_fn(cfg: LlamaConfig, params: dict, tokens: jax.Array,
            targets: jax.Array, mask: jax.Array | None = None,
            fused_ce: bool = True, **fwd_kwargs) -> jax.Array:
    """Mean next-token cross-entropy over unmasked positions."""
    if fused_ce:
        from ray_tpu.ops.loss import default_ce_chunk, fused_cross_entropy

        x = forward_hidden(cfg, params, tokens, **fwd_kwargs)
        head = unembed_weights(cfg, params)
        return fused_cross_entropy(x, head, targets, mask,
                                   default_ce_chunk())
    logits = forward(cfg, params, tokens, **fwd_kwargs)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    if mask is None:
        mask = jnp.ones_like(targets, jnp.float32)
    mask = mask.astype(jnp.float32)
    return (nll * mask).sum() / jnp.maximum(mask.sum(), 1.0)
