import jax, jax.numpy as jnp, numpy as np
from ray_tpu.ops.attention import flash_attention, blockwise_attention, attention_reference

rng = np.random.default_rng(0)
B,H,S,D = 2,4,512,64
q = jnp.asarray(rng.standard_normal((B,H,S,D)), jnp.bfloat16)
k = jnp.asarray(rng.standard_normal((B,H,S,D)), jnp.bfloat16)
v = jnp.asarray(rng.standard_normal((B,H,S,D)), jnp.bfloat16)

def loss_flash(q,k,v): return flash_attention(q,k,v,True,None,True).astype(jnp.float32).sum()
def loss_block(q,k,v): return blockwise_attention(q,k,v,causal=True).astype(jnp.float32).sum()
def loss_ref(q,k,v): return attention_reference(q,k,v,causal=True).astype(jnp.float32).sum()

for name, f in [("flash", loss_flash), ("block", loss_block), ("ref", loss_ref)]:
    val, grads = jax.value_and_grad(f, argnums=(0,1,2))(q,k,v)
    gn = [float(jnp.abs(g).max()) for g in grads]
    has_nan = [bool(jnp.isnan(g.astype(jnp.float32)).any()) for g in grads]
    print(name, float(val), "max|g|:", gn, "nan:", has_nan, flush=True)
