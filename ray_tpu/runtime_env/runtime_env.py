"""RuntimeEnv: the per-task/per-actor environment description.

Capability parity with the reference's RuntimeEnv (reference:
python/ray/runtime_env/runtime_env.py RuntimeEnv class; fields handled by
plugins in python/ray/_private/runtime_env/ — working_dir.py, py_modules.py,
pip.py/conda.py/uv.py, env-var injection): a validated dict of environment
requirements carried on every TaskSpec/ActorCreationSpec. Workers are reused
only for matching envs (the env hash is part of the scheduling key —
reference: worker_pool.h PopWorkerRequest runtime-env hash matching).

This build supports ``env_vars``, ``working_dir``, ``py_modules``, and
``config``; package-installer fields (``pip``/``conda``/``uv``) are validated
but rejected at setup time — the execution image is immutable (no network
installs), matching how hermetic TPU pods deploy code via packaged URIs
instead of per-task installs.
"""

from __future__ import annotations

import os
from typing import Any


_KNOWN_FIELDS = {
    "env_vars", "working_dir", "py_modules", "pip", "conda", "uv", "config",
    "image_uri", "container_run_options",
}


class RuntimeEnv(dict):
    """Dict-like, validated runtime environment."""

    def __init__(self, *, env_vars: dict[str, str] | None = None,
                 working_dir: str | None = None,
                 py_modules: list[str] | None = None,
                 pip: Any = None, conda: Any = None, uv: Any = None,
                 config: dict | None = None,
                 image_uri: str | None = None,
                 container_run_options: list[str] | None = None, **extra):
        super().__init__()
        from ray_tpu.runtime_env.container import validate_container_fields
        from ray_tpu.runtime_env.plugin import get_plugins

        plugin_fields = set(get_plugins())
        unknown = set(extra) - _KNOWN_FIELDS - plugin_fields
        if unknown:
            raise ValueError(f"unknown runtime_env fields: {sorted(unknown)}")
        for k in set(extra) & plugin_fields:
            self[k] = extra[k]  # plugin-owned; its validate() runs at setup
        if image_uri is not None or container_run_options is not None:
            probe = {"image_uri": image_uri,
                     "container_run_options": container_run_options}
            validate_container_fields(probe)
            if container_run_options is not None and image_uri is None:
                raise ValueError(
                    "container_run_options requires image_uri")
            if image_uri is not None:
                self["image_uri"] = image_uri
            if container_run_options is not None:
                self["container_run_options"] = list(container_run_options)
        if env_vars is not None:
            if not all(isinstance(k, str) and isinstance(v, str)
                       for k, v in env_vars.items()):
                raise TypeError("env_vars must be a dict[str, str]")
            self["env_vars"] = dict(env_vars)
        if working_dir is not None:
            if not isinstance(working_dir, str):
                raise TypeError("working_dir must be a path or packaged URI string")
            if not working_dir.startswith("kv://") and not os.path.isdir(working_dir):
                raise ValueError(f"working_dir {working_dir!r} is not a directory")
            self["working_dir"] = working_dir
        if py_modules is not None:
            if not isinstance(py_modules, (list, tuple)):
                raise TypeError("py_modules must be a list of paths/URIs")
            for m in py_modules:
                if not isinstance(m, str):
                    raise TypeError("py_modules entries must be strings")
                if not m.startswith("kv://") and not os.path.exists(m):
                    raise ValueError(f"py_module {m!r} does not exist")
            self["py_modules"] = list(py_modules)
        for name, val in (("pip", pip), ("conda", conda), ("uv", uv)):
            if val is not None:
                self[name] = val  # validated here, rejected at setup
        if config is not None:
            self["config"] = dict(config)

    @classmethod
    def from_dict(cls, d: dict | None) -> "RuntimeEnv":
        return cls(**(d or {}))

    def to_dict(self) -> dict:
        return dict(self)

    def has_uris(self) -> bool:
        return bool(self.get("working_dir") or self.get("py_modules"))
