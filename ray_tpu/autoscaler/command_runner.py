"""Command runners: how the autoscaler executes bootstrap commands on a
provisioned machine.

Capability parity with the reference's command-runner layer (reference:
python/ray/autoscaler/_private/command_runner.py — SSHCommandRunner sets up
freshly provisioned nodes over SSH; LocalNodeProvider runs on-host): the
autoscaler provisions capacity through a NodeProvider and then *joins* it to
the cluster by running ``python -m ray_tpu start --address=<head>`` through
one of these runners. GCE instances normally bootstrap via their
startup-script metadata instead (ray_tpu/autoscaler/gcp.py:_startup_script);
the SSH runner covers images where startup scripts are unavailable and
on-prem/bare-metal hosts.
"""

from __future__ import annotations

import subprocess
from typing import Callable, Sequence


class CommandRunner:
    """Executes a command on a target machine; raises on failure."""

    def run(self, cmd: Sequence[str], timeout: float = 120.0) -> str:
        raise NotImplementedError


class LocalCommandRunner(CommandRunner):
    """Runs on this host (reference: LocalNodeProvider's on-host setup).
    Used by SubprocessNodeProvider to bootstrap fake 'machines' as real OS
    processes, and for single-host deployments."""

    def run(self, cmd: Sequence[str], timeout: float = 120.0) -> str:
        proc = subprocess.run(list(cmd), capture_output=True, text=True,
                              timeout=timeout)
        if proc.returncode != 0:
            raise RuntimeError(
                f"command {cmd!r} failed ({proc.returncode}):\n"
                f"{proc.stderr[-2000:]}")
        return proc.stdout


class SshCommandRunner(CommandRunner):
    """Runs over SSH on a remote host (reference: SSHCommandRunner,
    command_runner.py:214). ``exec_fn`` is injectable so air-gapped tests
    can stub the transport."""

    def __init__(self, host: str, user: str = "root",
                 ssh_key: str | None = None,
                 ssh_options: Sequence[str] | None = None,
                 exec_fn: Callable[..., "subprocess.CompletedProcess"]
                 | None = None):
        self.host = host
        self.user = user
        self.ssh_key = ssh_key
        self.ssh_options = list(ssh_options or (
            "-o", "StrictHostKeyChecking=no",
            "-o", "ConnectTimeout=10",
            "-o", "BatchMode=yes",
        ))
        self._exec = exec_fn or (lambda argv, timeout: subprocess.run(
            argv, capture_output=True, text=True, timeout=timeout))

    def run(self, cmd: Sequence[str], timeout: float = 120.0) -> str:
        import shlex

        argv = ["ssh", *self.ssh_options]
        if self.ssh_key:
            argv += ["-i", self.ssh_key]
        argv.append(f"{self.user}@{self.host}")
        # The remote side word-splits; quote so JSON args (--resources
        # '{"TPU": 4}') survive intact.
        argv.append(" ".join(shlex.quote(c) for c in cmd))
        proc = self._exec(argv, timeout=timeout)
        if proc.returncode != 0:
            raise RuntimeError(
                f"ssh to {self.host} failed ({proc.returncode}):\n"
                f"{proc.stderr[-2000:]}")
        return proc.stdout
