"""Benchmark: Llama causal-LM training-step throughput, tokens/sec/chip.

Prints exactly ONE JSON line:
  {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}

vs_baseline is FLOP-normalized against the reference north-star (BASELINE.md:
Llama-3-8B DDP fine-tune at ~3,300 tokens/sec per A100-class chip, i.e.
6·N·rate ≈ 1.59e14 training FLOP/s/chip): vs_baseline = (6·N·tokens_per_sec)
/ 1.59e14 — >1.0 means this chip trains more model-FLOPs per second than the
reference's A100 number.

Outage behavior: the TPU tunnel can be down for hours (backend init hangs).
The probe retries with backoff for a bounded window; if the chip stays
unreachable the bench emits the LAST GOOD TPU measurement tagged
``"tpu_unreachable": true`` — a comparable number for round tracking —
instead of an incomparable CPU-fallback figure.

Measurement strategy: the known-good config runs FIRST (banks a number),
then more aggressive candidates (less remat, bigger batch — enabled by the
compact-moment optimizer freeing ~2.2 GB of HBM, train/optim.py) are tried
and the best throughput wins. A failed candidate (OOM at compile) costs one
AOT attempt, not the bench.
"""

from __future__ import annotations

import json
import os
import sys
import time


A100_8B_TOKENS_PER_SEC = 3300.0
A100_8B_PARAMS = 8.03e9
BASELINE_FLOPS = 6.0 * A100_8B_PARAMS * A100_8B_TOKENS_PER_SEC  # 1.59e14

METRIC = "llama_1b_train_tokens_per_sec_per_chip"

# Fallback if no BENCH_r*.json with a real TPU measurement is found on disk
# (round 2 was the most recent chip-measured number when this was written).
_LAST_GOOD_DEFAULT = {"round": "r02", "value": 14860.1, "vs_baseline": 0.583}


def _last_good() -> dict:
    """Most recent REAL TPU measurement from the recorded rounds — scanned
    at runtime so the outage fallback can never go stale after a better
    round lands. Also considers PERF_TRAIN_TPU.json, which this harness
    writes on every successful mid-round TPU run: a measurement banked
    hours before the driver's end-of-round bench survives a tunnel outage
    at round close (the round-3 failure mode)."""
    import glob
    import re

    best = dict(_LAST_GOOD_DEFAULT)
    here = os.path.dirname(os.path.abspath(__file__))
    best_round = -1
    for path in glob.glob(os.path.join(here, "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if not m:
            continue
        rnd = int(m.group(1))
        try:
            rec = json.load(open(path))
            rec = rec.get("parsed", rec)  # driver wraps the line
        except Exception:
            continue
        if (rec.get("metric") == METRIC and not rec.get("tpu_unreachable")
                and not rec.get("all_candidates_failed")
                and rec.get("value", 0) > 0 and rnd > best_round):
            best_round = rnd
            best = {"round": f"r{rnd:02d}", "value": rec["value"],
                    "vs_baseline": rec["vs_baseline"]}
    try:
        rec = json.load(open(os.path.join(here, "PERF_TRAIN_TPU.json")))
        if (rec.get("metric") == METRIC and rec.get("value", 0) > best["value"]
                and not rec.get("tpu_unreachable")):
            best = {"round": rec.get("round", "banked"),
                    "value": rec["value"],
                    "vs_baseline": rec["vs_baseline"]}
    except Exception:
        pass
    return best


def _bank(rec: dict) -> None:
    """Persist a successful TPU measurement next to the harness (see
    _last_good). ``value`` ratchets only within RUN VARIANCE (~1%): a
    re-run within 2% below the banked value keeps the banked number, but
    a genuinely slower measurement replaces it. ``last_run_value`` is
    ALWAYS the most recent run, so a ~1-2% regression hiding inside the
    variance band stays observable instead of vanishing behind a
    historical peak."""
    here = os.path.dirname(os.path.abspath(__file__))
    path = os.path.join(here, "PERF_TRAIN_TPU.json")
    rec = dict(rec)
    rec["last_run_value"] = rec.get("value")
    try:
        prev = json.load(open(path))
        if (prev.get("metric") == rec.get("metric")
                and rec.get("value", 0) < prev.get("value", 0)
                and rec.get("value", 0) >= prev.get("value", 0) * 0.98):
            # Within variance band: keep the better banked value (and its
            # derived fields, so the record stays internally consistent)
            # but still record this run in last_run_value.
            rec["value"] = prev["value"]
            rec["config"] = prev.get("config", rec.get("config"))
            if "vs_baseline" in prev:
                rec["vs_baseline"] = prev["vs_baseline"]
    except Exception:
        pass
    try:
        with open(path, "w") as f:
            json.dump(rec, f, indent=1)
    except Exception:
        pass


def _tpu_reachable(timeout: float = 90.0) -> bool:
    """Probe the TPU backend in a subprocess — backend init can hang
    indefinitely if the device tunnel is down, and it must not take the
    bench process with it."""
    import subprocess

    if os.environ.get("RTPU_BENCH_FORCE_NO_TPU") == "1":  # outage simulation
        return False
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; assert any(d.platform == 'tpu' for d in jax.devices())"],
            timeout=timeout, capture_output=True,
        )
        return r.returncode == 0
    except Exception:
        return False


def _wait_for_tpu(default_budget: float = 600.0) -> bool:
    """Retry the probe across a bounded window (driver budget), backing off
    between attempts — a transient tunnel blip must not discard the round's
    perf work. Shared by bench_serve.py."""
    budget = float(os.environ.get("RTPU_BENCH_PROBE_BUDGET_S",
                                  str(default_budget)))
    deadline = time.monotonic() + budget
    pause = 15.0
    while True:
        if _tpu_reachable():
            return True
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            return False
        time.sleep(min(pause, remaining))
        pause = min(pause * 2, 120.0)


def _emit(value: float, vs: float, extra: dict | None = None) -> None:
    rec = {"metric": METRIC, "value": round(value, 1),
           "unit": "tokens/sec/chip", "vs_baseline": round(vs, 3)}
    rec.update(extra or {})
    print(json.dumps(rec))


def _measure_candidates(cfg, seq, candidates, steps, warmup):
    """Try each (batch, remat, attn, opt) candidate; return
    (best_tok_per_sec, best_config, tried) with per-candidate cleanup so an
    OOM doesn't poison the next attempt."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from ray_tpu.parallel.mesh import MeshSpec, build_mesh
    from ray_tpu.train.optim import adamw_lowmem
    from ray_tpu.train.spmd import make_llama_train_step

    mesh = build_mesh(MeshSpec(dp=1), jax.devices()[:1])
    best = (0.0, None)
    tried = []
    for batch, remat, attn, opt_name in candidates:
        label = f"b{batch}/{remat}/{attn}/{opt_name}"
        try:
            if opt_name == "lowmem":
                opt = adamw_lowmem(3e-4, weight_decay=0.1)
            else:
                opt = optax.adamw(3e-4, weight_decay=0.1,
                                  mu_dtype=jnp.bfloat16)
            step_fn, init_state, shard = make_llama_train_step(
                cfg, mesh, optimizer=opt, attn_impl=attn, remat=remat,
            )
            state = init_state()
            rng = np.random.default_rng(0)
            tokens = shard(rng.integers(0, cfg.vocab_size, (batch, seq),
                                        dtype=np.int32))
            targets = shard(np.roll(np.asarray(tokens), -1, axis=1))
            for _ in range(warmup):
                state, m = step_fn(state, tokens, targets)
            jax.block_until_ready(m["loss"])
            t0 = time.perf_counter()
            for _ in range(steps):
                state, m = step_fn(state, tokens, targets)
            jax.block_until_ready(m["loss"])
            dt = (time.perf_counter() - t0) / steps
            tok_per_sec = batch * seq / dt
            tried.append({"config": label,
                          "tokens_per_sec": round(tok_per_sec, 1)})
            if tok_per_sec > best[0]:
                best = (tok_per_sec, label)
        except Exception as e:  # noqa: BLE001 - OOM/compile fallback chain
            tried.append({"config": label, "error": str(e)[:160]})
            print(f"candidate {label} failed: {str(e)[:200]}",
                  file=sys.stderr)
        finally:
            # Drop every live buffer before the next candidate allocates —
            # a single OOM leaks ~9 GB of params/optimizer state otherwise.
            state = step_fn = None  # noqa: F841
            for buf in jax.live_arrays():
                buf.delete()
            jax.clear_caches()
    return best[0], best[1], tried


def main() -> None:
    on_tpu = _wait_for_tpu()

    if not on_tpu:
        last = _last_good()
        _emit(last["value"], last["vs_baseline"],
              {"tpu_unreachable": True, "last_good_round": last["round"]})
        return

    import jax

    from ray_tpu.models.llama import LlamaConfig

    # ~1.1B-param geometry (Llama-3.2-1B-like), bf16, remat.
    cfg = LlamaConfig(
        vocab_size=32128, hidden_size=2048, intermediate_size=8192,
        num_layers=16, num_heads=32, num_kv_heads=8, head_dim=64,
        max_seq_len=2048, tie_embeddings=True, dtype="bfloat16",
    )
    seq = 2048
    # (batch, remat, attn, opt). The first row banks a number: 'attn'
    # remat saves only the attention residuals (~3x less activation HBM
    # than 'dots' — the round-3 OOM margin was 42 MB, this clears it by
    # gigabytes). Later rows spend HBM on bigger batches / less
    # recompute; best measured throughput wins. A failed candidate (OOM
    # at compile) costs one AOT attempt, not the bench.
    candidates = [
        (4, "attn", "flash", "lowmem"),
        (4, "attn+", "flash", "lowmem"),  # + saved SwiGLU gate (llama.py)
        (5, "attn", "flash", "lowmem"),   # r5: the odd-batch tiling penalty
        # vanished with the packed flash kernels (14,977 -> 16,707 tok/s;
        # head-pack grid rows b*h/4 are even for any b) — b5 now ties b4.
        (8, "attn", "flash", "lowmem"),
        (4, "dots", "flash", "lowmem"),   # round-2 winner shape + compact moments
        # Dropped (r04 chip-verified OOM at compile): b16/attn, b8/dots,
        # b4/dots+ — all exceed 15.75 GB HBM at this geometry; keeping them
        # would re-pay a failed AOT attempt every round (r03 verdict weak #2).
    ]
    tok_per_sec, config, tried = _measure_candidates(
        cfg, seq, candidates, steps=10, warmup=2)

    if tok_per_sec <= 0:
        # Every candidate failed even though the chip answered the probe —
        # that is a code/regression signal, NOT a tunnel outage. Emit the
        # last good number for tracking continuity but tag it honestly
        # (the per-candidate errors ride along for diagnosis).
        last = _last_good()
        _emit(last["value"], last["vs_baseline"],
              {"all_candidates_failed": True,
               "last_good_round": last["round"], "tried": tried})
        return

    n_params = cfg.num_params()
    vs = (6.0 * n_params * tok_per_sec) / BASELINE_FLOPS
    _bank({"metric": METRIC, "value": round(tok_per_sec, 1),
           "unit": "tokens/sec/chip", "vs_baseline": round(vs, 3),
           "config": config, "ts": time.time()})
    _emit(tok_per_sec, vs, {"config": config, "tried": tried})


if __name__ == "__main__":
    main()
