"""HTTP ingress proxy.

Capability parity with the reference's proxy (reference:
python/ray/serve/_private/proxy.py:1605 ProxyActor — HTTP ingress routed by
prefix to the application's ingress deployment, request forwarded through a
handle, response streamed back). Implemented over http.server in the proxy
actor's thread (stdlib-only; the box has no ASGI server).
"""

from __future__ import annotations

import json
import threading
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse


@dataclass
class Request:
    """What an ingress deployment's __call__ receives for an HTTP request
    (reference: starlette Request equivalent, minimal surface)."""

    method: str
    path: str
    query_params: dict[str, str] = field(default_factory=dict)
    headers: dict[str, str] = field(default_factory=dict)
    body: bytes = b""

    def json(self):
        return json.loads(self.body) if self.body else None

    @property
    def text(self) -> str:
        return self.body.decode()


class ProxyActor:
    """Binds an HTTP server; routes longest-prefix-match to the ingress
    deployment's handle. Runs as an actor (one per node in the reference;
    one per cluster here until multi-node proxying lands)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 8000):
        from ray_tpu.serve.handle import DeploymentHandle

        self._routes: dict[str, str] = {}
        self._handles: dict[str, DeploymentHandle] = {}
        self._lock = threading.Lock()

        proxy = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _dispatch(self):
                parsed = urlparse(self.path)
                route, dep = proxy._match(parsed.path)
                if dep is None:
                    self.send_response(404)
                    self.end_headers()
                    self.wfile.write(b"no application at this route")
                    return
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length) if length else b""
                req = Request(
                    method=self.command,
                    path=parsed.path[len(route.rstrip("/")):] or "/",
                    query_params={k: v[0] for k, v in
                                  parse_qs(parsed.query).items()},
                    headers={k: v for k, v in self.headers.items()},
                    body=body,
                )
                try:
                    hint = (self.headers.get("x-route-hint")
                            or _prefix_route_hint(body))
                    # Per-request budget: the x-request-timeout-s header
                    # overrides the deployment's request_timeout_s; the
                    # deadline rides the call end to end (router queue,
                    # replica admission, batcher).
                    timeout_s = None
                    raw_t = self.headers.get("x-request-timeout-s")
                    if raw_t:
                        try:
                            timeout_s = max(float(raw_t), 0.001)
                        except ValueError:
                            timeout_s = None
                    gen = proxy._get_handle(dep).options(
                        stream=True, route_hint=hint,
                        timeout_s=timeout_s).remote(req)
                    gen.timeout = timeout_s or 60.0  # bound per chunk
                    if gen.streaming:
                        # SSE/chunk streaming: write each produced chunk as
                        # it arrives; length-delimited by connection close
                        # (reference: proxy_request streaming path,
                        # proxy.py:481).
                        self.send_response(200)
                        self.send_header("Content-Type",
                                         "text/event-stream; charset=utf-8")
                        self.send_header("Cache-Control", "no-cache")
                        self.send_header("Connection", "close")
                        self.end_headers()
                        try:
                            for chunk in gen:
                                if isinstance(chunk, str):
                                    chunk = chunk.encode()
                                elif not isinstance(chunk,
                                                    (bytes, bytearray)):
                                    chunk = json.dumps(chunk).encode()
                                self.wfile.write(chunk)
                                self.wfile.flush()
                        except Exception:  # noqa: BLE001
                            # 200 + body already on the wire: terminate the
                            # stream (connection close) — a second status
                            # line would corrupt the client's event stream.
                            pass
                        return
                    result = next(gen)
                except Exception as e:  # noqa: BLE001 - mapped below
                    # Resilience-aware status mapping (reference: serve
                    # returns 503 on backpressure so clients/load balancers
                    # back off instead of piling on):
                    #   Overloaded        → 503 + Retry-After
                    #   DeadlineExceeded  → 504 (budget spent in-cluster)
                    #   anything else     → 500
                    from ray_tpu.serve import resilience

                    cause = resilience.unwrap(e)
                    if isinstance(cause, resilience.Overloaded):
                        self.send_response(503)
                        self.send_header(
                            "Retry-After",
                            str(max(1, int(cause.retry_after_s))))
                        self.end_headers()
                        self.wfile.write(
                            f"overloaded ({cause.where})".encode())
                        return
                    if isinstance(cause, (resilience.DeadlineExceeded,
                                          TimeoutError)):
                        self.send_response(504)
                        self.end_headers()
                        self.wfile.write(b"request deadline exceeded")
                        return
                    self.send_response(500)
                    self.end_headers()
                    self.wfile.write(repr(e).encode())
                    return
                status, ctype, payload = _encode(result)
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            do_GET = do_POST = do_PUT = do_DELETE = _dispatch

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()

    def _match(self, path: str):
        with self._lock:
            best = None
            for route, dep in self._routes.items():
                r = route.rstrip("/") or "/"
                if path == r or path.startswith(r.rstrip("/") + "/") or r == "/":
                    if best is None or len(r) > len(best[0]):
                        best = (r, dep)
            return best if best else ("/", None)

    def _get_handle(self, deployment_name: str):
        from ray_tpu.serve.handle import DeploymentHandle

        with self._lock:
            if deployment_name not in self._handles:
                self._handles[deployment_name] = DeploymentHandle(deployment_name)
            return self._handles[deployment_name]

    # -- control plane --

    def update_routes(self, routes: dict[str, str]) -> None:
        with self._lock:
            self._routes = dict(routes)

    def port(self) -> int:
        return self._port

    def ready(self) -> bool:
        return True

    def shutdown(self) -> None:
        self._server.shutdown()


def _prefix_route_hint(body: bytes) -> str | None:
    """Prefix-affinity hint for LLM-shaped requests (reference:
    routing_policies/prefix_aware): requests sharing a prompt prefix hash
    to the same hint, so the router sends them to the replica whose engine
    already holds that prefix's KV (engine-side reuse: LLMEngine prefix
    cache). Non-JSON / non-LLM bodies get no hint (pow-2 routing)."""
    if not body or len(body) > 1 << 20:
        return None
    try:
        payload = json.loads(body)
    except Exception:
        return None
    if not isinstance(payload, dict):
        return None
    text = None
    if isinstance(payload.get("prompt"), str):
        text = payload["prompt"]
    elif isinstance(payload.get("messages"), list) and payload["messages"]:
        first = payload["messages"][0]
        if isinstance(first, dict) and isinstance(first.get("content"), str):
            text = first["content"]
    if not text:
        return None
    import hashlib

    # Hash a FIXED-size head block so the divergent tail never enters the
    # hint: prompts sharing >= 128 chars (the system-prompt shape) map to
    # one replica. Prefixes shorter than the block scatter — acceptable,
    # their prefill is cheap anyway.
    return hashlib.sha1(text[:128].encode("utf-8", "ignore")).hexdigest()[:16]


def _encode(result) -> tuple[int, str, bytes]:
    if isinstance(result, Response):
        return result.status_code, result.content_type, result.body
    if isinstance(result, bytes):
        return 200, "application/octet-stream", result
    if isinstance(result, str):
        return 200, "text/plain; charset=utf-8", result.encode()
    return 200, "application/json", json.dumps(result).encode()


@dataclass
class Response:
    body: bytes
    status_code: int = 200
    content_type: str = "application/octet-stream"
