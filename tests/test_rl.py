"""RL stack: env physics, GAE, PPO learning, runner actors, Tuner
integration. (Reference test model: rllib/algorithms/ppo/tests/test_ppo.py
learning smoke + env runner tests.)"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.rl import PPO, PPOConfig
from ray_tpu.rl.env import CartPoleEnv, VectorEnv
from ray_tpu.rl.ppo import compute_gae


def test_cartpole_physics():
    env = CartPoleEnv(seed=0)
    obs = env.reset()
    assert obs.shape == (4,) and np.all(np.abs(obs) <= 0.05)
    total = 0
    for _ in range(500):
        obs, r, term, trunc = env.step(1)  # constant push tips the pole
        total += r
        if term or trunc:
            break
    assert term  # constant action must fail well before truncation
    assert 5 < total < 100


def test_vector_env_autoreset():
    vec = VectorEnv("CartPole-v1", 4, seed=0)
    vec.reset()
    for _ in range(200):
        _, _, dones = vec.step(np.ones(4, np.int32))
    rets = vec.drain_episode_returns()
    assert len(rets) >= 4  # several episodes ended and auto-reset
    assert all(r > 0 for r in rets)


def test_gae_matches_manual():
    import jax.numpy as jnp

    rewards = jnp.asarray([[1.0], [1.0], [1.0]])
    values = jnp.asarray([[0.5], [0.5], [0.5]])
    dones = jnp.zeros((3, 1), bool)
    last = jnp.asarray([0.5])
    gamma, lam = 0.9, 0.8
    adv, ret = compute_gae(rewards, values, dones, last, gamma, lam)
    # manual reverse recursion
    deltas = [1.0 + gamma * 0.5 - 0.5] * 3
    a2 = deltas[2]
    a1 = deltas[1] + gamma * lam * a2
    a0 = deltas[0] + gamma * lam * a1
    np.testing.assert_allclose(np.asarray(adv)[:, 0], [a0, a1, a2], rtol=1e-6)
    np.testing.assert_allclose(np.asarray(ret), np.asarray(adv) + 0.5,
                               rtol=1e-6)


def test_gae_resets_at_done():
    import jax.numpy as jnp

    rewards = jnp.ones((2, 1))
    values = jnp.zeros((2, 1))
    dones = jnp.asarray([[True], [False]])
    last = jnp.asarray([10.0])
    adv, _ = compute_gae(rewards, values, dones, last, 0.9, 1.0)
    # t=0 episode ended: no bootstrap through the boundary
    np.testing.assert_allclose(float(adv[0, 0]), 1.0, rtol=1e-6)
    np.testing.assert_allclose(float(adv[1, 0]), 1.0 + 0.9 * 10.0, rtol=1e-6)


def test_ppo_solves_cartpole():
    """The headline learning test (reference: rllib PPO CartPole tune runs
    to a reward threshold)."""
    algo = PPOConfig(num_envs_per_runner=8, rollout_len=128, lr=3e-4,
                     seed=0).build()
    best = 0.0
    for _ in range(50):
        r = algo.train_step()
        best = max(best, r["episode_return_mean"])
        if best >= 150.0:
            break
    algo.cleanup()
    assert best >= 150.0, f"PPO failed to learn CartPole: best {best}"


def test_ppo_runner_actors(rt_start):
    """Distributed rollout path: env-runner ACTORS sample in parallel and
    receive weight broadcasts (reference: EnvRunnerGroup over actors)."""
    algo = PPO({"ppo_config": PPOConfig(
        num_env_runners=2, num_envs_per_runner=4, rollout_len=32,
        seed=1)})
    r1 = algo.train_step()
    r2 = algo.train_step()
    assert r1["num_env_steps_sampled"] == 2 * 4 * 32
    assert "policy_loss" in r2
    algo.cleanup()


def test_ppo_under_tuner(rt_start):
    """PPO as a Tune trainable: a small sweep returns the better lr
    (reference: Algorithm is a Tune Trainable)."""
    tuner = tune.Tuner(
        PPO,
        param_space={
            "env": "CartPole-v1",
            "rollout_len": 64,
            "num_envs_per_runner": 4,
            "lr": tune.grid_search([3e-4, 0.0]),  # lr=0 can't learn
            "seed": 0,
        },
        tune_config=tune.TuneConfig(metric="episode_return_mean",
                                    mode="max"),
        stop={"training_iteration": 12},
    )
    grid = tuner.fit()
    assert len(grid) == 2
    best = grid.get_best_result()
    assert best.config["lr"] == 3e-4
    assert best.metrics["episode_return_mean"] > 25.0


def test_replay_buffers():
    from ray_tpu.rl import PrioritizedReplayBuffer, ReplayBuffer

    buf = ReplayBuffer(8, 3, seed=0)
    obs = np.arange(30, dtype=np.float32).reshape(10, 3)
    buf.add_batch(obs, np.arange(10), np.ones(10), obs + 1, np.zeros(10))
    assert len(buf) == 8  # ring wrapped: capacity bound holds
    b = buf.sample(4)
    assert b["obs"].shape == (4, 3) and (b["next_obs"] == b["obs"] + 1).all()

    pbuf = PrioritizedReplayBuffer(16, 3, seed=0)
    pbuf.add_batch(obs, np.arange(10), np.ones(10), obs + 1, np.zeros(10))
    b = pbuf.sample(6)
    assert "weights" in b and b["weights"].max() <= 1.0 + 1e-6
    # boost one transition's priority: it must dominate sampling (uniform
    # would draw it ~10% of the time; prioritized ~99%)
    pbuf.update_priorities(np.array([3]), np.array([100.0]))
    draws = np.concatenate([pbuf.sample(8)["idx"] for _ in range(25)])
    assert (draws == 3).mean() > 0.5, (draws == 3).mean()
    # its importance weight is the (relatively) smallest
    b = pbuf.sample(32)
    w3 = b["weights"][b["idx"] == 3]
    assert len(w3) and np.allclose(w3, b["weights"].min())


def test_dqn_learns_cartpole():
    """DQN with replay + target net reaches a learning threshold on CartPole
    (reference: rllib DQN CartPole runs; threshold kept modest for CI)."""
    from ray_tpu.rl import DQNConfig

    algo = DQNConfig(num_envs_per_runner=8, rollout_len=16,
                     learning_starts=256, seed=0).build()
    best = 0.0
    for _ in range(120):
        r = algo.train_step()
        best = max(best, r["episode_return_mean"])
        if best >= 100.0:
            break
    algo.cleanup()
    assert best >= 100.0, f"DQN failed to learn CartPole: best {best}"
    assert r["epsilon"] < 1.0 and r["buffer_size"] > 0


def test_dqn_prioritized_and_checkpoint():
    from ray_tpu.rl import DQN, DQNConfig

    algo = DQNConfig(prioritized_replay=True, learning_starts=64,
                     rollout_len=8, num_envs_per_runner=4, seed=2).build()
    for _ in range(3):
        r = algo.train_step()
    ckpt = algo.save_checkpoint()
    algo.cleanup()

    algo2 = DQN({"dqn_config": DQNConfig(seed=3)})
    algo2.load_checkpoint(ckpt)
    assert algo2.env_steps == ckpt["env_steps"]
    algo2.cleanup()


def test_bc_offline_training(rt_start):
    """Behavior cloning from an offline ray_tpu.data dataset recovers an
    expert policy (reference: rllib BC over ray.data offline data)."""
    import ray_tpu.data as rdata
    from ray_tpu.rl import BCConfig
    from ray_tpu.rl.env import CartPoleEnv

    # Expert: a simple angle+velocity controller that balances CartPole.
    env = CartPoleEnv(seed=0)
    obs_rows, act_rows = [], []
    for ep in range(30):
        obs = env.reset()
        done, steps = False, 0
        while not done and steps < 200:
            a = 1 if (obs[2] + 0.5 * obs[3]) > 0 else 0
            obs_rows.append(np.asarray(obs, np.float32))
            act_rows.append(a)
            obs, _, term, trunc = env.step(a)
            done = term or trunc
            steps += 1
    ds = rdata.from_blocks([{"obs": np.stack(obs_rows),
                             "actions": np.asarray(act_rows, np.int32)}])

    algo = BCConfig(dataset=ds, epochs_per_step=3,
                    evaluation_episodes=3, seed=0).build()
    last = None
    for _ in range(5):
        last = algo.train_step()
    # Return is the success criterion (perfect balancing = 500); accuracy
    # plateaus near the expert's sharp decision boundary.
    assert last["action_accuracy"] > 0.8, last
    assert last["episode_return_mean"] > 100.0, last
    # checkpoint round-trips
    ckpt = algo.save_checkpoint()
    algo.load_checkpoint(ckpt)


def test_vtrace_reduces_to_gae_like_targets_on_policy():
    """On-policy (behavior == target), V-trace vs targets equal the
    discounted n-step returns bootstrapped from V (rho = c = 1)."""
    import jax.numpy as jnp

    from ray_tpu.rl.impala import vtrace

    T, N = 5, 3
    rng = np.random.default_rng(0)
    rewards = jnp.asarray(rng.normal(size=(T, N)).astype(np.float32))
    values = jnp.asarray(rng.normal(size=(T, N)).astype(np.float32))
    dones = jnp.zeros((T, N), bool)
    last_value = jnp.asarray(rng.normal(size=(N,)).astype(np.float32))
    logp = jnp.asarray(rng.normal(size=(T, N)).astype(np.float32))

    vs, pg_adv = vtrace(logp, logp, rewards, values, dones, last_value,
                        gamma=0.9, rho_clip=1.0, c_clip=1.0)
    # manual n-step backward recursion with rho=c=1
    expect = np.zeros((T, N), np.float32)
    nxt = np.asarray(last_value)
    corr = np.zeros((N,), np.float32)
    for t in reversed(range(T)):
        delta = np.asarray(rewards)[t] + 0.9 * nxt - np.asarray(values)[t]
        corr = delta + 0.9 * corr
        expect[t] = np.asarray(values)[t] + corr
        nxt = np.asarray(values)[t]
    np.testing.assert_allclose(np.asarray(vs), expect, rtol=1e-5, atol=1e-5)


def test_impala_solves_cartpole_inline():
    """V-trace learner reaches the reward threshold (reference: rllib
    IMPALA CartPole runs)."""
    from ray_tpu.rl.impala import ImpalaConfig

    algo = ImpalaConfig(num_envs_per_runner=8, rollout_len=64, lr=5e-4,
                        seed=0).build()
    best = 0.0
    for _ in range(120):
        r = algo.train_step()
        best = max(best, r["episode_return_mean"])
        if best >= 150.0:
            break
    algo.cleanup()
    assert best >= 150.0, f"IMPALA failed to learn CartPole: best {best}"


def test_impala_async_runners(rt_start):
    """Async actor-learner loop: runner actors keep rollouts in flight,
    the learner consumes ready ones without a barrier, weight versions
    advance, and stale rollouts beyond the bound are dropped (reference:
    impala.py async EnvRunner sampling + max staleness)."""
    from ray_tpu.rl.impala import ImpalaConfig

    algo = ImpalaConfig(num_env_runners=2, num_envs_per_runner=4,
                        rollout_len=16, rollouts_per_step=2,
                        max_staleness=1, seed=1).build()
    try:
        r1 = algo.train_step()
        r2 = algo.train_step()
        assert r2["weight_version"] >= r1["weight_version"] >= 1
        assert r1["num_env_steps_sampled"] > 0
        assert "policy_loss" in r2
        # async pipeline stays primed: one in-flight sample per runner
        assert len(algo._inflight) == 2
    finally:
        algo.cleanup()


def test_multi_agent_env_protocol():
    """Dict-keyed obs/rewards/dones with the __all__ terminator
    (reference: multi_agent_env.py protocol)."""
    from ray_tpu.rl.multi_agent import CoordinationGame

    env = CoordinationGame(horizon=3, seed=0)
    obs = env.reset()
    assert set(obs) == {"a0", "a1"}
    for t in range(3):
        obs, rew, dones = env.step({"a0": 1, "a1": 1})
        assert rew == {"a0": 1.0, "a1": 1.0}  # matched actions
        assert dones["__all__"] == (t == 2)


def test_multi_agent_runner_policy_routing():
    """policy_mapping_fn routes each agent's experience into its policy's
    batch (reference: multi_agent_env_runner.py + policy mapping)."""
    import numpy as np

    from ray_tpu.rl.multi_agent import MultiAgentEnvRunner

    def act(params, obs, seed):
        n = obs.shape[0]
        return (np.full(n, params, np.int32), np.zeros(n, np.float32),
                np.zeros(n, np.float32))

    runner = MultiAgentEnvRunner(
        "CoordinationGame", rollout_len=8,
        policy_mapping_fn=lambda a: "p0" if a == "a0" else "p1",
        act_fns={"p0": act, "p1": act}, seed=0)
    runner.set_weights({"p0": 0, "p1": 1})  # p0 always acts 0, p1 acts 1
    out = runner.sample()
    out.pop("__episode_returns__")
    out.pop("__agent_episode_returns__")
    assert set(out) == {"p0", "p1"}
    assert out["p0"]["obs"].shape == (8, 1, 5)
    assert (out["p0"]["actions"] == 0).all()
    assert (out["p1"]["actions"] == 1).all()
    # mismatched actions -> zero reward everywhere
    assert (out["p0"]["rewards"] == 0).all()


def test_multi_agent_shared_policy_learns_coordination():
    """Shared-policy PPO reaches near-optimal coordination (reference:
    rllib multi-agent training runs)."""
    from ray_tpu.rl.multi_agent import MultiAgentPPOConfig

    algo = MultiAgentPPOConfig(rollout_len=128, lr=1e-3, seed=0).build()
    best = 0.0
    for _ in range(80):
        r = algo.train_step()
        best = max(best, r["episode_return_mean"])
        if best >= 14.0:
            break
    assert best >= 14.0, f"no coordination learned: best {best}"


def test_multi_agent_independent_policies():
    """Two independent policies (one per agent) train on disjoint batches
    and still coordinate."""
    from ray_tpu.rl.multi_agent import MultiAgentPPOConfig

    algo = MultiAgentPPOConfig(
        policies=("left", "right"),
        policy_mapping={"a0": "left", "a1": "right"},
        rollout_len=128, lr=1e-3, seed=2).build()
    best = 0.0
    for _ in range(80):
        r = algo.train_step()
        best = max(best, r["episode_return_mean"])
        if best >= 14.0:
            break
    assert best >= 14.0, f"independent policies failed: best {best}"
    assert set(r["policies"]) == {"left", "right"}


def test_sac_learns_pendulum():
    """SAC (continuous-control archetype): squashed-Gaussian actor + twin
    critics + auto temperature improves Pendulum return; TD targets
    bootstrap through time-limit truncation (reference:
    rllib/algorithms/sac)."""
    from ray_tpu.rl import SACConfig

    cfg = SACConfig(num_envs_per_runner=8, rollout_len=32,
                    learning_starts=512, train_batches_per_step=24,
                    batch_size=128, hidden=64, seed=0)
    algo = cfg.build()
    try:
        rets = []
        for _ in range(300):
            m = algo.step()
            rets.append(m["episode_return_mean"])
        early = sum(rets[20:60]) / 40
        late = sum(rets[-40:]) / 40
        assert late > early + 300, (early, late)
        assert 0.0 < m["alpha"] < 1.0  # temperature auto-tuned down
    finally:
        algo.cleanup()


def test_sac_rejects_discrete_env():
    from ray_tpu.rl import SACConfig

    with pytest.raises(Exception, match="continuous"):
        SACConfig(env="CartPole-v1").build()


def test_multi_agent_mixed_cooperative_competitive():
    """ChaseGame: heterogeneous objectives (predator team vs prey) with one
    policy serving MULTIPLE agent slots. Predator policy learns to CAPTURE
    (random play on the size-20 ring mostly times out at ~1.7 return;
    directed pursuit climbs toward the +5 capture bonus) while the prey's
    return mirrors it (zero-sum coupling). Exercises per-policy batch
    routing, per-policy return metrics, and capture terminations.

    Deterministic at seed 0; the measured gain is ~+2.9 against the +1.0
    threshold (re-tuned on jax 0.4.x after the ring-size root fix — the
    size-12 ring gave random predators ~4.6 of the ~4.95 ceiling, so no
    amount of learning could show a gain)."""
    from ray_tpu.rl import MultiAgentPPOConfig

    cfg = MultiAgentPPOConfig(
        env="ChaseGame", policies=("predator", "prey"),
        policy_mapping={"pred0": "predator", "pred1": "predator",
                        "prey": "prey"},
        rollout_len=256, lr=1e-3, hidden=32, seed=0)
    algo = cfg.build()
    try:
        first = algo.step()
        for _ in range(15):
            m = algo.step()
        assert m["predator/episode_return_mean"] > \
            first["predator/episode_return_mean"] + 1.0, (first, m)
        # zero-sum coupling between the two policies' returns
        assert abs(m["predator/episode_return_mean"]
                   + m["prey/episode_return_mean"]) < 0.7
        env = algo._runner.env
        assert env.captures > 0 and env.episodes >= env.captures
    finally:
        algo.cleanup()


def test_appo_solves_cartpole_inline():
    """Clipped-surrogate async PPO learns CartPole through the IMPALA
    machinery (reference: rllib APPO CartPole runs)."""
    from ray_tpu.rl import APPOConfig

    algo = APPOConfig(num_envs_per_runner=8, rollout_len=64, lr=5e-4,
                      clip_eps=0.3, seed=0).build()
    best = 0.0
    for _ in range(120):
        r = algo.train_step()
        best = max(best, r["episode_return_mean"])
        if best >= 150.0:
            break
    algo.cleanup()
    assert best >= 150.0, f"APPO failed to learn CartPole: best {best}"


def test_appo_async_runners(rt_start):
    """APPO inherits IMPALA's async runner protocol unchanged."""
    from ray_tpu.rl import APPOConfig

    algo = APPOConfig(num_env_runners=2, num_envs_per_runner=4,
                      rollout_len=16, rollouts_per_step=2,
                      max_staleness=1, seed=1).build()
    try:
        r1 = algo.train_step()
        r2 = algo.train_step()
        assert r2["weight_version"] >= r1["weight_version"] >= 1
        assert "policy_loss" in r2
        assert len(algo._inflight) == 2
    finally:
        algo.cleanup()


def test_cql_conservative_offline(rt_start):
    """CQL learns from mixed-quality offline data AND keeps Q-values of
    out-of-distribution actions below in-distribution ones (the
    conservative property the regularizer exists for); with alpha=0 the
    gap collapses toward plain TD behaviour (reference: rllib CQL)."""
    import ray_tpu.data as rdata
    from ray_tpu.rl import CQLConfig
    from ray_tpu.rl.env import CartPoleEnv
    from ray_tpu.rl.ppo import mlp_apply

    # Offline transitions: expert controller with 20% random actions.
    env = CartPoleEnv(seed=0)
    rng = np.random.default_rng(0)
    obs_l, act_l, rew_l, nxt_l, done_l = [], [], [], [], []
    for ep in range(40):
        obs = env.reset()
        done, steps = False, 0
        while not done and steps < 200:
            expert = 1 if (obs[2] + 0.5 * obs[3]) > 0 else 0
            a = int(rng.integers(2)) if rng.random() < 0.2 else expert
            nobs, r, term, trunc = env.step(a)
            obs_l.append(np.asarray(obs, np.float32)); act_l.append(a)
            rew_l.append(r); nxt_l.append(np.asarray(nobs, np.float32))
            done_l.append(float(term))
            obs = nobs
            done = term or trunc
            steps += 1
    ds = rdata.from_blocks([{
        "obs": np.stack(obs_l), "actions": np.asarray(act_l, np.int32),
        "rewards": np.asarray(rew_l, np.float32), "next_obs": np.stack(nxt_l),
        "dones": np.asarray(done_l, np.float32)}])

    algo = CQLConfig(dataset=ds, alpha=1.0, epochs_per_step=2,
                     evaluation_episodes=3, seed=0).build()
    last = None
    for _ in range(6):
        last = algo.train_step()
    assert last["num_samples_trained"] > 0
    # Conservative property is RELATIVE: the regularizer drives the
    # logsumexp gap (how far non-data actions sit above the data action)
    # below what plain TD (alpha=0) leaves on the same budget. (The gap
    # has a log(num_actions) floor, so no absolute threshold.)
    algo_td = CQLConfig(dataset=ds, alpha=0.0, epochs_per_step=2,
                        seed=0).build()
    for _ in range(6):
        base = algo_td.train_step()
    assert base["conservative_gap"] > last["conservative_gap"], (
        base, last)
    # The learned greedy policy is usable (mixed data still balances a bit)
    assert last["episode_return_mean"] > 50.0, last
    # checkpoint round-trips
    ckpt = algo.save_checkpoint()
    algo.load_checkpoint(ckpt)
    q = mlp_apply(algo.params, np.zeros((1, 4), np.float32))
    assert np.asarray(q).shape == (1, 2)


def test_dreamer_learns_cartpole_from_imagination():
    """Model-based RL (reference: rllib/algorithms/dreamerv3/): the world
    model + imagination-trained actor-critic beats the random-policy
    return (~20) on CartPole within a seed-pinned CI budget. The run is
    fully deterministic (seeded env/JAX/numpy), so the pinned trajectory
    reproduces. (Re-tuned on jax 0.4.x: latent=8 / free_bits=0.3 defaults
    — see DreamerConfig — lift the last-6 peak from ~22 to ~52 against
    the 30.0 threshold.)"""
    from ray_tpu.rl import DreamerConfig

    algo = DreamerConfig(env="CartPole-v1", seed=0).build()
    returns = [algo.step()["episode_return_mean"] for _ in range(24)]
    assert max(returns[-6:]) >= 30.0, returns
    assert max(returns[-6:]) > returns[0], returns
    ckpt = algo.save_checkpoint()
    algo.load_checkpoint(ckpt)


def test_marwil_offline_mixed_quality_data():
    """MARWIL (reference: rllib marwil.py): advantage-weighted imitation
    recovers a strong policy from a mixed-quality offline dataset, and the
    exponentiated-advantage weights demonstrably upweight
    better-than-baseline actions."""
    import ray_tpu.data as rdata
    from ray_tpu.rl import MARWILConfig
    from ray_tpu.rl.env import CartPoleEnv

    env = CartPoleEnv(seed=0)
    rng = np.random.default_rng(0)
    obs_rows, act_rows, ret_rows = [], [], []
    for ep in range(60):
        obs = env.reset()
        done, steps = False, 0
        ep_obs, ep_act, ep_rew = [], [], []
        scripted = ep % 5 == 0  # 1-in-5 expert-ish, rest biased-random
        while not done and steps < 200:
            if scripted:
                a = 1 if (obs[2] + 0.5 * obs[3]) > 0 else 0
            else:
                # Biased junk: plain BC imitates the majority's bias.
                a = int(rng.random() < 0.25)
            ep_obs.append(np.asarray(obs, np.float32))
            ep_act.append(a)
            obs, r, term, trunc = env.step(a)
            ep_rew.append(r)
            done = term or trunc
            steps += 1
        # Monte-Carlo returns-to-go.
        g = 0.0
        rets = []
        for r in reversed(ep_rew):
            g = r + 0.99 * g
            rets.append(g)
        rets.reverse()
        obs_rows += ep_obs
        act_rows += ep_act
        ret_rows += rets
    ds = rdata.from_blocks([{"obs": np.stack(obs_rows),
                             "actions": np.asarray(act_rows, np.int32),
                             "returns": np.asarray(ret_rows, np.float32)}])

    algo = MARWILConfig(dataset=ds, beta=1.0, epochs_per_step=4,
                        evaluation_episodes=5, seed=0).build()
    last = None
    for _ in range(6):
        last = algo.step()
    ckpt = algo.save_checkpoint()
    algo.load_checkpoint(ckpt)
    # Strong policy from a dataset that is 80% biased junk.
    assert last["episode_return_mean"] > 150.0, last

    # The advantage weighting itself: high-return-to-go samples carry
    # larger imitation weights than low ones through the trained critic.
    import jax.numpy as jnp
    from ray_tpu.rl.ppo import mlp_apply

    obs_all = jnp.asarray(np.stack(obs_rows))
    rets_all = np.asarray(ret_rows, np.float32)
    v = np.asarray(mlp_apply(algo.params["vf"], obs_all)[..., 0])
    adv = rets_all - v
    hi, lo = adv > np.quantile(adv, 0.9), adv < np.quantile(adv, 0.1)
    norm = float(np.maximum(np.sqrt(np.asarray(algo.ma_adv_norm)), 1e-3))
    w = np.clip(np.exp(1.0 * adv / norm), 0.0, 20.0)
    assert w[hi].mean() > 2.0 * w[lo].mean(), (w[hi].mean(), w[lo].mean())


def test_connector_pipeline_units():
    """Connector framework (reference: rllib/connectors/): composable
    stateful transforms with checkpointable state."""
    from ray_tpu.rl import (ConnectorPipeline, FrameStack,
                            NormalizeObservations, UnsquashActions)

    norm = NormalizeObservations()
    stack = FrameStack(k=3)
    pipe = ConnectorPipeline([norm, stack])
    assert pipe.output_multiplier == 3

    rng = np.random.default_rng(0)
    x = rng.normal(5.0, 2.0, size=(4, 2)).astype(np.float32)
    out = pipe(x)
    assert out.shape == (4, 6)
    for _ in range(200):
        pipe(rng.normal(5.0, 2.0, size=(4, 2)).astype(np.float32))
    y = rng.normal(5.0, 2.0, size=(4, 2)).astype(np.float32)
    normed = norm(y)
    assert abs(float(normed.mean())) < 1.0  # centered-ish
    assert 0.2 < float(normed.std()) < 2.0

    # Frozen application (bootstrap obs) must not advance the stack.
    stack_before = stack.state_dict()["buf"].copy()
    stack.frozen = True
    stack(y)
    stack.frozen = False
    np.testing.assert_array_equal(stack.state_dict()["buf"], stack_before)

    # reset defers a refill: the next pushed observation fills ALL of
    # that env's frames (reference behavior), other envs keep history.
    stack.reset(1)
    nxt = rng.normal(5.0, 2.0, size=(4, 2)).astype(np.float32)
    stack(nxt)
    buf = stack.state_dict()["buf"]
    assert (buf[1] == nxt[1]).all()
    assert not (buf[0, :-1] == buf[0, -1]).all()

    # state round-trips.
    st = pipe.state_dict()
    pipe2 = ConnectorPipeline([NormalizeObservations(), FrameStack(k=3)])
    pipe2.set_state(st)
    np.testing.assert_allclose(pipe2.connectors[0]._mean, norm._mean)

    u = UnsquashActions(limit=2.0)
    np.testing.assert_allclose(u(np.array([-1.5, 0.5, 1.0])),
                               [-2.0, 1.0, 2.0])


def test_connector_state_rides_ppo_checkpoints():
    """Checkpoint round-trip carries the runner's connector state — a
    policy trained behind a running normalizer restores with its
    statistics (reference: connector state in algorithm checkpoints)."""
    from ray_tpu.rl import (ConnectorPipeline, NormalizeObservations,
                            PPOConfig)

    def connector_factory():
        return ConnectorPipeline([NormalizeObservations()]), None

    algo = PPOConfig(env="CartPole-v1", rollout_len=64, seed=0,
                     connector_factory=connector_factory).build()
    algo.step()
    ckpt = algo.save_checkpoint()
    norm_state = ckpt["connector_state"]["env_to_module"][0]
    assert norm_state["count"] > 100

    algo2 = PPOConfig(env="CartPole-v1", rollout_len=64, seed=0,
                      connector_factory=connector_factory).build()
    algo2.load_checkpoint(ckpt)
    restored = algo2.runners.connector_state()["env_to_module"][0]
    np.testing.assert_allclose(restored["mean"], norm_state["mean"])
    assert restored["count"] == norm_state["count"]


def test_ppo_with_connector_pipeline_solves_cartpole():
    """End-to-end: PPO through env-to-module connectors (normalize +
    frame-stack, widened policy input) still reaches a solid CartPole
    return — the transforms run inside the EnvRunner sampling path."""
    from ray_tpu.rl import (ConnectorPipeline, FrameStack,
                            NormalizeObservations, PPOConfig)

    def connector_factory():
        return (ConnectorPipeline([NormalizeObservations(),
                                   FrameStack(k=2)]), None)

    algo = PPOConfig(env="CartPole-v1", rollout_len=128, seed=0,
                     connector_factory=connector_factory).build()
    best = 0.0
    for _ in range(30):
        m = algo.step()
        best = max(best, m.get("episode_return_mean", 0.0))
        if best > 150:
            break
    assert best > 150, best
