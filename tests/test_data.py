"""ray_tpu.data tests (reference test strategy: python/ray/data/tests —
transform correctness, shuffle ops, iteration, splits, IO round-trips)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rd


@pytest.fixture
def rt(rt_start):
    yield rt_start


def test_range_count_take(rt):
    ds = rd.range(100)
    assert ds.count() == 100
    rows = ds.take(5)
    assert rows == [{"id": i} for i in range(5)]


def test_from_items_rows(rt):
    ds = rd.from_items([{"a": 1}, {"a": 2}, {"a": 3}])
    assert ds.take_all() == [{"a": 1}, {"a": 2}, {"a": 3}]
    ds2 = rd.from_items([10, 20])
    assert ds2.take_all() == [{"item": 10}, {"item": 20}]


def test_map_filter_flat_map_fusion(rt):
    ds = (
        rd.range(50)
        .map(lambda r: {"id": r["id"] * 2})
        .filter(lambda r: r["id"] % 4 == 0)
        .flat_map(lambda r: [r, r])
    )
    rows = ds.take_all()
    vals = [r["id"] for r in rows]
    expect = [v for v in range(0, 100, 2) if v % 4 == 0 for _ in (0, 1)]
    assert sorted(vals) == sorted(expect)


def test_map_batches_numpy(rt):
    ds = rd.range(32).map_batches(
        lambda b: {"id": b["id"], "sq": b["id"] ** 2}, batch_size=10
    )
    rows = ds.take_all()
    assert all(r["sq"] == r["id"] ** 2 for r in rows)
    assert len(rows) == 32


def test_map_batches_pandas_format(rt):
    def add_col(df):
        df = df.copy()
        df["y"] = df["id"] + 1
        return df

    ds = rd.range(10).map_batches(add_col, batch_format="pandas")
    rows = ds.take_all()
    assert all(r["y"] == r["id"] + 1 for r in rows)


def test_map_batches_actor_pool(rt):
    class AddState:
        def __init__(self):
            self.offset = 100

        def __call__(self, batch):
            return {"id": batch["id"] + self.offset}

    ds = rd.range(20).map_batches(
        AddState, compute=rd.ActorPoolStrategy(size=2, num_cpus=0.5)
    )
    vals = sorted(r["id"] for r in ds.take_all())
    assert vals == list(range(100, 120))


def test_columns_ops(rt):
    ds = rd.from_items([{"a": 1, "b": 2}, {"a": 3, "b": 4}])
    assert ds.select_columns(["a"]).take_all() == [{"a": 1}, {"a": 3}]
    assert ds.drop_columns(["b"]).take_all() == [{"a": 1}, {"a": 3}]
    renamed = ds.rename_columns({"a": "x"}).take_all()
    assert renamed == [{"x": 1, "b": 2}, {"x": 3, "b": 4}]
    with_c = ds.add_column("c", lambda blk: blk["a"] + blk["b"]).take_all()
    assert [r["c"] for r in with_c] == [3, 7]


def test_limit_streaming(rt):
    ds = rd.range(1000).limit(17)
    assert ds.count() == 17
    assert [r["id"] for r in ds.take_all()] == list(range(17))


def test_sort(rt):
    rng = np.random.default_rng(0)
    vals = rng.permutation(200).tolist()
    ds = rd.from_items([{"v": v} for v in vals]).sort("v")
    out = [r["v"] for r in ds.take_all()]
    assert out == sorted(vals)
    out_desc = [
        r["v"]
        for r in rd.from_items([{"v": v} for v in vals])
        .sort("v", descending=True)
        .take_all()
    ]
    assert out_desc == sorted(vals, reverse=True)


def test_random_shuffle(rt):
    ds = rd.range(100).random_shuffle(seed=42)
    vals = [r["id"] for r in ds.take_all()]
    assert sorted(vals) == list(range(100))
    assert vals != list(range(100))


def test_repartition(rt):
    ds = rd.range(100, parallelism=10).repartition(3)
    mat = ds.materialize()
    assert mat.num_blocks() == 3
    assert mat.count() == 100
    assert sorted(r["id"] for r in mat.take_all()) == list(range(100))


def test_groupby_aggregate(rt):
    rows = [{"k": i % 3, "v": float(i)} for i in range(30)]
    ds = rd.from_items(rows).groupby("k").sum("v")
    out = {r["k"]: r["sum(v)"] for r in ds.take_all()}
    expect = {}
    for r in rows:
        expect[r["k"]] = expect.get(r["k"], 0.0) + r["v"]
    assert out == expect


def test_groupby_count_mean(rt):
    rows = [{"k": "a" if i < 10 else "b", "v": i} for i in range(25)]
    out = rd.from_items(rows).groupby("k").count().take_all()
    counts = {r["k"]: r["count()"] for r in out}
    assert counts == {"a": 10, "b": 15}
    means = {
        r["k"]: r["mean(v)"]
        for r in rd.from_items(rows).groupby("k").mean("v").take_all()
    }
    assert means["a"] == pytest.approx(4.5)
    assert means["b"] == pytest.approx(np.mean(np.arange(10, 25)))


def test_global_aggregates(rt):
    ds = rd.range(100)
    assert ds.sum("id") == 4950
    assert ds.min("id") == 0
    assert ds.max("id") == 99
    assert ds.mean("id") == pytest.approx(49.5)
    assert ds.std("id") == pytest.approx(np.std(np.arange(100), ddof=1))


def test_iter_batches(rt):
    ds = rd.range(100)
    batches = list(ds.iter_batches(batch_size=32))
    sizes = [len(b["id"]) for b in batches]
    assert sizes == [32, 32, 32, 4]
    assert np.concatenate([b["id"] for b in batches]).tolist() == list(range(100))
    batches = list(ds.iter_batches(batch_size=32, drop_last=True))
    assert [len(b["id"]) for b in batches] == [32, 32, 32]


def test_split(rt):
    parts = rd.range(90).split(3)
    assert [p.count() for p in parts] == [30, 30, 30]
    allv = sorted(r["id"] for p in parts for r in p.take_all())
    assert allv == list(range(90))


def test_streaming_split(rt):
    its = rd.range(60, parallelism=6).streaming_split(2)
    a = [r["id"] for r in its[0].iter_rows()]
    b = [r["id"] for r in its[1].iter_rows()]
    assert sorted(a + b) == list(range(60))
    assert a and b


def test_union_zip(rt):
    u = rd.range(5).union(rd.range(5))
    assert sorted(r["id"] for r in u.take_all()) == sorted(
        list(range(5)) * 2
    )
    z = rd.from_items([{"a": 1}, {"a": 2}]).zip(
        rd.from_items([{"b": 10}, {"b": 20}])
    )
    assert z.take_all() == [{"a": 1, "b": 10}, {"a": 2, "b": 20}]


def test_parquet_roundtrip(rt, tmp_path):
    ds = rd.range(50).map(lambda r: {"id": r["id"], "x": r["id"] * 0.5})
    files = ds.write_parquet(str(tmp_path / "out"))
    assert files
    back = rd.read_parquet(str(tmp_path / "out"))
    assert back.count() == 50
    assert back.sum("id") == ds.sum("id")


def test_csv_json_roundtrip(rt, tmp_path):
    ds = rd.from_items([{"a": 1, "b": "x"}, {"a": 2, "b": "y"}])
    ds.write_csv(str(tmp_path / "csv"))
    back = rd.read_csv(str(tmp_path / "csv"))
    assert sorted(back.take_all(), key=lambda r: r["a"]) == ds.take_all()
    ds.write_json(str(tmp_path / "json"))
    back = rd.read_json(str(tmp_path / "json"))
    assert sorted(back.take_all(), key=lambda r: r["a"]) == ds.take_all()


def test_schema_and_to_pandas(rt):
    ds = rd.range(10)
    assert "id" in ds.schema()
    df = ds.to_pandas()
    assert len(df) == 10
    assert df["id"].tolist() == list(range(10))


def test_map_groups(rt):
    rows = [{"k": i % 4, "v": float(i)} for i in range(40)]

    def norm(group):
        return {"k": group["k"], "v": group["v"] - group["v"].mean()}

    out = rd.from_items(rows).groupby("k").map_groups(norm).take_all()
    assert len(out) == 40
    by_k = {}
    for r in out:
        by_k.setdefault(r["k"], []).append(r["v"])
    for vs in by_k.values():
        assert np.mean(vs) == pytest.approx(0.0, abs=1e-9)


# ---------------------------------------------------------------------------
# joins / prefetch / batch llm (reference: join.py, iter_torch_batches,
# ray.data.llm batch inference)
# ---------------------------------------------------------------------------

def test_inner_join(rt_start):
    import ray_tpu.data as rd

    left = rd.from_items([{"id": i, "a": i * 10} for i in range(8)])
    right = rd.from_items([{"id": i, "b": i * 100} for i in range(4, 12)])
    out = sorted(left.join(right, on="id").take_all(),
                 key=lambda r: r["id"])
    assert [r["id"] for r in out] == [4, 5, 6, 7]
    assert all(r["b"] == r["id"] * 100 and r["a"] == r["id"] * 10
               for r in out)


def test_left_join_fills_misses(rt_start):
    import numpy as np

    import ray_tpu.data as rd

    left = rd.from_items([{"id": i, "a": i} for i in range(4)])
    right = rd.from_items([{"id": 1, "b": 11.0}, {"id": 3, "b": 33.0}])
    out = sorted(left.join(right, on="id", how="left").take_all(),
                 key=lambda r: r["id"])
    assert [r["id"] for r in out] == [0, 1, 2, 3]
    assert out[1]["b"] == 11.0 and out[3]["b"] == 33.0
    assert np.isnan(out[0]["b"]) and np.isnan(out[2]["b"])


def test_join_column_collision_suffix(rt_start):
    import ray_tpu.data as rd

    left = rd.from_items([{"id": 1, "v": "L"}])
    right = rd.from_items([{"id": 1, "v": "R"}])
    row = left.join(right, on="id").take_all()[0]
    assert row["v"] == "L" and row["v_r"] == "R"


def test_iter_jax_batches_prefetch(rt_start):
    import jax

    import ray_tpu.data as rd

    ds = rd.from_items([{"x": float(i)} for i in range(64)])
    seen = 0
    for batch in ds.iter_jax_batches(batch_size=16, prefetch=2):
        assert isinstance(batch["x"], jax.Array)
        seen += batch["x"].shape[0]
    assert seen == 64


def test_batch_llm_inference(rt_start):
    import ray_tpu.data as rd
    from ray_tpu.data.llm import ProcessorConfig, build_llm_processor
    from ray_tpu.llm import LLMConfig

    processor = build_llm_processor(
        LLMConfig(model="tiny", max_num_seqs=2, max_seq_len=64),
        config=ProcessorConfig(batch_size=4, concurrency=1,
                               sampling={"max_tokens": 3,
                                         "temperature": 0.0}))
    ds = rd.from_items([{"prompt": f"say {i}"} for i in range(6)])
    rows = processor(ds).take_all()
    assert len(rows) == 6
    assert all(isinstance(r["generated_text"], str) for r in rows)
    assert all(r["num_generated_tokens"] >= 1 for r in rows)


def test_stable_hash_is_process_independent(rt_start):
    """Partition hashing must agree across worker processes (Python hash()
    is SipHash-salted per interpreter)."""
    import subprocess
    import sys

    import numpy as np

    from ray_tpu.data.shuffle import _stable_hash

    here = _stable_hash(np.arange(16)).tolist()
    code = (
        "import numpy as np, json, sys\n"
        "sys.path.insert(0, '/root/repo')\n"
        "from ray_tpu.data.shuffle import _stable_hash\n"
        "print(json.dumps(_stable_hash(np.arange(16)).tolist()))\n"
    )
    out = subprocess.run([sys.executable, "-c", code], capture_output=True,
                         text=True, env={"PYTHONHASHSEED": "random",
                                         "PATH": "/usr/bin:/bin",
                                         "JAX_PLATFORMS": "cpu"})
    import json as _json

    assert _json.loads(out.stdout) == here
    # strings too
    s_here = _stable_hash(np.asarray(["a", "bb", "ccc"], object)).tolist()
    assert s_here == _stable_hash(np.asarray(["a", "bb", "ccc"],
                                             object)).tolist()


def test_device_prefetch_early_break_releases_producer(rt_start):
    import threading
    import time

    import ray_tpu.data as rd

    before = {t.name for t in threading.enumerate()}
    ds = rd.from_items([{"x": float(i)} for i in range(512)])
    for batch in ds.iter_jax_batches(batch_size=8, prefetch=2):
        break  # abandon early
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        alive = [t for t in threading.enumerate()
                 if t.name == "data-device-prefetch" and t.is_alive()]
        if not alive:
            break
        time.sleep(0.1)
    assert not [t for t in threading.enumerate()
                if t.name == "data-device-prefetch" and t.is_alive()]


def test_iter_torch_batches(rt_start):
    import torch

    import ray_tpu.data as rdata

    ds = rdata.range(10)
    batches = list(ds.iter_torch_batches(batch_size=4,
                                         dtypes=torch.float32))
    assert all(isinstance(b["id"], torch.Tensor) for b in batches)
    assert batches[0]["id"].dtype == torch.float32
    got = torch.cat([b["id"] for b in batches]).tolist()
    assert sorted(got) == [float(i) for i in range(10)]


def test_from_huggingface(rt_start):
    import datasets as hf

    import ray_tpu.data as rdata

    hfds = hf.Dataset.from_dict({"x": list(range(12)),
                                 "y": [i * 2 for i in range(12)]})
    ds = rdata.from_huggingface(hfds, rows_per_block=5)
    rows = sorted((int(r["x"]), int(r["y"])) for r in ds.iter_rows())
    assert rows == [(i, 2 * i) for i in range(12)]
    assert ds.count() == 12


def test_read_images(tmp_path):
    """read_images decodes a directory of PNG/JPEG into image/path columns
    (reference: ray.data.read_images, datasource/image_datasource.py)."""
    from PIL import Image
    import ray_tpu.data as rdata

    for i in range(6):
        arr = np.full((8 + i, 10, 3), i * 20, dtype=np.uint8)
        Image.fromarray(arr).save(tmp_path / f"img{i}.png")
    Image.fromarray(np.zeros((4, 4, 3), np.uint8)).save(
        tmp_path / "extra.jpg")

    # Variable-size read: object column, original shapes preserved.
    ds = rdata.read_images(str(tmp_path))
    rows = ds.take_all()
    assert len(rows) == 7
    shapes = {r["image"].shape for r in rows}
    assert (8, 10, 3) in shapes and (4, 4, 3) in shapes
    assert all(r["path"].endswith((".png", ".jpg")) for r in rows)

    # Resized read: dense batches of uniform shape.
    ds = rdata.read_images(str(tmp_path), size=(16, 12))
    batch = next(iter(ds.iter_batches(batch_size=7)))
    assert batch["image"].shape == (7, 16, 12, 3)
    assert batch["image"].dtype == np.uint8


def test_multimodal_ingest_to_trainer(tmp_path):
    """Images feed the trainer ingest path end-to-end: read_images →
    map (label from path) → streaming_split over 2 train workers."""
    from PIL import Image
    import ray_tpu.data as rdata
    from ray_tpu.train import JaxTrainer
    from ray_tpu.train.config import RunConfig, ScalingConfig

    img_dir = tmp_path / "imgs"
    img_dir.mkdir()
    for i in range(8):
        arr = np.full((6, 6, 3), i, dtype=np.uint8)
        Image.fromarray(arr).save(img_dir / f"class{i % 2}_{i}.png")

    def loop(config):
        from ray_tpu.train import get_dataset_shard, session

        it = get_dataset_shard("train")
        n, px = 0, 0.0
        for batch in it.iter_batches(batch_size=4):
            imgs = batch["image"]
            n += len(imgs)
            px += float(np.sum(imgs[..., 0], dtype=np.float64))
        session.report({"n": n, "px": px})

    ray_tpu.init(num_cpus=4)
    try:
        ds = rdata.read_images(str(img_dir), size=(6, 6))
        trainer = JaxTrainer(
            loop, datasets={"train": ds},
            scaling_config=ScalingConfig(num_workers=2),
            run_config=RunConfig(name="mm", storage_path=str(tmp_path)))
        result = trainer.fit()
        assert result.ok, result.error
        reports = result.metrics_history
        assert sum(r["n"] for r in reports) == 8
        # every pixel value accounted for across the split
        assert sum(r["px"] for r in reports) == sum(i * 36 for i in range(8))
    finally:
        ray_tpu.shutdown()


def test_read_tfrecords(tmp_path):
    """TFRecord framing + tf.train.Example wire decoding with no tensorflow
    dependency (reference: ray.data.read_tfrecords /
    datasource/tfrecords_datasource.py): round-trips bytes/float/int64
    features, validates framing CRCs, and supports raw payload mode."""
    import ray_tpu.data as rdata
    from ray_tpu.data.tfrecord import (
        crc32c, encode_example, write_records)

    # crc32c known-answer check (RFC 3720 test vector)
    assert crc32c(b"123456789") == 0xE3069283

    recs = [encode_example({
        "label": [i - 2], "weight": [0.5 * i, 1.5],
        "name": f"row{i}".encode(), "blob": b"ab\x00",  # trailing NUL
    }) for i in range(5)]
    write_records(str(tmp_path / "a.tfrecord"), recs[:3])
    write_records(str(tmp_path / "b.tfrecord"), recs[3:])

    ds = rdata.read_tfrecords(str(tmp_path), validate_data_crc=True)
    rows = sorted(ds.take_all(), key=lambda r: r["label"])
    assert len(rows) == 5
    # negative int64s survive the varint two's-complement round trip
    assert [int(r["label"]) for r in rows] == [-2, -1, 0, 1, 2]
    np.testing.assert_allclose(rows[2]["weight"], [1.0, 1.5], rtol=1e-6)
    assert rows[4]["name"] == b"row4"
    # binary payloads keep trailing NULs (no numpy 'S' densification)
    assert rows[0]["blob"] == b"ab\x00"

    # raw mode: framing only, payload untouched
    raw = rdata.read_tfrecords(str(tmp_path / "a.tfrecord"),
                               raw=True).take_all()
    assert [r["data"] for r in raw] == recs[:3]

    # corrupt framing is rejected
    blob = (tmp_path / "a.tfrecord").read_bytes()
    (tmp_path / "bad.tfrecord").write_bytes(blob[:8] + b"\x00\x00\x00\x00"
                                            + blob[12:])
    with pytest.raises(Exception, match="crc"):
        rdata.read_tfrecords(str(tmp_path / "bad.tfrecord")).take_all()


def test_read_sql_and_write_sql(rt, tmp_path):
    """DB-API round trip via stdlib sqlite3 (reference capability:
    ray.data.read_sql / Dataset.write_sql)."""
    import sqlite3

    db = str(tmp_path / "t.db")

    def factory(db=db):
        conn = sqlite3.connect(db, timeout=30)
        return conn

    conn = factory()
    conn.execute("CREATE TABLE items (id INTEGER, name TEXT, score REAL)")
    conn.executemany("INSERT INTO items VALUES (?, ?, ?)",
                     [(i, f"n{i}", i * 0.5) for i in range(20)])
    conn.commit()
    conn.close()

    ds = rd.read_sql("SELECT * FROM items", factory)
    rows = sorted(ds.take_all(), key=lambda r: r["id"])
    assert len(rows) == 20
    assert rows[3] == {"id": 3, "name": "n3", "score": 1.5}

    # sharded read: 4 range-partitioned tasks cover every row exactly once
    sharded = rd.read_sql("SELECT * FROM items", factory,
                          shard_column="id", num_shards=4)
    assert len(sharded.materialize()._refs_meta) == 4
    srows = sorted(sharded.take_all(), key=lambda r: r["id"])
    assert [r["id"] for r in srows] == list(range(20))

    # rows with a NULL shard key ride the first shard, never dropped
    conn = factory()
    conn.execute("INSERT INTO items VALUES (NULL, 'nk', 0.25)")
    conn.commit(); conn.close()
    with_null = rd.read_sql("SELECT * FROM items", factory,
                            shard_column="id", num_shards=4).take_all()
    assert len(with_null) == 21
    assert any(r["id"] is None for r in with_null)

    # int64-range shard keys (snowflake ids, ns timestamps): bounds must
    # stay exact integers — float bounds round above 2**53 and silently
    # drop the MIN rows from every shard's predicate.
    conn = factory()
    conn.execute("CREATE TABLE big (id INTEGER, name TEXT)")
    big_ids = [2**63 - 3, 2**63 - 2, 2**63 - 1]
    conn.executemany("INSERT INTO big VALUES (?, ?)",
                     [(i, f"b{i}") for i in big_ids])
    conn.commit(); conn.close()
    big = rd.read_sql("SELECT * FROM big", factory,
                      shard_column="id", num_shards=2).take_all()
    assert sorted(r["id"] for r in big) == big_ids

    # non-numeric shard columns are rejected loudly, not silently wrong
    with pytest.raises(Exception, match="numeric"):
        rd.read_sql("SELECT * FROM items WHERE id IS NOT NULL", factory,
                    shard_column="name", num_shards=2).take_all()

    # write back: filtered rows into a second table
    conn = factory()
    conn.execute("CREATE TABLE high (id INTEGER, name TEXT, score REAL)")
    conn.commit()
    conn.close()
    n = (rd.read_sql("SELECT * FROM items", factory)
         .filter(lambda r: r["score"] >= 5.0)
         .write_sql("INSERT INTO high VALUES (?, ?, ?)", factory))
    assert n == 10
    conn = factory()
    got = conn.execute("SELECT COUNT(*), MIN(score) FROM high").fetchone()
    conn.close()
    assert got == (10, 5.0)


def test_read_webdataset(rt, tmp_path):
    """Tar shards grouped into samples by key prefix (reference:
    ray.data.read_webdataset)."""
    import io
    import json
    import tarfile

    def add(tf, name, data: bytes):
        info = tarfile.TarInfo(name)
        info.size = len(data)
        tf.addfile(info, io.BytesIO(data))

    for shard, rng in [("s0.tar", range(3)), ("s1.tar", range(3, 5))]:
        with tarfile.open(tmp_path / shard, "w") as tf:
            for i in rng:
                add(tf, f"sample{i:04d}.caption.txt",
                    f"caption {i}".encode())
                add(tf, f"sample{i:04d}.cls", str(i % 2).encode())
                add(tf, f"sample{i:04d}.json",
                    json.dumps({"idx": i}).encode())

    ds = rd.read_webdataset(str(tmp_path))
    rows = sorted(ds.take_all(), key=lambda r: r["__key__"])
    assert len(rows) == 5
    # multi-part extension: column named by full ext, decoded by last part
    assert rows[0]["caption.txt"] == "caption 0"
    assert rows[0]["cls"] == 0 and rows[1]["cls"] == 1  # ints when parseable
    assert rows[4]["json"] == {"idx": 4}
    # one read task per shard
    assert len(rd.read_webdataset(str(tmp_path)).materialize()._refs_meta) == 2

    # directory-scoped keys: train/0001 and val/0001 are DIFFERENT samples
    # (basename-only keys would silently merge them)
    with tarfile.open(tmp_path / "s2.tar", "w") as tf:
        add(tf, "train/0001.txt", b"train one")
        add(tf, "val/0001.txt", b"val one")
    scoped = rd.read_webdataset(str(tmp_path / "s2.tar")).take_all()
    assert sorted(r["__key__"] for r in scoped) == ["train/0001", "val/0001"]
    assert sorted(r["txt"] for r in scoped) == ["train one", "val one"]


def test_read_webdataset_images(rt, tmp_path):
    import io
    import tarfile

    PIL = pytest.importorskip("PIL.Image")
    buf = io.BytesIO()
    PIL.fromarray(np.full((4, 6, 3), 7, np.uint8)).save(buf, format="PNG")
    png = buf.getvalue()
    with tarfile.open(tmp_path / "img.tar", "w") as tf:
        info = tarfile.TarInfo("a.png")
        info.size = len(png)
        tf.addfile(info, io.BytesIO(png))

    row = rd.read_webdataset(str(tmp_path / "img.tar")).take_all()[0]
    assert row["png"].shape == (4, 6, 3) and int(row["png"][0, 0, 0]) == 7
    raw = rd.read_webdataset(str(tmp_path / "img.tar"),
                             decode_images=False).take_all()[0]
    assert raw["png"] == png


def test_read_mongo_with_injected_client(rt):
    """read_mongo via an injected pymongo-shaped client (reference:
    ray.data.read_mongo) — pipeline pushdown + skip/limit sharding."""
    docs = [{"_id": i, "name": f"d{i}", "score": i * 1.5,
             "tags": ["a", "b", "c"]} for i in range(10)]

    def _match_one(d, flt):
        for k, v in flt.items():
            if isinstance(v, dict):  # operator form: {$gte: a, $lt: b}
                if "$gte" in v and not d.get(k) >= v["$gte"]:
                    return False
                if "$lt" in v and not d.get(k) < v["$lt"]:
                    return False
            elif d.get(k) != v:
                return False
        return True

    class FakeColl:
        def aggregate(self, stages):
            out = list(docs)
            for st in stages:
                if "$match" in st:
                    out = [d for d in out if _match_one(d, st["$match"])]
                elif "$unwind" in st:
                    field = st["$unwind"].lstrip("$")
                    out = [{**d, field: x} for d in out for x in d[field]]
                elif "$sort" in st:
                    (k, direc), = st["$sort"].items()
                    out = sorted(out, key=lambda d: d[k],
                                 reverse=direc < 0)
                elif "$skip" in st:
                    out = out[st["$skip"]:]
                elif "$limit" in st:
                    out = out[:st["$limit"]]
                elif "$project" in st:
                    keep = [k for k, v in st["$project"].items() if v]
                    out = [{k: d[k] for k in keep if k in d} for d in out]
                elif "$count" in st:
                    out = [{st["$count"]: len(out)}]
            return iter(out)

        def count_documents(self, flt):
            return len(docs)

    class FakeDB(dict):
        def __getitem__(self, k):
            return FakeColl()

    class FakeClient(dict):
        def __getitem__(self, k):
            return FakeDB()
        def close(self):
            pass

    ds = rd.read_mongo("mongodb://fake", "db", "c",
                       client_factory=FakeClient)
    rows = sorted(ds.take_all(), key=lambda r: r["_id"])
    assert len(rows) == 10 and rows[3]["name"] == "d3"

    sharded = rd.read_mongo("mongodb://fake", "db", "c",
                            client_factory=FakeClient, num_shards=3)
    assert len(sharded.materialize()._refs_meta) == 3
    assert sorted(r["_id"] for r in sharded.take_all()) == list(range(10))

    # more shards than documents: empty boundaries must not duplicate rows
    over = rd.read_mongo("mongodb://fake", "db", "c",
                         client_factory=FakeClient, num_shards=12)
    assert sorted(r["_id"] for r in over.take_all()) == list(range(10))

    piped = rd.read_mongo("mongodb://fake", "db", "c",
                          pipeline=[{"$match": {"name": "d7"}}],
                          client_factory=FakeClient).take_all()
    assert [r["_id"] for r in piped] == [7]

    # cardinality-changing pipeline + sharding is rejected LOUDLY: there
    # is no total order over pipeline output to partition on (unstable
    # sorts over $unwind ties silently drop/duplicate rows on real mongo)
    with pytest.raises(Exception, match="num_shards"):
        rd.read_mongo("mongodb://fake", "db", "c",
                      pipeline=[{"$unwind": "$tags"}],
                      client_factory=FakeClient, num_shards=4).take_all()
    # pipeline without sharding handles cardinality changes fine
    unwound = rd.read_mongo("mongodb://fake", "db", "c",
                            pipeline=[{"$unwind": "$tags"}],
                            client_factory=FakeClient).take_all()
    assert len(unwound) == 30  # 10 docs x 3 tags


def test_read_bigquery_with_injected_client(rt):
    """read_bigquery over Storage-API-shaped streams: one task per
    stream, rows concatenated (reference: ray.data.read_bigquery)."""
    stream_rows = {
        "s0": [{"id": 0, "v": "a"}, {"id": 1, "v": "b"}],
        "s1": [{"id": 2, "v": "c"}],
        "s2": [{"id": 3, "v": "d"}, {"id": 4, "v": "e"}],
    }

    class FakeBQ:
        def create_read_session(self, table, max_streams):
            assert table == "proj.ds.tbl"
            return list(stream_rows)[:max_streams]

        def read_rows(self, stream_id):
            return iter(stream_rows[stream_id])

    ds = rd.read_bigquery("proj.ds.tbl", client_factory=FakeBQ)
    assert len(ds.materialize()._refs_meta) == 3
    assert sorted(r["id"] for r in ds.take_all()) == [0, 1, 2, 3, 4]

    capped = rd.read_bigquery("proj.ds.tbl", client_factory=FakeBQ,
                              max_streams=2)
    assert sorted(r["id"] for r in capped.take_all()) == [0, 1, 2]


def test_read_delta_replays_transaction_log(rt, tmp_path):
    """read_delta: _delta_log add/remove replay + partitionValues as
    literal columns (reference: delta-rs-backed read_delta)."""
    import json as js

    import pyarrow as pa
    import pyarrow.parquet as pq

    root = tmp_path / "dl"
    (root / "_delta_log").mkdir(parents=True)

    def write_part(name, ids):
        pq.write_table(pa.table({"id": pa.array(ids, pa.int64())}),
                       root / name)

    write_part("f0.parquet", [0, 1])
    write_part("f1.parquet", [2, 3])
    write_part("f2.parquet", [4, 5])

    def commit(version, actions):
        with open(root / "_delta_log" / f"{version:020d}.json", "w") as f:
            for a in actions:
                f.write(js.dumps(a) + "\n")

    commit(0, [{"add": {"path": "f0.parquet",
                        "partitionValues": {"split": "train"}}},
               {"add": {"path": "f1.parquet",
                        "partitionValues": {"split": "val"}}}])
    # commit 1 compacts f1 away and adds f2
    commit(1, [{"remove": {"path": "f1.parquet"}},
               {"add": {"path": "f2.parquet",
                        "partitionValues": {"split": "val"}}}])

    rows = sorted(rd.read_delta(str(root)).take_all(),
                  key=lambda r: r["id"])
    assert [r["id"] for r in rows] == [0, 1, 4, 5]  # f1's rows are gone
    assert [r["split"] for r in rows] == ["train", "train", "val", "val"]

    with pytest.raises(FileNotFoundError, match="_delta_log"):
        rd.read_delta(str(tmp_path / "nope")).take_all()


def test_read_delta_from_checkpoint(rt, tmp_path):
    """Checkpointed table with vacuumed pre-checkpoint commits: the live
    set seeds from the parquet checkpoint, JSON replay resumes after it."""
    import json as js

    import pyarrow as pa
    import pyarrow.parquet as pq

    root = tmp_path / "dlc"
    log = root / "_delta_log"
    log.mkdir(parents=True)

    def write_part(name, ids):
        pq.write_table(pa.table({"id": pa.array(ids, pa.int64())}),
                       root / name)

    write_part("old.parquet", [0, 1])
    write_part("kept.parquet", [2])
    write_part("new.parquet", [3, 4])

    # checkpoint at version 5 holds the folded state: old + kept added,
    # old later removed (checkpoints carry surviving remove tombstones)
    ck_rows = [
        {"add": {"path": "old.parquet", "partitionValues": {}},
         "remove": None},
        {"add": {"path": "kept.parquet", "partitionValues": {"p": "k"}},
         "remove": None},
        {"add": None, "remove": {"path": "old.parquet"}},
    ]
    pq.write_table(pa.Table.from_pylist(ck_rows),
                   log / f"{5:020d}.checkpoint.parquet")
    with open(log / "_last_checkpoint", "w") as f:
        js.dump({"version": 5, "size": len(ck_rows)}, f)
    # a stale pre-checkpoint commit that must be ignored (already folded)
    with open(log / f"{5:020d}.json", "w") as f:
        f.write(js.dumps({"add": {"path": "old.parquet",
                                  "partitionValues": {}}}) + "\n")
    # post-checkpoint commit adds new.parquet
    with open(log / f"{6:020d}.json", "w") as f:
        f.write(js.dumps({"add": {"path": "new.parquet",
                                  "partitionValues": {"p": "n"}}}) + "\n")

    rows = sorted(rd.read_delta(str(root)).take_all(),
                  key=lambda r: r["id"])
    assert [r["id"] for r in rows] == [2, 3, 4]  # old.parquet stays dead


def test_shuffle_partitions_scale_with_bytes(rt):
    """Spill-aware shuffle sizing (reference: push-based shuffle target
    partition size): the all-to-all fan-out grows with total bytes so one
    reduce task never materializes more than ~target_shuffle_partition_bytes
    — datasets larger than the arena sort through bounded-memory tasks
    backed by the spilling object store."""
    from ray_tpu.data.context import DataContext
    from ray_tpu.data.shuffle import shuffle_partitions

    ctx = DataContext.get_current()
    old = ctx.target_shuffle_partition_bytes
    try:
        ctx.target_shuffle_partition_bytes = 1024
        # 40 blocks x 1 KB => 40 partitions even though the default is 8.
        fake = [(None, {"size_bytes": 1024}) for _ in range(40)]
        assert shuffle_partitions(fake, ctx) == 40
        # Small data keeps the default floor.
        small = [(None, {"size_bytes": 1}) for _ in range(40)]
        assert shuffle_partitions(small, ctx) == 8
        # The cap bounds runaway fan-out.
        huge = [(None, {"size_bytes": 10 * 1024 * 1024})] * 100
        assert shuffle_partitions(huge, ctx) == ctx.max_shuffle_partitions

        # End-to-end: a sort forced into many partitions is still correct.
        rng = np.random.default_rng(1)
        vals = rng.permutation(300).tolist()
        ds = rd.from_items([{"v": v} for v in vals]).sort("v")
        assert [r["v"] for r in ds.take_all()] == sorted(vals)
    finally:
        ctx.target_shuffle_partition_bytes = old


def test_stage_byte_budget_derived_from_arena(rt):
    """The executor's per-stage buffered-bytes budget is capped by the
    object-store share (reference: ResourceManager op budgets)."""
    import os

    from ray_tpu.data.context import DataContext
    from ray_tpu.data.executor import _StageExec
    from ray_tpu.data.plan import FusedMapStage
    from ray_tpu.utils import config as config_mod

    import ray_tpu

    stage = FusedMapStage(block_fn=lambda b: b, label="t", compute=None)
    prior = os.environ.get("RTPU_OBJECT_STORE_MEMORY_BYTES")
    os.environ["RTPU_OBJECT_STORE_MEMORY_BYTES"] = str(64 * 1024 * 1024)
    config_mod.set_config(config_mod.Config.load())
    try:
        ctx = DataContext.get_current()
        ex = _StageExec(stage, ctx, ray_tpu, n_stages=4)
        # 64 MB arena * 0.5 fraction / 4 stages = 8 MB per stage.
        assert ex.byte_budget == 8 * 1024 * 1024
    finally:
        if prior is None:
            os.environ.pop("RTPU_OBJECT_STORE_MEMORY_BYTES")
        else:
            os.environ["RTPU_OBJECT_STORE_MEMORY_BYTES"] = prior
        config_mod.set_config(config_mod.Config.load())


def test_actor_pool_autoscales_up_and_down(rt):
    """Elastic actor pools (reference: actor_pool_map_operator autoscaling):
    a deep input queue grows the pool toward max_size; idleness shrinks it
    back to min_size."""
    import time as _t

    from ray_tpu.data.context import DataContext
    from ray_tpu.data.executor import _StageExec
    from ray_tpu.data.plan import FusedMapStage

    import ray_tpu

    def slow(block):
        _t.sleep(0.2)
        return block

    comp = rd.ActorPoolStrategy(min_size=1, max_size=3, num_cpus=0.1)
    stage = FusedMapStage(block_fn=slow, label="t", compute=comp)
    ex = _StageExec(stage, DataContext.get_current(), ray_tpu, n_stages=1)
    ex.POOL_IDLE_S = 0.2  # fast wall-clock shrink for the test
    try:
        assert len(ex._pool) == 1
        refs = [ray_tpu.put({"id": np.arange(4)}) for _ in range(12)]
        for r in refs:
            ex.input_queue.append((r, {"num_rows": 4, "size_bytes": 32}))
        deadline = _t.monotonic() + 30
        while _t.monotonic() < deadline and (ex.input_queue or ex.in_flight):
            ex.launch()
            if ex.in_flight:
                ready, _ = ray_tpu.wait(list(ex.in_flight.keys()),
                                        num_returns=1, timeout=0.2)
                ex.collect_ready(ready)
        assert len(ex._pool) > 1, "pool never scaled up"
        # Drain and idle: the pool shrinks back to min_size.
        deadline = _t.monotonic() + 15
        while _t.monotonic() < deadline and len(ex._pool) > 1:
            ex.launch()
            _t.sleep(0.05)
        assert len(ex._pool) == 1, "pool never scaled back down"
        assert len(ex.outputs) == 12
    finally:
        ex.shutdown()
