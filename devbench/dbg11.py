import jax, jax.numpy as jnp, numpy as np
from jax import lax
NEG_INF=-1e30
rng = np.random.default_rng(0)
B,H,S,D,KB = 2,4,2048,64,512
q = jnp.asarray(rng.standard_normal((B,H,S,D)), jnp.bfloat16)
k = jnp.asarray(rng.standard_normal((B,H,S,D)), jnp.bfloat16)
v = jnp.asarray(rng.standard_normal((B,H,S,D)), jnp.bfloat16)
nb = S // KB
kb = k.reshape(B,H,nb,KB,D).transpose(2,0,1,3,4)
vb = v.reshape(B,H,nb,KB,D).transpose(2,0,1,3,4)
scale = 1.0/np.sqrt(D)

# bf16 s-blocks computed OUTSIDE, softmax-scan INSIDE (same numerics as orig fwd)
def from_sbf(sbf, vb):
    def step(carry, inputs):
        o, m, l = carry
        sb, vblk = inputs
        s = sb.astype(jnp.float32) * scale
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + p.sum(axis=-1)
        o_new = o * alpha[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p.astype(vblk.dtype), vblk).astype(jnp.float32)
        return (o_new, m_new, l_new), None
    o0 = jnp.zeros((B,H,S,D), jnp.float32); m0 = jnp.full((B,H,S), NEG_INF, jnp.float32); l0 = jnp.zeros((B,H,S), jnp.float32)
    (o, m, l), _ = lax.scan(step, (o0,m0,l0), (sbf, vb))
    return (o / jnp.maximum(l,1e-30)[..., None]).astype(jnp.bfloat16)

sbf = jnp.stack([jnp.einsum("bhqd,bhkd->bhqk", q, kb[j]) for j in range(nb)])  # bf16
_, g = jax.jit(jax.value_and_grad(lambda s: from_sbf(s, vb).astype(jnp.float32).sum()))(sbf)
print("dsbf nan:", bool(jnp.isnan(g.astype(jnp.float32)).any()), flush=True)

# dot INSIDE scan, everything else outside suspicion: loss = sum of per-block s·const
def dot_in_scan(q):
    def step(acc, kblk):
        s = jnp.einsum("bhqd,bhkd->bhqk", q, kblk).astype(jnp.float32)
        return acc + (jnp.tanh(s)).sum(), None
    acc, _ = lax.scan(step, jnp.zeros((), jnp.float32), kb)
    return acc
_, gq = jax.jit(jax.value_and_grad(dot_in_scan))(q)
print("dot-in-scan dq nan:", bool(jnp.isnan(gq.astype(jnp.float32)).any()), flush=True)
