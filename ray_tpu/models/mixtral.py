"""Mixtral-family sparse Mixture-of-Experts transformer.

Covers the reference's MoE serving/training capability (reference: BASELINE
config 5 runs Mixtral via vLLM engine kwargs + ray.util.collective all-to-all;
the reference has no first-class MoE implementation — SURVEY.md §2.4 EP row).
Here MoE is first-class and TPU-native:

- GShard/Switch-style capacity-based routing: top-k gates, per-expert token
  slots, dispatch/combine einsums. Everything is STATIC-shaped — no gather by
  dynamic token counts — so XLA tiles it onto the MXU and the ``expert``-
  sharded einsums lower to all-to-all over the mesh's ``ep`` axis
  automatically (the TPU-idiomatic equivalent of the reference's explicit
  collective all-to-all).
- Attention/rope/norms are shared with the Llama family; only the MLP is
  replaced by the expert layer; layers still scan-stacked.
- Load-balancing auxiliary loss (Switch Transformer form) returned alongside
  the LM loss.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.models import llama as _llama
from ray_tpu.ops.norms import rms_norm
from ray_tpu.ops.rope import apply_rope, rope_frequencies


@dataclass(frozen=True)
class MixtralConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 14336
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    head_dim: int = 128
    max_seq_len: int = 8192
    rope_theta: float = 1e6
    norm_eps: float = 1e-5
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    router_aux_coef: float = 0.02
    dtype: str = "bfloat16"

    @staticmethod
    def mixtral_8x7b() -> "MixtralConfig":
        return MixtralConfig()

    @staticmethod
    def tiny() -> "MixtralConfig":
        """Test-size: compiles in seconds, exercises routing + all code paths."""
        return MixtralConfig(vocab_size=256, hidden_size=64,
                             intermediate_size=128, num_layers=2, num_heads=4,
                             num_kv_heads=2, head_dim=16, max_seq_len=256,
                             num_experts=4, top_k=2, dtype="float32")

    @property
    def jnp_dtype(self):
        return jnp.dtype(self.dtype)

    def capacity(self, num_tokens: int) -> int:
        """Per-expert token slots for a batch of ``num_tokens``."""
        return max(1, int(math.ceil(
            self.capacity_factor * self.top_k * num_tokens / self.num_experts)))


def param_logical_axes(cfg: MixtralConfig) -> dict:
    return {
        "embed_tokens": ("vocab", "embed"),
        "lm_head": ("embed", "vocab"),
        "final_norm": ("embed",),
        "layers": {
            "wq": ("layers", "embed", "heads"),
            "wk": ("layers", "embed", "kv_heads"),
            "wv": ("layers", "embed", "kv_heads"),
            "wo": ("layers", "heads", "embed"),
            "router": ("layers", "embed", None),
            # Expert weights carry the ``expert`` logical axis → mesh ``ep``.
            "we_gate": ("layers", "expert", "embed", "mlp"),
            "we_up": ("layers", "expert", "embed", "mlp"),
            "we_down": ("layers", "expert", "mlp", "embed"),
            "attn_norm": ("layers", "embed"),
            "mlp_norm": ("layers", "embed"),
        },
    }


def init_params(cfg: MixtralConfig, key: jax.Array) -> dict:
    h, L, E = cfg.hidden_size, cfg.num_layers, cfg.num_experts
    qd = cfg.num_heads * cfg.head_dim
    kvd = cfg.num_kv_heads * cfg.head_dim
    i = cfg.intermediate_size
    dt = cfg.jnp_dtype
    keys = jax.random.split(key, 12)

    def norm_init(k, *shape, scale=None):
        scale = scale if scale is not None else 1.0 / math.sqrt(shape[-2])
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(dt)

    return {
        "embed_tokens": (jax.random.normal(keys[0], (cfg.vocab_size, h),
                                           jnp.float32) * 0.02).astype(dt),
        "lm_head": norm_init(keys[1], h, cfg.vocab_size,
                             scale=1.0 / math.sqrt(h)),
        "final_norm": jnp.ones((h,), dt),
        "layers": {
            "wq": norm_init(keys[2], L, h, qd),
            "wk": norm_init(keys[3], L, h, kvd),
            "wv": norm_init(keys[4], L, h, kvd),
            "wo": norm_init(keys[5], L, qd, h, scale=1.0 / math.sqrt(qd * 2 * L)),
            "router": norm_init(keys[6], L, h, E, scale=0.02),
            "we_gate": norm_init(keys[7], L, E, h, i),
            "we_up": norm_init(keys[8], L, E, h, i),
            "we_down": norm_init(keys[9], L, E, i, h,
                                 scale=1.0 / math.sqrt(i * 2 * L)),
            "attn_norm": jnp.ones((L, h), dt),
            "mlp_norm": jnp.ones((L, h), dt),
        },
    }


def compute_routing(cfg: MixtralConfig, logits: jax.Array, capacity: int):
    """Router logits [T, E] → (dispatch [T,E,C], combine [T,E,C], aux).

    Top-k gates renormalized to sum to 1 per token; slot positions assigned by
    running claim count per expert (token-major priority); claims beyond
    ``capacity`` are dropped. For a kept token, combine[t].sum() == 1.
    """
    T = logits.shape[0]
    E, K, C = cfg.num_experts, cfg.top_k, capacity
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    gate_vals, gate_idx = lax.top_k(probs, K)  # [T, K]
    gate_vals = gate_vals / jnp.maximum(
        gate_vals.sum(-1, keepdims=True), 1e-9)  # renormalize top-k

    # Slot assignment: for the k-th choice of each token, its position within
    # the chosen expert is the running count of earlier claims on that expert.
    expert_onehot = jax.nn.one_hot(gate_idx, E, dtype=jnp.int32)  # [T, K, E]
    flat_claims = expert_onehot.reshape(T * K, E)  # priority: token-major, k-minor
    position = jnp.cumsum(flat_claims, axis=0) - flat_claims  # claims before us
    position = (position * flat_claims).sum(-1).reshape(T, K)  # [T, K]
    kept = position < C

    # dispatch[t, e, c] = 1 where token t owns slot c of expert e
    slot_onehot = jax.nn.one_hot(position, C, dtype=jnp.float32)  # [T, K, C]
    dispatch = jnp.einsum("tke,tkc->tec", expert_onehot.astype(jnp.float32),
                          slot_onehot * kept[..., None])
    combine = jnp.einsum("tk,tke,tkc->tec",
                         gate_vals * kept, expert_onehot.astype(jnp.float32),
                         slot_onehot)

    # Switch load-balancing loss: E * Σ_e (token fraction)·(mean router prob).
    token_frac = dispatch.sum((0, 2)) / jnp.maximum(dispatch.sum(), 1.0)
    prob_frac = probs.mean(0)
    aux = E * jnp.sum(token_frac * prob_frac)
    return dispatch, combine, aux


def moe_block(cfg: MixtralConfig, x: jax.Array, lp: dict):
    """Capacity-routed expert MLP. x: [B, S, H] → ([B, S, H], aux_loss).

    Static-shape dispatch: tokens → [E, C, H] slots via one-hot einsum (the
    ``e``-sharded operands make XLA emit the ep all-to-all), per-expert SwiGLU
    as batched einsums on the MXU, combine back with the gate weights.
    Overflowing tokens beyond an expert's capacity are dropped (their residual
    stream passes through unchanged) — Switch/GShard semantics.
    """
    b, s, h = x.shape
    T = b * s
    C = cfg.capacity(T)
    dt = x.dtype
    xt = x.reshape(T, h)

    logits = (xt @ lp["router"]).astype(jnp.float32)  # [T, E]
    dispatch, combine, aux = compute_routing(cfg, logits, C)

    # [E, C, H] expert inputs — this einsum is the ep all-to-all boundary.
    expert_in = jnp.einsum("tec,th->ech", dispatch.astype(dt), xt)
    gate = jax.nn.silu(jnp.einsum(
        "ech,ehi->eci", expert_in, lp["we_gate"]).astype(jnp.float32)).astype(dt)
    up = jnp.einsum("ech,ehi->eci", expert_in, lp["we_up"])
    expert_out = jnp.einsum("eci,eih->ech", gate * up, lp["we_down"])
    y = jnp.einsum("tec,ech->th", combine.astype(dt), expert_out)
    return y.reshape(b, s, h), aux


def _layer(cfg: MixtralConfig, x, lp, inv_freq, positions, attn_impl):
    b, s, h = x.shape
    dt = x.dtype
    xn = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
    q = (xn @ lp["wq"]).reshape(b, s, cfg.num_heads, cfg.head_dim).transpose(0, 2, 1, 3)
    k = (xn @ lp["wk"]).reshape(b, s, cfg.num_kv_heads, cfg.head_dim).transpose(0, 2, 1, 3)
    v = (xn @ lp["wv"]).reshape(b, s, cfg.num_kv_heads, cfg.head_dim).transpose(0, 2, 1, 3)
    q = apply_rope(q, positions, inv_freq)
    k = apply_rope(k, positions, inv_freq)
    o = _llama._attention(cfg, q, k, v, attn_impl, None)
    o = o.transpose(0, 2, 1, 3).reshape(b, s, cfg.num_heads * cfg.head_dim)
    x = x + (o @ lp["wo"]).astype(dt)

    xn = rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
    y, aux = moe_block(cfg, xn, lp)
    return x + y.astype(dt), aux


def forward(cfg: MixtralConfig, params: dict, tokens: jax.Array,
            positions: jax.Array | None = None, attn_impl: str = "flash",
            remat: bool = True):
    """tokens [B, S] → (logits [B, S, V] fp32, mean aux loss)."""
    b, s = tokens.shape
    if positions is None:
        positions = jnp.arange(s)
    x = params["embed_tokens"][tokens]
    inv_freq = rope_frequencies(cfg.head_dim, cfg.rope_theta, None)

    from ray_tpu.models.llama import _remat_wrap

    layer_fn = _remat_wrap(
        partial(_layer, cfg, inv_freq=inv_freq, positions=positions,
                attn_impl=attn_impl),
        remat)

    def scan_body(x, lp):
        x, aux = layer_fn(x, lp)
        return x, aux

    x, aux = lax.scan(scan_body, x, params["layers"])
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    # bf16 MXU matmul with f32 accumulation — casting both operands to f32
    # would fall off the MXU fast path (see llama.forward).
    logits = jnp.einsum("bsh,hv->bsv", x, params["lm_head"],
                        preferred_element_type=jnp.float32)
    return logits, aux.mean()


def loss_fn(cfg: MixtralConfig, params: dict, tokens: jax.Array,
            targets: jax.Array, mask: jax.Array | None = None,
            **fwd_kwargs) -> jax.Array:
    """LM cross-entropy + router load-balancing loss."""
    logits, aux = forward(cfg, params, tokens, **fwd_kwargs)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    if mask is None:
        mask = jnp.ones_like(targets, jnp.float32)
    mask = mask.astype(jnp.float32)
    lm = (nll * mask).sum() / jnp.maximum(mask.sum(), 1.0)
    return lm + cfg.router_aux_coef * aux
