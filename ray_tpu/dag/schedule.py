"""Pipeline schedules: per-stage op orderings for the MPMD executor.

A compiled DAG runs each actor's op list strictly in order, once per
execution — so for a multi-microbatch training step the per-stage ORDER of
forward/backward ops IS the pipeline schedule (reference: the execution
schedules of compiled_dag_node.py:2002 _build_execution_schedule; the
GPipe/1F1B distinction in PP literature). The MPMD builder
(ray_tpu/dag/mpmd.py) asks a schedule for integer ranks and stamps them
onto the DAG nodes as ``schedule_rank``; CompiledDAG._compile sorts each
actor's ops by rank.

Rank layout per stage (one training step): rank 0 is the ingest op (stage
0 only), then forwards/backwards interleaved per the schedule, then the
optimizer apply last. A schedule is FEASIBLE iff, walking all stages'
op lists in any global interleaving consistent with the per-stage orders,
every op's upstream value has already been produced — both schedules here
are classical and feasible by construction.
"""

from __future__ import annotations


class PipelineSchedule:
    """Rank assignment for one stage's ops within a training step."""

    name: str = "base"

    def forward_rank(self, mb: int, stage: int, num_stages: int,
                     num_microbatches: int) -> int:
        raise NotImplementedError

    def backward_rank(self, mb: int, stage: int, num_stages: int,
                      num_microbatches: int) -> int:
        raise NotImplementedError

    def apply_rank(self, stage: int, num_stages: int,
                   num_microbatches: int) -> int:
        # After every forward and backward of the step.
        return 1 + 2 * num_microbatches + 1


class GPipeSchedule(PipelineSchedule):
    """Fill/drain: all forwards in microbatch order, then all backwards.

    Maximum intra-step overlap across stages (stage k runs forward of
    microbatch m while stage k+1 runs m-1); peak residual stash is all
    ``num_microbatches`` activations."""

    name = "gpipe"

    def forward_rank(self, mb, stage, num_stages, num_microbatches):
        return 1 + mb

    def backward_rank(self, mb, stage, num_stages, num_microbatches):
        return 1 + num_microbatches + mb

    def apply_rank(self, stage, num_stages, num_microbatches):
        return 1 + 2 * num_microbatches


class OneFOneBSchedule(PipelineSchedule):
    """1F1B: warm up with ``num_stages - stage`` forwards, then alternate
    backward/forward, then drain the remaining backwards. Same math as
    GPipe (the step still applies once, after all microbatches), but the
    residual stash peaks at the warmup depth instead of the full
    microbatch count."""

    name = "1f1b"

    def _warmup(self, stage, num_stages, num_microbatches):
        return min(num_microbatches, num_stages - stage)

    def forward_rank(self, mb, stage, num_stages, num_microbatches):
        w = self._warmup(stage, num_stages, num_microbatches)
        if mb < w:
            return 1 + mb
        # Steady state: forward of microbatch w+j follows backward j.
        return 1 + w + 2 * (mb - w) + 1

    def backward_rank(self, mb, stage, num_stages, num_microbatches):
        w = self._warmup(stage, num_stages, num_microbatches)
        if mb < num_microbatches - w:
            return 1 + w + 2 * mb
        # Drain: the last w backwards run after all forwards are done.
        return 1 + w + 2 * (num_microbatches - w) + (
            mb - (num_microbatches - w))

    def apply_rank(self, stage, num_stages, num_microbatches):
        return 1 + 2 * num_microbatches + 1


_SCHEDULES: dict[str, PipelineSchedule] = {}


def register_schedule(schedule: PipelineSchedule) -> None:
    _SCHEDULES[schedule.name] = schedule


def get_schedule(name: str) -> PipelineSchedule:
    try:
        return _SCHEDULES[name]
    except KeyError:
        raise ValueError(
            f"unknown pipeline schedule {name!r}; "
            f"registered: {sorted(_SCHEDULES)}") from None


register_schedule(GPipeSchedule())
register_schedule(OneFOneBSchedule())
