"""Object store, reference counting, and ID semantics.

Coverage modeled on the reference's refcount protocol tests (reference:
python/ray/tests/test_reference_counting.py shapes; protocol spec in
src/ray/core_worker/reference_counter.h — see SURVEY.md §8.1).
"""

import pytest

from ray_tpu.core.store import LocalObjectStore, ReferenceCounter
from ray_tpu.utils.ids import ActorID, JobID, NodeID, ObjectID, TaskID, WorkerID


def test_id_roundtrip():
    for cls in (JobID, NodeID, WorkerID, ActorID, TaskID):
        i = cls.from_random()
        assert cls.from_hex(i.hex()) == i
        assert not i.is_nil()
        assert cls.nil().is_nil()


def test_object_id_structure():
    job = JobID.from_random()
    t = TaskID.of(job)
    o0 = ObjectID.for_task_return(t, 0)
    o1 = ObjectID.for_task_return(t, 1)
    assert o0 != o1
    assert o0.task_id() == t and o1.task_id() == t
    assert o0.return_index() == 0 and o1.return_index() == 1
    assert t.job_id() == job


def test_actor_task_id_deterministic():
    a = ActorID.of(JobID.from_random())
    assert TaskID.for_actor_task(a, 5) == TaskID.for_actor_task(a, 5)
    assert TaskID.for_actor_task(a, 5) != TaskID.for_actor_task(a, 6)


def test_store_put_get_delete():
    store = LocalObjectStore(capacity_bytes=1 << 20)
    w = WorkerID.from_random()
    oid = ObjectID.for_put(w)
    store.put(oid, b"hello", w)
    assert store.get(oid) == b"hello"
    assert store.contains(oid)
    store.delete(oid)
    assert not store.contains(oid)


def test_store_blocking_get():
    import threading

    store = LocalObjectStore(capacity_bytes=1 << 20)
    w = WorkerID.from_random()
    oid = ObjectID.for_put(w)
    results = []

    def getter():
        results.append(store.get(oid, timeout=5))

    t = threading.Thread(target=getter)
    t.start()
    store.put(oid, b"later", w)
    t.join(timeout=5)
    assert results == [b"later"]


def test_store_spills_over_capacity(tmp_path):
    store = LocalObjectStore(capacity_bytes=1000, spill_dir=str(tmp_path))
    w = WorkerID.from_random()
    oids = []
    for i in range(10):
        oid = ObjectID.for_put(w)
        store.put(oid, bytes([i]) * 200, w)
        oids.append(oid)
    # memory stays under the spill threshold, all objects still readable
    assert store.used_bytes() <= 1000
    for i, oid in enumerate(oids):
        assert store.get(oid) == bytes([i]) * 200


def test_refcount_release_on_zero():
    released = []
    rc = ReferenceCounter(on_release=lambda oid, rec: released.append(oid))
    w = WorkerID.from_random()
    oid = ObjectID.for_put(w)
    rc.add_owned(oid, w)  # ownership registration only — no local ref
    rc.add_local_ref(oid)
    rc.add_local_ref(oid)
    rc.remove_local_ref(oid)
    assert released == []  # one live ObjectRef still holds it
    rc.remove_local_ref(oid)
    assert released == [oid]


def test_refcount_borrowers_block_release():
    released = []
    rc = ReferenceCounter(on_release=lambda oid, rec: released.append(oid))
    w, b = WorkerID.from_random(), WorkerID.from_random()
    oid = ObjectID.for_put(w)
    rc.add_owned(oid, w)
    rc.add_local_ref(oid)
    rc.add_borrowed(oid, w, b)
    rc.remove_local_ref(oid)
    assert released == []  # borrower still holds it
    rc.remove_borrower(oid, b)
    assert released == [oid]


def test_refcount_pending_task_blocks_release():
    released = []
    rc = ReferenceCounter(on_release=lambda oid, rec: released.append(oid))
    w = WorkerID.from_random()
    oid = ObjectID.for_put(w)
    rc.add_owned(oid, w)
    rc.add_local_ref(oid)
    rc.on_task_submitted([oid])
    rc.remove_local_ref(oid)
    assert released == []
    rc.on_task_finished([oid])
    assert released == [oid]


def test_serialization_roundtrip():
    import numpy as np

    from ray_tpu.utils import serialization as ser

    for obj in (42, "hi", [1, {"a": (2, 3)}], None):
        assert ser.deserialize(ser.serialize(obj)) == obj
    arr = np.random.rand(16, 16).astype(np.float32)
    out = ser.deserialize(ser.serialize(arr))
    np.testing.assert_array_equal(out, arr)
    assert out.flags.writeable


def test_config_env_override(monkeypatch):
    from ray_tpu.utils.config import Config

    monkeypatch.setenv("RTPU_WORKER_IDLE_TTL_S", "7.5")
    monkeypatch.setenv("RTPU_MAX_WORKERS_PER_NODE", "3")
    cfg = Config.load()
    assert cfg.worker_idle_ttl_s == 7.5
    assert cfg.max_workers_per_node == 3
    cfg2 = Config.load(overrides={"scheduler_spread_threshold": 0.9})
    assert cfg2.scheduler_spread_threshold == 0.9
    with pytest.raises(ValueError):
        Config.load(overrides={"nope": 1})


def test_ids_reseed_after_fork():
    """A fork()ed child must not replay the parent's id stream (ADVICE r3:
    cached _RAND_BASE/_COUNTER are inherited; os.register_at_fork reseeds)."""
    import os

    from ray_tpu.utils.ids import TaskID, JobID

    job = JobID.from_random()
    r, w = os.pipe()
    pid = os.fork()
    if pid == 0:  # child
        try:
            ids = b"".join(TaskID.of(job).binary() for _ in range(8))
            os.write(w, ids)
        finally:
            os._exit(0)
    os.close(w)
    child_ids = b""
    while True:
        chunk = os.read(r, 4096)
        if not chunk:
            break
        child_ids += chunk
    os.close(r)
    os.waitpid(pid, 0)
    child_set = {child_ids[i:i + 16] for i in range(0, len(child_ids), 16)}
    parent_set = {TaskID.of(job).binary() for _ in range(8)}
    assert len(child_set) == 8
    assert not (child_set & parent_set), "fork replayed the parent id stream"
